"""One full federated round on one box, through the public API.

The reference's de-facto system test is its Local* twins running a
miner -> validator -> averager round offline (SURVEY.md §4.1); this is that
round as a minimal, readable script. Run from the repo root:

    DT_FORCE_PLATFORM=cpu python examples/local_round.py

Everything here is the same machinery the real roles compose
(neurons/common.py) — swap InMemoryTransport/LocalChain for
HFHubTransport/BittensorChain and the code is a deployment.
"""

import os
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from distributedtraining_tpu.utils.platform import (  # noqa: E402
    force_platform_from_env)

force_platform_from_env()

from distributedtraining_tpu.chain import LocalChain  # noqa: E402
from distributedtraining_tpu.data import (ByteTokenizer,  # noqa: E402
                                          batch_iterator, prefetch,
                                          text_corpus)
from distributedtraining_tpu.engine import (AveragerLoop,  # noqa: E402
                                            MinerLoop, TrainEngine,
                                            Validator, WeightedAverage)
from distributedtraining_tpu.models import gpt2  # noqa: E402
from distributedtraining_tpu.transport import InMemoryTransport  # noqa: E402


def main() -> None:
    model, cfg = gpt2.make_model("tiny")
    tok = ByteTokenizer()
    train_docs = text_corpus(split="train", n_docs=48, source="synthetic")
    val_docs = text_corpus(split="val", n_docs=12, source="synthetic")

    def train_batches():
        return prefetch(batch_iterator(train_docs, tok, batch_size=4,
                                       seq_len=32, repeat=True,
                                       max_vocab=cfg.vocab_size))

    def val_batches():
        return batch_iterator(val_docs, tok, batch_size=4, seq_len=32,
                              max_vocab=cfg.vocab_size)

    transport = InMemoryTransport()
    with tempfile.TemporaryDirectory() as tmp:
        chain = LocalChain(os.path.join(tmp, "chain"), my_hotkey="hotkey_91")

        # --- miner: train, publish a weight delta --------------------------
        engine = TrainEngine(model, seq_len=32)
        miner = MinerLoop(engine, transport, "hotkey_0", send_interval=0)
        miner.bootstrap()
        report = miner.run(train_batches(), max_steps=40)
        miner.flush()
        print(f"miner  : {report.steps} steps, loss {report.last_loss:.4f}, "
              f"{report.pushes} delta pushes")

        # --- validator: score the delta, emit chain weights ----------------
        validator = Validator(TrainEngine(model, seq_len=32), transport,
                              chain, eval_batches=val_batches)
        validator.bootstrap()
        scores = validator.validate_and_score()
        nonzero = {s.hotkey: round(s.score, 5) for s in scores if s.score > 0}
        print(f"validator: base loss {validator.base_loss:.4f}, "
              f"scores {nonzero}")

        # --- averager: merge accepted deltas into a new base ---------------
        averager = AveragerLoop(TrainEngine(model, seq_len=32), transport,
                                LocalChain(os.path.join(tmp, "chain"),
                                           my_hotkey="hotkey_95"),
                                WeightedAverage(), val_batches=val_batches)
        assert averager.run_round(), "averager merged nothing"
        print(f"averager: accepted {averager.report.last_accepted}, "
              f"merged-base loss {averager.report.last_loss:.4f}")

        from distributedtraining_tpu.engine.train import host_zeros_template
        fetched = transport.fetch_base(host_zeros_template(engine))
        assert fetched is not None
        print(f"round complete: new base published (revision "
              f"{fetched[1][:12]}...)")


if __name__ == "__main__":
    main()
