"""Headline benchmark: miner train-step throughput, GPT-2-124M, one chip.

North-star metric per BASELINE.json: miner tokens/sec/chip for GPT-2-124M.
The reference publishes no numbers (BASELINE.md) — `vs_baseline` is reported
against the framework's own first recorded measurement (BENCH_r1), i.e. 1.0
establishes the baseline in round 1.

Prints exactly one JSON line:
  {"metric": ..., "value": N, "unit": "tokens/sec/chip", "vs_baseline": N}
"""

from __future__ import annotations

import json
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np

BATCH = 8
SEQ = 1024
WARMUP = 3
ITERS = 20
BASELINE_TOKENS_PER_SEC = None  # set from BENCH_r1 once recorded


def main() -> None:
    from distributedtraining_tpu.engine import TrainEngine
    from distributedtraining_tpu.models import gpt2

    model, cfg = gpt2.make_model("gpt2-124m")
    engine = TrainEngine(model, seq_len=SEQ)
    state = engine.init_state(jax.random.PRNGKey(0))

    rng = np.random.default_rng(0)
    batch = {
        "input_ids": jnp.asarray(
            rng.integers(0, cfg.vocab_size, (BATCH, SEQ)), jnp.int32),
    }

    for _ in range(WARMUP):
        state, m = engine.train_step(state, batch)
    float(m["loss"])  # full host sync — the axon backend's block_until_ready
    # does not actually block, so timing must end on a value fetch

    t0 = time.perf_counter()
    for _ in range(ITERS):
        state, m = engine.train_step(state, batch)
    final_loss = float(m["loss"])  # forces the whole dependency chain
    dt = time.perf_counter() - t0
    assert final_loss == final_loss, "loss is NaN"

    tokens_per_sec = BATCH * SEQ * ITERS / dt
    vs = (tokens_per_sec / BASELINE_TOKENS_PER_SEC
          if BASELINE_TOKENS_PER_SEC else 1.0)
    print(json.dumps({
        "metric": "miner_train_tokens_per_sec_per_chip_gpt2_124m",
        "value": round(tokens_per_sec, 1),
        "unit": "tokens/sec/chip",
        "vs_baseline": round(vs, 3),
    }))


if __name__ == "__main__":
    main()
