"""Headline benchmark: the BASELINE.json north-star pair on one chip.

Emits exactly ONE JSON line whose primary metric is miner train throughput
(GPT-2-124M tokens/sec/chip, flash attention, bf16 activations), pinned
against the round-1 measurement. The same object carries the rest of the
north star (BASELINE.json: "miner tokens/sec/chip + averager merge
wall-clock"):

  value / vs_baseline     tokens/sec/chip vs the pinned r01 figure
  mfu                     model-FLOP utilization vs the chip's peak bf16
  dense_tokens_per_sec    same step with attention_impl="dense"
  flash_speedup           flash/dense throughput ratio at T=1024
  merge_wallclock_s       averager weighted-merge of M=8 full GPT-2-124M
                          deltas (jitted, device-resident), mean seconds
  merge_gbps              delta bytes touched / merge wall-clock

The reference publishes no numbers (BASELINE.md); round 1 established
92,843 tok/s/chip on this rig, so vs_baseline > 1.0 means the framework got
faster than its own first measurement.
"""

from __future__ import annotations

import dataclasses
import functools
import gc
import json
import os
import time

import jax
import jax.numpy as jnp
import numpy as np

BATCH = 8
SEQ = 1024
WARMUP = 3
ITERS = 20
MERGE_M = 8           # miners in the merge bench (BASELINE config 3 scale)
MERGE_ITERS = 5
VAL_K = 8             # cohort size in the validator-round A/B
VAL_EVAL_BATCHES = 4
BASELINE_TOKENS_PER_SEC = 92843.0   # BENCH_r01.json, this rig, r01 code

# peak dense bf16 FLOP/s per chip by TPU generation (public spec sheets);
# MFU is reported against the best matching entry, else omitted. JAX reports
# the e-generations as "TPU v5 lite"/"TPU v6 lite", hence the ladder.
def _peak_flops() -> float | None:
    kind = ""
    try:
        kind = jax.devices()[0].device_kind.lower()
    except Exception:
        pass
    text = f"{kind} {os.environ.get('PALLAS_AXON_TPU_GEN', '').lower()}"
    if "v6e" in text or "v6 lite" in text:
        return 918e12
    if "v5p" in text:
        return 459e12
    if "v5e" in text or "v5 lite" in text:
        return 197e12
    if "v4" in text:
        return 275e12
    return None


def _bench_env() -> dict:
    """Rig forensics embedded in EVERY bench record (including degraded
    ones): device kind/counts, platform, and jax/jaxlib versions — the
    four rounds of bare ``value: 0.0, tunnel wedged`` artifacts
    (BENCH_r02–r05) were undiagnosable precisely because the record
    said nothing about the environment that produced it. Each field is
    probed independently so a wedged backend still yields the version
    fields."""
    out: dict = {}
    try:
        import jaxlib
        out["jax_version"] = jax.__version__
        out["jaxlib_version"] = jaxlib.__version__
    except Exception:
        pass
    try:
        out["platform"] = jax.default_backend()
    except Exception:
        pass
    try:
        devs = jax.devices()
        out["device_kind"] = devs[0].device_kind
        out["device_count"] = len(devs)
        out["host_count"] = jax.process_count()
    except Exception:
        pass
    return out


def _time_train(model, cfg, *, iters: int = ITERS,
                fused_loss: bool | str = False) -> float:
    """tokens/sec of the jitted train step (fwd+bwd+adamw) on one chip."""
    burst = _step_burst(model, cfg, fused_loss=fused_loss)
    burst(WARMUP)
    return burst(iters)


def _step_burst(model, cfg, *, fused_loss: bool | str = False,
                batch_size: int = BATCH):
    """Build a reusable timed-burst closure over a fresh engine+state.
    The ONE home of this rig's fetch discipline: block_until_ready does
    not actually block on the axon backend, so every timing must end on a
    float() fetch of a value depending on the work. Also the unit of the
    interleaved A/B comparisons — this rig drifts ~15% run-to-run, so only
    within-pair ratios are meaningful (scripts/measure.sh rule 4)."""
    from distributedtraining_tpu.engine import TrainEngine

    engine = TrainEngine(model, seq_len=SEQ, fused_loss=fused_loss)
    box = {"state": engine.init_state(jax.random.PRNGKey(0))}
    rng = np.random.default_rng(0)
    batch = {"input_ids": jnp.asarray(
        rng.integers(0, cfg.vocab_size, (batch_size, SEQ)), jnp.int32)}

    def burst(iters: int) -> float:
        state = box["state"]
        t0 = time.perf_counter()
        for _ in range(iters):
            state, m = engine.train_step(state, batch)
        final = float(m["loss"])  # the only fetch that really blocks here
        dt = time.perf_counter() - t0
        box["state"] = state
        assert final == final, "loss is NaN"
        return batch_size * SEQ * iters / dt

    return burst


def _ab_pairs(burst_a, burst_b, *, trials: int = 2, iters: int = 10):
    """Warm both, then alternate A/B bursts; returns the list of
    (a_tps, b_tps) pairs."""
    burst_a(WARMUP)
    burst_b(WARMUP)
    pairs = []
    for _ in range(trials):
        a = burst_a(iters)
        b = burst_b(iters)
        pairs.append((a, b))
    return pairs


def _pair_stats(pairs) -> tuple[float, float]:
    """(b_tokens_per_sec_mean, b_over_a_speedup_mean) of interleaved
    pairs — the only statistics any A/B in this file reports."""
    return (float(np.mean([b for _, b in pairs])),
            float(np.mean([b / a for a, b in pairs])))


def _ab_speedup(burst_a, model_b, cfg_b, *, fused_b: bool | str = False,
                batch_size: int = BATCH) -> tuple[float, float]:
    """Interleaved (b_tokens_per_sec_mean, b_over_a_speedup_mean).
    ``burst_a`` is the shared, already-compiled baseline burst — rebuilding
    the identical standard engine per comparison would add redundant XLA
    compiles to a bench run whose timeout budget is counted in compiles."""
    burst_b = _step_burst(model_b, cfg_b, fused_loss=fused_b,
                          batch_size=batch_size)
    return _pair_stats(_ab_pairs(burst_a, burst_b))


def _time_loop_vs_engine(model, cfg, base_burst, *, trials: int = 2,
                         iters: int = 10) -> dict:
    """PRODUCTION loop (MinerLoop.run) vs the bare jitted step
    (``base_burst``, the shared baseline), measured as INTERLEAVED burst
    pairs (scripts/measure.sh rule 4). The gap is pure loop overhead — the
    round-2 verdict flagged a per-step float() sync here; this sub-bench
    keeps it measured."""
    from distributedtraining_tpu.engine import TrainEngine
    from distributedtraining_tpu.engine.train import MinerLoop
    from distributedtraining_tpu.transport import InMemoryTransport

    engine = TrainEngine(model, seq_len=SEQ)   # same HLO: compile is cached
    rng = np.random.default_rng(0)
    host_batch = {"input_ids": rng.integers(0, cfg.vocab_size, (BATCH, SEQ),
                                            dtype=np.int32)}
    loop = MinerLoop(engine, InMemoryTransport(), "bench",
                     send_interval=1e9, check_update_interval=1e9,
                     log_every=10**9)
    loop.bootstrap(jax.random.PRNGKey(0))

    def batches(n):
        for _ in range(n):
            yield host_batch

    def loop_burst(n: int) -> float:
        t0 = time.perf_counter()
        loop.run(batches(n), max_steps=n)      # exit fetch ends the timing
        return BATCH * SEQ * n / (time.perf_counter() - t0)

    pairs = _ab_pairs(base_burst, loop_burst, trials=trials, iters=iters)
    assert loop.report.last_loss == loop.report.last_loss, "loss is NaN"
    loop_tps, loop_ratio = _pair_stats(pairs)
    return {"loop_tokens_per_sec": round(loop_tps, 1),
            "loop_vs_engine": round(loop_ratio, 3)}


def _time_validator_round(model, cfg, *, k: int = VAL_K,
                          n_batches: int = VAL_EVAL_BATCHES,
                          trials: int = 2) -> dict:
    """Validator-round A/B: the sequential score_miner spelling (one full
    eval pass per candidate, engine.evaluate) vs the batched cohort
    evaluator (engine/batched_eval.py) on the SAME base/deltas/batches.
    ``validator_round_sec``/``candidates_per_sec`` are the cohort path's
    numbers; the dispatch counts are exact by construction — sequential
    pays k programs per eval batch, the cohort pays one — so the ratio is
    the K-fold dispatch reduction the design claims, and the wall-clock
    pair is what this rig measured. CPU-measurable: the contrast is
    dispatch/placement overhead, which exists on every backend."""
    from distributedtraining_tpu import delta as delta_lib
    from distributedtraining_tpu.engine import TrainEngine
    from distributedtraining_tpu.engine.batched_eval import (
        BatchedCohortEvaluator)

    engine = TrainEngine(model, seq_len=SEQ)
    base = engine.place_params(model.init_params(jax.random.PRNGKey(0)))
    rng = np.random.default_rng(0)
    batches = [{"input_ids": jnp.asarray(
        rng.integers(0, cfg.vocab_size, (BATCH, SEQ)), jnp.int32)}
        for _ in range(n_batches)]
    leaves, treedef = jax.tree_util.tree_flatten(base)
    key = jax.random.PRNGKey(1)
    deltas = []
    for _ in range(k):
        key, kk = jax.random.split(key)
        ks = jax.random.split(kk, len(leaves))
        deltas.append(jax.tree_util.tree_unflatten(
            treedef, [0.01 * jax.random.normal(s, l.shape, l.dtype)
                      for s, l in zip(ks, leaves)]))

    def seq_round():
        # engine.evaluate's closing float() fetch ends each candidate's
        # timing on a real sync (the _step_burst fetch discipline)
        return [engine.evaluate(delta_lib.apply_delta(base, d), batches)
                for d in deltas]

    ev = BatchedCohortEvaluator(engine)

    def cohort_round():
        return ev.evaluate_cohort(base, deltas, batches)

    seq = seq_round()      # warm: compiles eval_step
    coh = cohort_round()   # warm: compiles the bucket program
    # parity guard: a fast-but-wrong cohort eval is not a win
    parity_err = max(abs(a[0] - b[0]) for a, b in zip(seq, coh))

    t0 = time.perf_counter()
    for _ in range(trials):
        seq_round()
    t_seq = (time.perf_counter() - t0) / trials
    t0 = time.perf_counter()
    for _ in range(trials):
        cohort_round()
    t_coh = (time.perf_counter() - t0) / trials

    return {
        "validator_k": k,
        "validator_eval_batches": n_batches,
        "validator_seq_round_sec": round(t_seq, 4),
        "validator_round_sec": round(t_coh, 4),
        "validator_round_speedup": round(t_seq / t_coh, 3),
        "candidates_per_sec": round(k / t_coh, 2),
        "validator_seq_dispatches": k * n_batches,
        "validator_cohort_dispatches": n_batches,
        "validator_dispatch_ratio": float(k),
        "validator_parity_max_abs_err": round(float(parity_err), 6),
    }


def _time_push_overlap(*, latency_s: float = 0.15, steps: int = 24,
                       push_every_s: float = 0.0) -> dict:
    """Miner publication A/B on a simulated-latency transport: the
    sequential push path (--no-push-async) vs the background pipeline
    (engine/publish.py), plus a no-push baseline that isolates the stall.

      push_stall_ms           training-thread stall per push, sync path
      push_stall_async_ms     same with the async pipeline
      push_overlap_speedup    sync wall-clock / async wall-clock
      push_stall_removed      fraction of the per-push stall the async
                              path hides (acceptance floor: >= 0.8)
      push_parity             async artifact bytes == sync artifact bytes

    CPU-measurable: the stall under test is host/network latency, which
    exists identically on every backend. The tiny model keeps the signal
    transport-dominated (the 124M delta's host serialization would
    swamp the simulated latency on this rig's CPU fallback), and the
    150 ms default is conservative vs production — a real Hub push of a
    full delta is O(seconds) (the E2E round artifacts), where the removed
    fraction only grows."""
    from distributedtraining_tpu.engine import FakeClock  # noqa: F401
    from distributedtraining_tpu.engine import TrainEngine
    from distributedtraining_tpu.engine.train import MinerLoop
    from distributedtraining_tpu.models import gpt2
    from distributedtraining_tpu.transport import InMemoryTransport

    class SlowTransport(InMemoryTransport):
        def publish_delta(self, miner_id, delta):
            time.sleep(latency_s)
            return super().publish_delta(miner_id, delta)

        def publish_delta_meta(self, miner_id, meta):
            time.sleep(latency_s / 10)
            super().publish_delta_meta(miner_id, meta)

    model, cfg = gpt2.make_model("tiny")
    seq = 64
    rng = np.random.default_rng(0)
    batch = {"input_ids": np.asarray(
        rng.integers(0, cfg.vocab_size, (BATCH, seq)), np.int32)}

    def run(send_interval, push_async):
        engine = TrainEngine(model, seq_len=seq)
        transport = SlowTransport()
        loop = MinerLoop(engine, transport, "bench-push",
                         send_interval=send_interval,
                         check_update_interval=1e9, log_every=10**9,
                         push_async=push_async)
        loop.bootstrap(jax.random.PRNGKey(0))

        def batches():
            while True:
                yield batch

        loop.run(batches(), max_steps=2)   # warm compiles outside timing
        t0 = time.perf_counter()
        loop.run(batches(), max_steps=steps)
        dt = time.perf_counter() - t0      # steady-state cadence only:
        loop.flush()                       # the final drain is shutdown
        assert loop.report.last_loss == loop.report.last_loss
        return dt, loop, transport

    # interleaved base/sync/async triplets (scripts/measure.sh rule 4:
    # this rig drifts run-to-run, only within-group contrasts count)
    base_dts, sync_dts, async_dts = [], [], []
    for _ in range(2):
        base_dts.append(run(1e9, False)[0])           # no pushes at all
        sync_dt, sync_loop, sync_t = run(push_every_s, False)
        async_dt, async_loop, async_t = run(push_every_s, True)
        sync_dts.append(sync_dt)
        async_dts.append(async_dt)
    base_dt = float(np.mean(base_dts))
    sync_dt = float(np.mean(sync_dts))
    async_dt = float(np.mean(async_dts))

    pushes = steps  # send_interval=0 fires the push action on every step
    stall_sync = max(0.0, sync_dt - base_dt)
    stall_async = max(0.0, async_dt - base_dt)
    out = {
        "push_latency_ms": round(latency_s * 1e3, 1),
        "push_steps": steps,
        "push_count_sync": sync_loop.report.pushes,
        "push_count_async": async_loop.report.pushes
        + async_loop.report.pushes_superseded,
        "push_stall_ms": round(stall_sync / pushes * 1e3, 2),
        "push_stall_async_ms": round(stall_async / pushes * 1e3, 2),
        "push_overlap_speedup": round(sync_dt / max(async_dt, 1e-9), 3),
        "push_stall_removed": round(
            1.0 - stall_async / stall_sync, 3) if stall_sync > 0 else None,
        "push_parity": bool(sync_t._deltas.get("bench-push")
                            == async_t._deltas.get("bench-push")),
    }
    return out


def _time_gather_deltas(*, n_miners: int = 4, latency_s: float = 0.05,
                        trials: int = 2) -> dict:
    """Averager ingest A/B over localfs (round-9 tentpole): serial ingest
    (1 worker, cache disabled — the shape of the pre-ingest gather loop)
    vs the pooled + content-addressed-cached ingestor
    (engine/ingest.py), staging the IDENTICAL artifacts.

      averager_ingest_serial_ms   serial cold round (per-miner sequential
                                  fetch+decode)
      averager_ingest_ms          pooled cold round (all fetches in
                                  flight at once, fused cohort screen)
      averager_ingest_warm_ms     pooled round with unchanged revisions —
                                  revision probes only, zero downloads
      ingest_speedup_cold/warm    serial / pooled wall-clock
      ingest_warm_downloads       artifact fetches in the warm round
                                  (acceptance: exactly 0)
      ingest_parity               accepted ids + delta bytes identical in
                                  both modes

    CPU-measurable: the contrast is transport latency overlap and skipped
    downloads — host/network time that exists identically on every
    backend. The simulated per-fetch latency is conservative vs a real
    Hub LFS pull (O(seconds) in every E2E round artifact)."""
    import shutil
    import tempfile

    from distributedtraining_tpu import serialization as ser
    from distributedtraining_tpu.engine.ingest import DeltaIngestor
    from distributedtraining_tpu.models import gpt2
    from distributedtraining_tpu.transport import LocalFSTransport

    model, cfg = gpt2.make_model("tiny")
    base = model.init_params(jax.random.PRNGKey(0))
    host = jax.tree_util.tree_map(
        lambda x: np.zeros(x.shape, x.dtype), base)

    tmp = tempfile.mkdtemp(prefix="ingest_bench_")
    try:
        downloads = []

        class SlowFS(LocalFSTransport):
            def fetch_delta_bytes(self, miner_id):
                time.sleep(latency_s)   # simulated network pull
                downloads.append(miner_id)
                return super().fetch_delta_bytes(miner_id)

        transport = SlowFS(tmp)
        hotkeys = [f"m{i}" for i in range(n_miners)]
        key = jax.random.PRNGKey(1)
        leaves, treedef = jax.tree_util.tree_flatten(base)
        for i, h in enumerate(hotkeys):
            key, k = jax.random.split(key)
            ks = jax.random.split(k, len(leaves))
            transport.publish_delta(h, jax.tree_util.tree_unflatten(
                treedef, [0.01 * jax.random.normal(s, l.shape, l.dtype)
                          for s, l in zip(ks, leaves)]))
            transport.publish_delta_meta(
                h, {"base_revision": "r1", "delta_id": f"{h}-000001"})

        serial = DeltaIngestor(transport, host, workers=1, cache_bytes=0,
                               max_delta_abs=1e3)
        pooled = DeltaIngestor(transport, host,
                               workers=min(8, n_miners),
                               max_delta_abs=1e3)
        try:
            serial.stage(hotkeys)   # warm the fused screen's compile
            pooled.cache.clear()

            def timed(ing, *, clear: bool):
                if clear:
                    ing.cache.clear()
                t0 = time.perf_counter()
                staged = ing.stage(hotkeys)
                return time.perf_counter() - t0, staged

            # interleaved serial/cold/warm triplets (measure.sh rule 4)
            t_serial, t_cold, t_warm = [], [], []
            staged_serial = staged_cold = staged_warm = None
            warm_downloads = 0
            for _ in range(trials):
                dt, staged_serial = timed(serial, clear=True)
                t_serial.append(dt)
                dt, staged_cold = timed(pooled, clear=True)
                t_cold.append(dt)
                downloads.clear()
                dt, staged_warm = timed(pooled, clear=False)
                t_warm.append(dt)
                warm_downloads += len(downloads)

            def accepted(staged):
                return [(s.hotkey, ser.to_msgpack(s.delta))
                        for s in staged if s.delta is not None]

            parity = (accepted(staged_serial) == accepted(staged_cold)
                      == accepted(staged_warm))
            ser_ms = float(np.mean(t_serial)) * 1e3
            cold_ms = float(np.mean(t_cold)) * 1e3
            warm_ms = float(np.mean(t_warm)) * 1e3
            return {
                "ingest_miners": n_miners,
                "ingest_fetch_latency_ms": round(latency_s * 1e3, 1),
                "averager_ingest_serial_ms": round(ser_ms, 2),
                "averager_ingest_ms": round(cold_ms, 2),
                "averager_ingest_warm_ms": round(warm_ms, 2),
                "ingest_speedup_cold": round(ser_ms / max(cold_ms, 1e-9),
                                             3),
                "ingest_speedup_warm": round(ser_ms / max(warm_ms, 1e-9),
                                             3),
                "ingest_warm_downloads": warm_downloads,
                "ingest_parity": bool(parity),
            }
        finally:
            serial.close()
            pooled.close()
    finally:
        shutil.rmtree(tmp, ignore_errors=True)


def _time_wire_v2(*, trials: int = 2) -> dict:
    """Delta wire A/B over localfs (round-12 tentpole): the dense v1
    msgpack push+gather vs the v2 sparse+quantized shard wire (density
    1/64, int8) on the IDENTICAL delta tree.

      wire_dense_bytes_per_push   bytes one v1 push lands on the
                                  transport (full f32 msgpack)
      wire_v2_bytes_per_push      bytes a COLD v2 push lands (all
                                  shards + manifest)
      wire_v2_warm_push_bytes     bytes a warm push lands when ONE
                                  layer changed (changed shard +
                                  manifest only — publisher dedupe)
      wire_bytes_ratio            dense / v2 cold (acceptance: >= 10)
      wire_encode_ms/decode_ms    pack+shard / assemble+densify host
                                  cost per push
      wire_warm_fetch_bytes       ingest bytes for the warm 1-layer
                                  round (manifest + 1 shard)
      wire_unchanged_layer_bytes  ingest bytes for unchanged layers in
                                  that round (acceptance: exactly 0 —
                                  shard-granular dedupe)
      wire_warm_shard_hit_rate    shard-cache hit fraction that round
      wire_parity                 staged v2 delta == reference
                                  sparsify+quantize decode, dense
                                  staging unchanged

    CPU-measurable: the contrast is artifact BYTES and host codec work —
    transport-independent quantities that exist identically on the Hub
    (where each byte additionally pays LFS round trips)."""
    import shutil
    import tempfile

    from distributedtraining_tpu import delta as delta_lib
    from distributedtraining_tpu import serialization as ser
    from distributedtraining_tpu.engine.ingest import DeltaIngestor
    from distributedtraining_tpu.engine.publish import DeltaPublisher
    from distributedtraining_tpu.models import gpt2
    from distributedtraining_tpu.transport import LocalFSTransport

    model, _ = gpt2.make_model("tiny")
    base = jax.device_get(model.init_params(jax.random.PRNGKey(0)))
    template = jax.tree_util.tree_map(
        lambda x: np.zeros(x.shape, np.float32), base)
    rs = np.random.RandomState(0)
    delta = jax.tree_util.tree_map(
        lambda x: (rs.randn(*np.shape(x)) * 0.01).astype(np.float32),
        template)

    class Report:
        pushes = pushes_failed = pushes_superseded = 0

    tmp = tempfile.mkdtemp(prefix="wire_bench_")
    published: list[tuple[str, int]] = []
    fetched: list[tuple[str, int]] = []

    class CountFS(LocalFSTransport):
        def publish_raw(self, mid, data):
            published.append((mid, len(data)))
            return super().publish_raw(mid, data)

        def fetch_delta_bytes(self, mid):
            d = super().fetch_delta_bytes(mid)
            if d is not None:
                fetched.append((mid, len(d)))
            return d

    try:
        transport = CountFS(tmp)
        # -- dense v1 push (file size IS the artifact bytes) ------------
        pub_dense = DeltaPublisher(transport, "dense0", report=Report())
        assert pub_dense.publish_now(delta, None, "r1")
        dense_bytes = os.path.getsize(
            os.path.join(tmp, "deltas", "dense0.msgpack"))

        # -- v2 cold push ----------------------------------------------
        pub = DeltaPublisher(
            transport, "m0", report=Report(),
            wire_spec={"format": 2, "density": 1 / 64, "quant": "int8"})
        # warm the pack programs first (one trace+compile per leaf shape;
        # a miner pays that once per run, not per push) so encode_ms is
        # the steady-state number
        pack = jax.jit(lambda d: delta_lib.pack_delta_v2(d, density=1 / 64))
        jax.block_until_ready(pack(delta))
        enc_ms = []
        t0 = time.perf_counter()
        packed, _res = jax.device_get(pack(delta))
        enc_ms.append((time.perf_counter() - t0) * 1e3)
        published.clear()
        assert pub.publish_now(packed, None, "r1")
        v2_cold_bytes = sum(n for _, n in published)

        # -- cold gather + parity --------------------------------------
        ing = DeltaIngestor(transport, template, workers=2,
                            max_delta_abs=1e3)
        try:
            staged = {s.hotkey: s for s in ing.stage(["dense0", "m0"])}
            ref = delta_lib.densify_packed_v2(packed, template)
            parity = all(
                np.array_equal(a, b) for a, b in
                zip(jax.tree_util.tree_leaves(staged["m0"].delta),
                    jax.tree_util.tree_leaves(ref))) and all(
                np.allclose(a, b) for a, b in
                zip(jax.tree_util.tree_leaves(staged["dense0"].delta),
                    jax.tree_util.tree_leaves(delta)))

            # -- warm rounds: ONE layer changes per trial ---------------
            warm_push, warm_fetch, unchanged_bytes, hits = [], [], [], []
            dec_ms = []
            d2 = delta
            for i in range(trials):
                d2 = dict(d2)
                # perturb one LARGE tensor (wte) so exactly one sharded
                # layer changes
                d2["wte"] = (d2["wte"] + 0.001 * (i + 1)).astype(np.float32)
                # the SAME jitted program as the cold push: shard bytes
                # are reproducible within one compiled encoder (how a
                # real miner runs), which is what makes unchanged layers
                # hash-identical push over push
                p2, _ = jax.device_get(pack(d2))
                published.clear()
                assert pub.publish_now(p2, None, "r1")
                warm_push.append(sum(n for _, n in published))
                fetched.clear()
                t0 = time.perf_counter()
                s = ing.stage(["m0"])[0]
                dec_ms.append((time.perf_counter() - t0) * 1e3)
                assert s.ok
                warm_fetch.append(sum(n for _, n in fetched))
                unchanged_bytes.append(sum(
                    n for mid, n in fetched
                    if mid.startswith("__shard__.") and "wte" not in mid))
                n_layers = len(delta_lib.packed_layer_entries(p2))
                n_fetched_shards = sum(
                    1 for mid, _ in fetched if mid.startswith("__shard__."))
                hits.append(1.0 - n_fetched_shards / n_layers)
        finally:
            ing.close()
            pub.close()
            pub_dense.close()

        return {
            "wire_dense_bytes_per_push": int(dense_bytes),
            "wire_v2_bytes_per_push": int(v2_cold_bytes),
            "wire_v2_warm_push_bytes": int(np.mean(warm_push)),
            "wire_bytes_ratio": round(dense_bytes / max(v2_cold_bytes, 1),
                                      2),
            "wire_encode_ms": round(float(np.mean(enc_ms)), 2),
            "wire_decode_ms": round(float(np.mean(dec_ms)), 2),
            "wire_warm_fetch_bytes": int(np.mean(warm_fetch)),
            "wire_unchanged_layer_bytes": int(sum(unchanged_bytes)),
            "wire_warm_shard_hit_rate": round(float(np.mean(hits)), 3),
            "wire_parity": bool(parity),
        }
    finally:
        shutil.rmtree(tmp, ignore_errors=True)


def _time_base_distribution(*, trials: int = 1) -> dict:
    """Base-distribution A/B over localfs (round-19 tentpole): the
    monolithic fetch_base pull vs the content-addressed sharded
    delta-pull (engine/basedist.py) of the IDENTICAL base tree.

      base_mono_bytes_per_pull    bytes one monolithic pull moves
                                  (the full model, every round)
      base_dist_cold_bytes        bytes the FIRST sharded pull moves
                                  (manifest + every shard — a cold
                                  fetcher pays the model once)
      base_dist_warm_bytes        bytes a warm pull moves when ONE
                                  layer changed (manifest + 1 shard)
      base_warm_bytes_ratio       mono / sharded-warm (acceptance:
                                  >= 5 — the ISSUE's byte-reduction
                                  gate)
      base_unchanged_layer_bytes  shard bytes fetched for UNCHANGED
                                  layers that round (acceptance:
                                  exactly 0 — store-granular dedupe)
      base_warm_hit_rate          store hit fraction that round
      base_mono_fetch_ms /        end-to-end host cost of one warm
      base_dist_fetch_ms          pull, each path
      base_dist_parity            sharded tree == monolithic tree,
                                  bit-exact (the fetched base IS the
                                  published base either way)

    trials=1 and a mini GPT2Config: the contrast is artifact BYTES —
    a transport-independent quantity — and the tier-1 budget is
    tight."""
    import shutil
    import tempfile

    from distributedtraining_tpu.engine.basedist import (BaseFetcher,
                                                         BasePublisher)
    from distributedtraining_tpu.models import gpt2
    from distributedtraining_tpu.transport import LocalFSTransport

    cfg = gpt2.GPT2Config(vocab_size=256, n_positions=64, n_embd=32,
                          n_head=2, n_layer=2)
    model, cfg = gpt2.make_model(cfg)
    base = jax.device_get(model.init_params(jax.random.PRNGKey(0)))
    template = jax.tree_util.tree_map(
        lambda x: np.zeros(np.shape(x), np.asarray(x).dtype), base)

    tmp = tempfile.mkdtemp(prefix="basedist_bench_")
    fetched: list[tuple[str, int]] = []

    class CountFS(LocalFSTransport):
        def fetch_delta_bytes(self, mid):
            d = super().fetch_delta_bytes(mid)
            if d is not None:
                fetched.append((mid, len(d)))
            return d

        def fetch_base_bytes(self):
            d = super().fetch_base_bytes()
            if d is not None:
                fetched.append(("__mono__", len(d)))
            return d

    try:
        transport = CountFS(tmp)
        pub = BasePublisher(transport)
        rev = transport.publish_base(base)
        assert pub.publish_revision(base, rev)
        mono_bytes = os.path.getsize(
            os.path.join(tmp, "base", "averaged_model.msgpack"))

        # -- cold sharded pull + parity vs monolithic -------------------
        f = BaseFetcher(transport)
        fetched.clear()
        got = f.fetch(template)
        assert got is not None and got[1] == rev
        cold_bytes = sum(n for _, n in fetched)
        mono = transport.fetch_base(template)
        parity = mono is not None and all(
            np.array_equal(a, b) for a, b in
            zip(jax.tree_util.tree_leaves(got[0]),
                jax.tree_util.tree_leaves(mono[0])))

        # -- warm rounds: ONE layer changes per trial (wpe — a mid-size
        # tensor; the sparse-delta merge regime moves a few layers per
        # round, not the whole tree, and the A/B isolates exactly that)
        warm_bytes, unchanged, hits = [], [], []
        dist_ms, mono_ms = [], []
        b2 = dict(base)
        for i in range(trials):
            b2 = dict(b2)
            b2["wpe"] = (np.asarray(b2["wpe"])
                         + np.float32(0.001 * (i + 1)))
            rev2 = transport.publish_base(b2)
            assert pub.publish_revision(b2, rev2)
            fetched.clear()
            lookups0 = f.shard_lookups_total
            hits0 = f.store_hits_total
            t0 = time.perf_counter()
            got2 = f.fetch(template)
            dist_ms.append((time.perf_counter() - t0) * 1e3)
            assert got2 is not None and got2[1] == rev2
            assert f.fallbacks_total == 0   # stayed on the shard plane
            warm_bytes.append(sum(n for _, n in fetched))
            unchanged.append(sum(
                n for mid, n in fetched
                if mid.startswith("__base__.s.") and "wpe" not in mid))
            looked = f.shard_lookups_total - lookups0
            hits.append((f.store_hits_total - hits0) / max(1, looked))
            parity = parity and np.array_equal(got2[0]["wpe"], b2["wpe"])
            t0 = time.perf_counter()
            mono2 = transport.fetch_base(template)
            mono_ms.append((time.perf_counter() - t0) * 1e3)
            parity = parity and mono2 is not None and all(
                np.array_equal(a, b) for a, b in
                zip(jax.tree_util.tree_leaves(got2[0]),
                    jax.tree_util.tree_leaves(mono2[0])))

        warm = float(np.mean(warm_bytes))
        return {
            "base_mono_bytes_per_pull": int(mono_bytes),
            "base_dist_cold_bytes": int(cold_bytes),
            "base_dist_warm_bytes": int(warm),
            "base_warm_bytes_ratio": round(mono_bytes / max(warm, 1.0), 1),
            "base_unchanged_layer_bytes": int(sum(unchanged)),
            "base_warm_hit_rate": round(float(np.mean(hits)), 3),
            "base_mono_fetch_ms": round(float(np.mean(mono_ms)), 2),
            "base_dist_fetch_ms": round(float(np.mean(dist_ms)), 2),
            "base_dist_parity": bool(parity),
        }
    finally:
        shutil.rmtree(tmp, ignore_errors=True)


def _time_hier_average(*, n_miners: int = 32, fanout: int = 4,
                       trials: int = 2) -> dict:
    """Hierarchical averager A/B (round-13 tentpole): the flat
    single-node merge (one node stages + merges EVERY miner, the
    reference topology) vs a fanout-``fanout`` tree
    (engine/hier_average.py: each sub-averager stages + folds + publishes
    its slice, the root stages + merges the partial aggregates), over
    localfs on the IDENTICAL mixed v1/v2 submissions.

      hier_flat_node_ms        one flat round: stage all miners + merge
      hier_sub_node_ms         slowest sub-averager round (stage slice +
                               fold + publish the aggregate)
      hier_root_node_ms        root round: stage aggregates + merge
      hier_per_node_ms         max(sub, root) — the tree's critical node
      hier_worknode_reduction  flat / per-node (acceptance: >= 2 at
                               n_miners/fanout >= 2 subtrees)
      hier_parity              root merge == flat weighted merge of the
                               same set (fp tolerance)
      hier_packed_peak_delta_bytes / hier_packed_stack_free
                               device peak-bytes growth across an
                               all-packed scatter-add aggregate of every
                               miner vs the M x params stack it must NOT
                               materialize (None when the backend
                               exposes no memory stats — CPU; the
                               structural pin lives in
                               tests/test_hier_average.py)

    CPU-measurable: per-node cost is transport fetch + decode + screen +
    merge arithmetic over that node's slice — host work that shrinks
    with the slice on every backend."""
    import shutil
    import tempfile

    from distributedtraining_tpu import delta as delta_lib
    from distributedtraining_tpu.engine.hier_average import (SubAverager,
                                                             plan_fanout)
    from distributedtraining_tpu.engine.ingest import DeltaIngestor
    from distributedtraining_tpu.engine.publish import DeltaPublisher
    from distributedtraining_tpu.models import gpt2
    from distributedtraining_tpu.transport import LocalFSTransport
    from distributedtraining_tpu.transport.base import agg_id
    from distributedtraining_tpu.utils.metrics import device_memory_watermarks

    model, _ = gpt2.make_model("tiny")
    template = jax.tree_util.tree_map(
        lambda x: np.zeros(x.shape, np.float32),
        jax.eval_shape(lambda: model.init_params(jax.random.PRNGKey(0))))

    class Report:
        pushes = pushes_failed = pushes_superseded = 0

    tmp = tempfile.mkdtemp(prefix="hier_bench_")
    try:
        transport = LocalFSTransport(tmp)
        transport.publish_base(template)
        hotkeys = [f"m{i:02d}" for i in range(n_miners)]
        rs = np.random.RandomState(0)
        consensus = {h: float(rs.uniform(0.5, 2.0)) for h in hotkeys}
        deltas = {}
        packed_all = []
        for i, h in enumerate(hotkeys):
            d = jax.tree_util.tree_map(
                lambda x: (np.random.RandomState(i).randn(*np.shape(x))
                           * 0.01).astype(np.float32), template)
            deltas[h] = d
            p = jax.device_get(delta_lib.pack_delta_v2(d,
                                                       density=1 / 64)[0])
            packed_all.append(p)
            if i % 4 == 0:   # every 4th miner publishes on the v2 wire
                pub = DeltaPublisher(
                    transport, h, report=Report(),
                    wire_spec={"format": 2, "density": 1 / 64,
                               "quant": "int8"})
                try:
                    assert pub.publish_now(p, None, None)
                finally:
                    pub.close()
                deltas[h] = delta_lib.densify_packed_v2(p, template)
            else:
                transport.publish_delta(h, d)

        plan = plan_fanout(hotkeys, fanout=fanout)
        nodes = sorted(plan)
        subs = {n: SubAverager(transport, n, template, plan[n],
                               consensus=consensus, ingest_cache_mb=0,
                               ingest_workers=4) for n in nodes}
        flat_ing = DeltaIngestor(transport, template, workers=4,
                                 cache_bytes=0, max_delta_abs=1e3)
        root_ing = DeltaIngestor(transport, template, workers=4,
                                 cache_bytes=0, max_delta_abs=1e3)
        try:
            def flat_round():
                staged = {s.hotkey: s for s in flat_ing.stage(hotkeys)
                          if s.ok}
                ids = sorted(staged)
                w = delta_lib.normalized_merge_weights(ids, consensus)
                agg = delta_lib.aggregate_deltas(
                    template, [staged[h].delta for h in ids], w)
                return jax.block_until_ready(agg), len(ids)

            def root_round():
                staged = [s for s in root_ing.stage(
                    [agg_id(n) for n in nodes]) if s.ok]
                ids = [s.hotkey for s in staged]
                cons = {s.hotkey: (s.agg_weight if s.agg_weight is not None
                                   else 1.0) for s in staged}
                w = delta_lib.normalized_merge_weights(ids, cons)
                agg = delta_lib.aggregate_deltas(
                    template, [s.delta for s in staged], w)
                return jax.block_until_ready(agg), len(ids)

            # warm every compile + publish the first aggregates
            flat_round()
            for n in nodes:
                assert subs[n].run_round() is True
            root_round()

            flat_ms, sub_ms, root_ms = [], [], []
            flat_agg = root_agg = None
            for _ in range(trials):
                t0 = time.perf_counter()
                flat_agg, n_flat = flat_round()
                flat_ms.append((time.perf_counter() - t0) * 1e3)
                worst = 0.0
                for n in nodes:
                    t0 = time.perf_counter()
                    assert subs[n].run_round() is True
                    worst = max(worst, (time.perf_counter() - t0) * 1e3)
                sub_ms.append(worst)
                t0 = time.perf_counter()
                root_agg, n_root = root_round()
                root_ms.append((time.perf_counter() - t0) * 1e3)
            assert n_flat == n_miners and n_root == len(nodes)

            parity_err = max(
                float(np.max(np.abs(np.asarray(a) - np.asarray(b))))
                for a, b in zip(jax.tree_util.tree_leaves(flat_agg),
                                jax.tree_util.tree_leaves(root_agg)))

            # packed scatter-add memory: peak-bytes growth across an
            # all-packed aggregate of every miner must stay far under
            # the M x params stack it replaces (backend stats only)
            params_bytes = sum(l.nbytes for l in
                               jax.tree_util.tree_leaves(template))
            before = device_memory_watermarks().get("mem_peak_bytes")
            packed_agg = delta_lib.aggregate_deltas(
                template, packed_all,
                np.full((n_miners,), 1.0 / n_miners, np.float32))
            jax.block_until_ready(packed_agg)
            after = device_memory_watermarks().get("mem_peak_bytes")
            if before is not None and after is not None:
                peak_delta = int(after - before)
                stack_free = peak_delta < n_miners * params_bytes // 2
            else:
                peak_delta = stack_free = None

            flat = float(np.mean(flat_ms))
            sub = float(np.mean(sub_ms))
            root = float(np.mean(root_ms))
            per_node = max(sub, root)
            return {
                "hier_miners": n_miners,
                "hier_fanout": fanout,
                "hier_subaveragers": len(nodes),
                "hier_flat_node_ms": round(flat, 2),
                "hier_sub_node_ms": round(sub, 2),
                "hier_root_node_ms": round(root, 2),
                "hier_per_node_ms": round(per_node, 2),
                "hier_worknode_reduction": round(flat / max(per_node,
                                                            1e-9), 3),
                "hier_parity_max_abs_err": float(parity_err),
                "hier_parity": bool(parity_err < 1e-5),
                "hier_packed_peak_delta_bytes": peak_delta,
                "hier_packed_stack_free": stack_free,
            }
        finally:
            flat_ing.close()
            root_ing.close()
            for s in subs.values():
                s.close()
    finally:
        shutil.rmtree(tmp, ignore_errors=True)


def _time_serve(*, n_requests: int = 8, prompt_len: int = 16,
                gen_tokens: int = 24, trials: int = 2) -> dict:
    """Serving-plane A/B (round-14 tentpole): naive sequential
    per-request generation — one jitted FULL forward of the padded
    sequence per token, requests one after another, the only spelling
    available before engine/serve.py — vs the continuous-batching paged-
    KV engine decoding all ``n_requests`` in one rolling batch. Both
    sides are greedy and parity-checked token-for-token. Also measured:
    the hot-swap stall (must sit below one decode-step p95 — the swap is
    a pointer rebind, the fetch/stage happened off-thread) and fresh
    compiles over a steady-state decode window (must be ZERO: the bucket
    ladders are warm after the first batch)."""
    from distributedtraining_tpu.engine.serve import GenerationEngine
    from distributedtraining_tpu.models import gpt2
    from distributedtraining_tpu.utils import obs

    cfg = gpt2.GPT2Config(vocab_size=256, n_positions=128, n_embd=64,
                          n_layer=2, n_head=4, dtype="float32",
                          vocab_multiple=128)
    model, cfg = gpt2.make_model(cfg)
    params = model.init_params(jax.random.PRNGKey(0), seq_len=8)
    params2 = model.init_params(jax.random.PRNGKey(7), seq_len=8)
    rng = np.random.RandomState(0)
    prompts = [list(rng.randint(0, cfg.vocab_size, size=prompt_len))
               for _ in range(n_requests)]
    T = prompt_len + gen_tokens

    naive_prog = jax.jit(
        lambda p, toks, cur: jnp.argmax(
            model.apply({"params": p}, toks,
                        attention_mask=(jnp.arange(T)[None, :]
                                        < cur).astype(jnp.int32)
                        )[0, cur - 1, :cfg.vocab_size]).astype(jnp.int32))

    def naive_all() -> list[list[int]]:
        outs = []
        for p in prompts:
            buf = np.zeros((1, T), np.int32)
            buf[0, :len(p)] = p
            cur, toks = len(p), []
            for _ in range(gen_tokens):
                nxt = int(naive_prog(params, buf, np.int32(cur)))
                buf[0, cur] = nxt
                toks.append(nxt)
                cur += 1
            outs.append(toks)
        return outs

    class _Sink:           # live registry for serve.* / compile.ms reads
        def log(self, *a, **k):
            pass

    obs.configure(_Sink(), role="bench")
    try:
        engine = GenerationEngine(model, params, revision="r1",
                                  max_slots=n_requests, page_size=16,
                                  max_seq_len=((T + 15) // 16) * 16)
        ref = naive_all()                       # compile + oracle
        assert engine.generate(prompts, gen_tokens) == ref, \
            "serve engine diverged from the naive loop"   # warm + parity
        reg = obs.registry()
        naive_s = engine_s = 0.0
        fresh_compiles = 0
        for _ in range(trials):                 # interleaved, like _ab_pairs
            t0 = time.perf_counter()
            naive_all()
            naive_s += time.perf_counter() - t0
            before = reg.histogram("compile.ms").count
            t0 = time.perf_counter()
            engine.generate(prompts, gen_tokens)
            engine_s += time.perf_counter() - t0
            fresh_compiles += reg.histogram("compile.ms").count - before
        total = trials * n_requests * gen_tokens
        naive_tps = total / naive_s
        engine_tps = total / engine_s
        step_p = reg.histogram("serve.step_ms").percentiles((50.0, 95.0))
        tok_p = reg.histogram("serve.token_ms").percentiles((50.0, 95.0))
        # hot swap: stage off-line (as the watcher thread would), then one
        # idle-engine step installs it; the stall is what the decode loop
        # actually paused for
        engine._pending_swap = ("r2", jax.device_put(params2))
        engine.step()
        assert engine.revision == "r2"
        swap_ms = reg.histogram("serve.swap_stall_ms").percentiles(
            (95.0,))["p95"]
        engine.close()

        # sampled-decode lane (round-16): a mixed greedy/sampled batch
        # through the sampled program family, run TWICE — wave 2 must
        # add zero fresh compiles (the (slot,page) ladder is shared and
        # temperature rides as data, not as a program variant), greedy
        # lanes must still match the oracle, and the sampled lanes must
        # be bit-identical across waves (seeded per-request PRNG)
        def mixed_run(eng):
            reqs = [eng.submit(p, gen_tokens) if i % 2 == 0 else
                    eng.submit(p, gen_tokens, temperature=0.8,
                               top_p=0.95, seed=17 + i)
                    for i, p in enumerate(prompts)]
            while not all(r.done_evt.is_set() for r in reqs):
                eng.step()
            return [list(r.tokens) for r in reqs]

        s_eng = GenerationEngine(model, params, revision="r1",
                                 max_slots=n_requests, page_size=16,
                                 max_seq_len=((T + 15) // 16) * 16)
        wave1 = mixed_run(s_eng)                 # warm the sampled family
        before = reg.histogram("compile.ms").count
        wave2 = mixed_run(s_eng)
        sampled_fresh = reg.histogram("compile.ms").count - before
        s_eng.close()
        sampled_greedy_parity = all(wave1[i] == ref[i]
                                    for i in range(0, n_requests, 2))

        # warm-prefix lane (round-16): every request shares a system
        # prompt two pages long; request 1 prefills it cold, the rest
        # reuse the cached pages (suffix-only prefill). Parity-pinned
        # against a cache-off engine over the same prompts.
        sys_prompt = list(rng.randint(0, cfg.vocab_size, size=32))
        tails = [list(rng.randint(0, cfg.vocab_size, size=8))
                 for _ in range(n_requests)]
        pfx_prompts = [sys_prompt + t for t in tails]
        pfx_T = len(sys_prompt) + 8 + gen_tokens   # own geometry: the
        pfx_seq = ((pfx_T + 15) // 16) * 16        # shared prompt is
        plain = GenerationEngine(model, params, max_slots=n_requests,
                                 page_size=16,     # longer than the A/B's
                                 max_seq_len=pfx_seq)
        pfx_ref = plain.generate(pfx_prompts, gen_tokens)
        plain.close()
        pfx_eng = GenerationEngine(model, params, max_slots=n_requests,
                                   page_size=16, prefix_cache=True,
                                   max_seq_len=pfx_seq)
        cold = pfx_eng.generate(pfx_prompts[:1], gen_tokens)   # seeds cache
        warm = pfx_eng.generate(pfx_prompts[1:], gen_tokens)
        pfx_parity = (cold + warm) == pfx_ref
        pfx_hit_rate = pfx_eng.prefix_hit_rate
        pfx_saved = pfx_eng.prefix_tokens_saved
        pfx_eng.close()

        # request-trace overhead lane (round-18 tentpole): ONE engine,
        # with its TraceBook toggled every OTHER STEP. Two separately
        # constructed engines disagree by ±6% from heap/dispatch-cache
        # placement alone (a two-engine null test shows it), and even
        # per-wave pairing wanders ±5% on a shared rig — so the A/B
        # interleaves at the finest grain the workload has: adjacent
        # full-batch steps, one traced, one not, inside the SAME
        # generation (every trace site guards ``if self.trace is not
        # None``, so mid-flight toggling is safe and output-invariant).
        # Adjacent steps share the rig's instantaneous state; the
        # median over a few hundred adjacent-pair ratios nulls to
        # 1.000±0.01 on the same rig where wave medians read ±7%.
        # Contract: <2% step-cost shift, ZERO fresh compiles in the
        # timed window, bit-identical output with tracing on.
        seq = ((T + 15) // 16) * 16
        tr_eng = GenerationEngine(model, params, max_slots=n_requests,
                                  page_size=16, max_seq_len=seq,
                                  trace=True)
        tr_book = tr_eng.trace
        trace_parity = tr_eng.generate(prompts, gen_tokens) == ref
        tr_eng.trace = None
        trace_parity &= tr_eng.generate(prompts, gen_tokens) == ref
        tr_eng.trace = tr_book

        tr_ratios: list[float] = []
        before = reg.histogram("compile.ms").count
        gc_was_on = gc.isenabled()
        try:
            for w in range(24 * trials):
                gc.collect()
                gc.disable()
                reqs = [tr_eng.submit(p, gen_tokens) for p in prompts]
                i, prev = 0, None   # prev = (was_traced, duration)
                while not all(r.done_evt.is_set() for r in reqs):
                    # phase flips per wave so neither lane always
                    # follows the admit/drain edges
                    use_on = (i + w) % 2 == 1
                    tr_eng.trace = tr_book if use_on else None
                    full = len(tr_eng._active) == n_requests
                    done0 = sum(r.done_evt.is_set() for r in reqs)
                    t0 = time.perf_counter()
                    tr_eng.step()
                    d = time.perf_counter() - t0
                    # only saturated steady-state decode steps are
                    # comparable: admit/prefill and finish steps carry
                    # per-REQUEST work that amortizes to ~0.15% of a
                    # request's compute but would be sampled here as
                    # one fat step in ~24
                    pure = (full and done0 ==
                            sum(r.done_evt.is_set() for r in reqs))
                    if pure:
                        if prev is not None and prev[0] != use_on:
                            off_d, on_d = ((prev[1], d) if use_on
                                           else (d, prev[1]))
                            if off_d > 0:
                                tr_ratios.append(on_d / off_d)
                            prev = None
                        else:
                            prev = (use_on, d)
                    else:
                        prev = None
                    i += 1
                gc.enable()
        finally:
            if gc_was_on:
                gc.enable()
            tr_eng.trace = tr_book
        trace_fresh = reg.histogram("compile.ms").count - before
        tr_eng.close()
        trace_overhead = (float(np.median(tr_ratios)) - 1.0
                          if tr_ratios else 0.0)

        # the decode-attention kernel-vs-XLA micro A/B rides in the serve
        # record (round-20 tentpole): the engine-level numbers above
        # already RUN the kernel on TPU — this isolates its contribution
        try:
            attn_ab = _time_decode_attn_kernel()
        except Exception as e:   # a failed sub-bench never sinks serve
            attn_ab = {"decode_attn_error": repr(e)}
        return {
            **attn_ab,
            "serve_naive_tokens_per_sec": round(naive_tps, 1),
            "serve_batched_tokens_per_sec": round(engine_tps, 1),
            "serve_speedup": round(engine_tps / naive_tps, 3),
            "serve_batch": n_requests,
            "serve_token_ms_p50": round(tok_p["p50"], 3),
            "serve_token_ms_p95": round(tok_p["p95"], 3),
            "serve_step_ms_p95": round(step_p["p95"], 3),
            "serve_swap_stall_ms": round(swap_ms, 3),
            "serve_swap_under_step_p95": bool(swap_ms < step_p["p95"]),
            "serve_steady_fresh_compiles": int(fresh_compiles),
            "serve_parity": True,
            "serve_sampled_steady_fresh_compiles": int(sampled_fresh),
            "serve_sampled_deterministic": bool(wave1 == wave2),
            "serve_sampled_greedy_parity": bool(sampled_greedy_parity),
            "serve_prefix_hit_rate": round(pfx_hit_rate, 3),
            "serve_prefill_tokens_saved": int(pfx_saved),
            "serve_prefix_parity": bool(pfx_parity),
            "serve_trace_overhead_frac": round(trace_overhead, 4),
            "serve_trace_fresh_compiles": int(trace_fresh),
            "serve_trace_parity": bool(trace_parity),
        }
    finally:
        obs.reset()


def _time_serve_speculative(*, n_requests: int = 2, prompt_len: int = 16,
                            gen_tokens: int = 48, trials: int = 5,
                            ks=(2, 4, 8)) -> dict:
    """Speculative-decoding A/B (round-21 tentpole): plain greedy decode
    vs draft-and-verify at draft-k in ``ks``, parity-pinned token-for-
    token against the plain engine every run. The timed contrast rides a
    HOST toy drafter (ScriptedDraftSource over the precomputed oracle
    continuations — acceptance 1.0 by construction): one batched verify
    pass then commits K+1 tokens per dispatch, which is the mechanism
    being bought, and it stays rig-meaningful even on CPU where a real
    draft-model forward costs a full jit dispatch per proposed token
    (that model-draft lane runs once and reports acceptance only, with
    ``serve_spec_degraded_reason`` marking the rig). Steady-state fresh
    compiles across every timed wave must be ZERO — the verify family
    rides the same (slot, page) ladders as decode.

    Batch 2 on purpose: speculation buys dispatches, so its win lives
    where per-dispatch overhead dominates — the low-batch latency
    regime. At full batch the same rig is compute-bound and the verify
    pass's extra positions roughly cancel the dispatch savings (the
    per-K numbers record that curve; the gated speedup is best-K)."""
    from distributedtraining_tpu.engine.serve import GenerationEngine
    from distributedtraining_tpu.engine.speculative import (
        DraftEngine, ScriptedDraftSource)
    from distributedtraining_tpu.models import gpt2
    from distributedtraining_tpu.utils import obs

    cfg = gpt2.GPT2Config(vocab_size=256, n_positions=128, n_embd=64,
                          n_layer=2, n_head=4, dtype="float32",
                          vocab_multiple=128)
    model, cfg = gpt2.make_model(cfg)
    params = model.init_params(jax.random.PRNGKey(0), seq_len=8)
    rng = np.random.RandomState(3)
    prompts = [list(rng.randint(0, cfg.vocab_size, size=prompt_len))
               for _ in range(n_requests)]
    T = prompt_len + gen_tokens
    seq = ((T + 15) // 16) * 16

    class _Sink:           # live registry for compile.ms deltas
        def log(self, *a, **k):
            pass

    obs.configure(_Sink(), role="bench")
    try:
        plain = GenerationEngine(model, params, max_slots=n_requests,
                                 page_size=16, max_seq_len=seq)
        ref = plain.generate(prompts, gen_tokens)    # warm + oracle
        total = n_requests * gen_tokens
        reg = obs.registry()
        ref_map = {tuple(p): r for p, r in zip(prompts, ref)}

        def oracle(req, k):
            full = ref_map[tuple(req.prompt)]
            return full[len(req.tokens):len(req.tokens) + k]

        # The speedup is a RATIO of two short timed lanes, so the lanes
        # are interleaved wave-for-wave (rig-speed drift between lanes
        # would corrupt a sequential A-then-B measurement) and each lane
        # keeps its best wave — contention only ever slows a wave, so
        # min-of-trials is the tighter per-wave estimator on a shared rig.
        engines = {}
        parity = True
        for k in ks:
            engines[k] = GenerationEngine(
                model, params, max_slots=n_requests, page_size=16,
                max_seq_len=seq, draft=ScriptedDraftSource(oracle),
                draft_k=k, debug_invariants=True)
            parity = parity and engines[k].generate(prompts,
                                                    gen_tokens) == ref
        before = reg.histogram("compile.ms").count     # all warm above
        plain_s = float("inf")
        spent = {k: float("inf") for k in ks}
        for _ in range(trials):
            t0 = time.perf_counter()
            assert plain.generate(prompts, gen_tokens) == ref
            plain_s = min(plain_s, time.perf_counter() - t0)
            for k in ks:
                t0 = time.perf_counter()
                got = engines[k].generate(prompts, gen_tokens)
                spent[k] = min(spent[k], time.perf_counter() - t0)
                parity = parity and got == ref
        steady_fresh = reg.histogram("compile.ms").count - before
        plain.close()
        plain_tps = total / plain_s
        out = {
            "serve_spec_batch": n_requests,
            "serve_spec_plain_tokens_per_sec": round(plain_tps, 1),
            "serve_spec_plain_tpot_ms": round(plain_s / total * 1e3, 3),
        }
        best_k, best_tps = 0, 0.0
        for k in ks:
            tps = total / spent[k]
            out[f"serve_spec_tokens_per_sec_k{k}"] = round(tps, 1)
            out[f"serve_spec_tpot_ms_k{k}"] = round(
                spent[k] / total * 1e3, 3)
            out[f"serve_spec_accept_rate_k{k}"] = round(
                engines[k].spec_accept_rate, 3)
            engines[k].close()
            if tps > best_tps:
                best_tps, best_k = tps, k
        out["serve_spec_best_k"] = int(best_k)
        out["serve_spec_speedup"] = round(best_tps / plain_tps, 3)
        out["serve_spec_steady_fresh_compiles"] = int(steady_fresh)
        out["serve_spec_parity"] = bool(parity)

        # model-draft lane: a real DraftEngine self-drafting the target
        # (acceptance must be ~1.0 — it proves the draft-KV position /
        # commit bookkeeping, not wall-clock; a draft the target's own
        # size cannot win the dispatch-count race on any rig)
        d_eng = GenerationEngine(
            model, params, max_slots=n_requests, page_size=16,
            max_seq_len=seq, draft_k=4, debug_invariants=True,
            draft=DraftEngine(model, params, max_slots=n_requests,
                              page_size=16))
        out["serve_spec_model_draft_parity"] = bool(
            d_eng.generate(prompts, gen_tokens) == ref)
        out["serve_spec_model_draft_accept_rate"] = round(
            d_eng.spec_accept_rate, 3)
        d_eng.close()
        if jax.default_backend() == "cpu":
            out["serve_spec_degraded_reason"] = (
                "cpu rig: model-draft timing is dispatch-bound; the "
                "timed speedup rides the host toy drafter only")
        return out
    finally:
        obs.reset()


def _time_kv_transfer(*, n_requests: int = 6, prompt_len: int = 24,
                      gen_tokens: int = 16) -> dict:
    """KV transfer plane A/B (round-24 tentpole): the disaggregated
    export -> publish -> fetch -> adopt path between a prefill-phase
    worker and a decode-phase worker over an in-memory transport,
    against the unified engine as the oracle. Three pins ride along:
    (1) parity — the disaggregated output (prefill worker's first
    token re-emitted, decode worker's paged decode after page
    adoption) must be token-identical for greedy lanes and
    bit-identical for sampled lanes (the counter PRNG makes token
    index, not worker, the stream coordinate); (2) dedupe — a second
    wave over the same prompts must publish manifest-only bytes (the
    content-addressed shards are already in the store on both sides);
    (3) zero steady-state fresh compiles on BOTH worker classes (the
    adopt program compiles once in wave 1, the bucket ladders are
    phase-subset warm after it). The virtual-clock serve lane then
    contrasts a unified worker under the prefill head-of-line cost
    model against a 1-prefill + 1-decode pair at the same offered
    load — the tpot p95 gain is the number the fleetsim
    ``disagg_tpot_gain_min`` gate holds."""
    from distributedtraining_tpu.engine import kv_transfer as kvt
    from distributedtraining_tpu.engine.serve import GenerationEngine
    from distributedtraining_tpu.models import gpt2
    from distributedtraining_tpu.transport import InMemoryTransport
    from distributedtraining_tpu.utils import loadgen, obs

    cfg = gpt2.GPT2Config(vocab_size=256, n_positions=128, n_embd=64,
                          n_layer=2, n_head=4, dtype="float32",
                          vocab_multiple=128)
    model, cfg = gpt2.make_model(cfg)
    params = model.init_params(jax.random.PRNGKey(0), seq_len=8)
    rng = np.random.RandomState(3)
    prompts = [list(rng.randint(0, cfg.vocab_size, size=prompt_len))
               for _ in range(n_requests)]
    seq = ((prompt_len + gen_tokens + 15) // 16) * 16

    def _eng(**kw):
        return GenerationEngine(model, params, revision="r1",
                                max_slots=n_requests, page_size=16,
                                max_seq_len=seq, **kw)

    def _drain(eng, reqs):
        while not all(r.done_evt.is_set() for r in reqs):
            eng.step()

    def _submit_all(eng, wave, **extra):
        # even lanes greedy, odd lanes sampled — both must survive the
        # worker hop bit-identically
        return [eng.submit(p, gen_tokens,
                           request_id=f"bench-kv-w{wave}-{i}",
                           **(extra if i % 2 == 0 else
                              {**extra, "temperature": 0.8,
                               "top_p": 0.95, "seed": 17 + i}))
                for i, p in enumerate(prompts)]

    class _Sink:
        def log(self, *a, **k):
            pass

    obs.configure(_Sink(), role="bench")
    try:
        uni = _eng()
        ref_reqs = _submit_all(uni, 0)
        _drain(uni, ref_reqs)
        ref = [list(r.tokens) for r in ref_reqs]
        uni.close()

        tr = InMemoryTransport()
        exporter = kvt.KVExporter(tr)
        adopter = kvt.KVAdopter(tr)
        pe = _eng(phase="prefill", kv_exporter=exporter)
        de = _eng(phase="decode", kv_adopter=adopter)
        reg = obs.registry()

        def disagg_wave(wave):
            pre = _submit_all(pe, wave)
            _drain(pe, pre)
            dec = []
            for i, (p, r) in enumerate(zip(prompts, pre)):
                kw = {} if i % 2 == 0 else {"temperature": 0.8,
                                            "top_p": 0.95, "seed": 17 + i}
                dec.append(de.submit(p, gen_tokens, kv_ref=r.kv_ref,
                                     first_token=r.first_token, **kw))
            _drain(de, dec)
            return [list(r.tokens) for r in dec]

        t0 = time.perf_counter()
        wave1 = disagg_wave(1)                 # cold: real wire bytes
        wave1_s = time.perf_counter() - t0
        wire_bytes = exporter.bytes_published
        before = reg.histogram("compile.ms").count
        wave2 = disagg_wave(2)                 # warm: dedupe + no compiles
        steady_fresh = reg.histogram("compile.ms").count - before
        rewire_bytes = exporter.bytes_published - wire_bytes
        parity = (wave1 == ref) and (wave2 == ref)
        exp_p = reg.histogram("serve.kv_export_ms").percentiles(
            (50.0, 95.0))
        fetch_p = reg.histogram("serve.kv_fetch_ms").percentiles(
            (50.0, 95.0))
        adopt_p = reg.histogram("serve.kv_adopt_ms").percentiles((95.0,))
        out = {
            "kv_transfer_parity": bool(parity),
            "kv_transfer_wire_bytes": int(wire_bytes),
            "kv_transfer_bytes_per_request": int(wire_bytes // n_requests),
            "kv_transfer_rewire_bytes": int(rewire_bytes),
            "kv_transfer_pages_per_request": int(
                (prompt_len + 15) // 16),
            "kv_transfer_export_ms_p50": round(exp_p["p50"], 3),
            "kv_transfer_export_ms_p95": round(exp_p["p95"], 3),
            "kv_transfer_fetch_ms_p50": round(fetch_p["p50"], 3),
            "kv_transfer_fetch_ms_p95": round(fetch_p["p95"], 3),
            "kv_transfer_adopt_ms_p95": round(adopt_p["p95"], 3),
            "kv_transfer_wave_s": round(wave1_s, 3),
            "kv_transfer_adoptions": int(de.kv_adopted),
            "kv_transfer_reprefills": int(de.kv_reprefills),
            "kv_transfer_steady_fresh_compiles": int(steady_fresh),
        }
        pe.close()
        de.close()

        # virtual-clock serve lane: unified worker paying the prefill
        # head-of-line cost vs a phase-split pair at the same offered
        # load — deterministic (seeded arrivals, virtual step clock),
        # so the gain is rig-independent
        spec = loadgen.OpenLoopSpec(rate_rps=24.0, duration_s=4.0,
                                    seed=0, vocab=cfg.vocab_size,
                                    max_new_tokens=8)
        lane = _eng()
        u = loadgen.run_open_loop(lane, spec, prefill_busy_steps=4)
        lane.close()
        tr2 = InMemoryTransport()
        lp = _eng(phase="prefill", kv_exporter=kvt.KVExporter(tr2))
        ld = _eng(phase="decode", kv_adopter=kvt.KVAdopter(tr2))
        d = loadgen.run_open_loop_disagg([lp], [ld], spec,
                                         prefill_busy_steps=4)
        lp.close()
        ld.close()
        u95 = u["tpot_ms"]["p95"]
        d95 = d["tpot_ms"]["p95"]
        out.update({
            "serve_disagg_unified_tpot_p95_ms": round(u95, 3),
            "serve_disagg_tpot_p95_ms": round(d95, 3),
            "serve_disagg_tpot_gain": round(u95 / max(d95, 1e-9), 3),
            "serve_disagg_handoffs": int(d["handoffs"]),
            "serve_disagg_kv_adopted": int(d["kv_adopted"]),
            "serve_disagg_kv_reprefills": int(d["kv_reprefills"]),
        })
        return out
    finally:
        obs.reset()


def _time_decode_attn_kernel(*, B: int = 4, Hq: int = 4, Hkv: int = 2,
                             D: int = 64, P: int = 16, MP: int = 8,
                             iters: int = 20) -> dict:
    """Fused paged-attention decode kernel vs the XLA gather+attend
    spelling (round-20 tentpole, half a): one layer's decode attention
    at serving shapes, parity-pinned <= 1e-6. On TPU both sides are
    real device programs and the ratio is the per-token attention win;
    off-TPU the kernel runs INTERPRETED (a correctness lane, orders of
    magnitude slower by construction), so the timing contrast is marked
    ``degraded_cpu`` and only the parity bit is rig-meaningful. Both
    programs register in the device observatory (``serve.decode_attn``
    vs the XLA path inside ``serve.decode``), so on TPU the roofline
    achieved-bandwidth fraction rides ``prog_achieved`` into the
    --baseline regression gate."""
    from distributedtraining_tpu.ops import paged_attention as pa
    from distributedtraining_tpu.utils import devprof

    on_tpu = jax.default_backend() in ("tpu", "axon")
    pool = 1 + B * MP
    rng = np.random.RandomState(0)
    q = jnp.asarray(rng.standard_normal((B, 1, Hq, D)), jnp.float32)
    k_pages = jnp.asarray(
        rng.standard_normal((pool, P, Hkv, D)), jnp.float32)
    v_pages = jnp.asarray(
        rng.standard_normal((pool, P, Hkv, D)), jnp.float32)
    k_new = jnp.asarray(rng.standard_normal((B, 1, Hkv, D)), jnp.float32)
    v_new = jnp.asarray(rng.standard_normal((B, 1, Hkv, D)), jnp.float32)
    tables = jnp.asarray(
        1 + np.arange(B * MP).reshape(B, MP), jnp.int32)
    seq_lens = jnp.asarray(
        rng.randint(1, MP * P, size=(B,)), jnp.int32)

    ref_prog = jax.jit(pa.paged_decode_reference)  # devprof: exempt (bench A/B twin of the serve.decode in-step path)
    kernel = devprof.wrap(
        "serve.decode_attn",
        jax.jit(functools.partial(pa.paged_decode_attention,
                                  interpret=not on_tpu)),
        bucket=f"{B}x{MP}")

    ref = ref_prog(q, k_pages, v_pages, tables, seq_lens, k_new, v_new)
    out = kernel(q, k_pages, v_pages, tables, seq_lens, k_new, v_new)
    if out is None:
        return {"decode_attn_kernel": "declined"}
    parity = float(jnp.max(jnp.abs(out - ref)))

    def timed(fn, n):
        jax.block_until_ready(
            fn(q, k_pages, v_pages, tables, seq_lens, k_new, v_new))
        t0 = time.perf_counter()
        for _ in range(n):
            r = fn(q, k_pages, v_pages, tables, seq_lens, k_new, v_new)
        jax.block_until_ready(r)
        return (time.perf_counter() - t0) / n * 1e3

    # interpret mode is a correctness lane: one timed call is plenty
    n_kernel = iters if on_tpu else 1
    out = {
        "decode_attn_parity_err": parity,
        "decode_attn_parity": bool(parity < 1e-6),
        "decode_attn_xla_ms": round(timed(ref_prog, iters), 3),
        "decode_attn_kernel_ms": round(timed(kernel, n_kernel), 3),
        "decode_attn_shape": f"B{B} Hq{Hq} Hkv{Hkv} D{D} P{P} MP{MP}",
    }
    if not on_tpu:
        out["decode_attn_degraded_cpu"] = True   # interpreted kernel
    else:
        out["decode_attn_speedup"] = round(
            out["decode_attn_xla_ms"] / out["decode_attn_kernel_ms"], 3)
    return out


def _time_packed_ingest(*, n_miners: int = 8, trials: int = 2) -> dict:
    """Packed wire-v2 ingest A/B (round-20 tentpole, half b): folding M
    contributions into one f32 aggregate via the XLA ``.at[idx].add``
    accumulate (a functional full-buffer copy per contribution without
    donation) vs the fused dequantize->scatter-add Pallas kernel
    (``delta.dequant_scatter``, O(k) bytes written in place). Parity
    pinned <= 1e-6 over the whole aggregate. Off-TPU the kernel side
    runs INTERPRETED — ``degraded_cpu``, parity-meaningful only — and
    the shapes shrink to keep the interpreter inside the bench budget.
    """
    from distributedtraining_tpu import delta as delta_lib
    from distributedtraining_tpu.ops import dequant_scatter as dsc

    on_tpu = jax.default_backend() in ("tpu", "axon")
    if on_tpu and not dsc.enabled():
        return {"packed_ingest_kernel": "declined"}
    # one above-cutoff leaf (indexed-form entries, the kernel's case)
    # plus one below-cutoff leaf (dense-form, both sides identical)
    shape = (128, 256) if on_tpu else (96, 64)
    rng = np.random.RandomState(0)
    template = {"w": np.zeros(shape, np.float32),
                "b": np.zeros((64,), np.float32)}
    packs = []
    for i in range(n_miners):
        d = {"w": jnp.asarray(rng.standard_normal(shape), jnp.float32),
             "b": jnp.asarray(rng.standard_normal((64,)), jnp.float32)}
        packs.append(delta_lib.pack_delta_v2(d, density=1.0 / 8.0)[0])
    weights = jnp.full((n_miners,), 1.0 / n_miners, jnp.float32)

    def fold():
        return delta_lib.aggregate_deltas(template, packs, weights)

    def timed(n):
        agg = fold()
        jax.block_until_ready(jax.tree_util.tree_leaves(agg))
        t0 = time.perf_counter()
        for _ in range(n):
            agg = fold()
        jax.block_until_ready(jax.tree_util.tree_leaves(agg))
        return agg, (time.perf_counter() - t0) / n * 1e3

    ref, xla_ms = timed(trials)
    try:
        dsc.use_interpret(not on_tpu)
        agg, kernel_ms = timed(trials if on_tpu else 1)
    finally:
        dsc.use_interpret(False)
    err = max(float(jnp.max(jnp.abs(ref[k] - agg[k]))) for k in ref)
    out = {
        "packed_ingest_miners": n_miners,
        "packed_ingest_parity_err": err,
        "packed_ingest_parity": bool(err < 1e-6),
        "packed_ingest_xla_ms": round(xla_ms, 3),
        "packed_ingest_kernel_ms": round(kernel_ms, 3),
    }
    if not on_tpu:
        out["packed_ingest_degraded_cpu"] = True
    else:
        out["packed_ingest_speedup"] = round(xla_ms / kernel_ms, 3)
    return out


def _time_metrics_overhead(*, steps: int = 100, trials: int = 2,
                           log_every: int = 5) -> dict:
    """Observability-layer A/B (round-8 satellite): the production
    MinerLoop with the obs layer OFF (no configured sink, no anomaly
    monitor — every obs call is a single-branch no-op) vs fully ON
    (utils/obs configured with a real JSONLSink, per-step step-time
    histogram, periodic registry flush at the log cadence, and an
    AnomalyMonitor fed every step). Both sides run the identical metrics
    sink and log cadence, so the contrast is exactly the new layer.
    Interleaved off/on pairs (scripts/measure.sh rule 4); acceptance
    floor: metrics_overhead_frac < 0.02."""
    import os as _os
    import tempfile

    from distributedtraining_tpu.engine import TrainEngine
    from distributedtraining_tpu.engine.train import MinerLoop
    from distributedtraining_tpu.models import gpt2
    from distributedtraining_tpu.transport import InMemoryTransport
    from distributedtraining_tpu.utils import obs
    from distributedtraining_tpu.utils.metrics import JSONLSink
    from distributedtraining_tpu.utils.obs import AnomalyMonitor

    model, cfg = gpt2.make_model("tiny")
    seq = 64
    rng = np.random.default_rng(0)
    batch = {"input_ids": np.asarray(
        rng.integers(0, cfg.vocab_size, (BATCH, seq)), np.int32)}

    def run_once(instrumented: bool) -> float:
        fd, tmp = tempfile.mkstemp(suffix=".jsonl")
        _os.close(fd)
        sink = JSONLSink(tmp)
        try:
            if instrumented:
                obs.configure(sink, role="bench")
            engine = TrainEngine(model, seq_len=seq)
            loop = MinerLoop(
                engine, InMemoryTransport(), "bench-obs",
                send_interval=1e9, check_update_interval=1e9,
                log_every=log_every, metrics=sink,
                anomaly=AnomalyMonitor() if instrumented else None)
            loop.bootstrap(jax.random.PRNGKey(0))

            def batches():
                while True:
                    yield batch

            loop.run(batches(), max_steps=2)   # warm compiles off-timing
            t0 = time.perf_counter()
            loop.run(batches(), max_steps=steps)
            dt = time.perf_counter() - t0      # exit loss fetch ends timing
            assert loop.report.last_loss == loop.report.last_loss
            return dt
        finally:
            obs.reset()
            sink.close()
            _os.unlink(tmp)

    offs, ons = [], []
    for _ in range(trials):
        offs.append(run_once(False))
        ons.append(run_once(True))
    off, on = float(np.mean(offs)), float(np.mean(ons))
    return {
        "metrics_steps": steps,
        "metrics_off_s": round(off, 4),
        "metrics_on_s": round(on, 4),
        "metrics_overhead_frac": round(max(0.0, on / off - 1.0), 4),
    }


def _time_devprof_overhead(*, steps: int = 100, trials: int = 2,
                           log_every: int = 5) -> dict:
    """Device-observatory A/B (round-17 tentpole): the production
    MinerLoop with the obs layer fully ON both sides (configured sink,
    step histograms, periodic flush — the round-8 baseline), and the
    contrast being exactly utils/devprof.py: per-program cost probes,
    blocking exec timing (CPU), per-(program, bucket) histograms, and
    the flush-time snapshot mirror. Interleaved off/on pairs
    (scripts/measure.sh rule 4); acceptance floor:
    devprof_overhead_frac < 0.02. The ON side's registry also yields
    the per-program achieved-fraction summary every bench record
    carries so ``--baseline`` gates utilization, not just the headline
    tokens/sec (fractions exist only where the roofline knows the chip
    — a TPU rig; CPU runs record the FLOPs/bytes attribution alone)."""
    import os as _os
    import tempfile

    from distributedtraining_tpu.engine import TrainEngine
    from distributedtraining_tpu.engine.train import MinerLoop
    from distributedtraining_tpu.models import gpt2
    from distributedtraining_tpu.transport import InMemoryTransport
    from distributedtraining_tpu.utils import devprof, obs
    from distributedtraining_tpu.utils.metrics import JSONLSink

    model, cfg = gpt2.make_model("tiny")
    seq = 64
    rng = np.random.default_rng(0)
    batch = {"input_ids": np.asarray(
        rng.integers(0, cfg.vocab_size, (BATCH, seq)), np.int32)}
    observed: dict = {}

    def run_once(instrumented: bool) -> float:
        fd, tmp = tempfile.mkstemp(suffix=".jsonl")
        _os.close(fd)
        sink = JSONLSink(tmp)
        try:
            obs.configure(sink, role="bench")
            if instrumented:
                devprof.enable()
            engine = TrainEngine(model, seq_len=seq)
            loop = MinerLoop(
                engine, InMemoryTransport(), "bench-devprof",
                send_interval=1e9, check_update_interval=1e9,
                log_every=log_every, metrics=sink)
            loop.bootstrap(jax.random.PRNGKey(0))

            def batches():
                while True:
                    yield batch

            loop.run(batches(), max_steps=2)   # warm compiles off-timing
            t0 = time.perf_counter()
            loop.run(batches(), max_steps=steps)
            dt = time.perf_counter() - t0      # exit loss fetch ends timing
            assert loop.report.last_loss == loop.report.last_loss
            if instrumented:
                recs = devprof.records()
                assert recs, "observatory recorded nothing"
                observed["devprof_programs"] = len(recs)
                observed["prog_achieved"] = devprof.achieved_fractions()
                for r in recs:
                    if r.prog == "train.step":
                        observed["devprof_train_step_flops"] = r.flops
                        observed["devprof_train_step_bytes"] = \
                            r.bytes_accessed
            return dt
        finally:
            devprof.reset()
            obs.reset()
            sink.close()
            _os.unlink(tmp)

    offs, ons = [], []
    for _ in range(trials):
        offs.append(run_once(False))
        ons.append(run_once(True))
    off, on = float(np.mean(offs)), float(np.mean(ons))
    return {
        "devprof_steps": steps,
        "devprof_off_s": round(off, 4),
        "devprof_on_s": round(on, 4),
        "devprof_overhead_frac": round(max(0.0, on / off - 1.0), 4),
        **observed,
    }


def _time_heartbeat_overhead(*, steps: int = 100, trials: int = 2,
                             interval: float = 0.02,
                             log_every: int = 5) -> dict:
    """Fleet-health-plane A/B (round-10 satellite): the production
    MinerLoop with the obs layer fully ON both sides (configured sink,
    log cadence, device watermark gauges — the round-8 baseline), and the
    contrast being exactly the heartbeat plane: a HeartbeatPublisher at a
    20 ms cadence (~3000x faster than the 60 s production default, so
    the measured fraction is a hard upper bound) collecting report
    vitals + registry digest + memory watermarks on its timer thread and
    publishing through an InMemoryTransport on its upload worker.
    Interleaved off/on pairs; acceptance floor:
    heartbeat_overhead_frac < 0.02."""
    import os as _os
    import tempfile

    from distributedtraining_tpu.engine import TrainEngine
    from distributedtraining_tpu.engine.health import (HeartbeatPublisher,
                                                       report_vitals)
    from distributedtraining_tpu.engine.train import MinerLoop
    from distributedtraining_tpu.models import gpt2
    from distributedtraining_tpu.transport import InMemoryTransport
    from distributedtraining_tpu.utils import obs
    from distributedtraining_tpu.utils.metrics import JSONLSink

    model, cfg = gpt2.make_model("tiny")
    seq = 64
    rng = np.random.default_rng(0)
    batch = {"input_ids": np.asarray(
        rng.integers(0, cfg.vocab_size, (BATCH, seq)), np.int32)}
    beats_sent = 0

    def run_once(instrumented: bool) -> float:
        nonlocal beats_sent
        fd, tmp = tempfile.mkstemp(suffix=".jsonl")
        _os.close(fd)
        sink = JSONLSink(tmp)
        hb = None
        try:
            obs.configure(sink, role="bench")
            engine = TrainEngine(model, seq_len=seq)
            transport = InMemoryTransport()
            loop = MinerLoop(
                engine, transport, "bench-hb",
                send_interval=1e9, check_update_interval=1e9,
                log_every=log_every, metrics=sink)
            if instrumented:
                hb = HeartbeatPublisher(
                    transport, "miner", "bench-hb", interval=interval,
                    vitals=report_vitals(loop.report))
                loop.heartbeat = hb
            loop.bootstrap(jax.random.PRNGKey(0))
            def batches():
                while True:
                    yield batch

            loop.run(batches(), max_steps=2)   # warm compiles off-timing
            t0 = time.perf_counter()
            loop.run(batches(), max_steps=steps)
            dt = time.perf_counter() - t0
            loop.flush()                       # final beat + worker drain
            if hb is not None:
                assert hb.sent >= 2, hb.sent   # the plane actually ran
                beats_sent += hb.sent
            return dt
        finally:
            if hb is not None:
                hb.close()
            obs.reset()
            sink.close()
            _os.unlink(tmp)

    offs, ons = [], []
    for _ in range(trials):
        offs.append(run_once(False))
        ons.append(run_once(True))
    off, on = float(np.mean(offs)), float(np.mean(ons))
    return {
        "heartbeat_steps": steps,
        "heartbeat_interval_s": interval,
        "heartbeat_beats_sent": beats_sent,
        "heartbeat_off_s": round(off, 4),
        "heartbeat_on_s": round(on, 4),
        "heartbeat_overhead_frac": round(max(0.0, on / off - 1.0), 4),
    }


def _time_remediation_overhead(*, miners: int = 8, rounds: int = 4,
                               trials: int = 2) -> dict:
    """Remediation-layer A/B (round-11 satellite): the production
    Validator round with the fleet health plane attached (FleetMonitor
    polling heartbeats, ledger, SLO evaluation — the round-10 baseline)
    vs the same round plus the RemediationEngine (engine/remediate.py):
    per-round breach folding, quarantine case advancement, the staging
    filter hook, score decay, and elastic cohort selection. Both sides
    stage the identical submissions, so the contrast is exactly the
    actuator layer. Interleaved off/on pairs; acceptance floor:
    remediation_overhead_frac < 0.02."""
    from types import SimpleNamespace

    from distributedtraining_tpu.engine import TrainEngine
    from distributedtraining_tpu.engine.health import FleetMonitor
    from distributedtraining_tpu.engine.health import build_heartbeat
    from distributedtraining_tpu.engine.remediate import RemediationEngine
    from distributedtraining_tpu.engine.train import host_wire_template
    from distributedtraining_tpu.engine.validate import Validator
    from distributedtraining_tpu.models import gpt2
    from distributedtraining_tpu.transport import InMemoryTransport
    from distributedtraining_tpu.transport.base import heartbeat_id

    model, cfg = gpt2.make_model("tiny")
    seq = 32
    rng = np.random.default_rng(0)
    batch = {"input_ids": np.asarray(
        rng.integers(0, cfg.vocab_size, (4, seq)), np.int32)}
    hotkeys = [f"m{i}" for i in range(miners)]

    class _Chain:
        my_hotkey = "bench-validator"

        def sync(self):
            return SimpleNamespace(hotkeys=hotkeys + [self.my_hotkey])

        def should_set_weights(self):
            return False

    def eval_batches():
        yield batch

    def beat(transport, hk, s):
        transport.publish_delta_meta(
            heartbeat_id("miner", hk),
            build_heartbeat("miner", hk, s, now=float(s), steps=float(s),
                            loss_ema=2.0, pushes=float(s)))

    def run_once(remediated: bool) -> float:
        engine = TrainEngine(model, seq_len=seq)
        transport = InMemoryTransport()
        template = host_wire_template(engine)
        leaves, treedef = jax.tree_util.tree_flatten(template)
        key = jax.random.PRNGKey(1)
        for hk in hotkeys:
            key, k = jax.random.split(key)
            ks = jax.random.split(k, len(leaves))
            transport.publish_delta(hk, jax.tree_util.tree_unflatten(
                treedef, [0.01 * np.asarray(jax.random.normal(s, l.shape),
                                            l.dtype)
                          for s, l in zip(ks, leaves)]))
            beat(transport, hk, 1)
        fleet = FleetMonitor(transport)
        rem = RemediationEngine(fleet) if remediated else None
        val = Validator(engine, transport, _Chain(),
                        eval_batches=eval_batches, cohort_size=8,
                        fleet=fleet, remediation=rem)
        try:
            val.bootstrap(rng=jax.random.PRNGKey(0))
            val.validate_and_score()       # warm: compiles off-timing
            t0 = time.perf_counter()
            for r in range(2, rounds + 2):
                for hk in hotkeys:
                    beat(transport, hk, r)
                val.validate_and_score()
            return (time.perf_counter() - t0) / rounds
        finally:
            val.close()

    offs, ons = [], []
    for _ in range(trials):
        offs.append(run_once(False))
        ons.append(run_once(True))
    off, on = float(np.mean(offs)), float(np.mean(ons))
    return {
        "remediation_rounds": rounds,
        "remediation_miners": miners,
        "remediation_off_s": round(off, 4),
        "remediation_on_s": round(on, 4),
        "remediation_overhead_frac": round(max(0.0, on / off - 1.0), 4),
    }


def _time_flight_overhead(*, steps: int = 100, trials: int = 2,
                          log_every: int = 5,
                          send_interval: float = 0.05) -> dict:
    """Flight-recorder A/B (round-15 tentpole): the production MinerLoop
    with the obs layer fully ON both sides (configured JSONLSink, span
    emission, per-step histogram, registry flush at the log cadence,
    pushes at a 50 ms cadence — ~16000x the production default, so the
    measured fraction is a hard upper bound), and the contrast being
    exactly the flight recorder (utils/flight.py): ring recording of
    every span close + publish outcome + registry-digest snapshot
    through the obs hooks. Interleaved off/on pairs; acceptance floor:
    flight_overhead_frac < 0.02."""
    import os as _os
    import tempfile

    from distributedtraining_tpu.engine import TrainEngine
    from distributedtraining_tpu.engine.train import MinerLoop
    from distributedtraining_tpu.models import gpt2
    from distributedtraining_tpu.transport import InMemoryTransport
    from distributedtraining_tpu.utils import flight, obs
    from distributedtraining_tpu.utils.metrics import JSONLSink

    model, cfg = gpt2.make_model("tiny")
    seq = 64
    rng = np.random.default_rng(0)
    batch = {"input_ids": np.asarray(
        rng.integers(0, cfg.vocab_size, (BATCH, seq)), np.int32)}
    events_recorded = 0
    bundle_events = 0

    def run_once(instrumented: bool) -> float:
        nonlocal events_recorded, bundle_events
        fd, tmp = tempfile.mkstemp(suffix=".jsonl")
        _os.close(fd)
        sink = JSONLSink(tmp)
        try:
            obs.configure(sink, role="bench")
            transport = InMemoryTransport()
            rec = None
            if instrumented:
                rec = flight.configure("miner", "bench-flight",
                                       transport=transport, capacity=512)
            loop = MinerLoop(
                TrainEngine(model, seq_len=seq), transport,
                "bench-flight", send_interval=send_interval,
                check_update_interval=1e9, log_every=log_every,
                metrics=sink)
            loop.bootstrap(jax.random.PRNGKey(0))

            def batches():
                while True:
                    yield batch

            loop.run(batches(), max_steps=2)   # warm compiles off-timing
            t0 = time.perf_counter()
            loop.run(batches(), max_steps=steps)
            dt = time.perf_counter() - t0
            loop.flush()
            if rec is not None:
                assert rec.recorded > 0, "flight ring never recorded"
                events_recorded += rec.recorded
                bundle = rec.freeze("bench")   # the freeze path works
                bundle_events += len(bundle["events"])
            return dt
        finally:
            flight.reset()
            obs.reset()
            sink.close()
            _os.unlink(tmp)

    offs, ons = [], []
    for _ in range(trials):
        offs.append(run_once(False))
        ons.append(run_once(True))
    off, on = float(np.mean(offs)), float(np.mean(ons))
    return {
        "flight_steps": steps,
        "flight_send_interval_s": send_interval,
        "flight_events_recorded": events_recorded,
        "flight_bundle_events": bundle_events,
        "flight_off_s": round(off, 4),
        "flight_on_s": round(on, 4),
        "flight_overhead_frac": round(max(0.0, on / off - 1.0), 4),
    }


def _time_lineage_overhead(*, miners: int = 8, rounds: int = 8,
                           trials: int = 3) -> dict:
    """Lineage-plane A/B (round-18 tentpole): the production
    AveragerLoop at soak cadence — every round stages ``miners`` fresh
    submissions, merges (WeightedAverage), evaluates, and publishes —
    with the contrast being exactly the provenance plane
    (engine/lineage.py): per-publish record build + content address +
    transport publish, plus the EWMA/CUSUM drift update. Records are
    KBs of JSON next to a full-model base publish, so the measured
    fraction bounds the real fleet's cost from far above (the bench
    merges a tiny model; production bases are 1000x the bytes).
    Interleaved off/on pairs; acceptance floor:
    lineage_overhead_frac < 0.02."""
    from types import SimpleNamespace

    from distributedtraining_tpu.engine import TrainEngine
    from distributedtraining_tpu.engine.average import (AveragerLoop,
                                                        WeightedAverage)
    from distributedtraining_tpu.engine.lineage import LineagePlane
    from distributedtraining_tpu.engine.train import host_wire_template
    from distributedtraining_tpu.models import gpt2
    from distributedtraining_tpu.transport import InMemoryTransport

    model, cfg = gpt2.make_model("tiny")
    seq = 32
    rng = np.random.default_rng(0)
    batch = {"input_ids": np.asarray(
        rng.integers(0, cfg.vocab_size, (4, seq)), np.int32)}
    hotkeys = [f"m{i}" for i in range(miners)]

    class _Chain:
        my_hotkey = "bench-averager"

        def sync(self):
            return SimpleNamespace(hotkeys=hotkeys + [self.my_hotkey])

        def consensus_scores(self):
            return {h: float(i + 1) for i, h in enumerate(hotkeys)}

    def eval_batches():
        yield batch

    records_published = 0

    def run_once(instrumented: bool) -> float:
        nonlocal records_published
        engine = TrainEngine(model, seq_len=seq)
        transport = InMemoryTransport()
        template = host_wire_template(engine)
        leaves, treedef = jax.tree_util.tree_flatten(template)
        lineage = LineagePlane(transport, node="bench-averager") \
            if instrumented else None
        loop = AveragerLoop(engine, transport, _Chain(),
                            WeightedAverage(),
                            val_batches=eval_batches,
                            publish_policy="always", ingest_workers=1,
                            lineage=lineage)

        def push(round_seed: int) -> None:
            key = jax.random.PRNGKey(round_seed)
            for hk in hotkeys:
                key, k = jax.random.split(key)
                ks = jax.random.split(k, len(leaves))
                transport.publish_delta(
                    hk, jax.tree_util.tree_unflatten(
                        treedef,
                        [1e-3 * np.asarray(jax.random.normal(s, l.shape),
                                           l.dtype)
                         for s, l in zip(ks, leaves)]))

        try:
            loop.bootstrap(rng=jax.random.PRNGKey(0))
            push(0)
            loop.run_round()               # warm: compiles off-timing
            t0 = time.perf_counter()
            for r in range(1, rounds + 1):
                push(r)                    # fresh revisions each round
                loop.run_round()
            dt = (time.perf_counter() - t0) / rounds
            if lineage is not None:
                assert lineage.records >= rounds, \
                    "lineage plane recorded fewer merges than rounds"
                records_published += lineage.records
            return dt
        finally:
            loop.close()

    offs, ons = [], []
    for _ in range(trials):
        offs.append(run_once(False))
        ons.append(run_once(True))
    # MEDIAN, not mean: a full averager round is ~130 ms on the tiny
    # preset, so one stray GC/compile hiccup (hundreds of ms) anywhere
    # in an interleaved pair would swamp the few-ms contrast being
    # measured; the median pins the typical round both sides actually
    # pay
    off, on = float(np.median(offs)), float(np.median(ons))
    return {
        "lineage_rounds": rounds,
        "lineage_miners": miners,
        "lineage_records_published": records_published,
        "lineage_off_s": round(off, 4),
        "lineage_on_s": round(on, 4),
        "lineage_overhead_frac": round(max(0.0, on / off - 1.0), 4),
    }


def _param_count(model) -> int:
    abstract = jax.eval_shape(
        lambda: model.init_params(jax.random.PRNGKey(0)))
    return sum(int(np.prod(l.shape))
               for l in jax.tree_util.tree_leaves(abstract))


def _time_merge(model) -> dict:
    """Averager merge wall-clock for MERGE_M full-parameter GPT-2-124M
    deltas — the second half of the north-star metric. Times BOTH
    spellings: the leafwise tree merge (one small kernel per tensor) and
    the raveled single-contraction form (delta.weighted_merge_flat).
    Single-chip here; the mesh path (ingest-sharded stack + psum
    all-reduce, parallel/collectives.py) is exercised by dryrun_multichip
    and tests/test_parallel.py."""
    from distributedtraining_tpu import delta as delta_lib

    params = model.init_params(jax.random.PRNGKey(0))
    key = jax.random.PRNGKey(1)
    leaves, treedef = jax.tree_util.tree_flatten(params)
    deltas = []
    for i in range(MERGE_M):
        key, k = jax.random.split(key)
        ks = jax.random.split(k, len(leaves))
        deltas.append(jax.tree_util.tree_unflatten(
            treedef, [0.01 * jax.random.normal(kk, l.shape, l.dtype)
                      for kk, l in zip(ks, leaves)]))
    stacked = delta_lib.stack_deltas(deltas)
    w = jnp.full((MERGE_M,), 1.0 / MERGE_M)
    n_bytes = sum(l.size * l.dtype.itemsize
                  for l in jax.tree_util.tree_leaves(stacked))

    def timed(merge_fn, stack):
        @jax.jit
        def merge(params, stacked, w):
            merged = merge_fn(params, stacked, w)
            # scalar probe depending on EVERY leaf: fetching one leaf would
            # end timing with other tensor merges still in flight (the axon
            # backend's block_until_ready does not actually block)
            probe = sum(l.reshape(-1)[0]
                        for l in jax.tree_util.tree_leaves(merged))
            return merged, probe

        _, probe = merge(params, stack, w)
        float(probe)  # warm + full sync
        t0 = time.perf_counter()
        for _ in range(MERGE_ITERS):
            _, probe = merge(params, stack, w)
        float(probe)
        return (time.perf_counter() - t0) / MERGE_ITERS

    out = {"merge_m": MERGE_M}
    dt = timed(delta_lib.weighted_merge, stacked)
    out["merge_wallclock_s"] = round(dt, 4)
    out["merge_gbps"] = round(n_bytes / dt / 1e9, 1)
    try:
        dt_flat = timed(delta_lib.weighted_merge_flat, stacked)
        out["merge_flat_wallclock_s"] = round(dt_flat, 4)
        out["merge_flat_gbps"] = round(n_bytes / dt_flat / 1e9, 1)
    except Exception as e:
        out["merge_flat_error"] = repr(e)
    try:
        # bf16 wire-delta stack (--delta-dtype bfloat16): the merge is
        # bandwidth-bound, so halving the stack's bytes should land near
        # 2x on wall-clock (accumulation stays f32 inside merge_leaf)
        stacked16 = jax.tree_util.tree_map(
            lambda x: x.astype(jnp.bfloat16), stacked)
        dt16 = timed(delta_lib.weighted_merge, stacked16)
        out["merge_bf16_wallclock_s"] = round(dt16, 4)
        out["merge_bf16_speedup"] = round(dt / dt16, 3)
    except Exception as e:
        out["merge_bf16_error"] = repr(e)
    try:
        # sparse8 wire cost (--delta-dtype sparse8): publisher-side
        # top-k+quantize and receiver-side densify for ONE 124M delta,
        # plus the artifact bytes — the 7B/8B transport story in numbers
        from distributedtraining_tpu import serialization as ser

        @jax.jit
        def sparsify(d):
            sp = delta_lib.sparsify_delta(d, density=1.0 / 64)
            # scalar probe over EVERY leaf — same rule as timed() above
            # (this backend's block_until_ready does not actually block)
            probe = sum(l.reshape(-1)[0].astype(jnp.float32)
                        for l in jax.tree_util.tree_leaves(sp))
            return sp, probe

        d0 = deltas[0]
        sp, probe = sparsify(d0)
        float(probe)  # warm + full sync
        t0 = time.perf_counter()
        for _ in range(MERGE_ITERS):
            sp, probe = sparsify(d0)
        float(probe)
        out["sparse8_encode_s"] = round(
            (time.perf_counter() - t0) / MERGE_ITERS, 4)
        blob = ser.to_msgpack(sp)
        out["sparse8_artifact_bytes"] = len(blob)
        out["sparse8_vs_f32_bytes"] = round(
            sum(np.asarray(l).nbytes
                for l in jax.tree_util.tree_leaves(d0)) / len(blob), 1)
        host_template = jax.tree_util.tree_map(
            lambda x: np.zeros(x.shape, np.float32), params)
        t0 = time.perf_counter()
        dense = delta_lib.sparse_delta_from_bytes(blob, host_template)
        out["sparse8_decode_s"] = round(time.perf_counter() - t0, 4)
        assert dense is not None
    except Exception as e:
        out["sparse8_error"] = repr(e)
    return out


def _require_backend(timeout_s: float = 180.0) -> tuple[str, str | None]:
    """First backend touch with a deadline; returns ``(backend,
    degraded_reason)`` — reason None only on a live TPU.

    This rig's TPU tunnel can wedge so hard that jax.devices() blocks
    forever (docs/perf.md). BENCH_r02–r05 all wedged here and surfaced
    rc=3 with ``value: 0.0`` — four rounds with no number at all and a
    headline that read as a throughput regression. Now a wedged (or
    absent) TPU backend DEGRADES instead of aborting: jax is re-pointed
    at the CPU platform and main() runs the reduced CPU A/B suite
    (every contrast that is host/dispatch/network time — validator
    cohorts, push overlap, ingest, heartbeat/remediation overhead — is
    real on any backend; only the throughput headline is rig-specific).
    Every record a degraded run emits carries ``degraded_reason`` so
    downstream consumers can tell "the tunnel was down" from "the code
    got slower". Even the poisoned-process case (the CPU backend itself
    cannot initialize) now exits 0: the record says exactly what
    happened and value 0.0 + degraded_reason is an environment fact,
    not a bench failure for the driver to page on. The stuck worker
    thread is daemon — abandoned, exactly like every other wedge-prone
    call under run_with_timeout."""
    import sys

    from distributedtraining_tpu.utils import ChainTimeout, run_with_timeout

    try:
        run_with_timeout(jax.devices, timeout_s, name="tpu-backend")
        backend = jax.default_backend()
        if backend == "tpu":
            return backend, None
        return backend, f"no TPU backend (jax initialized {backend!r})"
    except ChainTimeout:
        print(f"bench: TPU backend unreachable after {timeout_s:.0f}s; "
              "degrading to the CPU A/B suite", file=sys.stderr)
    reason = (f"TPU backend unreachable after {timeout_s:.0f}s "
              "(tunnel wedged; see docs/perf.md)")
    try:
        jax.config.update("jax_platforms", "cpu")
        run_with_timeout(jax.devices, 60.0, name="cpu-backend")
        return "cpu_fallback", reason
    except Exception:
        versions = {}
        try:  # version forensics only: a backend probe here would wedge
            import jaxlib
            versions = {"jax_version": jax.__version__,
                        "jaxlib_version": jaxlib.__version__}
        except Exception:
            pass
        print(json.dumps({
            "metric": "miner_train_tokens_per_sec_per_chip_gpt2_124m",
            "value": 0.0, "unit": "tokens/sec/chip", "vs_baseline": None,
            **versions,
            "degraded_reason": reason + " AND the CPU fallback failed "
                                        "to initialize",
            "error": f"TPU backend unreachable after {timeout_s:.0f}s "
                     "AND the CPU fallback failed to initialize "
                     "(tunnel wedged; see docs/perf.md)"}))
        sys.stdout.flush()
        sys.exit(0)


def _gate_baseline(record: dict, baseline_path: str,
                   *, max_drop: float = 0.2) -> list[str]:
    """Regression gate against a prior bench record (``--baseline``):
    flags the headline tokens/sec AND every per-program roofline
    achieved-fraction (``prog_achieved``, devprof) that dropped more
    than ``max_drop`` relative — a step can keep its tokens/sec
    headline while a constituent program's utilization collapses
    (e.g. a regressed merge hidden behind a faster eval), and only the
    per-program fractions catch that. Degraded records gate nothing
    (an environment fact is not a regression)."""
    import sys

    try:
        with open(baseline_path) as f:
            base = json.load(f)
    except (OSError, ValueError) as e:
        print(f"bench: cannot read --baseline {baseline_path}: {e}",
              file=sys.stderr)
        return []
    if record.get("degraded_cpu") or base.get("degraded_cpu"):
        return []
    regressions: list[str] = []
    bv, nv = base.get("value"), record.get("value")
    if isinstance(bv, (int, float)) and isinstance(nv, (int, float)) \
            and bv > 0 and nv < (1.0 - max_drop) * bv:
        regressions.append(
            f"headline tokens/sec {nv:.1f} < {(1 - max_drop):.0%} of "
            f"baseline {bv:.1f}")
    base_prog = base.get("prog_achieved") or {}
    now_prog = record.get("prog_achieved") or {}
    for prog, bfrac in sorted(base_prog.items()):
        nfrac = now_prog.get(prog)
        if not isinstance(bfrac, (int, float)) or bfrac <= 0:
            continue
        if not isinstance(nfrac, (int, float)):
            regressions.append(
                f"program {prog}: achieved-fraction disappeared "
                f"(baseline {bfrac:.4f})")
        elif nfrac < (1.0 - max_drop) * bfrac:
            regressions.append(
                f"program {prog}: achieved fraction {nfrac:.4f} < "
                f"{(1 - max_drop):.0%} of baseline {bfrac:.4f}")
    # speculative serving floor: the draft-and-verify lane must keep
    # buying >=1.3x tokens/sec over plain decode at its best K (an
    # absolute bar, not baseline-relative — losing the mechanism's win
    # is the regression, whatever the prior record said)
    sv = record.get("serve_spec_speedup")
    if isinstance(sv, (int, float)) and sv < 1.3:
        regressions.append(
            f"speculative serve speedup {sv:.2f}x at best "
            f"k={record.get('serve_spec_best_k')} < required 1.30x "
            f"over plain decode")
    return regressions


def main(argv=None) -> None:
    global BATCH, SEQ, WARMUP, ITERS, MERGE_M, MERGE_ITERS
    import argparse

    from distributedtraining_tpu.models import gpt2

    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--baseline", default=None, metavar="BENCH_rNN.json",
                    help="gate this run against a prior bench record: "
                         "exit 1 when the headline tokens/sec OR any "
                         "per-program roofline achieved-fraction "
                         "(prog_achieved, utils/devprof.py) regresses "
                         "more than 20%% relative — utilization "
                         "regressions gate even when the headline holds")
    args = ap.parse_args(argv)

    backend, degraded_reason = _require_backend()
    degraded = degraded_reason is not None
    preset = "gpt2-124m"
    if degraded:
        # CPU A/B suite (ROADMAP item 5, first half): the tiny preset at
        # a short sequence keeps every burst inside the driver budget —
        # the HEADLINE number is then rig-meaningless (marked degraded,
        # vs_baseline omitted as 0.0) but every A/B ratio below is a real
        # contrast, so a PR's perf delta still lands even with the
        # tunnel down.
        preset = "tiny"
        BATCH, SEQ, WARMUP, ITERS = 4, 64, 1, 6
        MERGE_M, MERGE_ITERS = 4, 2
    model, cfg = gpt2.make_model(preset)
    base_burst = _step_burst(model, cfg)   # ONE standard engine, reused by
    base_burst(WARMUP)                     # the headline and every A/B pair
    tokens_per_sec = base_burst(ITERS)

    extras = {"backend": backend, **_bench_env()}
    if degraded:
        extras["degraded_cpu"] = True
        extras["degraded_reason"] = degraded_reason
        extras["bench_model"] = preset
    if not degraded:
        try:
            # interleaved flash-vs-dense (variant = dense, so the headline
            # flash_speedup is 1/ratio)
            dense_model, _ = gpt2.make_model(
                gpt2.GPT2Config(attention_impl="dense"))
            dense_tps, dense_ratio = _ab_speedup(base_burst, dense_model,
                                                 cfg)
            extras["dense_tokens_per_sec"] = round(dense_tps, 1)
            extras["flash_speedup"] = round(1.0 / dense_ratio, 3)
        except Exception as e:  # a failed sub-bench never sinks the headline
            extras["dense_error"] = repr(e)

        try:
            # tiled-head CE that never materializes [B, T, V] logits
            # (lax.scan spelling, measured 0.93x at 124M in r2)
            fused_tps, fused_ratio = _ab_speedup(base_burst, model, cfg,
                                                 fused_b="scan")
            extras["fused_loss_tokens_per_sec"] = round(fused_tps, 1)
            extras["fused_loss_speedup"] = round(fused_ratio, 3)
        except Exception as e:
            extras["fused_loss_error"] = repr(e)

        try:
            # the Pallas fused-CE kernels (ops/pallas_ce.py) — candidate
            # default if they beat the standard path on-chip (docs/perf.md
            # ceiling analysis: the f32 logits are cost #1)
            pallas_tps, pallas_ratio = _ab_speedup(base_burst, model, cfg,
                                                   fused_b="pallas")
            extras["pallas_ce_tokens_per_sec"] = round(pallas_tps, 1)
            extras["pallas_ce_speedup"] = round(pallas_ratio, 3)
        except Exception as e:
            extras["pallas_ce_error"] = repr(e)

    try:
        # production MinerLoop.run vs the bare engine step, interleaved —
        # loop overhead should be ≲2% (round-2 verdict item 4)
        extras.update(_time_loop_vs_engine(model, cfg, base_burst))
    except Exception as e:
        extras["loop_error"] = repr(e)

    if not degraded:
        try:
            # --scan-blocks on-chip throughput (round-2 pending lever:
            # compile time is the known 38x win; per-step cost ~neutral)
            scan_model, _ = gpt2.make_model(
                dataclasses.replace(cfg, scan_blocks=True))
            scan_tps, scan_ratio = _ab_speedup(base_burst, scan_model, cfg)
            extras["scan_blocks_tokens_per_sec"] = round(scan_tps, 1)
            extras["scan_blocks_speedup"] = round(scan_ratio, 3)
        except Exception as e:
            extras["scan_blocks_error"] = repr(e)

        try:
            # logits_dtype=bfloat16: halves the largest activation
            # buffer's HBM round-trips (round-2 pending lever)
            b16_model, _ = gpt2.make_model(
                dataclasses.replace(cfg, logits_dtype="bfloat16"))
            b16_tps, b16_ratio = _ab_speedup(base_burst, b16_model, cfg)
            extras["logits_bf16_tokens_per_sec"] = round(b16_tps, 1)
            extras["logits_bf16_speedup"] = round(b16_ratio, 3)
        except Exception as e:
            extras["logits_bf16_error"] = repr(e)

    peak = _peak_flops()
    if peak:
        n_params = _param_count(model)
        # per-token model FLOPs: 6N for the matmuls (fwd+bwd) plus the
        # attention term 12 * L * E * T
        flops_per_token = 6 * n_params + 12 * cfg.n_layer * cfg.n_embd * SEQ
        extras["mfu"] = round(tokens_per_sec * flops_per_token / peak, 4)
        extras["peak_flops"] = peak

    try:
        extras.update(_time_merge(model))
    except Exception as e:
        extras["merge_error"] = repr(e)

    try:
        # batched cohort validation vs sequential score_miner (the round's
        # tentpole): dispatch ratio is exact, wall-clock is this rig's
        extras.update(_time_validator_round(model, cfg))
    except Exception as e:
        extras["validator_round_error"] = repr(e)

    try:
        # async miner publication pipeline vs the sequential push path on a
        # simulated-latency transport (round-7 tentpole): the stall is
        # host/network time, so the CPU A/B is the real contrast
        extras.update(_time_push_overlap())
    except Exception as e:
        extras["push_overlap_error"] = repr(e)

    try:
        # observability layer cost: production loop with utils/obs off vs
        # fully on (round-8 satellite; acceptance < 2%)
        extras.update(_time_metrics_overhead())
    except Exception as e:
        extras["metrics_overhead_error"] = repr(e)

    try:
        # device-observatory cost: obs fully on both sides, contrast =
        # utils/devprof.py (round-17 tentpole; acceptance < 2%). Also
        # the source of the per-program achieved-fraction summary the
        # --baseline gate reads.
        extras.update(_time_devprof_overhead())
    except Exception as e:
        extras["devprof_overhead_error"] = repr(e)

    try:
        # concurrent + cached averager ingest vs serial gather over
        # localfs (round-9 tentpole): cold speedup is the fetch pool,
        # warm speedup is the revision cache skipping every download
        extras.update(_time_gather_deltas())
    except Exception as e:
        extras["gather_deltas_error"] = repr(e)

    try:
        # dense v1 vs sparse+quantized shard-addressed v2 delta wire over
        # localfs (round-12 tentpole): bytes-per-push ratio, encode/decode
        # cost, and warm-round shard dedupe (unchanged layers fetch zero)
        extras.update(_time_wire_v2())
    except Exception as e:
        extras["wire_v2_error"] = repr(e)

    try:
        # monolithic base pull vs content-addressed sharded delta-pull
        # over localfs (round-19 tentpole): warm-round base-fetch bytes
        # collapse to manifest + changed shards, unchanged layers fetch
        # zero, fetched base bit-exact either way
        extras.update(_time_base_distribution())
    except Exception as e:
        extras["base_distribution_error"] = repr(e)

    try:
        # flat single-node merge vs fanout tree aggregation over localfs
        # (round-13 tentpole): per-node round cost O(miners) ->
        # O(miners / fanout), parity pinned
        extras.update(_time_hier_average())
    except Exception as e:
        extras["hier_average_error"] = repr(e)

    try:
        # continuous-batching serving vs naive sequential generation
        # (round-14 tentpole): tokens/sec at batch 8, per-token latency,
        # hot-swap stall, steady-state fresh compiles (must be zero)
        extras.update(_time_serve())
    except Exception as e:
        extras["serve_error"] = repr(e)

    try:
        # draft-and-verify speculative decoding vs plain greedy decode
        # (round-21 tentpole): tok/s and tpot at draft-k in {2,4,8},
        # parity-pinned, acceptance recorded, steady-state fresh
        # compiles must stay zero; --baseline gates the >=1.3x speedup
        extras.update(_time_serve_speculative())
    except Exception as e:
        extras["serve_spec_error"] = repr(e)

    try:
        # disaggregated prefill/decode KV transfer (round-24 tentpole):
        # export->publish->fetch->adopt A/B vs the unified engine —
        # bytes on wire, transfer-stage latencies, adoption parity pin
        # (greedy token-identical, sampled bit-identical), second-wave
        # dedupe, zero steady-state fresh compiles on both worker
        # classes, and the virtual-clock tpot p95 gain of a phase-split
        # pair over a unified worker under prefill head-of-line cost
        extras.update(_time_kv_transfer())
    except Exception as e:
        extras["kv_transfer_error"] = repr(e)

    try:
        # packed wire-v2 ingest: fused dequant->scatter-add kernel vs
        # the XLA accumulate (round-20 tentpole; parity-pinned, CPU
        # side runs the interpreted kernel and marks degraded)
        extras.update(_time_packed_ingest())
    except Exception as e:
        extras["packed_ingest_error"] = repr(e)

    try:
        # fleet health plane cost: production loop with the heartbeat
        # publisher at an aggressive cadence vs without (round-10
        # satellite; acceptance < 2%)
        extras.update(_time_heartbeat_overhead())
    except Exception as e:
        extras["heartbeat_overhead_error"] = repr(e)

    try:
        # remediation layer cost: validator rounds with the fleet plane
        # attached vs fleet plane + RemediationEngine (round-11
        # satellite; acceptance < 2%)
        extras.update(_time_remediation_overhead())
    except Exception as e:
        extras["remediation_overhead_error"] = repr(e)

    try:
        # flight-recorder cost: production miner loop with the obs layer
        # on both sides, contrast = the postmortem event ring
        # (round-15 tentpole; acceptance < 2%)
        extras.update(_time_flight_overhead())
    except Exception as e:
        extras["flight_overhead_error"] = repr(e)

    try:
        # lineage-plane cost: production averager rounds with the
        # provenance record + drift detector per publish vs without
        # (round-18 tentpole; acceptance < 2%)
        extras.update(_time_lineage_overhead())
    except Exception as e:
        extras["lineage_overhead_error"] = repr(e)

    if not degraded:
        try:
            # MFU scale point (round-2 verdict item 7): config 3's model
            # on one chip, scan-blocks for compile safety
            cfg355 = dataclasses.replace(gpt2.PRESETS["gpt2-355m"],
                                         scan_blocks=True)
            m355, _ = gpt2.make_model(cfg355)
            tps355 = _time_train(m355, cfg355, iters=8)
            extras["gpt2_355m_tokens_per_sec"] = round(tps355, 1)
            if peak:
                fpt = (6 * _param_count(m355)
                       + 12 * cfg355.n_layer * cfg355.n_embd * SEQ)
                extras["gpt2_355m_mfu"] = round(tps355 * fpt / peak, 4)
        except Exception as e:
            extras["gpt2_355m_error"] = repr(e)

    if os.environ.get("DT_BENCH_BIGVOCAB"):
        # the fused-CE crossover case: same 12-layer/768-wide body with a
        # Llama-3-width vocabulary (128256), where the head matmul
        # dominates the step — this pair decides whether pallas CE becomes
        # the default for the large-vocab family. Opt-in like batch-16:
        # the STANDARD-path baseline here materializes 4x1024x128256 f32
        # logits, a bigger program than the batch-16 one that wedged the
        # tunnel in r2 — never run it unattended (batch 4 keeps the
        # activation footprint inside one v5e's HBM; the ratio is what
        # matters, both sides see the same batch).
        try:
            cfg_bv = dataclasses.replace(cfg, vocab_size=128256)
            m_bv, _ = gpt2.make_model(cfg_bv)
            bv_burst = _step_burst(m_bv, cfg_bv, batch_size=4)
            bv_tps, bv_ratio = _ab_speedup(bv_burst, m_bv, cfg_bv,
                                           fused_b="pallas", batch_size=4)
            extras["bigvocab_pallas_tokens_per_sec"] = round(bv_tps, 1)
            extras["bigvocab_pallas_speedup"] = round(bv_ratio, 3)
        except Exception as e:
            extras["bigvocab_error"] = repr(e)

    if os.environ.get("DT_BENCH_B16"):
        # batch 16 via scan-blocks — the round-2 blocked MFU experiment.
        # Opt-in: a batch-16 compile once wedged this rig's tunnel for 8 h
        # (docs/perf.md), so the driver's unattended run never attempts it;
        # run manually via DT_BENCH_B16=1 after a healthy probe.
        try:
            scan_model, _ = gpt2.make_model(
                dataclasses.replace(cfg, scan_blocks=True))
            b16 = _step_burst(scan_model, cfg, batch_size=16)
            b16(WARMUP)
            tps_b16 = b16(ITERS)
            extras["batch16_scan_tokens_per_sec"] = round(tps_b16, 1)
            if peak:
                extras["batch16_scan_mfu"] = round(
                    tps_b16 * flops_per_token / peak, 4)
        except Exception as e:
            extras["batch16_error"] = repr(e)

    record = {
        "metric": "miner_train_tokens_per_sec_per_chip_gpt2_124m",
        "value": round(tokens_per_sec, 1),
        "unit": "tokens/sec/chip",
        # a tiny-model CPU headline must never read as a 124M TPU
        # regression: the baseline ratio only exists on the real rig
        "vs_baseline": (None if degraded
                        else round(tokens_per_sec / BASELINE_TOKENS_PER_SEC,
                                   3)),
        **extras,
    }
    regressions: list[str] = []
    if args.baseline:
        regressions = _gate_baseline(record, args.baseline)
        if regressions:
            record["utilization_regressions"] = regressions
    print(json.dumps(record))
    if regressions:
        import sys
        for r in regressions:
            print(f"bench: REGRESSION vs {args.baseline}: {r}",
                  file=sys.stderr)
        sys.exit(1)


if __name__ == "__main__":
    main()
