"""Server entry point: serve generation over the live base model.

The fourth role of the fleet (ROADMAP item 3): a continuous-batching
generation engine (engine/serve.py) that subscribes to the averager's
base-model revisions through the transport and hot-swaps weights between
decode steps — the federated loop's output, deployed continuously. Run
offline against a local round:

    python neurons/server.py --backend local --work-dir /tmp/run \
        --model tiny --dataset synthetic --serve-port 8900

POST token ids at it:

    curl -d '{"tokens": [1, 2, 3], "max_new_tokens": 16}' \
        http://127.0.0.1:8900/generate

Heartbeats carry the served base revision and tokens/sec, so
scripts/fleet_report.py shows train -> merge -> serve lag end to end;
``--obs-port`` exports the ``serve.*`` registry as ``dt_serve_*``.
"""

from __future__ import annotations

import logging
import os
import sys
import time

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)

# platform override BEFORE any backend touch (see utils/platform.py)
from distributedtraining_tpu.utils.platform import (  # noqa: E402
    force_platform_from_env)

force_platform_from_env()

from distributedtraining_tpu.config import RunConfig           # noqa: E402
from distributedtraining_tpu.engine.serve import (             # noqa: E402
    BaseRevisionWatcher, GenerationEngine, ServeHTTPFrontend, ServeLoop,
    host_param_template)
from neurons.common import (build, build_base_fetcher,         # noqa: E402
                            build_health_plane)

logger = logging.getLogger(__name__)


def _await_base(cfg: RunConfig, c, watcher: BaseRevisionWatcher):
    """Boot weights: the published base when one exists (polling until
    it does), else ``--init-from`` pretrained weights (serving can come
    up before the averager's first publish)."""
    deadline = (time.monotonic() + cfg.rounds * cfg.swap_poll
                if cfg.rounds else None)
    while True:
        if watcher.poll_once():
            staged = watcher.take_pending()
            if staged is not None:
                return staged[1], staged[0]
        params = c.initial_params()
        if params is not None:
            logger.info("no published base yet; serving --init-from "
                        "weights until one lands")
            return params, None
        if deadline is not None and time.monotonic() > deadline:
            raise SystemExit(
                "no base model appeared within the bounded wait "
                "(--rounds x --swap-poll); is the averager running?")
        logger.info("waiting for a published base model "
                    "(poll every %.1fs)...", cfg.swap_poll)
        time.sleep(cfg.swap_poll)


def _build_drafter(cfg: RunConfig, c):
    """Speculative drafter (``--speculative``): a :class:`DraftEngine`
    around the small fleet-trained base named by ``--draft-repo``
    ("preset@work_dir" — a second transport watches that deployment's
    averaged revisions and feeds the drafter's hot-swap lane). Empty
    ``--draft-repo`` self-drafts from the serving transport (smoke
    only: a draft the target's own size saves nothing). Every failure
    degrades to plain decode — a misconfigured drafter must never keep
    the server from serving."""
    if not cfg.serve_speculative:
        return None
    from distributedtraining_tpu.engine import speculative as _spec
    from distributedtraining_tpu.models import gpt2, llama
    try:
        if cfg.serve_draft_repo:
            preset, _, work_dir = cfg.serve_draft_repo.partition("@")
            family = llama if preset in llama.PRESETS else gpt2
            if preset not in family.PRESETS:
                raise ValueError(f"unknown draft preset {preset!r}")
            dmodel, _ = family.make_model(preset)
            from distributedtraining_tpu.transport import LocalFSTransport
            tr = LocalFSTransport(os.path.join(work_dir, "artifacts"))
        else:
            dmodel, tr = c.model, c.transport
        reason = _spec.compat_reason(dmodel, c.model_cfg)
        if reason:
            logger.warning("drafter incompatible (%s); serving plain",
                           reason)
            return None
        dwatcher = BaseRevisionWatcher(
            tr, lambda: host_param_template(dmodel),
            poll_s=max(cfg.swap_poll, 0.1))
        draft = _spec.DraftEngine(
            dmodel, max_slots=cfg.serve_slots,
            page_size=cfg.serve_page_size, watcher=dwatcher)
        # synchronous first pull so a draft base that is already
        # published speculates from step one; otherwise the watcher
        # thread installs it whenever it lands (plain decode until then)
        if dwatcher.poll_once():
            staged = dwatcher.take_pending()
            if staged is not None:
                draft.install_params(staged[1], revision=staged[0])
        dwatcher.start()
        logger.info("speculative decoding on: draft=%s k=%d ready=%s",
                    cfg.serve_draft_repo or "<self>", cfg.serve_draft_k,
                    draft.ready)
        return draft
    except Exception:
        logger.exception("drafter construction failed; serving plain")
        return None


def main(argv=None) -> int:
    logging.basicConfig(level=logging.INFO,
                        format="%(asctime)s %(name)s %(message)s")
    cfg = RunConfig.from_args("server", argv)
    c = build(cfg)
    # crash-forensics triggers (utils/flight.py, see neurons/miner.py)
    from distributedtraining_tpu.utils import flight
    flight.install_crash_hooks()

    # content-addressed base pulls (engine/basedist.py): hot-swap
    # fetches become delta-pulls of only the layers the merge moved
    base_fetcher = build_base_fetcher(cfg, c)
    watcher = BaseRevisionWatcher(
        c.transport, lambda: host_param_template(c.model),
        poll_s=max(cfg.swap_poll, 0.1), fetcher=base_fetcher)
    params, revision = _await_base(cfg, c, watcher)
    if base_fetcher is not None and revision is None and params is not None:
        # --init-from boot: seed the shard store from the weights we
        # serve, so the FIRST published base pulls only what differs
        base_fetcher.seed(params)
    # SLO burn-rate alerting over the request-trace stream
    # (engine/health.py): every finished/shed request the TraceBook
    # records feeds the monitor; multi-window rules fire the standard
    # breach escalation and export as dt_slo_burn{slo,window}
    from distributedtraining_tpu.engine import health as _health
    burn = (_health.BurnRateMonitor(metrics=c.metrics)
            if cfg.serve_trace else None)
    _health.attach_burn(burn)
    # disaggregated worker classes (engine/kv_transfer.py): a prefill
    # worker exports KV pages over the SERVING transport (the same
    # store base revisions ride), a decode worker adopts them; unified
    # touches neither
    from distributedtraining_tpu.engine import kv_transfer as _kvt
    kv_exporter = (_kvt.KVExporter(c.transport)
                   if cfg.serve_phase == "prefill" else None)
    kv_adopter = (_kvt.KVAdopter(c.transport)
                  if cfg.serve_phase == "decode" else None)
    engine = GenerationEngine(
        c.model, params, revision=revision,
        max_slots=cfg.serve_slots, page_size=cfg.serve_page_size,
        pool_pages=cfg.serve_kv_pages, max_seq_len=cfg.serve_max_seq,
        max_new_tokens=cfg.serve_max_new,
        eos_id=getattr(c.tokenizer, "eos_id", None),
        swap_policy=cfg.swap_policy, watcher=watcher,
        max_queue=cfg.serve_max_queue,
        prefix_cache=cfg.serve_prefix_cache,
        draft=(None if cfg.serve_phase == "prefill"
               else _build_drafter(cfg, c)),
        draft_k=cfg.serve_draft_k,
        trace=cfg.serve_trace,
        trace_exemplars=cfg.serve_trace_exemplars,
        trace_window_s=cfg.serve_trace_window or 30.0,
        burn=burn, phase=cfg.serve_phase,
        kv_exporter=kv_exporter, kv_adopter=kv_adopter)
    watcher.start()

    # health plane: the server heartbeats its SERVED revision (the
    # "base_revision" field every fleet consumer already reads) plus
    # tokens/sec and queue depth as numeric extras — fleet_report's
    # served_rev/tok_s columns come from here
    from distributedtraining_tpu.engine.health import Vitals
    from distributedtraining_tpu.utils import obs as _obs

    def _serve_counters():
        out = {"tokens_per_sec": engine.tokens_per_sec,
               "queue_depth": float(engine.queue_depth),
               "tokens": float(engine.tokens_emitted),
               "shed": float(engine.shed_count)}
        # prefix-cache effectiveness rides the heartbeat only once the
        # cache has seen traffic — fleet_report renders "-" otherwise
        if engine.prefix_hits + engine.prefix_misses > 0:
            out["prefix_hit_rate"] = engine.prefix_hit_rate
        # speculative acceptance rides the heartbeat once drafting has
        # actually verified tokens — fleet_report's acc_rate column
        if engine.speculative and engine.spec_rounds > 0:
            out["spec_accept_rate"] = engine.spec_accept_rate
        # request-level latency percentiles (engine/serve.py observes
        # serve.ttft_ms / serve.tpot_ms per token): ride the heartbeat
        # as numeric extras so fleet_report's ttft95/tpot95 columns show
        # caller-experienced latency next to tokens/sec. names() guards
        # the read — histogram() would CREATE an empty series and skew
        # the registry digest on idle servers.
        names = _obs.registry().names()
        for metric, field in (("serve.ttft_ms", "ttft_ms_p95"),
                              ("serve.tpot_ms", "tpot_ms_p95"),
                              ("serve.queue_age_ms", "q_age_ms_p95")):
            if metric in names:
                h = _obs.registry().histogram(metric)
                if h.count:
                    out[field] = h.percentiles((95.0,))["p95"]
        # worst fast-window burn rate across the serving SLOs —
        # fleet_report's slo_burn column (0.0 = comfortably on budget)
        if burn is not None:
            out["slo_burn"] = burn.max_burn()
        # disaggregated transfer volume — fleet_report's phase column
        # reads the string field; the kv counters ride only on workers
        # that actually export/adopt so unified heartbeats stay lean
        if engine.phase != "unified":
            out["phase"] = engine.phase
            out["kv_exported"] = float(engine.kv_exported)
            out["kv_adopted"] = float(engine.kv_adopted)
        return out

    vitals = Vitals(
        steps=lambda: engine.steps,
        counters=_serve_counters,
        base_revision=lambda: engine.revision)
    plane = build_health_plane(
        cfg, c, vitals=vitals,
        collect=(base_fetcher.heartbeat_fields
                 if base_fetcher is not None else None))

    frontend = None
    if cfg.serve_port:
        frontend = ServeHTTPFrontend(engine, cfg.serve_port,
                                     tokenizer=c.tokenizer)
        frontend.start()
    loop = ServeLoop(engine).start()
    from distributedtraining_tpu.utils import devprof, obs
    try:
        idle_since = None
        last_flush = time.monotonic()
        while True:
            time.sleep(0.25)
            if c.metrics is not None and \
                    time.monotonic() - last_flush >= 15.0:
                # registry snapshots (serve.* timings) at a steady
                # cadence, so fleet_report's registry[server] line and
                # offline joins see the serving numbers
                obs.flush(step=engine.steps)
                if burn is not None:
                    # burn-rate rules re-check on the same cadence; any
                    # firing walks the standard breach escalation
                    burn.evaluate()
                last_flush = time.monotonic()
            if cfg.max_steps is None:
                continue   # unbounded: serve until interrupted
            if engine.steps >= cfg.max_steps:
                logger.info("reached --max-steps %d decode steps",
                            cfg.max_steps)
                break
            # bounded runs (tests, smoke) must terminate without traffic
            # too: a drained queue that stays idle ends the run
            if engine.idle:
                idle_since = idle_since or time.monotonic()
                if time.monotonic() - idle_since > 2 * max(cfg.swap_poll,
                                                           1.0):
                    logger.info("bounded run idle; exiting at %d steps",
                                engine.steps)
                    break
            else:
                idle_since = None
    except KeyboardInterrupt:
        pass
    finally:
        if frontend is not None:
            frontend.close()
        loop.close()
        plane.close()
        engine.close()
        _health.attach_burn(None)
        if c.metrics is not None:
            obs.flush(step=engine.steps)
        # crash bundle (exceptional exits), then global obs state reset
        flight.shutdown()
        obs.reset()
        devprof.reset()
    logger.info("server done: steps=%d tokens=%d revision=%s",
                engine.steps, engine.tokens_emitted, engine.revision)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
