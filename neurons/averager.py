"""Averager entry point: merge miner deltas into the next base model.

Rebuild of the reference averager (neurons/averager.py:39-106 →
ParameterizedAverager, hivetrain/averaging_logic.py:335-583). Run offline:

    python neurons/averager.py --backend local --work-dir /tmp/run \
        --model tiny --dataset synthetic --strategy parameterized --rounds 1
"""

from __future__ import annotations

import logging
import os
import sys

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)

# platform override BEFORE any backend touch (see utils/platform.py)
from distributedtraining_tpu.utils.platform import (  # noqa: E402
    force_platform_from_env)

force_platform_from_env()

from distributedtraining_tpu.config import RunConfig           # noqa: E402
from distributedtraining_tpu.engine import (                   # noqa: E402
    AveragerLoop, GeneticMerge, OuterOptMerge, ParameterizedMerge,
    WeightedAverage)
from neurons.common import build, build_health_plane           # noqa: E402


def make_strategy(cfg: RunConfig, model):
    if cfg.strategy == "weighted":
        strategy = WeightedAverage(chunk_size=cfg.merge_chunk)
    elif cfg.strategy == "genetic":
        strategy = GeneticMerge(
            population=cfg.genetic_population,
            generations=cfg.genetic_generations,
            sigma=cfg.genetic_sigma,
            screen_batches=cfg.genetic_screen_batches or None)
    else:
        strategy = ParameterizedMerge(model, meta_epochs=cfg.meta_epochs,
                                      meta_lr=cfg.meta_lr,
                                      meta_optimizer=cfg.meta_optimizer)
    if cfg.outer_momentum > 0:
        strategy = OuterOptMerge(
            strategy, outer_lr=cfg.outer_lr, momentum=cfg.outer_momentum,
            # persist the DiLoCo velocity across supervised restarts
            state_path=os.path.join(cfg.work_dir, "averager_state",
                                    f"velocity_{cfg.hotkey}.msgpack"))
    return strategy


def _hier_nodes(cfg: RunConfig) -> list[str]:
    return [n.strip() for n in (cfg.hier_nodes or "").split(",")
            if n.strip()]


def _run_sub_averager(cfg: RunConfig, c, plane) -> int:
    """--hier sub: this process is one node of the aggregation tree
    (engine/hier_average.py) — gather the plan_fanout slice, publish the
    partial aggregate under __agg__.<node>. No eval set, no strategy, no
    base publication; failover rides a per-node subavg.<node> lease."""
    from distributedtraining_tpu.engine.hier_average import (SubAverager,
                                                             plan_fanout)
    from distributedtraining_tpu.engine.train import host_wire_template

    nodes = _hier_nodes(cfg)
    node = cfg.hier_node or cfg.hotkey
    if not nodes and cfg.hier_fanout <= 0:
        raise SystemExit("--hier sub needs --hier-nodes or --hier-fanout "
                         "to derive this node's miner slice")
    if nodes and node not in nodes:
        raise SystemExit(f"--hier-node {node!r} is not in --hier-nodes "
                         f"{nodes} — the slice plan would never assign "
                         "it a miner")

    def assigned():
        meta = c.chain.sync()
        hotkeys = [h for h in meta.hotkeys if h != cfg.hotkey]
        plan = plan_fanout(hotkeys, nodes=nodes or None,
                           fanout=cfg.hier_fanout or None)
        return plan.get(node, [])

    lease = None
    if cfg.remediate or cfg.standby:
        from distributedtraining_tpu.engine.remediate import LeaseManager
        lease = LeaseManager(c.transport, cfg.hotkey,
                             role=f"subavg.{node}")
    lineage = None
    if cfg.lineage:
        from distributedtraining_tpu.engine.lineage import LineagePlane
        lineage = LineagePlane(c.transport, node=f"subavg.{node}")
    mirror = None
    if cfg.base_wire_v2 and cfg.base_mirror:
        # regional mirror duty (engine/basedist.py): this __agg__ node
        # re-publishes the base shards it pulls under __mirror__.<node>
        # so nearby fetchers race a replica instead of the origin
        from distributedtraining_tpu.engine.basedist import MirrorDuty
        mirror = MirrorDuty(c.transport, node)
    sub = SubAverager(
        c.transport, node, lambda: host_wire_template(c.engine), assigned,
        consensus=lambda: getattr(c.chain, "consensus_scores",
                                  lambda: {})(),
        max_delta_abs=cfg.max_delta_abs,
        stale_deltas=cfg.stale_deltas or "skip",
        accept_quant=cfg.accept_quant,
        accept_wire_v2=cfg.accept_wire_v2,
        lora_cfg=c.lora_cfg,
        ingest_workers=cfg.ingest_workers,
        ingest_cache_mb=cfg.ingest_cache_mb,
        wire_spec=True if cfg.hier_wire_v2 else None,
        lease=lease, metrics=c.metrics, fleet=plane.fleet,
        lineage=lineage, mirror=mirror)
    try:
        merged = sub.run_periodic(interval=cfg.averaging_interval,
                                  rounds=cfg.rounds)
    except KeyboardInterrupt:
        merged = sub.report.rounds
    finally:
        plane.close()
        sub.close()
        from distributedtraining_tpu.utils import devprof, flight, obs
        flight.shutdown()
        obs.reset()
        devprof.reset()
    logging.info("sub-averager %s done: rounds=%d accepted=%d pushes=%d",
                 node, sub.report.rounds, sub.report.last_accepted,
                 sub.report.pushes)
    return 0 if merged else 1


def main(argv=None) -> int:
    logging.basicConfig(level=logging.INFO,
                        format="%(asctime)s %(name)s %(message)s")
    cfg = RunConfig.from_args("averager", argv)
    c = build(cfg)
    # crash-forensics triggers (utils/flight.py, see neurons/miner.py)
    from distributedtraining_tpu.utils import flight
    flight.install_crash_hooks()
    # fleet health plane: the averager both heartbeats AND monitors —
    # its FleetMonitor folds every gather's staging outcomes into the
    # contribution ledger and evaluates the SLO rules each round; a
    # breach arms the AnomalyMonitor one-shot (detection + counters —
    # no train loop here to tick a profiler capture).
    from distributedtraining_tpu.engine.health import report_vitals
    from distributedtraining_tpu.utils.obs import AnomalyMonitor
    anomaly = AnomalyMonitor()
    plane = build_health_plane(cfg, c, monitor=True,
                               anomaly=anomaly,
                               start_heartbeat=False)
    if cfg.hier == "sub":
        # the sub-averager role shares the build + health plane but runs
        # a different loop entirely (no base publication)
        if plane.heartbeat is not None:
            plane.heartbeat.start()
        return _run_sub_averager(cfg, c, plane)
    hierarchy = None
    if cfg.hier == "root":
        hierarchy = _hier_nodes(cfg)
        if not hierarchy and cfg.hier_fanout > 0:
            # fanout-only fleets: derive the auto-named node list from
            # the boot-time metagraph (the subs derive the same names);
            # --hier-nodes is the stable spelling when the fleet wobbles
            from distributedtraining_tpu.engine.hier_average import \
                plan_fanout
            meta = c.chain.sync()
            hierarchy = list(plan_fanout(
                [h for h in meta.hotkeys if h != cfg.hotkey],
                fanout=cfg.hier_fanout))
        if not hierarchy:
            raise SystemExit("--hier root needs --hier-nodes (or "
                             "--hier-fanout) to know which __agg__ "
                             "artifacts to gather")
    # publication lease (engine/remediate.py): held and renewed whenever
    # a remediating or standby-backed fleet runs, so base publication
    # stays single-writer across an averager failover
    lease = None
    if cfg.remediate or cfg.standby:
        from distributedtraining_tpu.engine.remediate import LeaseManager
        lease = LeaseManager(c.transport, cfg.hotkey)
    # provenance plane (engine/lineage.py): a content-addressed
    # __lineage__ record per landed merge + the merged-quality
    # EWMA/CUSUM drift detector, sharing the fleet's AnomalyMonitor
    # one-shot so a quality drift arms the same forensics a breach does
    lineage = None
    if cfg.lineage:
        from distributedtraining_tpu.engine.lineage import LineagePlane
        lineage = LineagePlane(c.transport, node=cfg.hotkey,
                               anomaly=anomaly)
    # content-addressed base distribution (engine/basedist.py): each
    # monolithic publish is followed by the changed-shard set + signed
    # per-revision manifest; the announce rider advertises the fleet's
    # __agg__ nodes (plus any --base-mirrors) as shard mirrors.
    # Single-host only — a pod's coordinator-gated monolithic publish
    # stays the whole story (the loop also gates on _multi()).
    base_dist = None
    if cfg.base_wire_v2:
        import jax as _jax
        if _jax.process_count() <= 1:
            from distributedtraining_tpu.engine.basedist import BasePublisher
            mirror_nodes = list(hierarchy or [])
            mirror_nodes += [m.strip() for m in
                             (cfg.base_mirrors or "").split(",")
                             if m.strip() and m.strip() not in mirror_nodes]
            base_dist = BasePublisher(c.transport, mirrors=mirror_nodes)
    loop = AveragerLoop(c.engine, c.transport, c.chain,
                        make_strategy(cfg, c.model),
                        val_batches=c.eval_batches(),
                        address_store=c.address_store,
                        max_delta_abs=cfg.max_delta_abs,
                        metrics=c.metrics, lora_cfg=c.lora_cfg,
                        accept_quant=cfg.accept_quant,
                        accept_wire_v2=cfg.accept_wire_v2,
                        stale_deltas=cfg.stale_deltas or "skip",
                        publish_policy=cfg.publish_policy,
                        ingest_workers=cfg.ingest_workers,
                        ingest_cache_mb=cfg.ingest_cache_mb,
                        fleet=plane.fleet,
                        remediation=plane.remediation,
                        lease=lease,
                        hierarchy=hierarchy,
                        lineage=lineage,
                        base_dist=base_dist)
    if plane.heartbeat is not None:
        plane.heartbeat.vitals = report_vitals(
            loop.report, base_revision=lambda: loop._base_revision)
        plane.heartbeat.start()
    try:
        if cfg.standby:
            # passive failover replica: NO bootstrap (a standby must
            # never publish a genesis base or steal the lease at boot) —
            # it follows the primary and bootstraps at takeover
            from distributedtraining_tpu.engine.remediate import (
                StandbyAverager)
            standby = StandbyAverager(
                loop, lease,
                deadline_s=(cfg.failover_deadline
                            or 3 * cfg.averaging_interval),
                poll_s=max(1.0, min(cfg.averaging_interval / 4, 30.0)))
            merged = standby.run(interval=cfg.averaging_interval,
                                 rounds=cfg.rounds)
        else:
            if lease is not None:
                try:
                    if not lease.acquire():
                        logging.warning(
                            "averager: lease held elsewhere at boot; "
                            "rounds will merge but stand down at publish "
                            "until the lease is reclaimed")
                except Exception:
                    logging.warning("averager: lease acquisition failed "
                                    "at boot; will retry lazily",
                                    exc_info=True)
            loop.bootstrap(params=c.initial_params)
            merged = loop.run_periodic(interval=cfg.averaging_interval,
                                       rounds=cfg.rounds)
    except KeyboardInterrupt:
        merged = loop.report.rounds > 0
    finally:
        plane.close()  # exporter socket + heartbeat timer + fleet pool
        loop.close()   # drain the ingest pool's worker threads
        # see neurons/miner.py: crash bundle, then global obs state reset
        flight.shutdown()
        from distributedtraining_tpu.utils import devprof, obs
        obs.reset()
        devprof.reset()
    logging.info("averager done: rounds=%d accepted=%d rejected=%d loss=%.4f",
                 loop.report.rounds, loop.report.last_accepted,
                 loop.report.last_rejected, loop.report.last_loss)
    return 0 if merged else 1


if __name__ == "__main__":
    raise SystemExit(main())
