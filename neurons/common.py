"""Shared composition for the role entry points.

The reference's neurons/{miner,validator,averager}.py each hand-assemble
dataset + tokenizer + model + HF/chain managers with copy-pasted Dataset
classes (neurons/miner.py:69-99 vs validator.py:62-93 vs averager.py:71-90).
Here composition is one function, driven by RunConfig, with no import-time
side effects.
"""

from __future__ import annotations

import dataclasses
import logging
import os
from typing import Any, Callable, Iterable

from distributedtraining_tpu.chain import LocalAddressStore, LocalChain
from distributedtraining_tpu.config import RunConfig
from distributedtraining_tpu.data import (ByteTokenizer, batch_iterator,
                                          load_tokenizer, text_corpus)
from distributedtraining_tpu.data.datasets import shuffle_seed_for
from distributedtraining_tpu.engine import TrainEngine, default_optimizer
from distributedtraining_tpu.models import gpt2, llama
from distributedtraining_tpu.parallel import make_mesh, resolve_mesh_config
from distributedtraining_tpu.transport import (InMemoryTransport,
                                               LocalFSTransport)
from distributedtraining_tpu.utils import JSONLSink, multi_sink

logger = logging.getLogger(__name__)


@dataclasses.dataclass
class Components:
    cfg: RunConfig
    model: Any
    model_cfg: Any
    engine: TrainEngine
    transport: Any
    chain: Any
    address_store: Any
    tokenizer: Any
    metrics: Any
    lora_cfg: Any = None  # set when --lora-rank > 0 (config 4 mode)

    def train_batches(self, *, repeat: bool = True) -> Iterable[dict]:
        import jax

        docs = text_corpus(split="train", source=self.cfg.dataset,
                           n_docs=self.cfg.n_docs)
        bs = self.cfg.batch_size
        if jax.process_count() > 1:
            # --batch-size is the GLOBAL batch on a pod: each process feeds
            # its own document shard at batch_size/process_count and the
            # engine assembles one global array per step (place_batch)
            from distributedtraining_tpu.parallel import multihost
            if bs % jax.process_count():
                # silently shrinking the global batch would surface later as
                # a baffling dp-axis divisibility error in place_batch
                raise SystemExit(
                    f"--batch-size {bs} (global) must be divisible by the "
                    f"process count {jax.process_count()}")
            docs = list(multihost.shard_documents(docs))
            bs //= jax.process_count()
        # ref trains via a shuffling DataLoader (neurons/miner.py:101-106);
        # eval stays ordered; per-hotkey seed decorrelates the miners
        it = batch_iterator(docs, self.tokenizer, batch_size=bs,
                            seq_len=self.cfg.seq_len, repeat=repeat,
                            max_vocab=self.model_cfg.vocab_size,
                            shuffle=True,
                            seed=shuffle_seed_for(self.cfg.hotkey))
        if self.cfg.prefetch_depth > 0:
            from distributedtraining_tpu.data import prefetch
            it = prefetch(it, depth=self.cfg.prefetch_depth)
        return it

    def initial_params(self):
        """Pretrained starting point per --init-from (None without the flag).
        Passed to bootstrap as a thunk and invoked only on the genesis path —
        a published base or local checkpoint always wins, and a supervised
        restart must not re-pay the checkpoint load/convert for weights it
        would immediately discard (reference boot order: from_pretrained then
        pull, neurons/miner.py:60 + training_manager.py:361-378)."""
        if not self.cfg.init_from:
            return None
        from distributedtraining_tpu.models import convert
        logger.info("loading pretrained weights from %s", self.cfg.init_from)
        return convert.load_params(self.cfg.init_from, self.model_cfg)

    _test_docs_cache = None

    def _test_docs(self) -> list[str]:
        if self._test_docs_cache is None:
            self._test_docs_cache = text_corpus(
                split="test", source=self.cfg.dataset,
                n_docs=max(256, self.cfg.n_docs // 8))
        return self._test_docs_cache

    def _batches_over(self, docs) -> Callable[[], Iterable[dict]]:
        cfg = self.cfg

        def factory():
            it = batch_iterator(docs, self.tokenizer,
                                batch_size=cfg.batch_size,
                                seq_len=cfg.eval_seq_len,
                                max_vocab=self.model_cfg.vocab_size)
            for i, b in enumerate(it):
                if i >= cfg.eval_batches:
                    break
                yield b

        return factory

    def eval_batches(self) -> Callable[[], Iterable[dict]]:
        """SERVER-side held-out shard (validator scoring, averager
        meta-learning/publish guard): the FRONT half of the test split —
        the reference evaluates the first ~100 test texts
        (neurons/validator.py:49,98). The back half is reserved for miner
        self-validation (``miner_val_batches``), keeping the two roles'
        eval data disjoint."""
        docs = self._test_docs()
        return self._batches_over(docs[: max(1, len(docs) // 2)]
                                  if len(docs) >= 4 else docs)

    def miner_val_batches(self) -> Callable[[], Iterable[dict]]:
        """Miner self-validation shard: a per-hotkey-offset rotation of the
        BACK half of the test split, disjoint from the validator's shard
        (round-5 advisor: a miner guarding on the IDENTICAL shard the
        validator scores biases its published state toward that shard by
        selection — its score reads high by construction). The per-hotkey
        rotation additionally decorrelates which windows different miners
        overfit toward, like shuffle_seed_for does for train order."""
        docs = self._test_docs()
        if len(docs) < 4:
            logger.warning(
                "test split too small (%d docs) to give the miner a "
                "disjoint self-eval shard; guard evals will share the "
                "validator's data", len(docs))
            tail = docs
        else:
            tail = docs[len(docs) // 2:]
        off = shuffle_seed_for(self.cfg.hotkey) % len(tail)
        return self._batches_over(tail[off:] + tail[:off])


@dataclasses.dataclass
class HealthPlane:
    """The role's slice of the fleet health plane (engine/health.py):
    its own heartbeat publisher, optionally a FleetMonitor (validator/
    averager), optionally the remediation engine acting on that
    monitor's breaches (engine/remediate.py, ``--remediate``), and
    optionally the Prometheus exporter (--obs-port)."""
    heartbeat: Any = None
    fleet: Any = None
    remediation: Any = None
    exporter: Any = None

    def close(self) -> None:
        """Idempotent teardown in dependency order (exporter may render
        the fleet ledger until the moment it stops serving)."""
        if self.exporter is not None:
            self.exporter.close()
        if self.heartbeat is not None:
            self.heartbeat.close()
        if self.fleet is not None:
            self.fleet.close()


def build_health_plane(cfg: RunConfig, c: Components, *,
                       vitals=None, monitor: bool = False,
                       anomaly=None,
                       collect=None,
                       start_heartbeat: bool = True) -> HealthPlane:
    """Assemble the role's health plane from config: a heartbeat
    publisher when ``--heartbeat-interval`` > 0 (``vitals`` supplies the
    body — engine/health.report_vitals over the role's report), a
    FleetMonitor for the delta-consuming roles (``monitor=True``), and
    the ``--obs-port`` exporter. Pod rule: only the coordinator
    publishes heartbeats or monitors the fleet (writes are gated there
    anyway, and N identical monitors would multiply probe traffic);
    the exporter serves per host — per-process registries differ."""
    from distributedtraining_tpu.parallel import multihost

    plane = HealthPlane()
    coordinator = multihost.is_coordinator()
    if cfg.heartbeat_interval > 0 and coordinator:
        from distributedtraining_tpu.engine.health import (FleetMonitor,
                                                           HeartbeatPublisher)
        if monitor:
            plane.fleet = FleetMonitor(c.transport, metrics=c.metrics,
                                       anomaly=anomaly)
            if cfg.remediate:
                from distributedtraining_tpu.engine.remediate import (
                    RemediationEngine, RemediationPolicy)
                rules = tuple(r.strip()
                              for r in cfg.quarantine_rules.split(",")
                              if r.strip())
                plane.remediation = RemediationEngine(
                    plane.fleet, metrics=c.metrics,
                    policy=RemediationPolicy(
                        quarantine_rules=rules,
                        probation_beats=cfg.probation_beats,
                        probation_rounds=cfg.probation_rounds,
                        score_decay=cfg.score_decay))
        plane.heartbeat = HeartbeatPublisher(
            c.transport, cfg.role, cfg.hotkey,
            interval=cfg.heartbeat_interval, vitals=vitals,
            collect=collect)
        if start_heartbeat:
            plane.heartbeat.start()
    elif cfg.remediate and coordinator:
        logger.warning(
            "--remediate has no effect without --heartbeat-interval > 0: "
            "remediation acts on SLO breaches, and breaches come from the "
            "heartbeat-fed FleetMonitor")
    if cfg.obs_port:
        from distributedtraining_tpu.utils.obs_http import ObsHTTPExporter
        plane.exporter = ObsHTTPExporter(
            cfg.obs_port, fleet=plane.fleet, role=cfg.role,
            profile_dir=os.path.join(cfg.work_dir, "debug_traces",
                                     cfg.hotkey))
        plane.exporter.start()
    return plane


def build_base_fetcher(cfg: RunConfig, c: Components):
    """The role's content-addressed base fetcher
    (engine/basedist.BaseFetcher) when ``--base-wire-v2`` is on, else
    None (the monolithic reference pull). Mirrors come from
    ``--base-mirrors`` (the averager's announce rider extends the list
    at fetch time). Single-host machinery — pods keep the coordinator
    broadcast path, so they get None."""
    import jax

    if not cfg.base_wire_v2 or jax.process_count() > 1:
        return None
    from distributedtraining_tpu.engine.basedist import BaseFetcher
    mirrors = [m.strip() for m in (cfg.base_mirrors or "").split(",")
               if m.strip()]
    return BaseFetcher(c.transport, mirrors=mirrors,
                       store_bytes=cfg.base_store_mb * (1 << 20))


def enable_compile_cache(path: str) -> None:
    """Point JAX's persistent compilation cache at ``path`` (ROADMAP
    item 5, first half): every role applies this at build, so a role
    RESTART — and a supervised respawn, and the averager failover
    standby — deserializes the previous process's XLA executables
    instead of recompiling the bucket ladders from scratch. The
    ``compile.ms`` histogram then measures cache-load time (tens of ms)
    instead of compile time (seconds); tests/test_serve.py pins the
    restart behavior. The threshold knobs are best-effort: names drift
    across JAX versions, and a missing knob only means the default
    threshold applies."""
    import jax

    os.makedirs(path, exist_ok=True)
    jax.config.update("jax_compilation_cache_dir", path)
    for knob, val in (("jax_persistent_cache_min_compile_time_secs", 0.0),
                      ("jax_persistent_cache_min_entry_size_bytes", 0)):
        try:
            jax.config.update(knob, val)
        except (AttributeError, ValueError):  # pragma: no cover — jax drift
            logger.debug("compile cache knob %s unavailable", knob)
    # the cache module memoizes "disabled" the first time ANY compile
    # runs without a dir configured (platform probes compile tiny
    # programs well before build()); reset so the new dir takes effect
    try:
        from jax._src import compilation_cache as _cc
        _cc.reset_cache()
    except Exception:  # pragma: no cover — private-API drift
        logger.debug("compilation_cache.reset_cache unavailable",
                     exc_info=True)
    logger.info("persistent compilation cache at %s", path)


def build(cfg: RunConfig) -> Components:
    import jax

    from distributedtraining_tpu.parallel import multihost

    # config 5 (multi-host pod): env-gated no-op on a single host; on a pod
    # every process of the role runs this same build and forms one SPMD
    # program over the global mesh
    multihost.initialize(coordinator_address=cfg.multihost_coordinator,
                         num_processes=cfg.multihost_processes,
                         process_id=cfg.multihost_id)

    if cfg.compile_cache_dir:
        # before ANY jit dispatch so the whole build benefits
        enable_compile_cache(cfg.compile_cache_dir)

    import dataclasses as _dc

    family = llama if cfg.model in llama.PRESETS else gpt2
    model_cfg = family.PRESETS[cfg.model]
    if cfg.scan_blocks:
        model_cfg = _dc.replace(model_cfg, scan_blocks=True)
    if cfg.logits_dtype:
        model_cfg = _dc.replace(model_cfg, logits_dtype=cfg.logits_dtype)
    if cfg.remat is not None:   # tri-state: None = keep the preset's default
        model_cfg = _dc.replace(model_cfg, remat=cfg.remat)
    model, model_cfg = family.make_model(model_cfg)

    mesh = None
    spec = cfg.mesh
    n_params = 0
    if spec.auto:
        import numpy as _np
        abstract = jax.eval_shape(
            lambda: model.init_params(jax.random.PRNGKey(0)))
        n_params = sum(int(_np.prod(l.shape))
                       for l in jax.tree_util.tree_leaves(abstract))
    if jax.process_count() > 1:
        rcfg = resolve_mesh_config(
            n_devices=len(jax.devices()), dp=spec.dp, fsdp=spec.fsdp,
            sp=spec.sp, tp=spec.tp, auto=spec.auto, model_params=n_params,
            dcn_dp=spec.dcn_dp)
        mesh = multihost.pod_mesh(dp=rcfg.dp, fsdp=rcfg.fsdp, sp=rcfg.sp,
                                  tp=rcfg.tp, dcn_dp=spec.dcn_dp)
    else:
        mcfg = resolve_mesh_config(
            n_devices=len(jax.devices()), dp=spec.dp, fsdp=spec.fsdp,
            sp=spec.sp, tp=spec.tp, auto=spec.auto, model_params=n_params,
            dcn_dp=spec.dcn_dp)
        if mcfg.n_devices > 1:
            mesh = make_mesh(mcfg)

    seq = cfg.seq_len if cfg.role == "miner" else cfg.eval_seq_len
    engine = TrainEngine(
        model,
        optimizer=default_optimizer(cfg.learning_rate,
                                    grad_clip=cfg.grad_clip,
                                    weight_decay=cfg.weight_decay,
                                    mu_dtype=cfg.mu_dtype),
        mesh=mesh, seq_len=seq, fused_loss=cfg.fused_loss,
        accum_steps=cfg.accum_steps)

    if cfg.backend == "memory":
        transport = InMemoryTransport()
    elif cfg.backend == "hf":
        if not cfg.averaged_model_repo_id:
            raise SystemExit(
                "--backend hf requires --averaged-model-repo-id")
        if cfg.role == "miner" and not cfg.my_repo_id:
            raise SystemExit("--backend hf miner requires --my-repo-id")
        from distributedtraining_tpu.transport import HFHubTransport
        transport = HFHubTransport(
            averaged_model_repo_id=cfg.averaged_model_repo_id,
            my_repo_id=cfg.my_repo_id,
            owns_base_repo=(cfg.role == "averager"))
    else:
        transport = LocalFSTransport(os.path.join(cfg.work_dir, "artifacts"))

    if cfg.chain == "bittensor":
        from distributedtraining_tpu.chain import (BittensorAddressStore,
                                                   BittensorChain)
        chain = BittensorChain(netuid=cfg.netuid,
                               wallet_name=cfg.wallet_name,
                               wallet_hotkey=cfg.wallet_hotkey,
                               network=cfg.subtensor_network,
                               epoch_length=cfg.epoch_length,
                               resync_blocks=cfg.resync_blocks,
                               vpermit_stake_limit=cfg.vpermit_stake_limit)
        # chain._rpc carries the deadline + per-call connection capture +
        # lazy-recycle discipline; injecting it keeps store and chain on
        # ONE live connection instead of desynchronizing after a recycle
        address_store = BittensorAddressStore(
            chain.subtensor, cfg.netuid, wallet=chain.wallet,
            rpc=chain._rpc)
    else:
        if cfg.backend == "hf":
            # deltas would flow through the Hub while scores stay in a
            # machine-local JSON no other participant can read
            logger.warning(
                "--backend hf with --chain local: chain state (scores, "
                "weights, repo registry) is local to this machine; use "
                "--chain bittensor for a multi-host deployment")
        chain_dir = os.path.join(cfg.work_dir, "chain")
        chain = LocalChain(chain_dir, my_hotkey=cfg.hotkey,
                           epoch_length=cfg.epoch_length,
                           vpermit_stake_limit=cfg.vpermit_stake_limit)
        address_store = LocalAddressStore(chain_dir)
    # artifact authenticity: sign publishes, verify fetches against
    # registered pubkeys (reference anchor: repo ownership + hotkey-signed
    # metrics, dummy_miner.py:63-68). Wrapped INSIDE the coordinator gate so
    # pod writes stay coordinator-only.
    identity = None
    if cfg.sign_artifacts:
        from distributedtraining_tpu.transport import SignedTransport
        from distributedtraining_tpu.utils.identity import Identity
        wallet_path = cfg.wallet_path or os.path.join(
            cfg.work_dir, "wallets", f"{cfg.hotkey}.json")
        # pod roles: ONLY the coordinator holds a signing identity — its
        # publishes are the only ones that leave the pod (gate_io), and N
        # processes generate-and-saving to one shared wallet path would
        # race, registering one process's key while another's lands in the
        # file (bricking the hotkey under first-write-wins on next boot)
        if multihost.is_coordinator():
            if os.path.exists(wallet_path):
                identity = Identity.load(wallet_path)
            else:
                identity = Identity.generate()
                identity.save(wallet_path)
                logger.info("generated signing identity %s at %s",
                            identity.hotkey, wallet_path)
        base_signer = cfg.base_signer or (
            cfg.hotkey if cfg.role == "averager" else None)
        transport = SignedTransport(
            transport, identity=identity,
            pubkey_resolver=address_store.retrieve_pubkey,
            base_signer=base_signer, my_hotkey=cfg.hotkey)
        register_ok = True
        if multihost.is_coordinator():
            try:
                address_store.store_pubkey(cfg.hotkey, identity.public_bytes)
            except ValueError:
                register_ok = False
        if jax.process_count() > 1:
            # every process must learn the coordinator's verdict: a
            # coordinator-only SystemExit would leave the workers alive and
            # hung at their first collective
            import numpy as _np
            from jax.experimental import multihost_utils as _mhu
            register_ok = bool(_mhu.broadcast_one_to_all(
                _np.asarray(register_ok, _np.int32)))
        if not register_ok:
            # key already registered for this hotkey and differs — a
            # rotated local wallet must fail loudly, not publish
            # artifacts every peer will reject
            raise SystemExit(
                f"hotkey {cfg.hotkey} has a different registered "
                f"pubkey; restore the original wallet file or use a "
                f"new hotkey")
    if cfg.chaos_spec:
        # deterministic fault injection (transport/chaos.py): wraps the
        # OUTERMOST transport layer so injected faults hit signed
        # publishes and verified fetches exactly like network faults
        # would. Soak/test machinery — the flag warns on every boot.
        from distributedtraining_tpu.transport.chaos import (ChaosSpec,
                                                             ChaosTransport)
        logger.warning("CHAOS INJECTION ACTIVE for role %s: %s",
                       cfg.role, cfg.chaos_spec)
        transport = ChaosTransport(transport,
                                   ChaosSpec.from_json(cfg.chaos_spec),
                                   role=cfg.role)
    # only the coordinator process of a pod role may write to the outside
    # world (delta pushes, base publishes, weight sets)
    transport, chain = multihost.gate_io(transport, chain)
    if jax.process_count() > 1 and cfg.backend != "hf":
        # reads pass through the gate on every process: with per-host
        # storage, workers would never observe published bases and diverge
        logger.warning(
            "multi-host run with --backend %s: every host reads %s "
            "directly — it MUST be shared storage (NFS/gcsfuse) across all "
            "hosts, or use --backend hf", cfg.backend, cfg.work_dir)

    if cfg.my_repo_id and multihost.is_coordinator():
        # advertise our repo like the reference miner does on-chain
        # (neurons/miner.py:36-44)
        address_store.store_repo(cfg.hotkey, cfg.my_repo_id)

    if cfg.tokenizer == "byte" or (cfg.tokenizer == "auto"
                                   and model_cfg.vocab_size < 50257):
        tokenizer = ByteTokenizer()
    elif cfg.tokenizer == "word":
        # corpus-fit word vocab, deterministic per corpus: every role of a
        # deployment rebuilds the identical mapping with no shared artifact
        # (the offline stand-in for the GPT-2 BPE — scripts/e2e_round.py)
        from distributedtraining_tpu.data import WordTokenizer
        tokenizer = WordTokenizer(
            text_corpus(split="train", source=cfg.dataset),
            vocab_size=model_cfg.vocab_size)
    elif cfg.tokenizer == "bpe":
        # REAL byte-level BPE (GPT-2's algorithm) trained locally on the
        # machine's own text — the big-vocab production tokenizer with
        # zero egress (data/bpe.py). Saved under the work_dir so the
        # three roles of a deployment train it once.
        from distributedtraining_tpu.data.bpe import BPETokenizer
        tokenizer = BPETokenizer.train_or_load(
            os.path.join(cfg.work_dir, "tokenizer",
                         f"bpe-{min(model_cfg.vocab_size, 32000)}.json"),
            vocab_size=min(model_cfg.vocab_size, 32000))
    else:
        tokenizer = load_tokenizer(
            "gpt2" if cfg.tokenizer == "auto" else cfg.tokenizer)

    sinks = []
    if cfg.metrics_path:
        sinks.append(JSONLSink(
            cfg.metrics_path,
            max_bytes=(cfg.metrics_rotate_mb * (1 << 20)
                       if cfg.metrics_rotate_mb > 0 else None),
            keep_segments=max(1, cfg.metrics_keep_segments)))
    if cfg.mlflow_uri:
        from distributedtraining_tpu.utils.metrics import MLflowSink
        sinks.append(MLflowSink(tracking_uri=cfg.mlflow_uri,
                                experiment=f"hivetrain-{cfg.netuid}",
                                run_name=f"{cfg.role}-{cfg.hotkey}"))
    metrics = multi_sink(*sinks) if sinks else None
    if metrics is not None:
        # bind the process-wide span/counter emitter (utils/obs.py) to
        # this role's sink: every engine/transport span and registry
        # flush lands in the same JSONL the scalar metrics do, which is
        # what scripts/obs_report.py joins across roles. Role mains reset
        # it on exit so sequential in-process role runs (e2e) stay clean.
        from distributedtraining_tpu.utils import obs
        obs.configure(metrics, role=cfg.role)
        if cfg.devprof:
            # device observatory (utils/devprof.py): per-program cost
            # attribution + roofline gauges on every registered hot
            # path; rides the same sink via the obs.flush hook. Role
            # mains reset it alongside obs on exit.
            from distributedtraining_tpu.utils import devprof
            devprof.enable()
    if cfg.flight_events > 0:
        # flight recorder (utils/flight.py): the bounded forensic ring
        # every role keeps, frozen into a transport-published __pm__
        # bundle on SLO breach / remediation / crash. Configured on every
        # process — bundle PUBLISHES ride the coordinator-gated transport
        # like any other write, so pod workers record locally and ship
        # nothing. Role mains install the crash hooks and call
        # flight.shutdown() on exit.
        from distributedtraining_tpu.utils import flight
        flight.configure(cfg.role, cfg.hotkey, transport=transport,
                         capacity=cfg.flight_events, config=cfg)

    lora_cfg = None
    if cfg.lora_rank > 0:
        from distributedtraining_tpu.models.lora import LoRAConfig
        lora_cfg = LoRAConfig(rank=cfg.lora_rank, alpha=cfg.lora_alpha)

    return Components(cfg=cfg, model=model, model_cfg=model_cfg,
                      engine=engine, transport=transport, chain=chain,
                      address_store=address_store, tokenizer=tokenizer,
                      metrics=metrics, lora_cfg=lora_cfg)
