"""Miner entry point: train on the current base, publish weight deltas.

Rebuild of the reference miner (neurons/miner.py:30-129 → DeltaLoop,
hivetrain/training_manager.py:345-433). Run offline end-to-end with:

    python neurons/miner.py --backend local --work-dir /tmp/run \
        --model tiny --dataset synthetic --max-steps 50
"""

from __future__ import annotations

import logging
import os
import sys

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)

# platform override BEFORE any backend touch (see utils/platform.py)
from distributedtraining_tpu.utils.platform import (  # noqa: E402
    force_platform_from_env)

force_platform_from_env()

from distributedtraining_tpu.config import RunConfig   # noqa: E402
from distributedtraining_tpu.engine import MinerLoop   # noqa: E402
from neurons.common import (build, build_base_fetcher,  # noqa: E402
                            build_health_plane)


def _guard_kwargs(cfg, c) -> dict:
    """Self-validation-guard wiring, shared by the full-param and LoRA
    branches. 0 disables; negative follows --send-interval (and disables
    when that is non-positive — push-every-step runs would eval every
    step and revert on per-step noise).

    The guard evals run on the miner's OWN disjoint slice of the test
    split (Components.miner_val_batches), never the validator's shard:
    keeping best-seen state by the exact data it is scored on would bias
    published scores upward by selection (round-5 advisor)."""
    if cfg.self_eval_interval == 0:
        return {}
    interval = (cfg.self_eval_interval if cfg.self_eval_interval > 0
                else cfg.send_interval)
    if interval <= 0:
        return {}
    return dict(val_batches=c.miner_val_batches(),
                val_guard_interval=interval,
                val_guard_patience=cfg.self_eval_patience,
                val_guard_margin=cfg.self_eval_margin)


def main(argv=None) -> int:
    logging.basicConfig(level=logging.INFO,
                        format="%(asctime)s %(name)s %(message)s")
    cfg = RunConfig.from_args("miner", argv)
    c = build(cfg)
    # crash-forensics triggers (utils/flight.py): an unhandled exception
    # (main or worker thread) or interpreter exit freezes the flight
    # ring into a transport-published postmortem bundle
    from distributedtraining_tpu.utils import flight
    flight.install_crash_hooks()

    trace = None
    if cfg.profile_dir:
        from distributedtraining_tpu.utils.metrics import TraceCapture
        trace = TraceCapture(cfg.profile_dir, steps=cfg.profile_steps)
    anomaly = None
    if cfg.anomaly_trace:
        # disarmed capture + monitor: a loss spike, push-failure streak,
        # or step-time p99 blowout arms ONE bounded profiler window
        # automatically (utils/obs.AnomalyMonitor); until then every
        # tick is a no-op
        from distributedtraining_tpu.utils.metrics import TraceCapture
        from distributedtraining_tpu.utils.obs import AnomalyMonitor
        anomaly = AnomalyMonitor(TraceCapture(
            cfg.anomaly_dir or os.path.join(cfg.work_dir, "anomaly_traces",
                                            cfg.hotkey),
            steps=cfg.profile_steps, arm=False))
    # content-addressed base pulls (engine/basedist.py): changed-hash
    # layers only, mirror racing, monolithic fallback; None when
    # --no-base-wire-v2 (or on a pod, where the coordinator broadcast
    # stays monolithic)
    base_fetcher = build_base_fetcher(cfg, c)
    store = None
    if cfg.checkpoint_interval > 0:
        from distributedtraining_tpu.checkpoint import CheckpointStore
        ckpt_dir = cfg.checkpoint_dir or os.path.join(
            cfg.work_dir, "checkpoints", cfg.hotkey)
        store = CheckpointStore(ckpt_dir)
    if c.lora_cfg is not None:
        # config-4 mode: adapter-only training, adapter-tree artifacts.
        # Reuse the composed engine's optimizer so --learning-rate and
        # --grad-clip apply to adapters too; the mesh shards the frozen
        # base (fsdp/tp) while adapters replicate.
        from distributedtraining_tpu.engine import LoRAEngine, LoRAMinerLoop
        if cfg.keep_optimizer_on_pull:
            # adapters are re-initialized on every base change (they are
            # defined RELATIVE to the base), so there is no state to
            # carry — refuse silently doing nothing
            logging.warning(
                "--keep-optimizer-on-pull has no effect for LoRA miners "
                "(adapters and their optimizer reset with the base); "
                "ignoring")
        engine = LoRAEngine(c.model, c.lora_cfg, optimizer=c.engine.tx,
                            mesh=c.engine.mesh, seq_len=cfg.seq_len,
                            accum_steps=cfg.accum_steps,
                            fused_loss=cfg.fused_loss)
        loop = LoRAMinerLoop(engine, c.transport, cfg.hotkey,
                             send_interval=cfg.send_interval,
                             check_update_interval=cfg.check_update_interval,
                             metrics=c.metrics, log_every=cfg.log_every,
                             checkpoint_store=store,
                             checkpoint_interval=cfg.checkpoint_interval,
                             push_async=cfg.push_async,
                             push_queue_depth=cfg.push_queue_depth,
                             trace=trace, anomaly=anomaly,
                             base_fetcher=base_fetcher,
                             **_guard_kwargs(cfg, c))
    else:
        loop = MinerLoop(c.engine, c.transport, cfg.hotkey,
                         send_interval=cfg.send_interval,
                         check_update_interval=cfg.check_update_interval,
                         metrics=c.metrics, log_every=cfg.log_every,
                         delta_dtype=(None if cfg.delta_dtype == "float32"
                                      else cfg.delta_dtype),
                         delta_density=cfg.delta_density,
                         wire_v2=cfg.wire_v2,
                         wire_density=cfg.wire_density,
                         wire_quant=cfg.wire_quant,
                         keep_optimizer_on_pull=cfg.keep_optimizer_on_pull,
                         checkpoint_store=store,
                         checkpoint_interval=cfg.checkpoint_interval,
                         push_async=cfg.push_async,
                         push_queue_depth=cfg.push_queue_depth,
                         trace=trace, anomaly=anomaly,
                         base_fetcher=base_fetcher,
                         **_guard_kwargs(cfg, c))
    # fleet health plane: heartbeat publisher (loop-managed: starts with
    # training, final beat + close in flush()) and the --obs-port
    # exporter. Vitals read the loop's live report.
    from distributedtraining_tpu.engine.health import report_vitals
    plane = build_health_plane(
        cfg, c, start_heartbeat=False,
        vitals=report_vitals(loop.report,
                             base_revision=lambda: loop._base_revision),
        # base-distribution extras (base_fetch_bytes / mirror hit rate)
        # ride the heartbeat so fleet_report's base_b/mirror_hit columns
        # show the delta-pull economy per node
        collect=(base_fetcher.heartbeat_fields
                 if base_fetcher is not None else None))
    loop.heartbeat = plane.heartbeat

    def _bootstrap():
        # bounded retry on TRANSPORT errors only: a preemption restart is
        # exactly when the backend may still be partitioned (the outage
        # that killed us), and an instant crash here burns supervise.sh's
        # crash-loop budget against a fault a short backoff rides out.
        # Programming errors re-raise immediately. bootstrap is
        # idempotent (restore + fetch, no partial publishes), so a retry
        # re-runs it whole.
        import time as _time
        for attempt in range(3):
            try:
                return loop.bootstrap(params=c.initial_params)
            except OSError:
                if attempt == 2:
                    raise
                delay = 2.0 * (attempt + 1)
                logging.warning("miner bootstrap: transport unreachable "
                                "(attempt %d/3); retrying in %.0fs",
                                attempt + 1, delay, exc_info=True)
                _time.sleep(delay)

    try:
        _bootstrap()
        report = loop.run(c.train_batches(), max_steps=cfg.max_steps)
        loop.flush()  # final delta + checkpoint so short runs still publish
    except KeyboardInterrupt:
        report = loop.report
        loop.flush()
    finally:
        if store is not None:
            store.close()
        plane.close()   # exporter socket + heartbeat timer (idempotent)
        # crash bundle first (an exceptional exit freezes the ring here,
        # while the transport is still wired), then drop the process-wide
        # observability state: sequential in-process role runs
        # (scripts/e2e_round.py, tests) must not bleed this role's
        # recorder/registry/sink into the next
        flight.shutdown()
        from distributedtraining_tpu.utils import devprof, obs
        obs.reset()
        devprof.reset()
    logging.info("miner done: steps=%d pushes=%d (failed=%d superseded=%d) "
                 "base_pulls=%d loss=%.4f",
                 report.steps, report.pushes, report.pushes_failed,
                 report.pushes_superseded, report.base_pulls,
                 report.last_loss)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
