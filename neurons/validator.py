"""Validator entry point: score every miner's delta, emit chain weights.

Rebuild of the reference validator (neurons/validator.py:26-115 →
ModelValidator/DeltaValidator, hivetrain/validation_logic.py). Run offline:

    python neurons/validator.py --backend local --work-dir /tmp/run \
        --model tiny --dataset synthetic --hotkey hotkey_91 --rounds 1
"""

from __future__ import annotations

import logging
import os
import sys

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)

# platform override BEFORE any backend touch (see utils/platform.py)
from distributedtraining_tpu.utils.platform import (  # noqa: E402
    force_platform_from_env)

force_platform_from_env()

from distributedtraining_tpu.config import RunConfig   # noqa: E402
from distributedtraining_tpu.engine import Validator   # noqa: E402
from neurons.common import (build, build_base_fetcher,  # noqa: E402
                            build_health_plane)


def main(argv=None) -> int:
    logging.basicConfig(level=logging.INFO,
                        format="%(asctime)s %(name)s %(message)s")
    cfg = RunConfig.from_args("validator", argv)
    c = build(cfg)
    # crash-forensics triggers (utils/flight.py, see neurons/miner.py)
    from distributedtraining_tpu.utils import flight
    flight.install_crash_hooks()
    base_fetcher = build_base_fetcher(cfg, c)
    validator = Validator(c.engine, c.transport, c.chain,
                          eval_batches=c.eval_batches(),
                          metric=cfg.score_metric,
                          max_delta_abs=cfg.max_delta_abs,
                          metrics=c.metrics, lora_cfg=c.lora_cfg,
                          accept_quant=cfg.accept_quant,
                          accept_wire_v2=cfg.accept_wire_v2,
                          stale_deltas=cfg.stale_deltas or "accept",
                          cohort_size=cfg.val_cohort,
                          pipeline_depth=cfg.val_pipeline_depth,
                          ingest_workers=cfg.ingest_workers,
                          ingest_cache_mb=cfg.ingest_cache_mb,
                          base_fetcher=base_fetcher)
    # the reference gates weight-setting to staked validators
    # (btt_connector.py:358-385); refuse up front instead of silently
    # burning eval compute on scores no one will ever see. On a pod the
    # COORDINATOR's verdict is broadcast: per-process chain syncs could
    # disagree at a stake boundary, and one process exiting while the rest
    # proceed would strand them at their first collective.
    import jax
    permitted = validator.has_vpermit() if jax.process_count() <= 1 else None
    if permitted is None:
        import numpy as np
        from jax.experimental import multihost_utils as mhu

        from distributedtraining_tpu.parallel import multihost
        local = validator.has_vpermit() if multihost.is_coordinator() else False
        permitted = bool(mhu.broadcast_one_to_all(
            np.asarray(local, np.int32)))
    if not permitted:
        if not cfg.allow_no_vpermit:
            raise SystemExit(
                f"hotkey {c.chain.my_hotkey} holds no validator permit "
                f"(stake < {cfg.vpermit_stake_limit}); pass "
                f"--allow-no-vpermit to run anyway without emitting weights")
        logging.warning("running WITHOUT a validator permit: weights will "
                        "not be emitted")
    # fleet health plane (after the permit gate, so a refused boot never
    # leaves an exporter socket or heartbeat timer behind): the validator
    # heartbeats AND monitors — its ledger carries the per-miner score
    # history alongside the staging outcomes; SLO breaches arm the
    # AnomalyMonitor one-shot (detection + counters).
    from distributedtraining_tpu.engine.health import Vitals
    from distributedtraining_tpu.utils.obs import AnomalyMonitor
    plane = build_health_plane(cfg, c, monitor=True,
                               anomaly=AnomalyMonitor(),
                               start_heartbeat=False,
                               collect=(base_fetcher.heartbeat_fields
                                        if base_fetcher is not None
                                        else None))
    validator.fleet = plane.fleet   # before the first round's lazy _ingest
    validator.remediation = plane.remediation  # and the lazy evaluator
    if plane.heartbeat is not None:
        plane.heartbeat.vitals = Vitals(
            steps=lambda: validator._round,
            loss=lambda: validator.base_loss,
            counters=lambda: {"rounds": validator._round},
            base_revision=lambda: validator._base_revision)
        plane.heartbeat.start()
    validator.bootstrap(params=c.initial_params)
    try:
        ok = validator.run_periodic(interval=cfg.validation_interval,
                                    rounds=cfg.rounds)
    except KeyboardInterrupt:
        logging.info("validator interrupted; exiting")
        return 0
    finally:
        plane.close()       # exporter socket + heartbeat timer + pool
        validator.close()   # drain the ingest pool's worker threads
        # see neurons/miner.py: crash bundle, then global obs state reset
        flight.shutdown()
        from distributedtraining_tpu.utils import devprof, obs
        obs.reset()
        devprof.reset()
    return 0 if ok else 1


if __name__ == "__main__":
    raise SystemExit(main())
