"""Role entry points: miner, validator, averager (SURVEY.md L5)."""
