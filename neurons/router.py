"""Router entry point: spread ``/generate`` across N serving hosts.

The admission tier in front of the serving fleet (engine/router.py):
polls each backend's ``/healthz`` for the load signals the heartbeat
plane already defines (queue depth, active slots, ``ttft_ms_p95`` /
``tpot_ms_p95``, served revision), routes every request to the
least-loaded backend on the majority revision, and sheds with
``429`` + ``Retry-After`` once every backend sits at its admission
bound — BEFORE the queueing knee FLEETSIM_r01 measured, not after.

The router holds no model state; run several behind DNS round-robin if
the router itself needs redundancy. Example:

    python neurons/router.py --port 8800 \
        --backend http://10.0.0.1:8900 --backend http://10.0.0.2:8900

    curl -d '{"tokens": [1, 2, 3]}' http://127.0.0.1:8800/generate
"""

from __future__ import annotations

import argparse
import logging
import os
import sys
import time

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)

from distributedtraining_tpu.engine.router import (            # noqa: E402
    RouterHTTPFrontend, RouterPolicy)

logger = logging.getLogger(__name__)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("--backend", action="append", dest="backends",
                    required=True,
                    help="serving backend base URL (repeatable), e.g. "
                         "http://10.0.0.1:8900")
    ap.add_argument("--port", type=int, default=8800)
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--max-queue", type=int, default=6,
                    help="per-backend admission bound (queued + active) "
                         "before the router sheds with 429")
    ap.add_argument("--shed-ttft-ms", type=float, default=0.0,
                    help="also shed a backend whose observed ttft p95 "
                         "exceeds this (0 = queue-bound only)")
    ap.add_argument("--no-prefer-revision", dest="prefer_revision",
                    action="store_false",
                    help="do not prefer backends on the majority base "
                         "revision")
    ap.add_argument("--poll-interval", type=float, default=1.0,
                    help="seconds between /healthz sweeps")
    ap.add_argument("--timeout", type=float, default=120.0,
                    help="per-request backend timeout (seconds)")
    ap.add_argument("--retry-after-cap", type=float, default=0.25,
                    help="max seconds to honor a backend's Retry-After "
                         "hint before retrying the next-best backend "
                         "(0 disables the wait)")
    ap.add_argument("-v", "--verbose", action="store_true")
    args = ap.parse_args(argv)

    logging.basicConfig(level=logging.INFO if args.verbose
                        else logging.WARNING)
    policy = RouterPolicy(max_queue_depth=args.max_queue,
                          shed_ttft_ms=args.shed_ttft_ms,
                          prefer_revision=args.prefer_revision)
    fe = RouterHTTPFrontend(args.backends, args.port, host=args.host,
                            policy=policy,
                            poll_interval_s=args.poll_interval,
                            timeout_s=args.timeout,
                            retry_after_cap_s=args.retry_after_cap)
    port = fe.start()
    print(f"router: http://{args.host}:{port}/generate -> "
          f"{len(args.backends)} backends (max queue {args.max_queue})",
          file=sys.stderr)
    try:
        while True:
            time.sleep(1.0)
    except KeyboardInterrupt:
        pass
    finally:
        fe.close()
    return 0


if __name__ == "__main__":
    sys.exit(main())
