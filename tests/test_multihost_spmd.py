"""Config-5 bring-up: a real 2-process jax.distributed run on CPU.

Two OS processes (coordinator + worker), each with 2 virtual CPU devices,
form one 4-device dp mesh through multihost.initialize/pod_mesh and execute
a sharded train step as one SPMD program, with distinct per-process data and
coordinator-gated IO — the single-host miniature of the v5e-64 launch
(SURVEY.md §7 step 9). The reference has no multi-node compute plane at all;
this is the capability its NCCL/MPI-flavored peers would provide.
"""

import os
import socket
import subprocess
import sys

import pytest

_WORKER = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                       "multihost_worker.py")


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def test_two_process_spmd_train_step():
    addr = f"127.0.0.1:{_free_port()}"
    env = {k: v for k, v in os.environ.items() if k != "XLA_FLAGS"}
    procs = [
        subprocess.Popen([sys.executable, _WORKER, str(pid), addr],
                         stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
                         text=True, env=env)
        for pid in (0, 1)
    ]
    outs = []
    for p in procs:
        try:
            out, _ = p.communicate(timeout=240)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            pytest.fail("multihost worker timed out")
        outs.append(out)
    for pid, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"worker {pid} failed:\n{out}"

    results = {}
    for out in outs:
        for line in out.splitlines():
            if line.startswith("RESULT "):
                _, pid, loss, coord = line.split()
                results[int(pid)] = (float(loss), int(coord))
    assert set(results) == {0, 1}, outs
    # one SPMD program: both processes observe the identical global loss
    assert results[0][0] == results[1][0]
    # exactly the coordinator reports coordinator status
    assert results[0][1] == 1 and results[1][1] == 0


def test_two_process_miner_cli(tmp_path):
    """The real role entry under jax.distributed: two miner processes form
    one fsdp=2 x dp=2 SPMD program (params sharded ACROSS processes), train,
    and exactly the coordinator publishes one delta — the full config-5
    wiring of neurons/common.build (initialize -> pod_mesh -> gated IO ->
    allgather-on-publish)."""
    # pre-publish a base into the shared work dir so the miners' bootstrap
    # takes the fetch path on both processes
    from distributedtraining_tpu.models import gpt2
    from distributedtraining_tpu.transport import LocalFSTransport
    import jax as _jax

    model, _ = gpt2.make_model("tiny")
    LocalFSTransport(str(tmp_path / "artifacts")).publish_base(
        model.init_params(_jax.random.PRNGKey(5)))

    addr = f"127.0.0.1:{_free_port()}"
    env = {k: v for k, v in os.environ.items() if k != "XLA_FLAGS"}
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
    env["DT_FORCE_PLATFORM"] = "cpu"
    miner = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "neurons", "miner.py")
    args = [
        "--work-dir", str(tmp_path), "--model", "tiny",
        "--dataset", "synthetic", "--hotkey", "hotkey_0",
        "--batch-size", "4", "--seq-len", "32",
        # send/check at 0s: the push's materialize collective and the pull's
        # coordinator-broadcast fire at EVERY poll site on both processes —
        # the exact desync hazards the synced-decision machinery exists for
        "--max-steps", "4", "--send-interval", "0",
        "--check-update-interval", "0",
        "--checkpoint-interval", "0",
        "--dp", "0", "--fsdp", "2",
        "--multihost-coordinator", addr, "--multihost-processes", "2",
    ]
    procs = [
        subprocess.Popen([sys.executable, miner, *args,
                          "--multihost-id", str(pid)],
                         stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
                         text=True, env=env)
        for pid in (0, 1)
    ]
    outs = []
    for p in procs:
        try:
            out, _ = p.communicate(timeout=240)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            pytest.fail("multihost miner timed out")
        outs.append(out)
    for pid, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"miner process {pid} failed:\n{out}"
    # exactly one delta artifact, written by the coordinator
    deltas = os.listdir(tmp_path / "artifacts" / "deltas")
    # exactly ONE artifact + ONE base-revision rider: both written once,
    # by the coordinator (CoordinatorGatedTransport gates publish_delta
    # AND publish_delta_meta)
    assert sorted(deltas) == ["hotkey_0.meta.json", "hotkey_0.msgpack"]
