"""Ops/lifecycle subsystems: identity, auto-update, load generation,
peer registry.

Reference coverage being formalized (SURVEY.md §2.1 "Ops/lifecycle" +
"Legacy/vestigial" rows): wallet generation (generate_wallets.py), version
polling + restart (utils/auto_update.py, run_miner.sh:229-268), dummy-miner
traffic (utils/dummy_miner.py), DHT bootstrap pool (utils/bootstrap_server.py).
"""

import itertools
import os

import numpy as np
import pytest

from distributedtraining_tpu.engine import TrainEngine, Validator
from distributedtraining_tpu.models import FeedforwardNet, ToyConfig
from distributedtraining_tpu.transport import InMemoryTransport
from distributedtraining_tpu.utils.auto_update import (
    AutoUpdater, file_version, parse_version)
from distributedtraining_tpu.utils.identity import (
    Identity, generate_wallets, load_wallets)
from distributedtraining_tpu.utils.loadgen import LoadGenerator
from distributedtraining_tpu.utils import registry as reg


# -- identity ---------------------------------------------------------------

def test_identity_sign_verify_roundtrip():
    ident = Identity.generate()
    msg = b"score report: loss improved"
    sig = ident.sign(msg)
    assert ident.verify(msg, sig)
    assert not ident.verify(b"tampered", sig)
    # a different key must not verify
    other = Identity.generate()
    assert not other.verify(msg, sig)


def test_wallet_storage_roundtrip(tmp_path):
    idents = generate_wallets(str(tmp_path), 3)
    loaded = load_wallets(str(tmp_path))
    assert [i.hotkey for i in idents] == [i.hotkey for i in loaded]
    # loaded wallets can still sign
    sig = loaded[0].sign(b"hello")
    assert idents[0].verify(b"hello", sig)
    # hotkeys are unique
    assert len({i.hotkey for i in idents}) == 3


def test_wallet_tamper_detection(tmp_path):
    ident = Identity.generate()
    path = str(tmp_path / "w.json")
    ident.save(path)
    import json
    payload = json.load(open(path))
    payload["hotkey"] = "hkdeadbeefdeadbeefdead"
    json.dump(payload, open(path, "w"))
    with pytest.raises(ValueError):
        Identity.load(path)


# -- auto-update ------------------------------------------------------------

def test_parse_version_forms():
    assert parse_version('__version__ = "1.2.3"\n') == "1.2.3"
    assert parse_version("2.0.1\n") == "2.0.1"
    assert parse_version("nothing here") is None


def test_file_version(tmp_path):
    p = tmp_path / "VERSION"
    p.write_text("0.9.1\n")
    assert file_version(str(p)) == "0.9.1"
    assert file_version(str(tmp_path / "missing")) is None


def test_autoupdater_triggers_only_on_change():
    calls = []
    published = {"v": "1.0.0"}
    upd = AutoUpdater("1.0.0", lambda: published["v"], update_cmd=None,
                      restart=lambda: calls.append("restart"))
    assert upd.check() is False          # same version: no-op
    published["v"] = None
    assert upd.check() is False          # unreachable source: no-op
    published["v"] = "1.1.0"
    assert upd.check() is True
    assert calls == ["restart"]


def test_autoupdater_failed_update_cmd_blocks_restart(tmp_path):
    calls = []
    upd = AutoUpdater("1.0.0", lambda: "2.0.0",
                      update_cmd=["false"], repo_dir=str(tmp_path),
                      restart=lambda: calls.append("restart"))
    assert upd.check() is False
    assert calls == []  # never restart into un-updated code


# -- load generation vs the validator's admission screens -------------------

def test_loadgen_poison_screened_by_validator():
    cfg = ToyConfig(image_size=8, hidden=8, n_classes=2)
    model = FeedforwardNet(cfg)

    def loss(model, params, batch):
        from distributedtraining_tpu.ops.losses import classification_loss
        logits = model.apply({"params": params}, batch["images"])
        return classification_loss(logits, batch["labels"])

    engine = TrainEngine(model, loss_fn=loss)
    transport = InMemoryTransport()
    import jax
    base = model.init_params(jax.random.PRNGKey(0))
    transport.publish_base(base)

    gen = LoadGenerator(transport, base, n_miners=8, poison_fraction=0.5)
    gen.publish_round()
    assert gen.report.published == 8
    assert gen.report.poisoned == 4

    from distributedtraining_tpu.data import image_batches

    def val_batches():
        return itertools.islice(
            image_batches(batch_size=16, n_classes=cfg.n_classes,
                          image_size=cfg.image_size, split="val"), 2)

    class _Chain:
        my_hotkey = "v"

        def sync(self):
            import types
            return types.SimpleNamespace(hotkeys=gen.hotkeys())

        def should_set_weights(self):
            return False

    validator = Validator(engine, transport, _Chain(),
                          eval_batches=val_batches, max_delta_abs=1e3)
    validator.bootstrap(jax.random.PRNGKey(0))
    scores = validator.validate_and_score()
    by_key = {s.hotkey: s for s in scores}
    assert len(by_key) == 8
    # every poisoned artifact is rejected with a reason, never scored
    rejected = [s for s in scores if s.reason != "ok"]
    assert len(rejected) == 4, [(s.hotkey, s.reason) for s in scores]
    reasons = {s.reason.split("(")[0] for s in rejected}
    assert reasons <= {"nonfinite", "shape_mismatch", "magnitude_exceeded",
                       "no_delta"}
    # benign artifacts all got evaluated
    assert sum(1 for s in scores if s.reason == "ok") == 4


# -- peer registry ----------------------------------------------------------

def test_registry_register_and_prune():
    r = reg.PeerRegistry(ttl=10.0)
    r.register("hk1", "host1:1234", now=100.0)
    r.register("hk2", "host2:1234", now=105.0)
    live = r.peers(now=108.0)
    assert {p["hotkey"] for p in live} == {"hk1", "hk2"}
    live = r.peers(now=112.0)   # hk1 is 12s old > ttl
    assert {p["hotkey"] for p in live} == {"hk2"}


def test_registry_bounded_memory():
    """A hostile client POSTing unlimited distinct hotkeys cannot grow the
    server without limit: past max_peers the oldest entries are evicted."""
    r = reg.PeerRegistry(ttl=1000.0, max_peers=8)
    for i in range(20):
        r.register(f"hk{i}", "a:1", now=100.0 + i)
    live = r.peers(now=120.0)
    assert len(live) <= 8
    # the newest registrations survive, the oldest were evicted
    assert {p["hotkey"] for p in live} == {f"hk{i}" for i in range(12, 20)}
    # refreshing an existing hotkey never evicts
    r.register("hk19", "a:2", now=121.0)
    assert len(r.peers(now=121.0)) <= 8


def test_registry_rejects_oversized_fields():
    srv, url = reg.serve(ttl=60.0)
    try:
        assert not reg.register_peer(url, "x" * 600, "10.0.0.1:5000")
        assert not reg.register_peer(url, "hkA", "y" * 600)
        assert reg.get_peers(url) == []
    finally:
        srv.shutdown()


def test_identity_save_resets_stale_tmp_permissions(tmp_path):
    """A stale world-readable tmp file must not leak the private key: save
    unlinks it and recreates 0600-from-birth (POSIX mode applies only at
    creation)."""
    import os
    path = str(tmp_path / "w.json")
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        f.write("stale")
    os.chmod(tmp, 0o644)
    ident = Identity.generate()
    ident.save(path)
    assert (os.stat(path).st_mode & 0o777) == 0o600
    assert Identity.load(path).hotkey == ident.hotkey


def test_registry_http_roundtrip():
    srv, url = reg.serve(ttl=60.0)
    try:
        assert reg.register_peer(url, "hkA", "10.0.0.1:5000")
        assert reg.register_peer(url, "hkB", "10.0.0.2:5000")
        peers = reg.get_peers(url)
        assert {p["hotkey"] for p in peers} == {"hkA", "hkB"}
        # stress-lite: the reference's bootstrap_stress hammers the pool
        for i in range(50):
            assert reg.register_peer(url, f"hk{i}", f"10.0.1.{i}:5000")
        assert len(reg.get_peers(url)) == 52
    finally:
        srv.shutdown()


def test_registry_rate_limiter_refuses_hammering_without_banning():
    """Too-fast re-registration is refused (chain-style hammering guard,
    btt_connector.py:454-480) but the registry NEVER permanently bans: the
    hotkey is an unauthenticated self-claim, so an attacker spoofing a
    victim's id must at worst rate-limit it, not lock it out forever."""
    t = [100.0]
    r = reg.PeerRegistry(ttl=60.0, rate_limit_seconds=5.0,
                         now_fn=lambda: t[0])
    assert r.register("hkA", "a:1")
    for _ in range(5):          # an attacker hammers the victim's hotkey
        t[0] += 1.0
        assert not r.register("hkA", "x:666")
    t[0] += 100.0
    # the real peer re-registers fine after the interval — no spoofed ban
    assert r.register("hkA", "a:1")
    assert r.register("hkB", "b:1")       # other callers unaffected


def test_registry_http_rate_limited_429():
    srv, url = reg.serve(ttl=60.0, rate_limit_seconds=30.0)
    try:
        assert reg.register_peer(url, "hkA", "10.0.0.1:5000")
        # immediate re-register is refused (HTTP 429 -> client False)
        assert not reg.register_peer(url, "hkA", "10.0.0.1:5000")
        # the first registration is still live
        assert {p["hotkey"] for p in reg.get_peers(url)} == {"hkA"}
    finally:
        srv.shutdown()


def test_trace_capture_bounded_window(tmp_path):
    """TraceCapture profiles exactly the post-warmup window and writes a
    TensorBoard-readable trace, then goes inert (jax.profiler, SURVEY §5)."""
    import jax
    import jax.numpy as jnp

    from distributedtraining_tpu.utils.metrics import TraceCapture

    d = str(tmp_path / "trace")
    cap = TraceCapture(d, steps=2, skip=1)
    f = jax.jit(lambda x: x * 2 + 1)
    for _ in range(6):
        f(jnp.ones((4,)))
        cap.tick()
    assert cap._done and not cap._active
    produced = [os.path.join(r, fn) for r, _, fns in os.walk(d) for fn in fns]
    assert produced, "no trace files written"
    cap.tick()  # inert after the window
    cap.close()


def test_trace_capture_close_mid_window(tmp_path):
    import jax
    import jax.numpy as jnp

    from distributedtraining_tpu.utils.metrics import TraceCapture

    cap = TraceCapture(str(tmp_path / "t2"), steps=100, skip=0)
    jax.jit(lambda x: x + 1)(jnp.ones(()))
    cap.tick()
    assert cap._active
    cap.close()
    assert cap._done and not cap._active


def _git(cwd, *args):
    import subprocess
    subprocess.run(["git", "-c", "user.email=t@t", "-c", "user.name=t",
                    *args], cwd=str(cwd), check=True, capture_output=True)


def test_autoupdater_hard_recovery_converges_dirty_tree(tmp_path):
    """A dirty AND diverged clone (the state that wedges `git pull
    --ff-only` forever) still converges to the published version via the
    reset-hard fallback — the re-clone behavior of run_miner.sh:229-268
    without the re-download (round-3 verdict #8)."""
    from distributedtraining_tpu.utils.auto_update import git_remote_version

    vf = "distributedtraining_tpu/__init__.py"
    origin = tmp_path / "origin"
    (origin / "distributedtraining_tpu").mkdir(parents=True)
    (origin / vf).write_text('__version__ = "1.0.0"\n')
    _git(origin, "init", "-q", "-b", "main")
    _git(origin, "add", "-A")
    _git(origin, "commit", "-qm", "v1")

    clone = tmp_path / "clone"
    _git(tmp_path, "clone", "-q", str(origin), str(clone))

    # diverge: local commit + dirty working tree
    (clone / "local.txt").write_text("local state\n")
    _git(clone, "add", "local.txt")
    _git(clone, "commit", "-qm", "local divergence")
    (clone / vf).write_text('__version__ = "0.0.0-dirty"\n')

    # publish v2 upstream
    (origin / vf).write_text('__version__ = "2.0.0"\n')
    _git(origin, "add", "-A")
    _git(origin, "commit", "-qm", "v2")

    calls = []
    upd = AutoUpdater(
        "1.0.0", lambda: git_remote_version(str(clone)),
        repo_dir=str(clone), restart=lambda: calls.append("restart"))
    assert upd.check() is True
    assert calls == ["restart"]
    assert (clone / vf).read_text() == '__version__ = "2.0.0"\n'

    # with the fallback disabled the same state blocks the restart
    (clone / vf).write_text('__version__ = "0.0.0-dirty"\n')
    _git(clone, "commit", "-qam", "diverge again")
    (origin / vf).write_text('__version__ = "3.0.0"\n')
    _git(origin, "add", "-A")
    _git(origin, "commit", "-qm", "v3")
    upd2 = AutoUpdater(
        "2.0.0", lambda: git_remote_version(str(clone)),
        repo_dir=str(clone), hard_recovery_ref=None,
        restart=lambda: calls.append("restart2"))
    assert upd2.check() is False
    assert calls == ["restart"]


def test_autoupdater_transient_failure_never_hard_resets(tmp_path):
    """A failing update command on a CLEAN, non-diverged tree is treated
    as transient — no `git reset --hard`, no restart, retry next poll —
    so a network blip can never silently discard operator state
    (round-4 advisor: the fallback used to fire on ANY failure)."""
    from distributedtraining_tpu.utils.auto_update import git_remote_version

    vf = "distributedtraining_tpu/__init__.py"
    origin = tmp_path / "origin"
    (origin / "distributedtraining_tpu").mkdir(parents=True)
    (origin / vf).write_text('__version__ = "1.0.0"\n')
    _git(origin, "init", "-q", "-b", "main")
    _git(origin, "add", "-A")
    _git(origin, "commit", "-qm", "v1")
    clone = tmp_path / "clone"
    _git(tmp_path, "clone", "-q", str(origin), str(clone))
    (origin / vf).write_text('__version__ = "2.0.0"\n')
    _git(origin, "add", "-A")
    _git(origin, "commit", "-qm", "v2")

    calls = []
    upd = AutoUpdater(
        "1.0.0", lambda: git_remote_version(str(clone)),
        update_cmd=("false",),  # simulated mid-pull failure
        repo_dir=str(clone), restart=lambda: calls.append("restart"))
    assert upd.check() is False
    assert calls == []
    # the clean clone is untouched (still at v1, history intact)
    assert (clone / vf).read_text() == '__version__ = "1.0.0"\n'

    # a SECOND consecutive clean failure with a reachable remote is
    # persistent (detached HEAD / missing upstream look exactly like
    # this) and recovers hard — lossless here, since clean+not-diverged
    # means the reset is a fast-forward
    assert upd.check() is True
    assert calls == ["restart"]
    assert (clone / vf).read_text() == '__version__ = "2.0.0"\n'

    # and on a DIRTY tree the first failing poll already recovers hard:
    # the fallback still exists for the state it was built for
    (origin / vf).write_text('__version__ = "3.0.0"\n')
    _git(origin, "add", "-A")
    _git(origin, "commit", "-qm", "v3")
    (clone / vf).write_text('__version__ = "0.0.0-dirty"\n')
    upd_dirty = AutoUpdater(
        "2.0.0", lambda: git_remote_version(str(clone)),
        update_cmd=("false",),
        repo_dir=str(clone), restart=lambda: calls.append("restart2"))
    assert upd_dirty.check() is True
    assert calls == ["restart", "restart2"]
    assert (clone / vf).read_text() == '__version__ = "3.0.0"\n'


def test_ensure_virtual_devices_env(monkeypatch):
    """ensure_virtual_devices raises an existing smaller count in place
    (appending a duplicate flag would rely on unspecified last-wins
    parsing) and leaves larger counts alone."""
    from distributedtraining_tpu.utils.platform import ensure_virtual_devices

    flag = "--xla_force_host_platform_device_count"
    monkeypatch.delenv("XLA_FLAGS", raising=False)
    ensure_virtual_devices(8)
    assert os.environ["XLA_FLAGS"] == f"{flag}=8"
    ensure_virtual_devices(64)
    assert os.environ["XLA_FLAGS"] == f"{flag}=64"
    ensure_virtual_devices(32)  # smaller: no change
    assert os.environ["XLA_FLAGS"] == f"{flag}=64"
    monkeypatch.setenv("XLA_FLAGS", f"--xla_cpu_foo=1 {flag}=2")
    ensure_virtual_devices(16)
    assert os.environ["XLA_FLAGS"] == f"--xla_cpu_foo=1 {flag}=16"
