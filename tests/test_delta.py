"""Delta algebra: round-trip, screening, stacking, merge gradients."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributedtraining_tpu import delta


def small_tree(seed=0, scale=1.0):
    k = jax.random.PRNGKey(seed)
    k1, k2, k3 = jax.random.split(k, 3)
    return {
        "layer": {"kernel": jax.random.normal(k1, (4, 8)) * scale,
                  "bias": jax.random.normal(k2, (8,)) * scale},
        "head": jax.random.normal(k3, (8, 2)) * scale,
    }


def test_delta_roundtrip():
    base = small_tree(0)
    trained = small_tree(1)
    d = delta.compute_delta(trained, base)
    restored = delta.apply_delta(base, d)
    for a, b in zip(jax.tree_util.tree_leaves(restored),
                    jax.tree_util.tree_leaves(trained)):
        np.testing.assert_allclose(a, b, rtol=1e-5, atol=1e-6)


def test_nan_screen():
    t = small_tree(0)
    assert not delta.has_nonfinite(t)
    t["head"] = t["head"].at[0, 0].set(jnp.nan)
    assert delta.has_nonfinite(t)
    t["head"] = t["head"].at[0, 0].set(jnp.inf)
    assert delta.has_nonfinite(t)


def test_shape_screen():
    base = small_tree(0)
    good = small_tree(1)
    assert delta.shapes_match(good, base)
    bad = dict(good)
    bad["head"] = jnp.zeros((8, 3))
    assert not delta.shapes_match(bad, base)
    missing = {"layer": good["layer"]}
    assert not delta.shapes_match(missing, base)


def test_dtype_screen_catches_f64_wire_payload():
    """jnp.asarray would downcast f64->f32 under x64-disabled JAX and make the
    dtype check vacuous; screen must compare numpy-side (live-probe regression)."""
    base = small_tree(0)
    d64 = jax.tree_util.tree_map(lambda x: np.zeros(x.shape, np.float64), base)
    ok, reason = delta.screen_delta(d64, base)
    assert not ok and reason == "shape_mismatch"


def test_screen_delta_magnitude():
    base = small_tree(0)
    d = delta.compute_delta(small_tree(1), base)
    ok, reason = delta.screen_delta(d, base, max_abs=1e-6)
    assert not ok and reason.startswith("magnitude_exceeded")
    ok, reason = delta.screen_delta(d, base, max_abs=1e6)
    assert ok


def test_stack_and_weighted_merge():
    base = small_tree(0)
    deltas = [delta.compute_delta(small_tree(i), base) for i in range(1, 4)]
    stacked = delta.stack_deltas(deltas)
    assert jax.tree_util.tree_leaves(stacked)[0].shape[0] == 3

    w = jnp.array([1.0, 0.0, 0.0])
    merged = delta.weighted_merge(base, stacked, w)
    expect = delta.apply_delta(base, deltas[0])
    for a, b in zip(jax.tree_util.tree_leaves(merged),
                    jax.tree_util.tree_leaves(expect)):
        np.testing.assert_allclose(a, b, rtol=1e-5)

    # uniform weights = plain average
    w = jnp.full((3,), 1.0 / 3)
    merged = delta.weighted_merge(base, stacked, w)
    mean_delta = jax.tree_util.tree_map(
        lambda *xs: sum(xs) / 3, *deltas)
    expect = delta.apply_delta(base, mean_delta)
    for a, b in zip(jax.tree_util.tree_leaves(merged),
                    jax.tree_util.tree_leaves(expect)):
        np.testing.assert_allclose(a, b, rtol=1e-5)


def test_flat_merge_matches_leafwise():
    """weighted_merge_flat is the single-kernel spelling of weighted_merge:
    identical values AND identical meta-gradient w.r.t. the weights."""
    base = small_tree(0)
    deltas = [delta.compute_delta(small_tree(i), base) for i in range(1, 5)]
    stacked = delta.stack_deltas(deltas)
    w = jnp.asarray([0.4, 0.3, 0.2, 0.1])

    a = delta.weighted_merge(base, stacked, w)
    b = delta.weighted_merge_flat(base, stacked, w)
    assert jax.tree_util.tree_structure(a) == jax.tree_util.tree_structure(b)
    for x, y in zip(jax.tree_util.tree_leaves(a),
                    jax.tree_util.tree_leaves(b)):
        assert x.shape == y.shape and x.dtype == y.dtype
        np.testing.assert_allclose(np.asarray(x), np.asarray(y),
                                   rtol=1e-6, atol=1e-6)

    def probe(merge_fn, w):
        merged = merge_fn(base, stacked, w)
        return sum(jnp.sum(l * l) for l in jax.tree_util.tree_leaves(merged))

    g1 = jax.grad(lambda w: probe(delta.weighted_merge, w))(w)
    g2 = jax.grad(lambda w: probe(delta.weighted_merge_flat, w))(w)
    np.testing.assert_allclose(np.asarray(g1), np.asarray(g2),
                               rtol=1e-5, atol=1e-6)


def test_merge_weight_gradient_matches_finite_difference():
    """jax.grad through the merge must equal numeric meta-gradient — this is
    the correctness core of the parameterized averager."""
    base = small_tree(0)
    deltas = [delta.compute_delta(small_tree(i), base) for i in range(1, 4)]
    stacked = delta.stack_deltas(deltas)

    def loss(w):
        merged = delta.weighted_merge(base, stacked, w)
        return sum(jnp.sum(l * l) for l in jax.tree_util.tree_leaves(merged))

    w0 = jnp.array([0.3, 0.5, 0.2])
    g = jax.grad(loss)(w0)
    eps = 1e-3
    for i in range(3):
        wp = w0.at[i].add(eps)
        wm = w0.at[i].add(-eps)
        fd = (loss(wp) - loss(wm)) / (2 * eps)
        np.testing.assert_allclose(g[i], fd, rtol=1e-2)


def test_per_tensor_merge():
    base = small_tree(0)
    deltas = [delta.compute_delta(small_tree(i), base) for i in range(1, 3)]
    stacked = delta.stack_deltas(deltas)
    w = delta.init_merge_weights(base, 2, per_tensor=True)
    merged = delta.per_tensor_weighted_merge(base, stacked, w)
    mean_delta = jax.tree_util.tree_map(lambda *xs: sum(xs) / 2, *deltas)
    expect = delta.apply_delta(base, mean_delta)
    for a, b in zip(jax.tree_util.tree_leaves(merged),
                    jax.tree_util.tree_leaves(expect)):
        np.testing.assert_allclose(a, b, rtol=1e-5)


def test_bf16_wire_delta_screens_and_merges():
    """compute_delta(wire_dtype='bfloat16'): half-size artifact accepted by
    the default screen (f64/int substitutions stay rejected), applied with
    f32 promotion, and merged with f32 accumulation."""
    import jax
    import jax.numpy as jnp

    from distributedtraining_tpu import delta

    base = {"a": jnp.ones((8, 4), jnp.float32),
            "b": jnp.zeros((3,), jnp.float32)}
    trained = jax.tree_util.tree_map(lambda x: x + 0.01, base)
    d16 = delta.compute_delta(trained, base, wire_dtype="bfloat16")
    assert all(l.dtype == jnp.bfloat16
               for l in jax.tree_util.tree_leaves(d16))

    ok, reason = delta.screen_delta(d16, base)
    assert ok, reason
    # a f64 submission must still be rejected (promotion attack)
    d64 = jax.tree_util.tree_map(lambda x: np.asarray(x, np.float64), d16)
    ok, reason = delta.screen_delta(d64, base)
    assert not ok and reason == "shape_mismatch"

    applied = delta.apply_delta(base, d16)
    assert all(l.dtype == jnp.float32
               for l in jax.tree_util.tree_leaves(applied))

    # merge of an all-bf16 stack: output f32, values within bf16 rounding
    # of the f32 merge (accumulation happens in f32 per merge_leaf)
    d32 = delta.compute_delta(trained, base)
    w = jnp.asarray([0.7, 0.3])
    m16 = delta.weighted_merge(base, delta.stack_deltas([d16, d16]), w)
    m32 = delta.weighted_merge(base, delta.stack_deltas([d32, d32]), w)
    for a, b in zip(jax.tree_util.tree_leaves(m16),
                    jax.tree_util.tree_leaves(m32)):
        assert a.dtype == jnp.float32
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-2)


def test_chunked_weighted_merge_matches_stacked():
    """Bounded-memory merge == stacked merge, including a chunk that does
    not divide M (zero-padding path) and bf16 wire deltas in the list."""
    import jax
    import jax.numpy as jnp

    from distributedtraining_tpu import delta

    base = {"a": jnp.ones((16, 8), jnp.float32),
            "b": {"c": jnp.full((5,), 2.0, jnp.float32)}}
    rng = np.random.default_rng(0)
    deltas = []
    for i in range(5):
        d = jax.tree_util.tree_map(
            lambda x: jnp.asarray(rng.normal(0, 0.01, x.shape), x.dtype),
            base)
        if i == 3:  # one bf16 wire submission in the mix
            d = jax.tree_util.tree_map(
                lambda x: x.astype(jnp.bfloat16), d)
        deltas.append(d)
    w = jnp.asarray([0.4, 0.1, 0.2, 0.2, 0.1])

    want = delta.weighted_merge(base, delta.stack_deltas(deltas), w)
    for chunk in (1, 2, 5, 8):
        got = delta.chunked_weighted_merge(base, deltas, w, chunk=chunk)
        for a, b in zip(jax.tree_util.tree_leaves(got),
                        jax.tree_util.tree_leaves(want)):
            assert a.dtype == b.dtype
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-5, atol=1e-7)
    with pytest.raises(ValueError):
        delta.chunked_weighted_merge(base, [], w)
    with pytest.raises(ValueError):
        delta.chunked_weighted_merge(base, deltas, w[:3])


def test_int8_wire_quantization_roundtrip_and_screens():
    """Per-tensor int8 wire format: bounded roundtrip error, hostile
    scales die in the existing screens after dequantization, non-float
    trees are refused loudly (no silent template mismatch)."""
    import jax
    import jax.numpy as jnp

    from distributedtraining_tpu import delta

    rng = np.random.default_rng(0)
    base = {"a": jnp.zeros((64, 32), jnp.float32),
            "b": jnp.zeros((17,), jnp.float32)}
    d = jax.tree_util.tree_map(
        lambda x: jnp.asarray(rng.normal(0, 0.01, x.shape), x.dtype), base)

    q = delta.quantize_delta(d)
    deq = delta.dequantize_delta(q)
    for a, b in zip(jax.tree_util.tree_leaves(deq),
                    jax.tree_util.tree_leaves(d)):
        err = float(jnp.abs(a - b).max())
        bound = float(jnp.abs(b).max()) / 127.0  # one quantization step
        assert err <= bound + 1e-9, (err, bound)
    ok, reason = delta.screen_delta(deq, base)
    assert ok, reason

    # hostile scales: inf/nan -> nonfinite screen; huge -> magnitude screen
    evil = jax.tree_util.tree_map(
        lambda l: {"q": l["q"], "scale": jnp.asarray(float("inf"))},
        q, is_leaf=delta._is_qleaf)
    ok, reason = delta.screen_delta(delta.dequantize_delta(evil), base)
    assert not ok and reason == "nonfinite"
    big = jax.tree_util.tree_map(
        lambda l: {"q": l["q"], "scale": jnp.asarray(1e30, jnp.float32)},
        q, is_leaf=delta._is_qleaf)
    ok, reason = delta.screen_delta(delta.dequantize_delta(big), base,
                                    max_abs=1e3)
    assert not ok and reason.startswith("magnitude_exceeded")

    # non-float leaves refuse loudly (the wire format is all-float)
    with pytest.raises(ValueError, match="non-float"):
        delta.quantize_delta({"a": jnp.zeros((4,), jnp.int32)})


def test_int8_hostile_f64_q_rejected():
    """A structurally matching tree whose "q" leaves are f64 must NOT pass
    the dtype-pinned quant load (8x memory amplification otherwise)."""
    import jax
    import jax.numpy as jnp

    from distributedtraining_tpu import delta, serialization as ser

    base = {"a": np.zeros((8, 4), np.float32)}
    tmpl = delta.quantized_template(base)
    legit = delta.quantize_delta({"a": jnp.full((8, 4), 0.01)})
    ser.validated_load(ser.to_msgpack(legit), tmpl, check_dtypes=True)
    hostile = {"a": {"q": np.ones((8, 4), np.float64),
                     "scale": np.float32(1.0)}}
    with pytest.raises(ser.PayloadError):
        ser.validated_load(ser.to_msgpack(hostile), tmpl, check_dtypes=True)


# -- sparse8 wire format -----------------------------------------------------

def _sparse_case():
    rng = np.random.default_rng(3)
    tree = {"big": jnp.asarray(rng.normal(size=(9000,)) * 0.01, jnp.float32),
            "ln": {"b": jnp.asarray(rng.normal(size=(32,)), jnp.float32)}}
    template = jax.tree_util.tree_map(
        lambda x: np.zeros(x.shape, np.float32), tree)
    return tree, template


def test_sparse8_roundtrip_topk_and_dense_small_leaves():
    from distributedtraining_tpu import serialization as ser

    tree, template = _sparse_case()
    sp = delta.sparsify_delta(tree, density=1.0 / 8)
    back = delta.sparse_delta_from_bytes(ser.to_msgpack(sp), template)
    assert back is not None
    big = np.asarray(tree["big"])
    got = np.asarray(back["big"])
    k = delta.sparse_k(big.size, 1.0 / 8)
    nz = np.nonzero(got)[0]
    top = set(np.argsort(-np.abs(big))[:k].tolist())
    assert set(nz.tolist()).issubset(top)
    # kept coordinates agree to one int8 step of the tensor max
    step = np.abs(big).max() / 127
    assert np.abs(got[nz] - big[nz]).max() <= step + 1e-7
    # small leaf ships dense: exact to its own int8 step
    ln, gln = np.asarray(tree["ln"]["b"]), np.asarray(back["ln"]["b"])
    assert np.abs(gln - ln).max() <= np.abs(ln).max() / 127 + 1e-7


def test_sparse8_jitted_matches_eager():
    tree, template = _sparse_case()
    from distributedtraining_tpu import serialization as ser
    eager = delta.sparsify_delta(tree, density=1.0 / 8)
    jitted = jax.jit(delta.sparsify_delta,
                     static_argnames=("density",))(tree, density=1.0 / 8)
    a = delta.sparse_delta_from_bytes(ser.to_msgpack(eager), template)
    b = delta.sparse_delta_from_bytes(ser.to_msgpack(jitted), template)
    for x, y in zip(jax.tree_util.tree_leaves(a),
                    jax.tree_util.tree_leaves(b)):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))


def test_sparse8_hostile_payloads_rejected():
    """Everything the publisher controls is validated: marker, paths,
    dtypes, k <= n, index bounds; the dense/int8 template loaders must
    also refuse the sparse artifact."""
    from distributedtraining_tpu import serialization as ser

    tree, template = _sparse_case()
    good = delta.sparsify_delta(tree)
    data = ser.to_msgpack(good)

    def mutate(fn):
        import copy
        t = copy.deepcopy(jax.device_get(good))
        fn(t)
        return delta.sparse_delta_from_bytes(ser.to_msgpack(t), template)

    assert delta.sparse_delta_from_bytes(data, template) is not None
    assert delta.sparse_delta_from_bytes(b"garbage", template) is None
    # out-of-bounds index
    assert mutate(lambda t: t["leaves"]["big"].__setitem__(
        "idx", np.asarray([10 ** 8], np.int32))) is None
    # wrong q dtype (would parse at inflated bytes)
    assert mutate(lambda t: t["leaves"]["big"].__setitem__(
        "q", t["leaves"]["big"]["q"].astype(np.float64))) is None
    # extra top-level key
    assert mutate(lambda t: t.__setitem__("extra", np.zeros(1))) is None
    # missing leaf
    assert mutate(lambda t: t["leaves"].pop("ln")) is None
    # non-finite scale
    assert mutate(lambda t: t["leaves"]["big"].__setitem__(
        "scale", np.float32(np.inf))) is None
    # k > n
    assert mutate(lambda t: (
        t["leaves"]["ln"]["b"].__setitem__(
            "idx", np.zeros(64, np.int32)),
        t["leaves"]["ln"]["b"].__setitem__(
            "q", np.zeros(64, np.int8)))) is None
    # dense and int8 loaders refuse the sparse artifact
    import pytest as _pytest
    with _pytest.raises(ser.PayloadError):
        ser.validated_load(data, template)
    with _pytest.raises(ser.PayloadError):
        ser.validated_load(data, delta.quantized_template(template),
                           check_dtypes=True)


def test_sparse8_hostile_marker_types_return_none():
    """The format marker is attacker bytes: string/array/float/NaN markers
    must read as not-sparse8 (None), never raise out of the decoder — a
    raised TypeError used to escape the fetch try-chain and abort the
    whole validator round (round-4 advisor, high)."""
    from distributedtraining_tpu import serialization as ser

    _, template = _sparse_case()
    for marker in ("1", b"1", np.asarray([1, 1], np.int32),
                   np.float32(np.nan), np.float32(1.0), None, [1], {"x": 1}):
        tree = {"__delta_format__": marker, "leaves": {}}
        try:
            data = ser.to_msgpack(tree)
        except Exception:
            continue  # unencodable marker can't arrive over the wire
        assert delta.sparse_delta_from_bytes(data, template) is None, marker
    # and densify itself obeys the return-None contract on direct calls
    assert delta.densify_sparse_delta(
        {"__delta_format__": "sparse8", "leaves": {}}, template) is None
