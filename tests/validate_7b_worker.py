"""Worker: shape-validate the llama2-7b preset on a v4-32-shaped virtual
mesh (32 CPU devices, dp=2 x fsdp=8 x tp=2). Run via subprocess by
tests/test_models.py — not a pytest file itself.

Everything is shape-level (jax.eval_shape): no 7B weights are materialized.
Catches exactly the class of first-contact failures a preset that has only
ever run at tiny scale hides — non-divisible sharded axes (GQA kv heads vs
tp), logical-rule gaps, LoRA target selection at full width, optimizer-state
sharding resolution. Prints "OK <n_params>" on success.
"""

import os
import sys

os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=32"

import jax

jax.config.update("jax_platforms", "cpu")

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402


def main() -> int:
    from distributedtraining_tpu.engine import LoRAEngine, TrainEngine
    from distributedtraining_tpu.models import llama
    from distributedtraining_tpu.models.lora import LoRAConfig
    from distributedtraining_tpu.parallel import MeshConfig, make_mesh
    from distributedtraining_tpu.parallel.sharding import mesh_shardings

    assert len(jax.devices()) == 32, jax.devices()
    model, cfg = llama.make_model("llama2-7b")
    mesh = make_mesh(MeshConfig(dp=2, fsdp=8, tp=2))
    seq = 4096

    # 1. every param leaf gets a sharding whose sharded axes divide evenly
    #    (shard_shape raises otherwise — e.g. GQA kv heads not divisible
    #    by tp)
    shardings = mesh_shardings(model, mesh, seq_len=seq)
    abstract = jax.eval_shape(
        lambda: model.init_params(jax.random.PRNGKey(0)))
    leaves = jax.tree_util.tree_leaves(abstract)
    slvs = jax.tree_util.tree_leaves(shardings)
    assert len(leaves) == len(slvs)
    n_params = 0
    n_sharded = 0
    for leaf, s in zip(leaves, slvs):
        s.shard_shape(leaf.shape)  # raises on non-divisible
        n_params += int(np.prod(leaf.shape))
        if any(ax is not None for ax in s.spec):
            n_sharded += 1
    assert 6.5e9 < n_params < 7.5e9, n_params
    assert n_sharded > len(leaves) * 0.8, (n_sharded, len(leaves))

    # 2. full-param engine: state skeleton + one traced train step, BOTH
    #    loss paths (the default [B,T,V]-logits loss and the fused
    #    no-logits loss config 4/5 would actually run) — eval_shape is
    #    allocation-free, so validating both costs nothing
    # global batch must divide dp*fsdp=16 (the shard_map fused-CE spelling
    # enforces what place_batch enforces at runtime)
    batch_abs = {"input_ids": jax.ShapeDtypeStruct((16, seq), np.int32)}
    for fused in (False, True):
        engine = TrainEngine(model, mesh=mesh, seq_len=seq,
                             fused_loss=fused)
        state_abs = engine.abstract_state()
        out_state, metrics = jax.eval_shape(engine.train_step, state_abs,
                                            batch_abs)
        assert metrics["loss"].shape == (), fused

    # 3. LoRA engine (config 4): sharded frozen base, replicated adapters,
    #    adapter-only step traces end to end
    lcfg = LoRAConfig(rank=8)
    leng = LoRAEngine(model, lcfg, mesh=mesh, seq_len=seq)
    lstate_abs = leng.abstract_state()
    base_abs = leng.abstract_params()
    n_adapter = sum(
        int(np.prod(l.shape))
        for l in jax.tree_util.tree_leaves(lstate_abs.params))
    assert n_adapter < n_params / 100, (n_adapter, n_params)
    lout, lmetrics = jax.eval_shape(leng.train_step, lstate_abs, base_abs,
                                    batch_abs)
    assert lmetrics["loss"].shape == ()

    # 4. scan_blocks layout (what a real 32-layer deployment runs for
    #    compile time): shardings resolve with the leading "layers" axis
    #    replicated, step traces, param count unchanged
    import dataclasses

    scan_model, scan_cfg = llama.make_model(
        dataclasses.replace(cfg, scan_blocks=True))
    scan_shardings = mesh_shardings(scan_model, mesh, seq_len=seq)
    scan_abs = jax.eval_shape(
        lambda: scan_model.init_params(jax.random.PRNGKey(0)))
    n_scan = 0
    for leaf, s in zip(jax.tree_util.tree_leaves(scan_abs),
                       jax.tree_util.tree_leaves(scan_shardings)):
        s.shard_shape(leaf.shape)
        n_scan += int(np.prod(leaf.shape))
    assert n_scan == n_params, (n_scan, n_params)
    scan_engine = TrainEngine(scan_model, mesh=mesh, seq_len=seq)
    _, scan_metrics = jax.eval_shape(
        scan_engine.train_step, scan_engine.abstract_state(), batch_abs)
    assert scan_metrics["loss"].shape == ()

    print(f"OK {n_params}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
