"""Locally-trained byte-level BPE (data/bpe.py): the real-vocab tokenizer.

Pins the properties the protocol depends on: deterministic training,
lossless save/load, pad contract (id 0), subword coverage of unseen
words, and the batch pipeline running end to end on BPE ids."""

import numpy as np
import pytest

tokenizers = pytest.importorskip("tokenizers")

from distributedtraining_tpu.data import batch_iterator  # noqa: E402
from distributedtraining_tpu.data.bpe import BPETokenizer  # noqa: E402

DOCS = [
    "The quick brown fox jumps over the lazy dog.",
    "Distributed training merges weight deltas from many miners.",
    "A validator scores each delta against the shared base model.",
    "Byte level BPE covers any unicode input via its 256-byte alphabet.",
] * 16


def _tok(vocab=600):
    return BPETokenizer.train(vocab_size=vocab, docs=DOCS)


def test_train_encode_decode_roundtrip():
    tok = _tok()
    text = "The validator scores weight deltas."
    ids = tok.encode(text)
    assert ids and all(0 < i < tok.vocab_size for i in ids)
    assert tok.decode(ids) == text


def test_unseen_words_still_encode():
    """Byte-level alphabet: any input tokenizes (no UNK holes)."""
    tok = _tok()
    ids = tok.encode("zxqvj kakorrhaphiophobia 日本語")
    assert ids
    assert tok.decode(ids).startswith("zxqvj")


def test_pad_id_reserved():
    tok = _tok()
    assert tok.pad_id == 0
    assert 0 not in tok.encode("some ordinary text")


def test_deterministic_and_persistent(tmp_path):
    p = str(tmp_path / "tok.json")
    a = BPETokenizer.train(vocab_size=600, docs=DOCS, save_path=p)
    b = BPETokenizer.load(p)
    c = BPETokenizer.train(vocab_size=600, docs=DOCS)
    text = "weight deltas from many miners"
    assert a.encode(text) == b.encode(text) == c.encode(text)
    # train_or_load prefers the saved artifact
    d = BPETokenizer.train_or_load(p, vocab_size=600)
    assert d.encode(text) == a.encode(text)


def test_batch_pipeline_on_bpe_ids():
    tok = _tok()
    batches = list(batch_iterator(DOCS, tok, batch_size=2, seq_len=16))
    assert batches
    ids = np.concatenate([b["input_ids"].ravel() for b in batches])
    assert ids.max() < tok.vocab_size and ids.min() >= 0


def test_corpus_training_reaches_32k():
    """The machine's own text supports a full 32k vocab — the property
    the big-vocab E2E (E2E_r04_bpe.json) relies on."""
    from distributedtraining_tpu.data.bpe import corpus_files
    files = corpus_files()
    assert len(files) > 50
    tok = BPETokenizer.train(vocab_size=32000, files=files)
    assert tok.vocab_size == 32000
