"""System test: a real multi-process federated round through the CLIs.

BASELINE.json config 3 at test scale: several miner OS processes train
concurrently against one shared LocalFS work dir, then a validator process
scores them and an averager process merges — all through the actual
``neurons/*.py`` entry points, not in-process loops. This is the test the
reference never had for its de-facto multi-node story (Local* twins,
SURVEY.md §4.1).
"""

import json
import os
import subprocess
import sys

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _run(role, *args):
    env = dict(os.environ)
    env["DT_FORCE_PLATFORM"] = "cpu"  # subprocesses must not grab the TPU
    env.pop("XLA_FLAGS", None)        # no virtual-device forcing needed
    return subprocess.Popen(
        [sys.executable, os.path.join(REPO, "neurons", f"{role}.py"), *args],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True)


COMMON = ["--backend", "local", "--model", "tiny", "--dataset", "synthetic",
          "--eval-batches", "2"]


def test_three_miners_validator_averager(tmp_path):
    work = str(tmp_path / "run")
    miners = [
        _run("miner", "--work-dir", work, *COMMON,
             "--hotkey", f"hotkey_{i}", "--max-steps", "25",
             "--send-interval", "1e9",        # flush publishes at exit
             "--heartbeat-interval", "5",     # fleet health plane on
             "--checkpoint-interval", "0")
        for i in range(3)
    ]
    for p in miners:
        out, _ = p.communicate(timeout=420)
        assert p.returncode == 0, out[-2000:]
        assert "miner done: steps=25" in out, out[-2000:]

    listing = os.listdir(os.path.join(work, "artifacts", "deltas"))
    deltas = [f for f in listing if f.endswith(".msgpack")]
    assert len(deltas) == 3, listing
    # every artifact ships its meta rider (base revision + the delta_id
    # correlation id, utils/obs.py)...
    riders = [f for f in listing if f.endswith(".meta.json")
              and not f.startswith("__hb__")]
    assert len(riders) == 3, listing
    # ...and every miner heartbeats under the reserved artifact id
    # (transport/base.heartbeat_id — the fleet health plane's channel)
    beats = [f for f in listing if f.startswith("__hb__.miner.")]
    assert len(beats) == 3, listing

    v = _run("validator", "--work-dir", work, *COMMON,
             "--hotkey", "hotkey_91", "--rounds", "1")
    out, _ = v.communicate(timeout=420)
    assert v.returncode == 0, out[-2000:]

    meta = json.load(open(os.path.join(work, "chain", "metagraph.json")))
    emitted = meta["ema_scores"]["hotkey_91"]
    positives = [h for h, s in emitted.items() if s > 0]
    assert set(positives) >= {"hotkey_0", "hotkey_1", "hotkey_2"}, positives

    avg_metrics = os.path.join(work, "averager_metrics.jsonl")
    a = _run("averager", "--work-dir", work, *COMMON,
             "--hotkey", "hotkey_95", "--rounds", "1",
             "--heartbeat-interval", "5",     # runs the FleetMonitor too
             "--metrics-path", avg_metrics,
             "--strategy", "weighted")
    out, _ = a.communicate(timeout=420)
    assert a.returncode == 0, out[-2000:]
    assert "accepted=3" in out, out[-2000:]
    assert os.path.exists(os.path.join(work, "artifacts", "base",
                                       "averaged_model.msgpack"))
    # merged loss is reported finite and below the tiny model's ~6.25 init
    line = [ln for ln in out.splitlines() if "averager done" in ln][-1]
    loss = float(line.rsplit("loss=", 1)[1])
    assert np.isfinite(loss) and loss < 6.2, line

    # the averager's FleetMonitor ledger (via scripts/fleet_report.py)
    # matches its own merge decisions exactly: 3 miners, each 1 published
    # + 1 accepted, heartbeats observed from all three
    sys.path.insert(0, os.path.join(REPO, "scripts"))
    import fleet_report
    rep = fleet_report.build_report([avg_metrics])
    for i in range(3):
        node = rep["nodes"][f"miner/hotkey_{i}"]
        assert node["published"] == 1 and node["accepted"] == 1, node
        assert node["declined"] == 0 and node["beats"] >= 1, node
        assert node["pushes"] >= 1, node     # from the heartbeat body
    assert sum(n.get("accepted", 0) for n in rep["nodes"].values()) == 3
