"""System test: a real multi-process federated round through the CLIs.

BASELINE.json config 3 at test scale: several miner OS processes train
concurrently against one shared LocalFS work dir, then a validator process
scores them and an averager process merges — all through the actual
``neurons/*.py`` entry points, not in-process loops. This is the test the
reference never had for its de-facto multi-node story (Local* twins,
SURVEY.md §4.1).
"""

import json
import os
import subprocess
import sys

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _run(role, *args):
    env = dict(os.environ)
    env["DT_FORCE_PLATFORM"] = "cpu"  # subprocesses must not grab the TPU
    env.pop("XLA_FLAGS", None)        # no virtual-device forcing needed
    return subprocess.Popen(
        [sys.executable, os.path.join(REPO, "neurons", f"{role}.py"), *args],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True)


COMMON = ["--backend", "local", "--model", "tiny", "--dataset", "synthetic",
          "--eval-batches", "2"]


def test_three_miners_validator_averager(tmp_path):
    work = str(tmp_path / "run")
    miners = [
        _run("miner", "--work-dir", work, *COMMON,
             "--hotkey", f"hotkey_{i}", "--max-steps", "25",
             "--send-interval", "1e9",        # flush publishes at exit
             "--checkpoint-interval", "0")
        for i in range(3)
    ]
    for p in miners:
        out, _ = p.communicate(timeout=420)
        assert p.returncode == 0, out[-2000:]
        assert "miner done: steps=25" in out, out[-2000:]

    listing = os.listdir(os.path.join(work, "artifacts", "deltas"))
    deltas = [f for f in listing if f.endswith(".msgpack")]
    assert len(deltas) == 3, listing
    # every artifact ships its meta rider (base revision + the delta_id
    # correlation id, utils/obs.py)
    riders = [f for f in listing if f.endswith(".meta.json")]
    assert len(riders) == 3, listing

    v = _run("validator", "--work-dir", work, *COMMON,
             "--hotkey", "hotkey_91", "--rounds", "1")
    out, _ = v.communicate(timeout=420)
    assert v.returncode == 0, out[-2000:]

    meta = json.load(open(os.path.join(work, "chain", "metagraph.json")))
    emitted = meta["ema_scores"]["hotkey_91"]
    positives = [h for h, s in emitted.items() if s > 0]
    assert set(positives) >= {"hotkey_0", "hotkey_1", "hotkey_2"}, positives

    a = _run("averager", "--work-dir", work, *COMMON,
             "--hotkey", "hotkey_95", "--rounds", "1",
             "--strategy", "weighted")
    out, _ = a.communicate(timeout=420)
    assert a.returncode == 0, out[-2000:]
    assert "accepted=3" in out, out[-2000:]
    assert os.path.exists(os.path.join(work, "artifacts", "base",
                                       "averaged_model.msgpack"))
    # merged loss is reported finite and below the tiny model's ~6.25 init
    line = [ln for ln in out.splitlines() if "averager done" in ln][-1]
    loss = float(line.rsplit("loss=", 1)[1])
    assert np.isfinite(loss) and loss < 6.2, line
