"""End-to-end offline round through the role entry points (CLI surface).

The reference is "tested" by running its Local* twins as a full
miner → validator → averager round on one box (SURVEY.md §4.1); this test is
that round, driven through neurons/{miner,validator,averager}.main with the
LocalFS transport + LocalJSON chain in a tmp dir.
"""

import json
import os
import sys

import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from neurons import averager, miner, validator  # noqa: E402


@pytest.fixture(autouse=True)
def _clean_flight():
    """build() now configures the flight recorder (utils/flight.py);
    the role mains shut it down on exit, but the tests below that call
    common.build() DIRECTLY (no main, no finally) must not leak it into
    the module guard."""
    yield
    from distributedtraining_tpu.utils import flight
    flight.reset()


def _common(tmp_path, hotkey, extra=()):
    return [
        "--backend", "local", "--work-dir", str(tmp_path),
        "--model", "tiny", "--dataset", "synthetic",
        "--hotkey", hotkey, "--dp", "1",
        "--batch-size", "4", "--seq-len", "32", "--eval-seq-len", "32",
        "--eval-batches", "2",
        *extra,
    ]


def test_full_offline_round(tmp_path):
    # -- miner trains and publishes a delta --------------------------------
    rc = miner.main(_common(
        tmp_path, "hotkey_0",
        ["--max-steps", "30", "--send-interval", "1e9",
         "--metrics-path", str(tmp_path / "miner_metrics.jsonl")]))
    assert rc == 0
    delta_path = tmp_path / "artifacts" / "deltas" / "hotkey_0.msgpack"
    assert delta_path.exists(), "miner flush must publish a delta"

    # -- validator scores it and sets chain weights ------------------------
    rc = validator.main(_common(tmp_path, "hotkey_91", ["--rounds", "1"]))
    assert rc == 0
    meta = json.loads((tmp_path / "chain" / "metagraph.json").read_text())
    weights = meta["weights"]["hotkey_91"]
    assert weights, "validator must emit weights"
    # the only delta came from hotkey_0; if anyone scored, it must be them
    if any(weights.values()):
        assert weights.get("hotkey_0", 0) == max(weights.values())

    # -- averager merges and publishes a new base --------------------------
    base_path = tmp_path / "artifacts" / "base" / "averaged_model.msgpack"
    rc = averager.main(_common(
        tmp_path, "hotkey_99",
        ["--rounds", "1", "--strategy", "weighted"]))
    assert rc == 0
    assert base_path.exists(), "averager must publish the merged base"

    # -- miner picks up the new base (optimizer-reset semantics) -----------
    rc = miner.main(_common(
        tmp_path, "hotkey_1",
        ["--max-steps", "5", "--send-interval", "1e9",
         "--check-update-interval", "0"]))
    assert rc == 0
    assert (tmp_path / "artifacts" / "deltas" / "hotkey_1.msgpack").exists()


def test_parameterized_strategy_cli(tmp_path):
    miner.main(_common(tmp_path, "hotkey_0",
                       ["--max-steps", "10", "--send-interval", "1e9"]))
    rc = averager.main(_common(
        tmp_path, "hotkey_99",
        ["--rounds", "1", "--strategy", "parameterized",
         "--meta-epochs", "1"]))
    assert rc == 0
    assert (tmp_path / "artifacts" / "base" / "averaged_model.msgpack").exists()


def test_miner_init_from_pretrained(tmp_path):
    """--init-from <checkpoint>: the miner starts from converted HF weights
    when no base is published (reference boot order, neurons/miner.py:60),
    and the first delta is computed against that pretrained base."""
    np = pytest.importorskip("numpy")
    torch = pytest.importorskip("torch")
    transformers = pytest.importorskip("transformers")
    from safetensors.numpy import save_file as st_save

    from distributedtraining_tpu.models import convert, gpt2

    hf_cfg = transformers.GPT2Config(
        vocab_size=512, n_positions=128, n_embd=64, n_layer=2, n_head=4,
        resid_pdrop=0.0, embd_pdrop=0.0, attn_pdrop=0.0)
    torch.manual_seed(0)
    hf = transformers.GPT2LMHeadModel(hf_cfg).eval()
    ckpt = tmp_path / "pretrained"
    ckpt.mkdir()
    # drop the causal-mask buffers (non-persistent in real checkpoints) and
    # the tied head duplicate — safetensors rejects shared tensors
    st_save({k: v.numpy() for k, v in hf.state_dict().items()
             if not k.endswith((".attn.bias", ".attn.masked_bias"))
             and k != "lm_head.weight"},
            str(ckpt / "model.safetensors"))

    rc = miner.main(_common(
        tmp_path, "hotkey_0",
        ["--max-steps", "3", "--send-interval", "1e9",
         "--checkpoint-interval", "0",
         "--init-from", str(ckpt)]))
    assert rc == 0

    # delta = trained - pretrained: applying it to the converted pretrained
    # tree must NOT equal applying it to a random-init tree
    from distributedtraining_tpu import serialization
    expected = convert.gpt2_from_hf(str(ckpt), gpt2.PRESETS["tiny"])
    wire = (tmp_path / "artifacts" / "deltas" / "hotkey_0.msgpack").read_bytes()
    d = serialization.validated_load(wire, expected)
    # 3 SGD steps move wte by small amounts: the delta's magnitude is far
    # smaller than the pretrained weights themselves, so trained ≈ pretrained
    import jax
    d_norm = np.sqrt(sum(float((np.asarray(l) ** 2).sum())
                         for l in jax.tree_util.tree_leaves(d)))
    w_norm = np.sqrt(sum(float((np.asarray(l) ** 2).sum())
                         for l in jax.tree_util.tree_leaves(expected)))
    assert 0 < d_norm < 0.5 * w_norm


def test_config_defaults_match_reference():
    from distributedtraining_tpu.config import RunConfig
    cfg = RunConfig.from_args("miner", [])
    assert cfg.learning_rate == 5e-4          # neurons/miner.py:121-128
    assert cfg.send_interval == 800.0         # neurons/miner.py:125
    assert cfg.validation_interval == 1800.0  # neurons/validator.py:112
    assert cfg.averaging_interval == 1200.0   # neurons/averager.py:106
    assert cfg.meta_epochs == 7               # neurons/averager.py:106
    assert cfg.epoch_length == 100            # base_subnet_config.py:72-77
    assert cfg.seq_len == 64 and cfg.eval_seq_len == 512


def test_round2_flags_parse_into_config():
    """Every round-2 CLI knob lands in RunConfig (regression guard for the
    from_args field filter silently dropping a renamed dest)."""
    from distributedtraining_tpu.config import RunConfig
    cfg = RunConfig.from_args("miner", [
        "--mu-dtype", "bfloat16", "--accum-steps", "4",
        "--prefetch-depth", "0", "--scan-blocks", "--fused-loss",
        "--mesh-auto", "--dcn-dp", "2", "--grad-clip", "1.0",
    ])
    assert cfg.mu_dtype == "bfloat16"
    assert cfg.accum_steps == 4
    assert cfg.prefetch_depth == 0
    assert cfg.scan_blocks is True
    assert cfg.fused_loss is True
    assert cfg.mesh.auto is True
    assert cfg.mesh.dcn_dp == 2
    assert cfg.grad_clip == 1.0
    # defaults stay conservative
    d = RunConfig.from_args("miner", [])
    assert d.mu_dtype is None and d.accum_steps == 1
    assert d.scan_blocks is False and d.mesh.auto is False
    assert d.prefetch_depth == 2


def test_round3_flags_parse_into_config():
    """Round-3 knobs land in RunConfig (same regression guard class)."""
    from distributedtraining_tpu.config import RunConfig
    m = RunConfig.from_args("miner", [
        "--delta-dtype", "int8", "--weight-decay", "0.1", "--remat",
        "--logits-dtype", "bfloat16", "--log-every", "7"])
    assert m.delta_dtype == "int8" and m.weight_decay == 0.1
    assert m.remat is True and m.log_every == 7
    a = RunConfig.from_args("averager", [
        "--merge-chunk", "4", "--genetic-population", "6",
        "--genetic-generations", "3", "--genetic-sigma", "0.2",
        "--max-delta-abs", "50"])
    assert a.merge_chunk == 4 and a.genetic_population == 6
    assert a.genetic_generations == 3 and a.genetic_sigma == 0.2
    assert a.max_delta_abs == 50.0
    v = RunConfig.from_args("validator", ["--score-metric", "perplexity"])
    assert v.score_metric == "perplexity"


def test_bf16_delta_round(tmp_path):
    """--delta-dtype bfloat16: the published delta is about half the f32
    artifact's bytes, and the validator/averager accept and merge it
    (screen + f32-accumulating merge)."""
    f32_dir, bf16_dir = tmp_path / "f32", tmp_path / "bf16"
    for d, extra in ((f32_dir, []), (bf16_dir, ["--delta-dtype", "bfloat16"])):
        rc = miner.main(_common(
            d, "hotkey_0",
            ["--max-steps", "8", "--send-interval", "1e9",
             "--checkpoint-interval", "0", *extra]))
        assert rc == 0
    f32_bytes = (f32_dir / "artifacts" / "deltas" / "hotkey_0.msgpack"
                 ).stat().st_size
    bf16_bytes = (bf16_dir / "artifacts" / "deltas" / "hotkey_0.msgpack"
                  ).stat().st_size
    assert bf16_bytes < 0.6 * f32_bytes, (bf16_bytes, f32_bytes)

    rc = validator.main(_common(bf16_dir, "hotkey_91", ["--rounds", "1"]))
    assert rc == 0
    meta = json.loads((bf16_dir / "chain" / "metagraph.json").read_text())
    assert meta["weights"]["hotkey_91"].get("hotkey_0", 0) > 0, \
        "validator rejected the bf16 wire delta"
    rc = averager.main(_common(
        bf16_dir, "hotkey_99", ["--rounds", "1", "--strategy", "weighted"]))
    assert rc == 0
    assert (bf16_dir / "artifacts" / "base" / "averaged_model.msgpack").exists()


def test_int8_delta_round(tmp_path):
    """--delta-dtype int8: the artifact shrinks ~4x vs f32 and the
    validator auto-detects the quantized wire form, dequantizes, and
    scores it; the averager merges it."""
    f32_dir, q_dir = tmp_path / "f32", tmp_path / "int8"
    for d, extra in ((f32_dir, []), (q_dir, ["--delta-dtype", "int8"])):
        rc = miner.main(_common(
            d, "hotkey_0",
            ["--max-steps", "8", "--send-interval", "1e9",
             "--checkpoint-interval", "0", *extra]))
        assert rc == 0
    f32_bytes = (f32_dir / "artifacts" / "deltas" / "hotkey_0.msgpack"
                 ).stat().st_size
    q_bytes = (q_dir / "artifacts" / "deltas" / "hotkey_0.msgpack"
               ).stat().st_size
    assert q_bytes < 0.35 * f32_bytes, (q_bytes, f32_bytes)

    rc = validator.main(_common(q_dir, "hotkey_91", ["--rounds", "1"]))
    assert rc == 0
    meta = json.loads((q_dir / "chain" / "metagraph.json").read_text())
    assert meta["weights"]["hotkey_91"].get("hotkey_0", 0) > 0, \
        "validator rejected the int8 wire delta"
    rc = averager.main(_common(
        q_dir, "hotkey_99", ["--rounds", "1", "--strategy", "weighted"]))
    assert rc == 0
    assert (q_dir / "artifacts" / "base" / "averaged_model.msgpack").exists()


def test_logits_dtype_flag_reaches_model_config(tmp_path):
    """--logits-dtype parses into RunConfig AND lands on the model config
    through neurons/common.build, like its siblings --scan-blocks and
    --fused-loss (round-2 verdict: the knob existed but was unreachable
    from the CLI)."""
    from distributedtraining_tpu.config import RunConfig
    from neurons import common

    cfg = RunConfig.from_args("miner", _common(
        tmp_path, "hotkey_0", ["--logits-dtype", "bfloat16", "--remat"]))
    assert cfg.logits_dtype == "bfloat16" and cfg.remat is True
    comps = common.build(cfg)
    assert comps.model_cfg.logits_dtype == "bfloat16"
    assert comps.model_cfg.remat is True
    # default: the model preset's own dtype/remat are left untouched
    d = RunConfig.from_args("miner", _common(tmp_path, "hotkey_0"))
    assert d.logits_dtype is None and d.remat is None
    dc = common.build(d).model_cfg
    assert dc.logits_dtype == "float32" and dc.remat is False
    # tri-state: --no-remat overrides a preset that defaults ON
    n = RunConfig.from_args("miner", _common(
        tmp_path, "hotkey_0", ["--no-remat"]))
    assert n.remat is False


def test_score_metric_flag(tmp_path):
    """--score-metric perplexity reaches the Validator and still scores a
    good delta positive (the reference's second scoring mode)."""
    from distributedtraining_tpu.config import RunConfig
    cfg = RunConfig.from_args("validator", _common(
        tmp_path, "hotkey_91", ["--score-metric", "perplexity"]))
    assert cfg.score_metric == "perplexity"

    miner.main(_common(tmp_path, "hotkey_0",
                       ["--max-steps", "15", "--send-interval", "1e9"]))
    rc = validator.main(_common(
        tmp_path, "hotkey_91",
        ["--rounds", "1", "--score-metric", "perplexity"]))
    assert rc == 0
    meta = json.loads((tmp_path / "chain" / "metagraph.json").read_text())
    assert meta["weights"]["hotkey_91"].get("hotkey_0", 0) > 0


def test_max_delta_abs_flag(tmp_path):
    """--max-delta-abs: a tight cap rejects an honest delta (scored 0);
    0 disables the screen entirely; parse + 0->None translation pinned."""
    from distributedtraining_tpu.config import RunConfig
    cfg = RunConfig.from_args("validator", _common(
        tmp_path, "hotkey_91", ["--max-delta-abs", "0"]))
    assert cfg.max_delta_abs == 0.0

    miner.main(_common(tmp_path, "hotkey_0",
                       ["--max-steps", "10", "--send-interval", "1e9"]))
    # absurdly tight cap: every real delta exceeds 1e-9 -> scored 0
    rc = validator.main(_common(
        tmp_path, "hotkey_91",
        ["--rounds", "1", "--max-delta-abs", "1e-9"]))
    assert rc == 0
    meta = json.loads((tmp_path / "chain" / "metagraph.json").read_text())
    assert meta["weights"]["hotkey_91"].get("hotkey_0", 1) == 0
    # 0 disables the magnitude screen -> the same delta now scores
    rc = validator.main(_common(
        tmp_path, "hotkey_91", ["--rounds", "1", "--max-delta-abs", "0"]))
    assert rc == 0
    meta = json.loads((tmp_path / "chain" / "metagraph.json").read_text())
    assert meta["weights"]["hotkey_91"].get("hotkey_0", 0) > 0


def test_validator_entry_refuses_without_vpermit(tmp_path):
    """hotkey_0 has miner stake (10 < vpermit limit 1000): the entry point
    must refuse up front unless --allow-no-vpermit is passed."""
    with pytest.raises(SystemExit, match="validator permit"):
        validator.main(_common(tmp_path, "hotkey_0", ["--rounds", "1"]))
    # escape hatch: runs, scores, but emits no weights
    rc = validator.main(_common(
        tmp_path, "hotkey_0", ["--rounds", "1", "--allow-no-vpermit"]))
    assert rc == 0
    meta = json.loads((tmp_path / "chain" / "metagraph.json").read_text())
    assert "hotkey_0" not in meta.get("weights", {})


def test_signed_round_end_to_end(tmp_path):
    """Full miner -> validator -> averager round with --sign-artifacts: every
    artifact crosses the wire in an Ed25519 envelope, pubkeys land in the
    chain dir, and a forged overwrite of the miner's delta is screened."""
    signed = ["--sign-artifacts", "--base-signer", "hotkey_99"]
    rc = miner.main(_common(
        tmp_path, "hotkey_0",
        ["--max-steps", "20", "--send-interval", "1e9", *signed]))
    assert rc == 0
    delta_path = tmp_path / "artifacts" / "deltas" / "hotkey_0.msgpack"
    from distributedtraining_tpu import signing
    assert signing.is_enveloped(delta_path.read_bytes())
    assert (tmp_path / "chain" / "pubkeys.json").exists()

    rc = validator.main(_common(tmp_path, "hotkey_91",
                                ["--rounds", "1", *signed]))
    assert rc == 0
    meta = json.loads((tmp_path / "chain" / "metagraph.json").read_text())
    assert meta["weights"]["hotkey_91"].get("hotkey_0", 0) > 0

    rc = averager.main(_common(
        tmp_path, "hotkey_99",
        ["--rounds", "1", "--strategy", "weighted", *signed]))
    assert rc == 0
    base_path = tmp_path / "artifacts" / "base" / "averaged_model.msgpack"
    assert signing.is_enveloped(base_path.read_bytes())

    # attacker overwrites the miner's delta with an unsigned payload: the
    # next validator round must score that miner 0 (no_delta)
    import numpy as np
    delta_path.write_bytes(b"\x00" * 64)
    rc = validator.main(_common(tmp_path, "hotkey_91",
                                ["--rounds", "1", *signed]))
    assert rc == 0


def test_round4_flags_parse_into_config():
    """Round-4 knobs land in RunConfig (same regression guard class)."""
    from distributedtraining_tpu.config import RunConfig
    v = RunConfig.from_args("validator", ["--no-accept-quant"])
    assert v.accept_quant is False
    a = RunConfig.from_args("averager", ["--no-accept-quant",
                                         "--genetic-screen-batches", "0"])
    assert a.accept_quant is False
    assert a.genetic_screen_batches == 0
    assert RunConfig.from_args("validator", []).accept_quant is True
    m = RunConfig.from_args("miner", ["--delta-dtype", "sparse8",
                                      "--delta-density", "0.03125"])
    assert m.delta_dtype == "sparse8" and m.delta_density == 0.03125


def test_sparse8_delta_round(tmp_path):
    """--delta-dtype sparse8: top-k int8 wire — the artifact shrinks well
    past the dense int8 form (>=8x beyond int8 at the default density,
    VERDICT r3 #5), the validator auto-detects the self-describing format
    and scores it, the averager merges it."""
    q_dir, sp_dir = tmp_path / "int8", tmp_path / "sparse8"
    for d, extra in ((q_dir, ["--delta-dtype", "int8"]),
                     (sp_dir, ["--delta-dtype", "sparse8"])):
        rc = miner.main(_common(
            d, "hotkey_0",
            ["--max-steps", "8", "--send-interval", "1e9",
             "--checkpoint-interval", "0", *extra]))
        assert rc == 0
    q_bytes = (q_dir / "artifacts" / "deltas" / "hotkey_0.msgpack"
               ).stat().st_size
    sp_bytes = (sp_dir / "artifacts" / "deltas" / "hotkey_0.msgpack"
                ).stat().st_size
    # tiny-model caveat: many leaves sit under the dense cutoff, so the
    # tiny-model ratio understates the big-model one; still demand a
    # clear multiple (the 124M evidence lives in the E2E artifact)
    assert sp_bytes < 0.5 * q_bytes, (sp_bytes, q_bytes)

    rc = validator.main(_common(sp_dir, "hotkey_91", ["--rounds", "1"]))
    assert rc == 0
    meta = json.loads((sp_dir / "chain" / "metagraph.json").read_text())
    assert meta["weights"]["hotkey_91"].get("hotkey_0", 0) > 0, \
        "validator rejected the sparse8 wire delta"
    rc = averager.main(_common(
        sp_dir, "hotkey_99", ["--rounds", "1", "--strategy", "weighted"]))
    assert rc == 0
    assert (sp_dir / "artifacts" / "base" / "averaged_model.msgpack").exists()


def test_llama_family_offline_round(tmp_path):
    """The full CLI round on the SECOND model family (tiny-llama: RoPE,
    GQA, RMSNorm, SwiGLU, separate lm_head) — family coverage at the
    protocol surface, not just the model-level tests."""
    args = lambda hk, extra: [
        "--backend", "local", "--work-dir", str(tmp_path),
        "--model", "tiny-llama", "--dataset", "synthetic",
        "--hotkey", hk, "--dp", "1",
        "--batch-size", "4", "--seq-len", "32", "--eval-seq-len", "32",
        "--eval-batches", "2", *extra,
    ]
    rc = miner.main(args("hotkey_0", [
        "--max-steps", "25", "--send-interval", "1e9",
        "--checkpoint-interval", "0", "--delta-dtype", "sparse8"]))
    assert rc == 0
    rc = validator.main(args("hotkey_91", ["--rounds", "1"]))
    assert rc == 0
    meta = json.loads((tmp_path / "chain" / "metagraph.json").read_text())
    assert meta["weights"]["hotkey_91"].get("hotkey_0", 0) > 0, \
        "validator rejected the llama sparse8 delta"
    rc = averager.main(args("hotkey_99",
                            ["--rounds", "1", "--strategy", "weighted"]))
    assert rc == 0
    assert (tmp_path / "artifacts" / "base"
            / "averaged_model.msgpack").exists()
