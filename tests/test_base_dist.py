"""Content-addressed base distribution (engine/basedist.py): sharded
publish, mirror racing, delta-pull rounds.

The acceptance pins here are the round's contract: a sharded pull is
bit-exact with the monolithic pull, a warm pull fetches ONLY
changed-hash layers (unchanged layer = 0 bytes), a hostile or torn
shard set is never decoded (it degrades to the monolithic base —
loudly), mirrors fail over to origin, and mixed old/new fleets
interoperate with no flag day.
"""

import os
import sys

import jax
import numpy as np
import pytest

from distributedtraining_tpu import serialization as ser
from distributedtraining_tpu.engine.basedist import (BaseFetcher,
                                                     BasePublisher,
                                                     BaseShardStore,
                                                     MirrorDuty,
                                                     assemble_base_tree,
                                                     base_layer_items,
                                                     read_base_wire_rider)
from distributedtraining_tpu.transport import base as tbase
from distributedtraining_tpu.transport.localfs import LocalFSTransport
from distributedtraining_tpu.transport.memory import InMemoryTransport

sys.path.insert(0, os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "scripts"))
import fleet_report  # noqa: E402


def _tree(seed=0):
    rs = np.random.RandomState(seed)
    return {"wte": rs.randn(20, 8).astype(np.float32),
            "h_0": {"w": rs.randn(8, 8).astype(np.float32),
                    "b": rs.randn(8).astype(np.float32)},
            "ln": rs.randn(8).astype(np.float32)}


def _template(tree=None):
    return jax.tree_util.tree_map(
        lambda x: np.zeros(np.shape(x), np.asarray(x).dtype),
        tree if tree is not None else _tree())


def _leaves(t):
    return [np.asarray(x) for x in jax.tree_util.tree_leaves(t)]


def _trees_equal(a, b) -> bool:
    return all(np.array_equal(x, y)
               for x, y in zip(_leaves(a), _leaves(b)))


class CountingFS(LocalFSTransport):
    """LocalFS that records every raw publish/fetch (id, nbytes)."""

    def __init__(self, root):
        super().__init__(root)
        self.published: list[tuple[str, int]] = []
        self.fetched: list[tuple[str, int]] = []

    def publish_raw(self, mid, data):
        self.published.append((mid, len(data)))
        return super().publish_raw(mid, data)

    def fetch_delta_bytes(self, mid):
        d = super().fetch_delta_bytes(mid)
        if d is not None:
            self.fetched.append((mid, len(d)))
        return d

    def fetch_base_bytes(self):
        d = super().fetch_base_bytes()
        if d is not None:
            self.fetched.append(("__mono__", len(d)))
        return d


def _published(transport, tree, *, mirrors=()):
    """Publish ``tree`` monolithically + sharded; returns (pub, rev)."""
    rev = transport.publish_base(tree)
    pub = BasePublisher(transport, mirrors=mirrors)
    assert pub.publish_revision(tree, rev)
    return pub, rev


# ---------------------------------------------------------------------------
# Manifest + shard container
# ---------------------------------------------------------------------------

def test_base_manifest_round_trip():
    layers = {"a/b": ("ab" * 32, 100), "c": ("cd" * 32, 7)}
    data = ser.build_base_manifest(layers, revision="rev123")
    assert ser.is_base_manifest(data)
    assert not ser.is_wire_v2_manifest(data)   # magics are disjoint
    man = ser.parse_base_manifest(data)
    assert man is not None
    assert man["revision"] == "rev123"
    assert man["layers"] == {k: {"h": h, "n": n}
                             for k, (h, n) in layers.items()}


@pytest.mark.parametrize("mutate", [
    lambda d: b"NOTMAGIC" + d[8:],                      # wrong magic
    lambda d: d[:8] + b"{garbage",                      # broken JSON
    lambda d: d[:8] + b'{"format":2,"layers":{}}',      # wrong format
    lambda d: d[:8] + b'{"format":1,"layers":{}}',      # empty layers
    lambda d: d[:8] + b'{"format":1,"revision":"r",'
                      b'"layers":{"k":{"h":"xx","n":1}}}',   # bad hash
    lambda d: d[:8] + b'{"format":1,"revision":"r","layers":'
                      b'{"k":{"h":"' + b"a" * 64 + b'","n":-1}}}',  # bad n
    lambda d: d[:8] + b'{"format":1,"layers":'
                      b'{"k":{"h":"' + b"a" * 64 + b'","n":1}}}',  # no rev
])
def test_base_manifest_hostile_variants(mutate):
    good = ser.build_base_manifest({"k": ("a" * 64, 1)}, revision="r")
    assert ser.parse_base_manifest(mutate(good)) is None


def test_base_shard_pack_round_trip():
    for arr in (np.arange(6, dtype=np.float32).reshape(2, 3),
                np.arange(4, dtype=np.int32),
                np.float32(3.5)):
        out = ser.unpack_base_shard(ser.pack_base_shard(arr))
        assert out is not None
        assert np.array_equal(out, np.asarray(arr))
        assert out.dtype == np.asarray(arr).dtype
    assert ser.unpack_base_shard(b"\x00garbage") is None
    # deterministic encoding: the fetcher's locally-derived digests must
    # match the publisher's (how the store warms off the fallback path)
    a = np.arange(8, dtype=np.float32)
    assert ser.pack_base_shard(a) == ser.pack_base_shard(a.copy())


def test_layer_items_assemble_round_trip():
    tree = _tree()
    items = base_layer_items(tree)
    assert set(items) == {"wte", "h_0/w", "h_0/b", "ln"}
    out = assemble_base_tree(items, _template(tree))
    assert out is not None and _trees_equal(out, tree)
    # missing layer / wrong shape / wrong dtype all reject
    assert assemble_base_tree({k: v for k, v in items.items()
                               if k != "ln"}, _template(tree)) is None
    bad = dict(items)
    bad["ln"] = np.zeros(9, np.float32)
    assert assemble_base_tree(bad, _template(tree)) is None
    bad = dict(items)
    bad["ln"] = items["ln"].astype(np.float64)
    assert assemble_base_tree(bad, _template(tree)) is None


def test_reserved_ids_and_slug_injectivity():
    assert tbase.is_reserved_id(tbase.base_shard_id("a/b.c"))
    assert tbase.is_reserved_id(tbase.base_manifest_id("rev"))
    assert tbase.is_reserved_id(tbase.BASE_PREFIX)   # the rider slot
    assert tbase.is_reserved_id(
        tbase.shard_id(tbase.mirror_node_id("sub0"), "wte"))
    assert tbase.is_reserved_id(tbase.mirror_node_id("sub0"))
    # slug injectivity rides shard_layer_slug (docs/wire.md)
    assert tbase.base_shard_id("a/b.c") != tbase.base_shard_id("a/b/c")
    # a manifest id can never collide with a shard id: revision slugs
    # contain no literal "." while shard ids carry the "s." segment
    assert tbase.base_manifest_id("s.wte") != tbase.base_shard_id("wte")


# ---------------------------------------------------------------------------
# Publisher + fetcher over localfs
# ---------------------------------------------------------------------------

def test_cold_sharded_pull_is_bit_exact(tmp_path):
    t = CountingFS(str(tmp_path))
    tree = _tree()
    _published(t, tree)
    f = BaseFetcher(t)
    got = f.fetch(_template(tree))
    assert got is not None
    mono = t.fetch_base(_template(tree))
    assert got[1] == mono[1]
    assert _trees_equal(got[0], mono[0])
    assert f.sharded_fetches_total == 1 and f.fallbacks_total == 0


def test_warm_pull_fetches_only_changed_layers(tmp_path):
    t = CountingFS(str(tmp_path))
    tree = _tree()
    pub, _ = _published(t, tree)
    f = BaseFetcher(t)
    assert f.fetch(_template(tree)) is not None
    tree2 = dict(tree)
    tree2["ln"] = tree["ln"] + 1.0
    rev2 = t.publish_base(tree2)
    assert pub.publish_revision(tree2, rev2)
    t.fetched.clear()
    got = f.fetch(_template(tree))
    assert got is not None and got[1] == rev2
    assert _trees_equal(got[0], tree2)
    shard_fetches = [(mid, n) for mid, n in t.fetched
                     if mid.startswith(tbase.BASE_PREFIX + ".s.")]
    # exactly ONE shard crossed the wire, and it is the changed layer;
    # every unchanged layer cost 0 bytes (the store served it)
    assert len(shard_fetches) == 1
    assert shard_fetches[0][0] == tbase.base_shard_id("ln")
    assert f.store_hits_total == 3


def test_publisher_dedupes_unchanged_shards(tmp_path):
    t = CountingFS(str(tmp_path))
    tree = _tree()
    pub, _ = _published(t, tree)
    uploads_cold = sum(1 for mid, _ in t.published
                       if mid.startswith(tbase.BASE_PREFIX + ".s."))
    assert uploads_cold == 4
    tree2 = dict(tree)
    tree2["ln"] = tree["ln"] + 1.0
    rev2 = t.publish_base(tree2)
    t.published.clear()
    assert pub.publish_revision(tree2, rev2)
    uploads_warm = [mid for mid, _ in t.published
                    if mid.startswith(tbase.BASE_PREFIX + ".s.")]
    assert uploads_warm == [tbase.base_shard_id("ln")]


def test_monolithic_fallback_seeds_the_store(tmp_path):
    """A fetcher whose first pull fell back to the monolithic path (no
    manifest yet) still delta-pulls the NEXT round: the fallback seeds
    the store with locally-derived digests."""
    t = CountingFS(str(tmp_path))
    tree = _tree()
    t.publish_base(tree)         # old averager: monolithic only
    f = BaseFetcher(t)
    assert f.fetch(_template(tree)) is not None
    assert f.fallbacks_total == 1
    # the averager upgrades; one layer changes
    pub = BasePublisher(t)
    tree2 = dict(tree)
    tree2["ln"] = tree["ln"] + 1.0
    rev2 = t.publish_base(tree2)
    assert pub.publish_revision(tree2, rev2)
    t.fetched.clear()
    got = f.fetch(_template(tree))
    assert got is not None and _trees_equal(got[0], tree2)
    assert f.fallbacks_total == 1          # no second fallback
    shard_fetches = [mid for mid, _ in t.fetched
                     if mid.startswith(tbase.BASE_PREFIX + ".s.")]
    assert shard_fetches == [tbase.base_shard_id("ln")]


def test_announce_rider_round_trip(tmp_path):
    t = LocalFSTransport(str(tmp_path))
    tree = _tree()
    _, rev = _published(t, tree, mirrors=["sub0", "sub1"])
    rider = read_base_wire_rider(t)
    assert rider == {"revision": rev, "mirrors": ["sub0", "sub1"]}
    # hostile rider reads as absent, never an exception
    t.publish_delta_meta(tbase.BASE_PREFIX, {"base_wire": "nope"})
    assert read_base_wire_rider(t) is None


# ---------------------------------------------------------------------------
# Hostile / torn inputs degrade loudly to the monolithic base
# ---------------------------------------------------------------------------

def test_hostile_manifest_falls_back_to_monolithic(tmp_path, caplog):
    t = CountingFS(str(tmp_path))
    tree = _tree()
    rev = t.publish_base(tree)
    t.publish_raw(tbase.base_manifest_id(rev),
                  ser.BASE_MANIFEST_MAGIC + b"{hostile")
    f = BaseFetcher(t)
    with caplog.at_level("WARNING"):
        got = f.fetch(_template(tree))
    assert got is not None and _trees_equal(got[0], tree)
    assert f.fallbacks_total == 1
    assert any("rejected" in r.message for r in caplog.records)


def test_bad_hash_manifest_falls_back(tmp_path):
    """A manifest whose hashes match nothing on the wire: every shard
    fails verification, the pull degrades to the monolithic base."""
    t = CountingFS(str(tmp_path))
    tree = _tree()
    rev = t.publish_base(tree)
    layers = {k: ("a" * 64, 10) for k in base_layer_items(tree)}
    t.publish_raw(tbase.base_manifest_id(rev),
                  ser.build_base_manifest(layers, revision=rev))
    f = BaseFetcher(t)
    got = f.fetch(_template(tree))
    assert got is not None and _trees_equal(got[0], tree)
    assert f.fallbacks_total == 1


def test_torn_shard_set_never_decodes(tmp_path):
    """One shard overwritten after the manifest committed (the
    mid-publish race): its hash check fails, the pull falls back, and
    the fetched tree is STILL the published base — a half-new assembly
    is never returned."""
    t = CountingFS(str(tmp_path))
    tree = _tree()
    pub, rev = _published(t, tree)
    t.publish_raw(tbase.base_shard_id("ln"),
                  ser.pack_base_shard(np.full(8, 999.0, np.float32)))
    f = BaseFetcher(t)
    got = f.fetch(_template(tree))
    assert got is not None and _trees_equal(got[0], tree)
    assert f.fallbacks_total == 1


def test_tampered_signed_manifest_exits_loudly(tmp_path, caplog):
    """Signed fleet: the manifest travels enveloped (publish_delta_raw)
    and a tampered one is REJECTED at the signature layer with a
    warning — the fetcher then falls back to the (equally signed,
    verified) monolithic base."""
    pytest.importorskip("cryptography")
    from distributedtraining_tpu.transport.signed import SignedTransport
    from distributedtraining_tpu.utils.identity import Identity

    ident = Identity.generate()

    def resolver(hotkey):
        # the averager's key also pins every reserved id it publishes
        return ident.public_bytes

    inner = LocalFSTransport(str(tmp_path))
    signed = SignedTransport(inner, identity=ident,
                             pubkey_resolver=resolver,
                             base_signer=ident.hotkey,
                             my_hotkey=ident.hotkey)
    tree = _tree()
    rev = signed.publish_base(tree)
    pub = BasePublisher(signed)
    assert pub.publish_revision(tree, rev)
    f = BaseFetcher(signed)
    got = f.fetch(_template(tree))
    assert got is not None and _trees_equal(got[0], tree)
    assert f.fallbacks_total == 0
    # attacker with write access swaps the manifest for unsigned bytes
    good = ser.build_base_manifest(
        {k: (ser.shard_digest(ser.pack_base_shard(v)), 1)
         for k, v in base_layer_items(_tree(seed=9)).items()},
        revision=rev)
    inner.publish_raw(tbase.base_manifest_id(rev), good)
    f2 = BaseFetcher(signed)
    with caplog.at_level("WARNING"):
        got2 = f2.fetch(_template(tree))
    # the forged manifest is rejected (logged), the pull degrades to
    # the signature-verified monolithic base — bit-exact, not hostile
    assert got2 is not None and _trees_equal(got2[0], tree)
    assert f2.fallbacks_total == 1
    assert any("rejected" in r.message for r in caplog.records)


def test_fetch_never_raises_on_probe_failure():
    class Dead(InMemoryTransport):
        def base_revision(self):
            raise OSError("backend down")

    f = BaseFetcher(Dead())
    assert f.fetch(_template()) is None


# ---------------------------------------------------------------------------
# Mirrors
# ---------------------------------------------------------------------------

class FaultyFS(CountingFS):
    """LocalFS whose origin base-shard slots and/or mirror slots can be
    switched off (ChaosError-free spelling: a plain OSError, which is
    what every isolation path treats as a transport fault)."""

    def __init__(self, root):
        super().__init__(root)
        self.origin_shards_dead = False
        self.mirrors_dead = False

    def fetch_delta_bytes(self, mid):
        if self.origin_shards_dead and \
                mid.startswith(tbase.BASE_PREFIX + ".s."):
            raise OSError("origin shard slot dead")
        if self.mirrors_dead and \
                mid.startswith(f"{tbase.SHARD_PREFIX}.{tbase.MIRROR_PREFIX}."):
            raise OSError("mirror replica dead")
        return super().fetch_delta_bytes(mid)


def test_mirror_serves_shards_and_fails_over(tmp_path):
    t = FaultyFS(str(tmp_path))
    tree = _tree()
    pub, rev = _published(t, tree, mirrors=["sub0"])
    mirror = MirrorDuty(t, "sub0")
    assert mirror.sync()
    # presence rider names the mirrored revision
    meta = t.fetch_delta_meta(tbase.mirror_node_id("sub0"))
    assert meta["mirror"]["revision"] == rev

    # origin shard slots die: the pull still completes entirely off the
    # mirror replica (the manifest's hashes verify whatever slot served)
    t.origin_shards_dead = True
    f = BaseFetcher(t)
    got = f.fetch(_template(tree))
    assert got is not None and _trees_equal(got[0], tree)
    assert f.fallbacks_total == 0 and f.mirror_hits_total == 4

    # a NEW revision with the mirror ALSO dead: per-shard fall-through
    # to origin (revived), no round loss
    t.origin_shards_dead = False
    t.mirrors_dead = True
    tree2 = dict(tree)
    tree2["ln"] = tree["ln"] + 1.0
    rev2 = t.publish_base(tree2)
    assert pub.publish_revision(tree2, rev2)
    f2 = BaseFetcher(t)
    got2 = f2.fetch(_template(tree))
    assert got2 is not None and _trees_equal(got2[0], tree2)
    assert got2[1] == rev2
    assert f2.mirror_hits_total == 0 and f2.fallbacks_total == 0


def test_mirror_sync_is_incremental(tmp_path):
    t = CountingFS(str(tmp_path))
    tree = _tree()
    pub, _ = _published(t, tree)
    mirror = MirrorDuty(t, "sub0")
    assert mirror.sync()
    republished = [mid for mid, _ in t.published
                   if mid.startswith(
                       f"{tbase.SHARD_PREFIX}.{tbase.MIRROR_PREFIX}.")]
    assert len(republished) == 4
    tree2 = dict(tree)
    tree2["ln"] = tree["ln"] + 1.0
    rev2 = t.publish_base(tree2)
    assert pub.publish_revision(tree2, rev2)
    t.published.clear()
    assert mirror.sync()
    republished = [mid for mid, _ in t.published
                   if mid.startswith(
                       f"{tbase.SHARD_PREFIX}.{tbase.MIRROR_PREFIX}.")]
    # only the changed layer re-replicates
    assert republished == [tbase.shard_id(tbase.mirror_node_id("sub0"),
                                          "ln")]
    # an unchanged revision is a no-op pass
    t.published.clear()
    assert mirror.sync()
    assert not t.published


# ---------------------------------------------------------------------------
# Mixed fleets (the no-flag-day negotiation)
# ---------------------------------------------------------------------------

def test_old_fetcher_against_new_averager(tmp_path):
    """A pre-round-19 node keeps using fetch_base and sees exactly the
    published base — the shard plane is an overlay, not a format
    change."""
    t = LocalFSTransport(str(tmp_path))
    tree = _tree()
    _, rev = _published(t, tree)
    got = t.fetch_base(_template(tree))
    assert got is not None and got[1] == rev
    assert _trees_equal(got[0], tree)


def test_new_fetcher_against_old_averager(tmp_path):
    """No manifest, no rider (old averager): the enabled fetcher
    silently takes the monolithic path every round."""
    t = LocalFSTransport(str(tmp_path))
    tree = _tree()
    t.publish_base(tree)
    f = BaseFetcher(t)
    got = f.fetch(_template(tree))
    assert got is not None and _trees_equal(got[0], tree)
    assert f.sharded_fetches_total == 0 and f.fallbacks_total == 1


def test_disabled_fetcher_is_plain_monolithic(tmp_path):
    t = CountingFS(str(tmp_path))
    tree = _tree()
    _published(t, tree)
    f = BaseFetcher(t, enabled=False)
    got = f.fetch(_template(tree))
    assert got is not None and _trees_equal(got[0], tree)
    # never probed the manifest id, never counted a fallback
    assert not any(mid.startswith(tbase.BASE_PREFIX)
                   for mid, _ in t.fetched)
    assert f.fallbacks_total == 0


# ---------------------------------------------------------------------------
# Degrade-to-current-base regression pins (the satellite fix)
# ---------------------------------------------------------------------------

def _mini_engine():
    from distributedtraining_tpu.engine.train import TrainEngine
    from distributedtraining_tpu.models import gpt2
    model, cfg = gpt2.make_model(gpt2.GPT2Config(
        vocab_size=128, n_positions=32, n_embd=16, n_layer=1, n_head=2))
    return TrainEngine(model, seq_len=16)


@pytest.fixture(scope="module")
def engine():
    return _mini_engine()


def test_watcher_degrades_on_hostile_manifest(tmp_path, engine):
    """BaseRevisionWatcher + fetcher: a hostile manifest (and then a
    torn monolithic base too) leaves serving on the current base —
    poll_once returns via the fallback or counts a failure, it never
    raises (chaos-pinned twin of the monolithic torn-fetch test)."""
    from distributedtraining_tpu.engine.serve import BaseRevisionWatcher
    from distributedtraining_tpu.engine.train import host_wire_template
    from distributedtraining_tpu.transport.chaos import (ChaosSpec,
                                                         ChaosTransport)

    inner = LocalFSTransport(str(tmp_path))
    t = ChaosTransport(inner, ChaosSpec())   # fault-free gate: the wrap
    #                                          pins the wrapper surface
    template = host_wire_template(engine)
    tree = jax.tree_util.tree_map(
        lambda x: np.asarray(np.random.RandomState(0).randn(*np.shape(x)),
                             np.asarray(x).dtype), template)
    rev = t.publish_base(tree)
    # hostile manifest for the revision: the sharded path must degrade
    inner.publish_raw(tbase.base_manifest_id(rev),
                      ser.BASE_MANIFEST_MAGIC + b"{hostile")
    fetcher = BaseFetcher(t)
    watcher = BaseRevisionWatcher(t, lambda: template, fetcher=fetcher)
    assert watcher.poll_once()            # staged via monolithic fallback
    staged = watcher.take_pending()
    assert staged is not None and staged[0] == rev
    assert fetcher.fallbacks_total == 1

    # now the monolithic base is ALSO torn: no stage, no raise — serving
    # stays on the current base
    inner.publish_base_raw(b"torn-garbage")
    assert watcher.poll_once() is False
    assert watcher.take_pending() is None


def test_miner_bootstrap_refuses_genesis_fork_on_torn_base(tmp_path,
                                                           engine):
    """A published-but-unreadable base at boot must NOT silently fork
    the miner to a genesis base: bootstrap retries briefly, then
    surfaces an OSError for the role's bounded bootstrap retry."""
    from distributedtraining_tpu.engine.train import MinerLoop

    t = LocalFSTransport(str(tmp_path))
    t.publish_base_raw(b"torn-garbage")   # revision exists, decode fails
    loop = MinerLoop(engine, t, "m0", send_interval=1e9, push_async=False)
    with pytest.raises(OSError):
        loop.bootstrap(rng=jax.random.PRNGKey(0))
    loop.flush()


def test_miner_bootstrap_degrades_on_manifest_parse_failure(tmp_path,
                                                            engine):
    """The satellite contract: a hostile/torn MANIFEST at boot degrades
    to the monolithic base — the miner comes up on the published base,
    not genesis, and not an exception."""
    from distributedtraining_tpu.engine.train import (MinerLoop,
                                                      host_wire_template)

    t = LocalFSTransport(str(tmp_path))
    template = host_wire_template(engine)
    tree = jax.tree_util.tree_map(
        lambda x: np.asarray(np.random.RandomState(1).randn(*np.shape(x)),
                             np.asarray(x).dtype), template)
    rev = t.publish_base(tree)
    t.publish_raw(tbase.base_manifest_id(rev),
                  ser.BASE_MANIFEST_MAGIC + b"{hostile")
    fetcher = BaseFetcher(t)
    loop = MinerLoop(engine, t, "m0", send_interval=1e9,
                     push_async=False, base_fetcher=fetcher)
    loop.bootstrap(rng=jax.random.PRNGKey(0))
    assert loop._base_revision == rev
    assert fetcher.fallbacks_total == 1
    # next round the averager publishes a HEALTHY manifest: the pull
    # goes back to the sharded path warm off the fallback-seeded store
    pub = BasePublisher(t)
    tree2 = dict(tree)
    key = sorted(tree2)[0]
    tree2[key] = jax.tree_util.tree_map(lambda x: x + 0.5, tree2[key]) \
        if isinstance(tree2[key], dict) else tree2[key] + 0.5
    rev2 = t.publish_base(tree2)
    assert pub.publish_revision(tree2, rev2)
    loop._check_pull()
    assert loop._base_revision == rev2
    assert fetcher.sharded_fetches_total == 1
    loop.flush()


# ---------------------------------------------------------------------------
# Fleetsim: the mirror-kill chaos scenario (satellite)
# ---------------------------------------------------------------------------

def test_fleetsim_mirror_kill_fails_over_with_no_round_loss():
    from distributedtraining_tpu.engine import fleetsim as fs

    spec = fs.FleetSpec(miners=8, validators=1, servers=0,
                        sub_averagers=2, rounds=6, seed=7, chaos=False,
                        standby=False, mirror_kill_round=4)
    result = fs.simulate(spec)
    assert result.rounds_completed == spec.rounds
    assert result.base_mirror_shard_hits > 0          # mirrors DID serve
    card = fs.assemble_scorecard(result)
    gate = card["gates"]["base_dist"]
    assert gate["ok"], gate
    assert gate["post_kill_mirror_bytes"] == 0        # dead means dead
    # every miner completed a pull every post-kill round: no round loss
    assert gate["post_kill_pulls"] == spec.miners * (spec.rounds
                                                     - spec.mirror_kill_round
                                                     + 1)
    # per-round accounting: mirror bytes moved before the kill
    samples = card["wire"]["samples"]
    pre_kill = samples[spec.mirror_kill_round - 2]
    assert pre_kill["base_mirror_fetch_bytes"] > 0


def test_fleetsim_base_bytes_accounting_splits_origin_and_mirror():
    from distributedtraining_tpu.engine import fleetsim as fs

    spec = fs.FleetSpec(miners=6, validators=1, servers=0,
                        sub_averagers=2, rounds=4, seed=1, chaos=False,
                        standby=False)
    result = fs.simulate(spec)
    last = result.wire_samples[-1]
    assert last["base_origin_fetch_bytes"] > 0
    assert last["base_mirror_fetch_bytes"] > 0
    assert (last["base_origin_fetch_bytes"]
            + last["base_mirror_fetch_bytes"]) <= last["fetch_bytes"]
    # the sharded plane OFF: no mirror bytes, byte-identical rerun logic
    # still holds (determinism is pinned module-wide in test_fleetsim)
    off = fs.simulate(dataclasses_replace(spec, base_wire_v2=False))
    assert off.wire_samples[-1]["base_mirror_fetch_bytes"] == 0
    assert off.base_sharded_pulls == 0


def dataclasses_replace(spec, **kw):
    import dataclasses
    return dataclasses.replace(spec, **kw)


# ---------------------------------------------------------------------------
# fleet_report columns (satellite)
# ---------------------------------------------------------------------------

def test_fleet_report_base_columns():
    assert "base_b" in fleet_report.COLUMNS
    assert "mirror_hit" in fleet_report.COLUMNS
    node = {"base_fetch_bytes": 5 * (1 << 20),
            "base_mirror_hit_rate": 0.875}
    assert fleet_report._cell(node, "base_b") == "5.0M"
    assert fleet_report._cell(node, "mirror_hit") == "0.88"
    assert fleet_report._cell({}, "base_b") == "-"
    assert fleet_report._cell({}, "mirror_hit") == "-"


def test_fetcher_heartbeat_fields(tmp_path):
    t = LocalFSTransport(str(tmp_path))
    tree = _tree()
    _published(t, tree)
    f = BaseFetcher(t)
    assert f.fetch(_template(tree)) is not None
    fields = f.heartbeat_fields()
    assert fields["base_fetch_bytes"] > 0
    assert fields["base_fetch_bytes"] == fields["base_last_fetch_bytes"]
    # every name must pass the heartbeat producer lint
    from distributedtraining_tpu.engine.health import build_heartbeat
    build_heartbeat("miner", "m0", 1, now=0.0, **fields)


# ---------------------------------------------------------------------------
# Store
# ---------------------------------------------------------------------------

def test_store_lru_byte_budget():
    store = BaseShardStore(max_bytes=100)
    a = np.zeros(10, np.float32)   # 40 bytes
    store.put("d1", a)
    store.put("d2", a)
    assert len(store) == 2 and store.nbytes == 80
    store.put("d3", a)             # evicts d1 (LRU)
    assert store.lookup("d1") is None
    assert store.lookup("d2") is not None
    assert store.nbytes == 80
    # an over-budget array is refused, not cached
    store.put("big", np.zeros(1000, np.float32))
    assert store.lookup("big") is None
    # budget 0 disables caching entirely
    off = BaseShardStore(max_bytes=0)
    off.put("d", a)
    assert off.lookup("d") is None
