"""Blockwise (portable lax-flash) attention: parity vs dense, dead-row
semantics, fallback routing. See ops/attention.py::blockwise_attention —
the memory-honest fallback when the Pallas flash kernel declines, and the
spelling the AOT scale artifacts compile (scripts/scale_aot.py)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributedtraining_tpu.ops.attention import (
    BLOCKWISE_FALLBACK_LEN, blockwise_attention, causal_attention,
    combine_masks, dot_product_attention, make_causal_mask)

B, T, H, D = 2, 200, 4, 16


@pytest.fixture(scope="module")
def qkv():
    rng = np.random.default_rng(0)
    q = jnp.asarray(rng.normal(size=(B, T, H, D)), jnp.float32)
    k = jnp.asarray(rng.normal(size=(B, T, H, D)), jnp.float32)
    v = jnp.asarray(rng.normal(size=(B, T, H, D)), jnp.float32)
    am = jnp.asarray(rng.integers(0, 2, (B, T)).astype(np.float32))
    am = am.at[:, 0].set(1)
    seg = jnp.asarray(np.sort(rng.integers(0, 3, (B, T)), axis=1), jnp.int32)
    return q, k, v, am, seg


@pytest.mark.parametrize("masks", ["none", "pad", "seg", "pad+seg"])
def test_blockwise_matches_dense(qkv, masks):
    """Forward and gradient parity vs the dense reference on every mask
    combination, with non-divisible block sizes (T=200, bq=64, bkv=48
    exercises both padding paths). Rows with no visible key (possible
    under pad+seg) emit exact 0 — the flash-kernel convention — and are
    excluded from the parity comparison (dense emits uniform garbage
    there; the data pipeline excludes such tokens from the loss)."""
    q, k, v, am, seg = qkv
    kwargs = {}
    if "pad" in masks:
        kwargs["attention_mask"] = am
    if "seg" in masks:
        kwargs["segment_ids"] = seg
    full = combine_masks(make_causal_mask(T), kwargs.get("attention_mask"),
                         kwargs.get("segment_ids"))
    ref = dot_product_attention(q, k, v, full)
    out = blockwise_attention(q, k, v, block_q=64, block_kv=48, **kwargs)
    alive = np.asarray(full.any(axis=-1))            # [B, H, Tq]
    alive_bthd = np.broadcast_to(
        alive.transpose(0, 2, 1)[..., None], out.shape)
    assert np.abs(np.asarray(out) - np.asarray(ref))[alive_bthd].max() < 2e-5
    dead = np.abs(np.asarray(out))[~alive_bthd]
    assert dead.size == 0 or dead.max() == 0

    alive_f = jnp.asarray(alive_bthd, jnp.float32)
    g_ref = jax.grad(lambda q_: ((dot_product_attention(q_, k, v, full)
                                  * alive_f) ** 2).sum())(q)
    g_new = jax.grad(lambda q_: ((blockwise_attention(
        q_, k, v, block_q=64, block_kv=48, **kwargs) * alive_f) ** 2).sum())(q)
    np.testing.assert_allclose(np.asarray(g_new), np.asarray(g_ref),
                               rtol=2e-4, atol=2e-4)


def test_flash_fallback_routes_by_length(qkv, monkeypatch):
    """On backends where the Pallas kernel declines, impl='flash' falls
    back to blockwise at long T (dense [T, T] temps would explode) and
    dense at short T (faster, tiny temps)."""
    from distributedtraining_tpu.ops import attention as attn
    q, k, v, am, seg = qkv
    calls = []
    monkeypatch.setattr(attn, "blockwise_attention",
                        lambda *a, **kw: calls.append("block") or
                        blockwise_attention(*a, **kw))
    # force the kernel to decline regardless of backend
    import distributedtraining_tpu.ops.flash_attention as fa
    monkeypatch.setattr(fa, "flash_attention", lambda *a, **kw: None)

    short = causal_attention(q, k, v, impl="flash")
    assert calls == []  # T=200 < threshold: dense fallback
    tlong = BLOCKWISE_FALLBACK_LEN
    rng = np.random.default_rng(1)
    ql = jnp.asarray(rng.normal(size=(1, tlong, 2, 8)), jnp.float32)
    causal_attention(ql, ql, ql, impl="flash")
    assert calls == ["block"]
    # and the explicit impl works at any length
    causal_attention(q, k, v, impl="blockwise")
    assert calls == ["block", "block"]
    assert short.shape == (B, T, H, D)
