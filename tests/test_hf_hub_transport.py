"""HFHubTransport against a stub HfApi — no network.

The stub models just enough of the Hub: per-repo file blobs, a commit SHA
that changes on every upload, and download-to-a-local-cache-file semantics
(including the transport's delete-after-read behavior). Covers the full
Transport protocol plus gc() ownership rules (reference squashes both its
delta repo and the shared averaged-model repo it owns,
hivetrain/hf_manager.py:73-136).
"""

from __future__ import annotations

import hashlib
import os

import numpy as np
import pytest

from distributedtraining_tpu import serialization as ser
from distributedtraining_tpu.transport.hf_hub import (BASE_FILE, DELTA_FILE,
                                                      HFHubTransport)


class StubHfApi:
    """In-memory Hub: {repo_id: {filename: bytes}} + fake commit SHAs."""

    def __init__(self, tmpdir):
        self.tmpdir = str(tmpdir)
        self.repos: dict[str, dict[str, bytes]] = {}
        self.shas: dict[str, str] = {}
        self.squashed: list[str] = []
        self.token = None

    def _bump(self, repo_id: str) -> str:
        blob = b"".join(self.repos.get(repo_id, {}).get(f, b"")
                        for f in sorted(self.repos.get(repo_id, {})))
        sha = hashlib.sha1(blob + repo_id.encode()).hexdigest()
        self.shas[repo_id] = sha
        return sha

    def upload_file(self, *, path_or_fileobj, path_in_repo, repo_id,
                    repo_type="model"):
        with open(path_or_fileobj, "rb") as f:
            data = f.read()
        self.repos.setdefault(repo_id, {})[path_in_repo] = data
        sha = self._bump(repo_id)

        class Info:
            oid = sha
        return Info()

    def hf_hub_download(self, *, repo_id, filename, **kw):
        from huggingface_hub.utils import EntryNotFoundError
        try:
            data = self.repos[repo_id][filename]
        except KeyError:
            raise EntryNotFoundError(f"{repo_id}/{filename} not found")
        path = os.path.join(self.tmpdir, f"{repo_id}_{filename}".replace(
            "/", "_"))
        with open(path, "wb") as f:
            f.write(data)
        return path

    def list_repo_refs(self, repo_id):
        class Branch:
            def __init__(self, sha):
                self.target_commit = sha

        class Refs:
            branches = ([Branch(self.shas[repo_id])]
                        if repo_id in self.shas else [])
        return Refs()

    def super_squash_history(self, *, repo_id):
        if repo_id not in self.repos:
            raise RuntimeError(f"403: not your repo {repo_id}")
        self.squashed.append(repo_id)


def tree():
    return {"w": np.arange(6, dtype=np.float32).reshape(2, 3),
            "b": np.ones((3,), np.float32)}


@pytest.fixture
def api(tmp_path):
    return StubHfApi(tmp_path / "hub_cache")


def make(api, **kw):
    os.makedirs(api.tmpdir, exist_ok=True)
    return HFHubTransport(averaged_model_repo_id="org/averaged", api=api, **kw)


def test_delta_round_trip_and_revision(api):
    t = make(api, my_repo_id="org/miner0")
    template = tree()
    assert t.fetch_delta("org/miner0", template) is None
    assert t.delta_revision("org/miner0") is None

    rev1 = t.publish_delta("miner_hotkey_ignored", tree())
    assert rev1 is not None
    got = t.fetch_delta("org/miner0", template)
    np.testing.assert_array_equal(got["w"], template["w"])
    assert t.delta_revision("org/miner0") == rev1

    # revision changes when content changes (commit-SHA polling semantics)
    changed = tree()
    changed["w"] = changed["w"] + 1
    rev2 = t.publish_delta("x", changed)
    assert rev2 != rev1


def test_download_deletes_cached_blob(api):
    t = make(api, my_repo_id="org/miner0")
    t.publish_delta("x", tree())
    assert t.fetch_delta("org/miner0", tree()) is not None
    # the cache file must not survive the read (disk-bounding behavior)
    leftovers = [f for f in os.listdir(api.tmpdir)
                 if DELTA_FILE.replace("/", "_") in f]
    assert leftovers == []


def test_base_round_trip(api):
    t = make(api)
    assert t.fetch_base(tree()) is None
    assert t.base_revision() is None
    rev = t.publish_base(tree())
    fetched = t.fetch_base(tree())
    assert fetched is not None
    got, got_rev = fetched
    np.testing.assert_array_equal(got["b"], np.ones((3,), np.float32))
    assert got_rev == rev == t.base_revision()
    assert BASE_FILE in api.repos["org/averaged"]


def test_fetch_rejects_oversize_and_garbage(api):
    t = make(api, my_repo_id="org/miner0", max_bytes=16)
    t.publish_delta("x", tree())  # serialized form exceeds 16 bytes
    assert t.fetch_delta("org/miner0", tree()) is None

    t2 = make(api, my_repo_id="org/miner1")
    api.repos["org/miner1"] = {DELTA_FILE: b"\xff\x00garbage"}
    api._bump("org/miner1")
    assert t2.fetch_delta("org/miner1", tree()) is None  # PayloadError -> None


def test_fetch_delta_bytes_single_read(api):
    t = make(api, my_repo_id="org/miner0")
    t.publish_delta("x", tree())
    data = t.fetch_delta_bytes("org/miner0")
    assert data is not None
    assert ser.from_msgpack(data, tree()) is not None
    assert t.fetch_delta_bytes("org/nonexistent") is None


def test_gc_squashes_own_repos_only(api):
    miner = make(api, my_repo_id="org/miner0")
    miner.publish_delta("x", tree())
    miner.gc()
    assert api.squashed == ["org/miner0"]

    api.squashed.clear()
    validator = make(api)  # no repo of its own, does not own the base
    validator.gc()
    assert api.squashed == []


def test_base_repo_squashed_before_publish_not_after(api):
    """Squash must precede the upload (reference order) so the revision
    publish_base returns stays the live one — squashing after would hand
    every peer a phantom revision change on identical bytes."""
    averager = make(api, owns_base_repo=True)
    rev1 = averager.publish_base(tree())          # repo absent: squash no-ops
    assert averager.base_revision() == rev1       # recorded rev is live
    api.squashed.clear()
    rev2 = averager.publish_base(tree())
    assert api.squashed == ["org/averaged"]       # squashed on publish...
    assert averager.base_revision() == rev2       # ...but rev still live
    averager.gc()                                  # gc never touches it
    assert api.squashed == ["org/averaged"]
