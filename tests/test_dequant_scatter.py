"""Fused dequantize->scatter-add kernel (ops/dequant_scatter.py) and its
delta.accumulate_delta integration.

Round 20's ingest half: the kernel-backed packed accumulate must match
the densify_packed_v2 + dense accumulate_delta spelling to 1e-6 on
every entry class the wire produces (int8 and f32 kept values,
dense-form below-cutoff leaves, empty leaves), keep today's screened
semantics on hostile payloads (duplicate indices SUM like the XLA
scatter-add; negative scales never reach an accumulate at all), and
the densify round-trip the kernel deletes must be VISIBLE when it
happens (the ``delta.densify_fallbacks`` counter, satellite 2).
Kernels run interpreted here (tier-1 forces CPU); real-chip variants
live in tests_tpu/test_dequant_scatter_tpu.py.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributedtraining_tpu import delta as dl
from distributedtraining_tpu.ops import dequant_scatter as dsc
from distributedtraining_tpu.utils import obs


@pytest.fixture(autouse=True)
def _no_force_interpret():
    yield
    dsc.use_interpret(False)


def _accumulate_both_ways(template, packed, w):
    """(kernel-backed result, XLA scatter-add result, densify+dense
    result) for one packed tree folded into a zeros accumulator."""
    acc0 = jax.tree_util.tree_map(
        lambda x: jnp.zeros(np.shape(x), jnp.float32), template)
    xla = dl.accumulate_delta(acc0, packed, w)
    dsc.use_interpret(True)
    assert dsc.enabled()
    kernel = dl.accumulate_delta(acc0, packed, w)
    dsc.use_interpret(False)
    dense = dl.densify_packed_v2(packed, template)
    assert dense is not None
    densified = dl.accumulate_delta(acc0, dense, w)
    return kernel, xla, densified


def _assert_tree_close(a, b, atol=1e-6):
    for la, lb in zip(jax.tree_util.tree_leaves(a),
                      jax.tree_util.tree_leaves(b)):
        np.testing.assert_allclose(np.asarray(la), np.asarray(lb),
                                   atol=atol)


# ---------------------------------------------------------------------------
# Kernel primitive
# ---------------------------------------------------------------------------

def test_kernel_matches_xla_scatter_int8_f32_duplicates():
    rng = np.random.default_rng(0)
    n, k = 2048, 96
    flat = jnp.asarray(rng.standard_normal(n), jnp.float32)
    q8 = jnp.asarray(rng.integers(-127, 128, k), jnp.int8)
    qf = jnp.asarray(rng.standard_normal(k), jnp.float32)
    for idx in (jnp.asarray(rng.integers(0, n, k), jnp.int32),  # dups likely
                jnp.zeros((k,), jnp.int32)):                    # all dups
        for q in (q8, qf):
            out = dsc.dequant_scatter_add(flat, idx, q, 0.37,
                                          interpret=True)
            assert out is not None
            ref = flat.at[idx].add(q.astype(jnp.float32) * 0.37)
            np.testing.assert_array_equal(np.asarray(out),
                                          np.asarray(ref))


def test_kernel_declines_oversize_and_empty():
    flat_big = jnp.zeros((dsc.MAX_ACC_ELEMS + 1,), jnp.float32)
    idx = jnp.asarray([0], jnp.int32)
    q = jnp.asarray([1], jnp.int8)
    assert dsc.dequant_scatter_add(flat_big, idx, q, 1.0,
                                   interpret=True) is None
    flat = jnp.zeros((128,), jnp.float32)
    assert dsc.dequant_scatter_add(flat, idx[:0], q[:0], 1.0,
                                   interpret=True) is None
    # and production CPU (no interpret override, no TPU): declined
    assert dsc.dequant_scatter_add(flat, idx, q, 1.0) is None
    assert not dsc.enabled()


# ---------------------------------------------------------------------------
# accumulate_delta integration: parity vs densify+accumulate
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("quant", ["int8", "none"])
def test_accumulate_kernel_matches_densify_path(quant):
    """The acceptance pin: kernel-routed packed accumulate ==
    densify_packed_v2 + dense accumulate_delta <= 1e-6, over a tree
    with an above-cutoff leaf (indexed entries), a below-cutoff leaf
    (dense-form entry), and an EMPTY leaf."""
    rng = np.random.default_rng(1)
    d = {"w": jnp.asarray(rng.standard_normal((96, 64)), jnp.float32),
         "b": jnp.asarray(rng.standard_normal((32,)), jnp.float32),
         "empty": jnp.zeros((0,), jnp.float32)}
    template = jax.tree_util.tree_map(
        lambda x: np.zeros(np.shape(x), np.float32), d)
    packed, _ = dl.pack_delta_v2(d, density=1.0 / 16.0, quant=quant)
    # the big leaf really is indexed-form, the small one dense-form
    assert packed["leaves"]["w"]["idx"].shape[0] > 0
    assert packed["leaves"]["b"]["idx"].shape[0] == 0
    kernel, xla, densified = _accumulate_both_ways(template, packed, 0.7)
    _assert_tree_close(kernel, densified)
    _assert_tree_close(kernel, xla)
    _assert_tree_close(xla, densified)


def test_aggregate_deltas_kernel_parity_mixed_cohort():
    """M mixed contributions (packed int8, packed f32, dense v1) folded
    by aggregate_deltas: kernel-routed == XLA <= 1e-6 over the whole
    aggregate — the sub-averager fold (engine/hier_average.py) and the
    flat packed merge (engine/average.py) both ride this path."""
    rng = np.random.default_rng(2)
    template = {"w": np.zeros((96, 64), np.float32),
                "b": np.zeros((32,), np.float32)}
    deltas = []
    for i in range(3):
        d = {"w": jnp.asarray(rng.standard_normal((96, 64)), jnp.float32),
             "b": jnp.asarray(rng.standard_normal((32,)), jnp.float32)}
        if i == 0:
            deltas.append(d)    # dense v1
        else:
            deltas.append(dl.pack_delta_v2(
                d, density=1.0 / 8.0,
                quant="int8" if i == 1 else "none")[0])
    w = jnp.asarray([0.2, 0.5, 0.3], jnp.float32)
    xla = dl.aggregate_deltas(template, deltas, w)
    dsc.use_interpret(True)
    kernel = dl.aggregate_deltas(template, deltas, w)
    dsc.use_interpret(False)
    _assert_tree_close(kernel, xla)


# ---------------------------------------------------------------------------
# Hostile payloads keep today's screened semantics
# ---------------------------------------------------------------------------

def test_hostile_duplicate_indices_sum_on_both_paths():
    """A hostile duplicate-index entry (honest encoders emit unique
    top-k indices): the kernel SUMS duplicates exactly like the XLA
    scatter-add — deterministic, and screened upstream regardless."""
    template = {"w": np.zeros((8192,), np.float32)}
    entry = {"idx": jnp.asarray([5, 5, 5, 9], jnp.int32),
             "q": jnp.asarray([10, 20, -5, 7], jnp.int8),
             "scale": jnp.asarray(0.5, jnp.float32)}
    packed = {dl.WIRE_V2_KEY: np.int32(dl.WIRE_V2_FORMAT),
              "leaves": {"w": entry}}
    assert dl.packed_matches(packed, template)
    acc0 = {"w": jnp.zeros((8192,), jnp.float32)}
    xla = dl.accumulate_delta(acc0, packed, 1.0)
    dsc.use_interpret(True)
    kernel = dl.accumulate_delta(acc0, packed, 1.0)
    dsc.use_interpret(False)
    np.testing.assert_allclose(np.asarray(kernel["w"]),
                               np.asarray(xla["w"]), atol=1e-6)
    assert float(kernel["w"][5]) == pytest.approx((10 + 20 - 5) * 0.5)


def test_negative_scale_never_reaches_accumulate():
    """Negative scales stay rejected at admission (packed_matches and
    the fused packed screen) — the kernel path changes nothing about
    what is allowed to accumulate."""
    template = {"w": np.zeros((8192,), np.float32)}
    hostile = {dl.WIRE_V2_KEY: np.int32(dl.WIRE_V2_FORMAT),
               "leaves": {"w": {"idx": np.asarray([1], np.int32),
                                "q": np.asarray([127], np.int8),
                                "scale": np.asarray(-1e6, np.float32)}}}
    assert not dl.packed_matches(hostile, template)
    verdicts = dl.screen_deltas([hostile], template, max_abs=1e3)
    assert verdicts[0] == (False, "shape_mismatch")


# ---------------------------------------------------------------------------
# Satellite 2: densify=False end-to-end, fallbacks counted
# ---------------------------------------------------------------------------

def _publish_packed(transport, hotkey, d, template):
    from distributedtraining_tpu.engine.publish import DeltaPublisher
    from distributedtraining_tpu.transport.retry import RetryPolicy

    class _Report:
        pushes = 0
        pushes_failed = 0
        pushes_superseded = 0

    fast = RetryPolicy(attempts=2, base_delay=0.0, max_delay=0.0,
                       jitter=0.0)
    pub = DeltaPublisher(transport, hotkey, report=_Report(),
                         publish_retry=fast, meta_retry=fast,
                         wire_spec={"format": 2, "density": 1.0 / 8.0,
                                    "quant": "int8"})
    assert pub.publish_now(dl.pack_delta_v2(d, density=1.0 / 8.0)[0],
                           None, "rev0")
    pub.close()


def test_ingest_densify_fallbacks_counter(tmp_path):
    """densify=True ingest of a packed submission counts ONE
    ``delta.densify_fallbacks``; densify=False ingest counts none and
    stages the PACKED tree — the regression signal fleet_report
    surfaces."""
    from distributedtraining_tpu.engine.ingest import DeltaIngestor
    from distributedtraining_tpu.transport.memory import InMemoryTransport

    rng = np.random.default_rng(3)
    template = {"w": np.zeros((96, 64), np.float32)}
    d = {"w": jnp.asarray(rng.standard_normal((96, 64)), jnp.float32)}
    transport = InMemoryTransport()
    _publish_packed(transport, "m0", d, template)

    class _Sink:
        def log(self, *a, **k):
            pass

    try:
        for densify, expect in ((True, 1), (False, 0)):
            obs.reset()
            obs.configure(_Sink(), role="test")
            ing = DeltaIngestor(transport, template, densify=densify,
                                workers=1, cache_bytes=0)
            (s,) = ing.stage(["m0"])
            ing.close()
            assert s.reason == "ok"
            assert dl.is_packed_v2(s.delta) is (not densify)
            snap = obs.registry().snapshot()
            assert snap.get("delta.densify_fallbacks", 0) == expect, \
                (densify, snap.get("delta.densify_fallbacks"))
    finally:
        obs.reset()


def test_flat_averager_stays_packed_end_to_end(tmp_path):
    """The satellite's end-to-end pin: an AveragerLoop whose strategy
    folds host lists (WeightedAverage) now ingests wire-v2 submissions
    with densify=False — the packed tree reaches the scatter-add merge
    un-densified, zero densify fallbacks, and the published base equals
    the densify-path base <= 1e-6."""
    from distributedtraining_tpu.engine import TrainEngine, WeightedAverage
    from distributedtraining_tpu.engine.average import AveragerLoop
    from distributedtraining_tpu.engine.train import host_wire_template
    from distributedtraining_tpu.models import gpt2
    from distributedtraining_tpu.transport.memory import InMemoryTransport

    class _Chain:
        my_hotkey = "avg"

        def sync(self):
            import types
            return types.SimpleNamespace(hotkeys=["m0"])

        def should_set_weights(self):
            return False

    model, cfg = gpt2.make_model(gpt2.GPT2Config(
        vocab_size=64, n_positions=32, n_embd=32, n_layer=2, n_head=2,
        dtype="float32", vocab_multiple=64))
    engine = TrainEngine(model, seq_len=16)
    transport = InMemoryTransport()
    base = model.init_params(jax.random.PRNGKey(0), seq_len=8)
    from distributedtraining_tpu.engine.train import wire_out
    transport.publish_base(wire_out(engine, base))

    template = host_wire_template(engine)
    rng = np.random.default_rng(4)
    d = jax.tree_util.tree_map(
        lambda x: jnp.asarray(rng.standard_normal(np.shape(x)) * 1e-3,
                              jnp.float32), template)
    _publish_packed(transport, "m0", d, template)

    avg = AveragerLoop(engine, transport, _Chain(), WeightedAverage(),
                       val_batches=None, publish_policy="always")
    try:
        assert avg._ingest().densify is False
        assert avg._packed_ingest is True
        ids, deltas = avg.gather_deltas()
        assert ids == ["m0"]
        assert dl.is_packed_v2(deltas[0])
        # the packed fold equals densify + dense fold
        w = jnp.asarray([1.0], jnp.float32)
        packed_agg = dl.aggregate_deltas(template, deltas, w)
        dense = dl.densify_packed_v2(deltas[0], template)
        dense_agg = dl.aggregate_deltas(template, [dense], w)
        _assert_tree_close(packed_agg, dense_agg)
    finally:
        avg.close()
