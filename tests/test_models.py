"""Model zoo: shapes, loss sanity, packing masks, LoRA zero-init property."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributedtraining_tpu.models import GPT2, GPT2Config, Llama, LlamaConfig, lora
from distributedtraining_tpu.models import gpt2 as gpt2_mod
from distributedtraining_tpu.models import llama as llama_mod
from distributedtraining_tpu.ops import causal_lm_loss


@pytest.fixture(scope="module")
def tiny_gpt2():
    model, cfg = gpt2_mod.make_model("tiny")
    params = model.init_params(jax.random.PRNGKey(0), seq_len=16)
    return model, cfg, params


def test_gpt2_forward_shape(tiny_gpt2):
    model, cfg, params = tiny_gpt2
    ids = jnp.zeros((2, 16), jnp.int32)
    logits = model.apply({"params": params}, ids)
    assert logits.shape == (2, 16, cfg.padded_vocab)
    assert logits.dtype == jnp.float32


def test_bf16_logits_storage(tiny_gpt2):
    """logits_dtype='bfloat16' halves the logit buffer while the loss stays
    within bf16 rounding of the f32-logits loss (accumulation is f32 either
    way — only storage precision changes)."""
    import dataclasses

    model, cfg, params = tiny_gpt2
    bf_model, bf_cfg = gpt2_mod.make_model(
        dataclasses.replace(cfg, logits_dtype="bfloat16"))
    ids = jnp.asarray(np.random.default_rng(0).integers(
        0, cfg.vocab_size, (2, 16)), jnp.int32)
    lf = model.apply({"params": params}, ids)
    lb = bf_model.apply({"params": params}, ids)
    assert lb.dtype == jnp.bfloat16 and lf.dtype == jnp.float32
    loss_f, _ = causal_lm_loss(lf, ids)
    loss_b, _ = causal_lm_loss(lb, ids)
    np.testing.assert_allclose(float(loss_b), float(loss_f),
                               rtol=1e-2)  # bf16 has ~3 significant digits


def test_gpt2_causality(tiny_gpt2):
    """Changing a future token must not change past logits."""
    model, cfg, params = tiny_gpt2
    k = jax.random.PRNGKey(1)
    ids = jax.random.randint(k, (1, 16), 0, cfg.vocab_size)
    logits1 = model.apply({"params": params}, ids)
    ids2 = ids.at[0, 10].set((ids[0, 10] + 1) % cfg.vocab_size)
    logits2 = model.apply({"params": params}, ids2)
    np.testing.assert_allclose(np.asarray(logits1[0, :10]),
                               np.asarray(logits2[0, :10]), atol=2e-2)
    assert not np.allclose(np.asarray(logits1[0, 10:]),
                           np.asarray(logits2[0, 10:]), atol=1e-3)


def test_segment_ids_isolate_packed_sequences(tiny_gpt2):
    """With packing, tokens must not attend across segment boundaries."""
    model, cfg, params = tiny_gpt2
    k = jax.random.PRNGKey(2)
    a = jax.random.randint(k, (1, 8), 0, cfg.vocab_size)
    b = jax.random.randint(jax.random.PRNGKey(3), (1, 8), 0, cfg.vocab_size)
    packed = jnp.concatenate([a, b], axis=1)
    seg = jnp.concatenate([jnp.zeros((1, 8), jnp.int32),
                           jnp.ones((1, 8), jnp.int32)], axis=1)
    pos = jnp.concatenate([jnp.arange(8), jnp.arange(8)])[None, :]
    packed_logits = model.apply({"params": params}, packed,
                                segment_ids=seg, position_ids=pos)
    solo_logits = model.apply({"params": params}, b)
    np.testing.assert_allclose(np.asarray(packed_logits[0, 8:]),
                               np.asarray(solo_logits[0]), atol=2e-2)


def test_loss_decreases_under_sgd(tiny_gpt2):
    model, cfg, params = tiny_gpt2
    ids = jax.random.randint(jax.random.PRNGKey(4), (4, 16), 0, cfg.vocab_size)

    def loss_fn(p):
        logits = model.apply({"params": p}, ids)
        loss, _ = causal_lm_loss(logits, ids)
        return loss

    l0 = loss_fn(params)
    g = jax.grad(loss_fn)(params)
    params2 = jax.tree_util.tree_map(lambda p, gr: p - 0.1 * gr, params, g)
    l1 = loss_fn(params2)
    assert float(l1) < float(l0)


def test_llama_forward_and_gqa():
    model, cfg = llama_mod.make_model("tiny-llama")
    params = model.init_params(jax.random.PRNGKey(0), seq_len=16)
    ids = jax.random.randint(jax.random.PRNGKey(1), (2, 16), 0, cfg.vocab_size)
    logits = model.apply({"params": params}, ids)
    assert logits.shape == (2, 16, cfg.padded_vocab)
    # causality holds with RoPE + GQA
    logits2 = model.apply({"params": params},
                          ids.at[0, 12].set((ids[0, 12] + 1) % cfg.vocab_size))
    np.testing.assert_allclose(np.asarray(logits[0, :12]),
                               np.asarray(logits2[0, :12]), atol=2e-2)


def test_lora_zero_init_is_identity():
    model, cfg = llama_mod.make_model("tiny-llama")
    params = model.init_params(jax.random.PRNGKey(0), seq_len=8)
    lcfg = lora.LoRAConfig(rank=4)
    lp = lora.init_lora(jax.random.PRNGKey(5), params, lcfg)
    eff = lora.apply_lora(params, lp, lcfg)
    for a, b in zip(jax.tree_util.tree_leaves(eff),
                    jax.tree_util.tree_leaves(params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b))


def test_lora_delta_matches_apply():
    model, cfg = llama_mod.make_model("tiny-llama")
    params = model.init_params(jax.random.PRNGKey(0), seq_len=8)
    lcfg = lora.LoRAConfig(rank=4)
    lp = lora.init_lora(jax.random.PRNGKey(5), params, lcfg)
    # give B nonzero values so the delta is nontrivial
    lp = jax.tree_util.tree_map(lambda x: x + 0.01, lp)
    from distributedtraining_tpu import delta as d
    full = d.apply_delta(params, lora.lora_to_full_delta(params, lp, lcfg))
    eff = lora.apply_lora(params, lp, lcfg)
    for a, b in zip(jax.tree_util.tree_leaves(full),
                    jax.tree_util.tree_leaves(eff)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-5)


def test_lora_adapts_expected_kernels():
    model, cfg = llama_mod.make_model("tiny-llama")
    params = model.init_params(jax.random.PRNGKey(0), seq_len=8)
    lp = lora.init_lora(jax.random.PRNGKey(5), params, lora.LoRAConfig(rank=2))
    # 2 layers x (wq, wk, wv, wo) = 8 adapted kernels
    assert len(lora.adapted_pairs(lp)) == 8


def test_llama2_7b_shapes_on_v4_32_mesh():
    """Shape-validate the llama2-7b preset (full-param AND LoRA engines) on
    a 32-device virtual mesh — subprocess because it needs its own
    XLA_FLAGS device count (VERDICT r01: presets never shape-validated at
    scale break on first contact, e.g. GQA kv-heads vs tp divisibility)."""
    import os
    import subprocess
    import sys

    worker = os.path.join(os.path.dirname(__file__), "validate_7b_worker.py")
    env = dict(os.environ,
               XLA_FLAGS="--xla_force_host_platform_device_count=32")
    proc = subprocess.run([sys.executable, worker], env=env,
                          capture_output=True, text=True, timeout=280)
    assert proc.returncode == 0, proc.stderr[-2000:]
    assert proc.stdout.startswith("OK "), proc.stdout
