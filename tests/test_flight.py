"""Flight recorder & postmortem plane (utils/flight.py).

Covers: the bounded event ring + producer/consumer schema lint, content-
addressed bundle freeze/publish/fetch over the reserved ``__pm__``
transport namespace, the obs span/flush/anomaly hooks, crash hooks,
publish-outcome events (including torn wire-v2 shard sets), lease/
remediation/SLO attachment of bundle references to the contribution
ledger, the debug endpoints, JSONL retention sweep, and the acceptance
round: a ChaosTransport round that kills a miner mid-publish must leave
a Transport-fetchable ``__pm__`` bundle whose reconstructed timeline
(scripts/postmortem.py) names the torn publish and the SLO rule that
fired, joined on cid across >= 2 roles.
"""

import json
import os
import sys
import threading
import urllib.error
import urllib.request

import jax
import numpy as np
import pytest

from distributedtraining_tpu import delta as dl
from distributedtraining_tpu.engine.health import (FleetMonitor, SLORule,
                                                   build_heartbeat)
from distributedtraining_tpu.engine.publish import DeltaPublisher
from distributedtraining_tpu.engine.remediate import (LeaseManager,
                                                      RemediationEngine,
                                                      RemediationPolicy)
from distributedtraining_tpu.transport import base as tbase
from distributedtraining_tpu.transport.chaos import (ChaosEvent,
                                                     ChaosTransport)
from distributedtraining_tpu.transport.localfs import LocalFSTransport
from distributedtraining_tpu.transport.memory import InMemoryTransport
from distributedtraining_tpu.transport.retry import RetryPolicy
from distributedtraining_tpu.utils import flight, obs
from distributedtraining_tpu.utils.metrics import InMemorySink, JSONLSink

sys.path.insert(0, os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "scripts"))
import postmortem  # noqa: E402

FAST_RETRY = RetryPolicy(attempts=2, base_delay=0.0, max_delay=0.0,
                         jitter=0.0)


@pytest.fixture(autouse=True)
def _clean_state():
    obs.reset()
    flight.reset()
    yield
    flight.reset()
    obs.reset()


class _Report:
    pushes = 0
    pushes_failed = 0
    pushes_superseded = 0


def _tree(seed=0, big=(300, 40), small=(32,)):
    rs = np.random.RandomState(seed)
    return {"wte": (rs.randn(*big) * 0.01).astype(np.float32),
            "ln": {"g": (rs.randn(*small) * 0.01).astype(np.float32)}}


# ---------------------------------------------------------------------------
# Ring + schema lint
# ---------------------------------------------------------------------------

def test_ring_is_bounded_and_thread_safe():
    rec = flight.FlightRecorder("miner", "m0", capacity=16)
    threads = [threading.Thread(
        target=lambda: [rec.record("note", i=i) for i in range(100)])
        for _ in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    evs = rec.events()
    assert len(evs) == 16                  # ring keeps only the tail
    assert rec.recorded >= 400             # lifetime counter keeps all
    assert all(e["kind"] in ("note", "config") for e in evs)


def test_record_rejects_unknown_kind_at_producer():
    rec = flight.FlightRecorder("miner", "m0")
    with pytest.raises(ValueError, match="unknown flight event kind"):
        rec.record("not_a_kind", x=1)
    # module helper is a no-op when unconfigured, lints when configured
    flight.record("not_a_kind")            # no recorder: silent no-op
    flight.configure("miner", "m0")
    with pytest.raises(ValueError):
        flight.record("not_a_kind")


def test_parse_bundle_rejects_junk_and_unknown_event_kinds():
    assert flight.parse_bundle(b"\x00garbage") is None
    assert flight.parse_bundle(b'{"pm": "no"}') is None
    assert flight.parse_bundle(
        json.dumps({"pm": 1, "role": "miner"}).encode()) is None
    assert flight.parse_bundle(
        b"x" * (flight.PM_MAX_BYTES + 1)) is None
    good = {"pm": 1, "role": "miner", "hotkey": "m0", "t": 1.0,
            "reason": "slo_stale_node",
            "events": [{"t": 1.0, "kind": "publish", "outcome": "ok"},
                       {"t": 2.0, "kind": "EVIL", "x": 1},
                       {"kind": "publish"},          # no timestamp
                       "not-a-dict"]}
    parsed = flight.parse_bundle(json.dumps(good).encode())
    assert parsed is not None
    assert [e["kind"] for e in parsed["events"]] == ["publish"]
    assert parsed["events_rejected"] == 3


def test_sanitize_config_redacts_secret_keys():
    out = flight.sanitize_config({
        "learning_rate": 5e-4, "role": "miner", "push_async": True,
        "wallet_path": "/secrets/w.json", "wallet_hotkey": "hot",
        "hf_token": "sk-xyz", "long": "x" * 1000, "skip": None})
    assert out["learning_rate"] == pytest.approx(5e-4)
    assert out["push_async"] is True
    assert out["wallet_path"] == "<redacted>"
    assert out["wallet_hotkey"] == "<redacted>"
    assert out["hf_token"] == "<redacted>"
    assert len(out["long"]) <= 400
    assert "skip" not in out


def test_bundle_is_content_addressed():
    rec = flight.FlightRecorder("miner", "m0", clock=lambda: 123.0)
    rec.record("note", what="x")
    b1 = rec.freeze("r")
    b2 = rec.freeze("r")
    # identical content except seq -> different address; same dict ->
    # digest is a pure function of the body
    assert b1["bundle_id"] != b2["bundle_id"]
    assert flight.bundle_digest(b1) == b1["bundle_id"]
    assert flight.bundle_digest(dict(b1)) == b1["bundle_id"]


# ---------------------------------------------------------------------------
# Publish / fetch over the reserved __pm__ namespace
# ---------------------------------------------------------------------------

def test_pm_id_is_reserved():
    pid = tbase.pm_id("miner", "m0")
    assert pid == "__pm__.miner.m0"
    assert tbase.is_pm_id(pid)
    assert tbase.is_reserved_id(pid)
    assert not tbase.is_pm_id("m0")


@pytest.mark.parametrize("make", [InMemoryTransport,
                                  "localfs"])
def test_freeze_publish_fetch_roundtrip(make, tmp_path):
    transport = (LocalFSTransport(str(tmp_path / "art"))
                 if make == "localfs" else make())
    rec = flight.configure("averager", "a0", transport=transport)
    rec.record("slo", rule="stale_node", hotkey="m0", round=3)
    ref = flight.freeze_and_publish("slo_stale_node")
    assert ref is not None
    fetched = flight.fetch_bundle(transport, "averager", "a0")
    assert fetched is not None
    assert fetched["bundle_id"] == ref
    assert fetched["reason"] == "slo_stale_node"
    assert any(e["kind"] == "slo" and e.get("rule") == "stale_node"
               for e in fetched["events"])
    # registry snapshot + digest ride the bundle
    assert fetched["role"] == "averager"
    assert flight.fetch_bundle(transport, "miner", "nobody") is None


def test_publish_truncates_oversized_bundles():
    transport = InMemoryTransport()
    rec = flight.FlightRecorder("miner", "m0", capacity=4096,
                                transport=transport)
    blob = "y" * 390
    for i in range(4000):
        rec.record("note", payload=blob, i=i)
    bundle = rec.freeze("big")
    assert rec.publish(bundle)
    data = transport.fetch_delta_bytes(tbase.pm_id("miner", "m0"))
    assert data is not None and len(data) <= flight.PM_MAX_BYTES
    parsed = flight.parse_bundle(data)
    assert parsed is not None and parsed["events"]
    # newest evidence survives the truncation
    assert parsed["events"][-1]["i"] == 3999


def test_publish_failure_is_survivable_and_mirrored_to_sink():
    class Broken(InMemoryTransport):
        def publish_raw(self, miner_id, data):
            raise OSError("dark")

    sink = InMemorySink()
    obs.configure(sink, role="miner")
    rec = flight.configure("miner", "m0", transport=Broken())
    rec.record("note", what="evidence")
    ref = flight.freeze_and_publish("crash")
    assert ref is not None                  # the reference still exists
    assert rec.publish_failures == 1
    mirrored = [r for r in sink.records if "postmortem" in r]
    assert mirrored and mirrored[0]["postmortem"]["bundle_id"] == ref


# ---------------------------------------------------------------------------
# obs hooks
# ---------------------------------------------------------------------------

def test_span_hook_records_spans_and_metrics_snapshots():
    sink = InMemorySink()
    obs.configure(sink, role="miner")
    rec = flight.configure("miner", "m0")
    with obs.span("push.upload", cid="m0-000007"):
        pass
    kinds = [e["kind"] for e in rec.events()]
    assert "span" in kinds
    span_ev = next(e for e in rec.events() if e["kind"] == "span")
    assert span_ev["name"] == "push.upload"
    assert span_ev["cid"] == "m0-000007"
    # the span registered span.push.upload_ms -> vocabulary changed ->
    # a metrics snapshot event landed with the digest
    metrics_ev = [e for e in rec.events() if e["kind"] == "metrics"]
    assert metrics_ev and metrics_ev[-1]["digest"] == obs.registry_digest()
    n = len(rec.events())
    with obs.span("push.upload"):
        pass                                # same vocabulary: span only
    kinds2 = [e["kind"] for e in rec.events()[n:]]
    assert kinds2 == ["span"]


def test_span_error_flag_and_anomaly_hook():
    sink = InMemorySink()
    obs.configure(sink, role="miner")
    rec = flight.configure("miner", "m0")
    with pytest.raises(RuntimeError):
        with obs.span("val.eval"):
            raise RuntimeError("boom")
    ev = next(e for e in rec.events()
              if e["kind"] == "span" and e["name"] == "val.eval")
    assert ev["error"] is True
    mon = obs.AnomalyMonitor()
    mon.observe_loss(float("nan"))
    anomalies = [e for e in rec.events() if e["kind"] == "anomaly"]
    assert anomalies and anomalies[0]["reason"] == "loss_nonfinite"


# ---------------------------------------------------------------------------
# Crash hooks
# ---------------------------------------------------------------------------

def test_crash_hooks_install_uninstall_and_freeze():
    transport = InMemoryTransport()
    flight.configure("miner", "m0", transport=transport)
    prev_hook = sys.excepthook
    flight.install_crash_hooks()
    assert flight.hooks_installed()
    assert sys.excepthook is not prev_hook
    try:
        raise RuntimeError("synthetic crash")
    except RuntimeError:
        et, ev, tb = sys.exc_info()
    # drive the installed hook directly (raising uncaught in pytest is
    # not an option); the default chain prints to stderr, which is fine
    sys.excepthook(et, ev, tb)
    fetched = flight.fetch_bundle(transport, "miner", "m0")
    assert fetched is not None and fetched["reason"] == "crash"
    assert fetched["crash"]["type"] == "RuntimeError"
    assert "synthetic crash" in fetched["crash"]["message"]
    assert any(e["kind"] == "crash" for e in fetched["events"])
    flight.uninstall_crash_hooks()
    assert sys.excepthook is prev_hook
    assert not flight.hooks_installed()


def test_shutdown_freezes_on_exceptional_exit_only():
    transport = InMemoryTransport()
    flight.configure("server", "s0", transport=transport)
    flight.shutdown()                      # clean exit: no crash bundle
    assert flight.fetch_bundle(transport, "server", "s0") is None
    assert not flight.dirty()
    flight.configure("server", "s0", transport=transport)
    try:
        raise ValueError("died mid-round")
    except ValueError:
        flight.shutdown()                  # role-main finally semantics
    fetched = flight.fetch_bundle(transport, "server", "s0")
    assert fetched is not None and fetched["reason"] == "crash"
    assert not flight.dirty()              # shutdown also resets


# ---------------------------------------------------------------------------
# Publish-outcome events (engine/publish.py)
# ---------------------------------------------------------------------------

def test_publisher_records_ok_and_failed_outcomes():
    rec = flight.configure("miner", "m1")
    transport = InMemoryTransport()
    pub = DeltaPublisher(transport, "m1", report=_Report(),
                         publish_retry=FAST_RETRY, meta_retry=FAST_RETRY)
    assert pub.publish_now(_tree(1), None, "rev0", "m1-000001")

    class Dark(InMemoryTransport):
        def publish_delta(self, miner_id, payload):
            raise OSError("dark")

    pub2 = DeltaPublisher(Dark(), "m1", report=_Report(),
                          publish_retry=FAST_RETRY, meta_retry=FAST_RETRY)
    assert pub2.publish_now(_tree(2), None, "rev0", "m1-000002") is False
    evs = [e for e in rec.events() if e["kind"] == "publish"]
    assert [(e["outcome"], e["cid"]) for e in evs] == \
        [("ok", "m1-000001"), ("failed", "m1-000002")]


def test_torn_v2_publish_names_shard_progress():
    """A wire-v2 publish that dies between shards records a ``torn``
    event naming how far it got — the forensic needle of a mid-publish
    kill."""
    rec = flight.configure("miner", "m2")

    class DiesOnSecondShard(InMemoryTransport):
        def __init__(self):
            super().__init__()
            self.shards = 0

        def publish_shard(self, hotkey, layer_key, data):
            self.shards += 1
            if self.shards >= 2:
                raise OSError("killed mid-publish")
            self.publish_raw(tbase.shard_id(hotkey, layer_key), data)

    pub = DeltaPublisher(DiesOnSecondShard(), "m2", report=_Report(),
                         publish_retry=FAST_RETRY, meta_retry=FAST_RETRY,
                         wire_spec={"format": 2, "density": 1 / 64,
                                    "quant": "int8"})
    packed = jax.device_get(dl.pack_delta_v2(_tree(3), density=1 / 64)[0])
    assert pub.publish_now(packed, None, "rev0", "m2-000001") is False
    torn = [e for e in rec.events()
            if e["kind"] == "publish" and e["outcome"] == "torn"]
    assert len(torn) == 1
    assert torn[0]["shards_done"] == 1
    assert torn[0]["shards_total"] == 2
    assert torn[0]["manifest"] is False
    assert torn[0]["cid"] == "m2-000001"


# ---------------------------------------------------------------------------
# Lease / SLO / remediation attachment
# ---------------------------------------------------------------------------

def test_lease_transitions_recorded_and_lost_freezes():
    transport = InMemoryTransport()
    rec = flight.configure("averager", "a1", transport=transport)
    primary = LeaseManager(transport, "a1")
    assert primary.acquire()
    usurper = LeaseManager(transport, "a2")
    assert usurper.acquire()
    assert primary.renew() is False        # superseded -> lost + freeze
    actions = [(e["action"], e.get("holder"))
               for e in rec.events() if e["kind"] == "lease"]
    assert ("acquired", "a1") in actions
    assert ("lost", "a2") in actions
    fetched = flight.fetch_bundle(transport, "averager", "a1")
    assert fetched is not None and fetched["reason"] == "lease_lost"


def test_slo_breach_freezes_bundle_and_stamps_ledger():
    transport = InMemoryTransport()
    sink = InMemorySink()
    obs.configure(sink, role="averager")
    flight.configure("averager", "a0", transport=transport)
    fm = FleetMonitor(transport, metrics=sink,
                      rules=[SLORule("stale_node", "stale", threshold=1)])
    try:
        transport.publish_delta_meta(
            tbase.heartbeat_id("miner", "m0"),
            build_heartbeat("miner", "m0", 1, now=1.0, steps=1.0))
        assert fm.poll(["m0"]) == 1
        for _ in range(3):                 # rounds advance, m0 silent
            fm.poll(["m0"])
        breaches = fm.evaluate_slos()
        assert len(breaches) == 1
        ref = breaches[0]["pm_ref"]
        assert ref
        assert fm.ledger()["miner/m0"]["pm_ref"] == ref
        fetched = flight.fetch_bundle(transport, "averager", "a0")
        assert fetched is not None
        assert fetched["bundle_id"] == ref
        assert fetched["reason"] == "slo_stale_node"
        slo_evs = [e for e in fetched["events"] if e["kind"] == "slo"]
        assert slo_evs and slo_evs[-1]["hotkey"] == "m0"
        # breach record mirrored to the sink with the reference
        logged = [r for r in sink.records if "slo_breach" in r]
        assert logged and logged[0]["pm_ref"] == ref
    finally:
        fm.close()


def test_remediation_attaches_breach_bundle_to_ledger():
    transport = InMemoryTransport()
    sink = InMemorySink()
    obs.configure(sink, role="validator")
    flight.configure("validator", "v0", transport=transport)
    fm = FleetMonitor(transport, metrics=sink,
                      rules=[SLORule("stale_node", "stale", threshold=1)])
    rem = RemediationEngine(
        fm, metrics=sink,
        policy=RemediationPolicy(quarantine_rules=("stale_node",)))
    try:
        transport.publish_delta_meta(
            tbase.heartbeat_id("miner", "m0"),
            build_heartbeat("miner", "m0", 1, now=1.0, steps=1.0))
        assert fm.poll(["m0"]) == 1
        for _ in range(3):
            fm.poll(["m0"])
        breaches = fm.evaluate_slos()
        actions = rem.observe_round(breaches)
        quar = [a for a in actions if a["remediation"] == "quarantined"]
        assert quar and quar[0]["pm_ref"] == breaches[0]["pm_ref"]
        assert fm.ledger()["miner/m0"]["pm_ref"] == quar[0]["pm_ref"]
        assert fm.ledger()["miner/m0"]["quarantined"] == 1
        rem_evs = [e for e in flight.recorder().events()
                   if e["kind"] == "remediation"]
        assert rem_evs and rem_evs[0]["action"] == "quarantined"
    finally:
        fm.close()


# ---------------------------------------------------------------------------
# Debug endpoints
# ---------------------------------------------------------------------------

def _get(port, path):
    with urllib.request.urlopen(
            f"http://127.0.0.1:{port}{path}") as resp:
        return resp.status, resp.read()


def test_debug_endpoints(tmp_path):
    from distributedtraining_tpu.utils.obs_http import ObsHTTPExporter
    sink = InMemorySink()
    obs.configure(sink, role="miner")
    transport = InMemoryTransport()
    rec = flight.configure("miner", "m0", transport=transport)
    rec.record("note", what="live")
    exp = ObsHTTPExporter(0, role="miner",
                          profile_dir=str(tmp_path / "prof"))
    port = exp.start()
    try:
        status, body = _get(port, "/debug/stacks")
        assert status == 200
        text = body.decode()
        assert "MainThread" in text or "obs-http" in text
        status, body = _get(port, "/debug/dump")
        assert status == 200
        bundle = json.loads(body)
        assert bundle["reason"] == "debug_dump"
        assert any(e["kind"] == "note" for e in bundle["events"])
        # ?publish=1 ships it through the transport too
        status, body = _get(port, "/debug/dump?publish=1")
        assert status == 200
        assert flight.fetch_bundle(transport, "miner", "m0") is not None
        status, body = _get(port, "/debug/profile?ms=40")
        assert status == 200
        info = json.loads(body)
        assert info["ms"] == pytest.approx(40.0)
        assert os.path.isdir(info["trace_dir"])
        assert flight.live_profile_sessions() == []
    finally:
        exp.close()


def test_debug_dump_without_recorder_is_503():
    from distributedtraining_tpu.utils.obs_http import ObsHTTPExporter
    exp = ObsHTTPExporter(0, role="miner")
    port = exp.start()
    try:
        with pytest.raises(urllib.error.HTTPError) as e:
            _get(port, "/debug/dump")
        assert e.value.code == 503
    finally:
        exp.close()


def test_capture_profile_rejects_concurrent_sessions(tmp_path):
    import distributedtraining_tpu.utils.flight as fl

    start = threading.Event()
    release = threading.Event()

    def slow_sleep(_s):
        start.set()
        release.wait(5.0)

    result = {}

    def runner():
        result["info"] = fl.capture_profile(str(tmp_path / "p1"), 5,
                                            sleep=slow_sleep)

    t = threading.Thread(target=runner)
    t.start()
    assert start.wait(5.0)
    assert len(fl.live_profile_sessions()) == 1
    with pytest.raises(RuntimeError, match="already running"):
        fl.capture_profile(str(tmp_path / "p2"), 5)
    release.set()
    t.join(5.0)
    assert result["info"]["trace_dir"].endswith("p1")
    assert fl.live_profile_sessions() == []


# ---------------------------------------------------------------------------
# JSONL retention sweep (satellite)
# ---------------------------------------------------------------------------

def test_jsonl_retention_sweep_on_open(tmp_path):
    path = str(tmp_path / "m.jsonl")
    for n in range(1, 7):                  # stale segments of an old run
        with open(f"{path}.{n}", "w") as f:
            f.write("{}\n")
    sink_obs = InMemorySink()
    obs.configure(sink_obs, role="miner")
    sink = JSONLSink(path, max_bytes=1 << 20, keep_segments=2)
    try:
        assert os.path.exists(f"{path}.6")  # lazy: nothing swept yet
        sink.log({"a": 1})                  # first record opens + sweeps
        assert sink.segments_pruned == 4
        assert os.path.exists(f"{path}.1") and os.path.exists(f"{path}.2")
        for n in range(3, 7):
            assert not os.path.exists(f"{path}.{n}")
        assert obs.registry().counter("obs.segments_pruned").value == 4
    finally:
        sink.close()


def test_jsonl_retention_override_and_validation(tmp_path):
    path = str(tmp_path / "m.jsonl")
    for n in range(1, 5):
        with open(f"{path}.{n}", "w") as f:
            f.write("{}\n")
    sink = JSONLSink(path, keep_segments=1, retention_segments=3)
    try:
        sink.log({"a": 1})
        assert sink.segments_pruned == 1    # only .4 fell outside 3
        assert os.path.exists(f"{path}.3")
        assert not os.path.exists(f"{path}.4")
    finally:
        sink.close()
    with pytest.raises(ValueError):
        JSONLSink(path, retention_segments=0)


# ---------------------------------------------------------------------------
# The acceptance round: chaos kill mid-publish -> fetchable forensics
# ---------------------------------------------------------------------------

def test_chaos_forensics_round_end_to_end(tmp_path):
    """A miner is chaos-killed mid-(wire-v2)-publish; its crash handler
    ships a postmortem bundle once the transport briefly heals (the
    supervisor's last gasp). The averager's SLO engine then breaches
    stale_node and freezes ITS bundle. scripts/postmortem.py must
    reconstruct one causal timeline from the two bundles + two JSONL
    streams: the torn publish is named with its shard progress and cid,
    the SLO rule that fired is named against the dead miner, and at
    least one cid joins events from both roles."""
    art = str(tmp_path / "artifacts")
    miner_jsonl = str(tmp_path / "miner.jsonl")
    avg_jsonl = str(tmp_path / "averager.jsonl")
    plain = LocalFSTransport(art)

    # ---- phase 1: the miner publishes a healthy v2 delta + heartbeat
    miner_sink = JSONLSink(miner_jsonl)
    obs.configure(miner_sink, role="miner")
    rec_m = flight.configure("miner", "m0", transport=plain)
    pub1 = DeltaPublisher(plain, "m0", report=_Report(),
                          publish_retry=FAST_RETRY, meta_retry=FAST_RETRY,
                          wire_spec={"format": 2, "density": 1 / 64,
                                     "quant": "int8"})
    packed1 = jax.device_get(dl.pack_delta_v2(_tree(1), density=1 / 64)[0])
    assert pub1.publish_now(packed1, None, None, "m0-000001")
    plain.publish_delta_meta(
        tbase.heartbeat_id("miner", "m0"),
        build_heartbeat("miner", "m0", 1, now=1.0, steps=10.0))

    # ---- phase 2: the next publish is killed between shard 1 and
    # shard 2 (each shard publish is one chaos op; the op schedule kills
    # the role at op 2 and revives it at op 4 — the window in which the
    # crash handler's bundle publish slips out)
    chaos_m = ChaosTransport(
        LocalFSTransport(art), role="miner",
        schedule=[ChaosEvent(2, "kill_role", "miner"),
                  ChaosEvent(4, "revive_role", "miner")])
    rec_m.transport = chaos_m
    pub2 = DeltaPublisher(chaos_m, "m0", report=_Report(),
                          publish_retry=FAST_RETRY, meta_retry=FAST_RETRY,
                          wire_spec={"format": 2, "density": 1 / 64,
                                     "quant": "int8"})
    packed2 = jax.device_get(dl.pack_delta_v2(_tree(2), density=1 / 64)[0])
    assert pub2.publish_now(packed2, None, None, "m0-000002") is False
    torn = [e for e in rec_m.events()
            if e["kind"] == "publish" and e["outcome"] == "torn"]
    assert torn and torn[0]["shards_done"] == 1 \
        and torn[0]["cid"] == "m0-000002"
    # the "process dies": role-main finally freezes the crash bundle,
    # whose publish rides op 4 — the revive — onto the shared store
    try:
        raise RuntimeError("miner chaos-killed mid-publish")
    except RuntimeError:
        flight.shutdown()
    obs.reset()
    miner_sink.close()
    miner_bundle = flight.fetch_bundle(plain, "miner", "m0")
    assert miner_bundle is not None, \
        "chaos-killed miner left no Transport-fetchable postmortem"
    assert miner_bundle["reason"] == "crash"
    assert any(e["kind"] == "publish" and e.get("outcome") == "torn"
               for e in miner_bundle["events"])

    # ---- phase 3: the averager's rounds observe the death
    avg_sink = JSONLSink(avg_jsonl)
    obs.configure(avg_sink, role="averager")
    chaos_a = ChaosTransport(LocalFSTransport(art), role="averager")
    flight.configure("averager", "a0", transport=chaos_a)
    fm = FleetMonitor(chaos_a, metrics=avg_sink,
                      rules=[SLORule("stale_node", "stale", threshold=1)])
    rem = RemediationEngine(
        fm, metrics=avg_sink,
        policy=RemediationPolicy(quarantine_rules=("stale_node",)))
    try:
        # round 1 sees the last heartbeat; the later rounds see silence.
        # stage_one-style fetches tag avg spans with the rider's cid
        # (still m0-000001: the torn publish never committed a manifest
        # or rider — manifest-last kept readers consistent)
        assert fm.poll(["m0"]) == 1
        with obs.span("avg.fetch", cid=obs.fetch_cid(chaos_a, "m0"),
                      miner="m0"):
            assert chaos_a.fetch_delta_bytes("m0") is not None
        for _ in range(3):
            fm.poll(["m0"])
        breaches = fm.evaluate_slos()
        assert [b["slo_breach"] for b in breaches] == ["stale_node"]
        actions = rem.observe_round(breaches)
        assert actions and actions[0]["remediation"] == "quarantined"
        assert fm.ledger()["miner/m0"]["pm_ref"] == breaches[0]["pm_ref"]
        fm.flush(avg_sink)
        obs.flush(avg_sink)
    finally:
        fm.close()
        flight.reset()
        obs.reset()
        avg_sink.close()
    avg_bundle = flight.fetch_bundle(plain, "averager", "a0")
    assert avg_bundle is not None
    assert avg_bundle["reason"] == "slo_stale_node"

    # ---- phase 4: scripts/postmortem.py reconstructs the timeline
    rep = postmortem.report(
        [miner_jsonl, avg_jsonl]
        + sorted(__import__("glob").glob(
            os.path.join(art, "deltas", "__pm__*"))))
    assert {"miner", "averager"} <= set(rep["roles"])
    assert len(rep["bundles"]) == 2
    # the torn publish is named, with its cid and shard progress
    torn = [e for e in rep["torn_publishes"] if e.get("outcome") == "torn"]
    assert torn, rep["torn_publishes"]
    assert torn[0]["cid"] == "m0-000002"
    assert torn[0]["shards_done"] == 1 and torn[0]["shards_total"] == 2
    assert torn[0]["source"] == "miner/m0"
    # the SLO rule that fired is named against the dead miner
    slo = [e for e in rep["slo_fired"] if e.get("rule") == "stale_node"
           or e.get("hotkey") == "m0"]
    assert slo, rep["slo_fired"]
    # >= 2 roles join on one cid: the miner's healthy publish and the
    # averager's fetch of that same artifact share m0-000001
    assert "m0-000001" in rep["joined_cids"], rep["joined_cids"]
    sources = rep["joined_cids"]["m0-000001"]
    assert any(s.startswith("miner/") for s in sources)
    assert any(s.startswith("averager/") for s in sources)
    # the timeline is time-ordered and spans both roles
    ts = [e["t"] for e in rep["timeline"]]
    assert ts == sorted(ts)
    # --json CLI spelling works end to end
    out = str(tmp_path / "pm.json")
    assert postmortem.main(["--work-dir", str(tmp_path), "--json",
                            "--out", out]) == 0
    with open(out) as f:
        rep2 = json.load(f)
    assert rep2["torn_publishes"] and rep2["slo_fired"]
