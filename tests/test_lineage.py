"""Lineage & contribution-attribution observatory (engine/lineage.py +
the __lineage__ reserved transport namespace + scripts/lineage_report).

The pins here are the audit contract: a record's content address
round-trips build -> publish -> fetch -> parse unchanged; the replay
audit re-derives a multi-miner (hierarchical, mixed v1+v2 wire) merged
revision with parity <= 1e-6 from nothing but the record + the store;
and every hostile case — a tampered record, a torn record, a drifted
contribution, a republished (mismatched) base — fails LOUDLY
(LineageError / lineage_report exit 2), never silently. Credit and
drift are pinned on constructed rounds with known answers.
"""

import json
import os
from types import SimpleNamespace

import jax
import numpy as np
import pytest

from distributedtraining_tpu import delta as dl
from distributedtraining_tpu.engine import lineage as lin
from distributedtraining_tpu.engine.average import (AveragerLoop,
                                                    GeneticMerge,
                                                    OuterOptMerge,
                                                    ParameterizedMerge,
                                                    WeightedAverage)
from distributedtraining_tpu.engine.hier_average import SubAverager
from distributedtraining_tpu.engine.publish import DeltaPublisher
from distributedtraining_tpu.transport import base as tbase
from distributedtraining_tpu.transport.chaos import ChaosSpec, ChaosTransport
from distributedtraining_tpu.transport.localfs import LocalFSTransport
from distributedtraining_tpu.transport.memory import InMemoryTransport
from distributedtraining_tpu.transport.retry import RetryPolicy

FAST_RETRY = RetryPolicy(attempts=2, base_delay=0.0, max_delay=0.0,
                         jitter=0.0)


def _tree(seed=0, big=(300, 40), small=(32,)):
    rs = np.random.RandomState(seed)
    return {"wte": (rs.randn(*big) * 0.01).astype(np.float32),
            "ln": {"g": (rs.randn(*small) * 0.01).astype(np.float32)}}


def _template(tree=None):
    return jax.tree_util.tree_map(
        lambda x: np.zeros(np.shape(x), np.float32), tree or _tree())


def _record(**over):
    kw = dict(kind="base", node="avg", revision="rev2", parent="rev1",
              round_no=3,
              contributions=[{"hotkey": "m0", "rev": "d0", "cid": "c0",
                              "weight": 0.25, "wire_bytes": 100,
                              "verdict": "ok", "score": 1.0},
                             {"hotkey": "m1", "rev": "d1", "cid": "c1",
                              "weight": 0.75, "wire_bytes": 0,
                              "verdict": "ok", "score": 3.0}],
              loss=1.5, parent_loss=1.6, now=123.0)
    kw.update(over)
    return lin.build_record(**kw)


# ---------------------------------------------------------------------------
# Record schema: build/parse/digest round trip
# ---------------------------------------------------------------------------

def test_record_digest_roundtrips_through_publish_and_parse():
    rec = _record()
    assert rec["record_id"] == lin.record_digest(rec)
    # the wall-clock stamp is outside the content address
    assert lin.record_digest(dict(rec, t=999.0)) == rec["record_id"]
    # byte round trip through parse preserves the digest
    parsed = lin.parse_record(json.dumps(rec, default=float).encode())
    assert parsed is not None
    assert lin.record_digest(parsed) == rec["record_id"]
    assert parsed["contributions"][0]["cid"] == "c0"
    assert parsed["parent"] == "rev1"


def test_parse_record_rejects_hostile_shapes():
    good = _record()
    hostile = [
        b"not json", b"[]", b"{}",
        json.dumps({**good, "lineage": 0}, default=float).encode(),
        json.dumps({**good, "kind": "evil"}, default=float).encode(),
        json.dumps({**good, "revision": ""}, default=float).encode(),
        json.dumps({**good, "contributions": "x"},
                   default=float).encode(),
        json.dumps({**good, "contributions": [{"weight": 1.0}]},
                   default=float).encode(),    # contribution sans hotkey
        b"{" * 100,                            # torn JSON
        b"x" * (lin.LINEAGE_MAX_BYTES + 1),    # oversized
    ]
    for data in hostile:
        assert lin.parse_record(data) is None, data[:40]


def test_lineage_id_is_reserved_and_injective():
    rid = tbase.lineage_id("abc123")
    assert tbase.is_lineage_id(rid)
    assert tbase.is_reserved_id(rid)
    # revisions with separator chars cannot collide
    assert tbase.lineage_id("a/b.c") != tbase.lineage_id("a.b/c")


def test_fetch_record_roundtrip_and_walk_chain():
    transport = InMemoryTransport()
    r1 = _record(revision="rev1", parent=None, contributions=[],
                 round_no=0, strategy="genesis", replayable=False)
    r2 = _record(revision="rev2", parent="rev1")
    assert lin.publish_record(transport, r1)
    assert lin.publish_record(transport, r2)
    got = lin.fetch_record(transport, "rev2")
    assert got["record_id"] == r2["record_id"]
    chain = lin.walk_chain(transport, "rev2")
    assert [r["revision"] for r in chain] == ["rev2", "rev1"]
    assert chain[-1]["parent"] is None
    assert lin.fetch_record(transport, "ghost") is None


def test_fetch_record_raises_loudly_on_tamper_and_torn():
    transport = InMemoryTransport()
    rec = _record()
    assert lin.publish_record(transport, rec)
    rid = tbase.lineage_id(rec["revision"])
    # tamper: a flipped weight keeps the JSON valid but breaks the
    # content address
    doc = json.loads(transport.fetch_delta_bytes(rid))
    doc["contributions"][0]["weight"] = 0.99
    transport.publish_raw(rid, json.dumps(doc).encode())
    with pytest.raises(lin.LineageError, match="tampered|content"):
        lin.fetch_record(transport, rec["revision"])
    # torn: truncated bytes are present-but-unparseable, also loud
    transport.publish_raw(
        rid, json.dumps(rec, default=float).encode()[:40])
    with pytest.raises(lin.LineageError, match="torn"):
        lin.fetch_record(transport, rec["revision"])


# ---------------------------------------------------------------------------
# Strategy weight declarations (what makes a record replayable)
# ---------------------------------------------------------------------------

def test_strategy_lineage_weight_declarations():
    w = np.asarray([0.25, 0.75], np.float32)
    got, kind = lin.resolve_weights(WeightedAverage(), w, 2)
    assert kind == "merge" and got == [0.25, 0.75]
    got, kind = lin.resolve_weights(GeneticMerge(), w, 2)
    assert kind == "merge" and got == [0.25, 0.75]
    # scalar meta-learned weights replay through the softmax
    strat = ParameterizedMerge(None, per_tensor=False)
    got, kind = lin.resolve_weights(strat, np.zeros(2, np.float32), 2)
    assert kind == "merge"
    np.testing.assert_allclose(got, [0.5, 0.5])
    # per-tensor and outer-momentum merges are attribution-only
    assert lin.resolve_weights(ParameterizedMerge(None, per_tensor=True),
                               w, 2) == (None, "opaque")
    assert lin.resolve_weights(OuterOptMerge(WeightedAverage()),
                               w, 2) == (None, "opaque")
    # a strategy without the hook is opaque, never an error
    assert lin.resolve_weights(object(), w, 2) == (None, "opaque")
    # shape/NaN mismatches resolve opaque instead of recording garbage
    assert lin.resolve_weights(WeightedAverage(), w, 3) == (None, "opaque")
    assert lin.resolve_weights(
        WeightedAverage(), np.asarray([np.nan, 1.0]), 2) == (None, "opaque")


# ---------------------------------------------------------------------------
# Quality-drift detector
# ---------------------------------------------------------------------------

def test_drift_detector_quiet_on_converging_loss():
    det = lin.QualityDriftDetector()
    for i in range(20):
        assert det.update(2.0 * (0.9 ** i)) is None
    assert det.breaches == 0


def test_drift_detector_fires_on_sustained_regression_and_rearms():
    det = lin.QualityDriftDetector(alpha=0.25, slack=0.02, threshold=0.25)
    for _ in range(5):
        det.update(1.0)
    fired = None
    for i in range(1, 20):
        fired = det.update(1.0 + 0.12 * i)
        if fired is not None:
            break
    assert fired is not None and fired["reason"] == "quality_drift"
    assert det.breaches == 1
    # the CUSUM resets on fire: a PERSISTING drift fires again
    again = None
    for i in range(20, 40):
        again = det.update(1.0 + 0.12 * i)
        if again is not None:
            break
    assert again is not None
    assert det.breaches == 2


def test_drift_detector_nonfinite_loss_breaches_immediately():
    det = lin.QualityDriftDetector()
    det.update(1.0)
    fired = det.update(float("nan"))
    assert fired is not None and fired["reason"] == "nonfinite_loss"


# ---------------------------------------------------------------------------
# Credit attribution
# ---------------------------------------------------------------------------

def _scored(rows):
    return [SimpleNamespace(hotkey=h, loss=l, score=s) for h, l, s in rows]


def test_loo_credits_weighted_by_normalized_scores():
    # base 2.0: m0 improved by 0.5 at weight 1/4, m1 by 0.1 at 3/4,
    # m2 worsened (negative credit), zero-score rows weigh nothing
    credits = lin.loo_credits(2.0, _scored([
        ("m0", 1.5, 1.0), ("m1", 1.9, 3.0), ("m2", 2.4, 0.0)]))
    np.testing.assert_allclose(credits["m0"], 0.25 * 0.5)
    np.testing.assert_allclose(credits["m1"], 0.75 * 0.1)
    np.testing.assert_allclose(credits["m2"], 0.0 * -0.4)
    # no base loss / no finite candidate losses -> no attribution
    assert lin.loo_credits(None, _scored([("m0", 1.0, 1.0)])) == {}
    assert lin.loo_credits(2.0, _scored([("m0", None, 1.0)])) == {}
    # all-zero scores fall back to uniform (the consensus rule)
    uniform = lin.loo_credits(2.0, _scored([("a", 1.0, 0.0),
                                            ("b", 3.0, 0.0)]))
    np.testing.assert_allclose(uniform["a"], 0.5)
    np.testing.assert_allclose(uniform["b"], -0.5)


def test_credit_ledger_one_estimate_per_revision():
    ledger = lin.CreditLedger(max_revisions=2)
    ledger.update("r1", 2.0, _scored([("m0", 1.0, 1.0)]))
    # re-validating the SAME revision replaces, never double-counts
    ledger.update("r1", 2.0, _scored([("m0", 1.5, 1.0)]))
    np.testing.assert_allclose(ledger.totals()["m0"], 0.5)
    # a new revision accumulates
    ledger.update("r2", 2.0, _scored([("m0", 1.5, 1.0)]))
    np.testing.assert_allclose(ledger.totals()["m0"], 1.0)
    # eviction settles old revisions into the totals (cumulative ledger)
    ledger.update("r3", 2.0, _scored([("m0", 1.9, 1.0)]))
    assert ledger.revisions() == ["r2", "r3"]
    np.testing.assert_allclose(ledger.totals()["m0"], 1.1)


def test_fleet_ledger_credit_reaches_exporter_as_dt_lineage_credit():
    from distributedtraining_tpu.engine.health import FleetMonitor
    from distributedtraining_tpu.utils import obs_http

    transport = InMemoryTransport()
    fm = FleetMonitor(transport, workers=1)
    try:
        fm.record_staging([SimpleNamespace(hotkey="m0", revision="d0",
                                           delta={}, reason="accepted",
                                           wire_bytes=10)])
        fm.record_credit({"m0": 0.125, "ghost": 0.0})
        led = fm.ledger()
        assert led["miner/m0"]["credit"] == 0.125
        assert "miner/ghost" not in led     # zero-credit never-seen
        body = obs_http.render(registry=None, fleet=fm)
        assert 'dt_lineage_credit{role="miner",hotkey="m0"} 0.125' in body
    finally:
        fm.close()


# ---------------------------------------------------------------------------
# Averager loop: record publication + replay audit (the acceptance pin)
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def engine_cfg():
    """ONE shared mini-GPT2 engine for every averager-round test in
    this module: the rounds only need a real evaluate() + wire
    templates, and sharing the instance shares its jitted programs —
    the per-test cost is the round, not a fresh compile set."""
    from distributedtraining_tpu.engine import TrainEngine
    from distributedtraining_tpu.models import gpt2

    model, cfg = gpt2.make_model(gpt2.GPT2Config(
        vocab_size=128, n_positions=64, n_embd=32, n_head=2, n_layer=2))
    return TrainEngine(model, seq_len=16), cfg


def _eval_batches(cfg):
    rs = np.random.RandomState(0)
    batch = {"input_ids": rs.randint(0, cfg.vocab_size, (2, 16))
             .astype(np.int32)}

    def factory():
        return iter([batch])

    return factory


class _Chain:
    def __init__(self, hotkeys, consensus=None, my_hotkey="avg"):
        self.my_hotkey = my_hotkey
        self._hotkeys = list(hotkeys)
        self._consensus = dict(consensus or {})

    def sync(self):
        return SimpleNamespace(hotkeys=self._hotkeys + [self.my_hotkey])

    def consensus_scores(self):
        return dict(self._consensus)


def _publish_mixed_fleet(transport, template, base_rev):
    """Three miners: two dense v1, one packed v2 — with cids."""
    for i, h in enumerate(["m0", "m1"]):
        d = jax.tree_util.tree_map(
            lambda x, s=i: (0.01 * (s + 1)
                            * np.random.RandomState(s).randn(*np.shape(x))
                            ).astype(np.float32), template)
        transport.publish_delta(h, d)
        transport.publish_delta_meta(h, {"delta_id": f"cid-{h}",
                                         "base_revision": base_rev})
    raw = jax.tree_util.tree_map(
        lambda x: (0.03 * np.random.RandomState(7).randn(*np.shape(x))
                   ).astype(np.float32), template)
    packed, _ = dl.pack_delta_v2(raw, density=1 / 8)

    class _R:
        pushes = pushes_failed = pushes_superseded = 0

    pub = DeltaPublisher(transport, "m2", report=_R(),
                         publish_retry=FAST_RETRY, meta_retry=FAST_RETRY,
                         wire_spec={"format": 2, "density": 1 / 8,
                                    "quant": "int8"})
    try:
        assert pub.publish_now(jax.device_get(packed), None, base_rev,
                               cid="cid-m2")
    finally:
        pub.close()


def _averager(engine, transport, cfg, consensus, plane, *,
              strategy=None, **over):
    kw = dict(val_batches=_eval_batches(cfg), publish_policy="always",
              stale_deltas="skip", ingest_workers=1, lineage=plane)
    kw.update(over)
    return AveragerLoop(engine, transport,
                        _Chain(list(consensus), consensus),
                        strategy if strategy is not None
                        else WeightedAverage(), **kw)


def test_averager_round_publishes_replayable_record(tmp_path, engine_cfg):
    """ACCEPTANCE: a multi-miner mixed v1+v2 merge re-derives from its
    lineage record with parity <= 1e-6, and the record carries full cid
    coverage, the genesis parent link, and the staging facts."""
    from distributedtraining_tpu.engine.train import host_wire_template

    engine, cfg = engine_cfg
    template = host_wire_template(engine)
    consensus = {"m0": 1.0, "m1": 2.0, "m2": 3.0}
    transport = LocalFSTransport(str(tmp_path))
    plane = lin.LineagePlane(transport, node="avg")
    loop = _averager(engine, transport, cfg, consensus, plane)
    try:
        loop.bootstrap(rng=jax.random.PRNGKey(0))
        genesis = transport.base_revision()
        grec = lin.fetch_record(transport, genesis)
        assert grec["strategy"] == "genesis" and grec["parent"] is None
        parent_params = transport.fetch_base(template)[0]
        _publish_mixed_fleet(transport, template, genesis)
        assert loop.run_round() is True
        rev = transport.base_revision()
        assert rev != genesis
        rec = lin.fetch_record(transport, rev)
        assert rec["parent"] == genesis
        assert rec["replayable"] and rec["weights_kind"] == "merge"
        by_hotkey = {c["hotkey"]: c for c in rec["contributions"]}
        assert set(by_hotkey) == {"m0", "m1", "m2"}
        assert by_hotkey["m2"]["cid"] == "cid-m2"
        assert by_hotkey["m2"]["wire_bytes"] > 0
        np.testing.assert_allclose(
            [by_hotkey[h]["weight"] for h in ("m0", "m1", "m2")],
            [1 / 6, 2 / 6, 3 / 6], rtol=1e-6)
        res = lin.replay_record(transport, rec, template,
                                parent=parent_params)
        assert res.ok and res.max_abs_diff <= 1e-6
        # the JSONL-mirror-free DAG walk reaches the genesis root
        chain = lin.walk_chain(transport, rev)
        assert [r["revision"] for r in chain] == [rev, genesis]
    finally:
        loop.close()


def test_replay_fails_loudly_on_weight_tamper_and_cli_exit(tmp_path, engine_cfg):
    """A tampered record (weight flipped to shift credit) must fail
    fetch_record AND exit lineage_report --replay nonzero."""
    import importlib.util
    import sys

    from distributedtraining_tpu import serialization as ser
    from distributedtraining_tpu.engine.train import host_wire_template

    engine, cfg = engine_cfg
    template = host_wire_template(engine)
    store = str(tmp_path / "artifacts")
    transport = LocalFSTransport(store)
    plane = lin.LineagePlane(transport, node="avg")
    loop = _averager(engine, transport, cfg,
                     {"m0": 1.0, "m1": 2.0, "m2": 3.0}, plane)
    try:
        loop.bootstrap(rng=jax.random.PRNGKey(0))
        genesis = transport.base_revision()
        parent_params = transport.fetch_base(template)[0]
        parent_path = str(tmp_path / "parent.msgpack")
        ser.save_file(parent_params, parent_path)
        _publish_mixed_fleet(transport, template, genesis)
        assert loop.run_round() is True
        rev = transport.base_revision()

        spec = importlib.util.spec_from_file_location(
            "lineage_report", os.path.join(
                os.path.dirname(os.path.dirname(
                    os.path.abspath(__file__))),
                "scripts", "lineage_report.py"))
        lr = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(lr)
        sys.modules.setdefault("lineage_report", lr)
        # the honest record replays through the CLI (exit 0)
        assert lr.main(["--store", store, "--replay", rev,
                        "--parent", parent_path]) == 0

        # tamper the stored record: flip one weight, keep JSON valid
        rid = tbase.lineage_id(rev)
        doc = json.loads(transport.fetch_delta_bytes(rid))
        doc["contributions"][0]["weight"] = 0.999
        transport.publish_raw(rid, json.dumps(doc).encode())
        assert lr.main(["--store", store, "--replay", rev,
                        "--parent", parent_path]) == 2
    finally:
        loop.close()


def test_replay_fails_on_republished_base_and_drifted_contribution(
        tmp_path, engine_cfg):
    from distributedtraining_tpu.engine.train import host_wire_template

    engine, cfg = engine_cfg
    template = host_wire_template(engine)
    transport = LocalFSTransport(str(tmp_path))
    plane = lin.LineagePlane(transport, node="avg")
    loop = _averager(engine, transport, cfg, {"m0": 1.0, "m1": 2.0,
                                              "m2": 3.0}, plane)
    try:
        loop.bootstrap(rng=jax.random.PRNGKey(0))
        genesis = transport.base_revision()
        parent_params = transport.fetch_base(template)[0]
        _publish_mixed_fleet(transport, template, genesis)
        assert loop.run_round() is True
        rec = lin.fetch_record(transport, transport.base_revision())

        # a drifted contribution: m0 republished since the record froze
        transport.publish_delta("m0", jax.tree_util.tree_map(
            lambda x: np.ones(np.shape(x), np.float32), template))
        with pytest.raises(lin.LineageError, match="drifted"):
            lin.replay_record(transport, rec, template,
                              parent=parent_params)

        # a republished (mismatched) base: the store no longer names the
        # recorded revision — loud, never a silent compare
        transport.publish_base(jax.tree_util.tree_map(
            lambda x: np.zeros(np.shape(x), np.float32), template))
        # restore m0 so the failure isolates to the base mismatch
        with pytest.raises(lin.LineageError):
            lin.replay_record(transport, rec, template,
                              parent=parent_params)
    finally:
        loop.close()


def test_opaque_strategy_records_are_attribution_only(tmp_path, engine_cfg):
    """OuterOptMerge publishes a NON-linear base: the record must say so
    (replayable False) and the replay audit must refuse, not produce a
    wrong parity number."""
    from distributedtraining_tpu.engine.train import host_wire_template

    engine, cfg = engine_cfg
    template = host_wire_template(engine)
    transport = LocalFSTransport(str(tmp_path))
    plane = lin.LineagePlane(transport, node="avg")
    loop = _averager(engine, transport, cfg, {"m0": 1.0, "m1": 2.0,
                                              "m2": 3.0}, plane,
                     strategy=OuterOptMerge(WeightedAverage()))
    try:
        loop.bootstrap(rng=jax.random.PRNGKey(0))
        genesis = transport.base_revision()
        _publish_mixed_fleet(transport, template, genesis)
        assert loop.run_round() is True
        rec = lin.fetch_record(transport, transport.base_revision())
        assert rec["replayable"] is False
        assert rec["weights_kind"] == "opaque"
        # contributions still carry the audit facts
        assert {c["hotkey"] for c in rec["contributions"]} \
            == {"m0", "m1", "m2"}
        with pytest.raises(lin.LineageError, match="not replayable"):
            lin.replay_record(transport, rec, template,
                              parent=transport.fetch_base(template)[0])
    finally:
        loop.close()


def test_chaos_transport_gates_lineage_records_without_raising():
    """ChaosTransport case: the reserved __lineage__ surface is gated
    like every artifact — a publish fault degrades to the JSONL mirror
    (False, counted), a fetch fault reads as None (counted) — and the
    caller never sees an exception from the plane's public entries."""
    inner = InMemoryTransport()
    rec = _record()
    dead = ChaosTransport(inner, ChaosSpec(publish_error_rate=1.0),
                          role="avg")
    assert lin.publish_record(dead, rec) is False
    assert lin.fetch_record(inner, rec["revision"]) is None  # never landed
    assert lin.publish_record(inner, rec) is True
    blind = ChaosTransport(inner, ChaosSpec(fetch_error_rate=1.0),
                           role="avg")
    assert lin.fetch_record(blind, rec["revision"]) is None  # fault, quiet
    got = lin.fetch_record(inner, rec["revision"])           # store intact
    assert got["record_id"] == rec["record_id"]


def test_lineage_publish_failure_is_isolated_from_the_round(tmp_path, engine_cfg):
    """ChaosTransport case: every lineage publish faults; the merge
    round still completes and publishes the base, the plane counts the
    failure, and the record survives in the metrics-sink mirror."""
    from distributedtraining_tpu.engine.train import host_wire_template

    engine, cfg = engine_cfg
    template = host_wire_template(engine)
    inner = LocalFSTransport(str(tmp_path))

    class _LineageChaos:
        """Faults exactly the reserved __lineage__ publishes."""

        def __init__(self, inner):
            self._inner = inner

        def publish_delta_raw(self, artifact_id, data):
            if tbase.is_lineage_id(artifact_id):
                raise OSError("injected lineage publish fault")
            return self._inner.publish_raw(artifact_id, data)

        def __getattr__(self, name):
            return getattr(self._inner, name)

    transport = _LineageChaos(inner)
    plane = lin.LineagePlane(transport, node="avg")
    loop = _averager(engine, transport, cfg, {"m0": 1.0, "m1": 2.0,
                                              "m2": 3.0}, plane)
    try:
        loop.bootstrap(rng=jax.random.PRNGKey(0))
        genesis = inner.base_revision()
        _publish_mixed_fleet(inner, template, genesis)
        assert loop.run_round() is True          # the round survived
        rev = inner.base_revision()
        assert rev != genesis                    # base landed
        assert lin.fetch_record(inner, rev) is None   # record did not
        assert plane.records >= 1                # ...but was built
    finally:
        loop.close()


def test_signed_transport_envelopes_and_verifies_lineage_records(
        tmp_path):
    """SignedTransport case: records travel enveloped under the delta
    context (attributable provenance); a tampered envelope reads as
    absent/torn, never as a verified record."""
    pytest.importorskip("cryptography")
    from distributedtraining_tpu.transport.signed import SignedTransport
    from distributedtraining_tpu.utils.identity import Identity

    inner = InMemoryTransport()
    ident = Identity.generate()
    signed = SignedTransport(inner, identity=ident)
    rec = _record()
    assert lin.publish_record(signed, rec)
    got = lin.fetch_record(signed, rec["revision"])
    assert got["record_id"] == rec["record_id"]
    # raw bytes on the wire are an envelope, not naked JSON
    raw = inner.fetch_delta_bytes(tbase.lineage_id(rec["revision"]))
    assert lin.parse_record(raw) is None or raw[:1] != b"{"


# ---------------------------------------------------------------------------
# Hierarchical records
# ---------------------------------------------------------------------------

def test_subaverager_agg_record_replays_mixed_wire(tmp_path):
    """A sub-averager's "agg" record re-derives the published aggregate
    from a mixed v1+v2 slice — the hierarchical half of the acceptance
    pin (the root's "base" record is pinned above)."""
    template = _template()
    transport = LocalFSTransport(str(tmp_path))
    transport.publish_base(_tree(100))
    base_rev = transport.base_revision()
    transport.publish_delta("m0", _tree(1))

    class _R:
        pushes = pushes_failed = pushes_superseded = 0

    packed, _ = dl.pack_delta_v2(_tree(2), density=1 / 8)
    pub = DeltaPublisher(transport, "m1", report=_R(),
                         publish_retry=FAST_RETRY, meta_retry=FAST_RETRY,
                         wire_spec={"format": 2, "density": 1 / 8,
                                    "quant": "int8"})
    plane = lin.LineagePlane(transport, node="subavg.n0")
    sub = SubAverager(transport, "n0", template, ["m0", "m1"],
                      consensus={"m0": 1.0, "m1": 3.0},
                      retry_policy=FAST_RETRY, publish_retry=FAST_RETRY,
                      meta_retry=FAST_RETRY, ingest_workers=1,
                      lineage=plane)
    try:
        assert pub.publish_now(jax.device_get(packed), None, base_rev)
        assert sub.run_round() is True
        agg_rev = transport.delta_revision(tbase.agg_id("n0"))
        rec = lin.fetch_record(transport, agg_rev)
        assert rec["kind"] == "agg"
        assert rec["artifact"] == tbase.agg_id("n0")
        assert rec["parent"] == base_rev
        np.testing.assert_allclose(
            [c["weight"] for c in rec["contributions"]], [0.25, 0.75])
        res = lin.replay_record(transport, rec, template)
        assert res.ok and res.max_abs_diff <= 1e-6
    finally:
        sub.close()
        pub.close()


def test_root_record_marks_agg_contributions(tmp_path, engine_cfg):
    """In hier mode the root's record names the __agg__ artifacts (tier
    "agg") with the subtree weight masses — the DAG's middle level."""
    from distributedtraining_tpu.engine.hier_average import plan_fanout
    from distributedtraining_tpu.engine.train import host_wire_template

    engine, cfg = engine_cfg
    template = host_wire_template(engine)
    hotkeys = ["m0", "m1", "m2", "m3"]
    consensus = {h: float(i + 1) for i, h in enumerate(hotkeys)}
    transport = LocalFSTransport(str(tmp_path))
    plane = lin.LineagePlane(transport, node="avg")
    loop = AveragerLoop(
        engine, transport, _Chain(hotkeys, consensus), WeightedAverage(),
        val_batches=_eval_batches(cfg), publish_policy="always",
        stale_deltas="skip", ingest_workers=1,
        hierarchy=["n0", "n1"], lineage=plane)
    subs = []
    try:
        loop.bootstrap(rng=jax.random.PRNGKey(0))
        genesis = transport.base_revision()
        parent_params = transport.fetch_base(template)[0]
        for i, h in enumerate(hotkeys):
            transport.publish_delta(h, jax.tree_util.tree_map(
                lambda x, s=i: (0.01 * (s + 1) * np.random.RandomState(s)
                                .randn(*np.shape(x))).astype(np.float32),
                template))
        plan = plan_fanout(hotkeys, nodes=["n0", "n1"])
        for node, slice_ in plan.items():
            sub = SubAverager(
                transport, node, template, slice_, consensus=consensus,
                retry_policy=FAST_RETRY, publish_retry=FAST_RETRY,
                meta_retry=FAST_RETRY, ingest_workers=1,
                lineage=lin.LineagePlane(transport,
                                         node=f"subavg.{node}"))
            subs.append(sub)
            assert sub.run_round() is True
        assert loop.run_round() is True
        rec = lin.fetch_record(transport, transport.base_revision())
        assert rec["parent"] == genesis
        assert {c["hotkey"] for c in rec["contributions"]} \
            == {tbase.agg_id("n0"), tbase.agg_id("n1")}
        assert all(c.get("tier") == "agg" for c in rec["contributions"])
        # each agg contribution's own record exists: the DAG level below
        for c in rec["contributions"]:
            sub_rec = lin.fetch_record(transport, c["rev"])
            assert sub_rec is not None and sub_rec["kind"] == "agg"
        # HIERARCHICAL replay (acceptance): the root's base record
        # re-derives the published base from the __agg__ artifacts at
        # the recorded per-subtree weights, parity <= 1e-6
        res = lin.replay_record(transport, rec, template,
                                parent=parent_params)
        assert res.ok and res.max_abs_diff <= 1e-6
    finally:
        for sub in subs:
            sub.close()
        loop.close()


# ---------------------------------------------------------------------------
# lineage_report report mode
# ---------------------------------------------------------------------------

def test_lineage_report_builds_dag_from_store_and_jsonl(tmp_path):
    import importlib.util
    import sys

    transport = LocalFSTransport(str(tmp_path / "artifacts"))
    r1 = _record(revision="rev1", parent=None, contributions=[],
                 round_no=0, strategy="genesis", replayable=False)
    r2 = _record(revision="rev2", parent="rev1")
    lin.publish_record(transport, r1)
    lin.publish_record(transport, r2)
    transport.publish_base(_tree(0))   # head probe target (any base)
    jsonl = tmp_path / "avg.jsonl"
    r3 = _record(revision="rev3", parent="rev2")   # history: mirror only
    jsonl.write_text(json.dumps({"lineage": r3}, default=float) + "\n")

    spec = importlib.util.spec_from_file_location(
        "lineage_report", os.path.join(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
            "scripts", "lineage_report.py"))
    lr = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(lr)
    rep = lr.build_report(transport, lr._load_jsonl_records([str(jsonl)]))
    revs = {r["revision"]: r for r in rep["revisions"]}
    assert set(revs) == {"rev1", "rev2", "rev3"}
    assert revs["rev2"]["source"] == "store"
    assert revs["rev3"]["source"] == "jsonl"
    assert rep["miners"]["m0"]["merges"] == 2
    text = lr.format_report(rep)
    assert "rev2" in text and "contribution rollup" in text
