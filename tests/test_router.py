"""Load-aware admission router (engine/router.py).

The policy layer is tested pure (no sockets): least-loaded choice,
revision preference, the overload -> shed verdict, and the Retry-After
estimate. The routed open-loop harness (utils/loadgen.py) then runs the
SAME policy over live engines, and one end-to-end test stands up two
real serving backends behind a :class:`RouterHTTPFrontend` and checks
routing parity plus the forced-shed 429.
"""

import json
import urllib.error
import urllib.request

import jax
import numpy as np
import pytest

from distributedtraining_tpu.engine.router import (BackendState,
                                                   RouterHTTPFrontend,
                                                   RouterPolicy)
from distributedtraining_tpu.engine.serve import (GenerationEngine,
                                                  ServeHTTPFrontend,
                                                  ServeLoop,
                                                  reference_generate)
from distributedtraining_tpu.models import gpt2
from distributedtraining_tpu.utils.loadgen import (OpenLoopSpec,
                                                   run_open_loop_routed)

TINY = gpt2.GPT2Config(vocab_size=128, n_positions=64, n_embd=32,
                       n_layer=2, n_head=2, dtype="float32",
                       vocab_multiple=64)


def _b(url, *, queue=0, active=0, ttft=0.0, tpot=0.0, rev=None,
       healthy=True, tps=0.0):
    return BackendState(url=url, healthy=healthy, queue_depth=queue,
                        active=active, ttft_ms_p95=ttft, tpot_ms_p95=tpot,
                        revision=rev, tokens_per_sec=tps)


# ---------------------------------------------------------------------------
# RouterPolicy (pure)
# ---------------------------------------------------------------------------

def test_policy_picks_least_loaded():
    pol = RouterPolicy(max_queue_depth=6)
    a = _b("http://a", queue=3, active=1)
    b = _b("http://b", queue=0, active=1)
    assert pol.choose([a, b]) is b


def test_policy_latency_breaks_queue_ties():
    """Equal outstanding work: the backend with the worse observed
    ttft/tpot p95 loses."""
    pol = RouterPolicy(max_queue_depth=6)
    slow = _b("http://a", queue=1, ttft=400.0)
    fast = _b("http://b", queue=1, ttft=20.0)
    assert pol.choose([slow, fast]) is fast


def test_policy_deterministic_url_tiebreak():
    pol = RouterPolicy(max_queue_depth=6)
    a = _b("http://a")
    b = _b("http://b")
    assert pol.choose([a, b]) is a
    assert pol.choose([b, a]) is a


def test_policy_sheds_when_all_overloaded():
    """Every live backend at the admission bound => None (the router
    turns that into 429 + Retry-After, never an unbounded queue)."""
    pol = RouterPolicy(max_queue_depth=4)
    backends = [_b("http://a", queue=3, active=1),
                _b("http://b", queue=4)]
    assert pol.choose(backends) is None
    assert pol.choose([]) is None
    assert pol.choose([_b("http://a", healthy=False)]) is None


def test_policy_ttft_shed_bound():
    pol = RouterPolicy(max_queue_depth=0, shed_ttft_ms=250.0)
    assert pol.choose([_b("http://a", ttft=300.0)]) is None
    assert pol.choose([_b("http://a", ttft=200.0)]) is not None


def test_policy_prefers_majority_revision():
    """Two backends on r2, one still serving r1: route to r2 — unless
    every r2 backend is overloaded, in which case the r1 straggler
    absorbs the request rather than shedding it."""
    pol = RouterPolicy(max_queue_depth=4)
    old = _b("http://old", rev="r1")
    new1 = _b("http://n1", rev="r2", queue=1)
    new2 = _b("http://n2", rev="r2", queue=2)
    assert pol.choose([old, new1, new2]) is new1
    # majority pool saturated: fall back to the off-revision backend
    new1.queue_depth = new2.queue_depth = 4
    assert pol.choose([old, new1, new2]) is old
    # preference off: pure least-loaded, revision ignored (old at
    # queue 0 beats both r2 backends at 1 and 2)
    flat = RouterPolicy(max_queue_depth=6, prefer_revision=False)
    new1.queue_depth, new2.queue_depth = 1, 2
    assert flat.choose([old, new1, new2]) is old
    assert pol.choose([old, new1, new2]) is new1    # preference on


def test_policy_retry_after_clamped():
    pol = RouterPolicy(max_queue_depth=2)
    assert pol.retry_after([]) == 1.0
    # huge backlog over a slow backend clamps at 30s
    assert pol.retry_after([_b("http://a", queue=500, tps=1.0)]) == 30.0
    assert pol.retry_after([_b("http://a", queue=1, tps=1e6)]) == 1.0


# ---------------------------------------------------------------------------
# Routed open loop (the fleetsim r04 harness)
# ---------------------------------------------------------------------------

def test_routed_open_loop_spreads_and_sheds():
    """Two tiny engines behind the policy at a rate one server cannot
    hold with a tight admission bound: every arrival is either routed
    or shed (conservation), both engines see work, and the admitted
    percentiles stay finite."""
    model, cfg = gpt2.make_model(TINY)
    params = model.init_params(jax.random.PRNGKey(0), seq_len=8)
    engines = [GenerationEngine(model, params, max_slots=2, page_size=8)
               for _ in range(2)]
    spec = OpenLoopSpec(rate_rps=400.0, duration_s=0.12, seed=3,
                        max_new_tokens=4, max_prompt_tokens=12)
    try:
        out = run_open_loop_routed(engines, spec, max_backend_queue=2)
    finally:
        for e in engines:
            e.close()
    assert out["router"] is True and out["servers"] == 2
    assert out["routed"] + out["shed"] == out["offered"]
    assert out["shed"] > 0                       # bound actually bit
    assert out["completed"] == out["routed"]     # admitted => finished
    assert np.isfinite(out["ttft_ms"]["p99"])
    # deterministic: same spec, fresh engines => byte-equal load point
    engines = [GenerationEngine(model, params, max_slots=2, page_size=8)
               for _ in range(2)]
    try:
        again = run_open_loop_routed(engines, spec, max_backend_queue=2)
    finally:
        for e in engines:
            e.close()
    assert again == out


# ---------------------------------------------------------------------------
# RouterHTTPFrontend (end to end over real backends)
# ---------------------------------------------------------------------------

@pytest.fixture()
def fleet():
    """Two live serving backends (engine + loop + HTTP frontend) and
    their base URLs; torn down frontends-first so the router's
    in-flight requests fail fast."""
    model, cfg = gpt2.make_model(TINY)
    params = model.init_params(jax.random.PRNGKey(0), seq_len=8)
    engines, loops, fes, urls = [], [], [], []
    for _ in range(2):
        eng = GenerationEngine(model, params, revision="r1", max_slots=2,
                               page_size=8)
        loop = ServeLoop(eng, idle_poll_s=0.02).start()
        fe = ServeHTTPFrontend(eng, 0, timeout_s=60.0)
        urls.append(f"http://127.0.0.1:{fe.start()}")
        engines.append(eng)
        loops.append(loop)
        fes.append(fe)
    try:
        yield model, params, urls
    finally:
        for fe in fes:
            fe.close()
        for loop in loops:
            loop.close()
        for eng in engines:
            eng.close()


def test_router_http_round_trip(fleet):
    model, params, urls = fleet
    router = RouterHTTPFrontend(urls, 0, poll_interval_s=30.0,
                                timeout_s=60.0)
    router.refresh()
    port = router.start()
    try:
        assert all(b.healthy for b in router.backends)
        prompt = [3, 1, 4, 1, 5]
        body = json.dumps({"tokens": prompt,
                           "max_new_tokens": 6}).encode()
        req = urllib.request.Request(
            f"http://127.0.0.1:{port}/generate", data=body,
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=30) as resp:
            out = json.loads(resp.read())
        assert out["tokens"] == reference_generate(model, params, prompt, 6)
        assert out["revision"] == "r1"
        assert router.routed == 1 and router.shed == 0
        # router's own healthz shows the fleet view
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/healthz", timeout=10) as resp:
            hz = json.loads(resp.read())
        assert hz["role"] == "router" and hz["routed"] == 1
        assert len(hz["backends"]) == 2
        assert all(b["revision"] == "r1" for b in hz["backends"])
    finally:
        router.close()


def test_router_http_shed_429(fleet):
    """Every backend reported at the admission bound: the router sheds
    with 429 + Retry-After WITHOUT forwarding to any backend."""
    _, _, urls = fleet
    router = RouterHTTPFrontend(
        urls, 0, policy=RouterPolicy(max_queue_depth=2),
        poll_interval_s=30.0, timeout_s=60.0)
    router.refresh()
    port = router.start()
    try:
        for b in router.backends:       # poisoned load picture
            b.queue_depth = 2
        body = json.dumps({"tokens": [1, 2, 3]}).encode()
        req = urllib.request.Request(
            f"http://127.0.0.1:{port}/generate", data=body,
            headers={"Content-Type": "application/json"})
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(req, timeout=10)
        assert ei.value.code == 429
        assert int(ei.value.headers["Retry-After"]) >= 1
        assert router.shed == 1 and router.routed == 0
    finally:
        router.close()


def test_router_retries_next_backend_on_failure(fleet):
    """First-choice backend gone (connection refused): the router
    retries the request on the next-best backend and the caller still
    gets a 200."""
    model, params, urls = fleet
    # a dead URL that the policy will rank FIRST (url tiebreak: the
    # bogus port sorts below the live ones only by luck, so pin scores)
    dead = "http://127.0.0.1:9"        # discard port: refused instantly
    router = RouterHTTPFrontend([dead] + urls, 0, poll_interval_s=30.0,
                                timeout_s=60.0)
    router.refresh()
    port = router.start()
    try:
        # refresh marks the dead backend unhealthy only after
        # unhealthy_after consecutive failures; force the interesting
        # case — dead backend believed healthy and least-loaded
        router.backends[0].healthy = True
        router.backends[0].queue_depth = 0
        prompt = [2, 7, 1]
        body = json.dumps({"tokens": prompt,
                           "max_new_tokens": 4}).encode()
        req = urllib.request.Request(
            f"http://127.0.0.1:{port}/generate", data=body,
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=30) as resp:
            out = json.loads(resp.read())
        assert out["tokens"] == reference_generate(model, params, prompt, 4)
        assert router.routed == 1
    finally:
        router.close()


def test_router_honors_backend_retry_after(fleet):
    """Satellite: a backend answering 429 with its own Retry-After gets
    that back-pressure honored (capped) BEFORE the next-best retry —
    the wait is observable via the patched sleep, the counter ticks,
    and the caller still gets a 200 from the second backend."""
    import http.server
    import threading

    from distributedtraining_tpu.utils import reqtrace

    seen_ids = []

    class _Shedding(http.server.BaseHTTPRequestHandler):
        def do_POST(self):
            seen_ids.append(self.headers.get(reqtrace.REQUEST_ID_HEADER))
            self.rfile.read(int(self.headers.get("Content-Length", 0)))
            body = json.dumps({"error": "overloaded"}).encode()
            self.send_response(429)
            self.send_header("Retry-After", "30")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def do_GET(self):    # /healthz for the poll sweep
            body = json.dumps({"role": "server", "queue_depth": 0,
                               "active": 0}).encode()
            self.send_response(200)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def log_message(self, *a):
            pass

    model, params, urls = fleet
    shed_srv = http.server.ThreadingHTTPServer(("127.0.0.1", 0), _Shedding)
    threading.Thread(target=shed_srv.serve_forever, daemon=True).start()
    shed_url = f"http://127.0.0.1:{shed_srv.server_address[1]}"
    router = RouterHTTPFrontend([shed_url] + urls, 0,
                                poll_interval_s=30.0, timeout_s=60.0,
                                retry_after_cap_s=0.05)
    waits = []
    router._sleep = waits.append
    router.refresh()
    try:
        # make the shedding backend the policy's first choice
        for b in router.backends:
            b.queue_depth = 0 if b.url == shed_url else 1
            b.healthy = True
            b.revision = "r1"    # else majority-revision ranks it last
        prompt = [9, 8, 7]
        body = json.dumps({"tokens": prompt,
                           "max_new_tokens": 4}).encode()
        rid = "rq-0123456789abcdef"
        code, out, hdrs = router._route(body, rid)
        assert code == 200
        assert out["tokens"] == reference_generate(model, params, prompt, 4)
        assert out["backend"] in urls            # retried next-best
        assert out["request_id"] == rid
        assert hdrs[reqtrace.REQUEST_ID_HEADER] == rid
        assert seen_ids == [rid]                 # id reached the backend
        # the backend's Retry-After (30s) honored but capped at 0.05s
        assert waits == [0.05]
        assert router.retry_after_honored == 1
        assert router.routed == 1 and router.shed == 0
    finally:
        router.close()
        shed_srv.shutdown()
        shed_srv.server_close()


def test_retry_after_cap_zero_disables_wait(fleet):
    """retry_after_cap_s=0: the back-pressure wait is off, the retry is
    immediate, the counter stays 0 (ops can disable the stall)."""
    _, _, urls = fleet
    router = RouterHTTPFrontend(urls, 0, poll_interval_s=30.0,
                                timeout_s=60.0, retry_after_cap_s=0.0)
    waits = []
    router._sleep = waits.append
    assert router.retry_after_cap_s == 0.0
    router.close()
    assert waits == [] and router.retry_after_honored == 0
