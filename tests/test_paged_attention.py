"""Fused paged-attention decode kernel (ops/paged_attention.py).

The correctness spine of round 20's serving half: the Pallas kernel
(run INTERPRETED here — tier-1 forces the CPU platform; the real-chip
variants live in tests_tpu/test_paged_attention_tpu.py) must match the
XLA gather+attend reference to 1e-6 at every shape class the engine
produces — GQA llama heads, ragged ``seq_lens``, page-boundary lengths,
trash-page-0 padded lanes — and the reference itself must match the
pre-kernel ``cached_attention`` spelling exactly, so the engine-level
greedy-parity pins (tests/test_serve.py) transfer to the kernel path.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributedtraining_tpu.ops import paged_attention as pa
from distributedtraining_tpu.ops.attention import cached_attention


def _case(B, Hq, Hkv, D, P, MP, lens, *, pool=None, seed=0,
          tables=None):
    rng = np.random.default_rng(seed)
    pool = pool or (1 + B * MP)
    q = jnp.asarray(rng.standard_normal((B, 1, Hq, D)), jnp.float32)
    kp = jnp.asarray(rng.standard_normal((pool, P, Hkv, D)), jnp.float32)
    vp = jnp.asarray(rng.standard_normal((pool, P, Hkv, D)), jnp.float32)
    kn = jnp.asarray(rng.standard_normal((B, 1, Hkv, D)), jnp.float32)
    vn = jnp.asarray(rng.standard_normal((B, 1, Hkv, D)), jnp.float32)
    if tables is None:
        tables = rng.integers(1, pool, (B, MP))
    pt = jnp.asarray(tables, jnp.int32)
    sl = jnp.asarray(lens, jnp.int32)
    return q, kp, vp, pt, sl, kn, vn


def _parity(args, atol=1e-6):
    out = pa.paged_decode_attention(*args, interpret=True)
    assert out is not None, "kernel declined a supported shape"
    ref = pa.paged_decode_reference(*args)
    err = float(jnp.max(jnp.abs(out - ref)))
    assert err < atol, f"kernel/reference divergence {err}"
    return out


# ---------------------------------------------------------------------------
# Kernel vs reference (interpret mode)
# ---------------------------------------------------------------------------

def test_kernel_matches_reference_gqa_ragged():
    """GQA llama heads (Hq=8 over Hkv=2) with ragged per-slot lengths —
    the llama serving shape class."""
    _parity(_case(3, 8, 2, 64, 8, 4, [13, 27, 5]))


def test_kernel_matches_reference_mha():
    """GPT-2 heads: Hkv == Hq (group size 1)."""
    _parity(_case(2, 4, 4, 32, 8, 4, [30, 2]))


def test_kernel_matches_reference_page_boundary_lengths():
    """Lengths at exact page multiples (0, P, MP*P-1): the mask edge
    sits on a DMA chunk edge; off-by-one here reads a dead page."""
    _parity(_case(4, 4, 2, 64, 8, 4, [0, 8, 16, 31]))


def test_kernel_matches_reference_multi_chunk():
    """MP > PAGES_PER_CHUNK: the online softmax crosses chunk
    boundaries (the grid's streaming dimension actually streams)."""
    assert 16 > pa.PAGES_PER_CHUNK
    _parity(_case(2, 4, 2, 64, 8, 16, [127, 64]))


def test_trash_page_zero_lanes():
    """Padded batch lanes: table all-zeros (the trash page), seq_len 0.
    The lane's output must be attention over ONLY its fresh token —
    trash-page garbage must not leak (the engine's dead-lane
    contract)."""
    q, kp, vp, pt, sl, kn, vn = _case(2, 4, 2, 64, 8, 4, [0, 0])
    # poison the trash page to make leakage loud
    kp = kp.at[0].set(1e3)
    vp = vp.at[0].set(1e3)
    pt = jnp.zeros_like(pt)
    out = _parity((q, kp, vp, pt, sl, kn, vn))
    # seq_len 0: softmax over the single fresh column = exactly v_new
    vn_heads = jnp.repeat(vn, 2, axis=2)     # broadcast kv -> q heads
    np.testing.assert_allclose(np.asarray(out), np.asarray(vn_heads),
                               atol=1e-6)


def test_kernel_under_jit():
    """The engine calls through jit: trace-time decline/accept must be
    stable and the jitted output identical to eager."""
    args = _case(2, 4, 2, 64, 8, 4, [13, 27])
    eager = pa.paged_decode_attention(*args, interpret=True)
    jitted = jax.jit(
        lambda *a: pa.paged_decode_attention(*a, interpret=True))(*args)
    np.testing.assert_allclose(np.asarray(eager), np.asarray(jitted),
                               atol=1e-6)


def test_kernel_declines_cleanly():
    """Off-TPU with no interpret override the kernel declines (tier-1
    production path is the XLA reference); multi-token queries decline
    everywhere (decode is one token per step)."""
    args = _case(2, 4, 2, 64, 8, 4, [13, 27])
    assert pa.paged_decode_attention(*args) is None      # CPU backend
    q, kp, vp, pt, sl, kn, vn = args
    q3 = jnp.concatenate([q, q, q], axis=1)
    assert pa.paged_decode_attention(q3, kp, vp, pt, sl, kn, vn,
                                     interpret=True) is None


# ---------------------------------------------------------------------------
# The reference vs the pre-kernel spelling (satellite: folded mask)
# ---------------------------------------------------------------------------

def _cached_attention_materialized_mask(q, k, v, ctx_lens):
    """The pre-round-20 cached_attention spelling: concatenated
    broadcast boolean mask + dot_product_attention — kept here as the
    oracle that the folded-iota rewrite changed no semantics."""
    from distributedtraining_tpu.ops.attention import \
        dot_product_attention
    B, Tq, _, _ = q.shape
    S = k.shape[1] - Tq
    ctx_valid = jnp.arange(S)[None, :] < ctx_lens[:, None]
    new_mask = jnp.tril(jnp.ones((Tq, Tq), bool))
    mask = jnp.concatenate(
        [jnp.broadcast_to(ctx_valid[:, None, :], (B, Tq, S)),
         jnp.broadcast_to(new_mask[None], (B, Tq, Tq))], axis=-1)
    return dot_product_attention(q, k, v, mask[:, None, :, :])


@pytest.mark.parametrize("Tq", [1, 3])
def test_cached_attention_folded_mask_matches_old_spelling(Tq):
    """The iota-compare mask fold is bit-for-bit the old concatenated
    mask: context valid below ctx_lens (0 and S included), trailing Tq
    causal among themselves and self-visible."""
    rng = np.random.default_rng(0)
    B, S, H, D = 3, 24, 2, 16
    q = jnp.asarray(rng.standard_normal((B, Tq, H, D)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((B, S + Tq, H, D)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((B, S + Tq, H, D)), jnp.float32)
    ctx_lens = jnp.asarray([0, 7, S], jnp.int32)
    new = cached_attention(q, k, v, ctx_lens)
    old = _cached_attention_materialized_mask(q, k, v, ctx_lens)
    np.testing.assert_array_equal(np.asarray(new), np.asarray(old))


def test_cached_attention_hlo_has_no_mask_concatenate():
    """The satellite's actual claim: the decode mask no longer exists
    as a concatenated broadcast buffer — no concatenate op over the
    mask shape in the lowered HLO (the k/v inputs still concatenate in
    the CALLER, not here)."""
    B, Tq, S, H, D = 4, 1, 64, 2, 16
    q = jnp.zeros((B, Tq, H, D), jnp.float32)
    k = jnp.zeros((B, S + Tq, H, D), jnp.float32)
    v = jnp.zeros((B, S + Tq, H, D), jnp.float32)
    lens = jnp.zeros((B,), jnp.int32)
    hlo = jax.jit(cached_attention).lower(q, k, v, lens).as_text()
    assert f"pred[{B},{Tq},{S + Tq}]" not in hlo


# ---------------------------------------------------------------------------
# Model wiring: the paged path is the gathered path, relocated
# ---------------------------------------------------------------------------

def test_model_kv_pages_matches_kv_ctx_gpt2():
    """One gpt2 decode step via the NEW kv_pages hook vs the legacy
    pre-gathered kv_ctx hook: same logits, same sown (k, v) — paging
    through the model is a memory-layout change, not a math change."""
    from distributedtraining_tpu.models import gpt2
    model, cfg = gpt2.make_model(gpt2.GPT2Config(
        vocab_size=64, n_positions=32, n_embd=32, n_layer=2, n_head=2,
        dtype="float32", vocab_multiple=64))
    params = model.init_params(jax.random.PRNGKey(0), seq_len=8)
    L, P, MP, B = cfg.n_layer, 8, 2, 2
    pool = 1 + B * MP
    rng = np.random.default_rng(1)
    kp = jnp.asarray(rng.standard_normal(
        (L, pool, P, cfg.n_head, cfg.head_dim)) * 0.1, jnp.float32)
    vp = jnp.asarray(rng.standard_normal(
        (L, pool, P, cfg.n_head, cfg.head_dim)) * 0.1, jnp.float32)
    tables = jnp.asarray(1 + np.arange(B * MP).reshape(B, MP), jnp.int32)
    seq_lens = jnp.asarray([5, 11], jnp.int32)
    tokens = jnp.asarray([[3], [7]], jnp.int32)

    paged, muts_p = model.apply(
        {"params": params}, tokens, position_ids=seq_lens[:, None],
        kv_pages=tuple((kp[i], vp[i]) for i in range(L)),
        page_tables=tables, kv_lens=seq_lens,
        sow_kv=True, mutable=["intermediates"])
    k_ctx = kp[:, tables].reshape(L, B, MP * P, cfg.n_head, cfg.head_dim)
    v_ctx = vp[:, tables].reshape(L, B, MP * P, cfg.n_head, cfg.head_dim)
    gathered, muts_g = model.apply(
        {"params": params}, tokens, position_ids=seq_lens[:, None],
        kv_ctx=tuple((k_ctx[i], v_ctx[i]) for i in range(L)),
        kv_lens=seq_lens, sow_kv=True, mutable=["intermediates"])
    np.testing.assert_allclose(np.asarray(paged), np.asarray(gathered),
                               atol=1e-6)
    for name in muts_p["intermediates"]:
        kp_s, vp_s = muts_p["intermediates"][name]["kv_cache"][0]
        kg_s, vg_s = muts_g["intermediates"][name]["kv_cache"][0]
        np.testing.assert_allclose(np.asarray(kp_s), np.asarray(kg_s),
                                   atol=1e-6)
        np.testing.assert_allclose(np.asarray(vp_s), np.asarray(vg_s),
                                   atol=1e-6)
