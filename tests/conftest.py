"""Test harness: force an 8-device virtual CPU mesh.

Multi-chip behavior (dp/fsdp/tp shardings, psum merges, ring attention) is
tested without TPU hardware by splitting the host CPU into 8 XLA devices —
the same technique the driver's dryrun uses. Must run before any JAX backend
initialization; the axon sitecustomize force-selects the TPU platform via
jax.config, so we override the config (env vars alone are not enough).
"""

import os
import sys

os.environ.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count=8")
if "--xla_force_host_platform_device_count" not in os.environ["XLA_FLAGS"]:
    os.environ["XLA_FLAGS"] += " --xla_force_host_platform_device_count=8"

import jax

jax.config.update("jax_platforms", "cpu")

# Session-scoped persistent compilation cache (the tier-1 budget lever,
# PR-19 satellite): dozens of test modules compile IDENTICAL tiny-model
# programs — the persistent cache keys on the HLO, so every repeat
# compile across modules deserializes instead of re-lowering (~30%
# suite-wide on this rig, measured on the engines/neurons subset).
# Exported via the ENVIRONMENT too, so subprocess tests (the
# multi-OS-process round, supervise) inherit the same cache. Role tests
# that point the cache elsewhere (neurons/common.enable_compile_cache)
# simply take over from their call onward, exactly as before.
import tempfile as _tempfile

_JAX_CACHE_DIR = _tempfile.mkdtemp(prefix="dt-test-jax-cache-")
os.environ.setdefault("JAX_COMPILATION_CACHE_DIR", _JAX_CACHE_DIR)
os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "0")
os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_ENTRY_SIZE_BYTES", "0")
jax.config.update("jax_compilation_cache_dir",
                  os.environ["JAX_COMPILATION_CACHE_DIR"])
for _knob, _val in (("jax_persistent_cache_min_compile_time_secs", 0.0),
                    ("jax_persistent_cache_min_entry_size_bytes", 0)):
    try:
        jax.config.update(_knob, _val)
    except (AttributeError, ValueError):  # pragma: no cover — jax drift
        pass

import atexit as _atexit
import shutil as _shutil

_atexit.register(_shutil.rmtree, _JAX_CACHE_DIR, True)

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import pytest  # noqa: E402


@pytest.fixture(autouse=True, scope="module")
def _no_leaked_nondaemon_threads():
    """Every background worker this framework spawns — data prefetch,
    stage_cohorts staging, the miner publication pipeline, async
    checkpoint saves — must be a DAEMON thread that its owner drains via
    flush()/close(): a leaked non-daemon worker blocks interpreter
    shutdown (CI hangs at 100% green). This guard asserts no test module
    leaves a NEW non-daemon thread running; threads that predate the
    module (pytest/jax internals) are exempt, and joiners get a grace
    window."""
    import threading
    import time as _time

    before = {t.ident for t in threading.enumerate()}
    yield
    deadline = _time.monotonic() + 5.0
    while True:
        leaked = [t for t in threading.enumerate()
                  if t.is_alive() and not t.daemon
                  and t.ident not in before]
        if not leaked:
            return
        if _time.monotonic() > deadline:
            raise AssertionError(
                f"test module leaked non-daemon threads: {leaked}")
        _time.sleep(0.05)


@pytest.fixture(autouse=True, scope="module")
def _no_leaked_ingest_pool():
    """Ingest-pool hygiene (the concurrent delta ingest, engine/ingest.py):
    its workers are DAEMON threads — invisible to the non-daemon guard
    above — named ``ingest-*`` and designed to idle out within ~2 s of
    their last job (or immediately on DeltaIngestor.close()). A worker
    still alive well past that means a wedged transport call or a pool
    whose owner never drained it; either way the module leaked live
    machinery into its successors. Daemon or not, fail the module."""
    import threading
    import time as _time

    yield
    deadline = _time.monotonic() + 6.0   # > IngestPool's 2 s idle timeout
    while True:
        leaked = [t for t in threading.enumerate()
                  if t.is_alive() and t.name.startswith("ingest-")]
        if not leaked:
            return
        if _time.monotonic() > deadline:
            raise AssertionError(
                f"test module left ingest pool threads alive: {leaked}; "
                "close() the DeltaIngestor (or its owning loop) in teardown")
        _time.sleep(0.05)


@pytest.fixture(autouse=True, scope="module")
def _no_leaked_health_plane():
    """Fleet-health-plane hygiene (engine/health.py + utils/obs_http.py):
    a HeartbeatPublisher's timer thread (named ``heartbeat-*``) and an
    ObsHTTPExporter's listening socket are long-lived background
    machinery that their owners must close() — a leaked timer keeps
    publishing into whatever transport the next module builds, and a
    leaked socket holds the port (and a serve thread) for the rest of
    the process. Force-clean so one offender cannot cascade, then fail
    the module."""
    import threading
    import time as _time

    yield
    from distributedtraining_tpu.utils import obs_http

    live = obs_http.live_exporters()
    for exp in live:
        exp.close()
    deadline = _time.monotonic() + 6.0
    while True:
        leaked = [t for t in threading.enumerate()
                  if t.is_alive() and t.name.startswith("heartbeat-")]
        if not leaked:
            break
        if _time.monotonic() > deadline:
            raise AssertionError(
                f"test module left heartbeat publisher threads alive: "
                f"{leaked}; close() the HeartbeatPublisher (or the loop "
                "that owns it) in teardown")
        _time.sleep(0.05)
    assert not live, (
        f"test module left HTTP exporters serving: {live}; call "
        "ObsHTTPExporter.close() in teardown")


@pytest.fixture(autouse=True, scope="module")
def _no_leaked_localfs_tmp():
    """Shard-publish hygiene (the wire-v2 shard container rides the
    localfs transport's publish_raw): every localfs artifact write —
    deltas, bases, SHARDS, manifests, ``__agg__.*`` partial aggregates —
    must follow the tmp + fsync + rename discipline, so a ``*.tmp`` file
    still present after a module means a publish path died between the
    two steps (torn-publish debris) or bypassed the atomic write
    altogether. A leaked tmp from a mid-publish kill is exactly the
    artifact a reader must never decode; fail the module that produced
    it — and name aggregate debris separately, because a torn aggregate
    poisons a whole SUBTREE's contribution, not one miner's. Scans
    every transport root this process constructed (localfs.live_roots)."""
    yield
    import glob as _glob

    from distributedtraining_tpu.transport import localfs

    leaked = []
    for root in localfs.live_roots():
        for sub in ("deltas", "base"):
            leaked += _glob.glob(os.path.join(root, sub, "*.tmp"))
    agg_leaked = [p for p in leaked
                  if os.path.basename(p).startswith("__agg__")]
    for path in leaked:   # force-clean so one offender cannot cascade
        try:
            os.unlink(path)
        except OSError:
            pass
    assert not agg_leaked, (
        f"test module leaked partially-published AGGREGATE artifacts: "
        f"{agg_leaked}; a sub-averager publish (engine/hier_average.py) "
        "died between tmp write and rename")
    assert not leaked, (
        f"test module leaked partially-published artifact temp files: "
        f"{leaked}; localfs writes must go through the atomic "
        "tmp+fsync+rename path (serialization.save_file / _write_atomic)")


@pytest.fixture(autouse=True, scope="module")
def _no_leaked_subaverager_threads():
    """Hierarchy hygiene (engine/hier_average.py): a SubAverager owns an
    ingest pool (covered by the ingest guard above) AND a DeltaPublisher
    worker named ``publish-__agg__.*`` that blocks on its queue until
    close() — a leaked one keeps publishing aggregates into whatever
    transport the next module builds. Fail the module that left one
    alive; the owning test must call SubAverager.close() in teardown."""
    import threading
    import time as _time

    yield
    deadline = _time.monotonic() + 6.0
    while True:
        leaked = [t for t in threading.enumerate()
                  if t.is_alive() and (t.name.startswith("publish-__agg__")
                                       or t.name.startswith("subavg-"))]
        if not leaked:
            return
        if _time.monotonic() > deadline:
            raise AssertionError(
                f"test module left sub-averager threads alive: {leaked}; "
                "close() the SubAverager in teardown")
        _time.sleep(0.05)


@pytest.fixture(autouse=True, scope="module")
def _no_leaked_serving_plane():
    """Serving-plane hygiene (engine/serve.py): a GenerationEngine may
    own a base-revision watcher thread (``serve-watch``), a ServeLoop
    scheduler thread (``serve-loop``), and a ServeHTTPFrontend listening
    socket (``serve-http-*`` thread) — same long-lived background
    machinery as the heartbeat/exporter pair, same rule: the owning test
    must close() them. A leaked watcher keeps fetching bases from
    whatever transport the next module builds; a leaked frontend holds
    the port AND a reference to a dead engine. Force-clean the sockets
    so one offender cannot cascade, then fail the module."""
    import threading
    import time as _time

    yield
    from distributedtraining_tpu.engine import serve as serve_mod

    live = serve_mod.live_frontends()
    for fe in live:
        fe.close()
    deadline = _time.monotonic() + 6.0
    while True:
        leaked = [t for t in threading.enumerate()
                  if t.is_alive() and (t.name.startswith("serve-watch")
                                       or t.name.startswith("serve-loop"))]
        if not leaked:
            break
        if _time.monotonic() > deadline:
            raise AssertionError(
                f"test module left serving threads alive: {leaked}; "
                "close() the GenerationEngine/ServeLoop (the engine "
                "closes its watcher) in teardown")
        _time.sleep(0.05)
    assert not live, (
        f"test module left generation frontends serving: {live}; call "
        "ServeHTTPFrontend.close() in teardown")


@pytest.fixture(autouse=True, scope="module")
def _no_leaked_flight_state():
    """Postmortem-plane hygiene (utils/flight.py): a configured flight
    recorder is PROCESS-WIDE state (same rule as the obs guard below), a
    leaked crash hook rewrites sys.excepthook/threading.excepthook for
    every later module, and a /debug/profile session whose jax profiler
    is still running poisons every later capture in the process (the
    profiler is a process global). Debug-endpoint SOCKETS ride the
    exporter and are covered by the health-plane guard above. Force-clean
    so one offender cannot cascade, then fail the module."""
    yield
    from distributedtraining_tpu.utils import flight

    live = flight.live_profile_sessions()
    for sess in live:
        try:
            sess.stop()
        except Exception:
            pass
    was_dirty = flight.dirty()
    had_hooks = flight.hooks_installed()
    flight.reset()
    assert not live, (
        f"test module left a /debug/profile session running: {live}; "
        "flight.capture_profile must stop its own trace")
    assert not was_dirty, (
        "test module left a configured flight recorder behind; call "
        "flight.reset() in teardown")
    assert not had_hooks, (
        "test module left flight crash hooks installed "
        "(sys.excepthook/threading.excepthook/atexit); call "
        "flight.uninstall_crash_hooks() or flight.reset() in teardown")


@pytest.fixture(autouse=True, scope="module")
def _no_leaked_fleetsim():
    """Fleet-simulator hygiene (engine/fleetsim.py): a FleetSim owns
    FleetMonitors (ingest pools + ledgers) for every validator and
    averager actor — process machinery the owning test must release via
    FleetSim.close() (fleetsim.simulate() does it for you). The
    simulator is deliberately thread-free (workers=1 pools run inline),
    so the check is the live-instance registry plus a sweep for any
    stray ``fleetsim-`` thread a future refactor might introduce.
    Force-clean so one offender cannot cascade, then fail the module."""
    import threading

    yield
    from distributedtraining_tpu.engine import fleetsim

    live = fleetsim.live_sims()
    for sim in live:
        sim.close()
    leaked_threads = [t for t in threading.enumerate()
                      if t.is_alive() and t.name.startswith("fleetsim")]
    assert not live, (
        f"test module left fleet simulators open: {live}; call "
        "FleetSim.close() (or use fleetsim.simulate()) in teardown")
    assert not leaked_threads, (
        f"test module left fleetsim threads alive: {leaked_threads}")


@pytest.fixture(autouse=True, scope="module")
def _no_leaked_obs_state():
    """Observability hygiene (mirrors the thread-leak guard above): the
    span/metric layer (utils/obs.py) is PROCESS-WIDE state — a test that
    configures a sink or populates the global registry and walks away
    silently pollutes every later module's metrics, and a TraceCapture
    whose jax profiler is still running poisons every later capture in
    the process. Each test module must leave both clean (obs.reset(), and
    drained/closed captures); this guard asserts it and force-cleans so
    one offender cannot cascade."""
    yield
    from distributedtraining_tpu.utils import metrics as metrics_mod
    from distributedtraining_tpu.utils import obs

    live = metrics_mod.live_captures()
    for cap in live:
        cap.close()
    was_dirty = obs.dirty()
    leftover = obs.registry().names() if was_dirty else []
    obs.reset()
    # the device observatory (utils/devprof.py) is the same kind of
    # process-wide state: an enabled registry left behind would keep
    # wrapping every later module's hot paths with blocking timings
    from distributedtraining_tpu.utils import devprof
    devprof_dirty = devprof.dirty()
    devprof_left = ([f"{r.prog}[{r.bucket}]" for r in devprof.records()]
                    if devprof_dirty else [])
    devprof.reset()
    assert not live, f"test module left a running TraceCapture: {live}"
    assert not was_dirty, (
        "test module left global obs state behind (configured sink or "
        f"registry metrics {leftover}); call obs.reset() in teardown")
    assert not devprof_dirty, (
        "test module left the device observatory enabled or populated "
        f"(programs {devprof_left}); call devprof.reset() in teardown")


@pytest.fixture(scope="session")
def devices():
    devs = jax.devices()
    assert len(devs) >= 8, f"expected 8 virtual cpu devices, got {devs}"
    return devs


@pytest.fixture(scope="session")
def rng():
    return jax.random.PRNGKey(0)
