"""Test harness: force an 8-device virtual CPU mesh.

Multi-chip behavior (dp/fsdp/tp shardings, psum merges, ring attention) is
tested without TPU hardware by splitting the host CPU into 8 XLA devices —
the same technique the driver's dryrun uses. Must run before any JAX backend
initialization; the axon sitecustomize force-selects the TPU platform via
jax.config, so we override the config (env vars alone are not enough).
"""

import os
import sys

os.environ.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count=8")
if "--xla_force_host_platform_device_count" not in os.environ["XLA_FLAGS"]:
    os.environ["XLA_FLAGS"] += " --xla_force_host_platform_device_count=8"

import jax

jax.config.update("jax_platforms", "cpu")

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import pytest  # noqa: E402


@pytest.fixture(scope="session")
def devices():
    devs = jax.devices()
    assert len(devs) >= 8, f"expected 8 virtual cpu devices, got {devs}"
    return devs


@pytest.fixture(scope="session")
def rng():
    return jax.random.PRNGKey(0)
