"""Mesh/sharding/collectives on the virtual 8-device CPU mesh."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from distributedtraining_tpu import delta
from distributedtraining_tpu.engine import TrainEngine
from distributedtraining_tpu.models import gpt2
from distributedtraining_tpu.parallel import (
    MeshConfig, best_mesh_shape, make_mesh, mesh_shardings)
from distributedtraining_tpu.parallel.collectives import psum_weighted_merge
from distributedtraining_tpu.data import ByteTokenizer, batch_iterator, text_corpus

SEQ = 32


def batches(cfg, n=6, batch=8):
    docs = text_corpus(split="train", n_docs=64, source="synthetic")
    it = batch_iterator(docs, ByteTokenizer(), batch_size=batch, seq_len=SEQ,
                        repeat=True, max_vocab=cfg.vocab_size)
    return [next(it) for _ in range(n)]


def test_make_mesh_shapes(devices):
    mesh = make_mesh(MeshConfig(dp=2, fsdp=2, tp=2))
    assert mesh.shape == {"dp": 2, "fsdp": 2, "sp": 1, "tp": 2}
    with pytest.raises(ValueError):
        make_mesh(MeshConfig(dp=16))


def test_best_mesh_heuristic():
    assert best_mesh_shape(1) == MeshConfig()
    assert best_mesh_shape(8) == MeshConfig(dp=8)
    big = best_mesh_shape(8, model_params=8_000_000_000)
    assert big.n_devices == 8 and big.tp > 1 or big.fsdp > 1


def test_param_shardings_resolve(devices):
    model, cfg = gpt2.make_model("tiny")
    mesh = make_mesh(MeshConfig(fsdp=2, tp=4))
    sh = mesh_shardings(model, mesh)
    flat = {"/".join(str(getattr(p, "key", p)) for p in path): s
            for path, s in jax.tree_util.tree_flatten_with_path(sh)[0]}
    wte = next(v for k, v in flat.items() if k.endswith("wte"))
    assert wte.spec == P("tp", "fsdp")  # ("vocab","embed") under the rules
    fc = next(v for k, v in flat.items() if "c_fc" in k and "kernel" in k)
    assert fc.spec == P("fsdp", "tp")   # ("embed","mlp")


@pytest.mark.parametrize("mesh_cfg", [
    MeshConfig(dp=8),
    MeshConfig(fsdp=8),
    MeshConfig(dp=2, fsdp=2, tp=2),
])
def test_sharded_training_matches_single_device(mesh_cfg, devices):
    """The same train step must produce the same losses on any mesh."""
    model, cfg = gpt2.make_model("tiny")
    bs = batches(cfg)

    ref_engine = TrainEngine(model, seq_len=SEQ)
    ref_state = ref_engine.init_state(jax.random.PRNGKey(0))
    ref_losses = []
    for b in bs:
        ref_state, m = ref_engine.train_step(ref_state, b)
        ref_losses.append(float(m["loss"]))

    mesh = make_mesh(mesh_cfg)
    engine = TrainEngine(model, mesh=mesh, seq_len=SEQ)
    state = engine.init_state(jax.random.PRNGKey(0))
    losses = []
    for b in bs:
        state, m = engine.train_step(state, engine.place_batch(b))
        losses.append(float(m["loss"]))

    np.testing.assert_allclose(losses, ref_losses, rtol=2e-3)


def test_psum_merge_matches_reference(devices):
    """ICI all-reduce merge == plain weighted merge, including with a miner
    count that doesn't divide the axis (padding path)."""
    model, cfg = gpt2.make_model("tiny")
    base = model.init_params(jax.random.PRNGKey(0), seq_len=8)
    deltas = [jax.tree_util.tree_map(
        lambda x, s=s: 0.01 * s * jnp.ones_like(x), base) for s in range(1, 6)]
    stacked = delta.stack_deltas(deltas)
    w = jnp.asarray([0.1, 0.3, 0.2, 0.25, 0.15])

    expect = delta.weighted_merge(base, stacked, w)
    mesh = make_mesh(MeshConfig(dp=8))
    got = psum_weighted_merge(base, stacked, w, mesh, axis="dp")
    for a, b in zip(jax.tree_util.tree_leaves(got),
                    jax.tree_util.tree_leaves(expect)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-5,
                                   atol=1e-6)


def test_stack_deltas_sharded_pads_and_places(devices):
    """Ingest sharding: miner axis sharded over the mesh, padded to the axis
    size, and equal to the host stack on the real entries."""
    from distributedtraining_tpu.parallel.collectives import (
        merge_axis, stack_deltas_sharded)

    model, cfg = gpt2.make_model("tiny")
    base = model.init_params(jax.random.PRNGKey(0), seq_len=8)
    deltas = [jax.tree_util.tree_map(
        lambda x, s=s: 0.01 * s * jnp.ones_like(x), base) for s in range(1, 4)]

    mesh = make_mesh(MeshConfig(dp=8))
    assert merge_axis(mesh) == "dp"
    stacked = stack_deltas_sharded(deltas, mesh, axis="dp")
    host = delta.stack_deltas(deltas)
    for s, h in zip(jax.tree_util.tree_leaves(stacked),
                    jax.tree_util.tree_leaves(host)):
        assert s.shape[0] == 8                      # padded 3 -> 8
        assert s.sharding.spec[0] == "dp"           # miner axis sharded
        np.testing.assert_array_equal(np.asarray(s[:3]), np.asarray(h))
        assert not np.asarray(s[3:]).any()          # zero padding


@pytest.mark.parametrize("strategy_name", ["weighted", "parameterized"])
def test_averager_round_on_mesh_matches_host(strategy_name, devices, tmp_path):
    """A full AveragerLoop round on a dp=8 mesh engine (ingest-sharded stack,
    psum/GSPMD all-reduce merge) publishes the same base as the host path —
    BASELINE config 3's merge, M=3 not dividing the axis (padding live)."""
    from distributedtraining_tpu.chain import LocalChain
    from distributedtraining_tpu.engine import (
        AveragerLoop, FakeClock, ParameterizedMerge, WeightedAverage)
    from distributedtraining_tpu.transport import InMemoryTransport

    model, cfg = gpt2.make_model("tiny")
    base = model.init_params(jax.random.PRNGKey(0))
    bs = batches(cfg, n=2)

    def make_strategy():
        if strategy_name == "weighted":
            return WeightedAverage()
        # sgd for host-vs-mesh PARITY: adam steps are ~lr*sign(g), so a
        # reduction-order sign flip on a near-zero meta-gradient becomes
        # a full-lr weight divergence (the round-4 on-chip lesson,
        # TUNNEL_r04.md); adam behavior itself is covered by the
        # discrimination tests in test_engines.py
        return ParameterizedMerge(model, meta_epochs=2, meta_lr=0.3,
                                  per_tensor=True, meta_optimizer="sgd")

    def run(engine):
        transport = InMemoryTransport()
        transport.publish_base(base)
        for i in range(3):
            d = jax.tree_util.tree_map(
                lambda x, s=i + 1: 0.005 * s * jnp.ones_like(x), base)
            transport.publish_delta(f"hotkey_{i}", d)
        chain = LocalChain(str(tmp_path / f"{strategy_name}-{id(engine)}"),
                           my_hotkey="hotkey_99", epoch_length=0,
                           clock=FakeClock())
        loop = AveragerLoop(engine, transport, chain, make_strategy(),
                            val_batches=lambda: bs, clock=FakeClock())
        loop.bootstrap(params=base)
        assert loop.run_round()
        assert loop.report.last_accepted == 3
        return jax.device_get(loop.base_params)

    host = run(TrainEngine(model, seq_len=SEQ))
    mesh = make_mesh(MeshConfig(dp=8))
    sharded = run(TrainEngine(model, mesh=mesh, seq_len=SEQ))
    for a, b in zip(jax.tree_util.tree_leaves(sharded),
                    jax.tree_util.tree_leaves(host)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-5, atol=1e-6)


def test_multihost_single_host_degradation(devices):
    """initialize() is a no-op on one host; pod_mesh spans all devices;
    shard_documents with one process yields everything."""
    from distributedtraining_tpu.parallel import multihost

    multihost.initialize()  # must not raise or start a coordinator
    assert multihost.is_coordinator()

    mesh = multihost.pod_mesh(fsdp=2, tp=2)
    assert mesh.shape["dp"] * mesh.shape["fsdp"] * mesh.shape["sp"] \
        * mesh.shape["tp"] == len(jax.devices())
    assert mesh.shape["fsdp"] == 2 and mesh.shape["tp"] == 2

    docs = list(multihost.shard_documents(["a", "b", "c"]))
    assert docs == ["a", "b", "c"]
    # explicit 2-process split: disjoint and covering
    p0 = list(multihost.shard_documents("abcdef", process_index=0,
                                        process_count=2))
    p1 = list(multihost.shard_documents("abcdef", process_index=1,
                                        process_count=2))
    assert p0 == list("ace") and p1 == list("bdf")

    import pytest as _pytest
    with _pytest.raises(ValueError):
        multihost.pod_mesh(fsdp=3)  # 8 % 3 != 0


def test_multihost_env_detection(monkeypatch):
    """The multi-process decision comes from environment signals only —
    probing jax.process_count() would initialize the XLA backend and make a
    later jax.distributed.initialize() raise unconditionally."""
    from distributedtraining_tpu.parallel import multihost

    for var in multihost._MULTIPROCESS_ENV_VARS + (
            "SLURM_NTASKS", "SLURM_NPROCS", "OMPI_COMM_WORLD_SIZE",
            "TPU_WORKER_HOSTNAMES"):
        monkeypatch.delenv(var, raising=False)
    # isolate from the /dev/accel* metadata-server fallback: on a real pod
    # slice it would answer >1 and on non-GCE hosts it would hit the network
    monkeypatch.setenv("TPU_SKIP_MDS_QUERY", "1")
    assert not multihost._multiprocess_env()

    # single-host TPU VMs set one hostname; only several workers signal a pod
    monkeypatch.setenv("TPU_WORKER_HOSTNAMES", "localhost")
    assert not multihost._multiprocess_env()
    monkeypatch.setenv("TPU_WORKER_HOSTNAMES", "w0,w1,w2,w3")
    assert multihost._multiprocess_env()
    monkeypatch.delenv("TPU_WORKER_HOSTNAMES")

    monkeypatch.setenv("SLURM_NTASKS", "1")
    assert not multihost._multiprocess_env()
    monkeypatch.setenv("SLURM_NTASKS", "4")
    assert multihost._multiprocess_env()
    monkeypatch.delenv("SLURM_NTASKS")
    monkeypatch.setenv("JAX_COORDINATOR_ADDRESS", "10.0.0.1:1234")
    assert multihost._multiprocess_env()


def test_resolve_mesh_config():
    from distributedtraining_tpu.parallel import resolve_mesh_config

    # explicit axes: dp=0 fills the remainder
    assert resolve_mesh_config(n_devices=8, fsdp=2, tp=2) == \
        MeshConfig(dp=2, fsdp=2, sp=1, tp=2)
    assert resolve_mesh_config(n_devices=8, dp=4) == MeshConfig(dp=4)
    # auto: small model -> pure dp; 8B params -> sharded axes
    assert resolve_mesh_config(n_devices=8, auto=True,
                               model_params=124_000_000) == MeshConfig(dp=8)
    big = resolve_mesh_config(n_devices=32, auto=True,
                              model_params=8_000_000_000)
    assert big.n_devices == 32 and (big.fsdp > 1 or big.tp > 1)
    # auto overrides explicit axes (documented contract of --mesh-auto)
    assert resolve_mesh_config(n_devices=8, dp=1, fsdp=8, auto=True,
                               model_params=1_000) == MeshConfig(dp=8)


def test_resolve_mesh_config_auto_with_dcn():
    from distributedtraining_tpu.parallel import resolve_mesh_config

    # auto + multi-slice: pick per granule, multiply dp — fsdp/sp/tp never
    # span a granule, so hybrid layout keeps them on ICI
    small = resolve_mesh_config(n_devices=16, auto=True, dcn_dp=2,
                                model_params=124_000_000)
    assert small == MeshConfig(dp=16)
    big = resolve_mesh_config(n_devices=32, auto=True, dcn_dp=2,
                              model_params=8_000_000_000)
    assert big.n_devices == 32
    assert big.dp % 2 == 0                 # dcn factor lives in dp
    assert big.fsdp * big.sp * big.tp <= 16  # inside one granule
    with pytest.raises(ValueError):
        resolve_mesh_config(n_devices=9, auto=True, dcn_dp=2)


def test_parameterized_mesh_merge_lowers_to_allreduce(devices):
    """The GSPMD claim at engine/average.py (_build_step): with an
    ingest-sharded miner stack, the parameterized mixture's sum over the
    miner axis must COMPILE to partial sums + an all-reduce — checked in
    the HLO text, not just numerically. This is also the regression guard
    for the closure trap _build_step documents: when base/stacked were
    closed over instead of passed as jit arguments, the stack was embedded
    as a (replicated) constant and NO collective appeared."""
    from distributedtraining_tpu.engine import ParameterizedMerge
    from distributedtraining_tpu.parallel.collectives import (
        merge_axis, stack_deltas_sharded)

    model, cfg = gpt2.make_model("tiny")
    base = model.init_params(jax.random.PRNGKey(0), seq_len=8)
    deltas = [jax.tree_util.tree_map(
        lambda x, s=s: 0.01 * s * jnp.ones_like(x), base) for s in range(1, 4)]
    mesh = make_mesh(MeshConfig(dp=8))
    stacked = stack_deltas_sharded(deltas, mesh, axis=merge_axis(mesh))

    pm = ParameterizedMerge(model, per_tensor=True)
    mixture, _, _ = pm._build_step(delta.miner_axis_size(stacked))
    w = jax.tree_util.tree_map(lambda _: jnp.zeros((3,), jnp.float32), base)
    txt = mixture.lower(w, base, stacked).compile().as_text()
    assert "all-reduce" in txt, "sharded merge compiled without an all-reduce"

    host_stack = delta.stack_deltas(deltas)
    mixture_host, _, _ = pm._build_step(delta.miner_axis_size(host_stack))
    txt_host = mixture_host.lower(
        w, base, host_stack).compile().as_text()
    assert "all-reduce" not in txt_host


def test_embed_lookup_matmul_backward(devices):
    """On dp x fsdp meshes the embedding backward takes the one-hot
    einsum spelling (no GSPMD involuntary-remat reshard of the cotangent
    — see ops/embed.py); gradients must equal the scatter spelling
    exactly, including duplicate-id accumulation, and routing must stay
    on the plain gather without an ambient dp x fsdp mesh."""
    from distributedtraining_tpu.ops import embed

    rng = np.random.default_rng(0)
    table = jnp.asarray(rng.normal(size=(64, 16)), jnp.float32)
    ids = jnp.asarray(rng.integers(0, 64, (4, 8)), jnp.int32)
    ids = ids.at[0, 0].set(ids[0, 1])  # force a duplicate (accumulation)
    ct = jnp.asarray(rng.normal(size=(4, 8, 16)), jnp.float32)

    assert not embed._ambient_mesh_needs_matmul_bwd()
    with make_mesh(MeshConfig(dp=2, fsdp=2, tp=2)):
        assert embed._ambient_mesh_needs_matmul_bwd()
    with make_mesh(MeshConfig(dp=8)):
        assert not embed._ambient_mesh_needs_matmul_bwd()

    take = embed._take_matmul_bwd(64, "float32")
    g_ref = jax.grad(lambda t: (jnp.take(t, ids, axis=0) * ct).sum())(table)
    g_new = jax.grad(lambda t: (take(t, ids) * ct).sum())(table)
    np.testing.assert_allclose(np.asarray(g_new), np.asarray(g_ref),
                               rtol=1e-6, atol=1e-6)
