"""Disaggregated prefill/decode serving (engine/kv_transfer.py + the
phase-specialized engine/serve.py workers + the phase-aware router).

The correctness spine is CROSS-WORKER IDENTITY: a request prefilled on
worker A (phase="prefill", KV pages exported as content-addressed
shards + a manifest-last per-request manifest) and decoded on worker B
(phase="decode", pages adopted into B's own PagePool) must produce
exactly what the unified engine produces — token-identical for greedy
lanes, BIT-identical for sampled lanes (the counter PRNG makes token
index, not worker, the stream coordinate), and still identical with a
speculative drafter on the decode side (losslessness composes with
adoption). Everything else — torn manifests, hash misses, base-revision
skew, pool accounting, the router's two-leg hop — is then tested as
"still identical, with the degrade counted".
"""

import json
import urllib.error
import urllib.request

import jax
import numpy as np
import pytest

from distributedtraining_tpu.engine import kv_transfer as kvt
from distributedtraining_tpu.engine.router import (RouterHTTPFrontend,
                                                   RouterPolicy)
from distributedtraining_tpu.engine.serve import (GenerationEngine,
                                                  ServeHTTPFrontend,
                                                  ServeLoop,
                                                  reference_generate)
from distributedtraining_tpu.engine.speculative import DraftEngine
from distributedtraining_tpu.models import gpt2
from distributedtraining_tpu.transport import InMemoryTransport
from distributedtraining_tpu.transport import base as tbase
from distributedtraining_tpu.utils import obs

TINY = gpt2.GPT2Config(vocab_size=128, n_positions=64, n_embd=32,
                       n_layer=2, n_head=2, dtype="float32",
                       vocab_multiple=64)

GEN = 8

_REF_CACHE: dict = {}


@pytest.fixture(scope="module")
def setup():
    model, cfg = gpt2.make_model(TINY)
    params = model.init_params(jax.random.PRNGKey(0), seq_len=8)
    rng = np.random.RandomState(0)
    prompts = [[int(t) for t in rng.randint(0, cfg.vocab_size, size=n)]
               for n in (5, 11, 3, 17)]
    return model, cfg, params, prompts


@pytest.fixture()
def sink():
    class _Sink:
        def __init__(self):
            self.records = []

        def log(self, rec, **kw):
            self.records.append(rec)

    s = _Sink()
    obs.configure(s, role="server")
    try:
        yield s
    finally:
        obs.reset()


def refs_for(model, params, prompts, n=GEN):
    out = []
    for p in prompts:
        key = (id(model), id(params), tuple(p), n)
        if key not in _REF_CACHE:
            _REF_CACHE[key] = reference_generate(model, params, p, n)
        out.append(_REF_CACHE[key])
    return out


def disagg_pair(model, params, *, revision="r1", decode_revision=None,
                transport=None, **dec_kw):
    """One prefill worker + one decode worker over a shared transport."""
    tr = transport if transport is not None else InMemoryTransport()
    pe = GenerationEngine(model, params, revision=revision, max_slots=4,
                          page_size=8, phase="prefill",
                          kv_exporter=kvt.KVExporter(tr))
    de = GenerationEngine(model, params,
                          revision=decode_revision or revision,
                          max_slots=4, page_size=8, phase="decode",
                          kv_adopter=kvt.KVAdopter(tr), **dec_kw)
    return tr, pe, de


def drain(eng, reqs):
    while not all(r.done_evt.is_set() for r in reqs):
        eng.step()
    return [list(r.tokens) for r in reqs]


def hop(pe, de, prompts, n=GEN, *, sampling=None):
    """Run the disaggregated two-leg path: prefill on ``pe``, hand the
    (kv_ref, first_token) pair to ``de``, return the decode outputs."""
    kw = dict(sampling or {})
    pre = [pe.submit(p, n, request_id=f"rq-hop-{i}", **kw)
           for i, p in enumerate(prompts)]
    drain(pe, pre)
    dec = [de.submit(p, n, kv_ref=r.kv_ref, first_token=r.first_token,
                     **kw)
           for p, r in zip(prompts, pre)]
    return pre, drain(de, dec)


# ---------------------------------------------------------------------------
# Wire codecs (pure)
# ---------------------------------------------------------------------------

def test_page_codec_roundtrip_and_rejects():
    rng = np.random.default_rng(0)
    k = rng.standard_normal((2, 8, 2, 16), dtype=np.float32)
    v = rng.standard_normal((2, 8, 2, 16), dtype=np.float32)
    data = kvt.pack_kv_page(k, v)
    out = kvt.unpack_kv_page(data)
    assert out is not None
    np.testing.assert_array_equal(out[0], k)
    np.testing.assert_array_equal(out[1], v)
    # every defect degrades to None, never raises
    assert kvt.unpack_kv_page(b"not msgpack") is None
    assert kvt.unpack_kv_page(data, max_bytes=16) is None
    skew = kvt.pack_kv_page(k, v[:, :4])          # K/V shape skew
    assert kvt.unpack_kv_page(skew) is None
    assert kvt.unpack_kv_page(
        kvt.pack_kv_page(k[0], v[0])) is None     # wrong rank


def test_manifest_codec_roundtrip_and_rejects():
    geom = {"layers": 2, "page_size": 8, "kv_heads": 2, "head_dim": 16,
            "dtype": "float32"}
    digest = "ab" * 32
    data = kvt.build_kv_manifest(request_id="rq-1", revision="r1",
                                 pages=[(digest, 128)], geometry=geom,
                                 prompt_len=5, first_token=7)
    man = kvt.parse_kv_manifest(data)
    assert man == {"request_id": "rq-1", "revision": "r1",
                   "prompt_len": 5, "first_token": 7, "geometry": geom,
                   "pages": [(digest, 128)]}
    # defensive reader: bad magic, truncation, tampered digest, zero
    # pages — all degrade to None (no transfer), never raise
    assert kvt.parse_kv_manifest(b"XX" + data[2:]) is None
    assert kvt.parse_kv_manifest(data[:-3]) is None
    assert kvt.parse_kv_manifest(
        data.replace(digest.encode(), b"zz" * 32)) is None
    bad = json.loads(data[len(kvt.KV_MANIFEST_MAGIC):])
    bad["pages"] = []
    assert kvt.parse_kv_manifest(
        kvt.KV_MANIFEST_MAGIC + json.dumps(bad).encode()) is None


# ---------------------------------------------------------------------------
# Cross-worker identity
# ---------------------------------------------------------------------------

def test_greedy_cross_worker_parity_and_pool_audit(setup, sink):
    """Prefill on A, decode on B: token-identical to the unified
    reference, with the page-pool conservation invariant audited every
    decode step (debug_invariants) and all pages returned to the free
    list when the batch drains."""
    model, cfg, params, prompts = setup
    tr, pe, de = disagg_pair(model, params, debug_invariants=True)
    try:
        pre, out = hop(pe, de, prompts)
        assert out == refs_for(model, params, prompts)
        assert pe.kv_exported == len(prompts)
        assert de.kv_adopted == len(prompts)
        assert de.kv_reprefills == 0
        # prefill legs finish as "prefilled" carrying the handoff pair
        assert all(r.status == "prefilled" and r.kv_ref
                   and r.first_token is not None for r in pre)
        # every adopted page came back: free + referenced tiles the pool
        de.pool.check({})
        assert de.pool.free == de.pool.total
    finally:
        pe.close()
        de.close()


def test_sampled_cross_worker_bit_identity(setup, sink):
    """Sampled lanes survive the worker hop BIT-identically: the
    counter PRNG is a pure function of (seed, token index), so the
    prefill worker's index-0 draw plus the decode worker's index-1..N
    draws reconstruct the unified engine's stream draw-for-draw."""
    model, cfg, params, prompts = setup
    sampling = {"temperature": 0.8, "top_p": 0.9, "seed": 23}
    uni = GenerationEngine(model, params, revision="r1", max_slots=4,
                           page_size=8)
    try:
        ref = uni.generate(prompts, GEN, **sampling)
    finally:
        uni.close()
    tr, pe, de = disagg_pair(model, params)
    try:
        _, out = hop(pe, de, prompts, sampling=sampling)
        assert out == ref
        assert de.kv_adopted == len(prompts)
    finally:
        pe.close()
        de.close()


def test_speculative_decode_on_adopted_pages(setup, sink):
    """Losslessness composes with adoption: a decode worker running
    draft-and-verify over ADOPTED pages (self-draft: acceptance 1.0)
    still produces the unified greedy output."""
    model, cfg, params, prompts = setup
    tr, pe, de = disagg_pair(
        model, params, debug_invariants=True, draft_k=4,
        draft=DraftEngine(model, params, max_slots=4, page_size=8))
    try:
        _, out = hop(pe, de, prompts)
        assert out == refs_for(model, params, prompts)
        assert de.kv_adopted == len(prompts)
        assert de.spec_accept_rate == pytest.approx(1.0)
    finally:
        pe.close()
        de.close()


# ---------------------------------------------------------------------------
# Degrades (every defect -> local prefill, counted, output-identical)
# ---------------------------------------------------------------------------

def test_transfer_defects_degrade_to_local_prefill(setup, sink):
    """Absent manifest, torn manifest bytes, and a corrupted page shard
    all degrade identically: the decode worker prefills locally,
    counts the re-prefill, and the output stays reference-identical."""
    model, cfg, params, prompts = setup
    tr, pe, de = disagg_pair(model, params)
    ref = refs_for(model, params, prompts[:1])
    try:
        # 1) absent manifest: the prefill leg never published
        r = de.submit(prompts[0], GEN, kv_ref="rq-never-published",
                      first_token=ref[0][0])
        assert drain(de, [r]) == ref
        assert de.kv_reprefills == 1 and de.kv_adopted == 0

        # 2) torn manifest: shards landed, the manifest write tore
        pre = [pe.submit(prompts[0], GEN, request_id="rq-torn")]
        drain(pe, pre)
        tbase.publish_kv_manifest(tr, "rq-torn", b"DTKV1\n{torn")
        r = de.submit(prompts[0], GEN, kv_ref="rq-torn",
                      first_token=pre[0].first_token)
        assert drain(de, [r]) == ref
        assert de.kv_reprefills == 2 and de.kv_adopted == 0

        # 3) hash miss: a shard the manifest pins serves wrong bytes
        pre = [pe.submit(prompts[0], GEN, request_id="rq-badpage")]
        drain(pe, pre)
        man = kvt.parse_kv_manifest(
            tbase.fetch_kv_manifest_bytes(tr, "rq-badpage"))
        digest = man["pages"][0][0]
        tr._deltas[tbase.kv_page_id(digest)] = \
            b"\x00" * man["pages"][0][1]
        r = de.submit(prompts[0], GEN, kv_ref="rq-badpage",
                      first_token=pre[0].first_token)
        assert drain(de, [r]) == ref
        assert de.kv_reprefills == 3 and de.kv_adopted == 0
        reg = obs.registry()
        assert reg.counter("serve.kv_reprefills").value == 3
        assert reg.counter("serve.kv_page_rejects").value >= 1
    finally:
        pe.close()
        de.close()


def test_revision_mismatch_refuses_adoption(setup, sink):
    """KV is a pure function of (params, tokens): pages prefilled on
    another base revision are refused LOUDLY — counted distinctly from
    transfer faults — and the request re-prefills on the decode
    worker's own revision, so the output matches ITS base."""
    model, cfg, params, prompts = setup
    tr, pe, de = disagg_pair(model, params, revision="r1",
                             decode_revision="r2")
    try:
        _, out = hop(pe, de, prompts[:2])
        assert out == refs_for(model, params, prompts[:2])
        assert de.kv_rev_mismatch == 2
        assert de.kv_reprefills == 2
        assert de.kv_adopted == 0
        assert obs.registry().counter("serve.kv_rev_mismatch").value == 2
    finally:
        pe.close()
        de.close()


def test_shared_prefix_dedupes_wire_bytes(setup, sink):
    """Content addressing pays: two prompts sharing a full-page prefix
    export bit-identical pages, so the second request's shards are
    publish no-ops and the adopter serves them from its page store
    without touching the wire."""
    model, cfg, params, _ = setup
    shared = [int(t) for t in
              np.random.RandomState(5).randint(0, cfg.vocab_size, 16)]
    pair = [shared + [3], shared + [9]]
    tr, pe, de = disagg_pair(model, params)
    try:
        _, out = hop(pe, de, pair)
        assert out == refs_for(model, params, pair)
        deduped = obs.registry().counter("serve.kv_pages_deduped").value
        # two full 8-token pages of shared prefix, deduped on BOTH the
        # export side (publish ledger) and the adopt side (page store)
        assert deduped >= 4
    finally:
        pe.close()
        de.close()


# ---------------------------------------------------------------------------
# Mixed fleet through the phase-aware router
# ---------------------------------------------------------------------------

@pytest.fixture()
def mixed_fleet(setup):
    """One unified + one prefill + one decode backend (shared KV
    transport), each behind a live HTTP frontend."""
    model, cfg, params, prompts = setup
    tr = InMemoryTransport()
    specs = [
        {"phase": "unified"},
        {"phase": "prefill", "kv_exporter": kvt.KVExporter(tr)},
        {"phase": "decode", "kv_adopter": kvt.KVAdopter(tr)},
    ]
    engines, loops, fes, urls = [], [], [], []
    for kw in specs:
        eng = GenerationEngine(model, params, revision="r1", max_slots=2,
                               page_size=8, **kw)
        loop = ServeLoop(eng, idle_poll_s=0.02).start()
        fe = ServeHTTPFrontend(eng, 0, timeout_s=60.0)
        urls.append(f"http://127.0.0.1:{fe.start()}")
        engines.append(eng)
        loops.append(loop)
        fes.append(fe)
    try:
        yield model, params, engines, urls
    finally:
        for fe in fes:
            fe.close()
        for loop in loops:
            loop.close()
        for eng in engines:
            eng.close()


def test_router_two_leg_disaggregated_route(mixed_fleet, sink):
    """The router learns worker classes from /healthz, routes the
    prefill leg to the prefill worker and the decode leg (kv_ref +
    first_token) to the decode worker, and the spliced output is
    reference-identical."""
    model, params, engines, urls = mixed_fleet
    router = RouterHTTPFrontend(urls, 0, poll_interval_s=30.0,
                                timeout_s=60.0)
    router.refresh()
    port = router.start()
    try:
        assert sorted(b.phase for b in router.backends) == \
            ["decode", "prefill", "unified"]
        prompt = [3, 1, 4, 1, 5]
        body = json.dumps({"tokens": prompt,
                           "max_new_tokens": 6}).encode()
        req = urllib.request.Request(
            f"http://127.0.0.1:{port}/generate", data=body,
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=30) as resp:
            out = json.loads(resp.read())
        assert out["tokens"] == reference_generate(model, params,
                                                   prompt, 6)
        assert router.disagg_routed == 1
        assert engines[1].kv_exported == 1   # prefill worker
        assert engines[2].kv_adopted == 1    # decode worker
        # the fleet view names each worker's class
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/healthz", timeout=10) as resp:
            hz = json.loads(resp.read())
        assert sorted(b["phase"] for b in hz["backends"]) == \
            ["decode", "prefill", "unified"]
    finally:
        router.close()


def test_router_excludes_prefill_workers_from_unified_fallback(
        mixed_fleet, sink):
    """With the decode worker gone the two-leg route is impossible; the
    router falls back to the UNIFIED pool only — a prefill-phase worker
    cannot serve /generate (409 by phase discipline), so it must never
    be in the fallback set."""
    model, params, engines, urls = mixed_fleet
    router = RouterHTTPFrontend(urls[:2], 0, poll_interval_s=30.0,
                                timeout_s=60.0)   # unified + prefill only
    router.refresh()
    port = router.start()
    try:
        prompt = [2, 7, 1, 8]
        body = json.dumps({"tokens": prompt,
                           "max_new_tokens": 6}).encode()
        for _ in range(3):
            req = urllib.request.Request(
                f"http://127.0.0.1:{port}/generate", data=body,
                headers={"Content-Type": "application/json"})
            with urllib.request.urlopen(req, timeout=30) as resp:
                out = json.loads(resp.read())
            assert out["tokens"] == reference_generate(model, params,
                                                       prompt, 6)
        assert router.disagg_routed == 0
        assert engines[1].kv_exported == 0   # prefill worker never hit
        assert engines[0].tokens_emitted >= 18
    finally:
        router.close()


# ---------------------------------------------------------------------------
# Fleet surfaces: report columns for the disaggregated plane
# ---------------------------------------------------------------------------

def test_fleet_report_phase_and_kv_columns(tmp_path):
    """One fleet table answers "do both worker classes exist AND is KV
    moving between them": the phase / kv_exp / kv_adp columns render
    from disaggregated server heartbeats, and unified rows show '-'."""
    import os
    import sys
    sys.path.insert(0, os.path.join(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))), "scripts"))
    import fleet_report
    path = tmp_path / "monitor.jsonl"
    recs = [
        {"heartbeat": {"hb": 1, "role": "server", "hotkey": "hk-pre",
                       "seq": 2, "t": 9.0, "phase": "prefill",
                       "kv_exported": 41, "kv_adopted": 0}},
        {"heartbeat": {"hb": 1, "role": "server", "hotkey": "hk-dec",
                       "seq": 2, "t": 9.0, "phase": "decode",
                       "kv_exported": 0, "kv_adopted": 37}},
        {"heartbeat": {"hb": 1, "role": "server", "hotkey": "hk-uni",
                       "seq": 2, "t": 9.0, "tokens_per_sec": 12.5}},
    ]
    path.write_text("\n".join(json.dumps(r) for r in recs) + "\n")
    for col in ("phase", "kv_exp", "kv_adp"):
        assert col in fleet_report.COLUMNS
    rep = fleet_report.build_report([str(path)])
    table = fleet_report.format_table(rep)
    assert "prefill" in table and "decode" in table
    assert "41" in table and "37" in table
    pre = rep["nodes"]["server/hk-pre"]
    assert pre["phase"] == "prefill" and pre["kv_exported"] == 41
    # a unified server's row renders '-' in every disagg column
    uni_row = next(ln for ln in table.splitlines() if "hk-uni" in ln)
    assert "prefill" not in uni_row and "decode" not in uni_row
