"""Self-healing fleet (engine/remediate.py + transport/chaos.py).

Covers: deterministic chaos injection (seeded fault sequences, partitions,
per-role kill switches, op-indexed schedules), the retry loop's
total-elapsed deadline, ledger pruning for deregistered hotkeys, the
quarantine/probation state machine against a live FleetMonitor, elastic
cohort sizing over the compiled-bucket ladder, the publication lease
protocol, miner preemption-resume hardening over localfs, and the
acceptance round: a localfs fleet where one miner is killed mid-round and
the averager mid-run under ChaosTransport — rounds keep completing, the
killed miner is quarantined in the ledger and re-admitted after clean
heartbeats, and exactly ONE averager publishes per round with a
monotonically increasing lease epoch across the standby failover.
"""

import math
import os
import sys

import jax
import numpy as np
import pytest

from distributedtraining_tpu.engine import TrainEngine
from distributedtraining_tpu.engine.average import (AveragerLoop,
                                                    WeightedAverage)
from distributedtraining_tpu.engine.batched_eval import (
    BatchedCohortEvaluator)
from distributedtraining_tpu.engine.health import (FleetMonitor, SLORule,
                                                   build_heartbeat)
from distributedtraining_tpu.engine.remediate import (LeaseManager,
                                                      RemediationEngine,
                                                      RemediationPolicy,
                                                      StandbyAverager,
                                                      elastic_cohort,
                                                      parse_lease)
from distributedtraining_tpu.engine.scheduler import FakeClock
from distributedtraining_tpu.engine.train import MinerLoop
from distributedtraining_tpu.engine.validate import Validator
from distributedtraining_tpu.models import gpt2
from distributedtraining_tpu.transport import (InMemoryTransport,
                                               LocalFSTransport)
from distributedtraining_tpu.transport.base import heartbeat_id, lease_id
from distributedtraining_tpu.transport.chaos import (ChaosError, ChaosEvent,
                                                     ChaosSpec,
                                                     ChaosTransport)
from distributedtraining_tpu.transport.retry import (RetryPolicy,
                                                     call_with_retry)
from distributedtraining_tpu.utils import obs
from distributedtraining_tpu.utils.metrics import InMemorySink

sys.path.insert(0, os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "scripts"))
import fleet_report  # noqa: E402


@pytest.fixture(autouse=True)
def _clean_obs():
    obs.reset()
    yield
    obs.reset()


# ---------------------------------------------------------------------------
# ChaosTransport
# ---------------------------------------------------------------------------

def _fault_sequence(transport, n=12):
    out = []
    for _ in range(n):
        try:
            transport.delta_revision("m0")
            out.append(0)
        except ChaosError:
            out.append(1)
    return out


def test_chaos_error_rates_are_seed_deterministic():
    spec = ChaosSpec(fetch_error_rate=0.4, seed=11)
    a = _fault_sequence(ChaosTransport(InMemoryTransport(), spec))
    b = _fault_sequence(ChaosTransport(InMemoryTransport(), spec))
    assert a == b and 0 < sum(a) < len(a)
    # a different seed produces a different (still deterministic) sequence
    c = _fault_sequence(ChaosTransport(
        InMemoryTransport(), ChaosSpec(fetch_error_rate=0.4, seed=12)))
    assert c != a


def test_chaos_partition_and_kill_switch():
    t = ChaosTransport(InMemoryTransport(), role="miner")
    t.inner.publish_raw("m0", b"x")
    assert t.delta_revision("m0") is not None
    t.partition("m0")
    with pytest.raises(ChaosError):
        t.fetch_delta_bytes("m0")
    t.heal("m0")
    assert t.fetch_delta_bytes("m0") == b"x"
    t.kill_role("miner")
    with pytest.raises(ChaosError):
        t.publish_raw("m0", b"y")
    with pytest.raises(ChaosError):
        t.base_revision()
    t.revive_role("miner")
    assert t.publish_raw("m0", b"y") is not None
    # a kill for a DIFFERENT role leaves this transport alone
    t.kill_role("averager")
    assert t.delta_revision("m0") is not None
    assert t.faults == 3


def test_chaos_schedule_fires_at_op_index():
    t = ChaosTransport(
        InMemoryTransport(), role="miner",
        schedule=[ChaosEvent(at_op=3, action="kill_role", target="miner"),
                  ChaosEvent(at_op=5, action="revive_role",
                             target="miner")])
    t.inner.publish_raw("m0", b"x")
    seq = _fault_sequence(t, 6)
    # ops 1-2 pass, 3-4 dead, 5+ revived — deterministic however the
    # surrounding test machinery paces its calls
    assert seq == [0, 0, 1, 1, 0, 0]


def test_chaos_spec_from_json_validates():
    spec = ChaosSpec.from_json(
        '{"fetch_error_rate": 0.25, "partitioned": ["hk0"], "seed": 2}')
    assert spec.fetch_error_rate == 0.25 and spec.partitioned == ("hk0",)
    with pytest.raises(ValueError):
        ChaosSpec.from_json('{"fetch_errr_rate": 0.25}')   # typo'd key
    with pytest.raises(ValueError):
        ChaosSpec.from_json('{"publish_error_rate": 1.5}')
    with pytest.raises(ValueError):
        ChaosSpec.from_json('[1, 2]')


def test_chaos_latency_uses_injected_sleep():
    slept = []
    t = ChaosTransport(InMemoryTransport(), ChaosSpec(latency_s=0.5),
                       sleep=slept.append)
    t.inner.publish_raw("m0", b"x")
    t.delta_revision("m0")
    assert slept == [0.5]


# ---------------------------------------------------------------------------
# Retry deadline (satellite)
# ---------------------------------------------------------------------------

def test_retry_max_elapsed_abandons_remaining_attempts():
    obs.configure(InMemorySink(), role="t")
    clock = FakeClock(0.0)
    calls = []

    def fail():
        calls.append(1)
        clock.advance(4.0)          # each try "blocks" 4 s (partition-ish)
        raise OSError("partitioned")

    policy = RetryPolicy(attempts=10, base_delay=1.0, max_delay=1.0,
                         jitter=0.0, max_elapsed=10.0)
    with pytest.raises(OSError):
        call_with_retry(fail, policy=policy, sleep=clock.sleep,
                        monotonic=clock.now, describe="probe")
    # tries at t=4, 9, 14 (4 s call + 1 s backoff each): after the third
    # try the next backoff would cross the 10 s deadline -> abandoned
    # with 7 of the 10 attempts unspent
    assert len(calls) == 3
    reg = obs.registry()
    assert reg.counter("transport.retry_deadline").value == 1
    assert reg.counter("transport.retry.exhausted").value == 0


def test_retry_without_deadline_spends_full_budget():
    clock = FakeClock(0.0)
    calls = []

    def fail():
        calls.append(1)
        raise OSError("nope")

    with pytest.raises(OSError):
        call_with_retry(fail, policy=RetryPolicy(attempts=4, base_delay=0.1,
                                                 jitter=0.0),
                        sleep=clock.sleep, monotonic=clock.now)
    assert len(calls) == 4


def test_retry_policy_validates_max_elapsed():
    with pytest.raises(ValueError):
        RetryPolicy(max_elapsed=0.0)
    RetryPolicy(max_elapsed=None)   # explicit None stays legal


# ---------------------------------------------------------------------------
# Ledger pruning (satellite)
# ---------------------------------------------------------------------------

def _beat(transport, role, hotkey, seq, **fields):
    transport.publish_delta_meta(
        heartbeat_id(role, hotkey),
        build_heartbeat(role, hotkey, seq, now=float(seq), **fields))


def test_fleet_prune_on_registry_departure():
    obs.configure(InMemorySink(), role="t")
    sink = InMemorySink()
    t = InMemoryTransport()
    fm = FleetMonitor(t, metrics=sink)
    try:
        _beat(t, "miner", "a", 1, loss_ema=9.0)
        _beat(t, "miner", "b", 1, loss_ema=2.0)
        fm.poll(["a", "b"])
        assert set(fm.nodes) == {("miner", "a"), ("miner", "b")}
        # "a" leaves the chain registry: pruned, tagged into the sink,
        # counted — and its loss_ema stops skewing the fleet median
        fm.poll(["b"])
        assert set(fm.nodes) == {("miner", "b")}
        assert obs.registry().counter("fleet.pruned").value == 1
        tagged = [r for r in sink.records if "fleet_pruned" in r]
        assert len(tagged) == 1
        assert tagged[0]["fleet_pruned"]["hotkey"] == "a"
        assert tagged[0]["fleet_pruned"]["loss_ema"] == 9.0
    finally:
        fm.close()


def test_fleet_prune_clears_fired_breaches():
    t = InMemoryTransport()
    fm = FleetMonitor(t, rules=[SLORule("stale_node", "stale", threshold=1)])
    try:
        _beat(t, "miner", "a", 1)
        _beat(t, "miner", "b", 1)
        fm.poll(["a", "b"])
        for _ in range(3):          # both go silent
            fm.poll(["a", "b"])
        assert {b["hotkey"] for b in fm.evaluate_slos()} == {"a", "b"}
        fm.poll(["b"])              # "a" deregisters
        assert all(key != ("miner", "a") for key in fm.nodes)
        assert all(f[1] != "a" for f in fm._fired)
    finally:
        fm.close()


# ---------------------------------------------------------------------------
# Quarantine state machine
# ---------------------------------------------------------------------------

def test_quarantine_probation_readmission_and_relapse():
    sink = InMemorySink()
    t = InMemoryTransport()
    fm = FleetMonitor(t, rules=[SLORule("stale_node", "stale", threshold=1)],
                      metrics=sink)
    rem = RemediationEngine(
        fm, metrics=sink,
        policy=RemediationPolicy(quarantine_rules=("stale_node",),
                                 probation_beats=2, probation_rounds=3))
    try:
        seq = 1
        _beat(t, "miner", "hk", seq)
        fm.poll(["hk"])
        rem.observe_round(fm.evaluate_slos())
        assert not rem.is_excluded("hk")
        for _ in range(3):          # hk goes silent -> stale breach
            fm.poll(["hk"])
            rem.observe_round(fm.evaluate_slos())
        assert rem.is_excluded("hk")
        assert fm.nodes[("miner", "hk")].quarantined
        assert rem.filter_hotkeys(["hk", "other"]) == ["other"]
        assert rem.decay_scores({"hk": 0.8, "other": 0.4}) == {
            "hk": 0.8 * 0.25, "other": 0.4}
        # silent rounds do NOT count toward re-admission
        fm.poll(["hk"])
        rem.observe_round(fm.evaluate_slos())
        assert rem.is_excluded("hk")
        # two clean fresh beats -> probation (re-admitted, watched)
        for _ in range(2):
            seq += 1
            _beat(t, "miner", "hk", seq)
            fm.poll(["hk"])
            rem.observe_round(fm.evaluate_slos())
        assert not rem.is_excluded("hk")
        node = fm.nodes[("miner", "hk")]
        assert not node.quarantined and node.probation
        assert rem.readmissions == 1
        # relapse DURING probation: the re-armed rule fires and
        # re-quarantines immediately
        for _ in range(3):
            fm.poll(["hk"])
            rem.observe_round(fm.evaluate_slos())
        assert rem.is_excluded("hk")
        acts = [r["remediation"] for r in sink.records
                if "remediation" in r]
        assert acts == ["quarantined", "readmitted", "requarantined"]
    finally:
        fm.close()


def test_probation_expires_to_healthy():
    t = InMemoryTransport()
    fm = FleetMonitor(t, rules=[SLORule("stale_node", "stale", threshold=1)])
    rem = RemediationEngine(
        fm, policy=RemediationPolicy(quarantine_rules=("stale_node",),
                                     probation_beats=1, probation_rounds=1))
    try:
        seq = 1
        _beat(t, "miner", "hk", seq)
        fm.poll(["hk"])
        for _ in range(3):
            fm.poll(["hk"])
            rem.observe_round(fm.evaluate_slos())
        assert rem.is_excluded("hk")
        for _ in range(3):          # beats keep coming, rounds pass
            seq += 1
            _beat(t, "miner", "hk", seq)
            fm.poll(["hk"])
            rem.observe_round(fm.evaluate_slos())
        assert "hk" not in rem.cases          # healthy again
        node = fm.nodes[("miner", "hk")]
        assert not node.quarantined and not node.probation
    finally:
        fm.close()


def test_quarantine_only_configured_rules():
    t = InMemoryTransport()
    fm = FleetMonitor(t, rules=[SLORule("stale_node", "stale", threshold=1)])
    rem = RemediationEngine(
        fm, policy=RemediationPolicy(quarantine_rules=("loss_divergence",)))
    try:
        _beat(t, "miner", "hk", 1)
        fm.poll(["hk"])
        for _ in range(3):
            fm.poll(["hk"])
            rem.observe_round(fm.evaluate_slos())
        # the stale breach fired but is not a quarantining rule here
        assert not rem.is_excluded("hk")
        assert fm.nodes[("miner", "hk")].breaches == ["stale_node"]
    finally:
        fm.close()


# ---------------------------------------------------------------------------
# Elastic cohorts
# ---------------------------------------------------------------------------

def test_elastic_cohort_ladder_and_compiled_preference():
    assert elastic_cohort(8, 8) == 8            # healthy: unchanged
    assert elastic_cohort(8, 12) == 8
    assert elastic_cohort(1, 0) == 1
    assert elastic_cohort(8, 3) == 4            # ladder bucket covering 3
    assert elastic_cohort(8, 3, compiled=[8]) == 8   # reuse the compiled one
    assert elastic_cohort(16, 5, compiled=[8, 16]) == 8
    assert elastic_cohort(16, 5, compiled=[2]) == 8  # too small to cover 5
    assert elastic_cohort(8, 0) == 1


def test_cohort_evaluator_prefers_compiled_bucket():
    obs.configure(InMemorySink(), role="t")
    model, cfg = gpt2.make_model("tiny")
    engine = TrainEngine(model, seq_len=8)
    base = engine.place_params(model.init_params(jax.random.PRNGKey(0)))
    zeros = jax.tree_util.tree_map(lambda x: np.zeros(x.shape, x.dtype),
                                   jax.device_get(base))
    batch = {"input_ids": np.zeros((2, 8), np.int32)}
    ev = BatchedCohortEvaluator(engine, prefer_compiled=True)
    ev.evaluate_cohort(base, [zeros] * 8, iter([batch]))   # compiles k=8
    assert ev.compiled_buckets() == frozenset({8})
    reg = obs.registry()
    assert reg.counter("val.cohort_bucket_compiles").value == 1
    # a shrunken fleet (3 candidates -> ladder bucket 4) pads UP to the
    # compiled 8-bucket instead of compiling the 4-bucket
    assert ev.bucket_for(3) == 8
    ev.evaluate_cohort(base, [zeros] * 3, iter([batch]))
    assert reg.counter("val.cohort_bucket_compiles").value == 1
    assert ev.compiled_buckets() == frozenset({8})
    # without the preference, the same shrink walks the ladder
    ev2 = BatchedCohortEvaluator(engine)
    ev2._buckets_seen.add(8)
    assert ev2.bucket_for(3) == 4


# ---------------------------------------------------------------------------
# The lease protocol
# ---------------------------------------------------------------------------

def test_lease_acquire_renew_supersede():
    t = InMemoryTransport()
    a = LeaseManager(t, "avg0")
    b = LeaseManager(t, "avg1")
    assert not a.holds()
    assert a.acquire() and a.epoch == 1
    assert a.renew() is True                    # uncontested renewal
    assert b.acquire() and b.epoch == 2         # successor epoch
    assert a.renew() is False and not a.holds()  # superseded: stand down
    assert b.renew() is True
    b.stamp("rev-42")
    cur = parse_lease(t.fetch_delta_meta(lease_id()))
    assert cur["epoch"] == 2 and cur["holder"] == "avg1"
    assert cur["base_revision"] == "rev-42"
    # a re-acquisition by the old holder moves PAST the observed epoch
    assert a.acquire() and a.epoch == 3


def test_lease_renew_fail_safe_on_unreadable_token():
    class Flaky(InMemoryTransport):
        broken = False

        def fetch_delta_meta(self, miner_id):
            if self.broken:
                raise OSError("partitioned")
            return super().fetch_delta_meta(miner_id)

    t = Flaky()
    a = LeaseManager(t, "avg0")
    assert a.acquire()
    t.broken = True
    # cannot confirm ownership -> must NOT publish this round
    assert a.renew() is False
    t.broken = False
    assert a.renew() is True                    # still epoch-1 holder


def test_parse_lease_rejects_junk():
    assert parse_lease(None) is None
    assert parse_lease({"epoch": 1}) is None
    assert parse_lease({"lease": 1, "epoch": 0, "holder": "x"}) is None
    assert parse_lease({"lease": 1, "epoch": 2, "holder": ""}) is None
    got = parse_lease({"lease": 1, "epoch": 2, "holder": "h", "t": 5,
                       "base_revision": 9})
    assert got == {"lease": 1, "epoch": 2, "holder": "h", "t": 5.0}


def test_standby_read_faults_do_not_reset_the_stall_clock():
    """A watch-read fault is "no evidence", not "activity": the stall
    clock keeps running through transport flaps and takeover still
    fires on deadline. Before this rule a flaky transport reset the
    clock on every value->None flap and could starve the failover
    indefinitely — the fleetsim chaos runs (tests/test_fleetsim.py)
    surfaced takeover latency scaling with the fetch error rate."""
    class Flaky(InMemoryTransport):
        broken = False

        def fetch_delta_meta(self, miner_id):
            if self.broken:
                raise OSError("flap")
            return super().fetch_delta_meta(miner_id)

        def base_revision(self):
            if self.broken:
                raise OSError("flap")
            return super().base_revision()

    clock = FakeClock(0.0)
    t = Flaky()
    primary = LeaseManager(t, "primary", clock=clock)
    assert primary.acquire() and primary.epoch == 1

    class _Loop:
        transport = t

        def bootstrap(self):
            pass

    standby_lease = LeaseManager(t, "standby", clock=clock)
    standby = StandbyAverager(_Loop(), standby_lease, deadline_s=100.0,
                              poll_s=10.0, clock=clock)
    assert standby.poll_once() == "following"     # baseline signature
    clock.advance(60.0)
    t.broken = True                               # every watch read flaps
    assert standby.poll_once() == "following"
    clock.advance(60.0)
    t.broken = False
    # 120s of NO positive evidence > deadline: the flap did not reset it
    assert standby.poll_once() == "takeover"
    assert standby.active and standby_lease.epoch == 2
    # and genuine primary activity DOES reset: fresh standby, renewing
    # primary
    standby2 = StandbyAverager(_Loop(), LeaseManager(t, "s2", clock=clock),
                               deadline_s=100.0, poll_s=10.0, clock=clock)
    assert standby2.poll_once() == "following"
    clock.advance(90.0)
    standby_lease.stamp("rev-x")                  # holder activity
    assert standby2.poll_once() == "following"
    clock.advance(90.0)                           # 90 < 100 since activity
    standby_lease.stamp("rev-y")
    assert standby2.poll_once() == "following"
    assert standby2.stalled_for() < 100.0


# ---------------------------------------------------------------------------
# Miner preemption-resume hardening (satellite; localfs regression)
# ---------------------------------------------------------------------------

def _mini_batches(cfg, n=3):
    rng = np.random.default_rng(0)
    return iter([{"input_ids": np.asarray(
        rng.integers(0, cfg.vocab_size, (2, 16)), np.int32)}] * n)


def test_miner_stale_checkpoint_falls_back_to_current_base(tmp_path):
    from distributedtraining_tpu.checkpoint import CheckpointStore
    from distributedtraining_tpu.engine.train import host_wire_template

    model, cfg = gpt2.make_model("tiny")
    transport = LocalFSTransport(str(tmp_path / "artifacts"))
    engine = TrainEngine(model, seq_len=16)
    # a published base the first miner run trains against
    template = host_wire_template(engine)
    base1 = jax.tree_util.tree_map(
        lambda x: np.full(x.shape, 0.01, x.dtype), template)
    transport.publish_base(base1)
    with CheckpointStore(str(tmp_path / "ckpt")) as store:
        loop = MinerLoop(TrainEngine(model, seq_len=16), transport, "hk",
                         send_interval=1e9, check_update_interval=1e9,
                         checkpoint_store=store, checkpoint_interval=1e9)
        loop.bootstrap(jax.random.PRNGKey(0))
        rev1 = loop._base_revision
        assert rev1 is not None
        loop.run(_mini_batches(cfg), max_steps=2)
        loop._save_checkpoint()
        assert store.latest_step() is not None

    # while preempted: the checkpointed revision VANISHES (a new base
    # replaces it — the averager moved on)
    base2 = jax.tree_util.tree_map(
        lambda x: np.full(x.shape, 0.02, x.dtype), template)
    rev2 = transport.publish_base(base2)
    assert rev2 != rev1

    with CheckpointStore(str(tmp_path / "ckpt")) as store2:
        loop2 = MinerLoop(TrainEngine(model, seq_len=16), transport, "hk",
                          send_interval=1e9, check_update_interval=1e9,
                          checkpoint_store=store2, checkpoint_interval=1e9)
        # must not crash: the stale snapshot's base is gone, so bootstrap
        # pulls the CURRENT base fresh
        loop2.bootstrap(jax.random.PRNGKey(0))
        assert loop2._base_revision == rev2
        assert loop2.state is not None
        # and it can keep training + pushing against the new base
        loop2.run(_mini_batches(cfg), max_steps=1)
        loop2._push_delta()
        loop2._publisher.flush()
        assert loop2.report.pushes == 1


def test_miner_resume_survives_partitioned_base_probe(tmp_path):
    from distributedtraining_tpu.checkpoint import CheckpointStore

    model, cfg = gpt2.make_model("tiny")
    transport = InMemoryTransport()     # NO base: genesis self-init, so the
    #                                     base travels inside the snapshot
    with CheckpointStore(str(tmp_path / "ckpt")) as store:
        loop = MinerLoop(TrainEngine(model, seq_len=16), transport, "hk",
                         send_interval=1e9, check_update_interval=1e9,
                         checkpoint_store=store, checkpoint_interval=1e9)
        loop.bootstrap(jax.random.PRNGKey(0))
        loop.run(_mini_batches(cfg), max_steps=2)
        loop._save_checkpoint()

    class Partitioned(InMemoryTransport):
        def base_revision(self):
            raise OSError("backend unreachable")

    with CheckpointStore(str(tmp_path / "ckpt")) as store2:
        loop2 = MinerLoop(TrainEngine(model, seq_len=16), Partitioned(),
                          "hk", send_interval=1e9, check_update_interval=1e9,
                          checkpoint_store=store2, checkpoint_interval=1e9)
        # the post-resume "did the base move" probe hits the partition;
        # the resume must survive on the checkpoint instead of crashing
        # (under supervise.sh a raise here burns the crash-loop budget)
        loop2.bootstrap(jax.random.PRNGKey(0))
        assert loop2.state is not None
        assert loop2.report.steps == 2


# ---------------------------------------------------------------------------
# The acceptance round: chaos, quarantine, failover — one localfs fleet
# ---------------------------------------------------------------------------

def _batch(cfg, n=2, seq=16, seed=0):
    rng = np.random.default_rng(seed)
    return {"input_ids": np.asarray(
        rng.integers(0, cfg.vocab_size, (n, seq)), np.int32)}


class _StubChain:
    """A 4-node registry (LocalChain's fixed 100-hotkey metagraph would
    make every partitioned round pay ~100 retry-fetch timeouts)."""

    def __init__(self, hotkeys, my_hotkey):
        self._hotkeys = list(hotkeys)
        self.my_hotkey = my_hotkey

    def sync(self):
        from types import SimpleNamespace
        return SimpleNamespace(hotkeys=list(self._hotkeys))


def test_chaos_round_quarantine_and_averager_failover(tmp_path):
    """Miner killed mid-round + averager killed mid-run, both under
    ChaosTransport: rounds keep completing, the quarantine lands in the
    ledger (with probation re-admission), and exactly one averager
    publication per round carries a monotonically increasing epoch."""
    model, cfg = gpt2.make_model("tiny")
    art = str(tmp_path / "artifacts")
    hotkeys = ["hotkey_0", "hotkey_1", "hotkey_2"]
    sink = InMemorySink()
    obs.configure(sink, role="averager")
    clock = FakeClock(1000.0)
    plain = LocalFSTransport(art)

    def eval_batches():
        yield _batch(cfg, seed=1)

    # -- miners: synthetic deltas + heartbeats ------------------------------
    from distributedtraining_tpu.engine.train import host_wire_template
    engine = TrainEngine(model, seq_len=16)
    template = host_wire_template(engine)
    leaves, treedef = jax.tree_util.tree_flatten(template)
    key = jax.random.PRNGKey(1)
    for hk in hotkeys:
        key, k = jax.random.split(key)
        ks = jax.random.split(k, len(leaves))
        plain.publish_delta(hk, jax.tree_util.tree_unflatten(
            treedef, [0.01 * np.asarray(jax.random.normal(s, l.shape),
                                        l.dtype)
                      for s, l in zip(ks, leaves)]))
    seqs = dict.fromkeys(hotkeys, 0)

    def beat(hk):
        seqs[hk] += 1
        _beat(plain, "miner", hk, seqs[hk], steps=float(seqs[hk]))

    # -- the primary averager: chaos transport + fleet + remediation + lease
    chaos = ChaosTransport(LocalFSTransport(art), role="averager")
    afm = FleetMonitor(chaos, metrics=sink, clock=clock,
                       rules=[SLORule("stale_node", "stale", threshold=1)])
    rem = RemediationEngine(
        afm, metrics=sink,
        policy=RemediationPolicy(quarantine_rules=("stale_node",),
                                 probation_beats=2, probation_rounds=1))
    lease = LeaseManager(chaos, "hotkey_99", clock=clock)
    avg = AveragerLoop(engine, chaos,
                       _StubChain(hotkeys + ["hotkey_99"], "hotkey_99"),
                       WeightedAverage(uniform=True),
                       val_batches=eval_batches, metrics=sink, clock=clock,
                       publish_policy="always", fleet=afm,
                       remediation=rem, lease=lease)
    assert lease.acquire() and lease.epoch == 1
    avg.bootstrap(rng=jax.random.PRNGKey(0))

    epochs = []                     # (epoch, base_revision) per publish

    def record_publish():
        cur = parse_lease(plain.fetch_delta_meta(lease_id()))
        assert cur is not None
        assert cur["base_revision"] == plain.base_revision(), \
            "the publication must carry the epoch that published it"
        epochs.append(cur["epoch"])

    def live_round(*miners):
        for hk in miners:
            beat(hk)
        prev = plain.base_revision()
        assert avg.run_round() is True
        assert plain.base_revision() != prev, "round did not publish"
        record_publish()

    # round 1: everyone healthy, all three merge
    live_round(*hotkeys)
    assert avg.report.last_accepted == 3
    assert math.isfinite(avg.report.last_loss)

    # -- miner hotkey_2 is KILLED mid-round: no more beats, and its
    # artifact partitions away (the averager sees fetch errors, not bytes)
    chaos.partition("hotkey_2")
    live_round("hotkey_0", "hotkey_1")          # r2: 1 silent round
    live_round("hotkey_0", "hotkey_1")          # r3: stale breach fires
    assert rem.is_excluded("hotkey_2")
    led = afm.ledger()
    assert led["miner/hotkey_2"]["quarantined"] == 1
    assert led["miner/hotkey_2"]["breaches"] == ["stale_node"]

    # steady state under quarantine: rounds keep merging the healthy two,
    # the exclusion shows in the ledger, and NO fresh screen/compile work
    # happens (everything rides the ingest cache + compiled programs)
    reg = obs.registry()
    fresh_before = reg.counter("screen.fresh_compiles").value
    compile_before = reg.histogram("compile.ms").count
    live_round("hotkey_0", "hotkey_1")          # r4
    assert avg.report.last_accepted == 2
    assert afm.ledger()["miner/hotkey_2"]["last_reason"] == "quarantined"
    assert reg.counter("screen.fresh_compiles").value == fresh_before
    assert reg.histogram("compile.ms").count == compile_before

    # -- hotkey_2 revives: clean heartbeats re-admit it into probation,
    # then it merges again
    chaos.heal("hotkey_2")
    live_round(*hotkeys)                        # r5: clean beat 1
    assert rem.is_excluded("hotkey_2")
    live_round(*hotkeys)                        # r6: clean beat 2 -> probation
    assert not rem.is_excluded("hotkey_2")
    assert afm.ledger()["miner/hotkey_2"]["probation"] == 1
    accepted_before = afm.ledger()["miner/hotkey_2"]["accepted"]
    live_round(*hotkeys)                        # r7: staged + merged again
    assert avg.report.last_accepted == 3
    assert afm.ledger()["miner/hotkey_2"]["accepted"] == accepted_before + 1
    assert "hotkey_2" not in rem.cases          # probation expired: healthy

    # every publish so far carried epoch 1
    assert epochs == [1] * len(epochs) and len(epochs) == 7

    # -- the averager is KILLED mid-run: its transport goes dark ------------
    chaos.kill_role("averager")
    for hk in hotkeys:
        beat(hk)
    prev_rev = plain.base_revision()
    assert avg.run_round() is False             # survives; nothing merges
    assert plain.base_revision() == prev_rev    # and nothing publishes

    # -- the standby detects the silence and takes over ---------------------
    lease2 = LeaseManager(plain, "hotkey_98", clock=clock)
    loop2 = AveragerLoop(TrainEngine(model, seq_len=16),
                         LocalFSTransport(art),
                         _StubChain(hotkeys + ["hotkey_98"], "hotkey_98"),
                         WeightedAverage(uniform=True),
                         val_batches=eval_batches, clock=clock,
                         publish_policy="always", lease=lease2)
    standby = StandbyAverager(loop2, lease2, deadline_s=100.0, poll_s=10.0,
                              clock=clock)
    assert standby.poll_once() == "following"   # baseline signature
    clock.advance(150.0)                        # primary silent past deadline
    assert standby.poll_once() == "takeover"
    assert standby.active and lease2.epoch == 2  # the successor epoch

    prev_rev = plain.base_revision()
    assert loop2.run_round() is True            # the standby's first round
    assert plain.base_revision() != prev_rev
    cur = parse_lease(plain.fetch_delta_meta(lease_id()))
    assert cur["epoch"] == 2 and cur["holder"] == "hotkey_98"
    assert cur["base_revision"] == plain.base_revision(), \
        "the standby's first publication carries the successor epoch"
    epochs.append(cur["epoch"])

    # -- the old primary comes back: it must STAND DOWN, not dual-publish ---
    chaos.revive_role("averager")
    for hk in hotkeys:
        beat(hk)
    skipped_before = avg.report.skipped_publishes
    standby_rev = plain.base_revision()
    assert avg.run_round() is True              # merges, refuses to publish
    assert avg.report.skipped_publishes == skipped_before + 1
    assert plain.base_revision() == standby_rev
    assert not lease.holds()

    # monotone epoch sequence across the whole run, exactly one writer
    assert epochs == sorted(epochs) and epochs[-1] == 2
    assert epochs.count(2) == 1 and epochs.count(1) == 7

    # the remediation + breach story is joinable offline too
    import json
    jsonl = tmp_path / "averager.jsonl"
    with open(jsonl, "w") as f:
        for r in sink.records:
            try:
                f.write(json.dumps(r, default=float) + "\n")
            except (TypeError, ValueError):
                pass
    rep = fleet_report.build_report([str(jsonl)])
    acts = [r["remediation"] for r in rep["remediations"]]
    assert acts[:2] == ["quarantined", "readmitted"]
    table = fleet_report.format_table(rep)
    assert "stale_node" in table

    avg.close()
    loop2.close()
