"""Artifact authenticity: Ed25519 envelopes + SignedTransport policy.

Reference anchor: hotkey-signed metric posts verified by the receiver
(hivetrain/utils/dummy_miner.py:63-68) and HF repo ownership. Here the same
trust applies to the artifacts themselves: forged or tampered payloads are
rejected, unsigned payloads are rejected once a hotkey has a registered key,
and the full loadgen poison battery (including "forged") is screened.
"""

import numpy as np
import pytest

from distributedtraining_tpu import serialization as ser
from distributedtraining_tpu import signing
from distributedtraining_tpu.chain import LocalAddressStore
from distributedtraining_tpu.transport import (InMemoryTransport,
                                               LocalFSTransport,
                                               SignedTransport)
from distributedtraining_tpu.utils.identity import Identity
from distributedtraining_tpu.utils.loadgen import LoadGenerator


def tree():
    return {"w": np.arange(4, dtype=np.float32), "b": np.zeros(2, np.float32)}


# -- envelope primitives -----------------------------------------------------

def test_wrap_unwrap_roundtrip():
    ident = Identity.generate()
    payload = b"hello artifact"
    ctx = signing.delta_context("hk1")
    env = signing.wrap(payload, ident, ctx)
    assert signing.is_enveloped(env)
    assert signing.unwrap(env, ctx) == payload
    assert signing.unwrap(env, ctx, expected_pub=ident.public_bytes) == payload


def test_unwrap_rejects_tamper_and_wrong_key_and_context():
    ident = Identity.generate()
    ctx = signing.delta_context("hk1")
    env = signing.wrap(b"data", ident, ctx)
    # payload tamper
    bad = env[:-1] + bytes([env[-1] ^ 1])
    with pytest.raises(ser.PayloadError):
        signing.unwrap(bad, ctx)
    # wrong expected pub (claimed hotkey has a different registered key)
    other = Identity.generate()
    with pytest.raises(ser.PayloadError):
        signing.unwrap(env, ctx, expected_pub=other.public_bytes)
    # cross-protocol replay: a delta envelope presented as a base
    with pytest.raises(ser.PayloadError):
        signing.unwrap(env, signing.base_context("hk1"))
    # replay under another hotkey
    with pytest.raises(ser.PayloadError):
        signing.unwrap(env, signing.delta_context("hk2"))


def test_unwrap_unsigned_policy():
    raw = b"plain bytes"
    assert signing.unwrap(raw, b"ctx") == raw
    with pytest.raises(ser.PayloadError):
        signing.unwrap(raw, b"ctx", require=True)


# -- SignedTransport over real backends --------------------------------------

@pytest.fixture(params=["memory", "localfs"])
def inner(request, tmp_path):
    if request.param == "memory":
        return InMemoryTransport()
    return LocalFSTransport(str(tmp_path / "artifacts"))


def test_signed_delta_roundtrip_and_forgery(inner, tmp_path):
    store = LocalAddressStore(str(tmp_path / "chain"))
    miner_ident = Identity.generate()
    store.store_pubkey("m0", miner_ident.public_bytes)

    miner_t = SignedTransport(inner, identity=miner_ident,
                              pubkey_resolver=store.retrieve_pubkey,
                              my_hotkey="m0")
    validator_t = SignedTransport(inner,
                                  pubkey_resolver=store.retrieve_pubkey)

    miner_t.publish_delta("m0", tree())
    got = validator_t.fetch_delta("m0", tree())
    assert got is not None
    np.testing.assert_array_equal(got["w"], tree()["w"])

    # attacker overwrites with an artifact signed by their own key
    attacker = Identity.generate()
    forged = signing.wrap(ser.to_msgpack(tree()), attacker,
                          signing.delta_context("m0"))
    inner.publish_raw("m0", forged)
    assert validator_t.fetch_delta("m0", tree()) is None

    # attacker downgrades to unsigned: also rejected (key is registered)
    inner.publish_raw("m0", ser.to_msgpack(tree()))
    assert validator_t.fetch_delta("m0", tree()) is None

    # unregistered hotkey, unsigned artifact: accepted (mixed fleet)
    inner.publish_raw("anon", ser.to_msgpack(tree()))
    assert validator_t.fetch_delta("anon", tree()) is not None
    # ... unless strict
    strict_t = SignedTransport(inner, pubkey_resolver=store.retrieve_pubkey,
                               strict=True)
    assert strict_t.fetch_delta("anon", tree()) is None


def test_signed_base_roundtrip_and_forgery(inner, tmp_path):
    store = LocalAddressStore(str(tmp_path / "chain"))
    avg_ident = Identity.generate()
    store.store_pubkey("hotkey_99", avg_ident.public_bytes)

    averager_t = SignedTransport(inner, identity=avg_ident,
                                 pubkey_resolver=store.retrieve_pubkey,
                                 my_hotkey="hotkey_99")
    miner_t = SignedTransport(inner, pubkey_resolver=store.retrieve_pubkey,
                              base_signer="hotkey_99")

    averager_t.publish_base(tree())
    fetched = miner_t.fetch_base(tree())
    assert fetched is not None
    got, rev = fetched
    assert rev is not None
    np.testing.assert_array_equal(got["b"], tree()["b"])

    # attacker replaces the base with one signed by their own key
    attacker = Identity.generate()
    inner.publish_base_raw(signing.wrap(ser.to_msgpack(tree()), attacker,
                                        signing.base_context("hotkey_99")))
    assert miner_t.fetch_base(tree()) is None
    # or an unsigned base: rejected too (signer key is registered)
    inner.publish_base_raw(ser.to_msgpack(tree()))
    assert miner_t.fetch_base(tree()) is None


def test_pubkey_first_write_wins(tmp_path):
    store = LocalAddressStore(str(tmp_path))
    a, b = Identity.generate(), Identity.generate()
    store.store_pubkey("hk", a.public_bytes)
    store.store_pubkey("hk", a.public_bytes)  # idempotent re-register ok
    with pytest.raises(ValueError):
        store.store_pubkey("hk", b.public_bytes)
    assert store.retrieve_pubkey("hk") == a.public_bytes


# -- loadgen forged mode ------------------------------------------------------

def test_loadgen_forged_poison_screened(tmp_path):
    """A signed fleet under the full poison battery: forged artifacts die at
    the authenticity screen, numeric poisons pass it (correctly signed) and
    die at the value screens."""
    inner = InMemoryTransport()
    store = LocalAddressStore(str(tmp_path))
    gen = LoadGenerator(inner, tree(), n_miners=10, poison_fraction=0.5,
                        sign=True)
    gen.register_pubkeys(store)
    gen.publish_round()
    assert gen.report.by_mode.get("forged", 0) >= 1

    validator_t = SignedTransport(inner, pubkey_resolver=store.retrieve_pubkey)
    fetched = {hk: validator_t.fetch_delta_bytes(hk) for hk in gen.hotkeys()}
    # poison order is deterministic: first n_poison identities, cycling modes
    modes = ("nan", "shape", "huge", "garbage", "forged")
    for i, hk in enumerate(gen.hotkeys()):
        data = fetched[hk]
        if i < 5 and modes[i] in ("garbage", "forged"):
            # garbage is unsigned (registered key -> rejected);
            # forged is wrong-key (rejected)
            assert data is None, (i, modes[i])
        elif i < 5:
            # correctly signed numeric poison: authenticity passes, the
            # value screens must catch it downstream
            assert data is not None
        else:
            # benign signed artifacts fetch and validate
            assert data is not None
            assert ser.validated_load(data, tree()) is not None


def test_base_accepted_without_configured_signer(inner, tmp_path):
    """A node with --sign-artifacts but no --base-signer still accepts a
    validly signed base (no trust anchor to check identity against) but
    rejects a delta envelope replayed as a base (kind check rides in the
    envelope)."""
    avg = Identity.generate()
    averager_t = SignedTransport(inner, identity=avg, my_hotkey="hotkey_99")
    miner_t = SignedTransport(inner)  # no base_signer, no resolver

    averager_t.publish_base(tree())
    fetched = miner_t.fetch_base(tree())
    assert fetched is not None

    # a signed DELTA replayed into the base slot is rejected by kind
    replay = signing.wrap(ser.to_msgpack(tree()), avg,
                          signing.delta_context("hotkey_99"))
    inner.publish_base_raw(replay)
    assert miner_t.fetch_base(tree()) is None

    # strict mode refuses unsigned bases even without a signer identity
    inner.publish_base_raw(ser.to_msgpack(tree()))
    assert miner_t.fetch_base(tree()) is not None   # lenient: accepted
    strict_t = SignedTransport(inner, strict=True)
    assert strict_t.fetch_base(tree()) is None


def test_rate_limiter_bounded_state():
    """Distinct-hotkey floods cannot grow limiter bookkeeping without bound;
    with the limiter disabled no state is kept at all."""
    from distributedtraining_tpu.chain.base import RateLimiter

    off = RateLimiter(0.0)
    for i in range(1000):
        assert off.allow(f"hk{i}")
    assert not off._last_request

    t = [0.0]
    on = RateLimiter(5.0, now_fn=lambda: t[0], max_tracked=64)
    for i in range(1000):
        t[0] += 10.0
        assert on.allow(f"hk{i}")
    assert len(on._last_request) <= 64


def test_unsigned_node_reads_signed_fleet(inner, tmp_path):
    """Mixed fleet, reverse direction: a node NOT running --sign-artifacts
    must still read a signed fleet's artifacts (it gains no authenticity,
    same trust as unsigned) instead of silently seeing 'no base' and
    self-initializing a divergent genesis."""
    avg, miner = Identity.generate(), Identity.generate()
    SignedTransport(inner, identity=avg,
                    my_hotkey="hotkey_99").publish_base(tree())
    SignedTransport(inner, identity=miner,
                    my_hotkey="m0").publish_delta("m0", tree())

    # plain transport (no SignedTransport wrapper at all)
    assert inner.base_revision() is not None
    fetched = inner.fetch_base(tree())
    assert fetched is not None, "unsigned node must read the signed base"
    np.testing.assert_array_equal(fetched[0]["w"], tree()["w"])
    got = inner.fetch_delta("m0", tree())
    assert got is not None, "unsigned node must read signed deltas"
    # raw-bytes path stays enveloped (SignedTransport verifies from it)
    assert signing.is_enveloped(inner.fetch_delta_bytes("m0"))


def test_replayed_stale_base_rejected(tmp_path):
    """An attacker with write access replaying an OLD validly-signed base
    (fleet rollback) is rejected: the signed context carries a monotonic
    sequence and verifiers keep a high-water mark."""
    inner = InMemoryTransport()
    store = LocalAddressStore(str(tmp_path))
    avg = Identity.generate()
    store.store_pubkey("hotkey_99", avg.public_bytes)

    t = [1000.0]
    averager_t = SignedTransport(inner, identity=avg,
                                 pubkey_resolver=store.retrieve_pubkey,
                                 my_hotkey="hotkey_99", now_fn=lambda: t[0])
    miner_t = SignedTransport(inner, pubkey_resolver=store.retrieve_pubkey,
                              base_signer="hotkey_99")

    averager_t.publish_base(tree())
    stale_bytes = inner.fetch_base_bytes()      # attacker records round N
    assert miner_t.fetch_base(tree()) is not None

    t[0] = 2000.0
    newer = tree()
    newer["w"] = newer["w"] + 1
    averager_t.publish_base(newer)
    fetched = miner_t.fetch_base(tree())
    assert fetched is not None                  # round N+1 accepted

    inner.publish_base_raw(stale_bytes)         # rollback attempt
    assert miner_t.fetch_base(tree()) is None   # sequence went backwards

    # but a freshly booted node (no watermark yet) still bootstraps
    fresh = SignedTransport(inner, pubkey_resolver=store.retrieve_pubkey,
                            base_signer="hotkey_99")
    assert fresh.fetch_base(tree()) is not None


def test_unsigned_validator_scores_signed_fleet(tmp_path):
    """fetch_delta_any's raw-bytes fast path (what the Validator actually
    uses) must strip unverified envelopes too — otherwise an unsigned
    validator on a signed fleet silently scores every miner 0."""
    from distributedtraining_tpu.engine.lora_train import fetch_delta_any
    from distributedtraining_tpu.models.lora import LoRAConfig

    inner = InMemoryTransport()
    miner = Identity.generate()
    SignedTransport(inner, identity=miner,
                    my_hotkey="m0").publish_delta("m0", tree())

    got = fetch_delta_any(inner, "m0", tree(), LoRAConfig(rank=2))
    assert got is not None
    np.testing.assert_array_equal(got["w"], tree()["w"])
