"""Device performance observatory (utils/devprof.py).

The tentpole contracts of ISSUE 12: per-program XLA cost attribution
(skip-not-fail where the backend has no cost model), the roofline
table's unknown-chip fallback, the closed program vocabulary as a
producer-side lint (plus the source-level lint that every jax.jit in
the five hot-path modules is wrapped or explicitly exempted), the
cardinality cap, the obs.flush mirror, the step-time anatomy join,
perf_report's where-the-time-goes/coverage table, and the
postmortem/perf_report Chrome-trace export round trip.
"""

import json
import os
import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributedtraining_tpu.utils import devprof, obs
from distributedtraining_tpu.utils.metrics import InMemorySink, JSONLSink

sys.path.insert(0, os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "scripts"))
import obs_report   # noqa: E402
import perf_report  # noqa: E402
import postmortem   # noqa: E402


@pytest.fixture(autouse=True)
def _clean():
    obs.reset()
    devprof.reset()
    yield
    devprof.reset()
    obs.reset()


# ---------------------------------------------------------------------------
# Roofline
# ---------------------------------------------------------------------------

def test_roofline_known_chips():
    v5e = devprof.roofline_for("TPU v5 lite")
    assert v5e.known and v5e.peak_flops == 197e12
    assert devprof.roofline_for("TPU v6 lite").peak_flops == 918e12
    assert devprof.roofline_for("TPU v5p").peak_flops == 459e12
    v4 = devprof.roofline_for("TPU v4")
    assert v4.hbm_bytes_per_s == 1228e9
    # ridge point = peak flops / peak bandwidth
    assert v4.ridge_intensity == pytest.approx(275e12 / 1228e9)


def test_roofline_unknown_chip_fallback():
    for kind in ("cpu", "Graphcore IPU", "", None):
        rl = devprof.roofline_for(kind)
        assert rl.known is False
        assert rl.peak_flops is None and rl.hbm_bytes_per_s is None
        assert rl.ridge_intensity is None
    # achieved fractions are omitted, never fabricated, on unknown chips
    stats = devprof.ProgramStats("train.step", "-")
    stats.flops = 1e9
    stats.exec_ms.observe(10.0)
    assert stats.achieved(devprof.roofline_for("cpu")) == (None, None)


def test_achieved_fractions_on_known_roofline():
    rl = devprof.roofline_for("TPU v5 lite")
    stats = devprof.ProgramStats("train.step", "8x1024")
    stats.flops = 197e12 * 0.005      # 0.5% of one peak-second
    stats.bytes_accessed = 819e9 * 0.01
    stats.exec_ms.observe(10.0)       # p50 = 10ms
    ff, bf = stats.achieved(rl)
    assert ff == pytest.approx(0.5)   # 0.005 peak-s of work in 0.01 s
    assert bf == pytest.approx(1.0)
    rec = stats.as_record(rl)
    assert rec["achieved_flops_frac"] == pytest.approx(0.5)
    assert rec["arith_intensity"] == pytest.approx(
        stats.flops / stats.bytes_accessed, rel=1e-3)


# ---------------------------------------------------------------------------
# wrap / track
# ---------------------------------------------------------------------------

def test_wrap_rejects_unknown_program_name():
    # the producer-side lint (the flight.EVENT_KINDS discipline): a hot
    # path cannot ship observed under a name outside the vocabulary
    with pytest.raises(ValueError, match="unknown devprof program"):
        devprof.wrap("my.new.thing", lambda x: x)
    with pytest.raises(ValueError, match="unknown devprof program"):
        with devprof.track("my.new.thing"):
            pass


def test_wrap_disabled_is_passthrough():
    calls = []
    w = devprof.wrap("delta.finite", lambda x: calls.append(x) or x * 2)
    assert w(3) == 6
    assert calls == [3]
    assert devprof.records() == []
    assert not devprof.dirty()


def test_wrap_records_calls_compile_and_exec():
    f = jax.jit(lambda x: (x @ x).sum())
    w = devprof.wrap("delta.merge", f,
                     bucket=lambda a, kw: a[0].shape[0])
    devprof.enable()
    x = jnp.ones((16, 16), jnp.float32)
    for _ in range(4):
        w(x)
    recs = devprof.records()
    assert len(recs) == 1
    r = recs[0]
    assert (r.prog, r.bucket) == ("delta.merge", "16")
    assert r.calls == 4
    assert r.compile_ms is not None and r.compile_ms > 0
    # first call is compile, the other three land in the exec histogram
    assert r.exec_ms.count == 3
    # cost attribution: skip-not-fail when the backend has no cost model
    if devprof.cost_analysis_available():
        assert r.flops and r.flops >= 2 * 16 ** 3 * 0.9
        assert r.bytes_accessed and r.bytes_accessed > 0
    else:  # pragma: no cover — exotic backend
        assert r.flops is None
    # a second bucket is a second record
    w(jnp.ones((8, 8), jnp.float32))
    assert {rec.bucket for rec in devprof.records()} == {"16", "8"}


def test_wrap_preserves_lower_and_wrapped():
    f = jax.jit(lambda x: x + 1)
    w = devprof.wrap("delta.finite", f)
    assert w.__wrapped__ is f
    assert w._devprof_name == "delta.finite"
    # AOT/HLO introspection keeps working through the wrapper
    assert "add" in w.lower(jnp.ones((2,))).as_text()


def test_track_host_phase():
    devprof.enable()
    with devprof.track("delta.densify"):
        pass
    (r,) = devprof.records()
    assert r.prog == "delta.densify" and r.host is True
    assert r.calls == 1 and r.exec_ms.count == 1
    assert r.flops is None  # host phases get no cost probe
    rec = r.as_record(devprof.roofline_for("cpu"))
    assert rec["host"] is True


def test_cardinality_cap_drops_and_counts():
    devprof.enable(max_programs=1)
    w1 = devprof.wrap("delta.finite", jax.jit(lambda x: x + 1))
    w2 = devprof.wrap("delta.merge", jax.jit(lambda x: x * 2))
    x = jnp.ones((4,))
    w1(x)
    w2(x)  # past the cap: dropped-and-counted, still executes
    assert [r.prog for r in devprof.records()] == ["delta.finite"]
    snap = devprof.snapshot()
    assert snap["dropped_programs"] >= 1
    assert any("dt_prog_dropped" in ln for ln in devprof.prom_lines())


# ---------------------------------------------------------------------------
# Exposure: prom lines, obs.flush mirror, anatomy
# ---------------------------------------------------------------------------

def test_prom_lines_labeled_series():
    devprof.enable()
    w = devprof.wrap("serve.decode", jax.jit(lambda x: x * 2), bucket="8x16")
    x = jnp.ones((4,))
    w(x)
    w(x)
    lines = devprof.prom_lines()
    text = "\n".join(lines)
    assert 'dt_prog_calls{prog="serve.decode",bucket="8x16"} 2.0' in text
    # the labeled per-program compile series (satellite: next to the
    # unlabeled compile.ms aggregate, which keeps rendering separately)
    assert 'dt_compile_ms{prog="serve.decode",bucket="8x16"}' in text
    assert 'dt_prog_exec_ms{prog="serve.decode",bucket="8x16",q="0.5"}' \
        in text
    # disabled -> nothing rendered
    devprof.disable()
    assert devprof.prom_lines() == []


def test_obs_http_render_includes_devprof():
    from distributedtraining_tpu.utils import obs_http
    obs.configure(InMemorySink(), role="t")
    devprof.enable()
    w = devprof.wrap("delta.finite", jax.jit(lambda x: x + 1))
    w(jnp.ones((4,)))
    body = obs_http.render()
    assert 'dt_prog_calls{prog="delta.finite",bucket="-"}' in body


def test_obs_flush_mirrors_devprof_record():
    sink = InMemorySink()
    obs.configure(sink, role="miner")
    devprof.enable()
    w = devprof.wrap("delta.finite", jax.jit(lambda x: x + 1))
    w(jnp.ones((4,)))
    obs.count("x")  # a nonempty registry so flush emits
    obs.flush()
    recs = [r for r in sink.records if "devprof" in r]
    assert recs, "flush did not mirror the devprof snapshot"
    dp = recs[-1]
    assert dp["role"] == "miner"
    progs = dp["devprof"]["programs"]
    assert progs and progs[0]["prog"] == "delta.finite"
    assert dp["devprof"]["roofline"]["device_kind"]
    # disabling detaches: no further mirror records
    devprof.disable()
    n = len([r for r in sink.records if "devprof" in r])
    obs.flush()
    assert len([r for r in sink.records if "devprof" in r]) == n


def test_anatomy_fields_join_step_and_device():
    sink = InMemorySink()
    obs.configure(sink, role="miner")
    devprof.enable()
    # 10 steps of 10ms wall, 4ms attributed device time, 1ms data wait
    for _ in range(10):
        obs.observe("miner.step_ms", 10.0)
        obs.observe("miner.data_wait_ms", 1.0)
    rec = devprof._get_record("train.step", "2x32")
    rec.calls = 10
    for _ in range(10):
        rec.exec_ms.observe(4.0)
    an = devprof.anatomy()
    assert an["anat.step_ms"] == pytest.approx(10.0)
    assert an["anat.device_ms"] == pytest.approx(4.0)
    assert an["anat.host_ms"] == pytest.approx(6.0)
    assert an["anat.data_wait_ms"] == pytest.approx(1.0)
    assert an["anat.device_frac"] == pytest.approx(0.4)
    # heartbeat vitals carry the anatomy as numeric linted extras
    from distributedtraining_tpu.engine.health import (Vitals,
                                                       build_heartbeat,
                                                       parse_heartbeat)
    body = Vitals().collect()
    assert body["anat.step_ms"] == pytest.approx(10.0)
    hb = build_heartbeat("miner", "m0", 1, now=0.0, **body)
    parsed = parse_heartbeat(hb)
    assert parsed["anat.device_frac"] == pytest.approx(0.4)
    devprof.disable()
    assert devprof.anatomy() == {}


# ---------------------------------------------------------------------------
# The tier-1 registration lint (flight.EVENT_KINDS discipline, source level)
# ---------------------------------------------------------------------------

# the hot-path modules the observatory must cover (the round-20 kernel
# modules included: a Pallas hot path must not ship unobserved either)
_HOT_MODULES = (
    "distributedtraining_tpu/engine/train.py",
    "distributedtraining_tpu/engine/batched_eval.py",
    "distributedtraining_tpu/parallel/collectives.py",
    "distributedtraining_tpu/delta.py",
    "distributedtraining_tpu/engine/serve.py",
    "distributedtraining_tpu/engine/speculative.py",
    "distributedtraining_tpu/engine/kv_transfer.py",
    "distributedtraining_tpu/ops/paged_attention.py",
    "distributedtraining_tpu/ops/dequant_scatter.py",
)


def _repo_root() -> str:
    return os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_every_jit_in_hot_modules_is_registered_or_exempt():
    """Every ``jax.jit(...)`` AND ``pl.pallas_call(...)`` call in the
    hot-path modules must be wrapped in ``devprof.wrap(...)`` (so it
    reports cost/exec under a closed-vocabulary name) or carry a
    ``# devprof: exempt(<reason>)`` comment on the call line — a new
    hot path (XLA or Pallas) cannot ship unobserved."""
    import ast

    for rel in _HOT_MODULES:
        path = os.path.join(_repo_root(), rel)
        src = open(path).read()
        lines = src.splitlines()
        tree = ast.parse(src)
        parents: dict[ast.AST, ast.AST] = {}
        for node in ast.walk(tree):
            for child in ast.iter_child_nodes(node):
                parents[child] = node
        offenders = []
        for node in ast.walk(tree):
            if not (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and isinstance(node.func.value, ast.Name)
                    and ((node.func.attr == "jit"
                          and node.func.value.id == "jax")
                         or (node.func.attr == "pallas_call"
                             and node.func.value.id == "pl"))):
                continue
            # wrapped: some ancestor is a devprof.wrap(...) call
            wrapped = False
            cur = node
            while cur in parents:
                cur = parents[cur]
                if (isinstance(cur, ast.Call)
                        and isinstance(cur.func, ast.Attribute)
                        and cur.func.attr == "wrap"
                        and isinstance(cur.func.value, ast.Name)
                        and cur.func.value.id == "devprof"):
                    wrapped = True
                    break
            if wrapped:
                continue
            if "# devprof: exempt" in lines[node.lineno - 1]:
                continue
            offenders.append(f"{rel}:{node.lineno}")
        assert not offenders, (
            f"jax.jit/pl.pallas_call sites neither devprof.wrap()-"
            f"registered nor '# devprof: exempt'-annotated: {offenders}")


def test_every_wrap_name_in_hot_modules_is_in_vocabulary():
    import re
    names = set()
    for rel in _HOT_MODULES:
        src = open(os.path.join(_repo_root(), rel)).read()
        names |= set(re.findall(
            r"devprof\.(?:wrap|track)\(\s*[\"']([^\"']+)[\"']", src))
    assert names, "no registrations found in the hot-path modules"
    unknown = names - set(devprof.PROGRAMS)
    assert not unknown, f"names outside devprof.PROGRAMS: {unknown}"
    # and the engine hot paths the ISSUE names are all present
    assert {"train.step", "eval.cohort", "merge.sharded", "delta.screen",
            "delta.densify", "serve.prefill", "serve.decode",
            "delta.dequant_scatter"} <= names


# ---------------------------------------------------------------------------
# perf_report: where-the-time-goes + coverage + Perfetto export
# ---------------------------------------------------------------------------

def _run_tiny_miner(tmp_path, steps=6):
    from distributedtraining_tpu.engine import TrainEngine
    from distributedtraining_tpu.engine.train import MinerLoop
    from distributedtraining_tpu.models import gpt2
    from distributedtraining_tpu.transport import InMemoryTransport

    path = str(tmp_path / "miner.jsonl")
    sink = JSONLSink(path)
    obs.configure(sink, role="miner")
    devprof.enable()
    model, cfg = gpt2.make_model(gpt2.GPT2Config(
        n_layer=2, n_embd=32, n_head=2, vocab_size=128, n_positions=32))
    engine = TrainEngine(model, seq_len=16)
    loop = MinerLoop(engine, InMemoryTransport(), "m0",
                     send_interval=1e9, check_update_interval=1e9,
                     log_every=2, metrics=sink)
    loop.bootstrap(jax.random.PRNGKey(0))
    rng = np.random.default_rng(0)
    batch = {"input_ids": rng.integers(0, 128, (2, 16), dtype=np.int32)}

    def batches():
        while True:
            yield batch

    loop.run(batches(), max_steps=steps)
    loop.flush()
    sink.close()
    return path


def test_perf_report_table_and_coverage(tmp_path):
    """Acceptance shape: a miner run yields a per-program table whose
    attributed device programs cover >= 90% of the measured step
    wall-clock (CPU blocking timing makes attribution exact here)."""
    path = _run_tiny_miner(tmp_path)
    rep = perf_report.build_report([path])
    assert rep["programs"], "no devprof records in the run's JSONL"
    progs = {r["prog"] for r in rep["programs"]}
    assert "train.step" in progs
    cov = rep["coverage"]["miner"]
    assert cov["step_histogram"] == "miner.step_ms"
    assert cov["coverage_frac"] >= 0.90, cov
    text = perf_report.format_table(rep)
    assert "train.step" in text and "coverage[miner]" in text
    # exit contract: 0 with records, 1 without
    assert perf_report.main([path]) == 0
    empty = tmp_path / "empty.jsonl"
    empty.write_text("")
    assert perf_report.main([str(empty)]) == 1


def test_perf_report_trace_export(tmp_path):
    path = _run_tiny_miner(tmp_path)
    out = tmp_path / "round.trace.json"
    assert perf_report.main([path, "--trace", str(out)]) == 0
    trace = json.loads(out.read_text())
    evs = trace["traceEvents"]
    names = {e["name"] for e in evs if e.get("ph") == "M"}
    assert "process_name" in names
    spans = [e for e in evs if e.get("ph") == "X"]
    assert spans and all("dur" in e and e["dur"] >= 0 for e in spans)


# ---------------------------------------------------------------------------
# postmortem --trace: two-role localfs-style round trip
# ---------------------------------------------------------------------------

def test_postmortem_trace_round_trip(tmp_path, capsys):
    """Two roles' span streams sharing a cid -> one Chrome-trace file:
    one track per role, the shared correlation id in args on both."""
    miner = tmp_path / "miner.jsonl"
    avg = tmp_path / "averager.jsonl"
    cid = "m0-000001"
    miner.write_text("\n".join(json.dumps(r) for r in [
        {"span": "push.snapshot", "dur_ms": 3.0, "t0": 100.0,
         "depth": 0, "role": "miner", "cid": cid},
        {"span": "push.upload", "dur_ms": 8.0, "t0": 100.01,
         "depth": 0, "role": "miner", "cid": cid},
    ]) + "\n")
    avg.write_text("\n".join(json.dumps(r) for r in [
        {"span": "avg.fetch", "dur_ms": 5.0, "t0": 100.2,
         "depth": 0, "role": "averager", "cid": cid},
        {"span": "avg.merge", "dur_ms": 2.0, "t0": 100.3,
         "depth": 0, "role": "averager", "cids": [cid]},
    ]) + "\n")
    out = tmp_path / "pm.trace.json"
    rc = postmortem.main([str(miner), str(avg), "--json",
                          "--trace", str(out)])
    assert rc == 0
    rep = json.loads(capsys.readouterr().out)
    assert cid in rep["joined_cids"]  # the causal join still works
    trace = json.loads(out.read_text())
    evs = trace["traceEvents"]
    tracks = {e["args"]["name"] for e in evs if e["name"] == "process_name"}
    assert {"miner/-", "averager/-"} <= tracks
    spans = [e for e in evs if e.get("ph") == "X"]
    assert len(spans) == 4
    joined = [e for e in spans if e["args"].get("cid") == cid]
    assert len(joined) >= 3  # cid rides into args on both tracks
    assert {e["pid"] for e in joined} != {joined[0]["pid"]} or \
        len({e["pid"] for e in spans}) == 2
    # timestamps are relative microseconds, ordered like the input
    by_name = {e["name"]: e for e in spans}
    assert by_name["push.snapshot"]["ts"] < by_name["avg.merge"]["ts"]
