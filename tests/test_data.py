"""Packing correctness and batch iteration."""

import numpy as np

from distributedtraining_tpu.data import (
    ByteTokenizer, batch_iterator, pack_documents, text_corpus)


def test_packing_shapes_and_masks():
    docs = [[1, 2, 3], [4, 5, 6, 7, 8], [9, 10]]
    rows = list(pack_documents(docs, seq_len=8, drop_remainder=False))
    assert all(r["input_ids"].shape == (8,) for r in rows)
    r0 = rows[0]
    # first row: doc0 (3 tokens, seg 0) + doc1 first 5 tokens (seg 1)
    np.testing.assert_array_equal(r0["input_ids"], [1, 2, 3, 4, 5, 6, 7, 8])
    np.testing.assert_array_equal(r0["segment_ids"], [0, 0, 0, 1, 1, 1, 1, 1])
    np.testing.assert_array_equal(r0["position_ids"], [0, 1, 2, 0, 1, 2, 3, 4])
    # boundary between docs is masked out (token 3's label would be 4)
    assert r0["loss_mask"][2] == 0.0
    assert r0["loss_mask"][0] == 1.0


def test_packing_no_pad_waste():
    """>90% of tokens in full rows are real (the reference's pad-to-64 gets
    ~single-digit utilization on short texts)."""
    docs = [[1] * np.random.default_rng(i).integers(5, 30) for i in range(100)]
    rows = list(pack_documents(docs, seq_len=64))
    util = np.mean([np.mean(r["input_ids"] != 0) for r in rows])
    assert util > 0.9


def test_byte_tokenizer_roundtrip():
    t = ByteTokenizer()
    s = "hello wörld"
    assert t.decode(t.encode(s)) == s
    assert max(t.encode(s)) < t.vocab_size


def test_corpus_and_batches_offline():
    docs = text_corpus(split="train", n_docs=32, source="synthetic")
    assert len(docs) == 32
    tok = ByteTokenizer()
    batches = list(batch_iterator(docs, tok, batch_size=4, seq_len=32))
    assert batches
    b = batches[0]
    assert b["input_ids"].shape == (4, 32)
    assert set(b) == {"input_ids", "segment_ids", "position_ids", "loss_mask"}
    # deterministic corpus
    docs2 = text_corpus(split="train", n_docs=32, source="synthetic")
    assert docs == docs2
    assert docs != text_corpus(split="test", n_docs=32, source="synthetic")


def test_files_corpus_reads_local_text(tmp_path):
    """files:<glob> source: real local files become paragraph documents,
    train/test splits are disjoint, order is deterministic."""
    from distributedtraining_tpu.data import text_corpus

    for i in range(3):
        paras = [f"file {i} paragraph {j} " + ("lorem ipsum dolor sit amet "
                 * 12) for j in range(8)]
        (tmp_path / f"doc{i}.txt").write_text("\n\n".join(paras))
    pat = str(tmp_path / "*.txt")
    train = text_corpus(split="train", source=f"files:{pat}")
    test = text_corpus(split="test", source=f"files:{pat}")
    assert train and test
    assert not set(train) & set(test)
    assert train == text_corpus(split="train", source=f"files:{pat}")
    import pytest
    with pytest.raises(FileNotFoundError):
        text_corpus(source=f"files:{tmp_path}/*.nope")


def test_word_tokenizer_deterministic_and_realistic(tmp_path):
    """Corpus-fit word vocab: identical across independent fits (what keeps
    the roles consistent with no shared artifact), ids spread beyond the
    byte range, unknown words map to unk."""
    from distributedtraining_tpu.data import WordTokenizer, text_corpus

    docs = text_corpus(split="train", source="synthetic")
    a = WordTokenizer(docs, vocab_size=300)
    b = WordTokenizer(list(docs), vocab_size=300)
    ids = a.encode(docs[0])
    assert ids == b.encode(docs[0])
    assert all(0 < i < 300 for i in ids)
    assert a._UNK in a.encode("zzzunseenword")
    # roundtrip through decode keeps the words (word-level, so exact)
    assert a.decode(a.encode("the state model train")) == \
        "the state model train"
