"""HF checkpoint import/export: logits parity against stock transformers.

The reference fine-tunes pretrained GPT-2 (neurons/miner.py:60); these tests
prove the converter reproduces HF's computation exactly, using tiny
randomly-initialized HF models (no network) — if a random checkpoint
round-trips to <=1e-3 logits parity, the real one does too, since the
mapping is purely structural.
"""

import numpy as np
import pytest

torch = pytest.importorskip("torch")
transformers = pytest.importorskip("transformers")

from distributedtraining_tpu.models import convert, gpt2, llama

B, T = 2, 16


def _hf_gpt2(vocab=512, n_embd=64, n_layer=2, n_head=4, n_positions=128):
    cfg = transformers.GPT2Config(
        vocab_size=vocab, n_positions=n_positions, n_embd=n_embd,
        n_layer=n_layer, n_head=n_head, activation_function="gelu_new",
        resid_pdrop=0.0, embd_pdrop=0.0, attn_pdrop=0.0)
    torch.manual_seed(0)
    return transformers.GPT2LMHeadModel(cfg).eval()


def _hf_llama(vocab=512, hidden=64, n_layer=2, n_head=4, n_kv=2, inter=128):
    cfg = transformers.LlamaConfig(
        vocab_size=vocab, hidden_size=hidden, num_hidden_layers=n_layer,
        num_attention_heads=n_head, num_key_value_heads=n_kv,
        intermediate_size=inter, max_position_embeddings=128,
        rope_theta=10000.0, attention_dropout=0.0, tie_word_embeddings=False,
        rms_norm_eps=1e-5)  # align with LlamaConfig default (HF's is 1e-6;
        # real checkpoints carry eps in config.json)
    torch.manual_seed(0)
    return transformers.LlamaForCausalLM(cfg).eval()


def test_gpt2_import_logits_parity():
    hf = _hf_gpt2()
    cfg = gpt2.GPT2Config(vocab_size=512, n_positions=128, n_embd=64,
                          n_layer=2, n_head=4, vocab_multiple=128,
                          dtype="float32", attention_impl="dense")
    model, _ = gpt2.make_model(cfg)
    params = convert.gpt2_from_hf(hf.state_dict(), cfg)

    rng = np.random.default_rng(0)
    ids = rng.integers(0, cfg.vocab_size, (B, T))
    with torch.no_grad():
        ref = hf(torch.from_numpy(ids)).logits.numpy()
    got = np.asarray(model.apply({"params": params}, ids))
    # compare on the real vocab slice; padded rows produce ~0 logits that HF
    # doesn't have
    np.testing.assert_allclose(got[..., :cfg.vocab_size], ref,
                               rtol=1e-3, atol=1e-3)


def test_gpt2_export_roundtrip():
    """our tree -> HF state dict -> load_state_dict -> same logits."""
    hf = _hf_gpt2()
    cfg = gpt2.GPT2Config(vocab_size=512, n_positions=128, n_embd=64,
                          n_layer=2, n_head=4, vocab_multiple=128,
                          dtype="float32", attention_impl="dense")
    params = convert.gpt2_from_hf(hf.state_dict(), cfg)
    state = convert.gpt2_to_hf(params, cfg)

    hf2 = _hf_gpt2()
    missing, unexpected = hf2.load_state_dict(
        {k: torch.from_numpy(v.copy()) for k, v in state.items()},
        strict=False)
    assert not unexpected
    # HF registers non-persistent buffers (attn.bias etc.) that state dicts
    # may omit; no *parameter* may be missing
    assert not [m for m in missing for p, _ in hf2.named_parameters()
                if m == p]
    ids = torch.from_numpy(
        np.random.default_rng(1).integers(0, cfg.vocab_size, (B, T)))
    with torch.no_grad():
        np.testing.assert_allclose(hf2(ids).logits.numpy(),
                                   hf(ids).logits.numpy(),
                                   rtol=1e-5, atol=1e-5)


def test_llama_import_logits_parity():
    hf = _hf_llama()
    cfg = llama.LlamaConfig(vocab_size=512, max_seq_len=128, n_embd=64,
                            n_layer=2, n_head=4, n_kv_head=2,
                            intermediate_size=128, remat=False,
                            dtype="float32", vocab_multiple=128)
    model, _ = llama.make_model(cfg)
    params = convert.llama_from_hf(hf.state_dict(), cfg)

    rng = np.random.default_rng(0)
    ids = rng.integers(0, cfg.vocab_size, (B, T))
    with torch.no_grad():
        ref = hf(torch.from_numpy(ids)).logits.numpy()
    got = np.asarray(model.apply({"params": params}, ids))
    np.testing.assert_allclose(got[..., :cfg.vocab_size], ref,
                               rtol=1e-3, atol=1e-3)


def test_llama_tied_embeddings_fallback():
    hf = _hf_llama()
    state = {k: v for k, v in hf.state_dict().items()
             if k != "lm_head.weight"}
    cfg = llama.LlamaConfig(vocab_size=512, max_seq_len=128, n_embd=64,
                            n_layer=2, n_head=4, n_kv_head=2,
                            intermediate_size=128, remat=False,
                            dtype="float32", vocab_multiple=128)
    params = convert.llama_from_hf(state, cfg)
    np.testing.assert_array_equal(params["lm_head"], params["wte"])


def test_import_validates_shapes():
    hf = _hf_gpt2()
    cfg = gpt2.GPT2Config(vocab_size=512, n_positions=128, n_embd=128,  # wrong E
                          n_layer=2, n_head=4, vocab_multiple=128)
    with pytest.raises(ValueError):
        convert.gpt2_from_hf(hf.state_dict(), cfg)
    with pytest.raises(KeyError):
        convert.gpt2_from_hf({}, gpt2.PRESETS["tiny"])


def test_load_flat_safetensors_file(tmp_path):
    """File-path sources: a safetensors file written by stock tooling loads
    through the hardened parser."""
    from safetensors.numpy import save_file as st_save

    arrs = {"wte.weight": np.arange(12, dtype=np.float32).reshape(3, 4)}
    p = tmp_path / "model.safetensors"
    st_save(arrs, str(p))
    flat = convert.load_flat(str(p))
    np.testing.assert_array_equal(flat["wte.weight"], arrs["wte.weight"])
    # and via directory resolution
    flat2 = convert.load_flat(str(tmp_path))
    np.testing.assert_array_equal(flat2["wte.weight"], arrs["wte.weight"])
