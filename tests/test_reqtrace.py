"""Request-scoped serving traces (utils/reqtrace.py).

Covers the unit layer (id minting, the closed stage vocabulary, the
coalescing timeline, TraceBook lifecycle on a fake clock), the
producer-site lint that keeps every wired module inside the stage
vocabulary (satellite of the devprof/flight closed-vocabulary pattern),
and two end-to-end stories over a real engine: tracing changes no
emitted token (parity with trace=False), and a sealed window freezes
tail exemplars into the flight recorder that
scripts/request_report.py can replay as a waterfall + Chrome trace.
"""

import ast
import json
import os
import sys
import urllib.request

import jax
import pytest

from distributedtraining_tpu.engine.serve import (GenerationEngine,
                                                  ServeHTTPFrontend,
                                                  ServeLoop,
                                                  reference_generate)
from distributedtraining_tpu.models import gpt2
from distributedtraining_tpu.transport.memory import InMemoryTransport
from distributedtraining_tpu.utils import flight, obs, reqtrace

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from scripts.request_report import (collect_exemplars,  # noqa: E402
                                    format_listing, format_waterfall,
                                    trace_entries)

TINY = gpt2.GPT2Config(vocab_size=128, n_positions=64, n_embd=32,
                       n_layer=2, n_head=2, dtype="float32",
                       vocab_multiple=64)


# ---------------------------------------------------------------------------
# mint_request_id
# ---------------------------------------------------------------------------

def test_mint_is_content_addressable():
    """Same (content, meta, seq) => bit-identical id; any ingredient
    change => different id. That reproducibility is what lets the
    router, the engine, and an offline report agree on one identity."""
    a = reqtrace.mint_request_id([1, 2, 3], seq=0, temperature=0.5)
    b = reqtrace.mint_request_id([1, 2, 3], seq=0, temperature=0.5)
    assert a == b
    assert a.startswith("rq-") and len(a) == 3 + 16
    assert reqtrace.mint_request_id([1, 2, 3], seq=1,
                                    temperature=0.5) != a
    assert reqtrace.mint_request_id([1, 2, 4], seq=0,
                                    temperature=0.5) != a
    assert reqtrace.mint_request_id([1, 2, 3], seq=0,
                                    temperature=0.7) != a


def test_mint_accepts_bytes_str_and_tokens():
    for content in (b"hello", "hello", [1, 2, 3]):
        rid = reqtrace.mint_request_id(content, seq=7)
        assert rid.startswith("rq-")
    # retries without an explicit seq stay distinguishable
    assert reqtrace.mint_request_id(b"x") != reqtrace.mint_request_id(b"x")


# ---------------------------------------------------------------------------
# the closed stage vocabulary
# ---------------------------------------------------------------------------

def test_unknown_stage_rejected_at_producer():
    assert reqtrace.check_stage("decode") == "decode"
    with pytest.raises(ValueError, match="unknown reqtrace stage"):
        reqtrace.check_stage("frobnicate")
    tr = reqtrace.RequestTrace("rq-x", 0, 0.0)
    with pytest.raises(ValueError, match="unknown reqtrace stage"):
        tr.record("decodez", 1.0)
    book = reqtrace.TraceBook()
    with pytest.raises(ValueError, match="unknown reqtrace stage"):
        book.reject(None, "overloaded")


_WIRED = ("engine/serve.py", "engine/router.py", "engine/speculative.py",
          "utils/loadgen.py")


def test_producer_sites_use_registered_stages():
    """The devprof/flight pattern for reqtrace: AST-walk every wired
    module for ``.stage(rid, "<literal>")`` / ``.reject(id, "<literal>")``
    call sites and require each literal to be a registered stage. A new
    instrumentation site with a typo'd stage fails HERE even if no test
    happens to drive that code path."""
    import distributedtraining_tpu
    pkg = os.path.dirname(distributedtraining_tpu.__file__)
    found: dict[str, set[str]] = {}
    for rel in _WIRED:
        with open(os.path.join(pkg, rel)) as f:
            tree = ast.parse(f.read())
        names = set()
        for node in ast.walk(tree):
            if not (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr in ("stage", "stage_span",
                                           "reject")
                    and len(node.args) >= 2
                    and isinstance(node.args[1], ast.Constant)
                    and isinstance(node.args[1].value, str)):
                continue
            names.add(node.args[1].value)
        found[rel] = names
    # the wiring actually exists (an empty lint proves nothing)
    assert found["engine/serve.py"] >= {"admit", "prefill", "decode",
                                        "spec", "cow", "preempt", "shed",
                                        "kv_export", "kv_adopt"}
    assert "spec_draft" in found["engine/speculative.py"]
    for rel, names in found.items():
        unknown = names - set(reqtrace.STAGES)
        assert not unknown, f"{rel} records unregistered stages {unknown}"


# ---------------------------------------------------------------------------
# RequestTrace: the coalescing timeline
# ---------------------------------------------------------------------------

def test_per_step_stages_coalesce():
    """Consecutive decode/spec/cow entries merge into one batched row
    (n steps, numeric fields accumulated) so a long generation keeps a
    bounded timeline; non-coalescing stages always append."""
    tr = reqtrace.RequestTrace("rq-x", 0, 100.0)
    tr.record("queue", 100.0, depth=0)
    tr.record("admit", 100.1)
    tr.record("prefill", 100.2, pfx_hit=0, pfx_tokens=0)
    for i in range(10):
        tr.record("decode", 100.3 + i * 0.01, tokens=1)
    tr.record("spec", 100.5, n_rounds=1, proposed=4, accepted=3)
    tr.record("spec", 100.6, n_rounds=1, proposed=4, accepted=1)
    tr.record("decode", 100.7, tokens=1)
    names = [e["stage"] for e in tr.stages]
    assert names == ["queue", "admit", "prefill", "decode", "spec",
                     "decode"]
    dec = tr.stages[3]
    assert dec["n"] == 10 and dec["tokens"] == 10
    assert dec["t"] == pytest.approx(100.3)
    assert dec["t_last"] == pytest.approx(100.39)
    spec = tr.stages[4]
    assert spec["n"] == 2 and spec["proposed"] == 8 and spec["accepted"] == 4
    # readmit (not in _COALESCE) appends even when consecutive
    tr.record("preempt", 100.8)
    tr.record("preempt", 100.9)
    assert [e["stage"] for e in tr.stages[-2:]] == ["preempt", "preempt"]


def test_timeline_overflow_is_flagged_not_unbounded():
    tr = reqtrace.RequestTrace("rq-x", 0, 0.0)
    for i in range(200):
        # alternate so nothing coalesces
        tr.record("preempt" if i % 2 else "readmit", float(i))
    assert len(tr.stages) == reqtrace._MAX_STAGES
    assert tr.overflow == 200 - reqtrace._MAX_STAGES
    assert tr.as_record()["overflow"] == tr.overflow


def test_note_latency_tpot_averages():
    tr = reqtrace.RequestTrace("rq-x", 0, 0.0)
    assert tr.tpot_ms is None
    tr.note_latency(ttft_ms=12.5)
    tr.note_latency(tpot_ms=4.0)
    tr.note_latency(tpot_ms=8.0)
    assert tr.ttft_ms == 12.5
    assert tr.tpot_ms == pytest.approx(6.0)


# ---------------------------------------------------------------------------
# TraceBook lifecycle (fake clock, stub burn monitor)
# ---------------------------------------------------------------------------

class _Req:
    """The slice of serve.ServeRequest the book reads."""

    def __init__(self, rid, request_id=None, t=1000.0):
        self.rid = rid
        self.request_id = request_id
        self.submitted_t = t
        self.tokens = [1, 2, 3]


class _Burn:
    def __init__(self):
        self.seen = []

    def observe(self, t, **kw):
        self.seen.append((t, kw))


def test_tracebook_lifecycle_and_burn_feed():
    now = [1000.0]
    burn = _Burn()
    book = reqtrace.TraceBook(clock=lambda: now[0], exemplar_k=2,
                              window_s=30.0, burn=burn)
    req = _Req(0, "rq-aaaa")
    book.start(req, depth=3)
    assert book.live_count == 1 and book.started == 1
    now[0] = 1000.2
    book.stage(0, "admit", queue_age_ms=200.0)
    book.stage(0, "prefill", pfx_hit=0, pfx_tokens=0)
    book.note_latency(0, ttft_ms=200.0)
    book.stage(0, "decode", tokens=1)
    book.note_latency(0, tpot_ms=5.0)
    assert book.seen(0, "admit") and not book.seen(0, "spec")
    # untracked rid: silent no-op, never raises
    book.stage(99, "decode", tokens=1)
    now[0] = 1000.5
    tr = book.finish(req, "done")
    assert tr is not None and tr.status == "done"
    assert tr.stages[-1]["stage"] == "emit"
    assert tr.stages[-1]["tokens"] == 3
    assert book.live_count == 0 and book.finished == 1
    # finish fed the burn monitor the latency outcome
    assert burn.seen == [(1000.5, {"ttft_ms": 200.0, "tpot_ms": 5.0})]
    # double finish: trace already popped, no double count
    assert book.finish(req, "done") is None
    assert book.finished == 1
    # reject feeds the shed stream and mints when the caller had no id
    rid = book.reject(None, "shed", retry_after_s=0.5)
    assert rid.startswith("rq-") and book.rejected == 1
    assert burn.seen[-1] == (1000.5, {"shed": True})
    rid2 = book.reject("rq-keep", "drain")
    assert rid2 == "rq-keep"
    c = book.counters()
    assert c["trace_finished"] == 1.0 and c["trace_rejected"] == 2.0


def test_window_auto_seals_on_expiry():
    now = [0.0]
    book = reqtrace.TraceBook(clock=lambda: now[0], window_s=10.0)
    r0 = _Req(0, "rq-a", t=0.0)
    book.start(r0)
    now[0] = 1.0
    book.finish(r0, "done")
    assert book.windows_sealed == 0          # window still open
    r1 = _Req(1, "rq-b", t=2.0)
    book.start(r1)
    now[0] = 11.0                            # past window_s
    book.finish(r1, "done")
    assert book.windows_sealed == 1
    # flight recorder unconfigured: sealed (counted) but nothing frozen
    assert book.exemplars_frozen == 0 and book.last_pm_ref is None


def test_exemplar_pick_is_ttft_union_tpot_tails():
    book = reqtrace.TraceBook(exemplar_k=1)
    slow_ttft = reqtrace.RequestTrace("rq-t", 0, 0.0)
    slow_ttft.note_latency(ttft_ms=500.0, tpot_ms=1.0)
    slow_tpot = reqtrace.RequestTrace("rq-p", 1, 0.0)
    slow_tpot.note_latency(ttft_ms=1.0, tpot_ms=80.0)
    fast = reqtrace.RequestTrace("rq-f", 2, 0.0)
    fast.note_latency(ttft_ms=2.0, tpot_ms=2.0)
    picked = book._pick_exemplars([fast, slow_ttft, slow_tpot])
    assert {t.request_id for t in picked} == {"rq-t", "rq-p"}


# ---------------------------------------------------------------------------
# end to end over a real engine
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def setup():
    model, cfg = gpt2.make_model(TINY)
    params = model.init_params(jax.random.PRNGKey(0), seq_len=8)
    prompts = [[7, 3, 11, 2, 9], [1, 2, 3, 4, 5, 6, 7, 8, 9, 10, 11],
               [42, 0, 99]]
    return model, params, prompts


def test_engine_trace_parity_and_stage_story(setup):
    """Tracing on vs off: bit-identical tokens (host-side only — no new
    jit programs, no device work), and every finished request tells the
    queue -> admit -> prefill -> decode -> emit story with latency
    attribution filled in."""
    model, params, prompts = setup
    eng_on = GenerationEngine(model, params, max_slots=4, page_size=8,
                              trace=True, trace_window_s=1e9)
    eng_off = GenerationEngine(model, params, max_slots=4, page_size=8,
                               trace=False)
    try:
        got_on = eng_on.generate(prompts, max_new_tokens=8)
        got_off = eng_off.generate(prompts, max_new_tokens=8)
        assert got_on == got_off
        assert eng_off.trace is None
        book = eng_on.trace
        assert book.started == len(prompts) == book.finished
        assert book.live_count == 0
        # finished traces wait in the open reservoir window
        assert len(book._window) == len(prompts)
        for tr in book._window:
            names = [e["stage"] for e in tr.stages]
            assert names[0] == "queue" and names[-1] == "emit"
            assert "admit" in names and "prefill" in names
            assert "decode" in names or "spec" in names
            assert tr.status == "done"
            assert tr.tokens == 8
            assert tr.ttft_ms is not None and tr.ttft_ms >= 0.0
            assert tr.tpot_ms is not None
            assert tr.request_id.startswith("rq-")
        # content-addressable: ids distinct across distinct requests
        assert len({t.request_id for t in book._window}) == len(prompts)
    finally:
        eng_on.close()
        eng_off.close()


def test_seal_window_freezes_exemplars_and_report_replays(setup, tmp_path):
    """The full forensic loop: live engine -> seal_window freezes the
    tail exemplars into the flight recorder -> request_report.py
    rebuilds the waterfall and the Chrome trace (one track per stage)
    from the published bundle alone."""
    model, params, prompts = setup
    transport = InMemoryTransport()
    flight.configure("server", "s0", transport=transport)
    eng = GenerationEngine(model, params, max_slots=4, page_size=8,
                           trace=True, trace_exemplars=8,
                           trace_window_s=1e9)
    try:
        eng.generate(prompts, max_new_tokens=8)
        ref = eng.trace.seal_window()
        assert ref, "seal_window must publish a bundle ref"
        assert eng.trace.last_pm_ref == ref
        assert eng.trace.exemplars_frozen == len(prompts)
        bundle = flight.fetch_bundle(transport, "server", "s0")
    finally:
        eng.close()
        flight.shutdown()
    assert bundle is not None and bundle["bundle_id"] == ref
    kinds = {e["kind"] for e in bundle["events"]}
    assert {"serve.trace.exemplar", "serve.trace.stage"} <= kinds

    exemplars = collect_exemplars([bundle])
    assert len(exemplars) == len(prompts)
    listing = format_listing(exemplars)
    rid, rec = sorted(exemplars.items())[0]
    assert rid in listing
    # the waterfall names every stage of the request's own timeline
    text = format_waterfall(rid, rec)
    for ev in rec["stages"]:
        assert ev["stage"] in text
    assert "ttft_ms" in text and "tokens=8" in text
    # chrome trace: one track (source) per STAGE, entries carry the
    # batched step counts
    entries = trace_entries(rid, rec)
    assert {e["source"] for e in entries} == \
        {e["stage"] for e in rec["stages"]}
    assert all(e["request_id"] == rid for e in entries)
    emit = [e for e in entries if e["source"] == "emit"]
    assert emit and emit[0]["tokens"] == 8


def test_cross_worker_hop_merges_into_one_waterfall(setup):
    """Disaggregated forensics: the prefill worker freezes a
    "prefilled" exemplar (timeline ending in kv_export), the decode
    worker freezes the decode leg (kv_adopt onward) under the SAME
    request id; request_report splices them into one cross-worker
    waterfall — prefill leg first, each row tagged with its leg."""
    from distributedtraining_tpu.engine import kv_transfer as kvt

    model, params, prompts = setup
    tr = InMemoryTransport()
    rids = [f"rq-hop-{i}" for i in range(len(prompts))]

    flight.configure("server", "pre0", transport=tr)
    pe = GenerationEngine(model, params, revision="r1", max_slots=4,
                          page_size=8, phase="prefill",
                          kv_exporter=kvt.KVExporter(tr),
                          trace=True, trace_exemplars=8,
                          trace_window_s=1e9)
    try:
        legs = [pe.submit(p, 8, request_id=rid)
                for p, rid in zip(prompts, rids)]
        while not all(r.done_evt.is_set() for r in legs):
            pe.step()
        assert pe.trace.seal_window()
        pre_bundle = flight.fetch_bundle(tr, "server", "pre0")
    finally:
        pe.close()
        flight.shutdown()

    flight.configure("server", "dec0", transport=tr)
    de = GenerationEngine(model, params, revision="r1", max_slots=4,
                          page_size=8, phase="decode",
                          kv_adopter=kvt.KVAdopter(tr),
                          trace=True, trace_exemplars=8,
                          trace_window_s=1e9)
    try:
        reqs = [de.submit(p, 8, request_id=rid, kv_ref=leg.kv_ref,
                          first_token=leg.first_token)
                for p, rid, leg in zip(prompts, rids, legs)]
        while not all(r.done_evt.is_set() for r in reqs):
            de.step()
        assert de.kv_adopted == len(prompts)
        assert de.trace.seal_window()
        dec_bundle = flight.fetch_bundle(tr, "server", "dec0")
    finally:
        de.close()
        flight.shutdown()

    exemplars = collect_exemplars([pre_bundle, dec_bundle])
    assert set(rids) <= set(exemplars)
    rec = exemplars[rids[0]]
    assert rec["hop"] and rec["summary"]["status"] == "done"
    assert rec["prefill_bundle_id"] == pre_bundle["bundle_id"]
    stage_legs = [(e["stage"], e.get("leg")) for e in rec["stages"]]
    assert ("kv_export", "prefill") in stage_legs
    assert ("kv_adopt", "decode") in stage_legs
    text = format_waterfall(rids[0], rec)
    assert "hop=prefill->decode" in text
    # splice order: every prefill-leg row above every decode-leg row
    assert text.index("kv_export") < text.index("kv_adopt")
    # bundle order must not matter
    flipped = collect_exemplars([dec_bundle, pre_bundle])
    assert flipped[rids[0]]["hop"]
    # the chrome trace keeps the leg tag per entry
    entries = trace_entries(rids[0], rec)
    assert {e.get("leg") for e in entries} == {"prefill", "decode"}


def test_http_frontend_propagates_request_id(setup):
    """The X-DT-Request-Id contract at the serving edge: a caller-sent
    id is honored end to end (body + echo header); an id-less caller
    gets an engine-minted one."""
    model, params, _ = setup
    eng = GenerationEngine(model, params, max_slots=2, page_size=8,
                           trace=True, trace_window_s=1e9)
    loop = ServeLoop(eng, idle_poll_s=0.02).start()
    fe = ServeHTTPFrontend(eng, 0, timeout_s=60.0)
    port = fe.start()
    try:
        prompt = [5, 4, 3, 2, 1]
        body = json.dumps({"tokens": prompt,
                           "max_new_tokens": 4}).encode()
        rid = "rq-cafecafecafecafe"
        req = urllib.request.Request(
            f"http://127.0.0.1:{port}/generate", data=body,
            headers={"Content-Type": "application/json",
                     reqtrace.REQUEST_ID_HEADER: rid})
        with urllib.request.urlopen(req, timeout=30) as resp:
            out = json.loads(resp.read())
            echoed = resp.headers.get(reqtrace.REQUEST_ID_HEADER)
        assert out["request_id"] == rid and echoed == rid
        assert out["tokens"] == reference_generate(model, params, prompt, 4)
        # the trace carries the caller's identity, not a re-mint
        assert any(t.request_id == rid for t in eng.trace._window)
        # no header: engine mints
        req = urllib.request.Request(
            f"http://127.0.0.1:{port}/generate", data=body,
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=30) as resp:
            out2 = json.loads(resp.read())
        assert out2["request_id"].startswith("rq-")
        assert out2["request_id"] != rid
    finally:
        fe.close()
        loop.close()
        eng.close()
