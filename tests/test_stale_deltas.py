"""Stale-delta detection via the base-revision rider.

The reference applies whatever delta is published to whatever base is
current (training_manager.py:417-422 -> averaging_logic.py:422-448): a
delta computed against base N merged into base N+1 re-adds the part of
the N->N+1 update the miner had already incorporated. The rider
(transport.publish_delta_meta) plus receiver policy close that hole;
these tests pin the full loop and the policy knobs."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributedtraining_tpu import delta as delta_lib
from distributedtraining_tpu.engine import (MinerLoop, TrainEngine,
                                            Validator, WeightedAverage)
from distributedtraining_tpu.engine.average import AveragerLoop
from distributedtraining_tpu.engine.scheduler import Clock
from distributedtraining_tpu.models import gpt2
from distributedtraining_tpu.transport import (InMemoryTransport,
                                               LocalFSTransport)
from distributedtraining_tpu.transport.base import parse_delta_meta


class FakeClock(Clock):
    def __init__(self):
        self.t = 0.0

    def now(self):
        return self.t

    def sleep(self, s):
        self.t += s

    def advance(self, s):
        self.t += s


class _Chain:
    my_hotkey = "hotkey_95"

    def sync(self):
        import types
        return types.SimpleNamespace(hotkeys=["m0"])

    def should_set_weights(self):
        return False


def _setup(transport):
    model, cfg = gpt2.make_model("tiny")
    engine = TrainEngine(model, seq_len=16)
    rng = np.random.default_rng(0)

    def batches(n):
        for _ in range(n):
            yield {"input_ids": jnp.asarray(
                rng.integers(0, cfg.vocab_size, (2, 16)), jnp.int32)}

    return model, engine, batches


def test_meta_rider_roundtrip_all_transports(tmp_path):
    for t in (InMemoryTransport(), LocalFSTransport(str(tmp_path))):
        t.publish_delta_meta("m0", {"base_revision": "abc123"})
        assert t.fetch_delta_meta("m0") == {"base_revision": "abc123"}
        assert t.fetch_delta_meta("ghost") is None


def test_parse_delta_meta_defensive():
    assert parse_delta_meta(None) is None
    assert parse_delta_meta(b"not json") is None
    assert parse_delta_meta(b"[1,2]") is None
    assert parse_delta_meta(b"x" * 5000) is None          # size cap
    assert parse_delta_meta(b'{"base_revision": 7}') is None  # wrong type
    long = '{"base_revision": "%s"}' % ("r" * 300)
    assert parse_delta_meta(long.encode()) is None        # oversize value
    assert parse_delta_meta(b'{"base_revision": "ok"}') == {
        "base_revision": "ok"}


def test_miner_publishes_rider_and_averager_skips_stale(tmp_path):
    """Full loop: push (rider) -> merge -> the SAME un-repushed delta is
    refused by the next round; a re-push after the pull is accepted."""
    transport = InMemoryTransport()
    model, engine, batches = _setup(transport)
    clock = FakeClock()
    miner = MinerLoop(engine, transport, "m0", clock=clock,
                      send_interval=1e9, check_update_interval=1e9)
    miner.bootstrap(jax.random.PRNGKey(0))
    # miner genesis base is local-only: publish it so revisions exist
    from distributedtraining_tpu.engine.train import wire_out
    transport.publish_base(wire_out(engine, miner.base_params))
    miner._base_revision = transport.base_revision()
    # train ON the eval batch: random-token corpora carry no learnable
    # signal beyond the marginal distribution, so a few steps on other
    # batches would not reliably improve the eval loss and the publish
    # guard would (correctly!) decline the merge this test needs published
    val = list(batches(1))
    miner.run(iter(val * 4), max_steps=4)
    miner.flush()
    meta = transport.fetch_delta_meta("m0")
    assert meta["base_revision"] == miner._base_revision
    assert meta["delta_id"] == "m0-000001"  # correlation id rides along

    # FIXED val batches (the same ones the miner trained on): the publish
    # guard compares base vs merged on the same batch factory — a
    # fresh-random factory would compare losses on different data and can
    # decline the publish (which then keeps the delta fresh and defeats
    # the staleness scenario)
    avg = AveragerLoop(engine, transport, _Chain(), WeightedAverage(),
                       val_batches=lambda: iter(val), clock=clock)
    avg.bootstrap()
    assert avg.run_round() is True          # fresh: merged + published
    assert avg.report.last_accepted == 1
    assert avg.report.skipped_publishes == 0
    # base moved; the same published delta is now stale
    assert avg.run_round() is False
    assert avg.report.last_rejected == 1
    # miner pulls the new base and re-pushes -> accepted again
    miner._check_pull()
    miner.run(iter(val * 2), max_steps=2)
    miner.flush()
    assert avg.run_round() is True
    assert avg.report.last_accepted == 1

    # policy off: the stale delta is merged again (reference mode); the
    # publish guard may still decline the re-publish, but the round runs
    avg2 = AveragerLoop(engine, transport, _Chain(), WeightedAverage(),
                        val_batches=lambda: iter(val), clock=clock,
                        stale_deltas="accept")
    avg2.bootstrap()
    assert avg2.run_round() is True         # fresh right now
    assert avg2.run_round() is True         # stale but accepted anyway
    assert avg2.report.last_accepted == 1


def test_validator_stale_policy(tmp_path):
    transport = InMemoryTransport()
    model, engine, batches = _setup(transport)
    base = model.init_params(jax.random.PRNGKey(0))
    transport.publish_base(base)
    rev1 = transport.base_revision()
    d = jax.tree_util.tree_map(lambda x: 0.01 * jnp.ones_like(x), base)
    transport.publish_delta("m0", d)
    transport.publish_delta_meta("m0", {"base_revision": rev1})
    # base moves
    moved = delta_lib.apply_delta(base, d)
    transport.publish_base(moved)

    class Chain(_Chain):
        my_hotkey = "hotkey_95"

    v_skip = Validator(engine, transport, Chain(),
                       eval_batches=lambda: batches(1),
                       stale_deltas="skip")
    v_skip.bootstrap()
    s = v_skip.score_miner("m0")
    assert s.score == 0 and s.reason == "stale_base"

    v_accept = Validator(engine, transport, Chain(),
                         eval_batches=lambda: batches(1))
    v_accept.bootstrap()
    s = v_accept.score_miner("m0")
    assert s.reason in ("ok",) or s.score >= 0  # scored, not refused

    # riderless submissions are never stale under either policy
    transport2 = InMemoryTransport()
    transport2.publish_base(base)
    transport2.publish_delta("m0", d)
    v2 = Validator(engine, transport2, Chain(),
                   eval_batches=lambda: batches(1), stale_deltas="skip")
    v2.bootstrap()
    assert v2.score_miner("m0").reason != "stale_base"


def test_stale_flag_parses():
    from distributedtraining_tpu.config import RunConfig
    a = RunConfig.from_args("averager", ["--stale-deltas", "accept"])
    assert a.stale_deltas == "accept"
    v = RunConfig.from_args("validator", ["--stale-deltas", "skip"])
    assert v.stale_deltas == "skip"
    assert RunConfig.from_args("validator", []).stale_deltas is None
