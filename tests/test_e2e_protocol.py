"""The real-protocol round (scripts/e2e_round.py) as a test.

The committed artifact E2E_r03.json is produced by the full GPT-2-124M
run (~10 min CPU); this test exercises the identical harness — real
checkpoint format, --init-from conversion, files: corpus, word
tokenizer, all three CLIs, the three protocol assertions — at a scale CI
can afford. Set DT_RUN_SLOW=1 to run the full 124M spelling here too.

Reference flow being reproduced: /root/reference/neurons/miner.py:54-106.
"""

import os
import sys

import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from scripts.e2e_round import make_hf_checkpoint, run  # noqa: E402


def test_protocol_round_tiny(tmp_path):
    """Checkpoint-boot -> train (loss must drop) -> score (must be > 0)
    -> merge (must publish) on the tiny preset; the run() helper asserts
    all three internally."""
    summary = run(str(tmp_path), steps=12, model="tiny", eval_batches=2)
    assert summary["train_loss_last"] < summary["train_loss_first"]
    assert summary["validator_score_hotkey_0"] > 0
    assert summary["merged_base_published"]


def test_protocol_round_hardened_tiny(tmp_path):
    """The full hardened stack in one round: Ed25519-signed artifacts AND
    int8 compressed wire deltas, through the same three CLIs."""
    summary = run(str(tmp_path), steps=12, model="tiny", eval_batches=2,
                  delta_dtype="int8", signed=True)
    assert summary["validator_score_hotkey_0"] > 0
    assert summary["signed_artifacts"] and summary["delta_dtype"] == "int8"
    # the signed envelope magic really is on the wire artifacts, and the
    # payload really is quantized (an ignored --delta-dtype would publish
    # ~4x these bytes: tiny's f32 delta is ~550 KB)
    from distributedtraining_tpu import signing
    delta_bytes = (tmp_path / "artifacts" / "deltas" /
                   "hotkey_0.msgpack").read_bytes()
    assert signing.is_enveloped(delta_bytes)
    assert summary["delta_artifact_bytes"] < 200_000, \
        summary["delta_artifact_bytes"]


def test_checkpoint_is_idempotent_and_bit_real(tmp_path):
    """The generated checkpoint is a real HF layout (loadable by the
    production converter) and a second call reuses it."""
    from distributedtraining_tpu.models import convert, gpt2

    path = make_hf_checkpoint(str(tmp_path / "ck"), model="tiny")
    mtime = os.path.getmtime(os.path.join(path, "model.safetensors"))
    assert make_hf_checkpoint(str(tmp_path / "ck"), model="tiny") == path
    assert os.path.getmtime(os.path.join(path, "model.safetensors")) == mtime
    params = convert.gpt2_from_hf(path, gpt2.PRESETS["tiny"])
    assert "wte" in params


@pytest.mark.skipif(not os.environ.get("DT_RUN_SLOW"),
                    reason="full 124M protocol round (~10 min CPU); "
                           "set DT_RUN_SLOW=1")
def test_protocol_round_gpt2_124m(tmp_path):
    summary = run(str(tmp_path), steps=30, model="gpt2-124m",
                  eval_batches=2)
    assert summary["train_loss_last"] < summary["train_loss_first"]
