"""examples/local_round.py must keep running (it is the README's library
quickstart and the shortest end-to-end handle on the public API)."""

import os
import subprocess
import sys


def test_local_round_example():
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ, DT_FORCE_PLATFORM="cpu")
    env.pop("XLA_FLAGS", None)
    proc = subprocess.run(
        [sys.executable, os.path.join(repo, "examples", "local_round.py")],
        env=env, capture_output=True, text=True, timeout=400)
    assert proc.returncode == 0, proc.stderr[-2000:]
    out = proc.stdout
    assert "round complete: new base published" in out, out
    assert "validator: base loss" in out and "hotkey_0" in out, out
