"""Serialization: msgpack/safetensors round-trips and hostile-payload rejection."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributedtraining_tpu import serialization as ser


def tree(seed=0):
    k = jax.random.PRNGKey(seed)
    return {"a": {"w": jax.random.normal(k, (3, 5)), "b": jnp.arange(4.0)},
            "c": jnp.ones((2, 2), jnp.bfloat16)}


@pytest.mark.parametrize("fmt", ["msgpack", "safetensors"])
def test_roundtrip(fmt):
    t = tree()
    if fmt == "msgpack":
        data = ser.to_msgpack(t)
        out = ser.from_msgpack(data, t)
    else:
        data = ser.to_safetensors(t)
        out = ser.from_safetensors(data, t)
    for a, b in zip(jax.tree_util.tree_leaves(out), jax.tree_util.tree_leaves(t)):
        assert np.asarray(a).dtype == np.asarray(b).dtype
        np.testing.assert_array_equal(np.asarray(a, np.float32),
                                      np.asarray(b, np.float32))


def test_file_roundtrip(tmp_path):
    t = tree()
    for name in ["x.msgpack", "x.safetensors"]:
        p = str(tmp_path / name)
        ser.save_file(t, p)
        out = ser.load_file(p, t)
        for a, b in zip(jax.tree_util.tree_leaves(out),
                        jax.tree_util.tree_leaves(t)):
            np.testing.assert_array_equal(np.asarray(a, np.float32),
                                          np.asarray(b, np.float32))


def test_streaming_msgpack_byte_identical():
    """to_msgpack_file (the leaf-streaming encoder HFHubTransport uploads
    through — one leaf of host RSS instead of the whole artifact) must
    produce EXACTLY the bytes of to_msgpack: mixed dtypes (bf16 included),
    scalars, nesting, and flax's oversized-leaf chunking."""
    import io

    import flax.serialization as flax_ser

    t = {**tree(), "bf": jnp.ones((3, 5), jnp.bfloat16),
         "s": {"c": np.float32(2.5), "d": np.arange(7)}}
    buf = io.BytesIO()
    n = ser.to_msgpack_file(t, buf)
    dense = ser.to_msgpack(t)
    assert buf.getvalue() == dense and n == len(dense)

    # chunked path: shrink flax's threshold so a 100-element leaf chunks
    old = flax_ser.MAX_CHUNK_SIZE
    flax_ser.MAX_CHUNK_SIZE = 64
    try:
        big = {"w": np.arange(100, dtype=np.float32)}
        buf = io.BytesIO()
        ser.to_msgpack_file(big, buf)
        assert buf.getvalue() == ser.to_msgpack(big)
        out = ser.from_msgpack(buf.getvalue(),
                               {"w": np.zeros(100, np.float32)})
        np.testing.assert_array_equal(out["w"], big["w"])
    finally:
        flax_ser.MAX_CHUNK_SIZE = old


def test_size_cap():
    t = tree()
    data = ser.to_msgpack(t)
    with pytest.raises(ser.PayloadError):
        ser.from_msgpack(data, t, max_bytes=10)


def test_malformed_rejected():
    with pytest.raises(ser.PayloadError):
        ser.from_msgpack(b"\x00garbage\xff\xff", tree())


def test_wrong_structure_rejected():
    t = tree()
    evil = {"totally": jnp.zeros((1,))}
    data = ser.to_msgpack(evil)
    with pytest.raises(ser.PayloadError):
        ser.validated_load(data, t)


def test_same_structure_wrong_leaf_shape_rejected():
    """Right names, wrong-shaped tensor: must not broadcast through delta
    arithmetic (review finding repro)."""
    t = tree()
    evil = jax.tree_util.tree_map(lambda x: x, t)
    evil["a"]["w"] = jnp.zeros((1,), jnp.float32)
    with pytest.raises(ser.PayloadError):
        ser.from_msgpack(ser.to_msgpack(evil), t)
    with pytest.raises(ser.PayloadError):
        ser.from_safetensors(ser.to_safetensors(evil), t)


def test_wrong_shape_rejected():
    t = tree()
    evil = jax.tree_util.tree_map(lambda x: jnp.zeros((7,) + x.shape, x.dtype), t)
    data = ser.to_msgpack(evil)
    with pytest.raises(ser.PayloadError):
        ser.validated_load(data, t)


def test_no_pickle_used():
    """The wire format must never invoke pickle (reference RCE hole,
    hf_manager.py:186-197)."""
    import distributedtraining_tpu.serialization as m
    import inspect
    src = inspect.getsource(m)
    assert "import pickle" not in src and "import torch" not in src


def test_fuzz_mutated_payloads_never_crash():
    """Byte-level fuzz over every untrusted parser: random mutations of
    VALID artifacts (flips, truncations, splices, header surgery) must
    produce PayloadError or a validated tree — never an unhandled
    exception, hang, or silently wrong-shaped result."""
    import numpy as np

    from distributedtraining_tpu import signing

    # Identity needs the optional cryptography dependency; without it the
    # unsigned surfaces still fuzz (strip_envelope is dependency-free)
    try:
        from distributedtraining_tpu.utils.identity import Identity
        ident = Identity.generate()
    except ModuleNotFoundError:
        ident = None

    template = {"w": np.arange(12, dtype=np.float32).reshape(3, 4),
                "b": np.ones((4,), np.float32)}
    seeds = [
        ser.to_msgpack(template),
        ser.to_safetensors(template),
    ]
    if ident is not None:
        seeds.append(signing.wrap(ser.to_msgpack(template), ident,
                                  signing.delta_context("hk")))
    rng = np.random.default_rng(0)
    n_parsed = 0
    for seed_bytes in seeds:
        buf = np.frombuffer(seed_bytes, np.uint8).copy()
        for trial in range(120):
            b = buf.copy()
            op = trial % 4
            if op == 0:      # flip a few random bytes
                idx = rng.integers(0, len(b), 4)
                b[idx] ^= rng.integers(1, 256, 4).astype(np.uint8)
            elif op == 1:    # truncate
                b = b[: rng.integers(0, len(b))]
            elif op == 2:    # splice two regions
                i, j = sorted(rng.integers(0, len(b), 2))
                b = np.concatenate([b[:i], b[j:], b[i:j]])
            else:            # prepend/append garbage
                junk = rng.integers(0, 256, 16).astype(np.uint8)
                b = np.concatenate([junk, b]) if trial % 8 else \
                    np.concatenate([b, junk])
            data = b.tobytes()
            parsers = [
                lambda d: ser.validated_load(d, template),
                lambda d: ser.from_safetensors(d, template),
                signing.strip_envelope,
            ]
            if ident is not None:
                parsers.append(
                    lambda d: signing.unwrap(d, signing.delta_context("hk"),
                                             expected_pub=ident.public_bytes))
            for parse in parsers:
                try:
                    out = parse(data)
                except ser.PayloadError:
                    continue
                n_parsed += 1
                if isinstance(out, dict):  # a survivor must be template-true
                    assert set(out) == set(template)
                    for k in template:
                        assert np.shape(out[k]) == template[k].shape
    # sanity: the harness isn't vacuous — every untouched seed parses on
    # its own surface (so the mutation loop exercised live parsers)
    assert ser.validated_load(seeds[0], template) is not None
    assert ser.from_safetensors(seeds[1], template) is not None
    if ident is not None:
        assert signing.unwrap(seeds[2], signing.delta_context("hk"),
                              expected_pub=ident.public_bytes) is not None


def test_scan_blocks_layout_mismatch_is_diagnosed():
    """A payload in the scan (stacked h/block) layout loaded against an
    unrolled template (or vice versa) must fail with a message naming the
    --scan-blocks flag disagreement — not an anonymous structure error
    (it used to be scored zero with nothing pointing at the mis-set flag)."""
    import numpy as np

    from distributedtraining_tpu import serialization as ser

    unrolled = {"wte": np.zeros((4, 2), np.float32),
                "h_0": {"w": np.ones((2, 2), np.float32)},
                "h_1": {"w": np.ones((2, 2), np.float32)}}
    stacked = {"wte": np.zeros((4, 2), np.float32),
               "h": {"block": {"w": np.ones((2, 2, 2), np.float32)}}}

    with pytest.raises(ser.PayloadError, match="scan-blocks"):
        ser.from_msgpack(ser.to_msgpack(stacked), unrolled)
    with pytest.raises(ser.PayloadError, match="scan-blocks"):
        ser.from_msgpack(ser.to_msgpack(unrolled), stacked)
    # an unrelated structure mismatch stays an anonymous structure error
    with pytest.raises(ser.PayloadError) as ei:
        ser.from_msgpack(ser.to_msgpack({"other": np.zeros(2)}), unrolled)
    assert "scan-blocks" not in str(ei.value)
