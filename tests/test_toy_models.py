"""Toy classification smoke path: the reference's MNIST harness equivalents.

The reference smoke-tests its miner/validator/averager engines on MNIST with
FeedforwardNN/SimpleCNN (training_manager.py:440-803,
validation_logic.py:265-318, new_training_manager.py:173-189). Same coverage
here on the synthetic image task: the toy nets learn, and the full federated
round (miner -> delta -> validator -> averager) runs end-to-end on a
non-LM model, proving the engines are task-agnostic.
"""

import itertools

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributedtraining_tpu.data import image_batches
from distributedtraining_tpu.engine import (
    AveragerLoop, FakeClock, MinerLoop, TrainEngine, Validator,
    WeightedAverage)
from distributedtraining_tpu.models import FeedforwardNet, SimpleCNN, ToyConfig
from distributedtraining_tpu.ops.losses import accuracy, classification_loss
from distributedtraining_tpu.transport import InMemoryTransport


def toy_loss(model, params, batch):
    logits = model.apply({"params": params}, batch["images"])
    return classification_loss(logits, batch["labels"])


def _accuracy(model, params, batches, n=5):
    accs = [float(accuracy(model.apply({"params": params}, b["images"]),
                           b["labels"]))
            for b in itertools.islice(batches, n)]
    return float(np.mean(accs))


CFG = ToyConfig(image_size=14, hidden=32, n_classes=4)


@pytest.mark.parametrize("net_cls", [FeedforwardNet, SimpleCNN])
def test_toy_net_learns(net_cls):
    model = net_cls(CFG)
    engine = TrainEngine(model, loss_fn=toy_loss)
    state = engine.init_state(jax.random.PRNGKey(0))
    batches = image_batches(batch_size=32, n_classes=CFG.n_classes,
                            image_size=CFG.image_size, split="train")
    acc0 = _accuracy(model, state.params,
                     image_batches(batch_size=32, n_classes=CFG.n_classes,
                                   image_size=CFG.image_size, split="val"))
    for batch in itertools.islice(batches, 60):
        state, m = engine.train_step(state, batch)
    acc1 = _accuracy(model, state.params,
                     image_batches(batch_size=32, n_classes=CFG.n_classes,
                                   image_size=CFG.image_size, split="val"))
    assert acc0 < 0.5                      # chance-ish at init
    assert acc1 > 0.9, f"net failed to learn: {acc0:.2f} -> {acc1:.2f}"
    assert np.isfinite(float(m["loss"]))


def test_toy_federated_round():
    """MNIST*Train -> MNISTValidator -> averager parity: a full offline round
    on the classification task."""
    model = FeedforwardNet(CFG)
    engine = TrainEngine(model, loss_fn=toy_loss)
    transport = InMemoryTransport()

    def train_stream():
        return image_batches(batch_size=32, n_classes=CFG.n_classes,
                             image_size=CFG.image_size, split="train")

    def val_batches():
        return itertools.islice(
            image_batches(batch_size=32, n_classes=CFG.n_classes,
                          image_size=CFG.image_size, split="val"), 3)

    # two miners train and push deltas
    for mid in ("m0", "m1"):
        miner = MinerLoop(engine, transport, mid, clock=FakeClock(),
                          send_interval=1e9, check_update_interval=1e9)
        miner.bootstrap(jax.random.PRNGKey(0))  # shared init = shared base
        miner.run(train_stream(), max_steps=40)
        miner.flush()

    # validator scores both deltas positively
    class _OneShotChain:
        my_hotkey = "validator"
        emitted = None

        def sync(self):
            import types
            return types.SimpleNamespace(hotkeys=["m0", "m1"])

        def should_set_weights(self):
            return True

        def set_weights(self, scores):
            self.emitted = scores
            return True

    chain = _OneShotChain()
    validator = Validator(engine, transport, chain, eval_batches=val_batches)
    validator.bootstrap(jax.random.PRNGKey(0))
    scores = validator.validate_and_score()
    assert {s.hotkey for s in scores} == {"m0", "m1"}
    assert all(s.score > 0 for s in scores), scores
    assert chain.emitted is not None

    # averager merges them into a better base
    base_loss = validator.base_loss
    avg = AveragerLoop(engine, transport, chain, WeightedAverage(),
                       val_batches=val_batches, clock=FakeClock())
    avg.bootstrap(jax.random.PRNGKey(0))
    assert avg.run_round()
    assert avg.report.last_accepted == 2
    assert avg.report.last_loss < base_loss
    # the merged base is now published for the next round
    fetched = transport.fetch_base(avg.base_params)
    assert fetched is not None
