"""Async miner publication pipeline (engine/publish.py).

Contracts pinned here, mirroring tests/test_batched_eval.py's pipeline
discipline:

1. PARITY — the async path publishes byte-identical artifacts (and the
   identical rider) to the sequential path, and --push-async off IS the
   sequential path (no worker thread ever starts).
2. SUPERSEDE — a push still pending when the next interval fires is
   replaced, never queued behind; counters record it.
3. FLUSH — flush() drains pending AND in-flight publishes before
   returning (shutdown/e2e semantics unchanged).
4. ISOLATION — publisher-worker exceptions (and retry-exhausted
   publishes) never kill training; failures land in
   MinerReport.pushes_failed.
5. POD RULE — on a cross-process mesh the snapshot + host
   materialization happen on the TRAINING thread; only the upload runs
   on the worker.
"""

import threading
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributedtraining_tpu import delta as delta_lib
from distributedtraining_tpu.engine import (
    FakeClock, MinerLoop, PublishWorker, SupersedeQueue, TrainEngine)
from distributedtraining_tpu.engine.publish import host_materialize
from distributedtraining_tpu.models import gpt2
from distributedtraining_tpu.transport import InMemoryTransport
from distributedtraining_tpu.transport.retry import (RetryPolicy,
                                                     call_with_retry)

SEQ = 32
BATCH = 4


@pytest.fixture(scope="module")
def setup():
    model, cfg = gpt2.make_model("tiny")
    rng = np.random.default_rng(0)
    batch = {"input_ids": np.asarray(
        rng.integers(0, cfg.vocab_size, (BATCH, SEQ)), np.int32)}
    return model, cfg, batch


def _run_miner(model, batch, *, push_async, transport=None, steps=12,
               send_interval=5.0, delta_dtype=None, **kw):
    engine = TrainEngine(model, seq_len=SEQ)
    transport = transport if transport is not None else InMemoryTransport()
    loop = MinerLoop(engine, transport, "m0", clock=FakeClock(),
                     send_interval=send_interval,
                     check_update_interval=1e9, log_every=10**9,
                     push_async=push_async, delta_dtype=delta_dtype, **kw)
    loop.bootstrap(jax.random.PRNGKey(0))

    def batches():
        while True:
            loop.clock.sleep(1.0)
            yield batch

    loop.run(batches(), max_steps=steps)
    loop.flush()
    return transport, loop


# ---------------------------------------------------------------------------
# the queue + worker primitives
# ---------------------------------------------------------------------------

def test_supersede_queue_newest_wins():
    q = SupersedeQueue(depth=1)
    assert q.offer("a") == 0
    assert q.offer("b") == 1     # a superseded before anyone took it
    assert q.offer("c") == 1
    assert q.take() == "c"
    q.task_done()
    with pytest.raises(ValueError):
        SupersedeQueue(depth=0)


def test_supersede_queue_in_flight_never_superseded():
    """An item the consumer already took completes; only PENDING items
    are replaced."""
    q = SupersedeQueue(depth=1)
    q.offer("a")
    assert q.take() == "a"       # in flight now
    assert q.offer("b") == 0     # nothing pending to supersede
    assert q.offer("c") == 1     # b was pending
    q.task_done()
    assert q.take() == "c"
    q.task_done()
    assert q.wait_drained(timeout=1.0)


def test_publish_worker_supersedes_while_blocked():
    """Jobs submitted while the worker is stuck in an upload coalesce to
    the newest; the blocked job still completes."""
    gate = threading.Event()
    started = threading.Event()
    ran = []

    def make(tag, block=False):
        def job():
            ran.append(tag)
            if block:
                started.set()
                gate.wait(5.0)
        return job

    w = PublishWorker(name="t", depth=1)
    w.submit(make("slow", block=True))
    assert started.wait(5.0)
    # worker is in flight on "slow"; these three coalesce to the newest
    w.submit(make("a"))
    w.submit(make("b"))
    w.submit(make("c"))
    gate.set()
    assert w.flush(timeout=5.0)
    assert ran == ["slow", "c"]
    assert w.jobs_superseded == 2
    w.close()


def test_publish_worker_survives_job_exceptions():
    errors = []
    w = PublishWorker(name="t", on_error=errors.append)
    w.submit(lambda: 1 / 0)
    assert w.flush(timeout=5.0)
    w.submit(lambda: None)       # worker still alive and draining
    assert w.flush(timeout=5.0)
    assert w.jobs_failed == 1 and w.jobs_run == 1
    assert isinstance(errors[0], ZeroDivisionError)
    w.close()


def test_publish_worker_thread_is_lazy_and_daemon():
    w = PublishWorker(name="t")
    assert w._thread is None     # sync-only loops never own a thread
    w.submit(lambda: None)
    assert w._thread is not None and w._thread.daemon
    w.close()
    assert w._thread is None


# ---------------------------------------------------------------------------
# retry (transport/retry.py)
# ---------------------------------------------------------------------------

def test_retry_backoff_bounded_and_jittered():
    import random
    policy = RetryPolicy(attempts=5, base_delay=1.0, max_delay=4.0,
                         jitter=0.5)
    rng = random.Random(0)
    for attempt, cap in ((1, 1.0), (2, 2.0), (3, 4.0), (4, 4.0)):
        for _ in range(20):
            d = policy.delay(attempt, rng)
            assert 0.5 * cap <= d <= 1.5 * cap
    with pytest.raises(ValueError):
        RetryPolicy(attempts=0)


def test_call_with_retry_recovers_then_gives_up():
    sleeps = []
    calls = {"n": 0}

    def flaky():
        calls["n"] += 1
        if calls["n"] < 3:
            raise OSError("hub hiccup")
        return "ok"

    assert call_with_retry(flaky, policy=RetryPolicy(attempts=3),
                           sleep=sleeps.append) == "ok"
    assert calls["n"] == 3 and len(sleeps) == 2

    def always():
        raise OSError("down")

    with pytest.raises(OSError):
        call_with_retry(always, policy=RetryPolicy(attempts=2),
                        sleep=sleeps.append)


# ---------------------------------------------------------------------------
# parity: async == sync, byte for byte
# ---------------------------------------------------------------------------

def test_async_artifacts_byte_identical_to_sync(setup):
    model, cfg, batch = setup
    t_sync, l_sync = _run_miner(model, batch, push_async=False)
    t_async, l_async = _run_miner(model, batch, push_async=True)
    assert l_sync.report.pushes == l_async.report.pushes >= 2
    assert t_sync._deltas["m0"] == t_async._deltas["m0"]


def test_async_parity_sparse8_wire(setup):
    """The fused snapshot program (delta + wire layout + sparse8 + finite
    flag in ONE jit) produces the identical artifact either way."""
    model, cfg, batch = setup
    t_sync, _ = _run_miner(model, batch, push_async=False,
                           delta_dtype="sparse8")
    t_async, _ = _run_miner(model, batch, push_async=True,
                            delta_dtype="sparse8")
    assert t_sync._deltas["m0"] == t_async._deltas["m0"]


def test_push_async_off_never_starts_a_worker(setup):
    model, cfg, batch = setup
    _, loop = _run_miner(model, batch, push_async=False)
    assert loop._publisher._worker._thread is None


def test_meta_rider_published_from_worker(setup):
    """With a published base, the async path uploads the base-revision
    rider after the artifact, same as sync."""
    model, cfg, batch = setup
    engine = TrainEngine(model, seq_len=SEQ)
    transport = InMemoryTransport()
    rev = transport.publish_base(engine.init_state(
        jax.random.PRNGKey(1)).params)
    t, loop = _run_miner(model, batch, push_async=True, transport=transport)
    assert loop.report.base_pulls == 0  # bootstrap pulled it, not run()
    meta = t.fetch_delta_meta("m0")
    assert meta["base_revision"] == rev
    # the rider also carries the push's correlation id (utils/obs.py)
    assert meta["delta_id"].startswith("m0-")


# ---------------------------------------------------------------------------
# supersede + flush semantics on the real loop
# ---------------------------------------------------------------------------

class _GatedTransport(InMemoryTransport):
    """publish_delta blocks until released — deterministic in-flight
    control (the _SlowTransport discipline of test_batched_eval)."""

    def __init__(self):
        super().__init__()
        self.gate = threading.Event()
        self.entered = threading.Event()
        self.publishes = 0

    def publish_delta(self, miner_id, delta):
        self.entered.set()
        assert self.gate.wait(10.0), "test forgot to release the gate"
        self.publishes += 1
        return super().publish_delta(miner_id, delta)


def test_pushes_supersede_while_upload_in_flight(setup):
    """Three pushes land while the first is stuck in the transport: the
    middle ones coalesce, the flush() artifact is the NEWEST state."""
    model, cfg, batch = setup
    engine = TrainEngine(model, seq_len=SEQ)
    transport = _GatedTransport()
    loop = MinerLoop(engine, transport, "m0", clock=FakeClock(),
                     send_interval=1.0, check_update_interval=1e9,
                     log_every=10**9, push_async=True)
    loop.bootstrap(jax.random.PRNGKey(0))

    def batches():
        while True:
            loop.clock.sleep(1.0)
            yield batch

    worker = threading.Thread(
        target=lambda: (loop.run(batches(), max_steps=6)), daemon=True)
    worker.start()
    assert transport.entered.wait(30.0)   # first push is in flight
    worker.join(30.0)                     # training finished meanwhile
    assert not worker.is_alive(), "training stalled behind the upload"
    transport.gate.set()
    loop.flush()
    # every push interval fired, but blocked uploads coalesced
    assert loop.report.pushes == transport.publishes
    assert loop.report.pushes + loop.report.pushes_superseded >= 3
    assert loop.report.pushes_superseded >= 1
    # the final artifact equals a fresh snapshot of the final state
    payload, _ = loop._push_snapshot()
    from distributedtraining_tpu import serialization as ser
    assert transport._deltas["m0"] == ser.to_msgpack(
        jax.device_get(payload))


def test_flush_drains_in_flight_publish(setup):
    model, cfg, batch = setup
    engine = TrainEngine(model, seq_len=SEQ)
    transport = _GatedTransport()
    loop = MinerLoop(engine, transport, "m0", clock=FakeClock(),
                     send_interval=1e9, check_update_interval=1e9,
                     log_every=10**9, push_async=True)
    loop.bootstrap(jax.random.PRNGKey(0))
    loop._push_delta()
    assert transport.entered.wait(30.0)
    assert "m0" not in transport._deltas    # still in flight
    done = threading.Event()
    t = threading.Thread(target=lambda: (loop.flush(), done.set()),
                         daemon=True)
    t.start()
    assert not done.wait(0.2), "flush returned with the publish in flight"
    transport.gate.set()
    assert done.wait(30.0)
    assert "m0" in transport._deltas
    assert loop.report.pushes >= 1


def test_worker_publish_failure_counted_not_fatal(setup):
    """A transport that dies (even past its retry budget) costs the report
    a pushes_failed tick; training and later pushes continue."""
    model, cfg, batch = setup

    class Dying(InMemoryTransport):
        def __init__(self):
            super().__init__()
            self.calls = 0

        def publish_delta(self, miner_id, delta):
            self.calls += 1
            if self.calls <= 4:   # eats the first push's whole retry budget
                raise OSError("hub down")
            return super().publish_delta(miner_id, delta)

    transport = Dying()
    t, loop = _run_miner(model, batch, push_async=True, transport=transport,
                         steps=12)
    assert loop.report.pushes_failed >= 1
    assert loop.report.pushes >= 1          # a later push recovered
    assert loop.report.steps == 12          # training never died
    assert "m0" in transport._deltas


def test_nonfinite_delta_screened_off_thread(setup):
    """The fused finite flag still blocks poisoned publishes when fetched
    on the worker."""
    model, cfg, batch = setup
    engine = TrainEngine(model, seq_len=SEQ)
    transport = InMemoryTransport()
    loop = MinerLoop(engine, transport, "m0", clock=FakeClock(),
                     send_interval=1e9, check_update_interval=1e9,
                     log_every=10**9, push_async=True)
    loop.bootstrap(jax.random.PRNGKey(0))
    loop.state = loop.state.replace(params=jax.tree_util.tree_map(
        lambda x: jnp.full_like(x, jnp.nan), loop.state.params))
    loop._push_delta()
    loop.flush()
    assert loop.report.pushes == 0
    assert "m0" not in transport._deltas


# ---------------------------------------------------------------------------
# pod rule: snapshot + materialization on-thread, upload-only background
# ---------------------------------------------------------------------------

def test_pod_mode_materializes_on_training_thread(setup):
    """With _multi() true, the worker must receive an already-HOST tree
    (the allgather is a collective — it may only run at the loop barrier)
    and the transport still sees exactly one publish."""
    model, cfg, batch = setup
    engine = TrainEngine(model, seq_len=SEQ)

    submitted = {}

    class Spy(InMemoryTransport):
        def publish_delta(self, miner_id, delta):
            submitted["thread"] = threading.current_thread().name
            submitted["host"] = all(
                isinstance(l, np.ndarray)
                for l in jax.tree_util.tree_leaves(delta))
            return super().publish_delta(miner_id, delta)

    transport = Spy()
    loop = MinerLoop(engine, transport, "m0", clock=FakeClock(),
                     send_interval=1e9, check_update_interval=1e9,
                     log_every=10**9, push_async=True)
    loop.bootstrap(jax.random.PRNGKey(0))
    loop._multi = lambda: True    # single-process stand-in for a pod mesh
    loop._push_delta()
    loop._publisher.flush()       # drain WITHOUT forcing a second push
    assert loop.report.pushes == 1
    # upload ran on the background worker...
    assert submitted["thread"].startswith("publish-")
    # ...but the tree it saw was materialized host-side on THIS thread
    assert submitted["host"]


def test_host_materialize_is_device_get_on_single_host(setup):
    model, cfg, batch = setup
    tree = {"a": jnp.ones((4, 4)), "b": np.zeros((2,))}
    out = host_materialize(tree)
    assert all(isinstance(l, np.ndarray)
               for l in jax.tree_util.tree_leaves(out))
    np.testing.assert_array_equal(out["a"], np.ones((4, 4)))


# ---------------------------------------------------------------------------
# async checkpoint lane (checkpoint.save_async)
# ---------------------------------------------------------------------------

def test_async_checkpoint_supersede_and_flush(setup, tmp_path):
    from distributedtraining_tpu.checkpoint import CheckpointStore, Snapshot

    model, cfg, batch = setup
    engine = TrainEngine(model, seq_len=SEQ)
    state = engine.init_state(jax.random.PRNGKey(0))
    with CheckpointStore(str(tmp_path)) as store:
        # burst of saves: pending ones supersede, the store ends on the
        # NEWEST revision with a contiguous step sequence
        for i in range(4):
            store.save_async(Snapshot(state=state, base_params=None,
                                      base_revision=f"r{i}",
                                      lifetime_steps=i))
        assert store.flush(timeout=60)
        steps = store.all_steps()
        assert steps == sorted(steps) and len(steps) <= 4
        assert store.read_meta()["base_revision"] == "r3"

    # precondition=False vetoes the write on the worker
    with CheckpointStore(str(tmp_path / "veto")) as store:
        store.save_async(Snapshot(state=state, base_params=None,
                                  base_revision="bad"),
                         precondition=lambda: False)
        assert store.flush(timeout=60)
        assert store.latest_step() is None


def test_miner_async_checkpoint_roundtrip(setup, tmp_path):
    """MinerLoop + push_async + a real store: the background save persists
    a state a fresh loop resumes from."""
    from distributedtraining_tpu.checkpoint import CheckpointStore

    model, cfg, batch = setup
    engine = TrainEngine(model, seq_len=SEQ)
    transport = InMemoryTransport()
    with CheckpointStore(str(tmp_path)) as store:
        loop = MinerLoop(engine, transport, "m0", clock=FakeClock(),
                         send_interval=1e9, check_update_interval=1e9,
                         log_every=10**9, push_async=True,
                         checkpoint_store=store, checkpoint_interval=1e9)
        loop.bootstrap(jax.random.PRNGKey(0))

        def batches():
            while True:
                yield batch

        loop.run(batches(), max_steps=3)
        loop.flush()
        assert store.latest_step() is not None

    with CheckpointStore(str(tmp_path)) as store:
        engine2 = TrainEngine(model, seq_len=SEQ)
        loop2 = MinerLoop(engine2, transport, "m0", clock=FakeClock(),
                          send_interval=1e9, check_update_interval=1e9,
                          log_every=10**9, checkpoint_store=store,
                          checkpoint_interval=1e9)
        loop2.bootstrap(jax.random.PRNGKey(1))
        assert loop2.report.steps == 3      # resumed, not re-initialized
