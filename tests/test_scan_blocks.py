"""scan_blocks: lax.scan'd transformer stack == unrolled stack.

The scan layout exists for XLA compile time (one traced block instead of
n_layer inlined copies — the lever that makes 32-80-layer models compile in
seconds). These tests pin the contract that makes it safe to enable: same
math, invertible layout conversion, and mesh shardings that resolve with the
extra leading "layers" axis.
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributedtraining_tpu.models import gpt2, llama

jtu = jax.tree_util


def _f32(cfg):
    return dataclasses.replace(cfg, dtype="float32")


@pytest.mark.parametrize("family", ["gpt2", "llama"])
def test_scan_logits_match_unrolled(family):
    if family == "gpt2":
        mod, cfg = gpt2, _f32(gpt2.PRESETS["tiny"])
    else:
        mod, cfg = llama, _f32(llama.PRESETS["tiny-llama"])
    m1, _ = mod.make_model(cfg)
    m2, _ = mod.make_model(dataclasses.replace(cfg, scan_blocks=True))
    p1 = m1.init_params(jax.random.PRNGKey(0))
    p2 = mod.stack_blocks(p1, cfg.n_layer)
    ids = jnp.asarray(np.random.default_rng(0).integers(
        0, cfg.vocab_size, (2, 16)), jnp.int32)
    l1 = np.asarray(m1.apply({"params": p1}, ids))
    l2 = np.asarray(m2.apply({"params": p2}, ids))
    # identical math in f32: agreement to float rounding, not model tolerance
    np.testing.assert_allclose(l1, l2, rtol=2e-6, atol=2e-6)


@pytest.mark.parametrize("family", ["gpt2", "llama"])
def test_stack_unstack_roundtrip_and_init_layout(family):
    if family == "gpt2":
        mod, cfg = gpt2, gpt2.PRESETS["tiny"]
    else:
        mod, cfg = llama, llama.PRESETS["tiny-llama"]
    m1, _ = mod.make_model(cfg)
    m2, _ = mod.make_model(dataclasses.replace(cfg, scan_blocks=True))
    p1 = m1.init_params(jax.random.PRNGKey(0))
    stacked = mod.stack_blocks(p1, cfg.n_layer)

    # scan-model init produces exactly the stacked structure/shapes
    p2 = m2.init_params(jax.random.PRNGKey(0))
    assert jtu.tree_structure(p2) == jtu.tree_structure(stacked)
    for a, b in zip(jtu.tree_leaves(p2), jtu.tree_leaves(stacked)):
        assert a.shape == b.shape

    # roundtrip is lossless
    back = mod.unstack_blocks(stacked, cfg.n_layer)
    assert jtu.tree_structure(back) == jtu.tree_structure(p1)
    for a, b in zip(jtu.tree_leaves(p1), jtu.tree_leaves(back)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_scan_train_step_on_mesh(devices):
    """Full sharded train step with the scan layout: the 'layers' logical
    axis must resolve (replicated) alongside the dp/fsdp/tp rules."""
    from distributedtraining_tpu.engine import TrainEngine
    from distributedtraining_tpu.parallel import MeshConfig, make_mesh

    cfg = dataclasses.replace(gpt2.PRESETS["tiny"], scan_blocks=True)
    model, _ = gpt2.make_model(cfg)
    mesh = make_mesh(MeshConfig(dp=2, fsdp=2, tp=2), devices=devices[:8])
    engine = TrainEngine(model, mesh=mesh, seq_len=32)
    state = engine.init_state(jax.random.PRNGKey(0))
    batch = {"input_ids": jnp.asarray(np.random.default_rng(0).integers(
        0, cfg.vocab_size, (4, 32)), jnp.int32)}
    state, m = engine.train_step(state, engine.place_batch(batch))
    assert np.isfinite(float(m["loss"]))
    # per-block leaves carry the leading [n_layer] axis and replicate it
    kern = state.params["h"]["block"]["c_attn"]["kernel"]
    assert kern.shape[0] == cfg.n_layer
    assert kern.sharding.spec[0] is None


def test_scan_with_ring_attention_on_sp_mesh(devices):
    """scan_blocks composes with sequence parallelism: ring attention's
    shard_map runs inside the lax.scan'd block on an sp mesh."""
    from distributedtraining_tpu.engine import TrainEngine
    from distributedtraining_tpu.ops import ring_attention as ring
    from distributedtraining_tpu.parallel import MeshConfig, make_mesh

    mesh = make_mesh(MeshConfig(dp=2, sp=4), devices=devices[:8])
    cfg = dataclasses.replace(gpt2.PRESETS["tiny"], n_positions=32,
                              attention_impl="ring", scan_blocks=True)
    model, _ = gpt2.make_model(cfg)
    try:
        engine = TrainEngine(model, mesh=mesh, seq_len=32)
        state = engine.init_state(jax.random.PRNGKey(0))
        batch = {"input_ids": jnp.asarray(np.random.default_rng(0).integers(
            0, cfg.vocab_size, (4, 32)), jnp.int32)}
        state, m = engine.train_step(state, engine.place_batch(batch))
        assert np.isfinite(float(m["loss"]))
    finally:
        ring.set_ring_mesh(None)


def test_lora_adapts_scan_layout():
    """LoRA on a scan-layout base: 3-D [L, in, out] kernels get per-layer
    factors and the effective params equal the unrolled equivalent."""
    from distributedtraining_tpu.models import lora

    cfg = gpt2.PRESETS["tiny"]
    m1, _ = gpt2.make_model(cfg)
    base = m1.init_params(jax.random.PRNGKey(0))
    stacked_base = gpt2.stack_blocks(base, cfg.n_layer)
    lcfg = lora.LoRAConfig(rank=2)

    ad = lora.init_lora(jax.random.PRNGKey(1), stacked_base, lcfg)
    pairs = lora.adapted_pairs(ad)
    assert pairs, "no kernels adapted under scan layout"
    assert all(p.a.ndim == 3 and p.a.shape[0] == cfg.n_layer for p in pairs)

    # randomize b so the delta is nonzero, then compare against doing the
    # same math layer-by-layer on the unrolled tree
    ad = jtu.tree_map(lambda x: x + 0.1, ad)
    eff_scan = lora.apply_lora(stacked_base, ad, lcfg)
    delta_scan = lora.lora_to_full_delta(stacked_base, ad, lcfg)
    eff_unrolled = gpt2.unstack_blocks(eff_scan, cfg.n_layer)
    for i in range(cfg.n_layer):
        got = np.asarray(eff_unrolled[f"h_{i}"]["c_attn"]["kernel"])
        a = np.asarray(ad["h"]["block"]["c_attn"]["kernel"].a[i])
        b = np.asarray(ad["h"]["block"]["c_attn"]["kernel"].b[i])
        want = np.asarray(base[f"h_{i}"]["c_attn"]["kernel"]) + \
            (a @ b) * lcfg.scaling
        np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)
    d = np.asarray(delta_scan["h"]["block"]["c_attn"]["kernel"])
    assert d.shape[0] == cfg.n_layer and np.abs(d).max() > 0


def test_convert_load_params_stacks_for_scan(tmp_path):
    """--init-from + scan_blocks: HF import lands in the scan layout."""
    from distributedtraining_tpu.models import convert

    cfg = gpt2.PRESETS["tiny"]
    m1, _ = gpt2.make_model(cfg)
    p1 = m1.init_params(jax.random.PRNGKey(0))
    flat = convert.gpt2_to_hf(p1, cfg)
    path = tmp_path / "model.safetensors"
    import safetensors.numpy as st
    st.save_file({k: np.asarray(v) for k, v in flat.items()}, str(path))

    scan_cfg = dataclasses.replace(cfg, scan_blocks=True)
    loaded = convert.load_params(str(path), scan_cfg)
    m2, _ = gpt2.make_model(scan_cfg)
    expect = gpt2.stack_blocks(p1, cfg.n_layer)
    assert jtu.tree_structure(jtu.tree_map(np.asarray, loaded)) == \
        jtu.tree_structure(jtu.tree_map(np.asarray, expect))
    for a, b in zip(jtu.tree_leaves(loaded), jtu.tree_leaves(expect)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-6, atol=1e-6)


def test_mixed_fleet_wire_layout(tmp_path):
    """--scan-blocks is a per-role choice: artifacts always travel in the
    unrolled wire layout (engine/train.py wire_out/wire_in), so a scan
    miner's delta scores on an unrolled validator, an unrolled miner's
    delta merges on a scan averager, and both miners pull the merged base
    back into their own layouts."""
    from distributedtraining_tpu.chain import LocalChain
    from distributedtraining_tpu.data import (ByteTokenizer, batch_iterator,
                                              text_corpus)
    from distributedtraining_tpu.engine import (AveragerLoop, MinerLoop,
                                                TrainEngine, Validator,
                                                WeightedAverage)
    from distributedtraining_tpu.transport import InMemoryTransport

    cfg = _f32(gpt2.PRESETS["tiny"])
    m_unroll, _ = gpt2.make_model(cfg)
    m_scan, _ = gpt2.make_model(dataclasses.replace(cfg, scan_blocks=True))
    e_unroll = TrainEngine(m_unroll, seq_len=32)
    e_scan = TrainEngine(m_scan, seq_len=32)

    docs = text_corpus(split="train", n_docs=32, source="synthetic")

    def batches(n=6):
        it = batch_iterator(docs, ByteTokenizer(), batch_size=4, seq_len=32,
                            repeat=True, max_vocab=cfg.vocab_size)
        return [next(it) for _ in range(n)]

    transport = InMemoryTransport()
    # genesis base published by an UNROLLED averager
    base = m_unroll.init_params(jax.random.PRNGKey(0))
    transport.publish_base(base)

    # scan miner trains from the unrolled wire base and publishes a delta
    scan_miner = MinerLoop(e_scan, transport, "hotkey_0",
                           send_interval=1e9, check_update_interval=1e9)
    scan_miner.bootstrap()
    scan_miner.run(iter(batches(10)), max_steps=10)
    scan_miner.flush()
    # unrolled miner publishes too
    u_miner = MinerLoop(e_unroll, transport, "hotkey_1",
                        send_interval=1e9, check_update_interval=1e9)
    u_miner.bootstrap()
    u_miner.run(iter(batches(10)), max_steps=10)
    u_miner.flush()

    # the wire really is unrolled: raw fetch against an unrolled template
    from distributedtraining_tpu import delta as delta_lib
    host = jtu.tree_map(lambda x: np.zeros(x.shape, x.dtype),
                        jax.eval_shape(lambda: base))
    wire_delta = transport.fetch_delta("hotkey_0", host)
    assert wire_delta is not None and "h_0" in wire_delta

    # UNROLLED validator scores BOTH deltas above zero
    chain = LocalChain(str(tmp_path), my_hotkey="hotkey_95", epoch_length=0)
    v = Validator(e_unroll, transport, chain,
                  eval_batches=lambda: iter(batches(2)))
    v.bootstrap()
    scores = {s.hotkey: s.score for s in v.validate_and_score()}
    assert scores.get("hotkey_0", 0) > 0, scores
    assert scores.get("hotkey_1", 0) > 0, scores

    # SCAN averager merges both and publishes; scan miner pulls it back
    avg = AveragerLoop(e_scan, transport, chain, WeightedAverage(),
                       val_batches=lambda: iter(batches(2)))
    avg.bootstrap()
    assert avg.run_round()
    assert avg.report.last_accepted == 2
    scan_miner._check_pull()
    assert scan_miner._base_revision == transport.base_revision()
    # and an unrolled miner can too
    u_miner._check_pull()
    assert u_miner._base_revision == transport.base_revision()


def test_mixed_fleet_lora_wire_layout(tmp_path):
    """Adapter artifacts normalize at the wire too: a scan_blocks LoRA
    miner's stacked [L, in, r] factors unstack to the universal per-block
    wire form, score on an UNROLLED validator, and merge on an unrolled
    averager."""
    from distributedtraining_tpu.chain import LocalChain
    from distributedtraining_tpu.data import (ByteTokenizer, batch_iterator,
                                              text_corpus)
    from distributedtraining_tpu.engine import (AveragerLoop, LoRAEngine,
                                                LoRAMinerLoop, TrainEngine,
                                                Validator, WeightedAverage)
    from distributedtraining_tpu.models.lora import LoRAConfig
    from distributedtraining_tpu.transport import InMemoryTransport

    cfg = _f32(gpt2.PRESETS["tiny"])
    m_unroll, _ = gpt2.make_model(cfg)
    m_scan, _ = gpt2.make_model(dataclasses.replace(cfg, scan_blocks=True))
    lcfg = LoRAConfig(rank=2)

    docs = text_corpus(split="train", n_docs=32, source="synthetic")

    def batches(n=6):
        it = batch_iterator(docs, ByteTokenizer(), batch_size=4, seq_len=32,
                            repeat=True, max_vocab=cfg.vocab_size)
        return [next(it) for _ in range(n)]

    transport = InMemoryTransport()
    transport.publish_base(m_unroll.init_params(jax.random.PRNGKey(0)))

    scan_lora = LoRAMinerLoop(LoRAEngine(m_scan, lcfg, seq_len=32),
                              transport, "hotkey_0",
                              send_interval=1e9, check_update_interval=1e9)
    scan_lora.bootstrap()
    scan_lora.run(iter(batches(12)), max_steps=12)
    scan_lora.flush()

    # the wire adapters are per-block (h_0...), not stacked
    from distributedtraining_tpu.engine.lora_train import adapter_template
    host_base = jtu.tree_map(
        lambda x: np.zeros(x.shape, x.dtype),
        jax.eval_shape(lambda: m_unroll.init_params(jax.random.PRNGKey(0))))
    wire = transport.fetch_delta(
        "hotkey_0", adapter_template(host_base, lcfg))
    assert wire is not None and "h_0" in wire

    e_unroll = TrainEngine(m_unroll, seq_len=32)
    chain = LocalChain(str(tmp_path), my_hotkey="hotkey_95", epoch_length=0)
    v = Validator(e_unroll, transport, chain,
                  eval_batches=lambda: iter(batches(2)), lora_cfg=lcfg)
    v.bootstrap()
    scores = {s.hotkey: s.score for s in v.validate_and_score()}
    assert scores.get("hotkey_0", 0) > 0, scores

    avg = AveragerLoop(e_unroll, transport, chain, WeightedAverage(),
                       val_batches=lambda: iter(batches(2)), lora_cfg=lcfg)
    avg.bootstrap()
    assert avg.run_round()
    assert avg.report.last_accepted == 1


def test_scan_consumer_accepts_unrolled_lora(tmp_path):
    """The reverse direction: a --scan-blocks validator/averager builds
    its adapter template in the WIRE layout, so an UNROLLED LoRA miner's
    adapters validate, score, and merge (reverting the wire-layout
    templates in validate.py/average.py breaks exactly this)."""
    from distributedtraining_tpu.chain import LocalChain
    from distributedtraining_tpu.data import (ByteTokenizer, batch_iterator,
                                              text_corpus)
    from distributedtraining_tpu.engine import (AveragerLoop, LoRAEngine,
                                                LoRAMinerLoop, TrainEngine,
                                                Validator, WeightedAverage)
    from distributedtraining_tpu.models.lora import LoRAConfig
    from distributedtraining_tpu.transport import InMemoryTransport

    cfg = _f32(gpt2.PRESETS["tiny"])
    m_unroll, _ = gpt2.make_model(cfg)
    m_scan, _ = gpt2.make_model(dataclasses.replace(cfg, scan_blocks=True))
    lcfg = LoRAConfig(rank=2)
    docs = text_corpus(split="train", n_docs=32, source="synthetic")

    def batches(n=6):
        it = batch_iterator(docs, ByteTokenizer(), batch_size=4, seq_len=32,
                            repeat=True, max_vocab=cfg.vocab_size)
        return [next(it) for _ in range(n)]

    transport = InMemoryTransport()
    transport.publish_base(m_unroll.init_params(jax.random.PRNGKey(0)))

    u_lora = LoRAMinerLoop(LoRAEngine(m_unroll, lcfg, seq_len=32),
                           transport, "hotkey_0",
                           send_interval=1e9, check_update_interval=1e9)
    u_lora.bootstrap()
    u_lora.run(iter(batches(12)), max_steps=12)
    u_lora.flush()

    e_scan = TrainEngine(m_scan, seq_len=32)
    chain = LocalChain(str(tmp_path), my_hotkey="hotkey_95", epoch_length=0)
    v = Validator(e_scan, transport, chain,
                  eval_batches=lambda: iter(batches(2)), lora_cfg=lcfg)
    v.bootstrap()
    scores = {s.hotkey: s.score for s in v.validate_and_score()}
    assert scores.get("hotkey_0", 0) > 0, scores

    avg = AveragerLoop(e_scan, transport, chain, WeightedAverage(),
                       val_batches=lambda: iter(batches(2)), lora_cfg=lcfg)
    avg.bootstrap()
    assert avg.run_round()
    assert avg.report.last_accepted == 1


@pytest.mark.parametrize("family", ["gpt2", "llama"])
def test_stack_blocks_preserves_host_numpy(family):
    """Wire -> scan conversion of a HOST tree must stay host-side.

    Averagers gather up to ~100 full-param deltas before merging them
    chunk-at-a-time (delta.chunked_weighted_merge bounds device memory at
    O(chunk x params)); a jnp.stack at the wire boundary would commit every
    delta to device HBM at ingest and defeat that bound (round-3 advisor,
    medium)."""
    if family == "gpt2":
        mod, cfg = gpt2, gpt2.PRESETS["tiny"]
    else:
        mod, cfg = llama, llama.PRESETS["tiny-llama"]
    m1, _ = mod.make_model(cfg)
    p1 = m1.init_params(jax.random.PRNGKey(0))
    host = jtu.tree_map(lambda x: np.asarray(x), p1)
    stacked = mod.stack_blocks(host, cfg.n_layer)
    assert all(isinstance(l, np.ndarray) for l in jtu.tree_leaves(stacked))
    # and device trees still produce device stacks (the training path)
    dev_stacked = mod.stack_blocks(p1, cfg.n_layer)
    assert all(isinstance(l, jax.Array) for l in jtu.tree_leaves(dev_stacked))
    # roundtrip of the host tree is lossless and host-side (index views)
    back = mod.unstack_blocks(stacked, cfg.n_layer)
    for a, b in zip(jtu.tree_leaves(host), jtu.tree_leaves(back)):
        assert isinstance(b, np.ndarray)
        np.testing.assert_array_equal(a, b)
