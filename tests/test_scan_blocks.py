"""scan_blocks: lax.scan'd transformer stack == unrolled stack.

The scan layout exists for XLA compile time (one traced block instead of
n_layer inlined copies — the lever that makes 32-80-layer models compile in
seconds). These tests pin the contract that makes it safe to enable: same
math, invertible layout conversion, and mesh shardings that resolve with the
extra leading "layers" axis.
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributedtraining_tpu.models import gpt2, llama

jtu = jax.tree_util


def _f32(cfg):
    return dataclasses.replace(cfg, dtype="float32")


@pytest.mark.parametrize("family", ["gpt2", "llama"])
def test_scan_logits_match_unrolled(family):
    if family == "gpt2":
        mod, cfg = gpt2, _f32(gpt2.PRESETS["tiny"])
    else:
        mod, cfg = llama, _f32(llama.PRESETS["tiny-llama"])
    m1, _ = mod.make_model(cfg)
    m2, _ = mod.make_model(dataclasses.replace(cfg, scan_blocks=True))
    p1 = m1.init_params(jax.random.PRNGKey(0))
    p2 = mod.stack_blocks(p1, cfg.n_layer)
    ids = jnp.asarray(np.random.default_rng(0).integers(
        0, cfg.vocab_size, (2, 16)), jnp.int32)
    l1 = np.asarray(m1.apply({"params": p1}, ids))
    l2 = np.asarray(m2.apply({"params": p2}, ids))
    # identical math in f32: agreement to float rounding, not model tolerance
    np.testing.assert_allclose(l1, l2, rtol=2e-6, atol=2e-6)


@pytest.mark.parametrize("family", ["gpt2", "llama"])
def test_stack_unstack_roundtrip_and_init_layout(family):
    if family == "gpt2":
        mod, cfg = gpt2, gpt2.PRESETS["tiny"]
    else:
        mod, cfg = llama, llama.PRESETS["tiny-llama"]
    m1, _ = mod.make_model(cfg)
    m2, _ = mod.make_model(dataclasses.replace(cfg, scan_blocks=True))
    p1 = m1.init_params(jax.random.PRNGKey(0))
    stacked = mod.stack_blocks(p1, cfg.n_layer)

    # scan-model init produces exactly the stacked structure/shapes
    p2 = m2.init_params(jax.random.PRNGKey(0))
    assert jtu.tree_structure(p2) == jtu.tree_structure(stacked)
    for a, b in zip(jtu.tree_leaves(p2), jtu.tree_leaves(stacked)):
        assert a.shape == b.shape

    # roundtrip is lossless
    back = mod.unstack_blocks(stacked, cfg.n_layer)
    assert jtu.tree_structure(back) == jtu.tree_structure(p1)
    for a, b in zip(jtu.tree_leaves(p1), jtu.tree_leaves(back)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_scan_train_step_on_mesh(devices):
    """Full sharded train step with the scan layout: the 'layers' logical
    axis must resolve (replicated) alongside the dp/fsdp/tp rules."""
    from distributedtraining_tpu.engine import TrainEngine
    from distributedtraining_tpu.parallel import MeshConfig, make_mesh

    cfg = dataclasses.replace(gpt2.PRESETS["tiny"], scan_blocks=True)
    model, _ = gpt2.make_model(cfg)
    mesh = make_mesh(MeshConfig(dp=2, fsdp=2, tp=2), devices=devices[:8])
    engine = TrainEngine(model, mesh=mesh, seq_len=32)
    state = engine.init_state(jax.random.PRNGKey(0))
    batch = {"input_ids": jnp.asarray(np.random.default_rng(0).integers(
        0, cfg.vocab_size, (4, 32)), jnp.int32)}
    state, m = engine.train_step(state, engine.place_batch(batch))
    assert np.isfinite(float(m["loss"]))
    # per-block leaves carry the leading [n_layer] axis and replicate it
    kern = state.params["h"]["block"]["c_attn"]["kernel"]
    assert kern.shape[0] == cfg.n_layer
    assert kern.sharding.spec[0] is None


def test_scan_with_ring_attention_on_sp_mesh(devices):
    """scan_blocks composes with sequence parallelism: ring attention's
    shard_map runs inside the lax.scan'd block on an sp mesh."""
    from distributedtraining_tpu.engine import TrainEngine
    from distributedtraining_tpu.ops import ring_attention as ring
    from distributedtraining_tpu.parallel import MeshConfig, make_mesh

    mesh = make_mesh(MeshConfig(dp=2, sp=4), devices=devices[:8])
    cfg = dataclasses.replace(gpt2.PRESETS["tiny"], n_positions=32,
                              attention_impl="ring", scan_blocks=True)
    model, _ = gpt2.make_model(cfg)
    try:
        engine = TrainEngine(model, mesh=mesh, seq_len=32)
        state = engine.init_state(jax.random.PRNGKey(0))
        batch = {"input_ids": jnp.asarray(np.random.default_rng(0).integers(
            0, cfg.vocab_size, (4, 32)), jnp.int32)}
        state, m = engine.train_step(state, engine.place_batch(batch))
        assert np.isfinite(float(m["loss"]))
    finally:
        ring.set_ring_mesh(None)


def test_lora_adapts_scan_layout():
    """LoRA on a scan-layout base: 3-D [L, in, out] kernels get per-layer
    factors and the effective params equal the unrolled equivalent."""
    from distributedtraining_tpu.models import lora

    cfg = gpt2.PRESETS["tiny"]
    m1, _ = gpt2.make_model(cfg)
    base = m1.init_params(jax.random.PRNGKey(0))
    stacked_base = gpt2.stack_blocks(base, cfg.n_layer)
    lcfg = lora.LoRAConfig(rank=2)

    ad = lora.init_lora(jax.random.PRNGKey(1), stacked_base, lcfg)
    pairs = lora.adapted_pairs(ad)
    assert pairs, "no kernels adapted under scan layout"
    assert all(p.a.ndim == 3 and p.a.shape[0] == cfg.n_layer for p in pairs)

    # randomize b so the delta is nonzero, then compare against doing the
    # same math layer-by-layer on the unrolled tree
    ad = jtu.tree_map(lambda x: x + 0.1, ad)
    eff_scan = lora.apply_lora(stacked_base, ad, lcfg)
    delta_scan = lora.lora_to_full_delta(stacked_base, ad, lcfg)
    eff_unrolled = gpt2.unstack_blocks(eff_scan, cfg.n_layer)
    for i in range(cfg.n_layer):
        got = np.asarray(eff_unrolled[f"h_{i}"]["c_attn"]["kernel"])
        a = np.asarray(ad["h"]["block"]["c_attn"]["kernel"].a[i])
        b = np.asarray(ad["h"]["block"]["c_attn"]["kernel"].b[i])
        want = np.asarray(base[f"h_{i}"]["c_attn"]["kernel"]) + \
            (a @ b) * lcfg.scaling
        np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)
    d = np.asarray(delta_scan["h"]["block"]["c_attn"]["kernel"])
    assert d.shape[0] == cfg.n_layer and np.abs(d).max() > 0


def test_convert_load_params_stacks_for_scan(tmp_path):
    """--init-from + scan_blocks: HF import lands in the scan layout."""
    from distributedtraining_tpu.models import convert

    cfg = gpt2.PRESETS["tiny"]
    m1, _ = gpt2.make_model(cfg)
    p1 = m1.init_params(jax.random.PRNGKey(0))
    flat = convert.gpt2_to_hf(p1, cfg)
    path = tmp_path / "model.safetensors"
    import safetensors.numpy as st
    st.save_file({k: np.asarray(v) for k, v in flat.items()}, str(path))

    scan_cfg = dataclasses.replace(cfg, scan_blocks=True)
    loaded = convert.load_params(str(path), scan_cfg)
    m2, _ = gpt2.make_model(scan_cfg)
    expect = gpt2.stack_blocks(p1, cfg.n_layer)
    assert jtu.tree_structure(jtu.tree_map(np.asarray, loaded)) == \
        jtu.tree_structure(jtu.tree_map(np.asarray, expect))
    for a, b in zip(jtu.tree_leaves(loaded), jtu.tree_leaves(expect)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-6, atol=1e-6)
