"""Ring attention vs dense oracle on the 8-device mesh; sp training E2E."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributedtraining_tpu.ops.attention import (
    dot_product_attention, make_causal_mask)
from distributedtraining_tpu.ops import ring_attention as ring
from distributedtraining_tpu.parallel import MeshConfig, make_mesh


@pytest.fixture(autouse=True)
def clean_ring_mesh():
    yield
    ring.set_ring_mesh(None)


def dense_oracle(q, k, v):
    mask = make_causal_mask(q.shape[1])[None, None, :, :]
    return dot_product_attention(q, k, v, mask)


@pytest.mark.parametrize("sp", [2, 4, 8])
def test_ring_matches_dense(sp, devices):
    mesh = make_mesh(MeshConfig(sp=sp))
    k0 = jax.random.PRNGKey(0)
    B, T, H, D = 2, 64, 4, 16
    q, k, v = (jax.random.normal(kk, (B, T, H, D))
               for kk in jax.random.split(k0, 3))
    out = ring.ring_attention(q, k, v, mesh=mesh)
    expect = dense_oracle(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(expect),
                               rtol=2e-4, atol=2e-5)


def test_ring_under_jit_with_sharded_inputs(devices):
    """The production shape: inputs sharded over sp, ring inside jit."""
    from jax.sharding import NamedSharding, PartitionSpec as P
    mesh = make_mesh(MeshConfig(sp=8))
    B, T, H, D = 2, 128, 4, 16
    k0 = jax.random.PRNGKey(1)
    q, k, v = (jax.random.normal(kk, (B, T, H, D))
               for kk in jax.random.split(k0, 3))
    sh = NamedSharding(mesh, P(None, "sp", None, None))
    qs, ks, vs = (jax.device_put(x, sh) for x in (q, k, v))
    out = jax.jit(lambda a, b, c: ring.ring_attention(a, b, c, mesh=mesh))(
        qs, ks, vs)
    np.testing.assert_allclose(np.asarray(out), np.asarray(dense_oracle(q, k, v)),
                               rtol=2e-4, atol=2e-5)


def test_ring_seq_not_divisible_raises(devices):
    mesh = make_mesh(MeshConfig(sp=8))
    q = jnp.zeros((1, 12, 2, 8))
    with pytest.raises(ValueError):
        ring.ring_attention(q, q, q, mesh=mesh)


def test_ring_fallback_without_mesh():
    q = jax.random.normal(jax.random.PRNGKey(0), (1, 16, 2, 8))
    out = ring.ring_attention(q, q, q)  # no mesh installed -> dense
    np.testing.assert_allclose(np.asarray(out),
                               np.asarray(dense_oracle(q, q, q)), rtol=1e-5)


def test_sequence_parallel_training_matches_single_device(devices):
    """Full train step with attention_impl='ring' on an sp=4 mesh must match
    the dense single-device step."""
    from distributedtraining_tpu.engine import TrainEngine
    from distributedtraining_tpu.models import gpt2
    from distributedtraining_tpu.data import ByteTokenizer, batch_iterator, text_corpus

    SEQ = 64
    cfg_ring = gpt2.GPT2Config(vocab_size=512, n_positions=128, n_embd=64,
                               n_layer=2, n_head=4, attention_impl="ring")
    cfg_dense = gpt2.GPT2Config(vocab_size=512, n_positions=128, n_embd=64,
                                n_layer=2, n_head=4)
    docs = text_corpus(split="train", n_docs=32, source="synthetic")
    # ring path has no segment support: use plain (unpacked) token rows
    rng = np.random.default_rng(0)
    bs = [{"input_ids": rng.integers(1, 256, (4, SEQ)).astype(np.int32)}
          for _ in range(4)]

    ref = TrainEngine(gpt2.GPT2(cfg_dense), seq_len=SEQ)
    ref_state = ref.init_state(jax.random.PRNGKey(0))
    ref_losses = []
    for b in bs:
        ref_state, m = ref.train_step(ref_state, b)
        ref_losses.append(float(m["loss"]))

    mesh = make_mesh(MeshConfig(dp=2, sp=4))
    eng = TrainEngine(gpt2.GPT2(cfg_ring), mesh=mesh, seq_len=SEQ)
    state = eng.init_state(jax.random.PRNGKey(0))
    losses = []
    for b in bs:
        state, m = eng.train_step(state, eng.place_batch(b))
        losses.append(float(m["loss"]))

    np.testing.assert_allclose(losses, ref_losses, rtol=2e-3)
