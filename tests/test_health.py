"""Fleet health plane (engine/health.py + utils/obs_http.py +
scripts/fleet_report.py).

Covers: heartbeat schema round-trip through a real transport and the
defensive parse of hostile riders, producer-side field-name linting,
Vitals rate/EMA derivation, the HeartbeatPublisher's background timer +
clean shutdown, SLO rule evaluation (all four kinds, one-shot firing,
AnomalyMonitor arming), the contribution ledger against real StagedDelta
outcomes, JSONLSink rotation + transparent segment reads, compile-time
accounting, and the full localfs fleet round: three miners heartbeat and
push, the validator scores and the averager merges with FleetMonitors
attached, one miner is "killed" mid-run and the stale-miner SLO fires,
fleet_report joins the JSONL streams into a ledger that matches the
averager's merge decisions exactly, and the Prometheus exporter serves
both registry and ledger metrics.
"""

import json
import math
import os
import re
import sys
import threading
import time
import urllib.request

import jax
import numpy as np
import pytest

from distributedtraining_tpu.engine import TrainEngine
from distributedtraining_tpu.engine.average import (AveragerLoop,
                                                    WeightedAverage)
from distributedtraining_tpu.engine.health import (BURN_WINDOWS,
                                                   BurnRateMonitor,
                                                   BurnRule, FleetMonitor,
                                                   HeartbeatPublisher,
                                                   NodeHealth, SLORule,
                                                   Vitals, attach_burn,
                                                   build_heartbeat,
                                                   default_burn_rules,
                                                   default_slo_rules,
                                                   live_burn_monitor,
                                                   parse_heartbeat,
                                                   report_vitals)
from distributedtraining_tpu.engine.ingest import StagedDelta
from distributedtraining_tpu.engine.scheduler import FakeClock
from distributedtraining_tpu.engine.train import MinerLoop
from distributedtraining_tpu.engine.validate import Validator
from distributedtraining_tpu.chain.local import LocalChain
from distributedtraining_tpu.models import gpt2
from distributedtraining_tpu.transport import (InMemoryTransport,
                                               LocalFSTransport)
from distributedtraining_tpu.transport.base import heartbeat_id
from distributedtraining_tpu.utils import obs
from distributedtraining_tpu.utils.metrics import (InMemorySink, JSONLSink,
                                                   jsonl_segments)
from distributedtraining_tpu.utils.obs import AnomalyMonitor
from distributedtraining_tpu.utils.obs_http import (ObsHTTPExporter,
                                                    live_exporters, render)

sys.path.insert(0, os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "scripts"))
import fleet_report  # noqa: E402
import obs_report  # noqa: E402


@pytest.fixture(autouse=True)
def _clean_obs():
    obs.reset()
    yield
    obs.reset()


# ---------------------------------------------------------------------------
# Heartbeat schema
# ---------------------------------------------------------------------------

def test_heartbeat_roundtrip_via_transport():
    t = InMemoryTransport()
    hb = build_heartbeat("miner", "hk0", 7, now=123.5, steps=42,
                         step_rate=1.5, loss_ema=2.25, pushes=3,
                         pushes_failed=1, base_revision="abc123",
                         registry_digest="deadbeef0123")
    t.publish_delta_meta(heartbeat_id("miner", "hk0"), hb)
    got = parse_heartbeat(t.fetch_delta_meta(heartbeat_id("miner", "hk0")))
    assert got is not None
    assert got["role"] == "miner" and got["hotkey"] == "hk0"
    assert got["seq"] == 7 and got["t"] == 123.5
    assert got["steps"] == 42.0 and got["loss_ema"] == 2.25
    assert got["base_revision"] == "abc123"
    # a heartbeat id never collides with a real hotkey's artifacts
    assert t.fetch_delta_meta("hk0") is None


def test_parse_heartbeat_rejects_junk():
    assert parse_heartbeat(None) is None
    assert parse_heartbeat([1, 2]) is None
    assert parse_heartbeat({"base_revision": "x"}) is None   # delta rider
    assert parse_heartbeat({"hb": 0, "role": "m", "hotkey": "h",
                            "seq": 1}) is None               # bad version
    assert parse_heartbeat({"hb": 1, "role": "m", "hotkey": "h"}) is None
    assert parse_heartbeat({"hb": 1, "role": 9, "hotkey": "h",
                            "seq": 1}) is None               # role not str
    # non-conforming fields are DROPPED, not fatal: bad names, oversized
    # strings, wrong-kind values
    got = parse_heartbeat({"hb": 1, "role": "miner", "hotkey": "h",
                           "seq": 2, "t": 1.0,
                           "BadName": 1.0, "x/y": 2.0,
                           "steps": "not-a-number",
                           "note": "x" * 500,
                           "loss_ema": 3.5})
    assert got == {"hb": 1, "role": "miner", "hotkey": "h", "seq": 2,
                   "t": 1.0, "loss_ema": 3.5}


def test_build_heartbeat_lints_field_names():
    # the registry name lint applies to heartbeat fields at the PRODUCER:
    # a field that cannot be a metric name must fail here, not at every
    # consumer (the conftest-era lint, extended to the heartbeat schema)
    with pytest.raises(ValueError):
        build_heartbeat("miner", "h", 1, now=0.0, **{"Bad Name": 1.0})
    from distributedtraining_tpu.engine.health import HEARTBEAT_FIELDS
    for name in HEARTBEAT_FIELDS:
        obs.check_metric_name(name)  # the documented schema itself lints


def test_vitals_step_rate_and_loss_ema():
    clock = FakeClock(100.0)
    state = {"steps": 0, "loss": 4.0}
    v = Vitals(steps=lambda: state["steps"], loss=lambda: state["loss"],
               counters=lambda: {"pushes": 2}, base_revision=lambda: "rev1",
               ema_alpha=0.5, clock=clock)
    first = v.collect()
    assert first["steps"] == 0.0 and "step_rate" not in first
    assert first["loss_ema"] == 4.0 and first["pushes"] == 2.0
    assert first["base_revision"] == "rev1"
    assert isinstance(first["registry_digest"], str)
    state["steps"], state["loss"] = 50, 2.0
    clock.advance(10.0)
    second = v.collect()
    assert second["step_rate"] == pytest.approx(5.0)
    assert second["loss_ema"] == pytest.approx(3.0)  # 4.0 + 0.5*(2-4)
    # non-finite losses never poison the EMA
    state["loss"] = float("nan")
    clock.advance(10.0)
    assert v.collect()["loss_ema"] == pytest.approx(3.0)


def test_report_vitals_reads_miner_report():
    from distributedtraining_tpu.engine.train import MinerReport
    r = MinerReport(steps=10, pushes=2, pushes_failed=1, last_loss=1.5)
    body = report_vitals(r).collect()
    assert body["steps"] == 10.0 and body["pushes"] == 2.0
    assert body["pushes_failed"] == 1.0
    assert body["loss_ema"] == 1.5


# ---------------------------------------------------------------------------
# Publisher
# ---------------------------------------------------------------------------

def test_heartbeat_publisher_periodic_and_clean_shutdown():
    t = InMemoryTransport()
    hb = HeartbeatPublisher(t, "miner", "hk0", interval=0.01,
                            vitals=Vitals(steps=lambda: 5))
    hb.start()
    deadline = time.monotonic() + 5.0
    while hb.sent < 3 and time.monotonic() < deadline:
        time.sleep(0.01)
    hb.close()
    assert hb.sent >= 3 and hb.failed == 0
    got = parse_heartbeat(t.fetch_delta_meta(heartbeat_id("miner", "hk0")))
    assert got is not None and got["seq"] >= 3 and got["steps"] == 5.0
    # the timer and upload worker are gone (the conftest guard's rule)
    assert not [th for th in threading.enumerate()
                if th.name.startswith("heartbeat-")]
    hb.close()  # idempotent


def test_heartbeat_publisher_survives_transport_failure():
    class Broken:
        def publish_delta_meta(self, node_id, meta):
            raise OSError("down")

    hb = HeartbeatPublisher(Broken(), "miner", "hk0", interval=60.0)
    hb.beat_now(wait=True)
    hb.beat_now(wait=True)
    hb.close()
    assert hb.failed == 2 and hb.sent == 0  # counted, never raised


# ---------------------------------------------------------------------------
# SLO rules
# ---------------------------------------------------------------------------

def _beat(transport, role, hotkey, seq, **fields):
    transport.publish_delta_meta(
        heartbeat_id(role, hotkey),
        build_heartbeat(role, hotkey, seq, now=float(seq), **fields))


def test_slo_rule_vocabulary_validated():
    with pytest.raises(ValueError):
        SLORule("ok_name", "no_such_kind", threshold=1)
    with pytest.raises(ValueError):
        SLORule("Bad Name", "stale", threshold=1)
    assert {r.kind for r in default_slo_rules()} == {
        "stale", "loss_divergence", "push_failures", "step_rate_collapse"}


def test_slo_stale_node_fires_once_and_arms_anomaly():
    class _Cap:
        arm_calls = 0
        def arm(self):
            self.arm_calls += 1
        def tick(self):
            pass
        def close(self):
            pass

    cap = _Cap()
    t = InMemoryTransport()
    fm = FleetMonitor(t, rules=[SLORule("stale_node", "stale", threshold=2)],
                      anomaly=AnomalyMonitor(cap), metrics=InMemorySink())
    try:
        _beat(t, "miner", "hk0", 1, steps=1)
        assert fm.poll(["hk0"]) == 1
        assert fm.evaluate_slos() == []          # fresh: within objective
        for _ in range(3):                       # hk0 goes silent
            fm.poll(["hk0"])
        breaches = fm.evaluate_slos()
        assert [b["slo_breach"] for b in breaches] == ["stale_node"]
        assert fm.evaluate_slos() == []          # one-shot per (node, rule)
        assert cap.arm_calls == 1                # armed the monitor capture
        assert fm.anomaly.triggered == "slo_stale_node"
        assert fm.nodes[("miner", "hk0")].breaches == ["stale_node"]
    finally:
        fm.close()


def test_slo_loss_divergence_needs_fleet_median():
    t = InMemoryTransport()
    fm = FleetMonitor(t, rules=[SLORule(
        "loss_divergence", "loss_divergence", threshold=0.5, factor=1.5)])
    try:
        _beat(t, "miner", "a", 1, loss_ema=2.0)
        _beat(t, "miner", "b", 1, loss_ema=2.1)
        fm.poll(["a", "b"])
        assert fm.evaluate_slos() == []  # two nodes: no meaningful median
        _beat(t, "miner", "c", 1, loss_ema=2.2)
        _beat(t, "miner", "d", 1, loss_ema=9.0)  # the diverged node
        fm.poll(["a", "b", "c", "d"])
        breaches = fm.evaluate_slos()
        assert [(b["slo_breach"], b["hotkey"]) for b in breaches] == [
            ("loss_divergence", "d")]
    finally:
        fm.close()


def test_slo_push_failure_streak_from_counter_deltas():
    t = InMemoryTransport()
    fm = FleetMonitor(t, rules=[SLORule(
        "push_failure_streak", "push_failures", threshold=3)])
    try:
        _beat(t, "miner", "hk", 1, pushes=5, pushes_failed=0)
        fm.poll(["hk"])
        _beat(t, "miner", "hk", 2, pushes=5, pushes_failed=2)
        fm.poll(["hk"])
        assert fm.evaluate_slos() == []          # streak 2 < 3
        _beat(t, "miner", "hk", 3, pushes=6, pushes_failed=3)
        fm.poll(["hk"])
        assert fm.evaluate_slos() == []          # a success reset it
        _beat(t, "miner", "hk", 4, pushes=6, pushes_failed=6)
        fm.poll(["hk"])
        assert [b["slo_breach"] for b in fm.evaluate_slos()] == [
            "push_failure_streak"]
    finally:
        fm.close()


def test_slo_step_rate_collapse_after_warmup():
    t = InMemoryTransport()
    fm = FleetMonitor(t, rules=[SLORule(
        "step_rate_collapse", "step_rate_collapse", threshold=0.0,
        factor=0.25, warmup=3)])
    try:
        for seq, rate in ((1, 10.0), (2, 11.0)):
            _beat(t, "miner", "hk", seq, step_rate=rate)
            fm.poll(["hk"])
        assert fm.evaluate_slos() == []          # still warming up
        _beat(t, "miner", "hk", 3, step_rate=1.0)  # < 0.25 x peak 11
        fm.poll(["hk"])
        breaches = fm.evaluate_slos()
        assert [b["slo_breach"] for b in breaches] == ["step_rate_collapse"]
    finally:
        fm.close()


# ---------------------------------------------------------------------------
# Contribution ledger
# ---------------------------------------------------------------------------

def test_ledger_counts_staging_outcomes():
    fm = FleetMonitor(InMemoryTransport(), metrics=InMemorySink())
    try:
        fm.round = 1
        fm.record_staging([
            StagedDelta("a", delta={"w": np.ones(2)}, reason="ok",
                        revision="r1", cid=None),
            StagedDelta("b", delta=None, reason="nonfinite",
                        revision="r9", cid=None),
            StagedDelta("v91", delta=None, reason="no_delta",
                        revision=None, cid=None),
        ])
        led = fm.ledger()
        assert "miner/v91" not in led   # never-published hotkeys stay out
        a, b = led["miner/a"], led["miner/b"]
        assert a["published"] == 1 and a["accepted"] == 1
        assert a["declined"] == 0 and a["stale_rounds"] == 0
        assert b["published"] == 1 and b["accepted"] == 0
        assert b["declined"] == 1 and b["last_reason"] == "nonfinite"
        # same revision staged again: published stays, staleness grows
        fm.round = 2
        fm.record_staging([StagedDelta("a", delta={"w": np.ones(2)},
                                       reason="ok", revision="r1",
                                       cid=None)])
        a = fm.ledger()["miner/a"]
        assert a["published"] == 1 and a["accepted"] == 2
        assert a["stale_rounds"] == 1
        fm.record_scores({"a": 0.25})
        assert fm.ledger()["miner/a"]["score"] == 0.25
    finally:
        fm.close()


# ---------------------------------------------------------------------------
# JSONL rotation (satellite) + segment-transparent reads
# ---------------------------------------------------------------------------

def test_jsonl_sink_rotation_and_segment_reads(tmp_path):
    path = str(tmp_path / "m.jsonl")
    sink = JSONLSink(path, max_bytes=2000, keep_segments=2)
    n = 120
    for i in range(n):
        sink.log({"i": i, "pad": "x" * 40})
    sink.close()
    assert sink.rotations >= 3
    segs = jsonl_segments(path)
    # bounded: at most keep_segments rotated files + the current file
    # (absent when the last write itself rotated — reopen is lazy)
    assert len(segs) <= 3
    assert segs == [s for s in (f"{path}.2", f"{path}.1", path)
                    if os.path.exists(s)]
    # oldest-first concatenation yields a strictly increasing tail of i's
    recs = obs_report.load_records([path])
    idx = [r["i"] for r in recs if "i" in r]
    assert idx == list(range(n - len(idx), n))  # newest kept, order intact
    assert idx[-1] == n - 1
    # every surviving line is a whole record (rotation never tears)
    for seg in segs:
        for line in open(seg):
            json.loads(line)


def test_jsonl_sink_no_rotation_by_default(tmp_path):
    path = str(tmp_path / "m.jsonl")
    sink = JSONLSink(path)
    for i in range(50):
        sink.log({"i": i, "pad": "x" * 100})
    sink.close()
    assert sink.rotations == 0 and jsonl_segments(path) == [path]


# ---------------------------------------------------------------------------
# Compile-time accounting (satellite)
# ---------------------------------------------------------------------------

def test_screen_deltas_records_compile_ms():
    from distributedtraining_tpu import delta as delta_lib
    obs.configure(InMemorySink(), role="t")
    # a shape no other test screens, so this test always sees a FRESH
    # compile however many screens ran before it in the process
    base = {"w": np.zeros((5, 7), np.float32)}
    deltas = [{"w": np.ones((5, 7), np.float32) * i} for i in range(2)]
    before = obs.registry().histogram("compile.ms").count
    verdicts = delta_lib.screen_deltas(deltas, base)
    assert all(ok for ok, _ in verdicts)
    reg = obs.registry()
    assert reg.histogram("compile.ms").count == before + 1
    assert reg.counter("screen.fresh_compiles").value >= 1
    # same shapes again: cached program, no new compile recorded
    delta_lib.screen_deltas(deltas, base)
    assert reg.histogram("compile.ms").count == before + 1


def test_cohort_evaluator_records_compile_ms():
    from distributedtraining_tpu.engine.batched_eval import (
        BatchedCohortEvaluator)
    obs.configure(InMemorySink(), role="t")
    model, cfg = gpt2.make_model("tiny")
    engine = TrainEngine(model, seq_len=8)
    base = engine.place_params(model.init_params(jax.random.PRNGKey(0)))
    zeros = jax.tree_util.tree_map(lambda x: np.zeros(x.shape, x.dtype),
                                   jax.device_get(base))
    batch = {"input_ids": np.zeros((2, 8), np.int32)}
    ev = BatchedCohortEvaluator(engine)
    ev.evaluate_cohort(base, [zeros, zeros], iter([batch]))
    reg = obs.registry()
    assert reg.counter("val.cohort_bucket_compiles").value == 1
    assert reg.histogram("compile.ms").count >= 1


# ---------------------------------------------------------------------------
# Exporter
# ---------------------------------------------------------------------------

_PROM_LINE = re.compile(
    r"^[a-zA-Z_][a-zA-Z0-9_]*(\{[^}]*\})? (NaN|[+-]?[0-9eE.+-]+)$")


def test_exporter_serves_registry_and_ledger(tmp_path):
    obs.configure(InMemorySink(), role="t")
    obs.count("publish.pushes", 3)
    obs.observe("miner.step_ms", 12.5)
    obs.gauge("device.mem_peak_bytes", 1e9)
    t = InMemoryTransport()
    fm = FleetMonitor(t, metrics=InMemorySink())
    exp = ObsHTTPExporter(0, fleet=fm, role="tester")
    try:
        _beat(t, "miner", "hk0", 1, steps=5, loss_ema=2.0, pushes=1)
        fm.poll(["hk0"])
        fm.record_staging([StagedDelta("hk0", delta={"w": np.ones(1)},
                                       reason="ok", revision="r1",
                                       cid=None)])
        port = exp.start()
        assert exp in live_exporters()
        body = urllib.request.urlopen(
            f"http://127.0.0.1:{port}/metrics", timeout=10).read().decode()
        lines = [ln for ln in body.splitlines() if ln]
        assert lines, body
        for ln in lines:
            if not ln.startswith("#"):
                assert _PROM_LINE.match(ln), ln
        assert "dt_publish_pushes 3.0" in body
        assert "dt_miner_step_ms_p50" in body       # histogram flattening
        assert "dt_device_mem_peak_bytes" in body   # gauge
        assert ('dt_fleet_accepted{role="miner",hotkey="hk0"} 1.0'
                in body)                            # ledger series
        assert 'dt_fleet_loss_ema{role="miner",hotkey="hk0"} 2.0' in body
        hz = json.loads(urllib.request.urlopen(
            f"http://127.0.0.1:{port}/healthz", timeout=10).read())
        assert hz["ok"] is True and hz["fleet_nodes"] == 1
    finally:
        exp.close()
        fm.close()
    assert exp not in live_exporters()
    with pytest.raises(Exception):
        urllib.request.urlopen(f"http://127.0.0.1:{port}/metrics",
                               timeout=1)


def test_render_is_parseable_with_empty_state():
    out = render(registry=obs.registry(), fleet=None)
    assert out.endswith("\n")


def test_render_exports_serve_latency_quantile_gauges():
    """serve.ttft_ms / serve.tpot_ms histograms additionally export as
    ONE labeled gauge family each — dt_serve_ttft_ms{q="0.5|0.95|0.99"}
    — so a Grafana latency panel selects quantiles by label; the
    flattened _p50/_p95/_p99 names keep rendering for existing
    dashboards."""
    reg = obs.Registry()
    for v in range(1, 101):
        reg.histogram("serve.ttft_ms").observe(float(v))
    reg.histogram("serve.tpot_ms").observe(7.0)
    body = render(registry=reg, fleet=None)
    assert 'dt_serve_ttft_ms{q="0.5"} 50.5' in body
    assert 'dt_serve_ttft_ms{q="0.95"}' in body
    assert 'dt_serve_ttft_ms{q="0.99"}' in body
    assert 'dt_serve_tpot_ms{q="0.95"} 7.0' in body
    # the flattened spellings survive alongside
    assert "dt_serve_ttft_ms_p50 50.5" in body
    for ln in body.splitlines():
        if ln and not ln.startswith("#"):
            assert _PROM_LINE.match(ln), ln
    # an EMPTY serve histogram emits no labeled series (no NaN spam),
    # and a counter under a quantile name is left alone
    reg2 = obs.Registry()
    reg2.histogram("serve.ttft_ms")
    reg2.counter("serve.tpot_ms".replace("tpot", "other")).inc()
    body2 = render(registry=reg2, fleet=None)
    assert '{q=' not in body2


# ---------------------------------------------------------------------------
# The full localfs fleet round
# ---------------------------------------------------------------------------

def _batch(cfg, n=2, seq=16, seed=0):
    rng = np.random.default_rng(seed)
    return {"input_ids": np.asarray(
        rng.integers(0, cfg.vocab_size, (n, seq)), np.int32)}


def test_fleet_round_localfs_ledger_matches_merge_and_stale_slo(tmp_path):
    """The acceptance round: 3 miners heartbeat + push over localfs, the
    validator and averager run FleetMonitors, miner hotkey_2 is killed
    after the first round (its publisher closes, no further beats), the
    stale-miner SLO fires and arms the AnomalyMonitor, and the
    fleet_report ledger matches the averager's merge decisions exactly."""
    model, cfg = gpt2.make_model("tiny")
    art = str(tmp_path / "artifacts")
    chain_dir = str(tmp_path / "chain")
    hotkeys = ["hotkey_0", "hotkey_1", "hotkey_2"]
    paths = {r: str(tmp_path / f"{r}.jsonl")
             for r in ("miner", "validator", "averager")}

    def eval_batches():
        yield _batch(cfg, seed=1)

    # -- miners: train, heartbeat, push ------------------------------------
    msink = JSONLSink(paths["miner"])
    obs.configure(msink, role="miner")
    publishers = {}
    try:
        for hk in hotkeys:
            transport = LocalFSTransport(art)
            loop = MinerLoop(TrainEngine(model, seq_len=16), transport, hk,
                             send_interval=1e9, check_update_interval=1e9,
                             metrics=msink, log_every=2)
            hb = HeartbeatPublisher(transport, "miner", hk, interval=1e9,
                                    vitals=report_vitals(loop.report))
            loop.bootstrap(jax.random.PRNGKey(0))
            loop.run(iter([_batch(cfg)] * 3), max_steps=3)
            loop._push_delta()
            loop._publisher.flush()
            hb.beat_now(wait=True)
            assert loop.report.pushes == 1
            publishers[hk] = hb
    finally:
        obs.reset()
        msink.close()

    # -- validator: scores the fleet, ledger gets the score history --------
    vsink = JSONLSink(paths["validator"])
    obs.configure(vsink, role="validator")
    vfm = FleetMonitor(LocalFSTransport(art), metrics=vsink)
    try:
        val = Validator(TrainEngine(model, seq_len=16),
                        LocalFSTransport(art),
                        LocalChain(chain_dir, my_hotkey="hotkey_91"),
                        eval_batches=eval_batches, metrics=vsink,
                        cohort_size=8, fleet=vfm)
        val.bootstrap(rng=jax.random.PRNGKey(0))
        results = val.validate_and_score()
        scored = {s.hotkey: s for s in results}
        for hk in hotkeys:
            assert scored[hk].loss is not None
        vled = vfm.ledger()
        for hk in hotkeys:
            assert vled[f"miner/{hk}"]["beats"] == 1
            assert vled[f"miner/{hk}"]["accepted"] == 1
            assert math.isfinite(vled[f"miner/{hk}"]["score"])
    finally:
        val.close()
        obs.reset()
        vsink.close()

    # -- averager round 1: all three merge ---------------------------------
    asink = JSONLSink(paths["averager"])
    obs.configure(asink, role="averager")

    class _Cap:
        arm_calls = 0
        def arm(self):
            self.arm_calls += 1
        def tick(self):
            pass
        def close(self):
            pass

    cap = _Cap()
    afm = FleetMonitor(LocalFSTransport(art), metrics=asink,
                       rules=[SLORule("stale_node", "stale", threshold=2)],
                       anomaly=AnomalyMonitor(cap))
    try:
        avg = AveragerLoop(TrainEngine(model, seq_len=16),
                           LocalFSTransport(art),
                           LocalChain(chain_dir, my_hotkey="hotkey_99"),
                           WeightedAverage(uniform=True),
                           val_batches=eval_batches, metrics=asink,
                           fleet=afm)
        avg.bootstrap(rng=jax.random.PRNGKey(0))
        assert avg.run_round() is True
        assert avg.report.last_accepted == 3

        led = afm.ledger()
        # the ledger IS the merge decision record: per-miner counts match
        # the averager's report exactly
        assert sum(led[f"miner/{h}"]["accepted"] for h in hotkeys) \
            == avg.report.last_accepted
        for hk in hotkeys:
            entry = led[f"miner/{hk}"]
            assert entry["published"] == 1 and entry["accepted"] == 1
            assert entry["declined"] == 0 and entry["beats"] == 1

        # -- kill hotkey_2 mid-run: no further beats from it ---------------
        publishers["hotkey_2"].close()
        for r in range(3):
            for hk in ("hotkey_0", "hotkey_1"):   # the living miners
                publishers[hk].beat_now(wait=True)
            assert avg.run_round() is True        # rounds keep merging
        led = afm.ledger()
        dead, alive = led["miner/hotkey_2"], led["miner/hotkey_0"]
        assert dead["breaches"] == ["stale_node"]
        assert alive["breaches"] == []
        assert cap.arm_calls == 1                 # SLO armed the one-shot
        assert afm.anomaly.triggered == "slo_stale_node"
        # the dead miner's unchanged artifact kept merging (stale_rounds
        # grows) — staleness is about HEARTBEATS, contribution about deltas
        assert dead["stale_rounds"] >= 3 and dead["accepted"] == 4
    finally:
        for hb in publishers.values():
            hb.close()
        avg.close()   # also closes afm
        obs.reset()
        asink.close()

    # -- fleet_report joins the streams ------------------------------------
    rep = fleet_report.build_report([paths["validator"], paths["averager"]])
    nodes = rep["nodes"]
    assert set(nodes) >= {f"miner/{h}" for h in hotkeys}
    assert nodes["miner/hotkey_2"]["accepted"] == 4
    assert nodes["miner/hotkey_2"]["published"] == 1
    assert nodes["miner/hotkey_0"]["published"] == 1
    assert nodes["miner/hotkey_2"]["breaches"] == ["stale_node"]
    assert rep["heartbeats"] >= 3
    assert any(b["slo_breach"] == "stale_node" and b["hotkey"] == "hotkey_2"
               for b in rep["breaches"])
    # registry section: the averager's flush snapshots are attributed
    assert "averager" in rep["registry"]
    table = fleet_report.format_table(rep)
    assert "hotkey_2" in table and "stale_node" in table
    # machine-readable: the ledger the driver asserts against
    out = json.dumps(rep, default=float)
    assert json.loads(out)["nodes"]["miner/hotkey_1"]["accepted"] == 4


# ---------------------------------------------------------------------------
# BurnRateMonitor: multi-window SLO burn over the request-trace stream
# ---------------------------------------------------------------------------

def test_burn_rule_vocabulary_validated():
    with pytest.raises(ValueError, match="unknown burn SLO"):
        BurnRule("latency", objective_ms=100.0)
    with pytest.raises(ValueError, match="budget"):
        BurnRule("shed", budget=0.0)
    with pytest.raises(ValueError, match="objective_ms"):
        BurnRule("ttft")          # a latency rule needs an objective
    with pytest.raises(ValueError, match="one BurnRule per slo"):
        BurnRateMonitor([BurnRule("shed"), BurnRule("shed")])
    slos = {r.slo for r in default_burn_rules()}
    assert slos == {"ttft", "tpot", "shed"}


def test_burn_math_min_samples_and_window_cutoff():
    """burn = (bad/n)/budget over the trailing window; sparse traffic
    (< min_samples in window) reads 0.0 so a quiet server never pages;
    events aging out of the window stop counting."""
    now = [10_000.0]
    mon = BurnRateMonitor([BurnRule("ttft", objective_ms=100.0,
                                    budget=0.1)],
                          clock=lambda: now[0], min_samples=10)
    # 9 violations: still below min_samples => 0.0
    for i in range(9):
        mon.observe(now[0], ttft_ms=500.0)
    assert mon.burn("ttft", 300.0) == 0.0
    mon.observe(now[0], ttft_ms=10.0)
    # 10 samples, 9 bad: (0.9)/0.1 = 9.0
    assert mon.burn("ttft", 300.0) == pytest.approx(9.0)
    assert mon.max_burn() == pytest.approx(9.0)
    # 30 good samples later, the window dilutes
    for _ in range(30):
        mon.observe(now[0], ttft_ms=10.0)
    assert mon.burn("ttft", 300.0) == pytest.approx((9 / 40) / 0.1)
    # advance past the window: the old outcomes age out entirely
    now[0] += 400.0
    for _ in range(10):
        mon.observe(now[0], ttft_ms=10.0)
    assert mon.burn("ttft", 300.0) == 0.0
    # shed outcomes never pollute the latency stream
    mon.observe(now[0], shed=True)
    assert mon.burn("ttft", 300.0) == 0.0


def test_burn_alert_needs_both_windows_and_fires_once():
    """The multi-window rule: a short-window spike alone (blip) does
    not page; short AND long past the factor does — once per
    (slo, pair) per monitor lifetime."""
    now = [100_000.0]
    mon = BurnRateMonitor([BurnRule("tpot", objective_ms=50.0,
                                    budget=0.02)],
                          clock=lambda: now[0], min_samples=5)
    short_s, long_s, factor = BURN_WINDOWS["fast"]
    # seed the LONG window with enough good traffic that only the
    # short window burns: long-window rate stays under factor*budget
    t_old = now[0] - long_s + 60.0
    for _ in range(2000):
        mon.observe(t_old, tpot_ms=1.0)
    for _ in range(20):
        mon.observe(now[0], tpot_ms=500.0)
    assert mon.burn("tpot", short_s) > factor      # short window burns
    assert mon.burn("tpot", long_s) < factor       # long one does not
    assert mon.evaluate(now[0]) == []              # blip: no page
    # sustained: violations now dominate the long window too
    for _ in range(3000):
        mon.observe(now[0], tpot_ms=500.0)
    fired = mon.evaluate(now[0], round_num=7)
    assert [f"slo_burn.{a['slo_burn']}.{a['window']}" for a in fired] \
        == ["slo_burn.tpot.fast", "slo_burn.tpot.slow"]
    assert all(a["burn_short"] > a["factor"] and
               a["burn_long"] > a["factor"] and a["round"] == 7
               for a in fired)
    # once per lifetime
    assert mon.evaluate(now[0]) == []
    assert mon.alerts == fired


def test_burn_shed_stream_escalation_and_gauges():
    """The shed SLO sees EVERY outcome (completion = good, refusal =
    bad); firing walks the standard escalation (metrics sink +
    anomaly one-shot) and the gauges export the full slo x window
    matrix for dt_slo_burn."""

    class _Anom:
        def __init__(self):
            self.fired = []

        def trigger_external(self, reason, **details):
            self.fired.append(reason)

    now = [50_000.0]
    sink = InMemorySink()
    anom = _Anom()
    mon = BurnRateMonitor([BurnRule("shed", budget=0.02)],
                          clock=lambda: now[0], metrics=sink,
                          anomaly=anom, min_samples=5)
    for _ in range(100):
        mon.observe(now[0], ttft_ms=10.0)   # completions: good
    assert mon.evaluate(now[0]) == []
    for _ in range(400):
        mon.observe(now[0], shed=True)      # refusals burn
    fired = mon.evaluate(now[0])
    assert {a["window"] for a in fired} == {"fast", "slow"}
    assert anom.fired == ["slo_burn.shed.fast", "slo_burn.shed.slow"]
    logged = [r for r in sink.records if r.get("slo_burn") == "shed"]
    assert len(logged) == 2
    gauges = mon.gauges(now[0])
    assert set(gauges) == {("shed", w)
                           for w in ("5m", "30m", "1h", "6h")}
    assert gauges[("shed", "5m")] > 14.4


def test_attach_burn_exports_dt_slo_burn():
    """obs_http.render picks up whichever monitor the serving role
    attached; detach (or monitor death) removes the series — weakref,
    a closed engine must not pin its monitor."""
    now = [1_000.0]
    mon = BurnRateMonitor(clock=lambda: now[0], min_samples=1)
    for _ in range(20):
        mon.observe(now[0], ttft_ms=999.0, tpot_ms=1.0)
    attach_burn(mon)
    try:
        assert live_burn_monitor() is mon
        body = render(registry=obs.registry(), fleet=None)
        assert '# TYPE dt_slo_burn gauge' in body
        assert 'dt_slo_burn{slo="ttft",window="5m"}' in body
        assert 'dt_slo_burn{slo="shed",window="6h"}' in body
    finally:
        attach_burn(None)
    assert live_burn_monitor() is None
    assert "dt_slo_burn" not in render(registry=obs.registry(),
                                       fleet=None)
