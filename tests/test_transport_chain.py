"""Transport backends, local chain simulator, scheduler, timeout wrapper."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributedtraining_tpu import delta
from distributedtraining_tpu.chain import LocalAddressStore, LocalChain
from distributedtraining_tpu.chain.base import (
    ema_update, mad_anomaly_mask, normalize_scores, quantize_u16)
from distributedtraining_tpu.engine.scheduler import FakeClock, PeriodicAction
from distributedtraining_tpu.transport import InMemoryTransport, LocalFSTransport
from distributedtraining_tpu.utils.timeout import ChainTimeout, run_with_timeout


def tree(seed=0):
    k = jax.random.PRNGKey(seed)
    return {"w": jax.random.normal(k, (4, 4)), "b": jnp.ones((4,))}


@pytest.fixture(params=["memory", "localfs"])
def transport(request, tmp_path):
    if request.param == "memory":
        return InMemoryTransport()
    return LocalFSTransport(str(tmp_path / "t"))


def test_delta_roundtrip_and_revision(transport):
    base = tree(0)
    d = delta.compute_delta(tree(1), base)
    assert transport.delta_revision("m1") is None
    assert transport.fetch_delta("m1", base) is None
    rev1 = transport.publish_delta("m1", d)
    assert rev1 is not None
    out = transport.fetch_delta("m1", base)
    for a, b in zip(jax.tree_util.tree_leaves(out), jax.tree_util.tree_leaves(d)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    # change detection: same content -> same revision; new content -> new
    assert transport.publish_delta("m1", d) == rev1
    rev2 = transport.publish_delta("m1", delta.tree_scale(d, 2.0))
    assert rev2 != rev1


def test_base_roundtrip(transport):
    base = tree(2)
    assert transport.base_revision() is None
    assert transport.fetch_base(base) is None
    rev = transport.publish_base(base)
    fetched, rev2 = transport.fetch_base(base)
    assert rev == rev2
    for a, b in zip(jax.tree_util.tree_leaves(fetched),
                    jax.tree_util.tree_leaves(base)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_corrupt_base_reads_as_absent(tmp_path):
    """Zero-length/garbage base file must not crash a bootstrapping node
    (live-probe regression)."""
    import os
    t = LocalFSTransport(str(tmp_path / "t"))
    os.makedirs(str(tmp_path / "t" / "base"), exist_ok=True)
    with open(str(tmp_path / "t" / "base" / "averaged_model.msgpack"), "wb") as f:
        f.write(b"")
    assert t.fetch_base(tree(0)) is None
    with open(str(tmp_path / "t" / "base" / "averaged_model.msgpack"), "wb") as f:
        f.write(b"\xff" * 100)
    assert t.fetch_base(tree(0)) is None


def test_malformed_delta_returns_none(transport):
    base = tree(0)
    evil = {"completely": jnp.zeros((2,))}
    transport.publish_delta("evil", evil)
    assert transport.fetch_delta("evil", base) is None


def test_localfs_path_traversal_guard(tmp_path):
    t = LocalFSTransport(str(tmp_path / "t"))
    t.publish_delta("../../escape", tree(0))
    import os
    assert not os.path.exists(str(tmp_path / "escape.msgpack"))
    files = os.listdir(str(tmp_path / "t" / "deltas"))
    assert len(files) == 1


# -- chain ------------------------------------------------------------------

def test_local_chain_genesis(tmp_path):
    c = LocalChain(str(tmp_path), my_hotkey="hotkey_95")
    m = c.sync()
    assert len(m.hotkeys) == 100
    assert c.get_validator_uids() == list(range(91, 100))
    assert m.stakes[0] == 10.0 and m.stakes[95] == 10000.0


def test_chain_weight_emission_and_consensus(tmp_path):
    clock = FakeClock()
    c = LocalChain(str(tmp_path), my_hotkey="hotkey_95", epoch_length=0,
                   clock=clock)
    scores = {f"hotkey_{i}": float(i % 5) for i in range(10)}
    assert c.should_set_weights()
    assert c.set_weights(scores)
    w = c.get_weights()
    assert max(w.values()) == 65535
    cons = c.consensus_scores()
    assert cons  # stake-weighted view exists
    top = max(cons, key=cons.get)
    assert scores[top] == max(scores[k] for k in scores)


def test_chain_epoch_gating(tmp_path):
    clock = FakeClock()
    c = LocalChain(str(tmp_path), epoch_length=100, clock=clock)
    assert c.should_set_weights()  # never set before
    c.set_weights({"hotkey_1": 1.0})
    assert not c.should_set_weights()
    clock.advance(100 * 12.0)  # one epoch of 12s blocks
    assert c.should_set_weights()


def test_chain_ema_smoothing(tmp_path):
    clock = FakeClock()
    c = LocalChain(str(tmp_path), my_hotkey="hotkey_95", epoch_length=0,
                   clock=clock)
    c.set_weights({"hotkey_1": 3.0})
    s = c._state()["ema_scores"]["hotkey_95"]["hotkey_1"]
    assert abs(s - 1.0) < 1e-9  # 1/3 * 3.0 + 2/3 * 0


def test_address_store(tmp_path):
    s = LocalAddressStore(str(tmp_path))
    assert s.retrieve_repo("hk") is None
    s.store_repo("hk", "org/repo")
    assert s.retrieve_repo("hk") == "org/repo"
    s2 = LocalAddressStore(str(tmp_path))  # persisted
    assert s2.retrieve_repo("hk") == "org/repo"


def test_rate_limiter_blacklists(tmp_path):
    clock = FakeClock()
    c = LocalChain(str(tmp_path), clock=clock, rate_limit_seconds=5.0)
    assert c.rate_limit("addr")
    clock.advance(1.0)
    assert not c.rate_limit("addr")   # too fast -> refused (violation 1)
    clock.advance(100.0)
    assert c.rate_limit("addr")       # transient offense forgiven
    for _ in range(3):                # persistent hammering -> blacklist
        clock.advance(0.1)
        assert not c.rate_limit("addr")
    clock.advance(100.0)
    assert not c.rate_limit("addr")   # blacklist persists


# -- pure score math --------------------------------------------------------

def test_score_math():
    assert ema_update({"a": 1.0}, {"a": 4.0})["a"] == pytest.approx(2.0)
    n = normalize_scores({"a": 1.0, "b": 3.0, "c": -5.0})
    assert n["c"] == 0 and abs(sum(n.values()) - 1.0) < 1e-9
    q = quantize_u16([0.25, 0.5])
    assert q == [32768, 65535]
    flags = mad_anomaly_mask([1.0, 1.1, 0.9, 1.05, 50.0])
    assert flags == [False, False, False, False, True]
    # one-sided: a weak-but-honest straggler far BELOW a tight leader
    # cluster is kept (the gamed direction is up, not down) — the
    # two-sided spelling zeroed the weak miner in the r4 discriminating
    # round (E2E_r04_discriminate.json)
    flags = mad_anomaly_mask([3.883, 3.642, 2.221])
    assert flags == [False, False, False]


# -- scheduler + timeout ----------------------------------------------------

def test_periodic_action():
    clock = FakeClock()
    fired = []
    a = PeriodicAction(10.0, lambda: fired.append(clock.now()), clock)
    assert not a.poll()
    clock.advance(9.9)
    assert not a.poll()
    clock.advance(0.2)
    assert a.poll()
    assert not a.poll()
    clock.advance(10.0)
    assert a.poll()
    assert len(fired) == 2


def test_run_with_timeout():
    assert run_with_timeout(lambda: 42, 5.0) == 42
    with pytest.raises(ChainTimeout):
        import time
        run_with_timeout(lambda: time.sleep(10), 0.1)
    with pytest.raises(ValueError):
        def boom():
            raise ValueError("x")
        run_with_timeout(boom, 5.0)


def test_run_with_timeout_abandonment_is_bounded_and_observable():
    """N consecutive hangs must not grow the live thread count unboundedly
    when the caller's on_timeout hook can unblock the worker (the
    connection-kill pattern in chain/bittensor_chain.py), and every
    abandonment is counted (round-4 verdict #8: the old wrapper parked a
    thread forever per hang with no cap or metric)."""
    import threading
    import time
    from distributedtraining_tpu.utils import timeout as to

    # other tests in this session park their own workers (a 10s sleeper
    # in test_run_with_timeout, the wedged-sync fake) -- measure relative
    # to the live count at entry, which can only shrink on its own
    baseline = to.abandoned_workers()
    start_total = to.abandoned_total()
    events = []
    for _ in range(5):
        ev = threading.Event()
        events.append(ev)
        with pytest.raises(ChainTimeout):
            # the worker parks on the event (stand-in for a dead socket);
            # on_timeout "kills the connection" by setting it
            run_with_timeout(ev.wait, 0.05, name="hang",
                             on_timeout=ev.set)
    assert to.abandoned_total() - start_total == 5  # every hang counted
    # all five workers were unblocked by the hook -> the live-abandoned
    # gauge drains back to the entry level instead of accumulating
    deadline = time.time() + 5.0
    while to.abandoned_workers() > baseline and time.time() < deadline:
        time.sleep(0.02)
    assert to.abandoned_workers() <= baseline

    # without a hook the worker genuinely leaks -- and the gauge says so
    ev = threading.Event()
    with pytest.raises(ChainTimeout):
        run_with_timeout(ev.wait, 0.05, name="hang-noresc")
    assert to.abandoned_total() == start_total + 6
    leaked = [t for t in threading.enumerate()
              if t.name == "timeout-hang-noresc"]
    assert leaked and leaked[0].is_alive()
    ev.set()  # clean up for other tests
    leaked[0].join(timeout=5.0)
    assert not leaked[0].is_alive()


def test_bittensor_chain_weight_pipeline_screens_anomalies():
    """BittensorChain.set_weights runs the same EMA->MAD->normalize->u16
    pipeline as LocalChain, without needing the SDK (faked subtensor)."""
    from distributedtraining_tpu.chain.bittensor_chain import BittensorChain

    captured = {}

    class _FakeSub:
        block = 1000

        def set_weights(self, *, wallet, netuid, uids, weights, version_key,
                        wait_for_inclusion):
            captured["uids"] = uids
            captured["weights"] = weights
            return True

    class _FakeMeta:
        hotkeys = [f"hk{i}" for i in range(6)]

    chain = BittensorChain.__new__(BittensorChain)
    chain.netuid = 1
    chain.epoch_length = 100
    chain.wallet = object()
    chain.subtensor = _FakeSub()
    chain.metagraph = _FakeMeta()
    chain._ema = {}
    chain._last_weight_block = -10**9

    # hk5 is a cheater: absurdly high score vs the peer cluster
    scores = {"hk0": 1.0, "hk1": 1.1, "hk2": 0.9, "hk3": 1.05, "hk5": 500.0}
    assert chain.set_weights(scores)
    w = dict(zip(captured["uids"], captured["weights"]))
    assert w.get(5, 0) == 0                      # anomaly zeroed
    assert all(w[u] > 0 for u in (0, 1, 2, 3))   # peers kept
    assert max(captured["weights"]) == 65535     # u16 normalization
    assert chain._last_weight_block == 1000      # epoch gate advanced
    assert not chain.should_set_weights()


# -- BittensorChain against a stub subtensor (no SDK, no network) ------------

def _stub_chain(*, resync_blocks=0, epoch_length=100):
    """A BittensorChain over fake subtensor/metagraph/wallet objects,
    bypassing __init__ (the SDK is absent in this image)."""
    from distributedtraining_tpu.chain.bittensor_chain import BittensorChain

    class FakeSub:
        def __init__(self):
            self.block = 1000
            self.commits = {}
            self.weight_calls = []

        def set_weights(self, *, wallet, netuid, uids, weights, version_key,
                        wait_for_inclusion):
            self.weight_calls.append((uids, weights, version_key))
            return True

        def commit(self, wallet, netuid, data):
            self.commits[(netuid, wallet.hotkey.ss58_address)] = data

        def get_commitment(self, netuid, hotkey):
            return self.commits.get((netuid, hotkey), "")

    class FakeMeta:
        def __init__(self):
            self.hotkeys = [f"hk{i}" for i in range(6)]
            self.S = [10.0, 10.0, 10.0, 10.0, 5000.0, 2000.0]
            self.sync_calls = 0

        def sync(self, subtensor=None, lite=True):
            self.sync_calls += 1

    class FakeWallet:
        class hotkey:
            ss58_address = "hk4"

    chain = BittensorChain.__new__(BittensorChain)
    chain.netuid = 7
    chain.epoch_length = epoch_length
    chain.resync_blocks = resync_blocks
    chain.vpermit_stake_limit = 1000.0
    chain._last_sync_block = -(10**9)
    chain.wallet = FakeWallet()
    chain.subtensor = FakeSub()
    chain.metagraph = FakeMeta()
    chain._ema = {}
    chain._last_weight_block = -(10**9)
    return chain


def test_bittensor_chain_sync_and_permits():
    c = _stub_chain()
    meta = c.sync()
    assert meta.hotkeys[4] == c.my_hotkey == "hk4"
    assert meta.block == 1000
    assert meta.stakes[4] == 5000.0
    # vpermit: uids with stake >= limit (btt_connector.py:358-380)
    assert c.get_validator_uids() == [4, 5]
    assert c.get_validator_uids(stake_limit=3000.0) == [4]


def test_bittensor_chain_resync_throttle():
    """Within resync_blocks of the last sync the cached metagraph is served
    without an RPC (reference resync cadence, btt_connector.py:270-282)."""
    c = _stub_chain(resync_blocks=50)
    c.sync()
    assert c.metagraph.sync_calls == 1
    c.subtensor.block = 1040            # +40 blocks: inside the window
    c.sync()
    assert c.metagraph.sync_calls == 1  # cached
    c.subtensor.block = 1060            # +60: window expired
    c.sync()
    assert c.metagraph.sync_calls == 2

    always = _stub_chain(resync_blocks=0)
    always.sync(); always.sync()
    assert always.metagraph.sync_calls == 2


def test_bittensor_chain_weight_epoch_gate():
    c = _stub_chain(epoch_length=100)
    assert c.should_set_weights()
    assert c.set_weights({"hk0": 1.0})
    assert c._last_weight_block == 1000
    assert not c.should_set_weights()        # same block: gated
    c.subtensor.block = 1099
    assert not c.should_set_weights()
    c.subtensor.block = 1100
    assert c.should_set_weights()            # epoch boundary


def test_bittensor_chain_set_weights_emits_u16():
    c = _stub_chain()
    assert c.set_weights({"hk0": 2.0, "hk1": 1.0})
    uids, weights, version = c.subtensor.weight_calls[-1]
    assert uids == [0, 1]
    assert max(weights) == 65535             # u16 quantization
    assert weights[0] > weights[1]
    from distributedtraining_tpu import spec_version
    assert version == spec_version()


def test_bittensor_address_store_roundtrip():
    from distributedtraining_tpu.chain.bittensor_chain import (
        BittensorAddressStore)
    c = _stub_chain()
    store = BittensorAddressStore(c.subtensor, 7, wallet=c.wallet)
    assert store.retrieve_repo("hk4") is None       # empty commitment -> None
    store.store_repo("hk4", "org/repo")
    assert store.retrieve_repo("hk4") == "org/repo"
    # pubkey registry is chain-identity's job on bittensor: no-op surface
    store.store_pubkey("hk4", b"\x00" * 32)
    assert store.retrieve_pubkey("hk4") is None


def test_bittensor_chain_hung_rpc_times_out():
    """A wedged substrate connection surfaces as ChainTimeout from sync()
    instead of hanging the engine loop (utils/timeout.py deadline)."""
    import time as _time

    from distributedtraining_tpu.chain import bittensor_chain as bc

    c = _stub_chain()
    c.metagraph.sync = lambda **kw: _time.sleep(10)
    old = bc.CHAIN_OP_TIMEOUT
    bc.CHAIN_OP_TIMEOUT = 0.2
    try:
        with pytest.raises(ChainTimeout):
            c.sync()
    finally:
        bc.CHAIN_OP_TIMEOUT = old


def test_bittensor_chain_serve_axon_stub():
    """serve_axon passthrough (serve_extrinsic parity) with timeout hygiene."""
    c = _stub_chain()

    class FakeAxon:
        def __init__(self, wallet=None, ip=None, port=None):
            self.ip, self.port = ip, port

    class FakeBT:
        axon = FakeAxon

    served = {}

    def fake_serve_axon(netuid, axon):
        served["args"] = (netuid, axon.ip, axon.port)
        return True

    c.bt = FakeBT()
    c.subtensor.serve_axon = fake_serve_axon
    assert c.serve_axon("10.0.0.1", 8091)
    assert served["args"] == (7, "10.0.0.1", 8091)
