"""Batched cohort evaluation (engine/batched_eval.py).

Three contracts pinned here:

1. PARITY — cohort scores equal the sequential score_miner spelling to fp
   tolerance, including zero-padded slots, the folded-in base, the
   GeneticMerge candidate expansion, and a round with screened-out /
   missing miners mixed in.
2. PIPELINE — stage_cohorts really overlaps staging of cohort n+1 with
   the caller's (device) work on cohort n when pipelined, stages lazily
   in caller order when not, and stops promptly on close().
3. SHARDING — on a mesh the candidate axis SHARDS across devices instead
   of replicating the K x param stack, checked on the placed arrays and
   in the compiled HLO (the test_parameterized_mesh_merge_lowers_to_
   allreduce discipline).
"""

import threading
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributedtraining_tpu import delta
from distributedtraining_tpu.chain import LocalChain
from distributedtraining_tpu.data import ByteTokenizer, batch_iterator, text_corpus
from distributedtraining_tpu.engine import (
    BatchedCohortEvaluator, FakeClock, TrainEngine, Validator, stage_cohorts)
from distributedtraining_tpu.models import gpt2
from distributedtraining_tpu.transport import InMemoryTransport

SEQ = 32
BATCH = 4


@pytest.fixture(scope="module")
def setup():
    model, cfg = gpt2.make_model("tiny")
    engine = TrainEngine(model, seq_len=SEQ)
    tok = ByteTokenizer()
    val_docs = text_corpus(split="val", n_docs=12, source="synthetic")

    def val_batches():
        return list(batch_iterator(val_docs, tok, batch_size=BATCH,
                                   seq_len=SEQ, max_vocab=cfg.vocab_size))[:3]

    base = model.init_params(jax.random.PRNGKey(0))
    return model, cfg, engine, val_batches, base


def _make_deltas(base, n, scale=0.01):
    leaves, treedef = jax.tree_util.tree_flatten(base)
    key = jax.random.PRNGKey(7)
    out = []
    for _ in range(n):
        key, k = jax.random.split(key)
        ks = jax.random.split(k, len(leaves))
        out.append(jax.tree_util.tree_unflatten(
            treedef, [scale * jax.random.normal(kk, l.shape, l.dtype)
                      for kk, l in zip(ks, leaves)]))
    return out


# ---------------------------------------------------------------------------
# parity
# ---------------------------------------------------------------------------

def test_cohort_matches_sequential_with_padding(setup):
    """3 candidates in a 4-bucket (one zero-padded slot) + the base folded
    into slot 0: every score equals the one-at-a-time engine.evaluate
    spelling to fp tolerance, and padding perturbs nothing."""
    model, cfg, engine, val_batches, base = setup
    deltas = _make_deltas(base, 3)
    ev = BatchedCohortEvaluator(engine)
    assert ev.bucket_for(len(deltas) + 1) == 4  # base + 3 -> one padded slot

    got = ev.evaluate_cohort(base, deltas, val_batches(), include_base=True)
    assert len(got) == 4

    want = [engine.evaluate(base, val_batches())]
    want += [engine.evaluate(delta.apply_delta(base, d), val_batches())
             for d in deltas]
    for (gl, gp), (wl, wp) in zip(got, want):
        assert gl == pytest.approx(wl, rel=2e-4, abs=1e-6)
        assert gp == pytest.approx(wp, rel=2e-4, abs=1e-6)


def test_bucket_ladder():
    class E:  # engine stub: bucket_for touches only .mesh
        mesh = None

    ev = BatchedCohortEvaluator(E())
    assert [ev.bucket_for(k) for k in (1, 2, 3, 5, 8, 9, 16)] == \
        [1, 2, 4, 8, 8, 16, 16]
    assert ev.bucket_for(17) == 32   # beyond the ladder: multiples of 16
    assert ev.bucket_for(33) == 48
    with pytest.raises(ValueError):
        ev.bucket_for(0)


def test_validator_cohort_round_matches_sequential(setup, tmp_path):
    """Full validator round, batched (cohort 4, pipelined) vs sequential
    (cohort 0): identical reasons for the screened-out NaN miner and the
    no-delta hotkeys, and equal scores/losses to fp tolerance for the
    real submissions — padded slots included (2 valid miners in a cohort
    sized 4)."""
    model, cfg, engine, val_batches, base = setup
    transport = InMemoryTransport()
    transport.publish_base(base)
    d1, d2 = _make_deltas(base, 2)
    transport.publish_delta("hotkey_1", d1)
    transport.publish_delta("hotkey_2", d2)
    transport.publish_delta("hotkey_3", jax.tree_util.tree_map(
        lambda x: jnp.full_like(x, jnp.nan), base))  # screened out

    def make(csize, pdepth):
        chain = LocalChain(str(tmp_path / f"c{csize}"), my_hotkey="hotkey_95",
                           epoch_length=0, clock=FakeClock())
        v = Validator(engine, transport, chain, eval_batches=val_batches,
                      cohort_size=csize, pipeline_depth=pdepth)
        v.bootstrap(jax.random.PRNGKey(0))
        return {s.hotkey: s for s in v.validate_and_score()}

    batched = make(4, 1)
    seq = make(0, 0)

    assert set(batched) == set(seq)
    assert batched["hotkey_3"].reason == seq["hotkey_3"].reason == "nonfinite"
    assert batched["hotkey_4"].reason == "no_delta"
    for h in ("hotkey_1", "hotkey_2"):
        assert batched[h].reason == "ok"
        assert batched[h].loss == pytest.approx(seq[h].loss,
                                                rel=2e-4, abs=1e-6)
        assert batched[h].score == pytest.approx(seq[h].score,
                                                 rel=2e-4, abs=2e-4)


def test_genetic_candidate_expansion_matches_weighted_merge(setup):
    """combine_candidate_deltas + evaluate_stacked (GeneticMerge's batched
    population eval) reproduces weighted_merge + engine.evaluate per
    weight vector."""
    model, cfg, engine, val_batches, base = setup
    deltas = _make_deltas(base, 3)
    stacked = delta.stack_deltas(deltas)
    ws = [jnp.asarray(w, jnp.float32) for w in
          ([1.0, 0.0, 0.0], [0.2, 0.5, 0.3], [1 / 3] * 3)]

    cands = delta.combine_candidate_deltas(stacked, jnp.stack(ws))
    ev = BatchedCohortEvaluator(engine)
    got = ev.evaluate_stacked(base, cands, len(ws), val_batches())

    for w, (gl, gp) in zip(ws, got):
        wl, wp = engine.evaluate(delta.weighted_merge(base, stacked, w),
                                 val_batches())
        assert gl == pytest.approx(wl, rel=2e-4, abs=1e-6)
        assert gp == pytest.approx(wp, rel=2e-4, abs=1e-6)


def test_empty_batches_give_nan(setup):
    model, cfg, engine, val_batches, base = setup
    ev = BatchedCohortEvaluator(engine)
    got = ev.evaluate_cohort(base, _make_deltas(base, 2), iter(()))
    assert len(got) == 2 and all(np.isnan(l) and np.isnan(p)
                                 for l, p in got)
    assert ev.evaluate_cohort(base, [], iter(())) == []


# ---------------------------------------------------------------------------
# fetch/eval pipeline
# ---------------------------------------------------------------------------

class _SlowTransport(InMemoryTransport):
    """Fake transport whose per-delta fetch takes ``latency`` seconds —
    the network half of the fetch/eval overlap under test."""

    def __init__(self, latency=0.05):
        super().__init__()
        self.latency = latency
        self.fetched = []

    def fetch_delta_bytes(self, miner_id):
        # the artifact pull fetch_delta_any routes every validation through
        time.sleep(self.latency)
        self.fetched.append((miner_id, time.monotonic()))
        return super().fetch_delta_bytes(miner_id)


def test_stage_cohorts_overlaps_staging_with_eval(setup, tmp_path):
    """With pipeline=True the stager runs AHEAD of the consumer: while the
    consumer still holds cohort 0 (the device-eval phase), the background
    worker has already fetched cohort 1's submissions through the slow
    transport. Event-ordered, not wall-clock-timed, so CI jitter cannot
    flake it."""
    model, cfg, engine, val_batches, base = setup
    transport = _SlowTransport(latency=0.02)
    transport.publish_base(base)
    hotkeys = [f"hotkey_{i}" for i in range(1, 5)]
    for h, d in zip(hotkeys, _make_deltas(base, 4)):
        transport.publish_delta(h, d)
    chain = LocalChain(str(tmp_path), my_hotkey="hotkey_95",
                       epoch_length=0, clock=FakeClock())
    v = Validator(engine, transport, chain, eval_batches=val_batches,
                  cohort_size=2, pipeline_depth=1)
    v.bootstrap(jax.random.PRNGKey(0))

    staged = stage_cohorts(hotkeys, 2, v._stage_miner, pipeline=True, depth=1)
    first = next(staged)
    assert [h for h, d, r in first] == hotkeys[:2]
    assert all(d is not None for _, d, _ in first)
    # consumer has NOT asked for cohort 1 — the worker must fetch it anyway
    deadline = time.monotonic() + 5.0
    while len(transport.fetched) < 4 and time.monotonic() < deadline:
        time.sleep(0.005)
    assert len(transport.fetched) >= 4, \
        "cohort 1 was not staged while cohort 0 was held by the consumer"
    second = next(staged)
    assert [h for h, d, r in second] == hotkeys[2:]
    staged.close()


def test_stage_cohorts_inline_is_lazy(setup, tmp_path):
    """pipeline=False (the multi-host discipline): staging happens on the
    CONSUMER thread, strictly on demand — after pulling cohort 0 nothing
    of cohort 1 has been fetched, so broadcast collectives inside
    stage_one interleave deterministically with the eval program's."""
    model, cfg, engine, val_batches, base = setup
    transport = _SlowTransport(latency=0.0)
    transport.publish_base(base)
    hotkeys = [f"hotkey_{i}" for i in range(1, 5)]
    for h, d in zip(hotkeys, _make_deltas(base, 4)):
        transport.publish_delta(h, d)
    chain = LocalChain(str(tmp_path), my_hotkey="hotkey_95",
                       epoch_length=0, clock=FakeClock())
    v = Validator(engine, transport, chain, eval_batches=val_batches,
                  cohort_size=2, pipeline_depth=0)
    v.bootstrap(jax.random.PRNGKey(0))

    staged = stage_cohorts(hotkeys, 2, v._stage_miner, pipeline=False)
    next(staged)
    assert [h for h, _ in transport.fetched] == hotkeys[:2]
    next(staged)
    assert [h for h, _ in transport.fetched] == hotkeys


def test_stage_cohorts_close_stops_worker():
    """close() mid-round (a failed validation round) stops the background
    stager promptly instead of letting it drain the whole miner list."""
    staged_items = []
    release = threading.Event()

    def stage_one(x):
        staged_items.append(x)
        release.wait(2.0)
        return x

    staged = stage_cohorts(list(range(8)), 1, stage_one,
                           pipeline=True, depth=1)
    deadline = time.monotonic() + 2.0
    while not staged_items and time.monotonic() < deadline:
        time.sleep(0.005)
    staged.close()
    release.set()
    time.sleep(0.1)
    n = len(staged_items)
    time.sleep(0.1)
    # worker stopped: no further items staged after close settled
    assert len(staged_items) <= n + 1 < 8


def test_stage_cohorts_rejects_bad_cohort_size():
    with pytest.raises(ValueError):
        stage_cohorts([1, 2], 0, lambda x: x)


# ---------------------------------------------------------------------------
# mesh: candidate axis shards, not replicates
# ---------------------------------------------------------------------------

def test_mesh_cohort_shards_candidate_axis(setup, devices):
    """The K x param stack must SHARD over the mesh's merge axis (each
    device holds k_pad/axis_size candidates), the compiled program's only
    collective is the trailing all-gather of per-candidate scalars, and
    the sharded scores still match the single-device engine to fp
    tolerance."""
    from distributedtraining_tpu.parallel import MeshConfig, make_mesh
    from distributedtraining_tpu.parallel.collectives import merge_axis

    model, cfg, engine, val_batches, base = setup
    mesh = make_mesh(MeshConfig(dp=8))
    mesh_engine = TrainEngine(model, mesh=mesh, seq_len=SEQ)
    ev = BatchedCohortEvaluator(mesh_engine)

    deltas = _make_deltas(base, 3)
    # bucket 4 rounds up to a multiple of the 8-way merge axis
    assert ev.bucket_for(len(deltas)) == 8

    placed_base = mesh_engine.place_params(base)
    stacked, k_real = ev.stack_cohort(deltas)
    assert k_real == 3
    axis = merge_axis(mesh)
    for leaf in jax.tree_util.tree_leaves(stacked):
        assert leaf.shape[0] == 8
        # sharded, not replicated: each device holds ONE candidate slice
        shard = leaf.addressable_shards[0]
        assert shard.data.shape[0] == 8 // mesh.shape[axis]

    prog = ev._program()
    placed = ev._place_batch(val_batches()[0])
    txt = prog.lower(placed_base, stacked, placed).compile().as_text()
    assert "all-gather" in txt, \
        "candidate-sharded cohort compiled without the trailing all-gather"

    got = ev.evaluate_stacked(placed_base, stacked, k_real, val_batches())
    want = [engine.evaluate(delta.apply_delta(base, d), val_batches())
            for d in deltas]
    for (gl, gp), (wl, wp) in zip(got, want):
        assert gl == pytest.approx(wl, rel=2e-4, abs=1e-6)
        assert gp == pytest.approx(wp, rel=2e-4, abs=1e-6)
