"""Fleet-scale observatory (engine/fleetsim.py + the open-loop serving
harness in utils/loadgen.py).

The simulator's whole value is that its verdicts are trustworthy at a
scale CI cannot field for real, so the pins here are about the
CONTRACTS: seed-determinism (byte-identical scorecards), injected
ground truth vs detected quarantines, postmortem coverage of injected
kills, lease-epoch monotonicity across a forced failover, hier-vs-flat
parity, and the open-loop latency curve exposing queueing collapse that
a closed loop would hide. The 1000-actor acceptance run itself is
``-m slow``; tier-1 exercises the same machinery at ~24 actors.
"""

import dataclasses
import json

import pytest

from distributedtraining_tpu.engine import fleetsim as fs
from distributedtraining_tpu.transport.chaos import ChaosError
from distributedtraining_tpu.utils import loadgen


def smoke_spec(**over) -> fs.FleetSpec:
    """~24 actors, small rounds: the tier-1 scale."""
    base = dict(miners=18, validators=2, servers=2, sub_averagers=0,
                standby=True, rounds=4, seed=0, validator_cohort=8)
    base.update(over)
    return fs.FleetSpec(**base)


# ---------------------------------------------------------------------------
# The hub
# ---------------------------------------------------------------------------

def test_hub_counts_bytes_and_partitions_bidirectionally():
    hub = fs.SimHub()
    hub.publish_raw("m1", b"x" * 100)
    hub.publish_delta_meta("__hb__.miner.m1", {"hb": 1})
    assert hub.publishes == 2 and hub.publish_bytes > 100
    assert hub.fetch_delta_bytes("m1") == b"x" * 100
    assert hub.fetch_bytes == 100
    hub.partition("m1")
    # the node's own artifact id AND its reserved ids are unreachable
    with pytest.raises(ChaosError):
        hub.publish_raw("m1", b"y")
    with pytest.raises(ChaosError):
        hub.fetch_delta_meta("__hb__.miner.m1")
    hub.heal("m1")
    assert hub.fetch_delta_bytes("m1") == b"x" * 100
    assert hub.partition_faults == 2


def test_spec_validation_and_control_twin():
    with pytest.raises(ValueError):
        fs.FleetSpec(miners=4, stale_miners=5)
    with pytest.raises(ValueError):
        fs.FleetSpec.from_json('{"minerz": 3}')
    spec = smoke_spec(kills=2, rounds=8, partitions_per_round=1,
                      stale_miners=2)
    ctrl = spec.control()
    assert not ctrl.chaos and ctrl.kills == 0 \
        and ctrl.partitions_per_round == 0
    # behavioral injections survive into the control twin
    assert ctrl.stale_miners == 2
    rt = fs.FleetSpec.from_json(json.dumps(dataclasses.asdict(spec)))
    assert rt == spec


# ---------------------------------------------------------------------------
# Smoke: the tier-1 scale run
# ---------------------------------------------------------------------------

def test_smoke_round_trip_and_scorecard_shape():
    spec = smoke_spec(rounds=3)
    res = fs.simulate(spec)
    ctrl = fs.simulate(spec.control())
    card = fs.assemble_scorecard(res, ctrl)
    assert card["actors"] == spec.total_actors == 24
    assert card["rounds"]["completed"] >= spec.rounds - 1
    assert len(card["wire"]["samples"]) == spec.rounds
    assert card["wire"]["bytes_per_round"] > 0
    # merged per-actor registries reached the scorecard
    assert card["registry"].get("sim.pushes", 0) > 0
    assert card["registry"].get("sim.beats", 0) > 0
    assert "parity" in card and card["parity"]["rel_diff"] >= 0.0
    assert card["gates"]["rounds"]["ok"]
    # finalize stamps the id and the ONE out-of-region field
    final = fs.finalize_scorecard(card, now=123.0)
    assert final["t"] == 123.0
    assert final["scorecard_id"] == fs.scorecard_id(final)


def test_simulate_leaves_no_live_sims_or_obs_state():
    from distributedtraining_tpu.utils import obs

    fs.simulate(smoke_spec(rounds=2))
    assert fs.live_sims() == []
    assert not obs.dirty()   # the sim never configures the global layer


# ---------------------------------------------------------------------------
# Determinism (the acceptance contract)
# ---------------------------------------------------------------------------

def test_same_seed_scorecards_byte_identical_modulo_timestamp():
    spec = smoke_spec(rounds=4, stale_miners=1, poison_miners=1,
                      kills=1, partitions_per_round=1, seed=7)
    a = fs.finalize_scorecard(
        fs.assemble_scorecard(fs.simulate(spec),
                              fs.simulate(spec.control())), now=1.0)
    b = fs.finalize_scorecard(
        fs.assemble_scorecard(fs.simulate(spec),
                              fs.simulate(spec.control())), now=2.0)
    assert a["t"] != b["t"]
    assert a["scorecard_id"] == b["scorecard_id"]
    a.pop("t"), b.pop("t")
    assert json.dumps(a, sort_keys=True) == json.dumps(b, sort_keys=True)


def test_seed_changes_chaos_schedule():
    spec = smoke_spec(rounds=4, kills=2, partitions_per_round=1, seed=1)
    r1 = fs.simulate(spec)
    r2 = fs.simulate(dataclasses.replace(spec, seed=2))
    assert fs.chaos_schedule_digest(r1) != fs.chaos_schedule_digest(r2)
    # different draws, different outcomes — not just a relabeled digest
    assert (r1.kills, r1.partitions) != (r2.kills, r2.partitions)


# ---------------------------------------------------------------------------
# Quarantine precision / recall vs injected ground truth
# ---------------------------------------------------------------------------

def test_quarantine_detects_injected_misbehavior():
    spec = smoke_spec(miners=20, rounds=9, stale_miners=2,
                      divergent_miners=2, pushfail_miners=2,
                      poison_miners=2)
    res = fs.simulate(spec)
    assert len(res.truth_bad) == 6   # poison is NOT quarantine truth
    card = fs.assemble_scorecard(res)
    q = card["gates"]["quarantine"]
    assert q["ok"], q
    assert q["precision"] >= 0.9 and q["recall"] >= 0.9
    # hostile payloads were DECLINED by the admission screens instead
    assert card["hostile"]["poison_declines"] > 0
    assert card["gates"]["hostile"]["ok"]


def test_transient_partition_is_not_quarantined():
    """A 2-round partition of an honest miner heals before the stale
    threshold (3 observation rounds): correct fleets do not quarantine
    weather."""
    spec = smoke_spec(miners=16, rounds=8, partitions_per_round=1,
                      publish_error_rate=0.0, fetch_error_rate=0.0)
    res = fs.simulate(spec)
    assert res.partitions                 # the schedule actually fired
    assert res.quarantined_ever == []     # and nobody got quarantined


# ---------------------------------------------------------------------------
# Kills: postmortem coverage + averager failover
# ---------------------------------------------------------------------------

def test_every_injected_kill_leaves_a_fetchable_bundle():
    spec = smoke_spec(miners=20, rounds=9, kills=3)
    res = fs.simulate(spec)
    assert len(res.kills) == 3
    assert res.pm_fetched == 3
    card = fs.assemble_scorecard(res)
    assert card["gates"]["postmortem"]["ok"]
    assert card["postmortem"]["coverage"] == 1.0
    # killed miners become quarantine ground truth (stale rule)
    killed = {k["hotkey"] for k in res.kills if k["role"] == "miner"}
    assert killed <= set(res.truth_bad)
    assert killed <= set(res.quarantined_ever)


def test_primary_kill_forces_standby_takeover_with_monotone_epoch():
    spec = smoke_spec(miners=16, rounds=9, kill_primary_round=4)
    res = fs.simulate(spec)
    assert res.takeovers == 1
    assert res.final_lease_epoch == 2     # epoch 1 primary, 2 standby
    # the fleet kept merging: at most the failover window was lost
    assert res.rounds_completed >= spec.rounds - 3
    card = fs.assemble_scorecard(res)
    assert card["gates"]["failover"]["ok"]
    assert card["gates"]["rounds"]["ok"]
    # the dead primary's own crash bundle is fetchable too
    assert any(k["role"] == "averager" for k in res.kills)
    assert card["postmortem"]["coverage"] == 1.0


# ---------------------------------------------------------------------------
# Hierarchy
# ---------------------------------------------------------------------------

def test_hier_merge_matches_flat_within_tolerance():
    spec = smoke_spec(miners=16, rounds=6, sub_averagers=4,
                      publish_error_rate=0.0, fetch_error_rate=0.0,
                      chaos=False)
    flat = dataclasses.replace(spec, sub_averagers=0)
    r_hier = fs.simulate(spec)
    r_flat = fs.simulate(flat)
    assert fs._rel_diff(r_hier.final_base, r_flat.final_base) < 1e-5
    # the root staged __agg__ ids, not per-miner artifacts
    card = fs.assemble_scorecard(r_hier)
    assert card["rounds"]["completed"] == spec.rounds


# ---------------------------------------------------------------------------
# Gate evaluation + baseline regression
# ---------------------------------------------------------------------------

def test_gates_fail_on_regressed_numbers():
    spec = smoke_spec(rounds=4, stale_miners=2)
    card = fs.assemble_scorecard(fs.simulate(spec))
    bad = json.loads(json.dumps(card))
    bad["quarantine"]["precision"] = 0.5
    gates = fs.evaluate_gates(bad)
    assert not gates["quarantine"]["ok"]
    bad2 = json.loads(json.dumps(card))
    bad2["rounds"]["completed"] = 0
    assert not fs.evaluate_gates(bad2)["rounds"]["ok"]


# ---------------------------------------------------------------------------
# Lineage coverage + merged-quality gates (engine/lineage.py)
# ---------------------------------------------------------------------------

def test_lineage_coverage_and_quality_gates_green_on_healthy_fleet():
    spec = smoke_spec(rounds=4, stale_miners=1, poison_miners=1)
    card = fs.assemble_scorecard(fs.simulate(spec))
    lin = card["lineage"]
    # every landed revision carries a fetchable, integrity-verified
    # record — coverage is 100%, not best-effort
    assert lin["published"] >= spec.rounds - 1
    assert lin["coverage"] == 1.0 and lin["tampered"] == 0
    assert lin["drift_breaches"] == 0
    # the toy problem converges, so merged quality strictly improves
    assert lin["quality_last"] < lin["quality_first"]
    assert card["gates"]["lineage"]["ok"]
    assert card["gates"]["quality"]["ok"]


def test_quality_and_lineage_gates_fail_on_regression():
    spec = smoke_spec(rounds=3)
    card = fs.assemble_scorecard(fs.simulate(spec))
    # a quality drift (or a run that ends WORSE than it started) fails
    # the scorecard, not just a human eyeball
    bad = json.loads(json.dumps(card))
    bad["lineage"]["drift_breaches"] = 1
    assert not fs.evaluate_gates(bad)["quality"]["ok"]
    bad2 = json.loads(json.dumps(card))
    bad2["lineage"]["quality_last"] = \
        bad2["lineage"]["quality_first"] + 1.0
    assert not fs.evaluate_gates(bad2)["quality"]["ok"]
    # missing or tampered records fail the coverage gate
    bad3 = json.loads(json.dumps(card))
    bad3["lineage"]["coverage"] = 0.5
    assert not fs.evaluate_gates(bad3)["lineage"]["ok"]
    bad4 = json.loads(json.dumps(card))
    bad4["lineage"]["tampered"] = 1
    assert not fs.evaluate_gates(bad4)["lineage"]["ok"]


def test_cli_finalize_ts_makes_reruns_byte_identical(tmp_path):
    """PR-11's caveat closed: with --finalize-ts injected, two same-seed
    CLI runs produce byte-identical scorecard FILES (previously equal
    only modulo the wall-clock ``t``)."""
    import importlib.util
    import os as _os

    spec_path = importlib.util.spec_from_file_location(
        "fleetsim_cli", _os.path.join(
            _os.path.dirname(_os.path.dirname(_os.path.abspath(__file__))),
            "scripts", "fleetsim.py"))
    cli = importlib.util.module_from_spec(spec_path)
    spec_path.loader.exec_module(cli)
    spec_json = json.dumps({"miners": 6, "validators": 1, "servers": 1,
                            "rounds": 2, "seed": 5,
                            "validator_cohort": 4})
    outs = []
    for name in ("a.json", "b.json"):
        out = str(tmp_path / name)
        rc = cli.main(["--spec", spec_json, "--no-serve", "--no-control",
                       "--out", out, "--finalize-ts", "123.0"])
        assert rc == 0
        outs.append(open(out, "rb").read())
    assert outs[0] == outs[1]
    card = json.loads(outs[0])
    assert card["t"] == 123.0
    assert card["lineage"]["coverage"] == 1.0


def test_baseline_regression_gate():
    spec = smoke_spec(rounds=4, stale_miners=2)
    card = fs.assemble_scorecard(fs.simulate(spec),
                                 fs.simulate(spec.control()))
    # identical baseline: no regression
    ok = fs.evaluate_gates(card, baseline=json.loads(json.dumps(card)))
    assert ok["baseline"]["ok"], ok["baseline"]
    # a much-better baseline makes the current numbers a regression
    better = json.loads(json.dumps(card))
    better["quarantine"]["precision"] = 1.0
    better["quarantine"]["recall"] = 1.0
    better["wire"]["bytes_per_round"] = \
        card["wire"]["bytes_per_round"] / 10.0
    gates = fs.evaluate_gates(card, baseline=better)
    assert not gates["baseline"]["ok"]
    assert any("bytes_per_round" in p
               for p in gates["baseline"]["problems"])


# ---------------------------------------------------------------------------
# Open-loop serving harness
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def serve_engine():
    import jax

    from distributedtraining_tpu.engine.serve import GenerationEngine
    from distributedtraining_tpu.models import gpt2

    model, cfg = gpt2.make_model(gpt2.GPT2Config(
        vocab_size=128, n_positions=64, n_embd=32, n_head=2, n_layer=2))
    params = model.init_params(jax.random.PRNGKey(0))
    eng = GenerationEngine(model, params, max_slots=4, page_size=8)
    yield eng
    eng.close()


def test_open_loop_arrivals_are_poisson_and_heavy_tailed():
    spec = loadgen.OpenLoopSpec(rate_rps=50.0, duration_s=20.0, seed=3)
    arr = loadgen.sample_arrivals(spec)
    times = [t for t, _ in arr]
    assert times == sorted(times)
    assert times[-1] < spec.duration_s
    # rate is approximately honored over a long window
    assert 0.6 * 50 * 20 < len(arr) < 1.4 * 50 * 20
    lens = [len(p) for _, p in arr]
    assert min(lens) >= spec.min_prompt_tokens
    assert max(lens) <= spec.max_prompt_tokens
    # heavy tail: the max dwarfs the median
    assert max(lens) >= 2 * sorted(lens)[len(lens) // 2]
    # seeded: same spec, same schedule
    assert loadgen.sample_arrivals(spec) == arr


def test_open_loop_exposes_queueing_collapse(serve_engine):
    low = loadgen.run_open_loop(serve_engine, loadgen.OpenLoopSpec(
        rate_rps=6.0, duration_s=2.0, seed=5, max_new_tokens=8))
    high = loadgen.run_open_loop(serve_engine, loadgen.OpenLoopSpec(
        rate_rps=120.0, duration_s=2.0, seed=5, max_new_tokens=8))
    assert low["offered"] > 0 and low["unfinished"] == 0
    # open-loop arrivals keep coming past capacity: p99 ttft blows up
    assert high["ttft_ms"]["p99"] > 5 * low["ttft_ms"]["p99"]
    # virtual-time accounting: deterministic on rerun, even on the warm
    # engine (the scheduler's decisions, not the host's speed)
    again = loadgen.run_open_loop(serve_engine, loadgen.OpenLoopSpec(
        rate_rps=6.0, duration_s=2.0, seed=5, max_new_tokens=8))
    assert json.dumps(again, sort_keys=True) == \
        json.dumps(low, sort_keys=True)


def test_serving_gate_reads_load_points(serve_engine):
    pts = [loadgen.run_open_loop(serve_engine, loadgen.OpenLoopSpec(
        rate_rps=r, duration_s=1.5, seed=9, max_new_tokens=8))
        for r in (5.0, 15.0, 45.0)]
    spec = smoke_spec(rounds=3)
    card = fs.assemble_scorecard(fs.simulate(spec), load_points=pts)
    g = card["gates"]["serving"]
    assert g["load_points"] == 3
    assert g["ok"], g
    # losing a point fails the coverage requirement
    card2 = fs.assemble_scorecard(fs.simulate(spec),
                                  load_points=pts[:2])
    assert not card2["gates"]["serving"]["ok"]


def test_disaggregated_topology_phases_and_gate():
    """FleetSpec.disaggregated splits the server cohort into
    prefill/decode worker classes: the scorecard's serve_phase section
    proves both classes lived (phase heartbeats) AND that KV actually
    moved (exports on prefill, adoptions on decode); a one-class fleet
    fails the gate."""
    spec = smoke_spec(rounds=3, disaggregated=True)
    card = fs.assemble_scorecard(fs.simulate(spec))
    sp = card["serve_phase"]
    assert sp["phases"] == {"prefill": 1, "decode": 1}
    assert sp["kv_exported"] > 0 and sp["kv_adopted"] > 0
    assert card["gates"]["serve_phase"]["ok"]
    bad = json.loads(json.dumps(card))
    bad["serve_phase"]["phases"] = {"prefill": 2}
    assert not fs.evaluate_gates(bad)["serve_phase"]["ok"]
    bad2 = json.loads(json.dumps(card))
    bad2["serve_phase"]["kv_adopted"] = 0
    assert not fs.evaluate_gates(bad2)["serve_phase"]["ok"]
    # the knob round-trips (spec JSON is the fleet's config artifact)
    rt = fs.FleetSpec.from_json(json.dumps(dataclasses.asdict(spec)))
    assert rt == spec
    # a non-disaggregated card has no serve_phase section or gate
    plain = fs.assemble_scorecard(fs.simulate(smoke_spec(rounds=3)))
    assert "serve_phase" not in plain
    assert "serve_phase" not in plain["gates"]


def test_disagg_load_points_and_knee_gate(serve_engine):
    """The two-lane load phase: a unified worker paying the prefill
    head-of-line cost vs a 1-prefill + 1-decode pair at the same
    offered rates. The disaggregated lane must win tpot p95 at the
    knee (highest common rate) by >= disagg_tpot_gain_min, and the
    serving gate records the comparison."""
    from distributedtraining_tpu.engine import kv_transfer as kvt
    from distributedtraining_tpu.engine.serve import GenerationEngine
    from distributedtraining_tpu.models import gpt2
    from distributedtraining_tpu.transport import InMemoryTransport
    import jax

    uni_pts = [loadgen.run_open_loop(serve_engine, loadgen.OpenLoopSpec(
        rate_rps=r, duration_s=1.5, seed=9, max_new_tokens=8),
        prefill_busy_steps=4) for r in (8.0, 24.0)]
    model, _ = gpt2.make_model(gpt2.GPT2Config(
        vocab_size=128, n_positions=64, n_embd=32, n_head=2, n_layer=2))
    params = model.init_params(jax.random.PRNGKey(0))
    tr = InMemoryTransport()
    pe = GenerationEngine(model, params, revision="r0", max_slots=4,
                          page_size=8, phase="prefill",
                          kv_exporter=kvt.KVExporter(tr))
    de = GenerationEngine(model, params, revision="r0", max_slots=4,
                          page_size=8, phase="decode",
                          kv_adopter=kvt.KVAdopter(tr))
    try:
        dis_pts = [loadgen.run_open_loop_disagg(
            [pe], [de], loadgen.OpenLoopSpec(
                rate_rps=r, duration_s=1.5, seed=9, max_new_tokens=8),
            prefill_busy_steps=4) for r in (8.0, 24.0)]
    finally:
        pe.close()
        de.close()
    d = dis_pts[-1]
    assert d["disaggregated"] and d["handoffs"] > 0
    assert d["kv_adopted"] == d["handoffs"] and d["kv_reprefills"] == 0
    # the head-of-line cost the split removes, visible in the curve
    assert d["tpot_ms"]["p95"] < uni_pts[-1]["tpot_ms"]["p95"]
    spec = smoke_spec(rounds=3, disaggregated=True)
    card = fs.assemble_scorecard(fs.simulate(spec),
                                 load_points=uni_pts + dis_pts)
    g = card["gates"]["serving"]
    assert g["ok"], g
    assert g["disaggregated"] and g["handoffs_total"] > 0
    knee = g["disagg_knee"]
    assert knee["rate_rps"] == 24.0
    assert knee["gain"] >= knee["gain_min"]
    # a regressed disaggregated lane fails the knee gate
    bad = json.loads(json.dumps(card))
    for p in bad["serving"]["load_points"]:
        if p.get("disaggregated"):
            p["tpot_ms"]["p95"] = uni_pts[-1]["tpot_ms"]["p95"]
    assert not fs.evaluate_gates(bad)["serving"]["ok"]


# ---------------------------------------------------------------------------
# The acceptance run (slow lane)
# ---------------------------------------------------------------------------

@pytest.mark.slow
def test_thousand_actor_acceptance_run(serve_engine):
    """ISSUE 11 acceptance: a 1000-actor, chaos-enabled run completes on
    CPU in bounded wall time; the scorecard holds parity vs the
    churn-free control, quarantine precision/recall >= 0.9 against the
    injected truth, a postmortem bundle for 100% of injected kills, a
    3-point open-loop latency curve — and a same-seed rerun reproduces
    the scorecard byte-identically."""
    spec = fs.FleetSpec(
        miners=960, validators=4, servers=8, sub_averagers=16,
        rounds=12, seed=0, stale_miners=24, divergent_miners=24,
        pushfail_miners=24, poison_miners=24, kills=12,
        kill_primary_round=5, partitions_per_round=4)
    assert spec.total_actors == 990
    pts = [loadgen.run_open_loop(serve_engine, loadgen.OpenLoopSpec(
        rate_rps=r, duration_s=4.0, seed=spec.seed, max_new_tokens=8))
        for r in (8.0, 24.0, 72.0)]
    card = fs.assemble_scorecard(fs.simulate(spec),
                                 fs.simulate(spec.control()), pts)
    assert card["ok"], {k: v for k, v in card["gates"].items()
                        if not v["ok"]}
    assert card["quarantine"]["precision"] >= 0.9
    assert card["quarantine"]["recall"] >= 0.9
    assert card["postmortem"]["coverage"] == 1.0
    assert card["parity"]["rel_diff"] <= 0.1
    assert len(card["serving"]["load_points"]) == 3
    # byte-identical rerun (load points are deterministic too, pinned
    # above at tier-1 scale — reuse them rather than re-decoding)
    card2 = fs.assemble_scorecard(fs.simulate(spec),
                                  fs.simulate(spec.control()), pts)
    assert json.dumps(card, sort_keys=True) == \
        json.dumps(card2, sort_keys=True)


@pytest.mark.slow
def test_thousand_actor_disaggregated_acceptance_run(serve_engine):
    """ISSUE 19 acceptance: the same ~1000-actor chaos fleet with the
    server cohort split into prefill/decode worker classes. Per-phase
    SLO gates stay green, both classes prove themselves through phase
    heartbeats + KV counters, and the disaggregated serve lane beats
    the unified baseline on tpot p95 at the load knee by >=
    disagg_tpot_gain_min. Deterministic like every other scorecard."""
    from distributedtraining_tpu.engine import kv_transfer as kvt
    from distributedtraining_tpu.engine.serve import GenerationEngine
    from distributedtraining_tpu.models import gpt2
    from distributedtraining_tpu.transport import InMemoryTransport
    import jax

    spec = fs.FleetSpec(
        miners=960, validators=4, servers=8, sub_averagers=16,
        rounds=12, seed=0, stale_miners=24, divergent_miners=24,
        pushfail_miners=24, poison_miners=24, kills=12,
        kill_primary_round=5, partitions_per_round=4,
        disaggregated=True)
    rates = (8.0, 24.0, 72.0)
    pts = [loadgen.run_open_loop(serve_engine, loadgen.OpenLoopSpec(
        rate_rps=r, duration_s=4.0, seed=spec.seed, max_new_tokens=8),
        prefill_busy_steps=4) for r in rates]
    model, _ = gpt2.make_model(gpt2.GPT2Config(
        vocab_size=128, n_positions=64, n_embd=32, n_head=2, n_layer=2))
    params = model.init_params(jax.random.PRNGKey(0))
    tr = InMemoryTransport()
    pe = GenerationEngine(model, params, revision="r0", max_slots=4,
                          page_size=8, phase="prefill",
                          kv_exporter=kvt.KVExporter(tr))
    de = GenerationEngine(model, params, revision="r0", max_slots=4,
                          page_size=8, phase="decode",
                          kv_adopter=kvt.KVAdopter(tr))
    try:
        pts += [loadgen.run_open_loop_disagg(
            [pe], [de], loadgen.OpenLoopSpec(
                rate_rps=r, duration_s=4.0, seed=spec.seed,
                max_new_tokens=8),
            prefill_busy_steps=4) for r in rates]
    finally:
        pe.close()
        de.close()
    card = fs.assemble_scorecard(fs.simulate(spec),
                                 fs.simulate(spec.control()), pts)
    assert card["ok"], {k: v for k, v in card["gates"].items()
                        if not v["ok"]}
    assert card["serve_phase"]["phases"] == {"prefill": 4, "decode": 4}
    assert card["serve_phase"]["kv_exported"] > 0
    assert card["serve_phase"]["kv_adopted"] > 0
    knee = card["gates"]["serving"]["disagg_knee"]
    assert knee["rate_rps"] == 72.0
    assert knee["gain"] >= knee["gain_min"]
    assert knee["kv_reprefills"] == 0
    card2 = fs.assemble_scorecard(fs.simulate(spec),
                                  fs.simulate(spec.control()), pts)
    assert json.dumps(card, sort_keys=True) == \
        json.dumps(card2, sort_keys=True)


# ---------------------------------------------------------------------------
# SLO burn-rate alerting (engine/health.BurnRateMonitor on the sim clock)
# ---------------------------------------------------------------------------

def test_slo_burn_detects_injected_latency_regression():
    """The injected-latency-regression scenario: servers' synthetic
    request stream slows by the factor from the injected round on, the
    multi-window burn alert must page within the gate's detect window,
    and the control twin (no injection) must stay silent."""
    spec = smoke_spec(rounds=9, latency_regression_round=6)
    assert spec.control().latency_regression_round == 0
    res = fs.simulate(spec)
    ctrl = fs.simulate(spec.control())
    card = fs.assemble_scorecard(res, ctrl)
    sb = card["slo_burn"]
    assert sb["injected_round"] == 6 and sb["alerts"] > 0
    assert sb["first_fire_round"] >= 6
    assert 1 <= sb["detect_rounds"] <= 3
    assert sb["control_alerts"] == 0          # zero false positives
    assert sb["peak_burn"] > 1.0
    # the regression violates the ttft objective; names carry slo+pair
    assert any(n.startswith("ttft.") for n in sb["alert_names"])
    gate = card["gates"]["slo_burn"]
    assert gate["ok"], gate
    assert gate["detect_rounds"] <= gate["detect_rounds_max"]
    # the regression is visible in the servers' heartbeat-side numbers
    # the fleet_report slo_burn column reads
    assert res.burn_peak > ctrl.burn_peak


def test_slo_burn_clean_fleet_stays_silent():
    """No injection: zero alerts, and the gate is vacuous (absent) —
    a page on a healthy fleet would be a gate failure instead."""
    spec = smoke_spec(rounds=6)
    card = fs.assemble_scorecard(fs.simulate(spec))
    sb = card["slo_burn"]
    assert sb["injected_round"] == 0 and sb["alerts"] == 0
    assert sb["detect_rounds"] is None
    assert "slo_burn" not in card["gates"]
    # a false positive IS a failing gate: forge one alert on the
    # uninjected card
    bad = json.loads(json.dumps(card))
    bad["slo_burn"]["alerts"] = 2
    bad["slo_burn"]["alert_names"] = ["ttft.fast"]
    gates = fs.evaluate_gates(bad)
    assert not gates["slo_burn"]["ok"]
    assert gates["slo_burn"]["false_positives"] == 2


def test_slo_burn_scenario_is_seed_deterministic():
    """The burn section rides the same determinism contract as the rest
    of the scorecard: same seed, byte-identical (modulo timestamp)."""
    spec = smoke_spec(rounds=9, latency_regression_round=6, seed=5)
    a = fs.finalize_scorecard(fs.assemble_scorecard(fs.simulate(spec)),
                              now=1.0)
    b = fs.finalize_scorecard(fs.assemble_scorecard(fs.simulate(spec)),
                              now=2.0)
    assert a["slo_burn"] == b["slo_burn"]
    assert a["scorecard_id"] == b["scorecard_id"]


def test_slo_burn_baseline_gate_catches_detection_regression():
    """--baseline: time-to-page may not regress past the prior
    scorecard's detect_rounds by more than one round."""
    spec = smoke_spec(rounds=9, latency_regression_round=6)
    card = fs.assemble_scorecard(fs.simulate(spec),
                                 fs.simulate(spec.control()))
    base = json.loads(json.dumps(card))
    ok = fs.evaluate_gates(card, baseline=base)
    assert ok["baseline"]["ok"], ok["baseline"]
    # a baseline that paged much faster than we now do fails the gate
    faster = json.loads(json.dumps(card))
    faster["slo_burn"]["detect_rounds"] = \
        card["slo_burn"]["detect_rounds"] - 2
    gates = fs.evaluate_gates(card, baseline=faster)
    assert not gates["baseline"]["ok"]
    assert any("slo_burn" in p for p in gates["baseline"]["problems"])
