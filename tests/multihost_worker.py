"""Worker script for the 2-process jax.distributed test (run via subprocess
by tests/test_multihost_spmd.py — not a pytest file itself).

Each of two processes owns 2 virtual CPU devices; together they form one
4-device dp mesh and run one full sharded train step as a single SPMD
program — the miniature of BASELINE config 5 (multi-host v5e-64).
Prints "RESULT <pid> <loss> <is_coord>" on success.
"""

import os
import sys

os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"

import jax

jax.config.update("jax_platforms", "cpu")

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402


def main() -> int:
    pid = int(sys.argv[1])
    addr = sys.argv[2]

    from distributedtraining_tpu.parallel import multihost

    multihost.initialize(coordinator_address=addr, num_processes=2,
                         process_id=pid)
    assert jax.process_count() == 2, jax.process_count()
    assert len(jax.devices()) == 4, jax.devices()
    assert len(jax.local_devices()) == 2

    mesh = multihost.pod_mesh()  # dp=4 over both processes
    assert mesh.shape["dp"] == 4

    # hybrid (DCN-aware) layout: with dcn_dp=2 the dp axis must cross the
    # slow network only at its outermost split — each outer-dp group is one
    # granule (process here; TPU slice on multislice hardware)
    hybrid = multihost.pod_mesh(dcn_dp=2)
    assert hybrid.shape["dp"] == 4
    dev_grid = hybrid.devices  # [dp=4, fsdp=1, sp=1, tp=1]
    outer_groups = dev_grid.reshape(2, 2, 1, 1, 1)
    for g in range(2):
        procs = {d.process_index for d in outer_groups[g].flat}
        assert len(procs) == 1, (g, procs)  # inner dp stays on one granule

    from distributedtraining_tpu.engine import TrainEngine
    from distributedtraining_tpu.models import gpt2

    model, cfg = gpt2.make_model("tiny")
    seq = 16
    engine = TrainEngine(model, mesh=mesh, seq_len=seq)
    state = engine.init_state(jax.random.PRNGKey(0))

    # distinct per-process data (as multihost.shard_documents would feed);
    # global batch = 4, local shard = 2 rows per process
    rng = np.random.default_rng(100 + pid)
    local = {"input_ids": rng.integers(0, cfg.vocab_size, (2, seq),
                                       dtype=np.int32)}
    for _ in range(2):
        state, m = engine.train_step(state, engine.place_batch(local))
    loss = float(m["loss"])
    assert np.isfinite(loss), loss
    assert int(state.step) == 2

    # coordinator gating: only process 0 writes
    sent = []

    class FakeTransport:
        def publish_delta(self, mid, d):
            sent.append(mid)
            return "rev"

        def publish_base(self, p):
            sent.append("base")
            return "rev"

        def gc(self):
            sent.append("gc")

    class FakeChain:
        def set_weights(self, w):
            sent.append("weights")

    t, c = multihost.gate_io(FakeTransport(), FakeChain())
    t.publish_delta("m0", None)
    t.publish_base(None)
    t.gc()
    c.set_weights({})
    expected = 4 if multihost.is_coordinator() else 0
    assert len(sent) == expected, (pid, sent)

    # -- validator on the pod: coordinator-only transport reads ------------
    # the worker's transport is EMPTY — if the validator read it locally
    # instead of broadcasting the coordinator's fetch, bootstrap would
    # self-init a different base and scores would diverge (or hang)
    from jax.experimental import multihost_utils as mhu

    from distributedtraining_tpu.engine import Validator
    from distributedtraining_tpu.transport import InMemoryTransport

    transport = InMemoryTransport()
    if multihost.is_coordinator():
        base = model.init_params(jax.random.PRNGKey(7))
        transport.publish_base(base)
        delta = jax.tree_util.tree_map(
            lambda x: np.full(x.shape, 1e-3, np.float32), base)
        transport.publish_delta("m1", delta)

    veng = TrainEngine(model, mesh=mesh, seq_len=seq)
    eval_batch = {"input_ids": np.arange(2 * seq, dtype=np.int32)
                  .reshape(2, seq) % cfg.vocab_size}
    v = Validator(veng, transport, FakeChain(),
                  eval_batches=lambda: iter([eval_batch]))
    v.bootstrap()
    assert v._base_revision is not None, \
        f"pid {pid}: validator must see the coordinator's base"
    score = v.score_miner("m1")
    assert score.reason == "ok", (pid, score)
    # the coordinator's numbers are everyone's numbers
    ref = np.asarray(mhu.broadcast_one_to_all(
        np.asarray([score.score, v.base_loss], np.float64)))
    np.testing.assert_allclose([score.score, v.base_loss], ref, rtol=1e-6)
    missing = v.score_miner("m_absent")
    assert missing.reason == "no_delta", (pid, missing)

    # -- averager on the pod: gather (coordinator reads, bytes broadcast),
    # -- psum merge over the cross-process mesh, coordinator-gated publish
    from distributedtraining_tpu.engine import AveragerLoop, WeightedAverage

    class OneMinerChain:
        my_hotkey = "avg"

        def sync(self):
            from distributedtraining_tpu.chain.base import Metagraph
            return Metagraph(hotkeys=["avg", "m1", "m_absent"],
                             uids=[0, 1, 2], stakes=[10000.0, 10.0, 10.0],
                             block=1)

        def consensus_scores(self):
            return {"m1": 1.0}

    gated_t, gated_c = multihost.gate_io(transport, OneMinerChain())
    avg = AveragerLoop(veng, gated_t, gated_c, WeightedAverage(),
                       val_batches=lambda: iter([eval_batch]))
    assert avg.run_round(), f"pid {pid}: averager merged nothing"
    assert avg.report.last_accepted == 1, (pid, avg.report)
    ref = np.asarray(mhu.broadcast_one_to_all(
        np.asarray([avg.report.last_loss], np.float64)))
    np.testing.assert_allclose([avg.report.last_loss], ref, rtol=1e-6)

    print(f"RESULT {pid} {loss:.6f} {int(multihost.is_coordinator())}",
          flush=True)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
