"""supervise.sh bounded-restart semantics (pm2 parity, run_miner.sh:215-224).

Exercises the real bash supervisor with second-scale cadences and a
SUPERVISE_CMD stand-in for the role process — the crash-loop give-up path
and the min-uptime crash-counter reset are exactly the semantics a round-1
advisor finding showed can silently break.
"""

import os
import subprocess
import time

SCRIPT = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "scripts", "supervise.sh")


def _env(**kw):
    env = dict(os.environ, NO_AUTO_UPDATE="1", POLL_S="1",
               RESTART_DELAY_S="0", UPDATE_CHECK_S="9999")
    env.update({k: str(v) for k, v in kw.items()})
    return env


def test_crash_loop_gives_up_after_max_restarts():
    """A role dying instantly (< MIN_UPTIME) trips the bounded-restart
    counter: MAX_RESTARTS=2 means 3 fast crashes, then exit 1."""
    proc = subprocess.run(
        ["bash", SCRIPT, "miner"],
        env=_env(SUPERVISE_CMD="false", MAX_RESTARTS="2", MIN_UPTIME_S="300"),
        capture_output=True, text=True, timeout=60)
    assert proc.returncode == 1
    assert proc.stdout.count("starting miner") == 3
    assert "giving up" in proc.stdout


def test_long_uptime_resets_crash_counter():
    """pm2 min_uptime semantics: a child that outlives MIN_UPTIME_S resets
    the counter, so occasional slow crashes never accumulate into a
    give-up."""
    import tempfile

    with tempfile.NamedTemporaryFile("r") as logf:
        proc = subprocess.Popen(
            ["bash", SCRIPT, "miner"],
            env=_env(SUPERVISE_CMD="sleep 2", MAX_RESTARTS="1",
                     MIN_UPTIME_S="1"),
            stdout=open(logf.name, "w"), stderr=subprocess.STDOUT, text=True)
        # each child lives 2s (>= MIN_UPTIME 1s): crashes reset every cycle;
        # poll with a deadline (not a fixed sleep) so CI load can't flake it
        try:
            deadline = time.time() + 45
            while time.time() < deadline:
                out = logf.read()
                logf.seek(0)
                if out.count("starting miner") >= 3:
                    break
                assert proc.poll() is None, out
                time.sleep(0.5)
            out = logf.read()
        finally:
            proc.terminate()
            proc.wait(timeout=10)
    assert out.count("starting miner") >= 3
    assert "giving up" not in out


def test_crash_detected_promptly_not_after_update_poll():
    """Advisor regression: the watchdog must notice a dead child on the
    POLL_S cadence, not after the (here 9999 s) update-poll sleep."""
    t0 = time.time()
    proc = subprocess.run(
        ["bash", SCRIPT, "miner"],
        env=_env(SUPERVISE_CMD="false", MAX_RESTARTS="0", MIN_UPTIME_S="300"),
        capture_output=True, text=True, timeout=60)
    assert proc.returncode == 1
    assert time.time() - t0 < 30, "crash detection waited on the update poll"


def test_chaos_killed_role_relaunches_with_args_intact(tmp_path):
    """A role SIGKILLed out from under the supervisor (the chaos-kill
    scenario, transport/chaos.py's process-level twin) must be relaunched
    promptly WITH ITS ORIGINAL ARGS — a supervisor that drops or reorders
    role flags on restart silently changes the node's config mid-soak."""
    import subprocess as sp
    import tempfile

    marker = "31259"
    helper = tmp_path / "role.sh"
    # exec makes the helper BECOME the sleep, so the kill hits the role
    # process itself (and the supervisor's TERM trap cleans it up at exit)
    helper.write_text(f'#!/bin/bash\necho "ARGS:$@"\nexec sleep {marker}\n')
    helper.chmod(0o755)
    args = ["--hotkey", "hk0", "--seq-len", "32"]
    with tempfile.NamedTemporaryFile("r") as logf:
        proc = sp.Popen(
            ["bash", SCRIPT, "miner", *args],
            env=_env(SUPERVISE_CMD=str(helper), MAX_RESTARTS="5",
                     MIN_UPTIME_S="1"),
            stdout=open(logf.name, "w"), stderr=sp.STDOUT, text=True)
        try:
            deadline = time.time() + 45
            killed = False
            out = ""
            while time.time() < deadline:
                out = logf.read()
                logf.seek(0)
                if not killed:
                    r = sp.run(["pgrep", "-f", f"sleep {marker}"],
                               capture_output=True, text=True)
                    if r.stdout.strip():
                        sp.run(["pkill", "-9", "-f", f"sleep {marker}"])
                        killed = True
                elif out.count("ARGS:") >= 2:
                    break
                assert proc.poll() is None, out
                time.sleep(0.3)
        finally:
            proc.terminate()
            proc.wait(timeout=10)
    lines = [ln for ln in out.splitlines() if ln.startswith("ARGS:")]
    assert len(lines) >= 2, out
    assert lines[0] == "ARGS:--hotkey hk0 --seq-len 32"
    assert len(set(lines)) == 1, lines        # every relaunch: args intact
    assert "giving up" not in out


def test_term_kills_role_child_too():
    """Supervisor TERM must take the role down with it — an orphaned child
    would hold the TPU/hotkey against the next service start."""
    marker = "31257"
    proc = subprocess.Popen(
        ["bash", SCRIPT, "miner"],
        env=_env(SUPERVISE_CMD=f"sleep {marker}", MAX_RESTARTS="5",
                 MIN_UPTIME_S="1"),
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True)
    try:
        deadline = time.time() + 20
        while time.time() < deadline:
            r = subprocess.run(["pgrep", "-f", f"sleep {marker}"],
                               capture_output=True, text=True)
            if r.stdout.strip():
                break
            time.sleep(0.2)
        assert r.stdout.strip(), "role child never started"
    finally:
        proc.terminate()
        proc.wait(timeout=10)
    time.sleep(1.0)
    r = subprocess.run(["pgrep", "-f", f"sleep {marker}"],
                       capture_output=True, text=True)
    assert not r.stdout.strip(), "role child orphaned after supervisor TERM"
