"""Train/validate/average engines + the full federated round offline.

The end-to-end test reproduces the reference's de-facto system test (the
Local* twins running a miner -> validator -> averager round on one box,
SURVEY.md §4.1) with real assertions.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributedtraining_tpu import delta
from distributedtraining_tpu.chain import LocalChain
from distributedtraining_tpu.data import ByteTokenizer, batch_iterator, text_corpus
from distributedtraining_tpu.engine import (
    AveragerLoop, FakeClock, GeneticMerge, MinerLoop, ParameterizedMerge,
    TrainEngine, Validator, WeightedAverage)
from distributedtraining_tpu.models import gpt2
from distributedtraining_tpu.transport import InMemoryTransport
from distributedtraining_tpu.utils.metrics import InMemorySink

SEQ = 32
BATCH = 4


@pytest.fixture(scope="module")
def setup():
    model, cfg = gpt2.make_model("tiny")
    engine = TrainEngine(model, seq_len=SEQ)
    tok = ByteTokenizer()
    train_docs = text_corpus(split="train", n_docs=48, source="synthetic")
    val_docs = text_corpus(split="val", n_docs=12, source="synthetic")

    def train_batches(repeat=True):
        return batch_iterator(train_docs, tok, batch_size=BATCH, seq_len=SEQ,
                              repeat=repeat, max_vocab=cfg.vocab_size)

    def val_batches():
        return list(batch_iterator(val_docs, tok, batch_size=BATCH,
                                   seq_len=SEQ, max_vocab=cfg.vocab_size))[:3]

    return model, cfg, engine, train_batches, val_batches


def test_train_engine_loss_decreases(setup):
    model, cfg, engine, train_batches, _ = setup
    state = engine.init_state(jax.random.PRNGKey(0))
    losses = []
    for i, batch in enumerate(train_batches()):
        if i >= 30:
            break
        state, m = engine.train_step(state, batch)
        losses.append(float(m["loss"]))
    assert np.mean(losses[-5:]) < np.mean(losses[:5])
    assert int(state.step) == 30


def test_bf16_mu_optimizer(setup):
    """--mu-dtype bfloat16 stores Adam's first moment in bf16 (half the
    HBM of the 7B/8B configs' largest optimizer buffer) and trains."""
    import optax

    from distributedtraining_tpu.engine.train import default_optimizer

    model, cfg, _, train_batches, _ = setup
    engine = TrainEngine(
        model, optimizer=default_optimizer(mu_dtype="bfloat16"), seq_len=SEQ)
    state = engine.init_state(jax.random.PRNGKey(0))
    adam = [s for s in jax.tree_util.tree_leaves(
        state.opt_state, is_leaf=lambda x: isinstance(x, optax.ScaleByAdamState))
        if isinstance(s, optax.ScaleByAdamState)]
    assert adam, "no ScaleByAdamState found in opt_state"
    mu_dtypes = {l.dtype for l in jax.tree_util.tree_leaves(adam[0].mu)}
    nu_dtypes = {l.dtype for l in jax.tree_util.tree_leaves(adam[0].nu)}
    assert mu_dtypes == {jnp.dtype(jnp.bfloat16)}
    assert nu_dtypes == {jnp.dtype(jnp.float32)}  # nu stays full precision
    batch = next(train_batches())
    state, m = engine.train_step(state, batch)
    assert np.isfinite(float(m["loss"]))


def test_grad_accumulation_matches_full_batch(setup):
    """accum_steps=N: one token-weighted update over N microbatches must
    reproduce the full-batch step (identical params up to f32 summation
    order)."""
    import dataclasses

    _, cfg, _, train_batches, _ = setup
    batch = next(train_batches())
    # f32 compute isolates the accumulation math from bf16 rounding (whose
    # microbatch-shape dependence Adam's g/sqrt(v) normalization amplifies)
    model, _ = gpt2.make_model(dataclasses.replace(cfg, dtype="float32"))

    e1 = TrainEngine(model, seq_len=SEQ)
    e4 = TrainEngine(model, seq_len=SEQ, accum_steps=4)
    s1 = e1.init_state(jax.random.PRNGKey(0))
    s4 = e4.init_state(jax.random.PRNGKey(0))
    for _ in range(3):
        s1, m1 = e1.train_step(s1, batch)
        s4, m4 = e4.train_step(s4, batch)
    np.testing.assert_allclose(float(m1["loss"]), float(m4["loss"]),
                               rtol=1e-4)
    assert float(m1["tokens"]) == float(m4["tokens"])
    for a, b in zip(jax.tree_util.tree_leaves(s1.params),
                    jax.tree_util.tree_leaves(s4.params)):
        np.testing.assert_allclose(np.asarray(a, np.float32),
                                   np.asarray(b, np.float32),
                                   rtol=2e-3, atol=2e-5)


def test_grad_accumulation_on_mesh(setup, devices):
    """accum composes with dp/fsdp sharding (microbatch still divides the
    batch axes); the sharded accumulated step runs and is finite."""
    from distributedtraining_tpu.parallel import MeshConfig, make_mesh

    model, cfg, _, train_batches, _ = setup
    mesh = make_mesh(MeshConfig(dp=2, fsdp=2), devices=devices[:4])
    engine = TrainEngine(model, mesh=mesh, seq_len=SEQ, accum_steps=2)
    state = engine.init_state(jax.random.PRNGKey(0))
    batch = next(train_batches())  # BATCH=4: microbatch 2 over dp*fsdp=4
    # microbatch rows (2) < dp*fsdp (4) would not divide; use a repeat
    batch = {k: np.concatenate([v, v], axis=0) for k, v in batch.items()}
    state, m = engine.train_step(state, engine.place_batch(batch))
    assert np.isfinite(float(m["loss"]))


def test_evaluate_token_weighted(setup):
    model, cfg, engine, _, val_batches = setup
    params = model.init_params(jax.random.PRNGKey(0))
    loss, ppl = engine.evaluate(params, val_batches())
    assert np.isfinite(loss) and ppl == pytest.approx(np.exp(loss), rel=1e-5)


def test_miner_loop_pushes_and_pulls(setup):
    model, cfg, engine, train_batches, _ = setup
    clock = FakeClock()
    transport = InMemoryTransport()
    sink = InMemorySink()
    miner = MinerLoop(engine, transport, "m0", clock=clock,
                      send_interval=5.0, check_update_interval=2.0,
                      metrics=sink, log_every=10)
    miner.bootstrap(jax.random.PRNGKey(0))

    def timed_batches():
        for b in train_batches():
            clock.advance(1.0)  # each step takes 1 fake second
            yield b

    report = miner.run(timed_batches(), max_steps=12)
    assert report.steps == 12
    assert report.pushes >= 2  # 12s of training, push every 5s
    assert transport.delta_revision("m0") is not None
    assert sink.records  # metrics logged

    # publish a new base -> miner should pull and reset
    new_base = model.init_params(jax.random.PRNGKey(7))
    transport.publish_base(new_base)
    report = miner.run(timed_batches(), max_steps=3)
    assert report.base_pulls >= 1
    # base_params actually replaced
    for a, b in zip(jax.tree_util.tree_leaves(miner.base_params),
                    jax.tree_util.tree_leaves(new_base)):
        if not np.allclose(np.asarray(a), np.asarray(b)):
            break
    else:
        pass  # identical is fine — reset happened right before training


def test_validator_scores_good_delta_higher(setup, tmp_path):
    model, cfg, engine, train_batches, val_batches = setup
    transport = InMemoryTransport()
    chain = LocalChain(str(tmp_path), my_hotkey="hotkey_95", epoch_length=0,
                       clock=FakeClock())
    base = model.init_params(jax.random.PRNGKey(0))
    transport.publish_base(base)

    # good miner: actually train from the base
    state = engine.init_state(params=base)
    for i, b in enumerate(train_batches()):
        if i >= 25:
            break
        state, _ = engine.train_step(state, b)
    transport.publish_delta("hotkey_1", delta.compute_delta(state.params, base))
    # bad miner: random noise delta
    noise = jax.tree_util.tree_map(
        lambda x: 0.5 * jax.random.normal(jax.random.PRNGKey(9), x.shape), base)
    transport.publish_delta("hotkey_2", noise)
    # NaN miner: must be screened
    nan_delta = jax.tree_util.tree_map(lambda x: jnp.full_like(x, jnp.nan), base)
    transport.publish_delta("hotkey_3", nan_delta)

    v = Validator(engine, transport, chain, eval_batches=val_batches)
    v.bootstrap(jax.random.PRNGKey(0))
    results = {s.hotkey: s for s in v.validate_and_score()}

    assert results["hotkey_1"].score > 0
    assert results["hotkey_1"].score > results["hotkey_2"].score
    assert results["hotkey_3"].score == 0 and results["hotkey_3"].reason == "nonfinite"
    assert results["hotkey_4"].reason == "no_delta"
    # weights made it on-chain
    w = chain.get_weights()
    assert w.get("hotkey_1", 0) == 65535


@pytest.mark.parametrize("strategy_name", ["weighted", "parameterized", "genetic"])
def test_merge_strategies_improve_or_match_base(setup, tmp_path, strategy_name):
    model, cfg, engine, train_batches, val_batches = setup
    base = model.init_params(jax.random.PRNGKey(0))

    # two trained miners + one noise miner
    deltas = []
    for seed in (1, 2):
        state = engine.init_state(params=base)
        it = train_batches()
        for i, b in enumerate(it):
            if i >= 15:
                break
            state, _ = engine.train_step(state, b)
        deltas.append(delta.compute_delta(state.params, base))
    noise = jax.tree_util.tree_map(
        lambda x: 0.3 * jax.random.normal(jax.random.PRNGKey(3), x.shape), base)
    deltas.append(noise)
    stacked = delta.stack_deltas(deltas)
    ids = ["m1", "m2", "noise"]

    if strategy_name == "weighted":
        strat = WeightedAverage(uniform=True)
    elif strategy_name == "parameterized":
        strat = ParameterizedMerge(model, meta_epochs=3, meta_lr=0.5,
                                   per_tensor=False)
    else:
        strat = GeneticMerge(population=4, generations=2, sigma=0.2)

    merged, weights = strat.merge(engine, base, stacked, ids,
                                  val_batches=val_batches)
    base_loss, _ = engine.evaluate(base, val_batches())
    merged_loss, _ = engine.evaluate(merged, val_batches())
    uniform, _ = WeightedAverage(uniform=True).merge(
        engine, base, stacked, ids, val_batches=val_batches)
    uniform_loss, _ = engine.evaluate(uniform, val_batches())
    if strategy_name == "weighted":
        assert np.isfinite(merged_loss)  # uniform includes the noise miner
    elif strategy_name == "parameterized":
        # gradient meta-learning must downweight noise enough to beat base
        assert merged_loss < base_loss
    else:
        # elite selection seeds with the uniform mixture, so the best-of-run
        # can never be worse than uniform
        assert merged_loss <= uniform_loss + 1e-4


def test_miner_val_guard_reverts_overfit_state(setup):
    """The self-validation guard (round-5 soak-plateau fix): a miner
    memorizing its train shard must (a) track its best held-out loss,
    (b) revert to the best state after `patience` non-improving evals,
    and (c) never push a delta evaluated worse than best+drift. Trained
    on random-token documents with a DISJOINT random val shard, val loss
    degrades quickly after the initial descent — the soak's plateau
    mechanism in miniature."""
    model, cfg, engine, _, _ = setup
    tok = ByteTokenizer()
    rng = np.random.default_rng(0)

    def rand_docs(seed, n):
        r = np.random.default_rng(seed)
        return ["".join(chr(97 + c) for c in r.integers(0, 26, 200))
                for _ in range(n)]

    train_docs = rand_docs(1, 4)   # tiny: memorizable in a few steps
    val_docs = rand_docs(2, 4)     # disjoint: memorization hurts here

    def train_batches():
        return batch_iterator(train_docs, tok, batch_size=BATCH,
                              seq_len=SEQ, repeat=True,
                              max_vocab=cfg.vocab_size)

    def val_batches():
        it = batch_iterator(val_docs, tok, batch_size=BATCH, seq_len=SEQ,
                            max_vocab=cfg.vocab_size)
        import itertools
        return itertools.islice(it, 2)

    clock = FakeClock()
    transport = InMemoryTransport()
    miner = MinerLoop(engine, transport, "m0", clock=clock,
                      send_interval=4.0, check_update_interval=1000.0,
                      log_every=100, val_batches=val_batches,
                      val_guard_interval=2.0, val_guard_patience=2,
                      # margin 0: any non-improving eval strikes — the
                      # deterministic setting for exercising the revert
                      # machinery (the default 0.1 noise band is for
                      # production plateaus)
                      val_guard_margin=0.0)
    miner.bootstrap(jax.random.PRNGKey(0))

    def timed(it):
        for b in it:
            clock.advance(1.0)
            yield b

    report = miner.run(timed(train_batches()), max_steps=120)
    assert report.val_reverts >= 1, report
    # the guard held on to a best state: current candidate's val loss is
    # within one eval window of the best ever seen
    cur, _ = engine.evaluate(miner.state.params, val_batches())
    assert miner._best_val is not None
    assert cur <= miner._best_val + 0.5, (cur, miner._best_val)
    # and the guard resets when a new base arrives
    transport.publish_base(model.init_params(jax.random.PRNGKey(9)))
    clock.advance(2000.0)
    miner._pull_action.poll()
    assert miner._best_val is None and miner._best_state is None


def test_genetic_merge_zero_generations_picks_best_of_population(setup):
    """--genetic-generations 0 degrades to best-of-initial-population
    (round-4 advisor: `elites` used to be unbound and raise NameError)."""
    model, cfg, engine, train_batches, val_batches = setup
    base = model.init_params(jax.random.PRNGKey(0))
    deltas = [jax.tree_util.tree_map(
        lambda x, s=s: 0.01 * s * jnp.ones_like(x), base) for s in (1, 2)]
    stacked = delta.stack_deltas(deltas)
    strat = GeneticMerge(population=3, generations=0, sigma=0.2)
    merged, w = strat.merge(engine, base, stacked, ["a", "b"],
                            val_batches=val_batches)
    assert np.asarray(w).shape == (2,)
    assert np.isfinite(engine.evaluate(merged, val_batches())[0])


def test_parameterized_merge_downweights_noise(setup):
    model, cfg, engine, train_batches, val_batches = setup
    base = model.init_params(jax.random.PRNGKey(0))
    state = engine.init_state(params=base)
    for i, b in enumerate(train_batches()):
        if i >= 15:
            break
        state, _ = engine.train_step(state, b)
    good = delta.compute_delta(state.params, base)
    noise = jax.tree_util.tree_map(
        lambda x: 0.5 * jax.random.normal(jax.random.PRNGKey(3), x.shape), base)
    stacked = delta.stack_deltas([good, noise])
    strat = ParameterizedMerge(model, meta_epochs=4, meta_lr=0.5,
                               per_tensor=False)
    merged, w = strat.merge(engine, base, stacked, ["good", "noise"],
                            val_batches=val_batches)
    probs = jax.nn.softmax(w)
    assert float(probs[0]) > float(probs[1])


def test_full_federated_round(setup, tmp_path):
    """miner -> transport -> validator -> chain -> averager -> new base ->
    miner pulls: the reference's whole outer loop, offline, with loss
    strictly improving at the merge."""
    model, cfg, engine, train_batches, val_batches = setup
    clock = FakeClock()
    transport = InMemoryTransport()
    chain_v = LocalChain(str(tmp_path), my_hotkey="hotkey_95", epoch_length=0,
                         clock=clock)
    chain_a = LocalChain(str(tmp_path), my_hotkey="hotkey_99", epoch_length=0,
                         clock=clock)

    base = model.init_params(jax.random.PRNGKey(0))
    transport.publish_base(base)

    # two miners train and push
    for hotkey, seed in [("hotkey_1", 1), ("hotkey_2", 2)]:
        miner = MinerLoop(engine, transport, hotkey, clock=clock,
                          send_interval=1e9, check_update_interval=1e9)
        miner.bootstrap(jax.random.PRNGKey(seed))
        miner.run(train_batches(), max_steps=15)
        miner.flush()

    # validator scores them onto the chain
    v = Validator(engine, transport, chain_v, eval_batches=val_batches)
    v.bootstrap(jax.random.PRNGKey(0))
    v.validate_and_score()
    assert chain_v.get_weights()

    # averager merges with meta-learned weights and publishes the new base
    avg = AveragerLoop(engine, transport, chain_a,
                       ParameterizedMerge(model, meta_epochs=2, meta_lr=0.3,
                                          per_tensor=False),
                       val_batches=val_batches)
    avg.bootstrap(jax.random.PRNGKey(0))
    base_loss, _ = engine.evaluate(avg.base_params, val_batches())
    assert avg.run_round()
    assert avg.report.last_accepted == 2
    assert avg.report.last_loss < base_loss

    # miners can pull the new base
    rev = transport.base_revision()
    miner = MinerLoop(engine, transport, "hotkey_1", clock=clock,
                      send_interval=1e9, check_update_interval=0.0)
    miner.bootstrap(jax.random.PRNGKey(1))
    assert miner._base_revision == rev


def test_outer_opt_merge_mechanics(setup):
    """Nesterov outer step over the merged delta (OuterOptMerge): velocity
    accumulates across rounds and the update matches the hand formula."""
    import jax.numpy as jnp
    from distributedtraining_tpu.engine import OuterOptMerge

    model, cfg, engine, _, _ = setup
    base = model.init_params(jax.random.PRNGKey(0))
    d1 = jax.tree_util.tree_map(lambda x: jnp.full_like(x, 0.01), base)
    stacked = delta.stack_deltas([d1])

    m, lr = 0.9, 0.5
    s = OuterOptMerge(WeightedAverage(uniform=True), outer_lr=lr, momentum=m)

    out1, _ = s.merge(engine, base, stacked, ["m0"])
    # round 1: v1 = d, update = m*v1 + d = (1+m)*d
    for b, o in zip(jax.tree_util.tree_leaves(base),
                    jax.tree_util.tree_leaves(out1)):
        np.testing.assert_allclose(np.asarray(o - b),
                                   lr * (1 + m) * 0.01, rtol=1e-5)

    # a FAILED round must not advance velocity: re-merging before commit
    # reproduces round 1's output exactly
    out_retry, _ = s.merge(engine, base, stacked, ["m0"])
    for a, b in zip(jax.tree_util.tree_leaves(out1),
                    jax.tree_util.tree_leaves(out_retry)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    s.commit()  # round published
    out2, _ = s.merge(engine, base, stacked, ["m0"])
    # round 2 (same base+delta): v2 = m*v1 + d = (m+1)*d
    # update = m*v2 + d = (m^2 + m + 1)*d
    for b, o in zip(jax.tree_util.tree_leaves(base),
                    jax.tree_util.tree_leaves(out2)):
        np.testing.assert_allclose(np.asarray(o - b),
                                   lr * (m * m + m + 1) * 0.01, rtol=1e-5)


def test_outer_opt_in_averager_loop(setup):
    """OuterOptMerge plugs into AveragerLoop and still lowers loss."""
    from distributedtraining_tpu.engine import OuterOptMerge

    model, cfg, engine, train_batches, val_batches = setup
    transport = InMemoryTransport()
    miner = MinerLoop(engine, transport, "m0", clock=FakeClock(),
                      send_interval=1e9, check_update_interval=1e9)
    miner.bootstrap(jax.random.PRNGKey(0))
    miner.run(train_batches(), max_steps=30)
    miner.flush()

    class _Chain:
        my_hotkey = "avg"

        def sync(self):
            import types
            return types.SimpleNamespace(hotkeys=["m0"])

    loop = AveragerLoop(engine, transport, _Chain(),
                        OuterOptMerge(WeightedAverage(uniform=True),
                                      outer_lr=0.7, momentum=0.9),
                        val_batches=val_batches, clock=FakeClock())
    loop.bootstrap(jax.random.PRNGKey(0))
    base_loss, _ = engine.evaluate(loop.base_params, val_batches())
    assert loop.run_round()
    assert loop.report.last_loss < base_loss


def test_unpermitted_validator_never_emits_weights(setup, tmp_path):
    """A miner-stake hotkey running the validator scores but must not
    set_weights (vpermit gate, btt_connector.py:358-385)."""
    model, cfg, engine, train_batches, val_batches = setup
    transport = InMemoryTransport()
    chain = LocalChain(str(tmp_path), my_hotkey="hotkey_5", epoch_length=0,
                       clock=FakeClock())
    transport.publish_base(model.init_params(jax.random.PRNGKey(0)))
    v = Validator(engine, transport, chain, eval_batches=val_batches)
    v.bootstrap(jax.random.PRNGKey(0))
    assert not v.has_vpermit()
    assert v.validate_and_score()          # scoring itself still works
    assert chain.get_weights() == {}       # but nothing was emitted


def test_outer_opt_velocity_persists_across_restart(setup, tmp_path):
    """A restarted OuterOptMerge resumes its DiLoCo velocity from disk and
    produces the same merged base as one that never died."""
    from distributedtraining_tpu.engine import OuterOptMerge, WeightedAverage

    model, cfg, engine, train_batches, val_batches = setup
    base = model.init_params(jax.random.PRNGKey(0))
    d = jax.tree_util.tree_map(lambda x: 0.01 * jnp.ones_like(x), base)
    stacked = delta.stack_deltas([d])
    path = str(tmp_path / "vel.msgpack")

    def one_round(strategy, b):
        merged, _ = strategy.merge(engine, b, stacked, ["m0"],
                                   consensus={"m0": 1.0})
        strategy.commit()
        return merged

    # continuous run: two rounds of accumulated momentum
    cont = OuterOptMerge(WeightedAverage(), momentum=0.9)
    b1 = one_round(cont, base)
    want = one_round(cont, b1)

    # persisted run: round 1, "crash", new instance restores velocity
    p1 = OuterOptMerge(WeightedAverage(), momentum=0.9, state_path=path)
    b1p = one_round(p1, base)
    import os
    assert os.path.exists(path)
    p2 = OuterOptMerge(WeightedAverage(), momentum=0.9, state_path=path)
    got = one_round(p2, b1p)

    for a, b in zip(jax.tree_util.tree_leaves(got),
                    jax.tree_util.tree_leaves(want)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-6)

    # a fresh strategy WITHOUT the file behaves differently (zero momentum)
    fresh = OuterOptMerge(WeightedAverage(), momentum=0.9)
    cold = one_round(fresh, b1p)
    diff = sum(float(jnp.sum(jnp.abs(a - b))) for a, b in zip(
        jax.tree_util.tree_leaves(cold), jax.tree_util.tree_leaves(want)))
    assert diff > 0


def test_outer_opt_velocity_restores_sharded_on_mesh(setup, tmp_path):
    """Mesh averager restart: restored velocity inherits the base's
    shardings instead of parking the full tree on one device."""
    from distributedtraining_tpu.engine import OuterOptMerge, WeightedAverage
    from distributedtraining_tpu.models import gpt2
    from distributedtraining_tpu.parallel import MeshConfig, make_mesh

    model, _ = gpt2.make_model("tiny")
    mesh = make_mesh(MeshConfig(dp=2, fsdp=2))
    eng = TrainEngine(model, mesh=mesh, seq_len=16)
    base = eng.place_params(model.init_params(jax.random.PRNGKey(0)))
    d = jax.tree_util.tree_map(lambda x: 0.01 * jnp.ones_like(x), base)
    stacked = delta.stack_deltas([d])
    path = str(tmp_path / "vel.msgpack")

    s1 = OuterOptMerge(WeightedAverage(), momentum=0.9, state_path=path)
    s1.merge(eng, base, stacked, ["m0"], consensus={"m0": 1.0})
    s1.commit()

    s2 = OuterOptMerge(WeightedAverage(), momentum=0.9, state_path=path)
    s2.merge(eng, base, stacked, ["m0"], consensus={"m0": 1.0})
    for b, v in zip(jax.tree_util.tree_leaves(base),
                    jax.tree_util.tree_leaves(s2.velocity)):
        assert v.sharding == b.sharding, (v.sharding, b.sharding)


def test_chunked_averager_round_matches_stacked(setup, tmp_path):
    """AveragerLoop + WeightedAverage hands the strategy the host delta
    list (host_list_ingest) and chunked merging publishes the identical
    base a full-device-stack merge would — with M deliberately not
    dividing chunk_size."""
    model, cfg, engine, train_batches, val_batches = setup
    base = model.init_params(jax.random.PRNGKey(0))

    def run(strategy):
        transport = InMemoryTransport()
        transport.publish_base(base)
        for i in range(3):
            d = jax.tree_util.tree_map(
                lambda x, s=i + 1: 0.004 * s * jnp.ones_like(x), base)
            transport.publish_delta(f"hotkey_{i}", d)
        chain = LocalChain(str(tmp_path / f"c{id(strategy)}"),
                           my_hotkey="hotkey_99", epoch_length=0)
        avg = AveragerLoop(engine, transport, chain, strategy,
                           val_batches=val_batches)
        avg.bootstrap(jax.random.PRNGKey(0))
        assert avg.run_round()
        host = jax.tree_util.tree_map(
            lambda x: np.zeros(x.shape, x.dtype),
            jax.eval_shape(lambda: base))
        return transport.fetch_base(host)[0]

    chunked = run(WeightedAverage(chunk_size=2))       # 3 deltas, chunk 2
    # control: a strategy WITHOUT host_list_ingest gets the full stack
    class StackedWeighted(WeightedAverage):
        host_list_ingest = False
    stacked = run(StackedWeighted())
    for a, b in zip(jax.tree_util.tree_leaves(chunked),
                    jax.tree_util.tree_leaves(stacked)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-5, atol=1e-7)


def test_parameterized_merge_reuses_compiled_step(setup):
    """Repeated merge() rounds must hit the cached jitted functions — a
    fresh function identity per round would retrace and recompile the
    full model fwd+bwd every averaging cycle."""
    model, cfg, engine, train_batches, val_batches = setup
    pm = ParameterizedMerge(model, meta_epochs=1, meta_lr=0.1,
                            per_tensor=False)
    assert pm._build_step(4) is pm._build_step(4)
    assert pm._build_step(4) is not pm._build_step(8)  # different shape

    base = model.init_params(jax.random.PRNGKey(0))
    d = jax.tree_util.tree_map(lambda x: 0.01 * jnp.ones_like(x), base)
    stacked = delta.stack_deltas([d, d])
    pm.merge(engine, base, stacked, ["a", "b"], val_batches=val_batches)
    n_after_first = len(pm._step_cache)
    pm.merge(engine, base, stacked, ["a", "b"], val_batches=val_batches)
    assert len(pm._step_cache) == n_after_first  # round 2 reused round 1's


def test_validator_metric_cardinality_bounded(setup, tmp_path):
    """The per-round metrics record uses a FIXED key set however many
    miners are scored (the reference's loss_<hotkey>/score_<hotkey> keys
    grow one metric series per uid — r3 verdict weak #6); full per-miner
    detail rides the single structured round_scores entry."""
    model, cfg, engine, train_batches, val_batches = setup
    transport = InMemoryTransport()
    chain = LocalChain(str(tmp_path), my_hotkey="hotkey_95", epoch_length=0,
                       clock=FakeClock())
    base = model.init_params(jax.random.PRNGKey(0))
    transport.publish_base(base)
    state = engine.init_state(params=base)
    for i, b in enumerate(train_batches()):
        if i >= 10:
            break
        state, _ = engine.train_step(state, b)
    transport.publish_delta("hotkey_1", delta.compute_delta(state.params, base))
    transport.publish_delta("hotkey_2", delta.compute_delta(state.params, base))

    sink = InMemorySink()
    v = Validator(engine, transport, chain, eval_batches=val_batches,
                  metrics=sink)
    v.bootstrap(jax.random.PRNGKey(0))
    v.validate_and_score()
    v.validate_and_score()
    assert len(sink.records) == 2
    keysets = [set(r) for r in sink.records]
    assert keysets[0] == keysets[1]          # no per-hotkey key growth
    assert not any(k.startswith(("loss_hotkey", "score_hotkey"))
                   for k in keysets[0])
    rec = sink.records[0]
    assert rec["step"] == 0 and sink.records[1]["step"] == 1
    assert rec["scored"] >= 2
    assert rec["round_scores"]["hotkey_1"]["score"] > 0
    assert rec["round_scores"]["hotkey_1"]["reason"] == "ok"
    # MLflow-style numeric filtering keeps backend series bounded
    numeric = {k: v for k, v in rec.items()
               if isinstance(v, (int, float))}
    assert "round_scores" not in numeric and len(numeric) >= 6


def test_genetic_merge_successive_halving_cuts_full_evals(setup, tmp_path):
    """screen_batches ranks the population on a val subset; only elites
    pay full passes (r3 verdict weak #7: ~100 full passes per round at
    the reference's defaults). Pins both the eval-count reduction and
    that the halving merge still improves on the base."""
    from distributedtraining_tpu.engine.average import GeneticMerge

    model, cfg, engine, train_batches, val_batches = setup
    base = model.init_params(jax.random.PRNGKey(0))
    deltas = []
    for seed in (1, 2):
        state = engine.init_state(params=base)
        for i, b in enumerate(train_batches()):
            if i >= 8:
                break
            state, _ = engine.train_step(state, b)
        deltas.append(delta.compute_delta(state.params, base))
    stacked = delta.stack_deltas(deltas)

    consumed = {"batches": 0}

    def counted_batches():
        def gen():
            for b in val_batches():
                consumed["batches"] += 1
                yield b
        return gen()

    # batched=False pins the SEQUENTIAL tiers' cost model (batches read
    # is proportional to candidates evaluated); the batched population
    # eval reads each batch once per COHORT, which collapses this
    # accounting — its selection parity is pinned in
    # tests/test_batched_eval.py instead
    g = GeneticMerge(population=6, generations=3, elite=2,
                     screen_batches=1, batched=False)
    merged, w = g.merge(engine, base, stacked, ["a", "b"],
                        val_batches=counted_batches)
    halved = consumed["batches"]
    base_loss, _ = engine.evaluate(base, val_batches())
    merged_loss, _ = engine.evaluate(merged, val_batches())
    assert merged_loss < base_loss

    consumed["batches"] = 0
    g_full = GeneticMerge(population=6, generations=3, elite=2,
                          screen_batches=None, batched=False)
    g_full.merge(engine, base, stacked, ["a", "b"],
                 val_batches=counted_batches)
    # the real cost is batches evaluated: screening reads 1 batch per
    # candidate, full passes are reserved for elites + the winner
    assert halved < consumed["batches"], (halved, consumed["batches"])


def test_averager_publish_policy_guards_regressions(setup, tmp_path):
    """--publish-policy improved: a merge that would WORSEN the shared
    base on the eval set is not published (the 2h soak showed
    always-publish compounding val-negative deltas upward — the
    reference's behavior, kept available as 'always')."""
    from distributedtraining_tpu.engine.average import AveragerLoop

    model, cfg, engine, train_batches, val_batches = setup
    transport = InMemoryTransport()
    chain = LocalChain(str(tmp_path), my_hotkey="hotkey_95", epoch_length=0,
                       clock=FakeClock())
    base = model.init_params(jax.random.PRNGKey(0))
    transport.publish_base(base)
    # a delta that HURTS: random noise, large enough to worsen eval loss
    noise = jax.tree_util.tree_map(
        lambda x: 0.3 * jax.random.normal(jax.random.PRNGKey(5), x.shape,
                                          x.dtype), base)
    transport.publish_delta("hotkey_1", noise)

    avg = AveragerLoop(engine, transport, chain, WeightedAverage(),
                       val_batches=val_batches, clock=FakeClock())
    avg.bootstrap()
    rev_before = transport.base_revision()
    # the round did meaningful work (True) but declined the publish
    assert avg.run_round() is True
    assert avg.report.skipped_publishes == 1
    assert transport.base_revision() == rev_before

    # reference mode publishes regardless
    avg2 = AveragerLoop(engine, transport, chain, WeightedAverage(),
                        val_batches=val_batches, clock=FakeClock(),
                        publish_policy="always")
    avg2.bootstrap()
    assert avg2.run_round() is True
    assert transport.base_revision() != rev_before

    # and a GOOD delta still publishes under the guard
    transport2 = InMemoryTransport()
    transport2.publish_base(base)
    state = engine.init_state(params=base)
    for i, b in enumerate(train_batches()):
        if i >= 10:
            break
        state, _ = engine.train_step(state, b)
    transport2.publish_delta("hotkey_1",
                             delta.compute_delta(state.params, base))
    avg3 = AveragerLoop(engine, transport2, chain, WeightedAverage(),
                        val_batches=val_batches, clock=FakeClock())
    avg3.bootstrap()
    assert avg3.run_round() is True
    assert avg3.report.skipped_publishes == 0

    # a NaN/overflowing merged loss must be DECLINED, not published (the
    # `not (loss <= base)` spelling — `loss > base` is False for NaN and
    # would publish the NaN base and disable the guard forever)
    transport3 = InMemoryTransport()
    transport3.publish_base(base)
    # finite wire values whose activations overflow in compute: the eval
    # loss comes out inf/NaN, which only the not-improved spelling rejects
    big = jax.tree_util.tree_map(lambda x: jnp.full_like(x, 1e30), base)
    transport3.publish_delta("hotkey_1", big)
    avg4 = AveragerLoop(engine, transport3, chain, WeightedAverage(),
                        val_batches=val_batches, clock=FakeClock(),
                        max_delta_abs=0)   # cap disabled: guard is last line
    avg4.bootstrap()
    rev = transport3.base_revision()
    assert avg4.run_round() is True
    assert avg4.report.skipped_publishes == 1
    assert transport3.base_revision() == rev
    # ...and the identical submission set is not re-merged next round
    assert avg4.run_round() is True
    assert avg4.report.skipped_publishes == 1  # recompute skipped


def test_miner_keep_optimizer_on_pull(setup):
    """--keep-optimizer-on-pull carries Adam moments across a base pull
    (the federated continuation deviation); the default resets them
    (reference parity, training_manager.py:371-377)."""
    model, cfg, engine, train_batches, _ = setup
    for keep in (False, True):
        clock = FakeClock()
        transport = InMemoryTransport()
        miner = MinerLoop(engine, transport, "m0", clock=clock,
                          send_interval=1000.0, check_update_interval=1.0,
                          log_every=100, keep_optimizer_on_pull=keep)
        miner.bootstrap(jax.random.PRNGKey(0))
        it = train_batches()
        for _ in range(4):
            clock.advance(1.0)
            miner.state, _ = engine.train_step(miner.state, next(it))
        mu_before = jax.tree_util.tree_leaves(miner.state.opt_state)
        nonzero_before = any(float(jnp.abs(l).max()) > 0
                             for l in mu_before if l.ndim > 0)
        assert nonzero_before  # moments accumulated
        transport.publish_base(model.init_params(jax.random.PRNGKey(3)))
        clock.advance(10.0)
        miner._pull_action.poll()
        assert miner.report.base_pulls == 1
        leaves = [l for l in jax.tree_util.tree_leaves(miner.state.opt_state)
                  if hasattr(l, "ndim") and l.ndim > 0]
        nonzero_after = any(float(jnp.abs(l).max()) > 0 for l in leaves)
        assert nonzero_after == keep, (keep, nonzero_after)
