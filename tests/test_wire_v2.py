"""Delta wire v2: sparse+quantized packed per-layer shards with
dedupe-aware ingest (delta.pack_delta_v2, the serialization shard
container, DeltaPublisher's changed-shards-only upload, and the
manifest-first DeltaIngestor path).

The parity pins here are the round's acceptance contract:
decode(encode(delta)) must match the sparsify+quantize v1 reference,
packed-form screen verdicts must match the dense screen on the same
cohort, and a torn shard set must never be decoded.
"""

import os

import jax
import numpy as np
import pytest

from distributedtraining_tpu import delta as dl
from distributedtraining_tpu import serialization as ser
from distributedtraining_tpu.engine.ingest import DeltaCache, DeltaIngestor
from distributedtraining_tpu.engine.publish import DeltaPublisher
from distributedtraining_tpu.transport import base as tbase
from distributedtraining_tpu.transport.localfs import LocalFSTransport
from distributedtraining_tpu.transport.memory import InMemoryTransport
from distributedtraining_tpu.transport.retry import RetryPolicy
from distributedtraining_tpu.utils import obs

FAST_RETRY = RetryPolicy(attempts=2, base_delay=0.0, max_delay=0.0,
                         jitter=0.0)


class _Report:
    pushes = 0
    pushes_failed = 0
    pushes_superseded = 0


def _tree(seed=0, big=(300, 40), small=(32,)):
    """A delta tree with one above-cutoff tensor (top-k sparsified) and
    one below-cutoff tensor (dense-form entry)."""
    rs = np.random.RandomState(seed)
    return {"wte": (rs.randn(*big) * 0.01).astype(np.float32),
            "ln": {"g": (rs.randn(*small) * 0.01).astype(np.float32)}}


def _template(tree):
    return jax.tree_util.tree_map(
        lambda x: np.zeros(np.shape(x), np.float32), tree)


def _leaves(t):
    return [np.asarray(x) for x in jax.tree_util.tree_leaves(t)]


def _v2_publisher(transport, hotkey, *, density=1 / 64, quant="int8"):
    return DeltaPublisher(
        transport, hotkey, report=_Report(), publish_retry=FAST_RETRY,
        meta_retry=FAST_RETRY,
        wire_spec={"format": 2, "density": density, "quant": quant})


def _ingestor(transport, template, **kw):
    kw.setdefault("workers", 1)
    kw.setdefault("max_delta_abs", 1e3)
    kw.setdefault("retry_policy", FAST_RETRY)
    return DeltaIngestor(transport, template, **kw)


# ---------------------------------------------------------------------------
# Parity pins
# ---------------------------------------------------------------------------

def test_pack_decode_matches_sparse_quantize_reference():
    """decode(encode(delta)) == densify(sparsify_delta(delta)): the v2
    packed form keeps the v1 top-k selection and int8 scales exactly
    (dense-form entries differ in LAYOUT only — empty idx, full q)."""
    delta = _tree()
    packed, _ = dl.pack_delta_v2(delta, density=1 / 64)
    dec = dl.densify_packed_v2(jax.device_get(packed), delta)
    ref = dl.densify_sparse_delta(
        jax.device_get(dl.sparsify_delta(delta, density=1 / 64)), delta)
    for a, b in zip(_leaves(dec), _leaves(ref)):
        np.testing.assert_array_equal(a, b)
    # the below-cutoff tensor really ships dense-form (no index bytes)
    entries = dl.packed_layer_entries(jax.device_get(packed))
    assert entries["ln/g"]["idx"].shape == (0,)
    assert entries["ln/g"]["q"].shape == (32,)
    assert entries["wte"]["idx"].shape[0] < delta["wte"].size


def test_packed_screen_verdicts_match_dense_screen():
    """The fused packed-form screen returns the dense screen's verdicts
    on the same cohort — good, magnitude-capped, and nonfinite members
    alike — without densifying ahead of the verdict."""
    good = _tree(0)
    too_big = _tree(1)
    too_big["wte"][0, 0] = 50.0           # decoded max exceeds the cap
    bad = _tree(2)
    base = _template(good)

    packed_cohort, dense_cohort = [], []
    for d in (good, too_big):
        p = jax.device_get(dl.pack_delta_v2(d, density=1 / 64)[0])
        packed_cohort.append(p)
        dense_cohort.append(dl.densify_packed_v2(p, base))
    # nonfinite member: quant="none" carries f32 kept values, so a NaN
    # survives encoding (int8 would crush it at the miner's finite flag)
    p_bad = jax.device_get(dl.pack_delta_v2(bad, density=1 / 64,
                                            quant="none")[0])
    q = p_bad["leaves"]["wte"]["q"].copy()
    q[0] = np.nan
    p_bad["leaves"]["wte"]["q"] = q
    packed_cohort.append(p_bad)
    dense_cohort.append(dl.densify_packed_v2(p_bad, base))

    vp = dl.screen_deltas(packed_cohort, base, max_abs=1.0)
    vd = dl.screen_deltas(dense_cohort, base, max_abs=1.0)
    assert [ok for ok, _ in vp] == [ok for ok, _ in vd] == [
        True, False, False]
    # same reason vocabulary, including the identical magnitude value
    assert vp == vd


def test_apply_delta_loss_parity_within_quant_tolerance():
    """base + decode(encode(delta)) scores like base + delta on a real
    model when the delta's support fits the kept-coordinate budget: the
    only loss difference left is int8 rounding."""
    from distributedtraining_tpu.models.toy import FeedforwardNet

    model = FeedforwardNet()
    base = jax.device_get(model.init_params(jax.random.PRNGKey(0)))
    rs = np.random.RandomState(3)
    # sparse update: every tensor gets a few large coordinates, well
    # under the 1/64 top-k budget of the big layers (small layers ship
    # dense anyway), so sparsification drops nothing and the remaining
    # error is quantization only
    delta = jax.tree_util.tree_map(
        lambda x: np.zeros(np.shape(x), np.float32), base)

    def spike(a, n=8):
        flat = a.reshape(-1)
        flat[rs.choice(flat.size, size=min(n, flat.size),
                       replace=False)] = 0.05
        return a

    delta = jax.tree_util.tree_map(spike, delta)
    packed, _ = dl.pack_delta_v2(delta, density=1 / 64)
    dec = dl.densify_packed_v2(jax.device_get(packed), base)

    images = rs.randn(16, 28, 28, 1).astype(np.float32)
    labels = rs.randint(0, 10, size=(16,))

    def loss(params):
        logits = model.apply({"params": params}, images)
        logp = jax.nn.log_softmax(logits)
        return float(-logp[np.arange(16), labels].mean())

    l_ref = loss(dl.apply_delta(base, delta))
    l_dec = loss(dl.apply_delta(base, dec))
    # int8 tolerance: per-tensor error <= scale = max|kept|/127
    assert abs(l_ref - l_dec) < 5e-3, (l_ref, l_dec)


def test_error_feedback_residual_ships_dropped_mass():
    """A coordinate persistently below the top-k threshold accumulates
    in the residual until it crosses it — repeated lossy publishes
    converge instead of dropping it forever (and without the residual
    it is dropped forever)."""
    n = 64 * 1024
    rs = np.random.RandomState(0)
    flat = np.zeros(n, np.float32)
    k = dl.sparse_k(n, 1 / 64)
    flat[:k] = 1.0 + 0.1 * rs.rand(k)     # the recurring top-k winners
    victim = n - 7
    flat[victim] = 0.3                    # persistently dropped
    delta = {"w": flat.reshape(256, 256)}

    # stateless (no residual): never ships the victim
    packed, _ = dl.pack_delta_v2(delta, density=1 / 64)
    dec = dl.densify_packed_v2(jax.device_get(packed), delta)
    assert dec["w"].reshape(-1)[victim] == 0.0

    residual = None
    shipped_at = None
    for i in range(6):
        packed, residual = dl.pack_delta_v2(delta, density=1 / 64,
                                            residual=residual)
        dec = dl.densify_packed_v2(jax.device_get(packed), delta)
        if dec["w"].reshape(-1)[victim] != 0.0:
            shipped_at = i
            break
    assert shipped_at is not None, "residual never promoted the victim"
    assert shipped_at >= 1                # genuinely below-threshold at first


# ---------------------------------------------------------------------------
# Codec hardening
# ---------------------------------------------------------------------------

def test_manifest_codec_round_trip_and_hostile_inputs():
    layers = {"a": ("ab" * 32, 10), "b/c": ("cd" * 32, 20)}
    man = ser.build_wire_manifest(layers, density=1 / 64, quant="int8")
    assert ser.is_wire_v2_manifest(man)
    parsed = ser.parse_wire_manifest(man)
    assert parsed["quant"] == "int8"
    assert parsed["density"] == pytest.approx(1 / 64)
    assert set(parsed["layers"]) == {"a", "b/c"}
    assert parsed["layers"]["a"] == {"h": "ab" * 32, "n": 10}

    import json
    assert ser.parse_wire_manifest(b"not a manifest") is None
    assert ser.parse_wire_manifest(ser.WIRE_V2_MAGIC + b"{broken") is None
    assert ser.parse_wire_manifest(
        ser.WIRE_V2_MAGIC + json.dumps({"format": 1, "layers": {}}).encode()
    ) is None
    bad_hash = {"format": 2, "layers": {"a": {"h": "XYZ", "n": 1}}}
    assert ser.parse_wire_manifest(
        ser.WIRE_V2_MAGIC + json.dumps(bad_hash).encode()) is None
    bad_n = {"format": 2, "layers": {"a": {"h": "ab" * 32, "n": -1}}}
    assert ser.parse_wire_manifest(
        ser.WIRE_V2_MAGIC + json.dumps(bad_n).encode()) is None
    # a hostile manifest can never be confused with msgpack wire forms
    assert dl.sparse_delta_from_bytes(man, {"a": np.zeros(4, np.float32)}) is None


def test_shard_codec_round_trip_and_garbage():
    entry = {"idx": np.asarray([1, 5], np.int32),
             "q": np.asarray([3, -7], np.int8),
             "scale": np.float32(0.25)}
    data = ser.pack_shard(entry)
    back = ser.unpack_shard(data)
    for key in ("idx", "q", "scale"):
        np.testing.assert_array_equal(back[key], entry[key])
    assert ser.unpack_shard(b"\x00garbage") is None
    assert ser.unpack_shard(ser.to_msgpack({"idx": 1})) is None
    with pytest.raises(ValueError):
        ser.pack_shard({"idx": entry["idx"]})


def test_wire_blob_round_trip():
    delta = _tree()
    packed = jax.device_get(dl.pack_delta_v2(delta, density=1 / 64)[0])
    blob = ser.pack_wire_blob(packed)
    assert ser.is_wire_v2_blob(blob)
    dense = ser.unpack_wire_blob(blob, _template(delta))
    ref = dl.densify_packed_v2(packed, _template(delta))
    for a, b in zip(_leaves(dense), _leaves(ref)):
        np.testing.assert_array_equal(a, b)
    # the generic decode chain accepts a blob too (pod broadcast path)
    from distributedtraining_tpu.engine.lora_train import densify_delta_bytes
    dense2 = densify_delta_bytes(blob, _template(delta))
    assert dense2 is not None
    assert ser.unpack_wire_blob(b"DTWIRE2B\n\x00junk",
                                _template(delta)) is None


def test_negative_scale_is_rejected_everywhere():
    """A hostile NEGATIVE scale must not slip under the magnitude cap:
    |q| * scale with scale < 0 would give a negative screen verdict
    while densifying to arbitrarily large |values|. Admission, densify,
    the cohort screen, and the sparse8 densifier all refuse it, and the
    fused screen's magnitude is sign-robust even on unvalidated input."""
    delta = _tree()
    base = _template(delta)
    p = jax.device_get(dl.pack_delta_v2(delta, density=1 / 64)[0])
    p["leaves"]["wte"]["scale"] = np.float32(-1e6)
    assert not dl.packed_matches(p, base)
    assert dl.densify_packed_v2(p, base) is None
    assert dl.screen_deltas([p], base, max_abs=1.0) == [
        (False, "shape_mismatch")]
    # defense in depth: even without the admission gate, the screen's
    # magnitude uses |scale| — the verdict cannot go negative
    _, mags = dl._packed_screen_stats(p["leaves"])
    assert float(mags[0]) > 1.0
    # the shared validator covers the v1 sparse8 wire too
    sp = jax.device_get(dl.sparsify_delta(delta, density=1 / 64))
    sp["leaves"]["wte"]["scale"] = np.float32(-1.0)
    assert dl.densify_sparse_delta(sp, base) is None


def test_empty_leaf_packs_and_round_trips():
    """A zero-element tensor (n == 0 forces the dense-form branch) must
    encode, screen, and decode — not crash the publish path on an empty
    jnp.max reduction."""
    delta = {"w": (np.random.RandomState(0).randn(300, 40)
                   * 0.01).astype(np.float32),
             "empty": np.zeros((0,), np.float32)}
    base = _template(delta)
    packed, res = dl.pack_delta_v2(delta, density=1 / 64)
    packed = jax.device_get(packed)
    assert np.shape(jax.device_get(res)["empty"]) == (0,)
    assert dl.packed_matches(packed, base)
    dec = dl.densify_packed_v2(packed, base)
    assert dec["empty"].shape == (0,)
    np.testing.assert_array_equal(
        dec["w"], dl.densify_sparse_delta(
            jax.device_get(dl.sparsify_delta(delta, density=1 / 64)),
            base)["w"])
    assert dl.screen_deltas([packed], base, max_abs=1e3) == [(True, "ok")]
    # sparse8 (v1) tolerates the empty leaf too
    sp = jax.device_get(dl.sparsify_delta(delta, density=1 / 64))
    assert dl.densify_sparse_delta(sp, base)["empty"].shape == (0,)


def test_hostile_layer_keys_fail_template_validation():
    delta = _tree()
    packed = jax.device_get(dl.pack_delta_v2(delta, density=1 / 64)[0])
    entries = dl.packed_layer_entries(packed)
    # colliding / alien keys reassemble into a tree that fails the
    # template check, never an exception
    bad = dict(entries)
    bad["wte/evil"] = entries["ln/g"]
    tree = dl.packed_from_layer_entries(bad)
    assert not dl.packed_matches(tree, _template(delta))
    assert dl.densify_packed_v2(tree, _template(delta)) is None


# ---------------------------------------------------------------------------
# Publish -> ingest round trips
# ---------------------------------------------------------------------------

class CountingFS(LocalFSTransport):
    """LocalFS with byte/op accounting on the raw publish/fetch surface."""

    def __init__(self, root):
        super().__init__(root)
        self.published = []
        self.fetched = []

    def publish_raw(self, mid, data):
        self.published.append((mid, len(data)))
        return super().publish_raw(mid, data)

    def fetch_delta_bytes(self, mid):
        d = super().fetch_delta_bytes(mid)
        if d is not None:
            self.fetched.append((mid, len(d)))
        return d


def test_publish_ingest_round_trip_with_shard_dedupe(tmp_path):
    """The acceptance round: a v2 push stages correctly, a warm round
    with an unchanged manifest downloads nothing, and a one-layer change
    re-uploads/re-fetches ONLY that layer's shard (plus the manifest) —
    with the wire.* counters observing it."""
    from distributedtraining_tpu.utils.metrics import JSONLSink

    path = str(tmp_path / "m.jsonl")
    sink = JSONLSink(path)
    obs.configure(sink, role="test")
    transport = CountingFS(str(tmp_path / "fs"))
    delta = _tree()
    template = _template(delta)
    pub = _v2_publisher(transport, "m0")
    ing = _ingestor(transport, template)
    try:
        pack = jax.jit(lambda d: dl.pack_delta_v2(d, density=1 / 64))
        packed = jax.device_get(pack(delta))[0]
        assert pub.publish_now(packed, None, "rev0", "cid-1")
        # manifest-last: the delta artifact lands after every shard
        assert transport.published[-1][0] == "m0"
        assert all(tbase.is_shard_id(m) for m, _ in transport.published[:-1])
        # rider declares the wire format (the META negotiation surface)
        assert transport.fetch_delta_meta("m0")["wire"]["format"] == 2

        s = ing.stage(["m0"])[0]
        assert s.ok and s.reason == "ok"
        assert s.wire_bytes > 0
        ref = dl.densify_packed_v2(packed, template)
        for a, b in zip(_leaves(s.delta), _leaves(ref)):
            np.testing.assert_array_equal(a, b)
        v2_bytes = sum(n for _, n in transport.published)
        dense_bytes = len(ser.to_msgpack(delta))
        assert dense_bytes > 5 * v2_bytes   # tiny tree; >=10x at scale

        # warm round: unchanged revision — zero transport bytes
        transport.fetched.clear()
        s2 = ing.stage(["m0"])[0]
        assert s2.ok and s2.cached and s2.wire_bytes == 0
        assert transport.fetched == []

        # one-layer change: only ln/g's shard (+ manifest) moves
        delta2 = {"wte": delta["wte"],
                  "ln": {"g": (delta["ln"]["g"] + 0.5).astype(np.float32)}}
        packed2 = jax.device_get(pack(delta2))[0]
        transport.published.clear()
        assert pub.publish_now(packed2, None, "rev0", "cid-2")
        pub_ids = [m for m, _ in transport.published]
        assert pub_ids == [tbase.shard_id("m0", "ln/g"), "m0"]

        transport.fetched.clear()
        deduped0 = obs.registry().counter("wire.shards_deduped").value
        s3 = ing.stage(["m0"])[0]
        assert s3.ok and not s3.cached
        fetch_ids = [m for m, _ in transport.fetched]
        assert fetch_ids == ["m0", tbase.shard_id("m0", "ln/g")]
        assert obs.registry().counter("wire.shards_deduped").value > deduped0
        for a, b in zip(_leaves(s3.delta),
                        _leaves(dl.densify_packed_v2(packed2, template))):
            np.testing.assert_array_equal(a, b)
    finally:
        ing.close()
        pub.close()
        obs.reset()
        sink.close()


def test_torn_shard_set_is_never_decoded(tmp_path):
    """Mid-publish state — old manifest, one shard already overwritten
    with newer content — must read as a transient miss, never a decode
    of mixed halves. A warm cache keeps serving the last CONSISTENT
    decode."""
    transport = CountingFS(str(tmp_path / "fs"))
    delta = _tree()
    template = _template(delta)
    pub = _v2_publisher(transport, "m0")
    ing_warm = _ingestor(transport, template)
    ing_cold = _ingestor(transport, template, cache_bytes=0)
    try:
        packed = jax.device_get(dl.pack_delta_v2(delta, density=1 / 64)[0])
        assert pub.publish_now(packed, None, "rev0")
        assert ing_warm.stage(["m0"])[0].ok

        # tear: overwrite one shard as a new publish would, manifest not
        # yet updated
        packed2 = jax.device_get(dl.pack_delta_v2(
            {"wte": delta["wte"],
             "ln": {"g": (delta["ln"]["g"] * 2).astype(np.float32)}},
            density=1 / 64)[0])
        new_entries = dl.packed_layer_entries(packed2)
        tbase.publish_shard(transport, "m0", "ln/g",
                            ser.pack_shard(new_entries["ln/g"]))

        cold = ing_cold.stage(["m0"])[0]
        assert not cold.ok and cold.reason == "no_delta"

        warm = ing_warm.stage(["m0"])[0]   # manifest revision unchanged
        assert warm.ok and warm.cached     # last consistent decode served
        ref = dl.densify_packed_v2(packed, template)
        for a, b in zip(_leaves(warm.delta), _leaves(ref)):
            np.testing.assert_array_equal(a, b)
    finally:
        ing_warm.close()
        ing_cold.close()
        pub.close()


def test_mid_publish_manifest_failure_heals_next_push(tmp_path):
    """A publish whose manifest upload dies after its shards landed
    leaves the transport readable-but-stale; the publisher reports a
    failed push, re-uploads on the next interval, and readers never
    decode the half-new state."""

    class FailManifest(CountingFS):
        manifest_outage = 0     # manifest publish attempts left to fail

        def publish_raw(self, mid, data):
            if self.manifest_outage and not tbase.is_shard_id(mid):
                self.manifest_outage -= 1
                raise OSError("injected manifest outage")
            return super().publish_raw(mid, data)

    transport = FailManifest(str(tmp_path / "fs"))
    delta = _tree()
    template = _template(delta)
    pub = _v2_publisher(transport, "m0")
    ing = _ingestor(transport, template, cache_bytes=0)
    try:
        pack = jax.jit(lambda d: dl.pack_delta_v2(d, density=1 / 64))
        assert pub.publish_now(jax.device_get(pack(delta))[0], None, "r0")
        assert ing.stage(["m0"])[0].ok

        delta2 = {"wte": (delta["wte"] + 0.1).astype(np.float32),
                  "ln": delta["ln"]}
        packed2 = jax.device_get(pack(delta2))[0]
        transport.manifest_outage = FAST_RETRY.attempts
        assert not pub.publish_now(packed2, None, "r0")   # counted failed
        assert pub.report.pushes_failed == 1

        torn = ing.stage(["m0"])[0]        # old manifest + new wte shard
        assert not torn.ok and torn.reason == "no_delta"

        assert pub.publish_now(packed2, None, "r0")       # heals
        healed = ing.stage(["m0"])[0]
        assert healed.ok
        ref = dl.densify_packed_v2(packed2, template)
        for a, b in zip(_leaves(healed.delta), _leaves(ref)):
            np.testing.assert_array_equal(a, b)
    finally:
        ing.close()
        pub.close()


def test_chaos_transport_carries_shard_and_manifest_ops(tmp_path):
    """ChaosTransport gates every shard/manifest operation like any
    other publish/fetch: injected faults surface as ordinary per-miner
    staging isolation (fetch_error / failed push), and a clean round
    afterwards works — the v2 wire adds no un-gated surface."""
    from distributedtraining_tpu.transport.chaos import (ChaosError,
                                                         ChaosSpec,
                                                         ChaosTransport)

    inner = CountingFS(str(tmp_path / "fs"))
    delta = _tree()
    template = _template(delta)

    # deterministic publish faults: the publisher retries past the first
    # injected error (seeded stream, rate .45, attempts=2 per op)
    chaos = ChaosTransport(inner, ChaosSpec(publish_error_rate=1.0, seed=3),
                           sleep=lambda s: None)
    pub = _v2_publisher(chaos, "m0")
    try:
        with pytest.raises(Exception):
            # every op faults: _publish_v2 must raise (not half-succeed
            # silently) so publish_now counts a failed push
            pub._publish_v2(jax.device_get(
                dl.pack_delta_v2(delta, density=1 / 64)[0]))
        assert not pub.publish_now(
            jax.device_get(dl.pack_delta_v2(delta, density=1 / 64)[0]),
            None, "r0")
        assert pub.report.pushes_failed == 1
    finally:
        pub.close()

    # fetch faults: staging isolates per miner, then a clean round works
    pub2 = _v2_publisher(inner, "m0")
    assert pub2.publish_now(
        jax.device_get(dl.pack_delta_v2(delta, density=1 / 64)[0]),
        None, "r0")
    pub2.close()
    chaos_fetch = ChaosTransport(inner, ChaosSpec(fetch_error_rate=1.0,
                                                  seed=1),
                                 sleep=lambda s: None)
    ing = _ingestor(chaos_fetch, template, cache_bytes=0)
    try:
        s = ing.stage(["m0"])[0]
        assert not s.ok and s.reason in ("fetch_error", "no_delta")
    finally:
        ing.close()
    ing2 = _ingestor(inner, template)
    try:
        assert ing2.stage(["m0"])[0].ok
        assert chaos_fetch.faults > 0
    finally:
        ing2.close()


def test_signed_transport_signs_manifest_and_passes_shards(tmp_path):
    """SignedTransport envelopes the manifest under the delta context
    (receivers with a registered key verify it); shards pass through
    unsigned, pinned by the signed manifest's content hashes; a
    tampered manifest is rejected wholesale."""
    pytest.importorskip("cryptography")
    from distributedtraining_tpu.transport.signed import SignedTransport
    from distributedtraining_tpu.utils.identity import Identity

    ident = Identity.generate("m0")
    keys = {"m0": ident.public_bytes()}
    inner = CountingFS(str(tmp_path / "fs"))
    signed = SignedTransport(inner, identity=ident,
                             pubkey_resolver=keys.get, my_hotkey="m0")
    reader = SignedTransport(CountingFS(str(tmp_path / "fs")),
                             pubkey_resolver=keys.get)
    delta = _tree()
    template = _template(delta)
    pub = _v2_publisher(signed, "m0")
    ing = _ingestor(reader, template)
    try:
        packed = jax.device_get(dl.pack_delta_v2(delta, density=1 / 64)[0])
        assert pub.publish_now(packed, None, "r0")
        s = ing.stage(["m0"])[0]
        assert s.ok
        ref = dl.densify_packed_v2(packed, template)
        for a, b in zip(_leaves(s.delta), _leaves(ref)):
            np.testing.assert_array_equal(a, b)

        # forged manifest (unsigned, key registered) is rejected
        forged_layers = {k: (ser.shard_digest(b"x"), 1)
                         for k in dl.packed_layer_entries(packed)}
        inner.publish_raw("m0", ser.build_wire_manifest(
            forged_layers, density=1 / 64, quant="int8"))
        ing.cache.clear()
        s2 = ing.stage(["m0"])[0]
        assert not s2.ok
    finally:
        ing.close()
        pub.close()


def test_mixed_fleet_v1_and_v2_miners_stage_and_merge():
    """The mixed-fleet acceptance round: one dense v1 miner and one v2
    miner stage through the same ingestor (the path both the validator
    and the averager gather through) and merge together."""
    transport = InMemoryTransport()
    delta_v1 = _tree(0)
    delta_v2 = _tree(1)
    template = _template(delta_v1)

    # v1 miner: classic dense publish + rider without a wire declaration
    transport.publish_delta("legacy", delta_v1)
    transport.publish_delta_meta("legacy", {"base_revision": "r0",
                                            "delta_id": "legacy-1"})
    # v2 miner: shard manifest + wire-declaring rider
    pub = _v2_publisher(transport, "modern")
    packed = jax.device_get(dl.pack_delta_v2(delta_v2, density=1 / 64)[0])
    assert pub.publish_now(packed, None, "r0", "modern-1")
    pub.close()
    assert transport.fetch_delta_meta("modern")["wire"]["format"] == 2
    assert "wire" not in transport.fetch_delta_meta("legacy")

    ing = _ingestor(transport, template, workers=2)
    try:
        staged = {s.hotkey: s for s in ing.stage(["legacy", "modern"],
                                                 base_revision="r0")}
        assert staged["legacy"].ok and staged["modern"].ok
        for a, b in zip(_leaves(staged["legacy"].delta), _leaves(delta_v1)):
            np.testing.assert_allclose(a, b, rtol=1e-6)
        ref = dl.densify_packed_v2(packed, template)
        for a, b in zip(_leaves(staged["modern"].delta), _leaves(ref)):
            np.testing.assert_array_equal(a, b)

        # and they merge into one base like any homogeneous cohort
        merged = dl.chunked_weighted_merge(
            template, [staged["legacy"].delta, staged["modern"].delta],
            np.asarray([0.5, 0.5], np.float32))
        expect = jax.tree_util.tree_map(
            lambda a, b: 0.5 * np.asarray(a) + 0.5 * np.asarray(b),
            staged["legacy"].delta, staged["modern"].delta)
        for a, b in zip(_leaves(merged), _leaves(expect)):
            np.testing.assert_allclose(a, b, rtol=1e-5, atol=1e-7)
    finally:
        ing.close()


def test_receiver_can_refuse_wire_v2():
    """--no-wire-v2 (accept_wire_v2=False): manifests stage as no_delta
    while v1 miners keep working — the v1-only posture."""
    transport = InMemoryTransport()
    delta = _tree()
    template = _template(delta)
    transport.publish_delta("legacy", delta)
    pub = _v2_publisher(transport, "modern")
    assert pub.publish_now(
        jax.device_get(dl.pack_delta_v2(delta, density=1 / 64)[0]),
        None, "r0")
    pub.close()
    ing = _ingestor(transport, template, accept_wire_v2=False)
    try:
        staged = {s.hotkey: s for s in ing.stage(["legacy", "modern"])}
        assert staged["legacy"].ok
        assert not staged["modern"].ok
        assert staged["modern"].reason == "no_delta"
    finally:
        ing.close()


def test_shard_cache_is_content_addressed_across_miners(tmp_path):
    """Two miners shipping an identical layer dedupe to ONE shard cache
    entry: the second miner's unchanged layer is served from cache even
    though its manifest was never seen before."""
    transport = CountingFS(str(tmp_path / "fs"))
    delta = _tree()
    template = _template(delta)
    pub_a = _v2_publisher(transport, "a")
    pub_b = _v2_publisher(transport, "b")
    ing = _ingestor(transport, template)
    try:
        pack = jax.jit(lambda d: dl.pack_delta_v2(d, density=1 / 64))
        packed = jax.device_get(pack(delta))[0]
        assert pub_a.publish_now(packed, None, "r0")
        assert pub_b.publish_now(packed, None, "r0")
        assert ing.stage(["a"])[0].ok
        transport.fetched.clear()
        s = ing.stage(["b"])[0]
        assert s.ok
        # miner b cost ONE manifest read; every shard came from the
        # content-addressed cache
        assert [m for m, _ in transport.fetched] == ["b"]
    finally:
        ing.close()
        pub_a.close()
        pub_b.close()


def test_delta_cache_shard_budget_and_eviction():
    cache = DeltaCache(max_bytes=2048)
    big = {"idx": np.zeros(0, np.int32), "q": np.zeros(1024, np.int8),
           "scale": np.float32(1)}
    cache.shard_put("a" * 64, big)
    assert cache.shard_lookup("a" * 64) is not None
    cache.shard_put("b" * 64, big)
    # budget forces the older shard out (LRU)
    assert cache.shard_lookup("a" * 64) is None
    assert cache.shard_lookup("b" * 64) is not None
    assert cache.nbytes <= 2048
    cache.clear()
    assert cache.nbytes == 0 and cache.shard_lookup("b" * 64) is None


def test_shard_slug_injective_for_dotted_layer_keys(tmp_path):
    """Layer keys containing '.' must not collide with '/'-separated
    ones after the slug join ('a/b.c' vs 'a/b/c'): a collision makes the
    publisher silently overwrite one layer's shard with the other and
    ingest fail that layer's hash check every round."""
    keys = ["a/b.c", "a/b/c", "a.b/c", "a/b%c", "a/b%2Ec", "a.b.c"]
    slugs = [tbase.shard_layer_slug(k) for k in keys]
    assert len(set(slugs)) == len(keys), slugs
    assert len({tbase.shard_id("m0", k) for k in keys}) == len(keys)

    # end to end: a model with a dotted parameter name publishes both
    # layers and stages them back intact
    rs = np.random.RandomState(0)
    delta = {"a": {"b.c": (rs.randn(64) * 0.01).astype(np.float32),
                   "b": {"c": (rs.randn(64) * 0.02).astype(np.float32)}}}
    template = _template(delta)
    transport = CountingFS(str(tmp_path / "fs"))
    pub = _v2_publisher(transport, "m0")
    ing = _ingestor(transport, template)
    try:
        packed = jax.device_get(dl.pack_delta_v2(delta, density=1 / 64)[0])
        assert len(dl.packed_layer_entries(packed)) == 2
        assert pub.publish_now(packed, None, "r0")
        # two distinct shard artifacts landed (plus the manifest)
        assert len([m for m, _ in transport.published
                    if tbase.is_shard_id(m)]) == 2
        s = ing.stage(["m0"])[0]
        assert s.ok, s.reason
        ref = dl.densify_packed_v2(packed, template)
        for a, b in zip(_leaves(s.delta), _leaves(ref)):
            np.testing.assert_array_equal(a, b)
    finally:
        ing.close()
        pub.close()


def test_reserved_shard_ids_and_localfs_roots(tmp_path):
    from distributedtraining_tpu.transport import localfs

    sid = tbase.shard_id("m0", "h_0/attn/w")
    assert tbase.is_shard_id(sid)
    assert tbase.is_reserved_id(sid)
    assert not tbase.is_shard_id("m0")
    root = str(tmp_path / "fs")
    LocalFSTransport(root)
    assert os.path.abspath(root) in localfs.live_roots()


def test_miner_loop_snapshot_carries_residual(tmp_path):
    """MinerLoop --wire-v2 integration: the push program threads the
    error-feedback residual across pushes, the artifact on the wire is
    a manifest, and a base pull resets the residual."""
    from distributedtraining_tpu.engine.train import MinerLoop, TrainEngine
    from distributedtraining_tpu.models import gpt2

    model, cfg = gpt2.make_model(gpt2.GPT2Config(
        vocab_size=128, n_positions=32, n_embd=16, n_layer=1, n_head=2))
    engine = TrainEngine(model, seq_len=16)
    transport = CountingFS(str(tmp_path / "fs"))
    loop = MinerLoop(engine, transport, "m0", send_interval=1e9,
                     push_async=False, wire_v2=True,
                     wire_density=1 / 64)
    loop.bootstrap(rng=jax.random.PRNGKey(0))
    assert loop._wire_residual is None
    loop._push_delta()
    assert loop._wire_residual is not None
    data = transport.fetch_delta_bytes("m0")
    assert ser.is_wire_v2_manifest(data)
    meta = transport.fetch_delta_meta("m0")
    assert meta["wire"] == {"format": 2, "density": 1 / 64,
                            "quant": "int8"}
    # a staged ingest decodes it against the engine's wire template
    from distributedtraining_tpu.engine.train import host_wire_template
    ing = _ingestor(transport, host_wire_template(engine))
    try:
        assert ing.stage(["m0"])[0].ok
    finally:
        ing.close()
    # base pull resets the residual
    transport.publish_base(jax.device_get(loop.state.params))
    loop._check_pull()
    assert loop._wire_residual is None
    loop.flush()


def test_nonfinite_delta_does_not_poison_residual(tmp_path):
    """A transient non-finite delta is skipped by the nan guard AND the
    loop-carried error-feedback residual keeps its pre-divergence value
    (new_res = delta + residual - decoded would smear the NaN into every
    later publish until the next base pull). After the miner recovers,
    the next publish is clean and stages."""
    from distributedtraining_tpu.engine.train import (MinerLoop,
                                                      TrainEngine,
                                                      host_wire_template)
    from distributedtraining_tpu.models import gpt2

    model, cfg = gpt2.make_model(gpt2.GPT2Config(
        vocab_size=128, n_positions=32, n_embd=16, n_layer=1, n_head=2))
    engine = TrainEngine(model, seq_len=16)
    transport = CountingFS(str(tmp_path / "fs"))
    loop = MinerLoop(engine, transport, "m0", send_interval=1e9,
                     push_async=False, wire_v2=True, wire_density=1 / 64)
    loop.bootstrap(rng=jax.random.PRNGKey(0))
    # drift params so the first (healthy) push leaves a real residual
    loop.state = loop.state.replace(params=jax.tree_util.tree_map(
        lambda x: x + 0.01, loop.state.params))
    healthy = loop.state
    loop._push_delta()
    res_before = jax.device_get(loop._wire_residual)
    assert all(np.isfinite(l).all() for l in _leaves(res_before))

    # transient divergence: NaN params -> the guard skips the push and
    # the residual must NOT commit the contaminated update
    published = len(transport.published)
    loop.state = loop.state.replace(params=jax.tree_util.tree_map(
        lambda x: jax.numpy.full_like(x, np.nan), loop.state.params))
    loop._push_delta()
    assert len(transport.published) == published      # push skipped
    res_after = jax.device_get(loop._wire_residual)
    for a, b in zip(_leaves(res_before), _leaves(res_after)):
        np.testing.assert_array_equal(a, b)

    # recovery: the very next healthy publish is finite and stages
    loop.state = healthy
    loop._push_delta()
    assert len(transport.published) > published
    ing = _ingestor(transport, host_wire_template(engine))
    try:
        s = ing.stage(["m0"])[0]
        assert s.ok, s.reason
        assert all(np.isfinite(l).all() for l in _leaves(s.delta))
    finally:
        ing.close()
    loop.flush()


def test_wire_v2_rejects_conflicting_v1_compression(tmp_path):
    from distributedtraining_tpu.engine.train import MinerLoop, TrainEngine
    from distributedtraining_tpu.models import gpt2

    model, _ = gpt2.make_model(gpt2.GPT2Config(
        vocab_size=128, n_positions=32, n_embd=16, n_layer=1, n_head=2))
    engine = TrainEngine(model, seq_len=16)
    with pytest.raises(ValueError, match="wire_v2"):
        MinerLoop(engine, InMemoryTransport(), "m0", wire_v2=True,
                  delta_dtype="sparse8")
