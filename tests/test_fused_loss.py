"""Fused linear cross-entropy: numerics vs the materialized-logits oracle.

The fused path (ops.losses.fused_linear_cross_entropy) computes the tied
LM head tile-by-tile with an online softmax, never materializing the
[B, T, V] logits. These tests pin its forward value AND parameter gradients
to the standard causal_lm_loss path at tolerances tight enough to catch any
online-softmax or label-gather slip, including non-dividing vocab/chunk
shapes and masked tokens.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributedtraining_tpu.engine import TrainEngine
from distributedtraining_tpu.engine.train import (_default_lm_loss,
                                                  _fused_lm_loss)
from distributedtraining_tpu.models import gpt2
from distributedtraining_tpu.ops.losses import (causal_lm_loss,
                                                fused_linear_cross_entropy)


def _case(V=300, E=16, N=24, seed=0, dtype=jnp.float32):
    rng = np.random.default_rng(seed)
    hidden = jnp.asarray(rng.standard_normal((N, E)), dtype)
    wte = jnp.asarray(rng.standard_normal((V, E)) * 0.3, dtype)
    labels = jnp.asarray(rng.integers(0, V, (N,)), jnp.int32)
    return hidden, wte, labels


@pytest.mark.parametrize("chunk", [64, 100, 300, 512])
def test_fused_matches_dense_value(chunk):
    """chunk < V, chunk not dividing V, chunk == V, chunk > V."""
    hidden, wte, labels = _case()
    logits = (hidden @ wte.T).astype(jnp.float32)[None]
    want, want_n = causal_lm_loss(
        jnp.concatenate([logits, logits[:, -1:]], axis=1),  # unshift helper
        jnp.concatenate([jnp.zeros((1, 1), jnp.int32), labels[None]], axis=1))
    got, got_n = fused_linear_cross_entropy(hidden[None], wte, labels[None],
                                            chunk=chunk)
    np.testing.assert_allclose(float(got), float(want), rtol=1e-5)
    assert float(got_n) == float(want_n)


def test_fused_grads_match_dense():
    hidden, wte, labels = _case(V=257, E=8, N=12)

    def dense(h, w):
        logits = jnp.einsum("ne,ve->nv", h, w).astype(jnp.float32)
        logz = jax.nn.logsumexp(logits, axis=-1)
        ll = jnp.take_along_axis(logits, labels[:, None], axis=-1)[..., 0]
        return jnp.mean(logz - ll)

    def fused(h, w):
        loss, _ = fused_linear_cross_entropy(h[None], w, labels[None],
                                             chunk=100)
        return loss

    gd = jax.grad(dense, argnums=(0, 1))(hidden, wte)
    gf = jax.grad(fused, argnums=(0, 1))(hidden, wte)
    for name, a, b in zip(("dhidden", "dwte"), gf, gd):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-4, atol=1e-6, err_msg=name)


def test_fused_respects_loss_mask():
    hidden, wte, labels = _case(N=10)
    mask = jnp.asarray([1, 1, 0, 1, 0, 1, 1, 1, 0, 1], jnp.float32)
    got, n = fused_linear_cross_entropy(hidden[None], wte, labels[None],
                                        mask[None], chunk=64)
    # oracle: per-token CE, masked mean
    logits = (hidden @ wte.T).astype(jnp.float32)
    per = jax.nn.logsumexp(logits, -1) - jnp.take_along_axis(
        logits, labels[:, None], -1)[..., 0]
    want = float(jnp.sum(per * mask) / jnp.sum(mask))
    np.testing.assert_allclose(float(got), want, rtol=1e-5)
    assert float(n) == 7.0


def test_fused_engine_matches_standard_engine():
    """Full model: _fused_lm_loss == _default_lm_loss in value and in the
    training trajectory (same init, same batches, losses track)."""
    model, cfg = gpt2.make_model("tiny")
    params = model.init_params(jax.random.PRNGKey(0), seq_len=16)
    rng = np.random.default_rng(0)
    batch = {"input_ids": jnp.asarray(
        rng.integers(0, cfg.vocab_size, (2, 16)), jnp.int32)}

    l0, n0 = _default_lm_loss(model, params, batch)
    l1, n1 = _fused_lm_loss(model, params, batch)
    np.testing.assert_allclose(float(l1), float(l0), rtol=1e-4)
    assert float(n0) == float(n1)

    std = TrainEngine(model, seq_len=16)
    fus = TrainEngine(model, seq_len=16, fused_loss=True)
    s_std = std.init_state(params=params)
    s_fus = fus.init_state(params=params)
    for i in range(4):
        batch = {"input_ids": jnp.asarray(
            rng.integers(0, cfg.vocab_size, (2, 16)), jnp.int32)}
        s_std, m_std = std.train_step(s_std, batch)
        s_fus, m_fus = fus.train_step(s_fus, batch)
        np.testing.assert_allclose(float(m_fus["loss"]), float(m_std["loss"]),
                                   rtol=5e-4)


def test_fused_engine_on_mesh():
    """fused_loss composes with mesh sharding (same LM task, so the guard
    that rejects custom loss_fn + mesh does not apply)."""
    from distributedtraining_tpu.parallel import MeshConfig, make_mesh

    model, cfg = gpt2.make_model("tiny")
    mesh = make_mesh(MeshConfig(dp=2, fsdp=2))
    engine = TrainEngine(model, mesh=mesh, seq_len=16, fused_loss=True)
    state = engine.init_state(jax.random.PRNGKey(0))
    rng = np.random.default_rng(0)
    batch = engine.place_batch({"input_ids": jnp.asarray(
        rng.integers(0, cfg.vocab_size, (4, 16)), jnp.int32)})
    state, m = engine.train_step(state, batch)
    assert np.isfinite(float(m["loss"]))

    with pytest.raises(ValueError):
        TrainEngine(model, seq_len=16, fused_loss=True,
                    loss_fn=lambda *a: None)


def test_fused_engine_llama():
    """The fused path picks up Llama's untied lm_head automatically — at
    Llama vocab widths the avoided logits tensor is the whole point."""
    from distributedtraining_tpu.models import llama

    model, cfg = llama.make_model("tiny-llama")
    params = model.init_params(jax.random.PRNGKey(0), seq_len=16)
    rng = np.random.default_rng(0)
    batch = {"input_ids": jnp.asarray(
        rng.integers(0, cfg.vocab_size, (2, 16)), jnp.int32)}
    l0, n0 = _default_lm_loss(model, params, batch)
    l1, n1 = _fused_lm_loss(model, params, batch)
    np.testing.assert_allclose(float(l1), float(l0), rtol=1e-4)
    assert float(n0) == float(n1)

    fus = TrainEngine(model, seq_len=16, fused_loss=True)
    state = fus.init_state(params=params)
    state, m = fus.train_step(state, batch)
    assert np.isfinite(float(m["loss"]))


def test_fused_engine_on_sp_mesh():
    """Fused CE composes with sequence parallelism: the hidden states enter
    the loss sharded over sp, and the off-by-one label shift forces a
    reshard GSPMD must handle."""
    from distributedtraining_tpu.ops import ring_attention as ring
    from distributedtraining_tpu.parallel import MeshConfig, make_mesh

    cfg = gpt2.GPT2Config(vocab_size=512, n_positions=32, n_embd=64,
                          n_layer=2, n_head=4, vocab_multiple=128,
                          attention_impl="ring")
    model, cfg = gpt2.make_model(cfg)
    mesh = make_mesh(MeshConfig(dp=2, sp=4))
    try:
        engine = TrainEngine(model, mesh=mesh, seq_len=32, fused_loss=True)
        state = engine.init_state(jax.random.PRNGKey(0))
        rng = np.random.default_rng(0)
        batch = engine.place_batch({"input_ids": jnp.asarray(
            rng.integers(0, cfg.vocab_size, (2, 32)), jnp.int32)})
        state, m = engine.train_step(state, batch)
        assert np.isfinite(float(m["loss"]))
    finally:
        ring.set_ring_mesh(None)


def test_fused_loss_on_lora_engine():
    """config-4 combination: adapter-only training with the tiled-head CE.
    Values match the dense-logits LoRA step (same init, same batch)."""
    from distributedtraining_tpu.engine import LoRAEngine
    from distributedtraining_tpu.models.lora import LoRAConfig

    model, cfg = gpt2.make_model("tiny")
    base = model.init_params(jax.random.PRNGKey(0), seq_len=16)
    lcfg = LoRAConfig(rank=2)
    rng = np.random.default_rng(0)
    batch = {"input_ids": jnp.asarray(
        rng.integers(0, cfg.vocab_size, (2, 16)), jnp.int32)}

    dense = LoRAEngine(model, lcfg, seq_len=16)
    fused = LoRAEngine(model, lcfg, seq_len=16, fused_loss=True)
    b = dense.place_params(base)
    sd = dense.init_state(jax.random.PRNGKey(1), b)
    sf = fused.init_state(jax.random.PRNGKey(1), b)
    for _ in range(3):
        sd, md = dense.train_step(sd, b, batch)
        sf, mf = fused.train_step(sf, b, batch)
    np.testing.assert_allclose(float(mf["loss"]), float(md["loss"]),
                               rtol=1e-3)
    for a, c in zip(jax.tree_util.tree_leaves(sd.params),
                    jax.tree_util.tree_leaves(sf.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(c),
                                   rtol=5e-3, atol=5e-5)


# ---------------------------------------------------------------------------
# Pallas spelling (ops/pallas_ce.py) — runs in interpret mode off-TPU, so
# the same numerics pins apply here; the on-chip execution record lives in
# tests_tpu/test_step_variants_tpu.py.
# ---------------------------------------------------------------------------

def test_pallas_ce_matches_dense_value_and_grads():
    """Forward value and BOTH grads against the materialized-logits oracle,
    with a non-dividing vocab (padding path) and a loss mask."""
    hidden, wte, labels = _case(V=300, E=64, N=24)
    mask = jnp.asarray((np.random.default_rng(1).random(24) > 0.3)
                       .astype(np.float32))

    def dense(h, w):
        logits = jnp.einsum("ne,ve->nv", h, w).astype(jnp.float32)
        logz = jax.nn.logsumexp(logits, axis=-1)
        ll = jnp.take_along_axis(logits, labels[:, None], axis=-1)[..., 0]
        per = logz - ll
        return jnp.sum(per * mask) / jnp.sum(mask)

    def pallas(h, w):
        loss, _ = fused_linear_cross_entropy(h[None], w, labels[None],
                                             mask[None], impl="pallas",
                                             interpret=True)
        return loss

    v0 = dense(hidden, wte)
    v1 = pallas(hidden, wte)
    np.testing.assert_allclose(float(v1), float(v0), rtol=1e-5)
    gd = jax.grad(dense, argnums=(0, 1))(hidden, wte)
    gp = jax.grad(pallas, argnums=(0, 1))(hidden, wte)
    for name, a, b in zip(("dhidden", "dwte"), gp, gd):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-4, atol=1e-6, err_msg=name)


def test_pallas_ce_bf16_hidden_f32_head():
    """The production dtype mix: bf16 activations against the f32 head
    param — dW must come back f32 (accumulated in f32 inside the kernel),
    dh in bf16."""
    hidden, wte, labels = _case(V=256, E=64, N=32, dtype=jnp.bfloat16)
    wte = wte.astype(jnp.float32)

    def pallas(h, w):
        loss, _ = fused_linear_cross_entropy(h[None], w, labels[None],
                                             impl="pallas", interpret=True)
        return loss

    def dense(h, w):
        logits = jnp.einsum("ne,ve->nv", h, w.astype(h.dtype),
                            preferred_element_type=jnp.float32)
        logz = jax.nn.logsumexp(logits, axis=-1)
        ll = jnp.take_along_axis(logits, labels[:, None], axis=-1)[..., 0]
        return jnp.mean(logz - ll)

    gp = jax.grad(pallas, argnums=(0, 1))(hidden, wte)
    gd = jax.grad(dense, argnums=(0, 1))(hidden, wte)
    assert gp[0].dtype == jnp.bfloat16
    assert gp[1].dtype == jnp.float32
    np.testing.assert_allclose(np.asarray(gp[1]), np.asarray(gd[1]),
                               rtol=2e-2, atol=2e-4)
    np.testing.assert_allclose(
        np.asarray(gp[0], np.float32), np.asarray(gd[0], np.float32),
        rtol=5e-2, atol=5e-4)


@pytest.mark.filterwarnings("ignore:pallas fused-CE requested on a non-TPU")
def test_pallas_engine_step_matches_standard():
    """Full train step with fused_loss='pallas' (interpret mode here —
    the engine passes interpret=None, so the off-TPU warning fires and is
    deliberately ignored) tracks the standard engine's loss trajectory."""
    model, cfg = gpt2.make_model("tiny")
    params = model.init_params(jax.random.PRNGKey(0), seq_len=16)
    rng = np.random.default_rng(0)
    std = TrainEngine(model, seq_len=16)
    pal = TrainEngine(model, seq_len=16, fused_loss="pallas")
    s_std = std.init_state(params=params)
    s_pal = pal.init_state(params=params)
    for _ in range(3):
        batch = {"input_ids": jnp.asarray(
            rng.integers(0, cfg.vocab_size, (2, 16)), jnp.int32)}
        s_std, m_std = std.train_step(s_std, batch)
        s_pal, m_pal = pal.train_step(s_pal, batch)
        np.testing.assert_allclose(float(m_pal["loss"]),
                                   float(m_std["loss"]), rtol=5e-4)


@pytest.mark.filterwarnings("ignore:pallas fused-CE")
def test_pallas_engine_on_mesh_matches_scan(devices):
    """fused_loss='pallas' on a dp x fsdp x tp mesh (the shard_map
    spelling, interpret mode here): full jitted train step tracks the
    GSPMD-partitioned scan spelling on the same mesh — the composition
    VERDICT r3 named as the missing piece (flagship kernel x flagship
    parallelism)."""
    import dataclasses

    import optax

    from distributedtraining_tpu.parallel import MeshConfig, make_mesh

    cfg = dataclasses.replace(gpt2.PRESETS["tiny"], n_embd=128, n_head=4,
                              dtype="float32")
    model, _ = gpt2.make_model(cfg)
    mesh = make_mesh(MeshConfig(dp=2, fsdp=2, tp=2))
    p = model.init_params(jax.random.PRNGKey(0), seq_len=16)
    # sgd: params diff == grad diff (no Adam sign-amplification on
    # near-zero grads; see tests_tpu/test_step_variants_tpu.py)
    pal = TrainEngine(model, mesh=mesh, seq_len=16, fused_loss="pallas",
                      optimizer=optax.sgd(1.0))
    scn = TrainEngine(model, mesh=mesh, seq_len=16, fused_loss="scan",
                      optimizer=optax.sgd(1.0))
    s_pal = pal.init_state(params=p)
    s_scn = scn.init_state(params=p)
    rng = np.random.default_rng(0)
    batch = {"input_ids": jnp.asarray(
        rng.integers(0, cfg.vocab_size, (4, 16)), jnp.int32)}
    s_pal, m_pal = pal.train_step(s_pal, pal.place_batch(batch))
    s_scn, m_scn = scn.train_step(s_scn, scn.place_batch(batch))
    np.testing.assert_allclose(float(m_pal["loss"]), float(m_scn["loss"]),
                               rtol=1e-5)
    assert float(m_pal["tokens"]) == float(m_scn["tokens"])
    for a, b in zip(jax.tree_util.tree_leaves(s_pal.params),
                    jax.tree_util.tree_leaves(s_scn.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-6)


def test_fused_on_unknown_mesh_axis_falls_back(devices, caplog):
    """fused_loss on a mesh with an axis outside dp/fsdp/tp/sp falls back
    to the unfused loss with a warning instead of refusing to construct:
    the fused path is a perf lever, and a role wired onto a research mesh
    should run correct-but-unfused rather than fail to boot. Nothing
    psums over the wrong axis set because the fused spelling never
    engages at all."""
    import logging as _logging

    import numpy as _np
    from jax.sharding import Mesh

    model, _ = gpt2.make_model("tiny")
    # the standard axes must exist (the logical sharding rules reference
    # them); the size->1 exotic 'ep' axis is what trips the fused check
    mesh = Mesh(_np.array(jax.devices()[:4]).reshape(2, 1, 1, 1, 2),
                ("dp", "fsdp", "sp", "tp", "ep"))
    with caplog.at_level(_logging.WARNING,
                         logger="distributedtraining_tpu.engine.train"):
        engine = TrainEngine(model, mesh=mesh, seq_len=16,
                             fused_loss="pallas")
    assert any("falling back to the unfused" in r.getMessage()
               for r in caplog.records)
    # the resolved loss is the plain (materialized-logits) spelling
    assert engine._task_loss is not None


@pytest.mark.filterwarnings("ignore:pallas fused-CE")
def test_pallas_engine_on_sp_mesh_matches_scan(devices):
    """fused_loss='pallas' on a dp x sp (ring attention) mesh: the mesh
    spelling shifts the LABELS instead of slicing the hidden states, so
    sequence shards carry no cross-shard dependency and the flagship
    kernel composes with the long-context path too."""
    import dataclasses

    import optax

    from distributedtraining_tpu.ops import ring_attention as ring
    from distributedtraining_tpu.parallel import MeshConfig, make_mesh

    cfg = dataclasses.replace(gpt2.PRESETS["tiny"], n_embd=128, n_head=4,
                              dtype="float32", attention_impl="ring",
                              n_positions=32)
    model, _ = gpt2.make_model(cfg)
    mesh = make_mesh(MeshConfig(dp=2, sp=4))
    try:
        p = model.init_params(jax.random.PRNGKey(0), seq_len=32)
        pal = TrainEngine(model, mesh=mesh, seq_len=32,
                          fused_loss="pallas", optimizer=optax.sgd(1.0))
        scn = TrainEngine(model, mesh=mesh, seq_len=32,
                          fused_loss="scan", optimizer=optax.sgd(1.0))
        s_pal = pal.init_state(params=p)
        s_scn = scn.init_state(params=p)
        rng = np.random.default_rng(0)
        batch = {"input_ids": jnp.asarray(
            rng.integers(0, cfg.vocab_size, (4, 32)), jnp.int32)}
        s_pal, m_pal = pal.train_step(s_pal, pal.place_batch(batch))
        s_scn, m_scn = scn.train_step(s_scn, scn.place_batch(batch))
        np.testing.assert_allclose(float(m_pal["loss"]),
                                   float(m_scn["loss"]), rtol=1e-5)
        assert float(m_pal["tokens"]) == float(m_scn["tokens"])
        for a, b in zip(jax.tree_util.tree_leaves(s_pal.params),
                        jax.tree_util.tree_leaves(s_scn.params)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-4, atol=1e-6)
    finally:
        ring.set_ring_mesh(None)


def test_fused_auto_selects_scan_off_tpu():
    """impl='auto' must not route through the Pallas kernels on a CPU
    backend (interpret mode is for tests; production fallback is scan)."""
    from distributedtraining_tpu.ops.pallas_ce import pallas_ce_available
    hidden, wte, _ = _case(V=256, E=128, N=16)
    assert pallas_ce_available(hidden, wte) is False


def test_pallas_explicit_off_tpu_warns():
    """Explicit impl='pallas' off-TPU without an interpret override must
    warn: interpret mode is orders of magnitude slower than the scan
    fallback the caller thinks they chose (round-3 advisor)."""
    hidden, wte, labels = _case(V=256, E=64, N=16)
    with pytest.warns(UserWarning, match="INTERPRET"):
        fused_linear_cross_entropy(hidden[None], wte, labels[None],
                                   impl="pallas")
    # an explicit acknowledgement is silent
    import warnings as _w
    with _w.catch_warnings():
        _w.simplefilter("error")
        fused_linear_cross_entropy(hidden[None], wte, labels[None],
                                   impl="pallas", interpret=True)
