"""Concurrent revision-aware delta ingest (engine/ingest.py).

Pins the ISSUE-4 contracts: the content-addressed host cache (hit on an
unchanged revision, invalidation on a new one, LRU eviction under the
byte budget), batched-screen parity with the per-miner ``screen_delta``,
span-context propagation into the pool's worker threads, and a
concurrent-fetch round trip over the localfs transport that downloads
each artifact exactly once per revision.
"""

import threading
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributedtraining_tpu import delta as delta_lib
from distributedtraining_tpu.engine.ingest import (DeltaCache, DeltaIngestor,
                                                   IngestPool, tree_nbytes)
from distributedtraining_tpu.models import gpt2
from distributedtraining_tpu.transport import (InMemoryTransport,
                                               LocalFSTransport)
from distributedtraining_tpu.utils import obs


@pytest.fixture(scope="module")
def base():
    model, cfg = gpt2.make_model("tiny")
    return model.init_params(jax.random.PRNGKey(0))


def _delta(base, scale, seed=0):
    key = jax.random.PRNGKey(seed)
    leaves, treedef = jax.tree_util.tree_flatten(base)
    ks = jax.random.split(key, len(leaves))
    return jax.tree_util.tree_unflatten(
        treedef, [scale * jax.random.normal(k, l.shape, l.dtype)
                  for k, l in zip(ks, leaves)])


def _host_template(base):
    return jax.tree_util.tree_map(lambda x: np.zeros(x.shape, x.dtype), base)


# ---------------------------------------------------------------------------
# DeltaCache
# ---------------------------------------------------------------------------

def test_cache_hit_on_unchanged_revision(base):
    cache = DeltaCache(1 << 30)
    d = jax.device_get(_delta(base, 0.01))
    cache.put("m0", "rev1", delta=d, reason="ok", cid="m0-000001")
    e = cache.lookup("m0", "rev1")
    assert e is not None and e.reason == "ok" and e.cid == "m0-000001"
    assert e.delta is d
    # a different miner or a different revision is never served
    assert cache.lookup("m1", "rev1") is None
    assert cache.lookup("m0", "rev2") is None


def test_cache_invalidation_on_new_revision(base):
    cache = DeltaCache(1 << 30)
    d1 = jax.device_get(_delta(base, 0.01, seed=1))
    d2 = jax.device_get(_delta(base, 0.02, seed=2))
    cache.put("m0", "rev1", delta=d1)
    before = cache.nbytes
    cache.put("m0", "rev2", delta=d2)   # new push REPLACES the old entry
    assert cache.lookup("m0", "rev1") is None
    assert cache.lookup("m0", "rev2").delta is d2
    assert len(cache) == 1              # one entry per hotkey, ever
    assert cache.nbytes == before       # old bytes released


def test_cache_lru_eviction_under_byte_budget(base):
    d = jax.device_get(_delta(base, 0.01))
    one = tree_nbytes(d)
    cache = DeltaCache(int(2.5 * one))   # room for two entries
    cache.put("m0", "r", delta=d)
    cache.put("m1", "r", delta=d)
    assert cache.lookup("m0", "r") is not None   # m0 is now most-recent
    cache.put("m2", "r", delta=d)                # evicts the LRU = m1
    assert cache.lookup("m1", "r") is None
    assert cache.lookup("m0", "r") is not None
    assert cache.lookup("m2", "r") is not None
    assert cache.nbytes <= cache.max_bytes
    # an entry bigger than the whole budget is refused, not thrashed
    small = DeltaCache(one // 2)
    small.put("m9", "r", delta=d)
    assert small.lookup("m9", "r") is None and small.nbytes == 0


def test_cache_disabled_and_negative_entries(base):
    off = DeltaCache(0)
    off.put("m0", "r", delta=jax.device_get(_delta(base, 0.01)))
    assert off.lookup("m0", "r") is None
    cache = DeltaCache(1 << 20)
    cache.put("m0", "r", delta=None, reason="nonfinite")
    e = cache.lookup("m0", "r")
    assert e.delta is None and e.reason == "nonfinite"
    assert cache.nbytes == 0


# ---------------------------------------------------------------------------
# Batched screening parity
# ---------------------------------------------------------------------------

def test_screen_deltas_parity_with_screen_delta(base):
    host = _host_template(base)
    good = jax.device_get(_delta(base, 0.01, seed=3))
    big = jax.tree_util.tree_map(lambda x: np.full(x.shape, 2e3, x.dtype),
                                 host)
    nan = jax.tree_util.tree_map(
        lambda x: np.full(x.shape, np.nan, x.dtype), host)
    bf16 = jax.tree_util.tree_map(
        lambda x: np.asarray(x, jnp.bfloat16), good)      # wire spelling
    f64 = jax.tree_util.tree_map(
        lambda x: np.asarray(x, np.float64), good)        # must reject
    wrong_shape = jax.tree_util.tree_map(
        lambda x: np.zeros(x.shape + (1,), x.dtype), host)
    cohort = [good, big, nan, bf16, f64, wrong_shape]
    batched = delta_lib.screen_deltas(cohort, host, max_abs=1e3)
    serial = [delta_lib.screen_delta(d, host, max_abs=1e3) for d in cohort]
    for (bok, brea), (sok, srea) in zip(batched, serial):
        assert bok == sok
        assert brea.split("(")[0] == srea.split("(")[0]
    assert [ok for ok, _ in batched] == [True, False, False, True, False,
                                         False]
    assert batched[1][1].startswith("magnitude_exceeded")
    assert batched[2][1] == "nonfinite"
    assert batched[4][1] == "shape_mismatch"
    assert batched[5][1] == "shape_mismatch"
    # max_abs disabled spellings (None and <= 0) pass the big delta
    for cap in (None, 0):
        assert delta_lib.screen_deltas([big], host, max_abs=cap)[0][0]


def test_screen_deltas_chunking_covers_long_cohorts(base):
    host = _host_template(base)
    cohort = [jax.device_get(_delta(base, 0.01, seed=i)) for i in range(11)]
    cohort[7] = jax.tree_util.tree_map(
        lambda x: np.full(x.shape, np.inf, x.dtype), host)
    out = delta_lib.screen_deltas(cohort, host, max_abs=1e3, chunk=4)
    assert len(out) == 11
    assert [i for i, (ok, _) in enumerate(out) if not ok] == [7]
    assert out[7][1] == "nonfinite"


# ---------------------------------------------------------------------------
# IngestPool
# ---------------------------------------------------------------------------

def test_pool_preserves_order_and_parallelizes():
    pool = IngestPool(4)
    try:
        t0 = time.perf_counter()
        out = pool.map(lambda x: (time.sleep(0.1), x * 2)[1], list(range(4)))
        dt = time.perf_counter() - t0
        assert out == [0, 2, 4, 6]
        assert dt < 0.35, f"4x0.1s of sleep took {dt:.2f}s — not concurrent"
    finally:
        pool.close()


def test_pool_serial_modes_run_inline():
    pool = IngestPool(1)
    main = threading.get_ident()
    seen = []
    assert pool.map(lambda x: seen.append(threading.get_ident()) or x,
                    [1, 2]) == [1, 2]
    assert set(seen) == {main}          # workers==1: no cross-thread hop
    assert pool.map(lambda x: x, [5]) == [5]   # single item: inline too
    assert pool.alive_workers() == 0
    pool.close()


def test_pool_propagates_span_context(tmp_path):
    """Satellite: spans opened inside pool workers keep the submitting
    thread's parent nesting and correlation id (obs.capture_context /
    use_context) — concurrent avg.fetch spans stay joinable on cid."""
    from distributedtraining_tpu.utils.metrics import JSONLSink

    path = str(tmp_path / "spans.jsonl")
    sink = JSONLSink(path)
    obs.configure(sink, role="test")
    pool = IngestPool(3)
    try:
        def inner(i):
            with obs.span(f"inner_{i}"):
                return None

        with obs.correlate("cid-xyz"):
            with obs.span("outer"):
                pool.map(inner, [0, 1])

        def work(i):
            with obs.span("worker_fetch", miner=f"m{i}"):
                return threading.current_thread().name

        with obs.span("outer2"):
            names = pool.map(work, [0, 1, 2])
        assert any(n.startswith("ingest-worker-") for n in names)
    finally:
        pool.close()
        obs.reset()
        sink.close()
    import json
    recs = [json.loads(l) for l in open(path)]
    fetch = [r for r in recs if r.get("span") == "worker_fetch"]
    assert len(fetch) == 3
    for r in fetch:
        assert r["parent"] == "outer2", r   # nesting crossed the thread
        assert r["depth"] == 1, r
    inner = [r for r in recs if str(r.get("span", "")).startswith("inner_")]
    assert inner and all(r.get("cid") == "cid-xyz" for r in inner)


def test_pool_reraises_worker_exception_and_workers_idle_out():
    pool = IngestPool(2, idle_timeout=0.2)

    def boom(x):
        if x == 1:
            raise ValueError("job 1 failed")
        return x

    with pytest.raises(ValueError, match="job 1 failed"):
        pool.map(boom, [0, 1, 2])
    deadline = time.monotonic() + 3.0
    while pool.alive_workers() and time.monotonic() < deadline:
        time.sleep(0.05)
    assert pool.alive_workers() == 0, "workers did not idle out"
    # the pool is reusable after an idle-out AND after close()
    assert pool.map(lambda x: x + 1, [1, 2]) == [2, 3]
    pool.close()
    assert pool.map(lambda x: x, [7, 8]) == [7, 8]
    pool.close()


# ---------------------------------------------------------------------------
# DeltaIngestor round trips
# ---------------------------------------------------------------------------

class _CountingFS(LocalFSTransport):
    """localfs with download/probe accounting and optional fetch latency."""

    def __init__(self, root, latency=0.0):
        super().__init__(root)
        self.latency = latency
        self.downloads = []
        self.probes = 0

    def fetch_delta_bytes(self, miner_id):
        if self.latency:
            time.sleep(self.latency)
        self.downloads.append(miner_id)
        return super().fetch_delta_bytes(miner_id)

    def delta_revision(self, miner_id):
        self.probes += 1
        return super().delta_revision(miner_id)


def _publish_fleet(transport, base, n=4, scale=0.01):
    deltas = []
    for i in range(n):
        d = jax.device_get(_delta(base, scale, seed=10 + i))
        transport.publish_delta(f"m{i}", d)
        transport.publish_delta_meta(
            f"m{i}", {"base_revision": "base-r1", "delta_id": f"m{i}-000001"})
        deltas.append(d)
    return deltas


def test_concurrent_localfs_round_trip_downloads_once_per_revision(
        base, tmp_path):
    host = _host_template(base)
    transport = _CountingFS(str(tmp_path), latency=0.05)
    _publish_fleet(transport, base, n=4)
    ing = DeltaIngestor(transport, host, workers=4, max_delta_abs=1e3)
    try:
        hotkeys = [f"m{i}" for i in range(4)] + ["ghost"]
        t0 = time.perf_counter()
        staged = ing.stage(hotkeys, base_revision="base-r1")
        cold = time.perf_counter() - t0
        assert [s.hotkey for s in staged] == hotkeys          # input order
        assert [s.reason for s in staged] == ["ok"] * 4 + ["no_delta"]
        assert all(s.cid == f"m{i}-000001"
                   for i, s in enumerate(staged[:4]))
        assert sorted(transport.downloads) == ["m0", "m1", "m2", "m3"]
        assert cold < 4 * 0.05 + 0.1, \
            f"cold stage not concurrent: {cold:.2f}s"
        # -- warm round: revisions unchanged -> ZERO artifact downloads ---
        transport.downloads.clear()
        warm = ing.stage(hotkeys, base_revision="base-r1")
        assert [s.reason for s in warm] == ["ok"] * 4 + ["no_delta"]
        assert all(s.cached for s in warm[:4])
        assert transport.downloads == []
        # byte-identical to the cold round's accepted deltas
        for a, b in zip(staged[:4], warm[:4]):
            assert all(np.array_equal(np.asarray(x), np.asarray(y))
                       for x, y in zip(jax.tree_util.tree_leaves(a.delta),
                                       jax.tree_util.tree_leaves(b.delta)))
        # -- one miner re-pushes: only that artifact is re-downloaded ----
        transport.publish_delta(
            "m2", jax.device_get(_delta(base, 0.03, seed=99)))
        third = ing.stage(hotkeys, base_revision="base-r1")
        assert transport.downloads == ["m2"]
        assert [s.cached for s in third[:4]] == [True, True, False, True]
        assert all(s.reason == "ok" for s in third[:4])
    finally:
        ing.close()


def test_stale_skip_avoids_download_and_recovers(base, tmp_path):
    host = _host_template(base)
    transport = _CountingFS(str(tmp_path))
    _publish_fleet(transport, base, n=2)
    ing = DeltaIngestor(transport, host, stale_deltas="skip", workers=2)
    try:
        # rider names base-r1; the receiver sits at base-r2 -> stale, and
        # the full-model artifact is NEVER downloaded
        staged = ing.stage(["m0", "m1"], base_revision="base-r2")
        assert [s.reason for s in staged] == ["stale_base"] * 2
        assert transport.downloads == []
        # matching base: accepted, fetched now (rider-only entry upgrades)
        staged = ing.stage(["m0", "m1"], base_revision="base-r1")
        assert [s.reason for s in staged] == ["ok"] * 2
        assert sorted(transport.downloads) == ["m0", "m1"]
        # riderless submissions are never stale
        transport.publish_delta(
            "bare", jax.device_get(_delta(base, 0.01, seed=5)))
        (s,) = ing.stage(["bare"], base_revision="base-r2")
        assert s.reason == "ok"
    finally:
        ing.close()


def test_ingestor_isolates_per_miner_failures(base):
    host = _host_template(base)

    class Flaky(InMemoryTransport):
        def fetch_delta_bytes(self, miner_id):
            if miner_id == "cursed":
                raise OSError("transport exploded")
            return super().fetch_delta_bytes(miner_id)

    t = Flaky()
    d = jax.device_get(_delta(base, 0.01))
    t.publish_delta("good", d)
    t.publish_delta("cursed", d)
    ing = DeltaIngestor(t, host, workers=2)
    try:
        staged = ing.stage(["good", "cursed"])
        assert {s.hotkey: s.reason for s in staged} == {
            "good": "ok", "cursed": "fetch_error"}
    finally:
        ing.close()


def test_ingestor_screen_caches_negative_verdicts(base):
    host = _host_template(base)
    t = InMemoryTransport()
    nan = jax.tree_util.tree_map(
        lambda x: np.full(x.shape, np.nan, x.dtype), host)
    t.publish_delta("m0", nan)
    fetches = []
    orig = t.fetch_delta_bytes
    t.fetch_delta_bytes = lambda h: fetches.append(h) or orig(h)
    ing = DeltaIngestor(t, host, workers=1)
    try:
        assert ing.stage(["m0"])[0].reason == "nonfinite"
        assert fetches == ["m0"]
        # same revision: the screened-out verdict is served from cache —
        # a hostile artifact costs one decode per revision, not per round
        assert ing.stage(["m0"])[0].reason == "nonfinite"
        assert fetches == ["m0"]
    finally:
        ing.close()
