"""Serving plane (engine/serve.py): continuous-batching generation with a
paged KV cache and hot-swapped base weights.

The correctness spine is the greedy-parity pin: every engine output must
be token-identical to ``reference_generate`` — a full model forward of
the growing sequence per token, no cache, no padding — for the pinned
prompts, before and across a hot-swap boundary. Everything else (paging,
bucket padding, preemption, swap policies, chaos degradation) is then
tested as "still token-identical under X".
"""

import json
import os
import urllib.request

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributedtraining_tpu.engine.serve import (BaseRevisionWatcher,
                                                  BucketLadder,
                                                  GenerationEngine,
                                                  ServeHTTPFrontend,
                                                  ServeLoop,
                                                  host_param_template,
                                                  reference_generate)
from distributedtraining_tpu.models import gpt2, llama
from distributedtraining_tpu.transport import InMemoryTransport
from distributedtraining_tpu.utils import obs

# f32 keeps the argmax parity pin numerically honest (bf16 near-ties can
# flip between the cached and full-recompute spellings); serving real
# bf16 models is a throughput choice, not a correctness contract
TINY = gpt2.GPT2Config(vocab_size=128, n_positions=64, n_embd=32,
                       n_layer=2, n_head=2, dtype="float32",
                       vocab_multiple=64)

GEN = 8  # tokens generated per request in most tests

# the eager reference loop is the slow half of every parity pin; the
# pinned (params, prompt, n) oracles are deterministic, so share them
# across tests instead of re-deriving per test
_REF_CACHE: dict = {}


@pytest.fixture(scope="module")
def setup():
    model, cfg = gpt2.make_model(TINY)
    params1 = model.init_params(jax.random.PRNGKey(0), seq_len=8)
    params2 = model.init_params(jax.random.PRNGKey(7), seq_len=8)
    rng = np.random.RandomState(0)
    prompts = [[int(t) for t in rng.randint(0, cfg.vocab_size, size=n)]
               for n in (5, 11, 3, 17)]
    return model, cfg, params1, params2, prompts


@pytest.fixture()
def sink():
    class _Sink:
        def __init__(self):
            self.records = []

        def log(self, rec, **kw):
            self.records.append(rec)

    s = _Sink()
    obs.configure(s, role="server")
    try:
        yield s
    finally:
        obs.reset()


def refs_for(model, params, prompts, n=GEN):
    out = []
    for p in prompts:
        key = (id(model), id(params), tuple(p), n)
        if key not in _REF_CACHE:
            _REF_CACHE[key] = reference_generate(model, params, p, n)
        out.append(_REF_CACHE[key])
    return out


# ---------------------------------------------------------------------------
# Greedy parity
# ---------------------------------------------------------------------------

def test_greedy_parity_continuous_batch(setup):
    """Mixed-length prompts decoded as one rolling batch are
    token-identical to the reference loop, per request."""
    model, cfg, params, _, prompts = setup
    # fewer slots than requests: the scheduler admits as slots free up
    eng = GenerationEngine(model, params, max_slots=2, page_size=8)
    try:
        assert eng.generate(prompts, GEN) == refs_for(model, params, prompts)
        assert eng.tokens_emitted == GEN * len(prompts)
    finally:
        eng.close()


def test_paged_equals_contiguous(setup):
    """Paged KV (small pages, gathered per step) vs a contiguous cache
    (one page holds the whole sequence): identical outputs — paging is a
    memory layout, not a math change."""
    model, cfg, params, _, prompts = setup
    paged = GenerationEngine(model, params, max_slots=2, page_size=8)
    contiguous = GenerationEngine(model, params, max_slots=2, page_size=64)
    try:
        assert contiguous.pages_per_slot == 1
        out_p = paged.generate(prompts, GEN)
        out_c = contiguous.generate(prompts, GEN)
        assert out_p == out_c == refs_for(model, params, prompts)
    finally:
        paged.close()
        contiguous.close()


def test_llama_gqa_parity():
    """The Llama path: GQA cache stores n_kv_head heads and broadcasts
    at decode; rotary positions come from the slot's sequence length."""
    cfg = llama.LlamaConfig(vocab_size=128, max_seq_len=64, n_embd=32,
                            n_layer=2, n_head=4, n_kv_head=2,
                            intermediate_size=64, remat=False,
                            dtype="float32", vocab_multiple=64)
    model, cfg = llama.make_model(cfg)
    params = model.init_params(jax.random.PRNGKey(3), seq_len=8)
    rng = np.random.RandomState(1)
    prompts = [list(rng.randint(0, cfg.vocab_size, size=n)) for n in (4, 9)]
    eng = GenerationEngine(model, params, max_slots=2, page_size=8)
    try:
        assert eng.generate(prompts, 6) == refs_for(model, params, prompts, 6)
        # the cache really is GQA-narrow
        assert eng._kv[0].shape[-2] == cfg.n_kv_head
    finally:
        eng.close()


def test_eos_stops_generation(setup):
    model, cfg, params, _, prompts = setup
    ref = reference_generate(model, params, prompts[0], GEN)
    eos = ref[0]
    eng = GenerationEngine(model, params, max_slots=2, page_size=8,
                           eos_id=eos)
    try:
        [out] = eng.generate([prompts[0]], GEN)
        assert out == reference_generate(model, params, prompts[0], GEN,
                                         eos_id=eos)
        assert out[-1] == eos and len(out) < GEN
    finally:
        eng.close()


def test_submit_validation(setup):
    model, cfg, params, _, _ = setup
    eng = GenerationEngine(model, params, max_slots=2, page_size=8)
    try:
        with pytest.raises(ValueError):
            eng.submit([])
        with pytest.raises(ValueError):
            eng.submit(list(range(60)), max_new_tokens=20)  # > max_seq_len
    finally:
        eng.close()


# ---------------------------------------------------------------------------
# Bucket ladder / no-retrace
# ---------------------------------------------------------------------------

def test_bucket_ladder_shape():
    lad = BucketLadder(8, prefer_compiled=False)
    assert lad.buckets == (1, 2, 4, 8)
    assert [lad.bucket_for(n) for n in (1, 2, 3, 5, 8)] == [1, 2, 4, 8, 8]
    assert lad.bucket_for(9) == 16  # beyond top: multiples of top
    lad2 = BucketLadder(8, prefer_compiled=True)
    lad2.mark(8)
    assert lad2.bucket_for(3) == 8  # pads up to the compiled bucket


def test_steady_state_zero_fresh_compiles(setup, sink):
    """The acceptance pin: after one warm batch, a second identical load
    adds ZERO fresh compiles — compile.ms count and the serve bucket
    counters stay flat (the PR-8 no-retrace discipline on the decode
    ladder)."""
    model, cfg, params, _, prompts = setup
    eng = GenerationEngine(model, params, max_slots=4, page_size=8)
    try:
        refs = refs_for(model, params, prompts)
        assert eng.generate(prompts, GEN) == refs     # warm the ladders
        reg = obs.registry()
        before = (reg.histogram("compile.ms").count,
                  reg.counter("serve.decode_bucket_compiles").value,
                  reg.counter("serve.prefill_bucket_compiles").value)
        assert eng.generate(prompts, GEN) == refs     # steady state
        after = (reg.histogram("compile.ms").count,
                 reg.counter("serve.decode_bucket_compiles").value,
                 reg.counter("serve.prefill_bucket_compiles").value)
        assert after == before, f"steady-state decode compiled: " \
                                f"{before} -> {after}"
    finally:
        eng.close()


def test_prefer_compiled_pads_partial_batch(setup):
    """A partial batch after a full one reuses the compiled full-batch
    program (padding waste) instead of compiling the exact fit."""
    model, cfg, params, _, prompts = setup
    eng = GenerationEngine(model, params, max_slots=4, page_size=8)
    try:
        eng.generate(prompts[:4], GEN)
        keys = set(eng._decode_progs)
        eng.generate(prompts[:2], GEN)       # 2 active: pads up to 4
        assert set(eng._decode_progs) == keys
    finally:
        eng.close()


# ---------------------------------------------------------------------------
# Hot swap
# ---------------------------------------------------------------------------

def test_hot_swap_drain_parity_across_boundary(setup, sink):
    """Under the drain policy a request admitted before the swap finishes
    on the revision it started on; one admitted after decodes on the new
    revision — both token-identical to their revision's reference loop,
    and each response is stamped with the revision that produced it."""
    model, cfg, params1, params2, prompts = setup
    tr = InMemoryTransport()
    rev1 = tr.publish_base(params1)
    watcher = BaseRevisionWatcher(tr, lambda: host_param_template(model),
                                  poll_s=999.0)
    assert watcher.poll_once()
    staged = watcher.take_pending()
    eng = GenerationEngine(model, watcher=watcher, max_slots=2, page_size=8,
                           swap_policy="drain")
    eng.install_params(staged[1], revision=staged[0])
    try:
        ra = eng.submit(prompts[0], GEN)
        for _ in range(3):
            eng.step()
        rev2 = tr.publish_base(params2)
        assert watcher.poll_once()           # stages the new revision
        rb = eng.submit(prompts[1], GEN)
        while not (ra.done_evt.is_set() and rb.done_evt.is_set()):
            eng.step()
        assert [ra.tokens] == refs_for(model, params1, prompts[:1])
        assert ra.revision == rev1
        assert [rb.tokens] == refs_for(model, params2, prompts[1:2])
        assert rb.revision == rev2
        reg = obs.registry()
        assert reg.counter("serve.swaps").value == 1
        # the stall the decode loop actually paused for is a pointer
        # rebind — well under one decode step
        stall = reg.histogram("serve.swap_stall_ms").percentiles((95.0,))
        step = reg.histogram("serve.step_ms").percentiles((95.0,))
        assert stall["p95"] < step["p95"]
    finally:
        eng.close()


def test_hot_swap_restart_regenerates_on_new_revision(setup):
    model, cfg, params1, params2, prompts = setup
    eng = GenerationEngine(model, params1, revision="r1", max_slots=2,
                           page_size=8, swap_policy="restart")
    try:
        req = eng.submit(prompts[0], GEN)
        for _ in range(3):
            eng.step()
        assert req.tokens  # mid-stream
        eng._pending_swap = ("r2", jax.device_put(params2))
        while not req.done_evt.is_set():
            eng.step()
        assert [req.tokens] == refs_for(model, params2, prompts[:1])
        assert req.revision == "r2"
    finally:
        eng.close()


def test_chaos_fetch_degrades_to_current_base(setup, sink):
    """A failed/torn revision fetch must degrade to the current base,
    never stall the batch: with every transport fetch failing, the
    watcher counts failures and generation proceeds bit-identically on
    the old revision."""
    from distributedtraining_tpu.transport.chaos import (ChaosSpec,
                                                         ChaosTransport)
    model, cfg, params1, params2, prompts = setup
    inner = InMemoryTransport()
    rev1 = inner.publish_base(params1)
    chaotic = ChaosTransport(inner, ChaosSpec(fetch_error_rate=1.0, seed=3),
                             role="server")
    watcher = BaseRevisionWatcher(chaotic,
                                  lambda: host_param_template(model),
                                  poll_s=999.0)
    eng = GenerationEngine(model, params1, revision=rev1, max_slots=2,
                           page_size=8, watcher=watcher)
    try:
        inner.publish_base(params2)          # a new revision exists...
        assert not watcher.poll_once()       # ...but every fetch fails
        out = eng.generate(prompts[:2], GEN)
        assert out == refs_for(model, params1, prompts[:2])
        assert eng.revision == rev1
        assert obs.registry().counter(
            "serve.swap_fetch_failures").value >= 1
        assert obs.registry().counter("serve.swaps").value == 0
    finally:
        eng.close()


def test_watcher_thread_lifecycle(setup):
    model, cfg, params1, _, _ = setup
    tr = InMemoryTransport()
    tr.publish_base(params1)
    watcher = BaseRevisionWatcher(tr, lambda: host_param_template(model),
                                  poll_s=0.01)
    watcher.start()
    try:
        import time
        deadline = time.monotonic() + 5.0
        while watcher.take_pending() is None:
            assert time.monotonic() < deadline, "watcher never staged"
            time.sleep(0.01)
    finally:
        watcher.close()


# ---------------------------------------------------------------------------
# Paging pressure
# ---------------------------------------------------------------------------

def test_preemption_under_page_pressure(setup, sink):
    """An undersized pool forces preemption; preempted requests requeue
    and regenerate identically (greedy decode is deterministic), and the
    engine records that it happened."""
    model, cfg, params, _, _ = setup
    rng = np.random.RandomState(5)
    prompts = [list(rng.randint(0, cfg.vocab_size, size=10))
               for _ in range(3)]
    eng = GenerationEngine(model, params, max_slots=2, page_size=8,
                           max_seq_len=32, pool_pages=6)
    try:
        assert eng.generate(prompts, 16) == refs_for(model, params,
                                                     prompts, 16)
        assert obs.registry().counter("serve.preempted").value >= 1
    finally:
        eng.close()


# ---------------------------------------------------------------------------
# Metrics / exporter / fleet report
# ---------------------------------------------------------------------------

def test_serve_ttft_tpot_histograms(setup, sink):
    """Request-level latency observability: TTFT (queue admit -> first
    token, one sample per finished admission) and TPOT (the wall gap
    between a slot's consecutive tokens) land as registry histograms and
    export as dt_serve_ttft_ms_* / dt_serve_tpot_ms_* gauges."""
    from distributedtraining_tpu.utils import obs_http
    model, cfg, params, _, prompts = setup
    eng = GenerationEngine(model, params, max_slots=4, page_size=8)
    try:
        outs = eng.generate(prompts[:3], GEN)
        reg = obs.registry()
        ttft = reg.histogram("serve.ttft_ms")
        tpot = reg.histogram("serve.tpot_ms")
        # one TTFT sample per request; TPOT covers every non-first token
        assert ttft.count == 3
        assert tpot.count == sum(len(o) for o in outs) - 3
        assert ttft.percentiles((95.0,))["p95"] >= 0.0
        text = obs_http.render()
        assert "dt_serve_ttft_ms_p95" in text
        assert "dt_serve_tpot_ms_p95" in text
    finally:
        eng.close()


def test_fleet_report_ttft_tpot_columns(tmp_path):
    """The serving-latency heartbeat extras reach the fleet table as
    ttft95/tpot95 columns (scripts/fleet_report.py)."""
    import sys
    sys.path.insert(0, os.path.join(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))), "scripts"))
    import fleet_report
    path = tmp_path / "monitor.jsonl"
    path.write_text(json.dumps(
        {"heartbeat": {"hb": 1, "role": "server", "hotkey": "hk-s",
                       "seq": 3, "t": 9.0, "tokens_per_sec": 88.5,
                       "ttft_ms_p95": 41.25, "tpot_ms_p95": 7.5,
                       "steps": 100.0}}) + "\n")
    rep = fleet_report.build_report([str(path)])
    table = fleet_report.format_table(rep)
    assert "ttft95" in fleet_report.COLUMNS
    assert "tpot95" in fleet_report.COLUMNS
    assert "41.2" in table and "7.5" in table


def test_serve_metrics_reach_prometheus_exporter(setup, sink):
    from distributedtraining_tpu.utils import obs_http
    model, cfg, params, _, prompts = setup
    eng = GenerationEngine(model, params, max_slots=2, page_size=8)
    try:
        eng.generate(prompts[:2], GEN)
        text = obs_http.render()
        for needle in ("dt_serve_tokens ", "dt_serve_step_ms_p95",
                       "dt_serve_tokens_per_sec", "dt_serve_queue_depth",
                       "dt_compile_ms_count"):
            assert needle in text, f"{needle} missing from exposition"
    finally:
        eng.close()


def test_server_heartbeat_carries_served_revision(setup):
    """The server's vitals ride the standard heartbeat schema: the
    served revision via the protocol's base_revision field, tokens/sec
    as a numeric extra — parse_heartbeat keeps both for the fleet
    ledger."""
    from distributedtraining_tpu.engine.health import (Vitals,
                                                       build_heartbeat,
                                                       parse_heartbeat)
    vit = Vitals(steps=lambda: 42.0,
                 counters=lambda: {"tokens_per_sec": 123.4,
                                   "queue_depth": 2.0},
                 base_revision=lambda: "rev-abc")
    body = build_heartbeat("server", "hk-s", 1, now=1000.0, **vit.collect())
    parsed = parse_heartbeat(body)
    assert parsed is not None
    assert parsed["base_revision"] == "rev-abc"
    assert parsed["tokens_per_sec"] == pytest.approx(123.4)
    assert parsed["role"] == "server"


def test_fleet_monitor_polls_server_heartbeats():
    """Monitor roles poll the server role alongside miners, and the
    ledger record carries the served revision + tokens/sec extras —
    the fleet table's rev/tok_s columns work from a monitor's JSONL,
    not only the server's own."""
    from distributedtraining_tpu.engine.health import (FleetMonitor,
                                                       HeartbeatPublisher,
                                                       Vitals)
    tr = InMemoryTransport()
    vit = Vitals(steps=lambda: 42.0,
                 counters=lambda: {"tokens_per_sec": 77.7,
                                   "queue_depth": 1.0},
                 base_revision=lambda: "rev-xyz")
    hb = HeartbeatPublisher(tr, "server", "hk-s", interval=999.0,
                            vitals=vit)
    try:
        hb.beat_now()
    finally:
        hb.close()
    fm = FleetMonitor(tr)
    try:
        assert "server" in fm.roles
        assert fm.poll(["hk-s"]) == 1
        rec = fm.ledger()["server/hk-s"]
        assert rec["base_revision"] == "rev-xyz"
        assert rec["tokens_per_sec"] == pytest.approx(77.7)
    finally:
        fm.close()


def test_fleet_report_serve_columns(tmp_path):
    """One CLI shows train -> merge -> serve lag: the report renders the
    rev and tok_s columns from server heartbeats."""
    import sys
    sys.path.insert(0, os.path.join(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))), "scripts"))
    import fleet_report
    path = tmp_path / "monitor.jsonl"
    recs = [
        {"heartbeat": {"hb": 1, "role": "server", "hotkey": "hk-s",
                       "seq": 3, "t": 9.0, "base_revision": "deadbeef01",
                       "tokens_per_sec": 88.5, "steps": 100.0}},
        {"heartbeat": {"hb": 1, "role": "miner", "hotkey": "hk-m",
                       "seq": 5, "t": 9.0, "base_revision": "deadbeef01",
                       "steps": 10.0}},
    ]
    path.write_text("\n".join(json.dumps(r) for r in recs) + "\n")
    rep = fleet_report.build_report([str(path)])
    table = fleet_report.format_table(rep)
    assert "rev" in fleet_report.COLUMNS
    assert "tok_s" in fleet_report.COLUMNS
    assert "deadbeef01"[:10] in table
    assert "88.5" in table
    server = rep["nodes"]["server/hk-s"]
    assert server["tokens_per_sec"] == pytest.approx(88.5)


# ---------------------------------------------------------------------------
# HTTP frontend + serve loop
# ---------------------------------------------------------------------------

def test_http_frontend_round_trip(setup):
    model, cfg, params, _, prompts = setup
    eng = GenerationEngine(model, params, revision="r1", max_slots=2,
                           page_size=8)
    loop = ServeLoop(eng, idle_poll_s=0.02).start()
    fe = ServeHTTPFrontend(eng, 0, timeout_s=60.0)
    port = fe.start()
    try:
        body = json.dumps({"tokens": prompts[0],
                           "max_new_tokens": 8}).encode()
        req = urllib.request.Request(
            f"http://127.0.0.1:{port}/generate", data=body,
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=30) as resp:
            out = json.loads(resp.read())
        assert out["tokens"] == reference_generate(model, params,
                                                   prompts[0], 8)
        assert out["status"] == "done"
        assert out["revision"] == "r1"
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/healthz", timeout=10) as resp:
            hz = json.loads(resp.read())
        assert hz["ok"] and hz["revision"] == "r1"
        # malformed request: 400, not a wedged handler
        bad = urllib.request.Request(
            f"http://127.0.0.1:{port}/generate", data=b'{"tokens": []}',
            headers={"Content-Type": "application/json"})
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(bad, timeout=10)
        assert ei.value.code == 400
    finally:
        fe.close()
        loop.close()
        eng.close()


# ---------------------------------------------------------------------------
# Persistent compilation cache (ROADMAP item 5, first half)
# ---------------------------------------------------------------------------

def test_compile_cache_restart(tmp_path, setup, sink):
    """--compile-cache-dir: a restarted serving process re-traces but
    deserializes yesterday's executables — the cache directory gains NO
    new entries for the identical bucket programs, and decode output
    stays pinned. (In-memory jit caches are cleared to simulate the
    restart; compile.ms still counts the re-dispatches, now measuring
    cache-load cost.)"""
    from neurons.common import enable_compile_cache
    model, cfg, params, _, prompts = setup
    cache_dir = str(tmp_path / "xla-cache")
    refs = refs_for(model, params, prompts[:2], 6)
    try:
        def bucket_entries():
            # the serving programs proper (incidental one-op jit_<prim>
            # helpers may come and go; they cost microseconds)
            return {f for f in os.listdir(cache_dir)
                    if f.endswith("-cache")
                    and ("jit_prefill" in f or "jit_step" in f)}

        enable_compile_cache(cache_dir)
        eng = GenerationEngine(model, params, max_slots=2, page_size=8)
        assert eng.generate(prompts[:2], 6) == refs
        eng.close()
        entries = bucket_entries()
        assert entries, "persistent cache stayed empty"
        jax.clear_caches()                    # the "restart"
        reg = obs.registry()
        compiles_before = reg.histogram("compile.ms").count
        eng2 = GenerationEngine(model, params, max_slots=2, page_size=8)
        assert eng2.generate(prompts[:2], 6) == refs
        eng2.close()
        # the restarted process re-dispatched (compile.ms moved)...
        assert reg.histogram("compile.ms").count > compiles_before
        # ...but every bucket program came FROM the cache: no new
        # prefill/decode entries
        assert bucket_entries() == entries, (
            f"restart recompiled fresh bucket programs: "
            f"{sorted(bucket_entries() - entries)}")
    finally:
        jax.config.update("jax_compilation_cache_dir", None)


def test_run_config_serving_flags():
    from distributedtraining_tpu.config import RunConfig
    cfg = RunConfig.from_args("server", [
        "--serve-port", "8123", "--serve-slots", "4", "--page-size", "8",
        "--kv-pages", "64", "--max-new-tokens", "32", "--swap-policy",
        "restart", "--swap-poll", "2.5", "--compile-cache-dir", "/tmp/cc",
        "--model", "tiny", "--backend", "memory"])
    assert cfg.role == "server"
    assert cfg.serve_port == 8123
    assert cfg.serve_slots == 4
    assert cfg.serve_page_size == 8
    assert cfg.serve_kv_pages == 64
    assert cfg.serve_max_new == 32
    assert cfg.swap_policy == "restart"
    assert cfg.swap_poll == 2.5
    assert cfg.compile_cache_dir == "/tmp/cc"
    # every role grows the cache flag (restarts of ALL roles skip
    # recompiles)
    for role in ("miner", "validator", "averager"):
        c = RunConfig.from_args(role, ["--compile-cache-dir", "/tmp/cc"])
        assert c.compile_cache_dir == "/tmp/cc"


# ---------------------------------------------------------------------------
# Sampled decode (round 16): seeded determinism + compile discipline
# ---------------------------------------------------------------------------

SAMPLE_KW = dict(temperature=0.9, top_p=0.95, seed=42)


def test_sampled_decode_deterministic_across_runs(setup):
    """Same seed + same batch composition => bit-identical sampled
    streams across engine instances (the PRNG key is
    fold_in(PRNGKey(seed), token_index) — a pure function of the
    request, never of wall clock or slot layout)."""
    model, cfg, params, _, prompts = setup
    outs = []
    for _ in range(2):
        eng = GenerationEngine(model, params, max_slots=2, page_size=8)
        try:
            outs.append(eng.generate(prompts[:2], GEN, **SAMPLE_KW))
        finally:
            eng.close()
    assert outs[0] == outs[1]
    # and sampling actually sampled: not the greedy stream
    assert outs[0] != refs_for(model, params, prompts[:2])


def test_sampled_stream_independent_of_batch_mix(setup):
    """A request's sampled stream is identical whether its batch
    neighbors are greedy or sampled — and the greedy lane inside a
    mixed batch stays bit-identical to the reference oracle (both lanes
    run the ONE sampled program; temperature rides as data)."""
    model, cfg, params, _, prompts = setup
    eng = GenerationEngine(model, params, max_slots=2, page_size=8)
    try:
        pure = eng.generate(prompts[:2], GEN, **SAMPLE_KW)
    finally:
        eng.close()
    eng = GenerationEngine(model, params, max_slots=2, page_size=8)
    try:
        r_greedy = eng.submit(prompts[0], GEN)
        r_sampled = eng.submit(prompts[1], GEN, **SAMPLE_KW)
        while not (r_greedy.done_evt.is_set()
                   and r_sampled.done_evt.is_set()):
            eng.step()
        assert list(r_greedy.tokens) == refs_for(
            model, params, prompts[:1])[0]
        assert list(r_sampled.tokens) == pure[1]
    finally:
        eng.close()


def test_sampled_decode_zero_fresh_compiles(setup, sink):
    """The mixed greedy/sampled acceptance pin: after one warm mixed
    batch, an identical second wave adds ZERO fresh compiles — the
    sampled program family rides the same (slot, page) BucketLadder and
    sampling parameters are arguments, not trace constants."""
    model, cfg, params, _, prompts = setup
    eng = GenerationEngine(model, params, max_slots=4, page_size=8)

    def wave():
        reqs = [eng.submit(p, GEN) if i % 2 == 0
                else eng.submit(p, GEN, **SAMPLE_KW)
                for i, p in enumerate(prompts)]
        while not all(r.done_evt.is_set() for r in reqs):
            eng.step()
        return [list(r.tokens) for r in reqs]

    try:
        w1 = wave()                                   # warm
        reg = obs.registry()
        before = (reg.histogram("compile.ms").count,
                  reg.counter("serve.decode_bucket_compiles").value,
                  reg.counter("serve.prefill_bucket_compiles").value)
        w2 = wave()                                   # steady state
        after = (reg.histogram("compile.ms").count,
                 reg.counter("serve.decode_bucket_compiles").value,
                 reg.counter("serve.prefill_bucket_compiles").value)
        assert after == before, \
            f"sampled steady state compiled: {before} -> {after}"
        assert w1 == w2                               # seeded determinism
    finally:
        eng.close()


# ---------------------------------------------------------------------------
# Prefix cache: shared pages, refcounts, copy-on-write
# ---------------------------------------------------------------------------

def test_prefix_cache_shared_prefill_parity(setup, sink):
    """Requests sharing a two-page system prompt reuse its cached KV
    pages (suffix-only prefill) and still decode token-identical to the
    full-recompute oracle; the cache counts hits and prefill tokens
    saved."""
    model, cfg, params, _, _ = setup
    rng = np.random.RandomState(11)
    sysp = [int(t) for t in rng.randint(0, cfg.vocab_size, size=16)]
    prompts = [sysp + [int(t) for t in rng.randint(0, cfg.vocab_size,
                                                   size=4)]
               for _ in range(3)]
    eng = GenerationEngine(model, params, max_slots=2, page_size=8,
                           prefix_cache=True, debug_invariants=True)
    try:
        assert eng.generate(prompts, GEN) == refs_for(model, params,
                                                      prompts)
        assert eng.prefix_hits >= 1
        assert eng.prefix_tokens_saved >= 16
        assert obs.registry().counter("serve.prefix_hits").value >= 1
    finally:
        eng.close()


def test_prefix_cache_cow_divergent_continuations(setup):
    """Copy-on-write correctness: a shared prefix ending mid-page is
    copied before the diverging request writes into it — every
    continuation matches its unshared reference exactly (a stronger pin
    than the 1e-6 budget), and the engine actually took the CoW path."""
    model, cfg, params, _, _ = setup
    rng = np.random.RandomState(13)
    # 12 shared tokens = 1 full page + half a page on page_size=8:
    # the second admission's suffix starts mid-page => admit-time CoW
    sysp = [int(t) for t in rng.randint(0, cfg.vocab_size, size=12)]
    prompts = [sysp + [int(t) for t in rng.randint(0, cfg.vocab_size,
                                                   size=5)]
               for _ in range(2)]
    eng = GenerationEngine(model, params, max_slots=2, page_size=8,
                           prefix_cache=True, debug_invariants=True)
    try:
        assert eng.generate(prompts, GEN) == refs_for(model, params,
                                                      prompts)
        assert eng.cow_copies >= 1
    finally:
        eng.close()


def test_page_pool_invariant_preempt_readmit_exhaustion(setup, sink):
    """The round-16 accounting regression: preempted-then-readmitted
    slots release and re-acquire pages through the refcount discipline.
    ``debug_invariants`` audits free + referenced == total (with exact
    per-holder refcounts) after EVERY step, through preemption,
    readmission, and pool exhaustion, with the prefix cache holding
    references of its own."""
    model, cfg, params, _, _ = setup
    rng = np.random.RandomState(17)
    sysp = [int(t) for t in rng.randint(0, cfg.vocab_size, size=8)]
    prompts = [sysp + [int(t) for t in rng.randint(0, cfg.vocab_size,
                                                   size=2 + i)]
               for i in range(3)]
    eng = GenerationEngine(model, params, max_slots=2, page_size=8,
                           max_seq_len=32, pool_pages=7,
                           prefix_cache=True, debug_invariants=True)
    try:
        assert eng.generate(prompts, 16) == refs_for(model, params,
                                                     prompts, 16)
        assert obs.registry().counter("serve.preempted").value >= 1
        eng._check_invariants()
    finally:
        eng.close()


def test_page_pool_check_catches_drift():
    """PagePool.check is a real audit: a refcount the engine cannot
    explain fails loudly."""
    from distributedtraining_tpu.engine.serve import PagePool
    pool = PagePool(5)
    pages = pool.alloc(2)
    pool.check({pages[0]: 1, pages[1]: 1})       # honest books balance
    pool.incref(pages[0])
    with pytest.raises(AssertionError):
        pool.check({pages[0]: 1, pages[1]: 1})   # drifted books do not
    pool.decref(pages[0])
    pool.decref(pages[0])
    pool.decref(pages[1])
    pool.check({})


# ---------------------------------------------------------------------------
# HTTP admission control: 429 on shed, 503 on drain
# ---------------------------------------------------------------------------

def test_http_shed_429_with_retry_after(setup):
    """Past --max-queue the frontend sheds with 429 + Retry-After
    instead of queueing the caller into the latency knee."""
    model, cfg, params, _, prompts = setup
    eng = GenerationEngine(model, params, max_slots=2, page_size=8,
                           max_queue=1)
    fe = ServeHTTPFrontend(eng, 0, timeout_s=30.0)
    port = fe.start()
    try:
        eng.submit(prompts[0], 4)        # no loop running: stays queued
        body = json.dumps({"tokens": prompts[1]}).encode()
        req = urllib.request.Request(
            f"http://127.0.0.1:{port}/generate", data=body,
            headers={"Content-Type": "application/json"})
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(req, timeout=10)
        assert ei.value.code == 429
        assert int(ei.value.headers["Retry-After"]) >= 1
        assert eng.shed_count == 1
    finally:
        fe.close()
        eng.close()


def test_http_drain_503_during_swap(setup):
    """While a drain-policy swap waits on in-flight sequences, new HTTP
    requests get 503 + Retry-After (come back on the new revision), not
    an indefinite queue slot."""
    model, cfg, params, params2, prompts = setup
    eng = GenerationEngine(model, params, revision="r1", max_slots=2,
                           page_size=8, swap_policy="drain")
    fe = ServeHTTPFrontend(eng, 0, timeout_s=30.0)
    port = fe.start()
    try:
        eng.submit(prompts[0], GEN)
        eng.step()                       # admit: one sequence in flight
        eng._pending_swap = ("r2", jax.device_put(params2))
        body = json.dumps({"tokens": prompts[1]}).encode()
        req = urllib.request.Request(
            f"http://127.0.0.1:{port}/generate", data=body,
            headers={"Content-Type": "application/json"})
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(req, timeout=10)
        assert ei.value.code == 503
        assert int(ei.value.headers["Retry-After"]) >= 1
    finally:
        fe.close()
        eng.close()


def test_http_sampling_params_round_trip(setup):
    """temperature/top_p/seed ride the POST body; the same seed returns
    the same stream on a second identical request."""
    model, cfg, params, _, prompts = setup
    eng = GenerationEngine(model, params, max_slots=2, page_size=8)
    loop = ServeLoop(eng, idle_poll_s=0.02).start()
    fe = ServeHTTPFrontend(eng, 0, timeout_s=60.0)
    port = fe.start()
    try:
        body = json.dumps({"tokens": prompts[0], "max_new_tokens": 8,
                           "temperature": 0.9, "top_p": 0.95,
                           "seed": 7}).encode()
        outs = []
        for _ in range(2):
            req = urllib.request.Request(
                f"http://127.0.0.1:{port}/generate", data=body,
                headers={"Content-Type": "application/json"})
            with urllib.request.urlopen(req, timeout=30) as resp:
                outs.append(json.loads(resp.read())["tokens"])
        assert outs[0] == outs[1]
        assert outs[0] != reference_generate(model, params, prompts[0], 8)
    finally:
        fe.close()
        loop.close()
        eng.close()


def test_prefix_cache_flushed_on_hot_swap(setup, sink):
    """A base-revision swap invalidates the prefix cache: cached KV is a
    function of the params that produced it, so post-swap shared-prefix
    requests must re-prefill under the NEW params and match the new
    revision's oracle exactly — never reuse revision-1 pages."""
    model, cfg, params1, params2, _ = setup
    rng = np.random.RandomState(17)
    sysp = [int(t) for t in rng.randint(0, cfg.vocab_size, size=16)]
    prompts = [sysp + [int(t) for t in rng.randint(0, cfg.vocab_size,
                                                   size=4)]
               for _ in range(2)]
    eng = GenerationEngine(model, params1, revision="r1", max_slots=2,
                           page_size=8, prefix_cache=True,
                           debug_invariants=True)
    try:
        # warm the cache under params1 (second request hits the prefix)
        assert eng.generate(prompts, GEN) == refs_for(model, params1,
                                                      prompts)
        assert eng.prefix_hits >= 1
        assert len(eng._cache) > 0
        eng._pending_swap = ("r2", jax.device_put(params2))
        eng.step()                          # idle engine: swap lands now
        assert eng.revision == "r2"
        assert len(eng._cache) == 0         # stale entries flushed...
        assert obs.registry().counter("serve.prefix_flushes").value == 1
        # ...and their pool references released (books still balance)
        eng._check_invariants()
        # the same shared-prefix traffic now decodes on params2 exactly
        assert eng.generate(prompts, GEN) == refs_for(model, params2,
                                                      prompts)
        assert eng.prefix_hits >= 2         # cache rebuilt and hit again
    finally:
        eng.close()
