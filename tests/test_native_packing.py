"""Native C++ packer vs the pure-Python oracle: exact output parity.

The C++ path (native/packing.cpp) must be bit-identical to pack_documents'
Python loop for every field, including the chunked-streaming wrapper that
feeds it bounded buffers.
"""

import numpy as np
import pytest

from distributedtraining_tpu import native
from distributedtraining_tpu.data import packing


def _collect(it):
    rows = list(it)
    if not rows:
        return None
    return {k: np.stack([r[k] for r in rows]) for k in rows[0]}


def _random_docs(rng, n_docs, max_len):
    return [list(rng.integers(1, 1000, rng.integers(0, max_len + 1)))
            for _ in range(n_docs)]


requires_native = pytest.mark.skipif(native.load("packing") is None,
                                     reason="native toolchain unavailable")


@requires_native
@pytest.mark.parametrize("seq_len,drop", [(16, True), (16, False),
                                          (64, True), (64, False)])
def test_native_matches_oracle(seq_len, drop):
    rng = np.random.default_rng(0)
    docs = _random_docs(rng, 200, 3 * seq_len)  # includes empty + long docs
    want = _collect(packing.pack_documents(docs, seq_len,
                                           drop_remainder=drop,
                                           native=False))
    got = _collect(packing.pack_documents(docs, seq_len,
                                          drop_remainder=drop, native=True))
    assert want.keys() == got.keys()
    for k in want:
        np.testing.assert_array_equal(want[k], got[k], err_msg=k)


@requires_native
def test_native_chunked_streaming_matches_oracle():
    """Tiny chunk budget forces many native calls with carry-over tails."""
    rng = np.random.default_rng(1)
    seq_len = 32
    docs = _random_docs(rng, 300, 2 * seq_len)
    want = _collect(packing.pack_documents(docs, seq_len,
                                           drop_remainder=False,
                                           native=False))
    got = _collect(packing._pack_documents_native(
        iter(docs), seq_len, drop_remainder=False, chunk_tokens=64))
    for k in want:
        np.testing.assert_array_equal(want[k], got[k], err_msg=k)


@requires_native
def test_native_empty_and_degenerate():
    assert _collect(packing.pack_documents([], 16, native=True)) is None
    # single doc exactly one row
    doc = list(range(1, 17))
    got = _collect(packing.pack_documents([doc], 16, native=True))
    want = _collect(packing.pack_documents([doc], 16, native=False))
    for k in want:
        np.testing.assert_array_equal(want[k], got[k], err_msg=k)


@requires_native
def test_native_packer_is_faster():
    """Not a benchmark assertion in CI spirit — a sanity floor that the
    native path actually beats the Python loop on a realistic workload."""
    import time
    rng = np.random.default_rng(2)
    # array docs: the zero-conversion fast path (HF tokenizers hand back
    # arrays; list docs spend ~95% of wall time in np.asarray either way)
    docs = [rng.integers(1, 50000, 700).astype(np.int32)
            for _ in range(400)]

    def best_of(native, runs=3):
        times, n = [], None
        for _ in range(runs):  # best-of: a loaded test machine spikes singles
            t0 = time.perf_counter()
            n = sum(1 for _ in packing.pack_documents(docs, 1024,
                                                      native=native))
            times.append(time.perf_counter() - t0)
        return n, min(times)

    n_py, t_py = best_of(False)
    n_nat, t_nat = best_of(True)
    assert n_py == n_nat
    assert t_nat < t_py, (t_nat, t_py)
