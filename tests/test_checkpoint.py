"""Checkpoint/resume: Orbax round-trip + miner preemption recovery.

The reference has no local checkpointing (HF Hub is its only store,
SURVEY.md §5); these tests cover the stronger guarantee this framework adds —
a preempted miner resumes with optimizer moments, base snapshot, and base
revision intact.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributedtraining_tpu.checkpoint import CheckpointStore, Snapshot
from distributedtraining_tpu.data import ByteTokenizer, batch_iterator, text_corpus
from distributedtraining_tpu.engine import FakeClock, MinerLoop, TrainEngine
from distributedtraining_tpu.models import gpt2
from distributedtraining_tpu.transport import InMemoryTransport

SEQ = 32
BATCH = 4


@pytest.fixture(scope="module")
def setup():
    model, cfg = gpt2.make_model("tiny")
    engine = TrainEngine(model, seq_len=SEQ)
    tok = ByteTokenizer()
    docs = text_corpus(split="train", n_docs=24, source="synthetic")

    def batches():
        return batch_iterator(docs, tok, batch_size=BATCH, seq_len=SEQ,
                              repeat=True, max_vocab=cfg.vocab_size)

    return model, cfg, engine, batches


def _tree_equal(a, b):
    return all(
        np.array_equal(np.asarray(x), np.asarray(y))
        for x, y in zip(jax.tree_util.tree_leaves(a),
                        jax.tree_util.tree_leaves(b)))


def test_store_round_trip(tmp_path, setup):
    model, cfg, engine, _ = setup
    state = engine.init_state(jax.random.PRNGKey(1))
    snap = Snapshot(state=state, base_params=state.params,
                    base_revision="rev-abc")

    with CheckpointStore(str(tmp_path / "ckpt")) as store:
        assert store.latest_step() is None
        store.save(0, snap)
        assert store.latest_step() == 0

        template = Snapshot(state=engine.init_state(jax.random.PRNGKey(2)),
                            base_params=model.init_params(jax.random.PRNGKey(2)),
                            base_revision=None)
        restored = store.restore(template)

    assert restored.base_revision == "rev-abc"
    assert _tree_equal(restored.state.params, snap.state.params)
    assert _tree_equal(restored.state.opt_state, snap.state.opt_state)
    assert _tree_equal(restored.base_params, snap.base_params)


def test_store_retention_gc(tmp_path, setup):
    model, cfg, engine, _ = setup
    state = engine.init_state(jax.random.PRNGKey(1))
    snap = Snapshot(state=state, base_params=state.params, base_revision=None)
    with CheckpointStore(str(tmp_path / "ckpt"), max_to_keep=2) as store:
        for step in (1, 2, 3, 4):
            store.save(step, snap)
        assert store.all_steps() == [3, 4]
        assert store.latest_step() == 4


def test_miner_resume_after_preemption(tmp_path, setup):
    model, cfg, engine, batches = setup
    transport = InMemoryTransport()
    ckpt_dir = str(tmp_path / "miner-ckpt")

    clock = FakeClock()
    with CheckpointStore(ckpt_dir) as store:
        miner = MinerLoop(engine, transport, "m0", clock=clock,
                          send_interval=1e9, check_update_interval=1e9,
                          checkpoint_store=store, checkpoint_interval=1e9)
        miner.bootstrap(jax.random.PRNGKey(0))
        miner.run(batches(), max_steps=7)
        miner.flush()  # checkpoint + delta push
        params_before = jax.device_get(miner.state.params)
        opt_before = jax.device_get(miner.state.opt_state)

    # "preemption": a brand-new process (fresh loop + store)
    with CheckpointStore(ckpt_dir) as store2:
        miner2 = MinerLoop(engine, transport, "m0", clock=FakeClock(),
                           send_interval=1e9, check_update_interval=1e9,
                           checkpoint_store=store2, checkpoint_interval=1e9)
        miner2.bootstrap(jax.random.PRNGKey(99))  # rng must NOT matter
        assert int(miner2.state.step) == 7
        assert miner2.report.steps == 7
        assert _tree_equal(miner2.state.params, params_before)
        assert _tree_equal(miner2.state.opt_state, opt_before)
        # resumed miner keeps training from where it left off
        miner2.run(batches(), max_steps=3)
        assert int(miner2.state.step) == 10
        # and its delta base survived: delta = params - base is nonzero
        d = jax.tree_util.tree_leaves(miner2.state.params)
        b = jax.tree_util.tree_leaves(miner2.base_params)
        assert any(not np.array_equal(np.asarray(x), np.asarray(y))
                   for x, y in zip(d, b))


def test_corrupt_checkpoint_falls_back_to_base(tmp_path, setup):
    """An unreadable/corrupt checkpoint must not wedge the miner: bootstrap
    logs and falls through to the base-pull/self-init path instead of
    raising (a raise would crash-loop the role under supervise.sh)."""
    model, cfg, engine, batches = setup

    class BrokenStore:
        def latest_step(self):
            return 3

        def restore(self, template, step=None):
            raise OSError("disk fault: truncated checkpoint")

        def next_step(self):
            return 4

    transport = InMemoryTransport()
    miner = MinerLoop(engine, transport, "m0", clock=FakeClock(),
                      send_interval=1e9, check_update_interval=1e9,
                      checkpoint_store=BrokenStore(), checkpoint_interval=1e9)
    miner.bootstrap(jax.random.PRNGKey(0))  # must not raise
    assert miner.state is not None
    assert int(miner.state.step) == 0       # self-initialized, not restored
    miner.run(batches(), max_steps=2)
    assert miner.report.steps == 2


def test_resume_after_base_pull_step_reset(tmp_path, setup):
    """Checkpoint keys must stay monotonic across base pulls: the training
    step resets to 0 on every base update, so a step-keyed store would
    resolve 'latest' to a stale pre-reset checkpoint."""
    model, cfg, engine, batches = setup
    transport = InMemoryTransport()
    ckpt_dir = str(tmp_path / "ckpt")

    clock = FakeClock()
    with CheckpointStore(ckpt_dir) as store:
        miner = MinerLoop(engine, transport, "m0", clock=clock,
                          send_interval=1e9, check_update_interval=1e9,
                          checkpoint_store=store, checkpoint_interval=1e9)
        miner.bootstrap(jax.random.PRNGKey(0))
        miner.run(batches(), max_steps=9)
        miner.flush()  # seq 0: step 9, no base revision

        # operator publishes a new base -> miner pulls, step resets to 0
        new_base = model.init_params(jax.random.PRNGKey(7))
        transport.publish_base(new_base)
        clock.advance(2e9)
        miner._check_pull()
        assert int(miner.state.step) == 0
        miner.run(batches(), max_steps=3)  # periodic action also fires here
        miner.flush()  # newest save: step 3 < 9, against the NEW base
        new_rev = miner._base_revision
        assert new_rev is not None
        # flush right after a save with identical content must not duplicate
        n_saves = len(store.all_steps())
        miner.flush()
        assert len(store.all_steps()) == n_saves

    with CheckpointStore(ckpt_dir) as store2:
        miner2 = MinerLoop(engine, transport, "m0", clock=FakeClock(),
                           send_interval=1e9, check_update_interval=1e9,
                           checkpoint_store=store2, checkpoint_interval=1e9)
        miner2.bootstrap(jax.random.PRNGKey(0))
        # must resume the NEWEST save (post-base-pull), not the highest step
        assert int(miner2.state.step) == 3
        assert miner2._base_revision == new_rev


def test_resume_on_mesh_replaces_shardings(tmp_path, setup, devices):
    """Restored params AND optimizer moments must be re-placed per the mesh
    sharding rules — raw restored arrays are unsharded and would replicate
    full moments on every device."""
    from distributedtraining_tpu.parallel import MeshConfig, make_mesh

    model, cfg, _, batches = setup
    mesh = make_mesh(MeshConfig(dp=2, fsdp=2, tp=2), devices=devices)
    engine = TrainEngine(model, mesh=mesh, seq_len=SEQ)
    transport = InMemoryTransport()
    ckpt_dir = str(tmp_path / "ckpt")

    with CheckpointStore(ckpt_dir) as store:
        miner = MinerLoop(engine, transport, "m0", clock=FakeClock(),
                          send_interval=1e9, check_update_interval=1e9,
                          checkpoint_store=store, checkpoint_interval=1e9)
        miner.bootstrap(jax.random.PRNGKey(0))
        expected_shardings = jax.tree_util.tree_map(
            lambda x: x.sharding, miner.state.opt_state)
        miner.run(batches(), max_steps=2)
        miner.flush()

    with CheckpointStore(ckpt_dir) as store2:
        miner2 = MinerLoop(engine, transport, "m0", clock=FakeClock(),
                           send_interval=1e9, check_update_interval=1e9,
                           checkpoint_store=store2, checkpoint_interval=1e9)
        miner2.bootstrap(jax.random.PRNGKey(0))
        assert int(miner2.state.step) == 2
        restored_shardings = jax.tree_util.tree_map(
            lambda x: x.sharding, miner2.state.opt_state)
        for want, got in zip(jax.tree_util.tree_leaves(expected_shardings),
                             jax.tree_util.tree_leaves(restored_shardings)):
            assert want == got, (want, got)
        # and it keeps training on the mesh
        for i, b in enumerate(batches()):
            if i >= 2:
                break
            miner2.state, m = engine.train_step(miner2.state,
                                                engine.place_batch(b))
        assert np.isfinite(float(m["loss"]))


def test_nan_state_never_checkpointed(tmp_path, setup):
    """A NaN'd miner must stay recoverable by restart: persisting poisoned
    params would wedge it forever (restore prefers the checkpoint)."""
    model, cfg, engine, batches = setup
    with CheckpointStore(str(tmp_path / "ckpt")) as store:
        miner = MinerLoop(engine, InMemoryTransport(), "m0", clock=FakeClock(),
                          send_interval=1e9, check_update_interval=1e9,
                          checkpoint_store=store, checkpoint_interval=1e9)
        miner.bootstrap(jax.random.PRNGKey(0))
        miner.run(batches(), max_steps=2)
        poisoned = jax.tree_util.tree_map(
            lambda x: jnp.full_like(x, jnp.nan), miner.state.params)
        miner.state = miner.state.replace(params=poisoned)
        miner.flush()
        assert store.latest_step() is None  # nothing persisted


def test_resume_pulls_when_base_moved(tmp_path, setup):
    """A miner that was down while the averager published a new base must
    pull it at resume, not push deltas against the superseded revision."""
    model, cfg, engine, batches = setup
    transport = InMemoryTransport()
    ckpt_dir = str(tmp_path / "ckpt")

    with CheckpointStore(ckpt_dir) as store:
        miner = MinerLoop(engine, transport, "m0", clock=FakeClock(),
                          send_interval=1e9, check_update_interval=1e9,
                          checkpoint_store=store, checkpoint_interval=1e9)
        miner.bootstrap(jax.random.PRNGKey(0))
        miner.run(batches(), max_steps=5)
        miner.flush()

    # while the miner is down: new base published
    transport.publish_base(model.init_params(jax.random.PRNGKey(7)))

    with CheckpointStore(ckpt_dir) as store2:
        miner2 = MinerLoop(engine, transport, "m0", clock=FakeClock(),
                           send_interval=1e9, check_update_interval=1e9,
                           checkpoint_store=store2, checkpoint_interval=1e9)
        miner2.bootstrap(jax.random.PRNGKey(0))
        assert miner2.report.base_pulls == 1
        assert miner2._base_revision == transport.base_revision()
        assert int(miner2.state.step) == 0  # fresh optimizer on the new base
        assert miner2.report.steps == 5     # lifetime counter survives


def test_restore_empty_store_returns_none(tmp_path, setup):
    model, cfg, engine, _ = setup
    with CheckpointStore(str(tmp_path / "empty")) as store:
        template = Snapshot(state=engine.init_state(jax.random.PRNGKey(0)),
                            base_params=None, base_revision=None)
        assert store.restore(template) is None


def test_published_base_not_persisted_in_snapshot(tmp_path):
    """When the base is recoverable by transport revision, checkpoints omit
    it (for a LoRA miner the frozen base is ~99.9% of the bytes); restore
    re-pulls it and resumes. A self-init genesis base (no revision) still
    travels in the snapshot."""
    import os

    from distributedtraining_tpu.checkpoint import CheckpointStore
    from distributedtraining_tpu.engine import FakeClock, MinerLoop, TrainEngine
    from distributedtraining_tpu.models import gpt2
    from distributedtraining_tpu.transport import InMemoryTransport

    model, cfg = gpt2.make_model("tiny")
    transport = InMemoryTransport()
    transport.publish_base(model.init_params(jax.random.PRNGKey(1)))

    def du(d):
        return sum(os.path.getsize(os.path.join(r, f))
                   for r, _, fs in os.walk(d) for f in fs)

    with CheckpointStore(str(tmp_path / "pub")) as store:
        engine = TrainEngine(model, seq_len=16)
        m = MinerLoop(engine, transport, "m0", clock=FakeClock(),
                      send_interval=1e9, check_update_interval=1e9,
                      checkpoint_store=store)
        m.bootstrap(jax.random.PRNGKey(0))
        m.flush()
        assert store.read_meta()["has_base"] is False
        pub_bytes = du(str(tmp_path / "pub"))

    with CheckpointStore(str(tmp_path / "gen")) as store2:
        engine2 = TrainEngine(model, seq_len=16)
        m2 = MinerLoop(engine2, InMemoryTransport(), "m0", clock=FakeClock(),
                       send_interval=1e9, check_update_interval=1e9,
                       checkpoint_store=store2)
        m2.bootstrap(jax.random.PRNGKey(0))  # no published base: genesis
        m2.flush()
        assert store2.read_meta()["has_base"] is True
        gen_bytes = du(str(tmp_path / "gen"))

    # the published-base snapshot skips a full param tree (state is params +
    # 2 adam moments + base -> dropping base saves ~1/4)
    assert pub_bytes < gen_bytes * 0.85, (pub_bytes, gen_bytes)

    # and the omitted-base checkpoint actually resumes
    with CheckpointStore(str(tmp_path / "pub")) as store3:
        engine3 = TrainEngine(model, seq_len=16)
        m3 = MinerLoop(engine3, transport, "m0", clock=FakeClock(),
                       send_interval=1e9, check_update_interval=1e9,
                       checkpoint_store=store3)
        m3.bootstrap(jax.random.PRNGKey(7))
        assert m3._base_revision == m._base_revision
