"""bench.py's helpers at tiny scale.

The driver runs bench.py unattended at round end on hardware this CI
never sees; a broken helper means a silently lost measurement round, so
the burst/A-B/merge plumbing is pinned here on the CPU backend with a
tiny model (the numbers are meaningless off-TPU — only the mechanics and
contracts are under test).
"""

import json

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import bench
from distributedtraining_tpu.models import gpt2


@pytest.fixture()
def tiny(monkeypatch):
    monkeypatch.setattr(bench, "BATCH", 2)
    monkeypatch.setattr(bench, "SEQ", 32)
    monkeypatch.setattr(bench, "WARMUP", 1)
    monkeypatch.setattr(bench, "MERGE_M", 3)
    monkeypatch.setattr(bench, "MERGE_ITERS", 2)
    model, cfg = gpt2.make_model(gpt2.GPT2Config(
        n_layer=2, n_embd=64, n_head=2, vocab_size=256, n_positions=32))
    return model, cfg


def test_step_burst_contract(tiny):
    model, cfg = tiny
    burst = bench._step_burst(model, cfg)
    a = burst(2)
    b = burst(2)
    assert a > 0 and b > 0
    # state persists across bursts (the warm burst really warms)
    burst16 = bench._step_burst(model, cfg, batch_size=4)
    assert burst16(1) > 0


def test_ab_speedup_and_pair_stats(tiny):
    model, cfg = tiny
    base = bench._step_burst(model, cfg)
    base(1)
    tps, ratio = bench._ab_speedup(base, model, cfg, fused_b="scan")
    assert tps > 0 and ratio > 0
    assert bench._pair_stats([(100.0, 50.0), (200.0, 100.0)]) == (75.0, 0.5)


def test_loop_vs_engine_reports_both_keys(tiny):
    model, cfg = tiny
    base = bench._step_burst(model, cfg)
    base(1)
    out = bench._time_loop_vs_engine(model, cfg, base, trials=1, iters=2)
    assert set(out) == {"loop_tokens_per_sec", "loop_vs_engine"}
    assert out["loop_tokens_per_sec"] > 0


def test_time_merge_reports_all_spellings(tiny):
    model, cfg = tiny
    out = bench._time_merge(model)
    for key in ("merge_wallclock_s", "merge_gbps", "merge_flat_wallclock_s",
                "merge_bf16_wallclock_s", "merge_bf16_speedup",
                "sparse8_encode_s", "sparse8_decode_s",
                "sparse8_artifact_bytes", "sparse8_vs_f32_bytes"):
        assert key in out, out
    assert out["merge_m"] == 3
    assert out["sparse8_vs_f32_bytes"] > 4  # beats even dense int8's 4x


def test_time_validator_round_ab(tiny):
    """The cohort-vs-sequential validator A/B (ISSUE 1 acceptance): the
    dispatch-count reduction is exact and >= 2x at K=4, the cohort path's
    wall-clock beats the sequential spelling even on CPU (the contrast is
    dispatch/placement overhead, present on every backend), and the two
    paths agree numerically."""
    model, cfg = tiny
    out = bench._time_validator_round(model, cfg, k=4, n_batches=3,
                                      trials=2)
    for key in ("validator_round_sec", "validator_seq_round_sec",
                "candidates_per_sec", "validator_round_speedup"):
        assert key in out and out[key] > 0, out
    assert out["validator_seq_dispatches"] == 12
    assert out["validator_cohort_dispatches"] == 3
    assert out["validator_dispatch_ratio"] >= 2.0
    assert out["validator_round_speedup"] > 1.0, out
    assert out["validator_parity_max_abs_err"] < 1e-4


def test_time_push_overlap_ab():
    """The async-vs-sync miner publish A/B (ISSUE 2 acceptance): with a
    simulated-latency transport the pipeline hides the training-thread
    stall (>= 80% at the bench's default 150 ms; the floor here is looser
    because CI boxes run loaded) and the published artifacts are
    byte-identical. Cheap spelling: fewer steps, still latency-bound."""
    out = bench._time_push_overlap(latency_s=0.1, steps=10)
    for key in ("push_stall_ms", "push_stall_async_ms",
                "push_overlap_speedup", "push_stall_removed"):
        assert key in out and out[key] is not None, out
    assert out["push_parity"] is True, out
    assert out["push_overlap_speedup"] > 1.2, out
    assert out["push_stall_removed"] >= 0.5, out
    # the stall the sync path pays per push is at least the injected
    # transport latency (upload + rider)
    assert out["push_stall_ms"] >= 80.0, out


def test_time_gather_deltas_ab():
    """The pooled+cached averager ingest A/B (ISSUE 4 acceptance): on a
    cold round with >= 4 miners the concurrent pool beats the serial
    gather (<= 0.5x wall-clock over localfs at the bench's simulated
    latency), a warm round with unchanged revisions downloads ZERO
    artifact bytes and beats serial outright, and accepted deltas are
    byte-identical in both modes. Cheap spelling: shorter latency, same
    contrasts (they are host/network time, present on every backend)."""
    out = bench._time_gather_deltas(n_miners=4, latency_s=0.03, trials=2)
    for key in ("averager_ingest_ms", "averager_ingest_serial_ms",
                "averager_ingest_warm_ms", "ingest_speedup_cold",
                "ingest_speedup_warm"):
        assert key in out and out[key] > 0, out
    assert out["ingest_parity"] is True, out
    assert out["ingest_warm_downloads"] == 0, out
    assert out["averager_ingest_ms"] <= 0.5 * \
        out["averager_ingest_serial_ms"], out
    assert out["averager_ingest_warm_ms"] < \
        out["averager_ingest_serial_ms"], out


def test_time_heartbeat_overhead_ab():
    """The fleet-health-plane A/B (ISSUE 5 acceptance): the production
    MinerLoop with a HeartbeatPublisher at an aggressive cadence vs
    without. The plane must actually run (beats sent) and its measured
    cost must stay under the 2% acceptance floor — loosened to 10% here
    because short CI bursts on loaded boxes are noise-dominated; the
    recorded bench (docs/perf.md) pins the real number. Host contention
    only ever INFLATES the measured fraction, so on a miss the burst is
    re-measured (min-of-attempts is the tighter estimator on a shared
    rig — a single in-suite burst has measured 0.02–0.13 either way)."""
    for attempt in range(3):
        out = bench._time_heartbeat_overhead(steps=30, trials=1)
        for key in ("heartbeat_off_s", "heartbeat_on_s",
                    "heartbeat_overhead_frac"):
            assert key in out and out[key] is not None, out
        assert out["heartbeat_beats_sent"] >= 2, out
        if out["heartbeat_overhead_frac"] < 0.10:
            break
    assert out["heartbeat_overhead_frac"] < 0.10, out


def test_time_remediation_overhead_ab():
    """The remediation-layer A/B (ISSUE 6 acceptance): validator rounds
    with the fleet plane vs fleet plane + RemediationEngine. The layer
    must actually run both sides' rounds and its measured cost must stay
    small — loosened to 15% here because short CI bursts on loaded boxes
    are noise-dominated; the recorded bench (docs/perf.md) pins the real
    number against the < 2% acceptance floor. The rounds are ~20 ms, so
    scheduler jitter alone can blow the cap; noise only inflates the
    fraction, so a miss re-measures (min-of-attempts)."""
    for attempt in range(3):
        out = bench._time_remediation_overhead(miners=4, rounds=2, trials=1)
        for key in ("remediation_off_s", "remediation_on_s",
                    "remediation_overhead_frac"):
            assert key in out and out[key] is not None, out
        assert out["remediation_off_s"] > 0 and out["remediation_on_s"] > 0
        if out["remediation_overhead_frac"] < 0.15:
            break
    assert out["remediation_overhead_frac"] < 0.15, out


def test_time_flight_overhead_ab():
    """The flight-recorder A/B (ISSUE 10 tentpole): the production
    MinerLoop with the obs layer on both sides, contrast = the
    postmortem event ring (utils/flight.py). The ring must actually
    record (span closes, publish outcomes, registry snapshots) and
    freeze, and its measured cost must stay small — loosened to 25%
    here because short CI bursts on loaded boxes are noise-dominated
    (the same 30-step burst has measured 3%–18% across runs on the
    shared 1-core rig); the recorded bench (docs/perf.md) pins the
    real number against the < 2% acceptance floor. Noise only inflates
    the fraction; a miss re-measures (min-of-attempts)."""
    for attempt in range(3):
        out = bench._time_flight_overhead(steps=30, trials=1)
        for key in ("flight_off_s", "flight_on_s", "flight_overhead_frac"):
            assert key in out and out[key] is not None, out
        assert out["flight_events_recorded"] > 0, out
        assert out["flight_bundle_events"] > 0, out
        if out["flight_overhead_frac"] < 0.25:
            break
    assert out["flight_overhead_frac"] < 0.25, out


def test_time_lineage_overhead_ab():
    """The lineage-plane A/B (ISSUE 13 tentpole): production averager
    rounds with the provenance record + drift detector per publish vs
    without (engine/lineage.py). The plane must actually freeze records
    each merged round, and its measured cost must stay small — loosened
    to 25% here because at 2 rounds x ~70 ms a single scheduler hiccup
    on a loaded CI box is a double-digit fraction by itself; the
    recorded bench (docs/perf.md round 18, median of 3 trials) pins
    the real number against the < 2% acceptance floor. Noise only
    inflates the fraction; a miss re-measures (min-of-attempts)."""
    for attempt in range(3):
        out = bench._time_lineage_overhead(miners=3, rounds=2, trials=1)
        for key in ("lineage_off_s", "lineage_on_s",
                    "lineage_overhead_frac"):
            assert key in out and out[key] is not None, out
        assert out["lineage_records_published"] >= 2, out
        assert out["lineage_off_s"] > 0 and out["lineage_on_s"] > 0
        if out["lineage_overhead_frac"] < 0.25:
            break
    assert out["lineage_overhead_frac"] < 0.25, out


def test_time_devprof_overhead_ab():
    """The device-observatory A/B (ISSUE 12 tentpole): the production
    MinerLoop with the obs layer on both sides, contrast =
    utils/devprof.py (per-program cost probes, blocking exec timing on
    CPU, flush-time snapshot mirror). The observatory must actually
    attribute the train step (records + FLOPs where the backend has a
    cost model) and its measured cost must stay small — loosened to
    10% here because short CI bursts on loaded boxes are
    noise-dominated; the recorded bench (docs/perf.md) pins the real
    number against the < 2% acceptance floor."""
    from distributedtraining_tpu.utils import devprof

    # Noise only inflates the fraction; a miss re-measures
    # (min-of-attempts is the tighter estimator on a shared rig).
    for attempt in range(3):
        out = bench._time_devprof_overhead(steps=30, trials=1)
        for key in ("devprof_off_s", "devprof_on_s",
                    "devprof_overhead_frac"):
            assert key in out and out[key] is not None, out
        assert out["devprof_programs"] >= 1, out
        assert "prog_achieved" in out  # empty on CPU (unknown roofline)
        if devprof.cost_analysis_available():
            assert out["devprof_train_step_flops"] > 0, out
        if out["devprof_overhead_frac"] < 0.10:
            break
    assert out["devprof_overhead_frac"] < 0.10, out


def test_bench_env_forensics():
    """Every bench record embeds the rig forensics (ISSUE 12 satellite):
    device kind/counts, platform, jax/jaxlib versions — what four
    rounds of bare 'tunnel wedged' artifacts were missing."""
    env = bench._bench_env()
    for key in ("jax_version", "jaxlib_version", "platform",
                "device_kind", "device_count", "host_count"):
        assert key in env, env
    assert env["platform"] == "cpu"
    assert env["device_count"] >= 1 and env["host_count"] >= 1
    assert env["jax_version"] == jax.__version__


def test_gate_baseline_utilization(tmp_path):
    """--baseline gating (ISSUE 12 satellite): the per-program
    achieved-fraction regresses -> flagged even when the headline
    holds; degraded records gate nothing."""
    base = {"value": 100.0, "prog_achieved": {"train.step": 0.40,
                                              "serve.decode": 0.20}}
    bp = tmp_path / "base.json"
    bp.write_text(json.dumps(base))
    # headline holds, one program's utilization collapses
    rec = {"value": 101.0, "prog_achieved": {"train.step": 0.10,
                                             "serve.decode": 0.19}}
    regs = bench._gate_baseline(rec, str(bp))
    assert len(regs) == 1 and "train.step" in regs[0]
    # headline regression gates too
    regs = bench._gate_baseline(
        {"value": 50.0, "prog_achieved": base["prog_achieved"]}, str(bp))
    assert any("headline" in r for r in regs)
    # within-tolerance run passes; missing program is flagged
    assert bench._gate_baseline(dict(base), str(bp)) == []
    regs = bench._gate_baseline({"value": 100.0, "prog_achieved": {}},
                                str(bp))
    assert len(regs) == 2
    # degraded on either side: an environment fact, not a regression
    assert bench._gate_baseline({"value": 0.0, "degraded_cpu": True},
                                str(bp)) == []
    # unreadable baseline degrades to no gate
    assert bench._gate_baseline(dict(base), str(tmp_path / "nope")) == []


def test_peak_flops_ladder(monkeypatch):
    monkeypatch.setenv("PALLAS_AXON_TPU_GEN", "v5e")
    assert bench._peak_flops() == 197e12
    monkeypatch.setenv("PALLAS_AXON_TPU_GEN", "v6e")
    assert bench._peak_flops() == 918e12
    monkeypatch.setenv("PALLAS_AXON_TPU_GEN", "")
    # CPU backend, no env hint -> None (mfu omitted, bench still runs)
    assert bench._peak_flops() is None


def test_require_backend_degraded_exit_paths(monkeypatch, capsys):
    """The degraded exit contract (BENCH_r02–r05 postmortem): a wedged
    TPU tunnel must NEVER surface rc=3 with a bare ``value: 0.0`` —
    the CPU fallback runs (exit 0 at the end of main), and every
    record names WHY it is degraded via ``degraded_reason``."""
    from distributedtraining_tpu import utils as dt_utils

    # 1. live non-TPU backend (this CI): degraded with a reason, no exit
    backend, reason = bench._require_backend(timeout_s=30.0)
    assert backend == "cpu"
    assert reason is not None and "no TPU backend" in reason

    # 2. TPU probe wedges, CPU fallback initializes: degrade + reason
    calls = {"n": 0}

    def fake_run_with_timeout(fn, timeout, name=None):
        calls["n"] += 1
        if name == "tpu-backend":
            raise dt_utils.ChainTimeout(f"{name} wedged")
        return fn() if name != "cpu-backend" else None

    monkeypatch.setattr(dt_utils, "run_with_timeout",
                        fake_run_with_timeout)
    backend, reason = bench._require_backend(timeout_s=1.0)
    assert backend == "cpu_fallback"
    assert "unreachable" in reason
    assert calls["n"] == 2

    # 3. even the CPU fallback cannot initialize: the emergency record
    # still exits 0 (an environment fact, not a bench failure) and
    # carries degraded_reason
    def always_wedged(fn, timeout, name=None):
        raise dt_utils.ChainTimeout(f"{name} wedged")

    monkeypatch.setattr(dt_utils, "run_with_timeout", always_wedged)
    with pytest.raises(SystemExit) as exc:
        bench._require_backend(timeout_s=1.0)
    assert exc.value.code == 0
    rec = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert rec["value"] == 0.0
    assert "degraded_reason" in rec and "unreachable" in \
        rec["degraded_reason"]
    assert rec["vs_baseline"] is None   # never reads as a 0.0 regression
    # even the emergency record carries version forensics (the backend
    # probes would wedge, so device fields are rightly absent)
    assert rec["jax_version"] == jax.__version__
    assert "jaxlib_version" in rec
