"""Round-trip observability layer (utils/obs.py + scripts/obs_report.py).

Covers: span nesting/ordering through the configured sink, histogram
percentiles against the numpy reference, registry name/kind linting,
JSONLSink thread-safety, anomaly triggers arming a TraceCapture exactly
once, TraceCapture arm gating, and the full correlation-id round trip —
a localfs miner -> validator -> averager mini-round whose three JSONL
streams join into one per-delta phase trace via scripts/obs_report.py.
"""

import json
import os
import sys
import threading

import jax
import numpy as np
import pytest

from distributedtraining_tpu.engine import TrainEngine
from distributedtraining_tpu.engine.average import AveragerLoop, WeightedAverage
from distributedtraining_tpu.engine.train import MinerLoop
from distributedtraining_tpu.engine.validate import Validator
from distributedtraining_tpu.chain.local import LocalChain
from distributedtraining_tpu.models import gpt2
from distributedtraining_tpu.transport import LocalFSTransport
from distributedtraining_tpu.utils import obs
from distributedtraining_tpu.utils.metrics import (InMemorySink, JSONLSink,
                                                   TraceCapture,
                                                   device_metrics,
                                                   live_captures)
from distributedtraining_tpu.utils.obs import AnomalyMonitor, Registry

sys.path.insert(0, os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "scripts"))
import obs_report  # noqa: E402


@pytest.fixture(autouse=True)
def _clean_obs():
    obs.reset()
    yield
    obs.reset()


# ---------------------------------------------------------------------------
# Registry
# ---------------------------------------------------------------------------

def test_histogram_percentiles_match_numpy():
    reg = Registry()
    h = reg.histogram("test.latency_ms")
    rng = np.random.default_rng(0)
    vals = rng.uniform(0.0, 100.0, size=200)
    for v in vals:
        h.observe(float(v))
    p = h.percentiles()
    for q in (50, 95, 99):
        assert p[f"p{q}"] == pytest.approx(np.percentile(vals, q), abs=1e-9)
    assert h.count == 200
    snap = reg.snapshot()
    assert snap["test.latency_ms.count"] == 200.0
    assert snap["test.latency_ms.p95"] == p["p95"]


def test_histogram_ring_is_bounded():
    h = Registry().histogram("test.h")
    for v in range(10_000):
        h.observe(float(v))
    assert h.count == 10_000
    assert len(h._ring) == h.capacity
    # percentiles reflect the most recent window only
    assert h.percentiles()["p50"] >= 10_000 - h.capacity


def test_metric_name_lint():
    reg = Registry()
    for bad in ("Bad", "a-b", "a b", "", "UPPER.case", "x/y"):
        with pytest.raises(ValueError):
            reg.counter(bad)
    reg.counter("ok.name_1")  # valid
    # duplicate registration under a different kind is rejected
    with pytest.raises(ValueError):
        reg.histogram("ok.name_1")
    # get-or-create under the SAME kind returns the same instrument
    assert reg.counter("ok.name_1") is reg.counter("ok.name_1")


def test_registry_flush_to_sink():
    reg = Registry()
    reg.counter("c.x").inc(3)
    reg.histogram("h.y").observe(2.0)
    sink = InMemorySink()
    snap = reg.flush_to(sink, step=7)
    assert snap["c.x"] == 3.0
    assert sink.records[-1]["step"] == 7
    assert sink.records[-1]["h.y.count"] == 1.0


def test_module_helpers_noop_when_disabled():
    obs.count("x.y", 2)
    obs.observe("x.z", 1.0)
    with obs.span("x.phase"):
        pass
    assert not obs.dirty()  # nothing recorded, nothing configured


def test_registry_cardinality_cap_drops_new_names():
    reg = Registry(max_names=3)
    reg.counter("a").inc()
    reg.histogram("b").observe(1.0)
    reg.gauge("c").set(5.0)
    # past the cap: fully-usable DETACHED instruments, never snapshotted
    dropped = reg.counter("d")
    dropped.inc(99)
    reg.histogram("e").observe(1.0)
    assert len(reg) == 3
    assert reg.dropped_names == 2
    assert set(reg.names()) == {"a", "b", "c"}
    assert "d" not in reg.snapshot()
    # existing names keep working at the cap
    assert reg.counter("a") is reg.counter("a")
    with pytest.raises(ValueError):
        Registry(max_names=0)


def test_registry_merge_folds_all_instrument_kinds():
    a, b = Registry(), Registry()
    a.counter("c").inc(2)
    b.counter("c").inc(3)
    b.counter("only_b").inc(1)
    a.gauge("g").set(1.0)
    b.gauge("g").set(7.0)
    a.histogram("h").observe(1.0)
    b.histogram("h").observe(3.0)
    b.histogram("h").observe(5.0)
    out = a.merge(b)
    assert out is a
    snap = a.snapshot()
    assert snap["c"] == 5.0                  # counters add
    assert snap["only_b"] == 1.0             # new names materialize
    assert snap["g"] == 7.0                  # gauges: last-merged-wins
    assert snap["h.count"] == 3.0 and snap["h.sum"] == 9.0
    assert a.histogram("h").percentiles()["p50"] == 3.0
    # kind mismatch is the usual duplicate-registration lint
    c = Registry()
    c.histogram("c")
    with pytest.raises(ValueError):
        c.merge(a)
    # merging into a capped registry drops-and-counts past the cap
    capped = Registry(max_names=1)
    capped.merge(a)
    assert len(capped) == 1 and capped.dropped_names >= 1


def test_registry_peek_never_creates():
    reg = Registry()
    assert reg.peek("ghost") is None
    assert len(reg) == 0
    h = reg.histogram("h")
    assert reg.peek("h") is h


# ---------------------------------------------------------------------------
# Spans
# ---------------------------------------------------------------------------

def test_span_nesting_ordering_and_cid_inheritance():
    sink = InMemorySink()
    obs.configure(sink, role="tester")
    with obs.span("outer", cid="cid-1", foo="bar"):
        with obs.span("inner"):
            pass
    spans = [r for r in sink.records if "span" in r]
    assert [s["span"] for s in spans] == ["inner", "outer"]  # exit order
    inner, outer = spans
    assert inner["parent"] == "outer" and inner["depth"] == 1
    assert "parent" not in outer and outer["depth"] == 0
    assert inner["cid"] == "cid-1"  # inherited from the enclosing span
    assert outer["cid"] == "cid-1" and outer["foo"] == "bar"
    assert outer["role"] == inner["role"] == "tester"
    assert outer["dur_ms"] >= inner["dur_ms"]
    assert outer["t0"] <= inner["t0"]
    # span latencies also land in the registry
    assert obs.registry().histogram("span.outer_ms").count == 1


def test_span_records_error_flag():
    sink = InMemorySink()
    obs.configure(sink)
    with pytest.raises(RuntimeError):
        with obs.span("boom"):
            raise RuntimeError("x")
    rec = [r for r in sink.records if r.get("span") == "boom"][0]
    assert rec["error"] is True


def test_correlate_is_thread_local():
    sink = InMemorySink()
    obs.configure(sink)
    seen = {}

    def worker():
        seen["worker_cid"] = obs.current_cid()
        with obs.correlate("w-1"):
            with obs.span("w.phase"):
                pass

    with obs.correlate("main-1"):
        t = threading.Thread(target=worker)
        t.start()
        t.join()
        assert obs.current_cid() == "main-1"
    assert seen["worker_cid"] is None  # main's cid never leaked across
    rec = [r for r in sink.records if r.get("span") == "w.phase"][0]
    assert rec["cid"] == "w-1"


# ---------------------------------------------------------------------------
# JSONLSink thread-safety (PR satellite)
# ---------------------------------------------------------------------------

def test_jsonl_sink_concurrent_writers_no_torn_lines(tmp_path):
    path = tmp_path / "metrics.jsonl"
    sink = JSONLSink(str(path))
    n_threads, n_records = 8, 200

    def writer(tid):
        for i in range(n_records):
            sink.log({"tid": tid, "i": i, "pad": "x" * 64})

    threads = [threading.Thread(target=writer, args=(t,))
               for t in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    sink.close()
    lines = path.read_text().splitlines()
    assert len(lines) == n_threads * n_records
    recs = [json.loads(line) for line in lines]  # every line parses whole
    per_tid = {}
    for r in recs:
        per_tid.setdefault(r["tid"], []).append(r["i"])
    for tid, seq in per_tid.items():
        assert seq == list(range(n_records))  # per-writer order preserved


def test_jsonl_sink_lazy_file_creation(tmp_path):
    path = tmp_path / "lazy.jsonl"
    sink = JSONLSink(str(path))
    assert not path.exists()  # no file until the first record
    sink.log({"a": 1})
    assert path.exists()
    sink.close()


# ---------------------------------------------------------------------------
# Anomaly triggers + TraceCapture arming
# ---------------------------------------------------------------------------

class _StubCapture:
    def __init__(self):
        self.arm_calls = 0
        self.ticks = 0
        self.closed = False

    def arm(self):
        self.arm_calls += 1

    def tick(self):
        self.ticks += 1

    def close(self):
        self.closed = True


def test_anomaly_loss_spike_arms_capture_exactly_once():
    cap = _StubCapture()
    mon = AnomalyMonitor(cap, loss_warmup=2, push_failure_streak=2)
    for _ in range(3):
        mon.observe_loss(1.0)
    assert mon.triggered is None
    mon.observe_loss(10.0)  # > 2x EMA
    assert mon.triggered == "loss_spike"
    assert cap.arm_calls == 1
    # later anomalies of ANY kind never re-arm
    mon.observe_loss(100.0)
    mon.observe_push_counters(0, 5)
    mon.observe_loss(float("nan"))
    assert cap.arm_calls == 1
    assert mon.triggered == "loss_spike"  # first reason wins


def test_anomaly_push_failure_streak():
    cap = _StubCapture()
    mon = AnomalyMonitor(cap, push_failure_streak=3)
    mon.observe_push_counters(pushes=1, failed=1)
    mon.observe_push_counters(pushes=2, failed=1)  # success resets streak
    mon.observe_push_counters(pushes=2, failed=2)
    mon.observe_push_counters(pushes=2, failed=3)
    assert mon.triggered is None
    mon.observe_push_counters(pushes=2, failed=4)
    assert mon.triggered == "push_failure_streak"
    assert cap.arm_calls == 1


def test_anomaly_step_time_p99_blowout():
    cap = _StubCapture()
    mon = AnomalyMonitor(cap, step_warmup=64, check_every=32,
                         step_p99_factor=8.0)
    for _ in range(63):
        mon.observe_step_ms(1.0)
    assert mon.triggered is None
    for _ in range(33):  # p99 >> 8x p50 once the check lands
        mon.observe_step_ms(500.0)
    assert mon.triggered == "step_time_p99"
    assert cap.arm_calls == 1


def test_anomaly_nonfinite_loss_triggers():
    mon = AnomalyMonitor(None)  # capture-less monitor: detection only
    mon.observe_loss(float("inf"))
    assert mon.triggered == "loss_nonfinite"


class _FakeProfiler:
    def __init__(self):
        self.started = []
        self.stopped = 0

    def start_trace(self, d):
        self.started.append(d)

    def stop_trace(self):
        self.stopped += 1


class _FakeJax:
    def __init__(self):
        self.profiler = _FakeProfiler()


def test_tracecapture_arm_gating(tmp_path):
    cap = TraceCapture(str(tmp_path / "tr"), steps=2, skip=1, arm=False)
    cap._jax = _FakeJax()  # never touch the real profiler in tests
    for _ in range(10):
        cap.tick()  # disarmed: free no-ops
    assert not cap._jax.profiler.started and not cap._done
    cap.arm()
    assert cap.armed
    cap.tick()                       # skip window
    assert not cap._jax.profiler.started
    cap.tick()                       # starts
    assert cap._jax.profiler.started == [str(tmp_path / "tr")]
    assert cap in live_captures()
    cap.tick()                       # in-window
    cap.tick()                       # stops (seen > skip + steps)
    assert cap._jax.profiler.stopped == 1 and cap._done
    assert cap not in live_captures()
    cap.arm()                        # a finished capture can never re-arm
    cap.tick()
    assert cap._jax.profiler.stopped == 1
    assert len(cap._jax.profiler.started) == 1


def test_tracecapture_default_is_armed(tmp_path):
    cap = TraceCapture(str(tmp_path / "tr"), steps=1, skip=0)
    cap._jax = _FakeJax()
    cap.tick()
    assert cap._jax.profiler.started  # legacy behavior: live immediately
    cap.close()
    assert cap._jax.profiler.stopped == 1


def test_device_metrics_cached_psutil_state():
    a = device_metrics()
    b = device_metrics()
    assert "chain_abandoned_workers" in a
    # psutil ships in this image; the cached-state path must keep serving
    if "rss_mb" in a:
        assert "rss_mb" in b and b["rss_mb"] > 0


# ---------------------------------------------------------------------------
# obs_report joining
# ---------------------------------------------------------------------------

def _write_jsonl(path, records):
    with open(path, "w") as f:
        for r in records:
            f.write(json.dumps(r) + "\n")


def test_obs_report_joins_three_streams(tmp_path):
    cid = "hotkey_0-000001"
    _write_jsonl(tmp_path / "miner.jsonl", [
        {"ts": 1.0, "train_loss": 3.2},  # non-span records are ignored
        {"span": "push.snapshot", "cid": cid, "dur_ms": 5.0, "t0": 100.0},
        {"span": "push.upload", "cid": cid, "dur_ms": 50.0, "t0": 100.01},
    ])
    _write_jsonl(tmp_path / "validator.jsonl", [
        {"span": "val.fetch", "cid": cid, "dur_ms": 8.0, "t0": 101.0},
        {"span": "val.screen", "cid": cid, "dur_ms": 2.0, "t0": 101.01},
        {"span": "val.cohort_eval", "cids": [cid, "other-000007"],
         "dur_ms": 30.0, "t0": 102.0},
    ])
    _write_jsonl(tmp_path / "averager.jsonl", [
        {"span": "avg.merge", "cids": [cid], "dur_ms": 20.0, "t0": 110.0},
    ])
    rep = obs_report.report([str(tmp_path / f) for f in
                             ("miner.jsonl", "validator.jsonl",
                              "averager.jsonl")])
    tr = rep["deltas"][cid]
    assert set(tr["phases_ms"]) == {"snapshot", "upload", "fetch", "screen",
                                    "eval", "merge"}
    assert tr["phases_ms"]["upload"] == pytest.approx(50.0)
    assert tr["phases_ms"]["eval"] == pytest.approx(30.0)
    assert tr["shared_by"]["eval"] == 2  # cohort program shared by 2 cids
    assert tr["roundtrip_s"] == pytest.approx(110.02 - 100.0, abs=1e-3)
    # the cohort-mate got its own (eval-only) trace
    assert "other-000007" in rep["deltas"]
    table = obs_report.format_table(rep)
    assert cid in table and "roundtrip_s" in table


def test_obs_report_tolerates_torn_tail(tmp_path):
    p = tmp_path / "m.jsonl"
    with open(p, "w") as f:
        f.write(json.dumps({"span": "push.upload", "cid": "c-1",
                            "dur_ms": 1.0, "t0": 1.0}) + "\n")
        f.write('{"span": "push.m')  # crashed writer's torn last line
    rep = obs_report.report([str(p)])
    assert list(rep["deltas"]) == ["c-1"]


# ---------------------------------------------------------------------------
# Correlation round trip: localfs miner -> validator -> averager
# ---------------------------------------------------------------------------

def _batch(cfg, n=2, seq=16, seed=0):
    rng = np.random.default_rng(seed)
    return {"input_ids": np.asarray(
        rng.integers(0, cfg.vocab_size, (n, seq)), np.int32)}


def test_correlation_id_roundtrip_localfs(tmp_path):
    model, cfg = gpt2.make_model("tiny")
    transport = LocalFSTransport(str(tmp_path / "artifacts"))
    chain_dir = str(tmp_path / "chain")
    batch = _batch(cfg)

    def eval_batches():
        yield _batch(cfg, seed=1)

    paths = {r: str(tmp_path / f"{r}.jsonl")
             for r in ("miner", "validator", "averager")}

    # -- miner: train a few steps, push with a correlation id --------------
    sink = JSONLSink(paths["miner"])
    obs.configure(sink, role="miner")
    try:
        loop = MinerLoop(TrainEngine(model, seq_len=16), transport,
                         "hotkey_0", send_interval=1e9,
                         check_update_interval=1e9, metrics=sink,
                         log_every=2)
        loop.bootstrap(jax.random.PRNGKey(0))
        loop.run(iter([batch] * 3), max_steps=3)
        loop.flush()  # the push: snapshot/upload spans + delta_id rider
        assert loop.report.pushes == 1
    finally:
        obs.reset()
        sink.close()

    meta = transport.fetch_delta_meta("hotkey_0")
    cid = obs.rider_delta_id(meta)
    assert cid == "hotkey_0-000001"

    # -- validator: cohort-scores the delta, spans tagged with the cid -----
    sink = JSONLSink(paths["validator"])
    obs.configure(sink, role="validator")
    try:
        val = Validator(TrainEngine(model, seq_len=16), transport,
                        LocalChain(chain_dir, my_hotkey="hotkey_91"),
                        eval_batches=eval_batches, metrics=sink,
                        cohort_size=8, pipeline_depth=1)
        val.bootstrap(rng=jax.random.PRNGKey(0))
        results = val.validate_and_score()
        assert any(s.hotkey == "hotkey_0" and s.loss is not None
                   for s in results)
    finally:
        obs.reset()
        sink.close()

    # -- averager: merges it, the merge span records the cid ---------------
    sink = JSONLSink(paths["averager"])
    obs.configure(sink, role="averager")
    try:
        avg = AveragerLoop(TrainEngine(model, seq_len=16), transport,
                           LocalChain(chain_dir, my_hotkey="hotkey_99"),
                           WeightedAverage(uniform=True),
                           val_batches=eval_batches, metrics=sink)
        avg.bootstrap(rng=jax.random.PRNGKey(0))
        assert avg.run_round() is True
        assert avg.report.last_accepted == 1
    finally:
        obs.reset()
        sink.close()

    # -- join: one trace covering the artifact's whole life ----------------
    rep = obs_report.report(list(paths.values()))
    assert cid in rep["deltas"], rep["deltas"].keys()
    phases = rep["deltas"][cid]["phases_ms"]
    for phase in ("snapshot", "upload", "fetch", "screen", "eval", "merge"):
        assert phase in phases, f"missing {phase}: {phases}"
    assert rep["deltas"][cid]["roundtrip_s"] >= 0
    # per-role roles tagged correctly in the raw records
    recs = obs_report.load_records([paths["validator"]])
    vs = [r for r in recs if r.get("span") == "val.fetch"
          and r.get("cid") == cid]
    assert vs and vs[0]["role"] == "validator"
    # the averager's metrics record names which delta ids entered the merge
    arecs = obs_report.load_records([paths["averager"]])
    merged_ids = [r["merge_delta_ids"] for r in arecs
                  if "merge_delta_ids" in r]
    assert merged_ids and merged_ids[-1] == {"hotkey_0": cid}


# ---------------------------------------------------------------------------
# Doc-drift lint: every dt_* name the exporter can emit is documented
# ---------------------------------------------------------------------------

def test_every_exporter_metric_name_is_documented():
    """Doc-drift lint (PR-13 satellite, the metric twin of the
    EVENT_KINDS/devprof producer-lint discipline): every dt_* metric
    name the Prometheus exporter (utils/obs_http.py) can emit must
    appear in docs/observability.md. Three emission sources:

    - registry names: every LITERAL first argument of obs.count /
      obs.gauge across the package (dynamic f-string names are covered
      by their documented ``<rule>``-style placeholder rows and are
      not enumerable statically);
    - span names: every literal obs.span(...) name (rendered as
      ``span.<name>_ms`` / the span taxonomy table);
    - labeled families: the _FLEET_SERIES ledger series, the SLO
      breach family, and every literal dt_* family in
      utils/devprof.py + utils/obs_http.py.

    A metric added without a doc row fails HERE, at the producer, not
    in a dashboard review months later."""
    import ast
    import glob as _glob
    import re

    import distributedtraining_tpu as pkg
    from distributedtraining_tpu.utils import devprof, obs_http

    root = os.path.dirname(pkg.__file__)
    doc_path = os.path.join(os.path.dirname(root), "docs",
                            "observability.md")
    doc = open(doc_path).read()

    counter_names: set[str] = set()
    span_names: set[str] = set()
    for path in _glob.glob(os.path.join(root, "**", "*.py"),
                           recursive=True):
        tree = ast.parse(open(path).read(), filename=path)
        for node in ast.walk(tree):
            if not (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and isinstance(node.func.value, ast.Name)
                    and node.func.value.id == "obs"
                    and node.args
                    and isinstance(node.args[0], ast.Constant)
                    and isinstance(node.args[0].value, str)):
                continue
            if node.func.attr in ("count", "gauge"):
                counter_names.add(node.args[0].value)
            elif node.func.attr == "span":
                span_names.add(node.args[0].value)

    families = {"dt_" + suffix for _, suffix, _ in obs_http._FLEET_SERIES}
    families.add("dt_fleet_slo_breached")
    for mod in (devprof, obs_http):
        src = open(mod.__file__).read()
        families |= set(re.findall(r'"(dt_[a-z0-9_]+)"', src))

    missing = sorted(
        [n for n in counter_names if n not in doc]
        + [f"span:{n}" for n in span_names if n not in doc]
        + [f for f in families if f not in doc])
    assert not missing, (
        "metric names the exporter can emit are missing from "
        f"docs/observability.md: {missing} — add a table row (or a "
        "placeholder rule row) for each")


def test_concurrent_scrapes_during_registry_flush():
    """Satellite: /metrics and /debug/dump raced from two scraper
    threads while the main thread churns the registry with flushes and
    new series — every response parses, no 500s, no torn Prometheus
    text (partial lines / missing trailing newline), and the exporter
    survives to serve a clean final scrape."""
    import urllib.request

    from distributedtraining_tpu.utils import flight
    from distributedtraining_tpu.utils.obs_http import ObsHTTPExporter

    sink = InMemorySink()
    obs.configure(sink, role="scraper")
    flight.configure("scraper", "s0")
    exp = ObsHTTPExporter(0, role="scraper")
    port = exp.start()
    stop = threading.Event()
    errors: list = []
    bodies: list = []

    def _scrape(path, parse):
        while not stop.is_set():
            try:
                with urllib.request.urlopen(
                        f"http://127.0.0.1:{port}{path}",
                        timeout=10) as r:
                    raw = r.read().decode()
                    assert r.status == 200
                parse(raw)
                bodies.append(path)
            except Exception as e:  # noqa: BLE001 - collected for assert
                errors.append((path, repr(e)))
                return

    def _parse_prom(raw):
        assert raw.endswith("\n"), "torn text: no trailing newline"
        for ln in raw.splitlines():
            if ln and not ln.startswith("#"):
                name = ln.split("{")[0].split(" ")[0]
                assert name.startswith("dt_"), f"torn line: {ln!r}"
                float(ln.rsplit(" ", 1)[1])

    threads = [
        threading.Thread(target=_scrape, args=("/metrics", _parse_prom)),
        threading.Thread(target=_scrape,
                         args=("/debug/dump", json.loads)),
    ]
    for t in threads:
        t.start()
    try:
        # churn: new counter names, histogram traffic, full flushes and
        # flight events racing the scrapers' renders — keep churning
        # until both endpoints have been scraped several times
        import time as _time
        deadline = _time.time() + 30.0
        i = 0
        while (bodies.count("/metrics") < 4
               or bodies.count("/debug/dump") < 4) and not errors \
                and _time.time() < deadline:
            obs.count(f"scrape.race_{i % 7}")
            obs.observe("scrape.lat_ms", float(i))
            obs.gauge("scrape.g", float(i))
            flight.record("note", text=f"race {i}")
            obs.registry().flush_to(sink, step=i)
            i += 1
    finally:
        stop.set()
        for t in threads:
            t.join(timeout=30)
        exp.close()
        flight.shutdown()
    assert not errors, errors
    # both endpoints actually got scraped repeatedly under churn
    assert bodies.count("/metrics") > 3
    assert bodies.count("/debug/dump") > 3
