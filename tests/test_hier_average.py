"""Hierarchical sharded averager (engine/hier_average.py + the packed
accumulate path in delta.py + the cached sharded cohort merge in
parallel/collectives.py).

The parity pins here are the round's acceptance contract: a sub-averager
gathering a MIXED fleet (v1 dense and v2 packed miners) must produce
aggregates identical to the flat merge of the same set; the root's merge
of sub aggregates must equal the flat weighted merge of every miner
within fp tolerance; the packed accumulate must never materialize a
dense M x params stack; and a sub-averager killed mid-publish must
degrade the root to the surviving subtrees, never sink the round.
"""

import os
from types import SimpleNamespace

import jax
import numpy as np
import pytest

from distributedtraining_tpu import delta as dl
from distributedtraining_tpu.engine.average import (AveragerLoop,
                                                    WeightedAverage)
from distributedtraining_tpu.engine.hier_average import (SubAverager,
                                                         plan_fanout,
                                                         subtree_weights)
from distributedtraining_tpu.engine.ingest import DeltaIngestor
from distributedtraining_tpu.parallel import collectives
from distributedtraining_tpu.parallel.mesh import MeshConfig, make_mesh
from distributedtraining_tpu.transport import base as tbase
from distributedtraining_tpu.transport.chaos import ChaosSpec, ChaosTransport
from distributedtraining_tpu.transport.localfs import LocalFSTransport
from distributedtraining_tpu.transport.memory import InMemoryTransport
from distributedtraining_tpu.transport.retry import RetryPolicy
from distributedtraining_tpu.utils import obs

FAST_RETRY = RetryPolicy(attempts=2, base_delay=0.0, max_delay=0.0,
                         jitter=0.0)


def _tree(seed=0, big=(300, 40), small=(32,)):
    """A delta tree with one above-cutoff tensor (top-k sparsified on the
    v2 wire) and one below-cutoff tensor (dense-form entry)."""
    rs = np.random.RandomState(seed)
    return {"wte": (rs.randn(*big) * 0.01).astype(np.float32),
            "ln": {"g": (rs.randn(*small) * 0.01).astype(np.float32)}}


def _template(tree=None):
    return jax.tree_util.tree_map(
        lambda x: np.zeros(np.shape(x), np.float32), tree or _tree())


def _leaves(t):
    return [np.asarray(jax.device_get(x))
            for x in jax.tree_util.tree_leaves(t)]


def _sub(transport, node, template, assigned, **kw):
    kw.setdefault("retry_policy", FAST_RETRY)
    kw.setdefault("publish_retry", FAST_RETRY)
    kw.setdefault("meta_retry", FAST_RETRY)
    kw.setdefault("ingest_workers", 1)
    return SubAverager(transport, node, template, assigned, **kw)


# ---------------------------------------------------------------------------
# Fanout planning + subtree weights
# ---------------------------------------------------------------------------

def test_plan_fanout_deterministic_balanced_and_total():
    hotkeys = [f"m{i}" for i in range(10)]
    plan = plan_fanout(hotkeys, fanout=4)
    assert sorted(plan) == ["sub0", "sub1", "sub2"]   # ceil(10/4) nodes
    # every miner assigned exactly once, slices balanced to within one
    assigned = [h for slice_ in plan.values() for h in slice_]
    assert sorted(assigned) == sorted(hotkeys)
    sizes = {len(s) for s in plan.values()}
    assert max(sizes) - min(sizes) <= 1
    # deterministic under enumeration order (round-robin over SORTED keys)
    plan2 = plan_fanout(list(reversed(hotkeys)), fanout=4)
    assert plan == plan2
    # explicit node list: every node present even when the fleet shrinks
    plan3 = plan_fanout(["m0"], nodes=["a", "b"])
    assert plan3 == {"a": ["m0"], "b": []}
    with pytest.raises(ValueError):
        plan_fanout(hotkeys)


def test_subtree_weights_mass_and_uniform_fallback():
    w, mass = subtree_weights(["a", "b"], {"a": 3.0, "b": 1.0})
    np.testing.assert_allclose(np.asarray(w), [0.75, 0.25])
    assert mass == 4.0
    # no score mass -> uniform vector, miner-COUNT mass (the spelling
    # under which the root's C_j/sum(C) telescopes to flat uniform 1/M)
    w, mass = subtree_weights(["a", "b", "c"], {})
    np.testing.assert_allclose(np.asarray(w), [1 / 3] * 3)
    assert mass == 3.0
    w, mass = subtree_weights(["a"], {"a": -5.0})
    np.testing.assert_allclose(np.asarray(w), [1.0])
    assert mass == 1.0


def test_normalized_weights_use_unpadded_m():
    """The 1-miner-on-a-mesh edge (satellite pin): weights normalize over
    the REAL m; padding to an axis or bucket adds zero-weight slots that
    change nothing. A normalization over the padded m would publish
    1/axis_size of the update."""
    w = dl.normalized_merge_weights(["only"], {})
    np.testing.assert_array_equal(np.asarray(w), [1.0])
    padded = dl.pad_merge_weights(w, 8)
    assert padded.shape == (8,)
    assert float(padded.sum()) == 1.0      # mass preserved, not 1/8

    base = _tree(99)
    d = _tree(7)
    stacked = dl.pad_stack(dl.stack_deltas([d]), 8)
    assert dl.miner_axis_size(stacked) == 8
    merged = dl.weighted_merge_jit(base, stacked,
                                   dl.pad_merge_weights(w, 8))
    for got, b, x in zip(_leaves(merged), _leaves(base), _leaves(d)):
        np.testing.assert_array_equal(got, b + x)   # exactly base + delta


def test_one_miner_mesh_merge_exact(devices):
    """Same pin through the sharded path: a 1-miner cohort padded to an
    8-wide mesh axis merges to exactly base + delta."""
    collectives.reset_merge_cache()
    base = _tree(1)
    d = _tree(2)
    mesh = make_mesh(MeshConfig(dp=8))
    w = dl.normalized_merge_weights(["only"], None)
    merged = collectives.sharded_cohort_merge(
        base, dl.stack_deltas([d]), w, mesh, axis="dp")
    for got, b, x in zip(_leaves(merged), _leaves(base), _leaves(d)):
        np.testing.assert_allclose(got, b + x, rtol=0, atol=0)


# ---------------------------------------------------------------------------
# Packed accumulate (the merge path that never densifies a stack)
# ---------------------------------------------------------------------------

def test_accumulate_packed_matches_densify_path():
    """The packed scatter-add decodes with the densifier's arithmetic —
    equal to acc + w * densify up to XLA multiply-add fusion (~1 ulp)."""
    delta = _tree(3)
    packed, _ = dl.pack_delta_v2(delta, density=1 / 8)
    packed = jax.device_get(packed)
    acc = _tree(4)
    w = 0.37
    got = dl.accumulate_delta(acc, packed, w)
    dense = dl.densify_packed_v2(packed, _template())
    ref = jax.tree_util.tree_map(
        lambda a, x: a + np.float32(w) * x, acc, dense)
    for g, r in zip(_leaves(got), _leaves(ref)):
        np.testing.assert_allclose(g, r, rtol=1e-5, atol=1e-8)


def test_aggregate_deltas_mixed_fleet_matches_flat_merge():
    """A mixed v1-dense + v2-packed cohort aggregates identically to the
    flat weighted merge of the densified set (satellite pin)."""
    dense_deltas = [_tree(i) for i in range(2)]
    packed_deltas = []
    for i in range(2, 4):
        p, _ = dl.pack_delta_v2(_tree(i), density=1 / 8)
        packed_deltas.append(jax.device_get(p))
    mixed = dense_deltas + packed_deltas
    w = dl.normalized_merge_weights(
        ["a", "b", "c", "d"], {"a": 1.0, "b": 2.0, "c": 3.0, "d": 4.0})
    agg = dl.aggregate_deltas(_template(), mixed, w)

    densified = dense_deltas + [dl.densify_packed_v2(p, _template())
                                for p in packed_deltas]
    flat = dl.weighted_merge(_template(), dl.stack_deltas(densified), w)
    for g, r in zip(_leaves(agg), _leaves(flat)):
        np.testing.assert_allclose(g, r, rtol=1e-6, atol=1e-7)


def test_packed_accumulate_never_builds_a_stack_or_densifies(monkeypatch):
    """The acceptance invariant, asserted structurally: aggregating an
    all-packed cohort must touch neither stack_deltas (the M x params
    stack) nor densify_packed_v2 (a dense per-miner copy)."""
    def boom(*a, **k):
        raise AssertionError("packed merge path materialized dense state")

    monkeypatch.setattr(dl, "stack_deltas", boom)
    monkeypatch.setattr(dl, "densify_packed_v2", boom)
    packed = [jax.device_get(dl.pack_delta_v2(_tree(i), density=1 / 8)[0])
              for i in range(6)]
    agg = dl.aggregate_deltas(_template(), packed,
                              np.full((6,), 1 / 6, np.float32))
    assert all(np.isfinite(l).all() for l in _leaves(agg))


# ---------------------------------------------------------------------------
# Cached sharded cohort merge (the pjit'd mesh path)
# ---------------------------------------------------------------------------

def test_sharded_cohort_merge_parity_and_bucket_reuse(devices):
    collectives.reset_merge_cache()
    base = _tree(0)
    deltas = [_tree(i + 1) for i in range(5)]
    w5 = dl.normalized_merge_weights(
        [f"m{i}" for i in range(5)], {f"m{i}": float(i + 1)
                                      for i in range(5)})
    mesh = make_mesh(MeshConfig(dp=8))

    got = collectives.sharded_cohort_merge(
        base, dl.stack_deltas(deltas), w5, mesh, axis="dp")
    ref = collectives.psum_weighted_merge(
        base, dl.stack_deltas(deltas), w5, mesh, axis="dp")
    for a, b in zip(_leaves(got), _leaves(ref)):
        np.testing.assert_allclose(a, b, rtol=1e-6, atol=1e-7)

    # a wobbling cohort (3 then 5 then 7) lands on ONE padded bucket (8
    # on an 8-wide axis) and ONE compiled program — no compile storm
    for m in (3, 7):
        sub = deltas[:m] if m <= len(deltas) else deltas + [
            _tree(10 + i) for i in range(m - len(deltas))]
        wm = dl.normalized_merge_weights([str(i) for i in range(m)], None)
        collectives.sharded_cohort_merge(
            base, dl.stack_deltas(sub), wm, mesh, axis="dp")
    assert len(collectives._MERGE_PROGRAMS) == 1
    seen = {t for (mk, ak, t) in collectives._MERGE_BUCKETS_SEEN
            if mk is mesh}
    assert seen == {8}

    # prefer_compiled: a 9-miner cohort would target 16, but with no 16
    # program compiled and none bigger, it compiles 16; afterwards a
    # 10-miner cohort reuses it instead of minting a new rung
    assert collectives.merge_bucket(9, mesh, "dp") == 16
    collectives.mark_merge_bucket(16, mesh, "dp")
    assert collectives.merge_bucket(10, mesh, "dp") == 16
    collectives.reset_merge_cache()


def test_merge_bucket_ladder_single_device():
    collectives.reset_merge_cache()
    assert collectives.merge_bucket(1) == 1
    assert collectives.merge_bucket(5) == 8
    assert collectives.merge_bucket(17) == 32
    # prefer_compiled pads an uncompiled rung up to a compiled one
    collectives.mark_merge_bucket(8)
    assert collectives.merge_bucket(3) == 8
    assert collectives.merge_bucket(3, prefer_compiled=False) == 4
    collectives.reset_merge_cache()


# ---------------------------------------------------------------------------
# WeightedAverage: weight memoization + packed host lists
# ---------------------------------------------------------------------------

class _Sink:
    def __init__(self):
        self.records = []

    def log(self, rec, step=None):
        self.records.append(rec)


def test_weighted_average_memoizes_consensus_weights():
    obs.configure(_Sink(), role="test")
    try:
        strat = WeightedAverage()
        engine = SimpleNamespace(mesh=None)
        base = _tree(0)
        deltas = [_tree(1), _tree(2)]
        ids = ["a", "b"]
        cons = {"a": 1.0, "b": 3.0}
        m1, w1 = strat.merge(engine, base, list(deltas), ids,
                             consensus=cons)
        assert obs.registry().snapshot().get("merge.weights_reused",
                                             0) == 0
        m2, w2 = strat.merge(engine, base, list(deltas), ids,
                             consensus=dict(cons))   # equal, not identical
        assert obs.registry().snapshot()["merge.weights_reused"] == 1
        np.testing.assert_array_equal(np.asarray(w1), np.asarray(w2))
        for a, b in zip(_leaves(m1), _leaves(m2)):
            np.testing.assert_array_equal(a, b)
        # a changed score (or cohort) recomputes
        strat.merge(engine, base, list(deltas), ids,
                    consensus={"a": 2.0, "b": 3.0})
        assert obs.registry().snapshot()["merge.weights_reused"] == 1
        np.testing.assert_allclose(np.asarray(w1), [0.25, 0.75])
    finally:
        obs.reset()


def test_weighted_average_merges_packed_host_list():
    strat = WeightedAverage()
    engine = SimpleNamespace(mesh=None)
    base = _tree(0)
    packed = [jax.device_get(dl.pack_delta_v2(_tree(i), density=1 / 8)[0])
              for i in (1, 2)]
    dense = [_tree(3)]
    merged, w = strat.merge(engine, base, packed + dense,
                            ["a", "b", "c"], consensus=None)
    densified = [dl.densify_packed_v2(p, _template()) for p in packed] \
        + dense
    ref = dl.weighted_merge(base, dl.stack_deltas(densified), w)
    for a, b in zip(_leaves(merged), _leaves(ref)):
        np.testing.assert_allclose(a, b, rtol=1e-6, atol=1e-7)


# ---------------------------------------------------------------------------
# Agg rider validation
# ---------------------------------------------------------------------------

def test_agg_rider_weight_defensive_parse():
    from distributedtraining_tpu.engine.ingest import _rider_agg_weight

    assert _rider_agg_weight({"agg": {"weight": 4.5}}) == 4.5
    assert _rider_agg_weight({"agg": {"weight": 0}}) == 0.0
    for hostile in (None, {}, {"agg": None}, {"agg": []},
                    {"agg": {"weight": "big"}}, {"agg": {"weight": -1}},
                    {"agg": {"weight": float("nan")}},
                    {"agg": {"weight": float("inf")}},
                    {"agg": {"weight": True}}, {"agg": {}}):
        assert _rider_agg_weight(hostile) is None


def test_ingestor_keeps_packed_form_when_densify_off():
    transport = InMemoryTransport()
    template = _template()
    packed, _ = dl.pack_delta_v2(_tree(5), density=1 / 8)
    from distributedtraining_tpu.engine.publish import DeltaPublisher

    class _R:
        pushes = pushes_failed = pushes_superseded = 0

    pub = DeltaPublisher(transport, "m0", report=_R(),
                         publish_retry=FAST_RETRY, meta_retry=FAST_RETRY,
                         wire_spec={"format": 2, "density": 1 / 8,
                                    "quant": "int8"})
    try:
        assert pub.publish_now(jax.device_get(packed), None, "r1")
        ing = DeltaIngestor(transport, template, workers=1,
                            max_delta_abs=1e3, retry_policy=FAST_RETRY,
                            densify=False)
        try:
            s = ing.stage(["m0"])[0]
            assert s.ok and dl.is_packed_v2(s.delta)
            # and the cache serves the packed form back on a warm round
            s2 = ing.stage(["m0"])[0]
            assert s2.cached and dl.is_packed_v2(s2.delta)
        finally:
            ing.close()
    finally:
        pub.close()


# ---------------------------------------------------------------------------
# SubAverager rounds
# ---------------------------------------------------------------------------

def test_sub_averager_publishes_flat_equivalent_aggregate(tmp_path):
    transport = LocalFSTransport(str(tmp_path))
    transport.publish_base(_tree(100))
    base_rev = transport.base_revision()
    template = _template()

    # mixed fleet: two dense v1 miners, one packed v2 miner
    d0, d1 = _tree(1), _tree(2)
    transport.publish_delta("m0", d0)
    transport.publish_delta("m1", d1)
    p2, _ = dl.pack_delta_v2(_tree(3), density=1 / 8)
    from distributedtraining_tpu.engine.publish import DeltaPublisher

    class _R:
        pushes = pushes_failed = pushes_superseded = 0

    vpub = DeltaPublisher(transport, "m2", report=_R(),
                          publish_retry=FAST_RETRY, meta_retry=FAST_RETRY,
                          wire_spec={"format": 2, "density": 1 / 8,
                                     "quant": "int8"})
    cons = {"m0": 1.0, "m1": 2.0, "m2": 5.0}
    sub = _sub(transport, "n0", template, ["m0", "m1", "m2"],
               consensus=cons)
    try:
        assert vpub.publish_now(jax.device_get(p2), None, base_rev)
        assert sub.run_round() is True
        assert sub.report.last_accepted == 3
        assert sub.report.pushes == 1

        # the aggregate is an ordinary delta under the reserved id
        aid = tbase.agg_id("n0")
        got = transport.fetch_delta(aid, template)
        assert got is not None
        meta = transport.fetch_delta_meta(aid)
        assert meta["agg"]["weight"] == 8.0          # clamped mass
        assert meta["agg"]["miners"] == 3
        assert meta["base_revision"] == base_rev

        d2 = dl.densify_packed_v2(jax.device_get(p2), template)
        w = dl.normalized_merge_weights(["m0", "m1", "m2"], cons)
        ref = dl.weighted_merge(template, dl.stack_deltas([d0, d1, d2]), w)
        for a, b in zip(_leaves(got), _leaves(ref)):
            np.testing.assert_allclose(a, b, rtol=1e-6, atol=1e-7)
    finally:
        sub.close()
        vpub.close()


def test_sub_averager_wire_v2_aggregate_is_lossless(tmp_path):
    """wire_spec=True ships the aggregate itself on the v2 shard wire at
    density 1.0 + quant none — LOSSLESS, so the root decodes the exact
    aggregate tree and parity survives the extra hop."""
    transport = LocalFSTransport(str(tmp_path))
    transport.publish_base(_tree(100))
    template = _template()
    transport.publish_delta("m0", _tree(1))
    sub = _sub(transport, "n0", template, ["m0"], wire_spec=True)
    try:
        assert sub.run_round() is True
        aid = tbase.agg_id("n0")
        ing = DeltaIngestor(transport, template, workers=1,
                            max_delta_abs=1e3, retry_policy=FAST_RETRY)
        try:
            s = ing.stage([aid])[0]
            assert s.ok
            assert s.agg_weight == 1.0
            for a, b in zip(_leaves(s.delta), _leaves(_tree(1))):
                np.testing.assert_allclose(a, b, rtol=0, atol=0)
        finally:
            ing.close()
    finally:
        sub.close()


def test_sub_averager_empty_round_publishes_nothing(tmp_path):
    transport = LocalFSTransport(str(tmp_path))
    transport.publish_base(_tree(100))
    sub = _sub(transport, "n0", _template(), ["ghost0", "ghost1"])
    try:
        assert sub.run_round() is False
        assert transport.delta_revision(tbase.agg_id("n0")) is None
    finally:
        sub.close()


def test_sub_averager_lease_standdown(tmp_path):
    """A sub-averager is just another lease-holding role (PR-6 machinery):
    when a rival holds subavg.<node> at a higher epoch, the round merges
    but publishes nothing."""
    from distributedtraining_tpu.engine.remediate import LeaseManager

    transport = LocalFSTransport(str(tmp_path))
    transport.publish_base(_tree(100))
    transport.publish_delta("m0", _tree(1))
    rival = LeaseManager(transport, "rival", role="subavg.n0")
    assert rival.acquire()
    mine = LeaseManager(transport, "me", role="subavg.n0")
    sub = _sub(transport, "n0", _template(), ["m0"], lease=mine)
    try:
        assert mine.acquire()          # epoch rival+1: now the holder
        assert rival.renew() is False  # rival stands down
        assert sub.run_round() is True
        assert sub.report.pushes == 1  # held lease -> published
        # rival steals the lease back at a higher epoch: next round
        # merges but stands down instead of double-writing the aggregate
        assert rival.acquire()
        assert sub.run_round() is True
        assert sub.report.pushes == 1
        assert sub.report.skipped_publishes == 1
    finally:
        sub.close()


# ---------------------------------------------------------------------------
# Root round: hierarchy == flat, and degradation under chaos
# ---------------------------------------------------------------------------

def _engine_fixture():
    from distributedtraining_tpu.engine import TrainEngine
    from distributedtraining_tpu.models import gpt2

    model, cfg = gpt2.make_model("tiny")
    return TrainEngine(model, seq_len=16), cfg


def _eval_batches(cfg, n=1):
    rs = np.random.RandomState(0)
    batches = [{"input_ids": rs.randint(0, cfg.vocab_size, (2, 16))
                .astype(np.int32)} for _ in range(n)]

    def factory():
        return iter(list(batches))

    return factory


class _Chain:
    def __init__(self, hotkeys, consensus=None, my_hotkey="avg"):
        self.my_hotkey = my_hotkey
        self._hotkeys = list(hotkeys)
        self._consensus = dict(consensus or {})

    def sync(self):
        return SimpleNamespace(hotkeys=self._hotkeys + [self.my_hotkey])

    def consensus_scores(self):
        return dict(self._consensus)


def _publish_fleet(transport, template, consensus):
    """Six miners: four dense v1, two packed v2 — the mixed fleet."""
    from distributedtraining_tpu.engine.publish import DeltaPublisher

    deltas = {}
    for i in range(4):
        h = f"m{i}"
        deltas[h] = jax.tree_util.tree_map(
            lambda x, s=i: (0.01 * (s + 1)
                            * np.random.RandomState(s).randn(*np.shape(x))
                            ).astype(np.float32), template)
        transport.publish_delta(h, deltas[h])
    for i in range(4, 6):
        h = f"m{i}"
        raw = jax.tree_util.tree_map(
            lambda x, s=i: (0.01 * (s + 1)
                            * np.random.RandomState(s).randn(*np.shape(x))
                            ).astype(np.float32), template)
        packed, _ = dl.pack_delta_v2(raw, density=1 / 8)
        packed = jax.device_get(packed)

        class _R:
            pushes = pushes_failed = pushes_superseded = 0

        pub = DeltaPublisher(transport, h, report=_R(),
                             publish_retry=FAST_RETRY,
                             meta_retry=FAST_RETRY,
                             wire_spec={"format": 2, "density": 1 / 8,
                                        "quant": "int8"})
        try:
            assert pub.publish_now(packed, None, None)
        finally:
            pub.close()
        deltas[h] = dl.densify_packed_v2(packed, template)
    return deltas


def test_hierarchy_parity_with_flat_merge(tmp_path):
    """END-TO-END parity pin (acceptance): fanout-2 hierarchy over a
    mixed 6-miner fleet publishes the same base as the flat single-node
    merge of the identical submissions, within fp tolerance."""
    from distributedtraining_tpu.engine.train import host_wire_template

    engine, cfg = _engine_fixture()
    template = host_wire_template(engine)
    hotkeys = [f"m{i}" for i in range(6)]
    consensus = {h: float(i + 1) for i, h in enumerate(hotkeys)}

    results = {}
    for mode in ("flat", "hier"):
        transport = LocalFSTransport(str(tmp_path / mode))
        chain = _Chain(hotkeys, consensus)
        loop = AveragerLoop(
            engine, transport, chain, WeightedAverage(),
            val_batches=_eval_batches(cfg), publish_policy="always",
            stale_deltas="skip", ingest_workers=1,
            hierarchy=None if mode == "flat" else ["n0", "n1", "n2"])
        loop.bootstrap(rng=jax.random.PRNGKey(0))
        deltas = _publish_fleet(transport, template, consensus)
        subs = []
        try:
            if mode == "hier":
                plan = plan_fanout(hotkeys, nodes=["n0", "n1", "n2"])
                for node, slice_ in plan.items():
                    sub = _sub(transport, node, template, slice_,
                               consensus=consensus)
                    subs.append(sub)
                    assert sub.run_round() is True
            assert loop.run_round() is True
            assert loop.report.last_accepted == (6 if mode == "flat"
                                                 else 3)
            fetched = transport.fetch_base(template)
            assert fetched is not None
            results[mode] = fetched[0]
        finally:
            for sub in subs:
                sub.close()
            loop.close()

    # reference check: the flat merge really is sum (c_i / C) d_i
    for a, b in zip(_leaves(results["flat"]), _leaves(results["hier"])):
        np.testing.assert_allclose(a, b, rtol=2e-5, atol=1e-6)


def test_root_degrades_when_sub_killed_mid_publish(tmp_path):
    """ChaosTransport acceptance round: a sub-averager whose publish path
    dies mid-round leaves its OLD aggregate (rider naming the previous
    base) behind; the root's stale skip retires it and the round merges
    the surviving subtrees only."""
    from distributedtraining_tpu.engine.train import host_wire_template

    engine, cfg = _engine_fixture()
    template = host_wire_template(engine)
    hotkeys = [f"m{i}" for i in range(6)]
    consensus = {h: float(i + 1) for i, h in enumerate(hotkeys)}

    inner = LocalFSTransport(str(tmp_path))
    chain = _Chain(hotkeys, consensus)
    loop = AveragerLoop(
        engine, inner, chain, WeightedAverage(),
        val_batches=_eval_batches(cfg), publish_policy="always",
        stale_deltas="skip", ingest_workers=1,
        hierarchy=["n0", "n1"])
    loop.bootstrap(rng=jax.random.PRNGKey(0))
    _publish_fleet(inner, template, consensus)

    plan = plan_fanout(hotkeys, nodes=["n0", "n1"])
    chaos = {node: ChaosTransport(inner, ChaosSpec(), role=node)
             for node in plan}
    subs = {node: _sub(chaos[node], node, template, plan[node],
                       consensus=consensus) for node in plan}
    try:
        for sub in subs.values():
            assert sub.run_round() is True
        assert loop.run_round() is True
        assert loop.report.last_accepted == 2
        base2 = inner.base_revision()
        base2_tree = inner.fetch_base(template)[0]

        # round 2: n0 republishes against the new base; n1's publish path
        # is killed mid-publish (fetches fine, every publish op faults)
        assert subs["n0"].run_round() is True
        chaos["n1"].spec = ChaosSpec(publish_error_rate=1.0)
        assert subs["n1"].run_round() is True     # merged...
        assert subs["n1"].report.pushes_failed >= 1   # ...but not landed
        meta = inner.fetch_delta_meta(tbase.agg_id("n1"))
        assert meta["base_revision"] != base2     # the STALE leftover

        assert loop.run_round() is True
        # the root degraded to the surviving subtree instead of
        # double-applying n1's aggregate-vs-superseded-base
        assert loop.report.last_accepted == 1
        assert loop.report.last_rejected == 1
        # and the published base is exactly base2 + n0's aggregate (the
        # lone survivor carries normalized weight 1.0)
        a0 = inner.fetch_delta(tbase.agg_id("n0"), template)
        base3_tree = inner.fetch_base(template)[0]
        for b3, b2, a in zip(_leaves(base3_tree), _leaves(base2_tree),
                             _leaves(a0)):
            np.testing.assert_allclose(b3, b2 + a, rtol=2e-5, atol=1e-6)
    finally:
        for sub in subs.values():
            sub.close()
        loop.close()


def test_fleet_ledger_tiers_aggregates(tmp_path):
    """The contribution ledger (and fleet_report's tier column) tells
    aggregates from miner deltas."""
    import importlib.util
    import sys

    from distributedtraining_tpu.engine.health import FleetMonitor

    transport = LocalFSTransport(str(tmp_path))
    transport.publish_base(_tree(100))
    transport.publish_delta("m0", _tree(1))
    fm = FleetMonitor(transport)
    sub = _sub(transport, "n0", _template(), ["m0"], fleet=fm)
    try:
        assert sub.run_round() is True
        ing = DeltaIngestor(transport, _template(), workers=1,
                            max_delta_abs=1e3, retry_policy=FAST_RETRY,
                            observer=fm.record_staging)
        try:
            s = ing.stage([tbase.agg_id("n0")])[0]
            assert s.ok
        finally:
            ing.close()
        led = fm.ledger()
        assert led["miner/m0"]["tier"] == "miner"
        agg_key = f"miner/{tbase.agg_id('n0')}"
        assert led[agg_key]["tier"] == "agg"
        assert led[agg_key]["accepted"] == 1

        # fleet_report renders the column (older records default "miner")
        spec = importlib.util.spec_from_file_location(
            "fleet_report", os.path.join(
                os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                "scripts", "fleet_report.py"))
        fr = importlib.util.module_from_spec(spec)
        sys.modules.setdefault("fleet_report", fr)
        spec.loader.exec_module(fr)
        assert fr._cell(led[agg_key], "tier") == "agg"
        assert fr._cell({}, "tier") == "miner"
        assert "tier" in fr.COLUMNS
    finally:
        sub.close()
