"""Speculative decoding (engine/speculative.py + engine/serve.py).

The contract under test is LOSSLESSNESS, not speed: with any drafter —
model-backed, scripted oracle, scripted adversary, stale, or absent —
the engine's output must be token-identical to what plain decode would
have produced. Greedy lanes pin against ``reference_generate``; sampled
lanes pin BIT-identical against the spec-off engine (the counter PRNG
makes the accept/resample rule collapse to prefix matching, so the
stream is the same draw-for-draw). Everything else — CoW pages, pool
accounting, draft hot-swap, target restart-swap invalidation, compile
discipline — is tested as "still token-identical under X".
"""

import jax
import numpy as np
import pytest

from distributedtraining_tpu.engine.serve import (GenerationEngine,
                                                  reference_generate)
from distributedtraining_tpu.engine.speculative import (DraftEngine,
                                                        ScriptedDraftSource,
                                                        compat_reason)
from distributedtraining_tpu.models import gpt2
from distributedtraining_tpu.utils import obs

TINY = gpt2.GPT2Config(vocab_size=128, n_positions=64, n_embd=32,
                       n_layer=2, n_head=2, dtype="float32",
                       vocab_multiple=64)

GEN = 8

_REF_CACHE: dict = {}


@pytest.fixture(scope="module")
def setup():
    model, cfg = gpt2.make_model(TINY)
    params1 = model.init_params(jax.random.PRNGKey(0), seq_len=8)
    params2 = model.init_params(jax.random.PRNGKey(7), seq_len=8)
    rng = np.random.RandomState(0)
    prompts = [[int(t) for t in rng.randint(0, cfg.vocab_size, size=n)]
               for n in (5, 11, 3, 17)]
    return model, cfg, params1, params2, prompts


@pytest.fixture()
def sink():
    class _Sink:
        def __init__(self):
            self.records = []

        def log(self, rec, **kw):
            self.records.append(rec)

    s = _Sink()
    obs.configure(s, role="server")
    try:
        yield s
    finally:
        obs.reset()


def refs_for(model, params, prompts, n=GEN):
    out = []
    for p in prompts:
        key = (id(model), id(params), tuple(p), n)
        if key not in _REF_CACHE:
            _REF_CACHE[key] = reference_generate(model, params, p, n)
        out.append(_REF_CACHE[key])
    return out


def spec_engine(model, params, draft, *, k=4, **kw):
    kw.setdefault("max_slots", 4)
    kw.setdefault("page_size", 8)
    kw.setdefault("debug_invariants", True)
    return GenerationEngine(model, params, draft=draft, draft_k=k, **kw)


def oracle_for(model, params, prompts, n=GEN):
    """A scripted drafter that always proposes the target's own next
    tokens — acceptance 1.0 by construction."""
    ref_map = {tuple(p): r for p, r in zip(prompts,
                                           refs_for(model, params,
                                                    prompts, n))}

    def fn(req, k):
        full = ref_map[tuple(req.prompt)]
        return full[len(req.tokens):len(req.tokens) + k]

    return ScriptedDraftSource(fn)


# ---------------------------------------------------------------------------
# Greedy identity
# ---------------------------------------------------------------------------

def test_greedy_identity_self_draft(setup, sink):
    """Self-drafting (draft == target): every proposal must verify, so
    acceptance is exactly 1.0 — which also proves the draft-KV position
    and commit bookkeeping are exact (one misfed position would skew
    the draft logits and break the 1.0)."""
    model, cfg, params, _, prompts = setup
    draft = DraftEngine(model, params, max_slots=4, page_size=8)
    eng = spec_engine(model, params, draft)
    try:
        assert eng.generate(prompts, GEN) == refs_for(model, params, prompts)
        assert eng.spec_accept_rate == 1.0
        assert eng.spec_rounds < GEN * len(prompts)  # actually speculated
    finally:
        eng.close()


def test_greedy_identity_mismatched_draft(setup, sink):
    """A draft with DIFFERENT weights proposes mostly-wrong tokens;
    output must still be token-identical to the oracle (rejection
    resamples the target's own pick), acceptance lands somewhere in
    [0, 1)."""
    model, cfg, params1, params2, prompts = setup
    draft = DraftEngine(model, params2, max_slots=4, page_size=8)
    eng = spec_engine(model, params1, draft)
    try:
        assert eng.generate(prompts, GEN) == refs_for(model, params1,
                                                      prompts)
        assert 0.0 <= eng.spec_accept_rate < 1.0
    finally:
        eng.close()


def test_scripted_zero_accept_degenerates_to_plain_decode(setup, sink):
    """An adversarial drafter (always wrong): every round accepts 0
    tokens and emits exactly the target's pick — plain decode in
    disguise, token-identical, acceptance 0.0."""
    model, cfg, params, _, prompts = setup
    refs = refs_for(model, params, prompts)
    ref_map = {tuple(p): r for p, r in zip(prompts, refs)}

    def anti(req, k):   # oracle token + 1 (mod V): guaranteed mismatch
        full = ref_map[tuple(req.prompt)]
        nxt = full[len(req.tokens):len(req.tokens) + k]
        return [(t + 1) % cfg.vocab_size for t in nxt]

    eng = spec_engine(model, params, ScriptedDraftSource(anti))
    try:
        assert eng.generate(prompts, GEN) == refs
        assert eng.spec_accept_rate == 0.0
        assert eng.tokens_emitted == GEN * len(prompts)
    finally:
        eng.close()


def test_scripted_all_accept_commits_k_at_a_time(setup, sink):
    """The oracle drafter: every proposal verifies, each round commits
    K+1 tokens, so the whole batch finishes in far fewer verify rounds
    than tokens."""
    model, cfg, params, _, prompts = setup
    eng = spec_engine(model, params, oracle_for(model, params, prompts))
    try:
        assert eng.generate(prompts, GEN) == refs_for(model, params, prompts)
        assert eng.spec_accept_rate == 1.0
        # 8 tokens at K=4 -> ceil(8 / (4+1)) = 2 rounds per request
        assert eng.spec_rounds <= 2
    finally:
        eng.close()


# ---------------------------------------------------------------------------
# Sampled lanes: bit-identity spec-on vs spec-off
# ---------------------------------------------------------------------------

def _sampled_run(eng, prompts, *, n=GEN):
    reqs = [eng.submit(p, n) if i % 2 == 0 else
            eng.submit(p, n, temperature=0.8, top_p=0.9, seed=100 + i)
            for i, p in enumerate(prompts)]
    while not all(r.done_evt.is_set() for r in reqs):
        eng.step()
    return [list(r.tokens) for r in reqs]


def test_sampled_stream_bit_identical_spec_on_off(setup, sink):
    """Mixed greedy/sampled batch: the spec-on streams must equal the
    spec-off streams DRAW FOR DRAW — the counter PRNG keys every pick by
    (seed, stream index), so verify's picks are the plain path's picks."""
    model, cfg, params1, params2, prompts = setup
    plain = GenerationEngine(model, params1, max_slots=4, page_size=8)
    off = _sampled_run(plain, prompts)
    plain.close()
    draft = DraftEngine(model, params2, max_slots=4, page_size=8)
    eng = spec_engine(model, params1, draft)
    try:
        assert _sampled_run(eng, prompts) == off
    finally:
        eng.close()


def test_sampled_stream_batch_composition_invariant(setup, sink):
    """Each request run SOLO through a speculating engine produces the
    same stream it produced inside the full batch — the per-request
    (seed, index) keying means batch layout can never leak into
    output."""
    model, cfg, params1, params2, prompts = setup
    draft = DraftEngine(model, params2, max_slots=4, page_size=8)
    eng = spec_engine(model, params1, draft)
    try:
        batched = _sampled_run(eng, prompts)
    finally:
        eng.close()
    for i, p in enumerate(prompts):
        draft = DraftEngine(model, params2, max_slots=4, page_size=8)
        solo = spec_engine(model, params1, draft)
        try:
            if i % 2 == 0:
                r = solo.submit(p, GEN)
            else:
                r = solo.submit(p, GEN, temperature=0.8, top_p=0.9,
                                seed=100 + i)
            while not r.done_evt.is_set():
                solo.step()
            assert list(r.tokens) == batched[i]
        finally:
            solo.close()


@pytest.mark.parametrize("k", [1, 2, 8])
def test_draft_k_variations(setup, sink, k):
    """Output is invariant in K (only round count changes)."""
    model, cfg, params, params2, prompts = setup
    draft = DraftEngine(model, params2, max_slots=4, page_size=8)
    eng = spec_engine(model, params, draft, k=k)
    try:
        assert eng.generate(prompts, GEN) == refs_for(model, params, prompts)
    finally:
        eng.close()


# ---------------------------------------------------------------------------
# Acceptance-prefix edge cases under shared CoW pages
# ---------------------------------------------------------------------------

def test_mid_page_commit_under_shared_prefix_pages(setup, sink):
    """Requests sharing a cached system-prompt prefix speculate while
    their tails CoW off shared pages; multi-token commits land mid-page
    with ``debug_invariants`` auditing PagePool refcounts and the draft
    pool every step. Output pinned against the plain engine."""
    model, cfg, params, params2, prompts = setup
    rng = np.random.RandomState(3)
    sys_prompt = [int(t) for t in rng.randint(0, cfg.vocab_size, size=17)]
    shared = [sys_prompt + p for p in prompts]
    plain = GenerationEngine(model, params, max_slots=4, page_size=8)
    want = plain.generate(shared, GEN)
    plain.close()
    draft = DraftEngine(model, params2, max_slots=4, page_size=8)
    eng = spec_engine(model, params, draft, prefix_cache=True)
    try:
        cold = eng.generate(shared[:1], GEN)      # seeds the prefix cache
        warm = eng.generate(shared[1:], GEN)      # CoW off cached pages
        assert cold + warm == want
        assert eng.prefix_hits >= 1
    finally:
        eng.close()


def test_draft_pool_accounting(setup, sink):
    """Draft states own their pages exactly once; finishing requests
    release them (the ``_release`` -> ``draft.drop`` hook), and an
    explicit audit passes at every point."""
    model, cfg, params, _, prompts = setup
    draft = DraftEngine(model, params, max_slots=4, page_size=8)
    eng = spec_engine(model, params, draft)
    try:
        eng.generate(prompts, GEN)
        draft.check()
        assert not draft._states      # every slot released on finish
        assert draft.pool.free == draft.pool.total  # no page leaked
    finally:
        eng.close()


# ---------------------------------------------------------------------------
# Swap interactions
# ---------------------------------------------------------------------------

class _FakeWatcher:
    """Stands in for BaseRevisionWatcher: the engine only calls
    ``take_pending`` (between steps) and ``close``."""

    def __init__(self):
        self.staged = None

    def take_pending(self):
        staged, self.staged = self.staged, None
        return staged

    def close(self):
        pass


def test_draft_not_ready_degrades_to_plain_decode(setup, sink):
    """A DraftEngine with no installed params is not ``ready``: the
    engine must serve plain decode (token-identical), counting the
    fallback."""
    model, cfg, params, _, prompts = setup
    draft = DraftEngine(model, max_slots=4, page_size=8)
    assert not draft.ready
    eng = spec_engine(model, params, draft)
    try:
        assert eng.generate(prompts, GEN) == refs_for(model, params, prompts)
        assert eng.spec_rounds == 0
        assert obs.registry().counter("serve.spec_fallbacks").value >= 1
    finally:
        eng.close()


def test_draft_hot_swap_mid_run(setup, sink):
    """A new draft revision lands mid-generation: the watcher lane
    installs it between steps, flushing all draft KV; output stays
    token-identical (draft params can only change ACCEPTANCE) and the
    swap is counted."""
    model, cfg, params1, params2, prompts = setup
    watcher = _FakeWatcher()
    draft = DraftEngine(model, params2, max_slots=4, page_size=8,
                        revision="d1", watcher=watcher)
    eng = spec_engine(model, params1, draft)
    try:
        reqs = [eng.submit(p, GEN) for p in prompts]
        for _ in range(2):
            eng.step()
        flushes = draft.flush_count
        watcher.staged = ("d2", jax.device_put(params1))  # self-draft now
        while not all(r.done_evt.is_set() for r in reqs):
            eng.step()
        assert [list(r.tokens) for r in reqs] == refs_for(model, params1,
                                                          prompts)
        assert draft.revision == "d2"
        assert draft.flush_count > flushes
        assert obs.registry().counter("serve.spec_draft_swaps").value == 1
    finally:
        eng.close()


def test_target_restart_swap_invalidates_draft(setup, sink):
    """THE drain-swap interaction fix: a target-base hot swap under the
    restart policy lands mid-speculation. Every in-flight draft state
    was built against output of the OLD target params — the restart
    must drop them all (counted as ``serve.spec_invalidations``), and
    the requeued requests must finish token-identical to the NEW
    params' oracle, with no stale draft KV surviving."""
    model, cfg, params1, params2, prompts = setup
    n = 24     # long enough that the swap lands mid-speculation
    draft = DraftEngine(model, params1, max_slots=4, page_size=8)
    eng = spec_engine(model, params1, draft, swap_policy="restart")
    try:
        reqs = [eng.submit(p, n) for p in prompts]
        eng.step()                    # prefill + first speculation
        eng.step()
        assert draft._states          # speculation is in flight
        stale = dict(draft._states)
        eng._pending_swap = ("r2", jax.device_put(params2))
        eng.step()                    # swap installs, slots restart
        assert eng.revision == "r2"
        # the same step re-admits the requeued requests and speculates
        # again — but from FRESH draft states: every pre-swap state
        # (draft KV seeded by the old params' output) was dropped
        for rid, st in draft._states.items():
            assert st is not stale.get(rid)
        inval = obs.registry().counter("serve.spec_invalidations").value
        assert inval == len(prompts)
        while not all(r.done_evt.is_set() for r in reqs):
            eng.step()
        assert [list(r.tokens) for r in reqs] == refs_for(model, params2,
                                                          prompts, n)
    finally:
        eng.close()


# ---------------------------------------------------------------------------
# Compile discipline
# ---------------------------------------------------------------------------

def test_zero_steady_state_fresh_compiles(setup, sink):
    """Two identical mixed greedy/sampled waves through a speculating
    engine: wave 2 must add ZERO fresh compiles — draft, verify, and
    prefill families are all warm on their shared (slot, page)
    ladders."""
    model, cfg, params, _, prompts = setup
    draft = DraftEngine(model, params, max_slots=4, page_size=8)
    eng = spec_engine(model, params, draft)
    try:
        _sampled_run(eng, prompts)               # warm every family
        reg = obs.registry()
        before = reg.histogram("compile.ms").count
        wave2 = _sampled_run(eng, prompts)
        assert reg.histogram("compile.ms").count == before
        plain = GenerationEngine(model, params, max_slots=4, page_size=8)
        assert wave2 == _sampled_run(plain, prompts)
        plain.close()
    finally:
        eng.close()


# ---------------------------------------------------------------------------
# Compatibility / plumbing
# ---------------------------------------------------------------------------

def test_compat_vocab_mismatch_rejected(setup):
    model, cfg, params, _, _ = setup
    other, _ = gpt2.make_model(gpt2.GPT2Config(
        vocab_size=64, n_positions=32, n_embd=16, n_layer=1, n_head=1,
        vocab_multiple=64))
    assert compat_reason(other, cfg) is not None
    with pytest.raises(ValueError, match="incompatible draft"):
        GenerationEngine(model, params, max_slots=2, page_size=8,
                         draft=DraftEngine(other, max_slots=2,
                                           page_size=8))


def test_router_backend_speed_factor():
    """Heartbeat spec extras scale the router's outstanding-work score;
    defaults leave non-speculating fleets byte-identical."""
    from distributedtraining_tpu.engine.router import (BackendState,
                                                       RouterPolicy)
    plain = BackendState(url="a")
    plain.update({"ok": True, "queue_depth": 2, "active": 1})
    spec = BackendState(url="b")
    spec.update({"ok": True, "queue_depth": 2, "active": 1,
                 "spec_accept_rate": 0.75, "spec_k": 4})
    assert plain.speed_factor == 1.0
    assert spec.speed_factor == 4.0
    pol = RouterPolicy()
    assert pol.score(spec) < pol.score(plain)
    assert pol.choose([plain, spec]) is spec
