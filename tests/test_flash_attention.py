"""Flash-attention kernel parity vs the dense oracle.

The suite's conftest forces the CPU platform (virtual 8-device mesh), where
the Pallas kernel declines by design — so these tests skip there and run
when the suite is pointed at real TPU hardware
(``JAX_PLATFORMS=tpu pytest tests/test_flash_attention.py -p no:cacheprovider``
with the conftest override removed, or via bench-side validation). The
decline-to-dense contract itself IS tested on CPU.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributedtraining_tpu.ops.attention import causal_attention
from distributedtraining_tpu.ops.flash_attention import flash_attention

on_tpu = jax.default_backend() in ("tpu", "axon")


def _qkv(B=2, T=512, H=4, D=64, seed=0):
    rng = np.random.default_rng(seed)
    return tuple(jnp.asarray(rng.standard_normal((B, T, H, D)), jnp.bfloat16)
                 for _ in range(3))


def test_declines_off_tpu_or_short():
    q, k, v = _qkv(T=128)
    assert flash_attention(q, k, v) is None  # short seq declines everywhere
    if not on_tpu:
        q, k, v = _qkv(T=512)
        assert flash_attention(q, k, v) is None


@pytest.mark.skipif(not on_tpu, reason="pallas kernel needs TPU")
def test_matches_dense_unmasked():
    q, k, v = _qkv()
    out = flash_attention(q, k, v)
    assert out is not None
    ref = causal_attention(q, k, v, impl="dense")
    np.testing.assert_allclose(np.asarray(out, np.float32),
                               np.asarray(ref, np.float32), atol=3e-2)


@pytest.mark.skipif(not on_tpu, reason="pallas kernel needs TPU")
def test_matches_dense_with_segments_and_grads():
    q, k, v = _qkv()
    B, T = q.shape[:2]
    rng = np.random.default_rng(1)
    seg = jnp.asarray(np.repeat(rng.integers(0, 3, (B, T // 128)), 128,
                                axis=1), jnp.int32)
    out = flash_attention(q, k, v, segment_ids=seg)
    assert out is not None
    ref = causal_attention(q, k, v, segment_ids=seg, impl="dense")
    np.testing.assert_allclose(np.asarray(out, np.float32),
                               np.asarray(ref, np.float32), atol=3e-2)

    gf = jax.grad(lambda q: jnp.sum(
        flash_attention(q, k, v, segment_ids=seg).astype(jnp.float32)))(q)
    gd = jax.grad(lambda q: jnp.sum(
        causal_attention(q, k, v, segment_ids=seg,
                         impl="dense").astype(jnp.float32)))(q)
    np.testing.assert_allclose(np.asarray(gf, np.float32),
                               np.asarray(gd, np.float32), atol=1e-1)
