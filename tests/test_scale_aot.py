"""AOT compile + HBM budget for BASELINE configs 4/5 (scripts/scale_aot.py).

Runs the real artifact generator as a subprocess (it owns its own device
count / platform setup) and asserts both target configs compile on their
pod-shaped virtual meshes AND fit the per-chip HBM budgets. This is the
round-5 upgrade of validate_7b_worker's shape-level checks: buffer
assignment catches collective layouts, GSPMD resharding, and actual
per-device argument/temp sizes that jax.eval_shape cannot."""

import json
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_scale_aot_configs_fit(tmp_path):
    out = tmp_path / "scale.json"
    env = dict(os.environ, DT_FORCE_PLATFORM="cpu")
    # the script sets its own xla_force_host_platform_device_count
    env.pop("XLA_FLAGS", None)
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts", "scale_aot.py"),
         "--out", str(out)],
        cwd=REPO, env=env, capture_output=True, text=True, timeout=1800)
    assert proc.returncode == 0, proc.stdout[-2000:] + proc.stderr[-2000:]
    rec = json.loads(out.read_text())
    assert rec["all_fit"] is True
    by_name = {c["config"]: c for c in rec["configs"]}
    c4 = by_name["BASELINE config 4"]
    assert c4["devices"] == 32 and c4["per_device"]["fits"]
    assert 6.5e9 < c4["n_params"] < 7.5e9
    c5 = by_name["BASELINE config 5"]
    assert c5["devices"] == 64 and c5["per_device"]["fits"]
    assert 7.5e9 < c5["n_params"] < 8.5e9
    # the budgets are the real chips': v4 32 GiB, v5e 16 GiB
    assert c4["per_device"]["hbm_budget_gib"] == 32
    assert c5["per_device"]["hbm_budget_gib"] == 16
