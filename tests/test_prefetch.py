"""Background input pipeline (data/prefetch.py) — the DataLoader-workers
equivalent (reference tokenizes in worker processes, neurons/miner.py:101-106).
"""

import threading
import time

import numpy as np
import pytest

from distributedtraining_tpu.data import (ByteTokenizer, batch_iterator,
                                          prefetch, text_corpus)


def test_order_and_content_preserved():
    docs = text_corpus(split="train", n_docs=16, source="synthetic")
    direct = list(batch_iterator(docs, ByteTokenizer(), batch_size=2,
                                 seq_len=16))
    fetched = list(prefetch(batch_iterator(docs, ByteTokenizer(),
                                           batch_size=2, seq_len=16)))
    assert len(direct) == len(fetched) > 0
    for a, b in zip(direct, fetched):
        assert set(a) == set(b)
        for k in a:
            np.testing.assert_array_equal(a[k], b[k])


def test_shuffle_permutes_per_epoch_deterministically():
    docs = text_corpus(split="train", n_docs=12, source="synthetic")
    tok = ByteTokenizer()

    def epochs(shuffle, seed=0, n=24):
        out, it = [], batch_iterator(docs, tok, batch_size=1, seq_len=16,
                                     repeat=True, shuffle=shuffle, seed=seed)
        for _ in range(n):
            out.append(next(it)["input_ids"].tobytes())
        return out

    plain = epochs(False)
    shuf = epochs(True)
    assert plain != shuf                      # order actually changes
    assert shuf == epochs(True)               # deterministic from the seed
    assert plain == epochs(False)             # unshuffled stays stable
    assert shuf != epochs(True, seed=1)       # seed actually steers it


def test_shuffle_seed_per_identity():
    from distributedtraining_tpu.data.datasets import shuffle_seed_for

    a, b = shuffle_seed_for("hotkey_0"), shuffle_seed_for("hotkey_1")
    assert a != b                       # distinct miners, distinct streams
    assert a == shuffle_seed_for("hotkey_0")  # stable across restarts
    assert 0 <= a < 2**32


def test_transform_runs_in_worker():
    main = threading.get_ident()
    seen = []

    def tf(x):
        seen.append(threading.get_ident())
        return x * 2

    out = list(prefetch(range(5), transform=tf))
    assert out == [0, 2, 4, 6, 8]
    assert seen and all(t != main for t in seen)


def test_exception_propagates():
    def gen():
        yield 1
        raise RuntimeError("boom")

    it = prefetch(gen())
    assert next(it) == 1
    with pytest.raises(RuntimeError, match="boom"):
        next(it)
    # iterator is closed after the error
    with pytest.raises(StopIteration):
        next(it)


def test_depth_bounds_producer():
    produced = []

    def gen():
        for i in range(100):
            produced.append(i)
            yield i

    it = prefetch(gen(), depth=2)
    time.sleep(0.3)  # give the worker time to run ahead if it could
    # queue(depth=2) + one item in-flight in the worker
    assert len(produced) <= 4
    assert next(it) == 0
    it.close()


def test_close_stops_infinite_source():
    def forever():
        i = 0
        while True:
            yield i
            i += 1

    it = prefetch(forever(), depth=1)
    assert next(it) == 0
    it.close()
    with pytest.raises(StopIteration):
        next(it)
    # worker drains out on its own after close
    deadline = time.time() + 5
    while it._worker.is_alive() and time.time() < deadline:
        time.sleep(0.05)
    assert not it._worker.is_alive()


def test_context_manager():
    with prefetch(range(3)) as it:
        assert next(it) == 0
    with pytest.raises(StopIteration):
        next(it)


def test_cross_thread_close_unblocks_consumer():
    """A consumer blocked in __next__ (empty queue, slow producer) must
    return promptly when another thread calls close() — the single
    unbounded get() used to sleep forever once the worker dropped its
    pending put."""
    gate = threading.Event()

    def slow():
        yield 0
        gate.wait(10)  # park the producer so the consumer blocks
        yield 1

    it = prefetch(slow(), depth=1)
    assert next(it) == 0
    got = []

    def consume():
        try:
            next(it)
        except StopIteration:
            got.append("stop")

    t = threading.Thread(target=consume)
    t.start()
    time.sleep(0.2)        # consumer is now blocked in __next__
    it.close()             # cross-thread close
    t.join(timeout=5)
    gate.set()
    assert not t.is_alive(), "consumer stayed blocked after close()"
    assert got == ["stop"]


def test_transform_stopiteration_is_a_bug_not_exhaustion():
    """PEP 479: StopIteration escaping the transform must surface as an
    error, not masquerade as a clean end-of-stream."""
    def tf(x):
        raise StopIteration

    it = prefetch(range(3), transform=tf)
    with pytest.raises(RuntimeError, match="StopIteration"):
        next(it)
